"""Conservation-flow pass: statically proven "no silent drop".

The system's defining invariant is exact end-to-end sample conservation
(docs/resilience.md: ``ingested == emitted + shed + quarantined +
requeued + accounted_lost``). Until this pass, the invariant was
enforced only by whichever e2e/soak test happened to exercise a given
drop path — PR 16's parked-repost bug and PR 9's checkpoint
staging-drain bug were both silent-drop instances found *late* by e2e.
This pass makes "every discarded sample is credited to a ledger
counter" a machine-checked property of the pipeline hot set, the same
static+runtime pairing as lock-discipline/TSan-lite (the runtime twin
is ``lint/ledger_audit.py``).

Model
-----
A **sample-flow graph** over the pipeline hot set (:data:`HOT_SET`):
functions that hold in-flight sample state, from the intake points
(:data:`SOURCES` — parse, ``import_*``, ``sample_many``,
``merge_sealed``, ``handle_handoff``, ``/replicate``) through the store
groups, the flusher, and the sinks/forwarders/handoff/standby egress.
Within each hot function, every **discard edge** — a ``continue``, a
bare in-loop ``return``, or a truncating same-name slice — must be
*discharged* on its path by one of:

- a **credit API** (:data:`CREDIT_CALLS` /
  :data:`CREDIT_COUNTER_TOKENS` / :data:`CREDIT_METRIC_TOKENS`):
  LaneLedger/Quarantine ``.count()``, ``account_shed``,
  ``_requeue_group`` / ``_requeue_forward_part``, a
  ``*_dropped_total`` / ``*_requeued_total`` counter bump, …
- a **forward API** (:data:`FORWARD_CALLS`): the state was handed
  onward (staged, merged, emitted, posted, parked) before the edge, or
- a ``raise`` (accounting responsibility propagates to the caller).

The path test is lexical-per-branch: the statements preceding the edge
in each enclosing block down from the function body (an ``else`` branch
never inherits credit from its ``if`` body, and an ``except`` handler
never inherits credit from its partially-executed ``try`` body). That
is exact for the straight-line+guard shape the pipeline is written in,
and errs toward flagging — a deliberate benign edge carries
``# lint: ok(silent-drop) <written justification>`` (the pragma-justify
pass refuses an empty reason; baseline policy stays empty).

Exception edges are the sibling pass (``lint/exceptsafety.py``); the
credit-API registry below is generated into docs/static-analysis.md
(``--credit-table``) and drift-checked by the ``ledger-registry`` pass;
registry liveness (every entry resolves to real code — the pass cannot
silently go vacuous) is the ``ledger-coverage`` pass
(``lint/ledgercov.py``).
"""

from __future__ import annotations

import ast
import fnmatch
from typing import Dict, Iterator, List, Optional, Tuple

from veneur_tpu.lint.framework import (Finding, Project, SourceFile,
                                       dotted, qualname, register)

# ---------------------------------------------------------------------------
# registries (drift-checked: ledger-registry + ledger-coverage)
# ---------------------------------------------------------------------------

#: The pipeline hot set: relpath -> qualname patterns (fnmatch) of the
#: functions that hold in-flight sample state. Parser/ingest lanes ->
#: store groups -> flusher -> sinks/forwarders/handoff/standby.
HOT_SET: Dict[str, List[str]] = {
    "veneur_tpu/samplers/parser.py": [
        "parse_metric_ssf", "convert_metrics", "convert_indicator_metrics",
    ],
    "veneur_tpu/ingest/lanes.py": [
        "IngestLane._ingest_once", "IngestLane._stage_native",
        "IngestLane._stage_python", "IngestLane._stage_one_metric",
        "IngestLane._seal",
        "IngestFleet.merge_sealed", "IngestFleet._merge_chunk",
        "IngestFleet._fold_ledger",
    ],
    "veneur_tpu/core/store.py": [
        "MetricStore.process_metric", "MetricStore.process_batch",
        "MetricStore.import_*", "MetricStore.handoff_extract",
        "MetricStore._lane_remap", "MetricStore._requeue_group",
        "MetricStore._run_flush_units", "MetricStore._unit_failed",
        "MetricStore._flush_generation", "MetricStore._flush_scalars",
        "MetricStore._emit_digest_result", "MetricStore._emit_set_result",
        "ScalarGroup.sample", "ScalarGroup.combine",
        "DigestGroup.sample", "DigestGroup.sample_many",
        "DigestGroup.import_centroids", "DigestGroup.import_centroids_bulk",
        "SetGroup.sample", "SetGroup.sample_many",
        "SetGroup.import_registers", "SetGroup.import_registers_row",
        "HeavyHitterGroup.sample", "HeavyHitterGroup.sample_many",
        "HeavyHitterGroup.import_sketch",
        "bulk_stage_import_centroids",
    ],
    "veneur_tpu/core/tiered.py": [
        "*.sample", "*.sample_many", "*.import_*", "*promote*",
        "*._drain_samples", "*._drain_imports", "*._drain_staging",
    ],
    "veneur_tpu/fleet/mesh_tiered.py": [
        "*._pool_drain_samples", "*._pool_drain_imports",
        "*._maybe_promote", "MeshTieredDigestGroup.flush*",
    ],
    "veneur_tpu/flusher.py": [
        "flush_once", "_flush_once", "_build_stream",
        "_requeue_forward_part",
    ],
    "veneur_tpu/sinks/datadog.py": [
        "DatadogMetricSink.flush_columnar", "DatadogMetricSink.flush_chunk",
        "DatadogMetricSink._post_chunk_body",
        "DatadogMetricSink._park_locked",
        "DatadogMetricSink.repost_requeued",
    ],
    "veneur_tpu/sinks/channel.py": ["*.flush", "*.ingest"],
    "veneur_tpu/forward/convert.py": ["*"],
    "veneur_tpu/forward/http_forward.py": ["*.forward*", "*._post*",
                                           "post_helper"],
    "veneur_tpu/forward/grpc_forward.py": ["*.forward*", "*.send*"],
    "veneur_tpu/fleet/handoff.py": [
        "HandoffManager._run_handoff*", "HandoffManager.refresh",
        "HandoffManager._send*", "HandoffManager._post_blob",
        "HandoffManager._requeue", "HandoffManager.handle_handoff",
        "HandoffManager.recover_spool",
        "split_group_snapshot", "_filter_rows",
    ],
    "veneur_tpu/fleet/standby.py": [
        "StandbyManager.capture", "StandbyManager.dispatch",
        "StandbyManager._send", "StandbyManager.handle_replicate",
        "StandbyManager.promote", "ReplicaShadow.*",
    ],
    "veneur_tpu/server.py": [
        "Server.handle_metric_packet", "Server.handle_packet",
        "Server.handle_ssf_packet", "Server.handle_ssf",
        "Server.handle_ssf_batch", "Server.handle_ssf_stream",
        "Server._shed_spans", "Server._native_ssf_pump",
        "Server._native_pump",
        "SpanWorker.work", "SpanWorker.flush",
        "_SinkIngestor.offer", "_SinkIngestor.offer_batch",
        "_SinkIngestor._work", "_SinkIngestor.drain",
        "EventWorker.add", "EventWorker.flush",
    ],
    "veneur_tpu/proxy/proxy.py": [
        "Proxy.proxy_metrics", "Proxy.proxy_traces", "Proxy._fan_out",
        "Proxy._post_batch", "Proxy._post_batch_inner",
    ],
    "veneur_tpu/proxy/grpc_proxy.py": ["*.send_metrics", "*._forward"],
}

#: Intake points: a call to one of these introduces in-flight sample
#: state (documented in the registry table; liveness pinned by
#: ledger-coverage).
SOURCES = (
    "parse_metric", "parse_metric_ssf", "convert_metrics",
    "import_columnar", "import_lane_chunk", "import_digests_bulk",
    "sample_many", "merge_sealed", "handle_handoff", "handle_replicate",
)

#: Callee base names whose invocation credits a ledger counter.
CREDIT_CALLS = frozenset({
    "account_shed", "_quarantine_samples",
    "_scrub_counter_batch", "_scrub_float_batch",
    "_requeue_group", "_requeue_forward_part", "count_requeued",
    "_park_locked", "_fold_ledger", "_shed_spans",
})

#: ``.count(...)`` receivers that ARE ledgers: any dotted-path segment
#: matching one of these tokens (``self.ledger.count``,
#: ``quarantine.count``, ``q.count``).
CREDIT_RECEIVER_TOKENS = ("ledger", "quarantine", "quar")
_CREDIT_RECEIVER_EXACT = frozenset({"q"})

#: Counter-attribute tokens: an augmented assignment onto an attribute
#: containing one of these is ledger accounting (``chunk_rows_dropped
#: += n``, ``shed_records += n``, ``parse_errors += 1``).
CREDIT_COUNTER_TOKENS = (
    "dropped", "requeued", "shed", "quarantin", "lost", "spill",
    "errors", "scrubbed", "skipped", "timeout",
)

#: Self-metric name fragments: emitting one of these strings is ledger
#: accounting (``*_requeued_total`` / ``*_dropped_total`` emissions,
#: ``accounted_lost`` folds).
CREDIT_METRIC_TOKENS = (
    "dropped_total", "requeued_total", "accounted_lost", "shed_total",
    "lost_total", "errors_total", ".shed", ".quarantined",
)

#: Callee base names that hand in-flight state ONWARD (staged, merged,
#: emitted, posted, parked, spooled) — the path is not a drop.
FORWARD_CALLS = frozenset({
    "append", "extend", "appendleft", "put", "put_nowait", "put_one",
    "_put_one", "_stage_span", "_stage_one_metric", "_memoize",
    "sample", "sample_many", "combine", "merge", "merge_sealed",
    "add", "add_many", "set_many", "offer", "offer_batch",
    "emit", "send", "send_metrics", "post", "write",
    "handle_ssf", "handle_ssf_batch", "process_metric", "process_batch",
    "proxy_metrics", "proxy_traces",
})
#: Prefixes with the same meaning (``import_*``, ``_emit_*``, …).
FORWARD_PREFIXES = ("import_", "_emit", "emit_", "flush", "_flush",
                    "forward", "_forward", "_post", "stage_", "_stage",
                    "_drain", "restore", "_restore", "capture",
                    "replicate")


# ---------------------------------------------------------------------------
# discharge tests
# ---------------------------------------------------------------------------

def _base_name(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _is_credit_node(node: ast.AST) -> bool:
    """True when this single AST node is a ledger credit."""
    if isinstance(node, ast.Call):
        name = _base_name(node.func)
        if name in CREDIT_CALLS:
            return True
        if name == "count":
            path = dotted(node.func) or ""
            segs = path.lower().split(".")
            recv = segs[:-1]
            if any(t in seg for seg in recv for t in
                   CREDIT_RECEIVER_TOKENS) \
                    or (recv and recv[-1] in _CREDIT_RECEIVER_EXACT):
                return True
        for arg in node.args:
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str) \
                    and any(t in arg.value for t in CREDIT_METRIC_TOKENS):
                return True
        return False
    if isinstance(node, ast.AugAssign):
        target = dotted(node.target)
        if target:
            leaf = target.split(".")[-1].lower()
            if any(t in leaf for t in CREDIT_COUNTER_TOKENS):
                return True
            # un-counting an intake tally (``self.parsed -= n``) keeps
            # the identity exact without a drop-side credit
            if isinstance(node.op, ast.Sub) and "parsed" in leaf:
                return True
    return False


def _is_forward_node(node: ast.AST) -> bool:
    """True when this single AST node hands sample state onward."""
    if isinstance(node, ast.Call):
        name = _base_name(node.func)
        if name is None:
            return False
        if name in FORWARD_CALLS:
            return True
        return any(name.startswith(p) for p in FORWARD_PREFIXES)
    if isinstance(node, (ast.Yield, ast.YieldFrom, ast.Raise)):
        return True
    if isinstance(node, ast.Return) and node.value is not None:
        return True
    if isinstance(node, ast.Assign):
        # container store: out[k] = v
        return any(isinstance(t, ast.Subscript) for t in node.targets)
    return False


def _stmt_discharges(stmt: ast.AST) -> bool:
    """Does any node under ``stmt`` credit a ledger or forward state?
    Nested function/class bodies don't execute here — skipped."""
    stack = [stmt]
    while stack:
        node = stack.pop()
        if node is not stmt and isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef,
                       ast.ClassDef)):
            continue
        if _is_credit_node(node) or _is_forward_node(node):
            return True
        stack.extend(ast.iter_child_nodes(node))
    return False


_BLOCK_FIELDS = ("body", "orelse", "finalbody")


def _path_stmts(node: ast.AST, fn: ast.AST,
                parents: Dict[ast.AST, ast.AST],
                stop_at: Optional[ast.AST] = None) -> Iterator[ast.AST]:
    """Statements lexically preceding ``node`` on its branch path, from
    its own block up through every enclosing block to ``fn``'s body
    (or ``stop_at``).  Path-accurate for straight-line + if/else
    nesting: an ``else`` branch never sees the ``if`` body, and a
    handler never sees its try body (partially executed on the
    exception edge)."""
    cur = node
    while cur is not fn and cur is not stop_at:
        parent = parents.get(cur)
        if parent is None:
            return
        if isinstance(parent, ast.ExceptHandler):
            if cur in parent.body:
                for s in parent.body[:parent.body.index(cur)]:
                    yield s
            # skip OVER the try: its body may have run only partially
            # before the exception, so its credits don't count; the
            # try's own preceding siblings still do
            tr = parents.get(parent)
            if tr is not None:
                cur = tr
                continue
        else:
            for field in _BLOCK_FIELDS:
                block = getattr(parent, field, None)
                if isinstance(block, list) and cur in block:
                    for s in block[:block.index(cur)]:
                        yield s
                    break
        cur = parent


def _discharged(node: ast.AST, fn: ast.AST,
                parents: Dict[ast.AST, ast.AST]) -> bool:
    return any(_stmt_discharges(s) for s in _path_stmts(node, fn, parents))


def _enclosing_loop(node: ast.AST, fn: ast.AST,
                    parents: Dict[ast.AST, ast.AST]):
    cur = parents.get(node)
    while cur is not None and cur is not fn:
        if isinstance(cur, (ast.For, ast.While)):
            return cur
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return None
        cur = parents.get(cur)
    return None


def _is_trunc_slice(stmt: ast.AST) -> Optional[str]:
    """``x = x[...bounded slice...]`` (or ``del x[n:]``): the dropped
    half vanishes unless credited. Returns the variable name."""
    if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
        t, v = stmt.targets[0], stmt.value
        if isinstance(t, ast.Name) and isinstance(v, ast.Subscript) \
                and isinstance(v.value, ast.Name) \
                and v.value.id == t.id \
                and isinstance(v.slice, ast.Slice) \
                and (v.slice.upper is not None
                     or v.slice.lower is not None):
            return t.id
    if isinstance(stmt, ast.Delete):
        for t in stmt.targets:
            if isinstance(t, ast.Subscript) \
                    and isinstance(t.value, ast.Name) \
                    and isinstance(t.slice, ast.Slice):
                return t.value.id
    return None


# ---------------------------------------------------------------------------
# the pass
# ---------------------------------------------------------------------------

def iter_hot_functions(project: Project
                       ) -> Iterator[Tuple[SourceFile, ast.AST, str]]:
    """(file, function node, qualname) for every hot-set function.
    Shared with exceptsafety/ledgercov so the three passes agree on the
    analyzed surface."""
    for relpath in sorted(HOT_SET):
        sf = project.files.get(relpath)
        if sf is None:
            continue
        patterns = HOT_SET[relpath]
        for node in sf.nodes:
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            qn = qualname(node, sf.parents)
            if any(fnmatch.fnmatchcase(qn, pat) for pat in patterns):
                yield sf, node, qn


def _check_function(sf: SourceFile, fn: ast.AST,
                    qn: str) -> List[Finding]:
    parents = sf.parents
    out: List[Finding] = []

    def flag(node: ast.AST, what: str):
        if sf.suppressed(node.lineno, "silent-drop"):
            return
        out.append(Finding(
            pass_name="drop-flow", code="silent-drop",
            file=sf.relpath, line=node.lineno,
            anchor=f"{qn}:{what}",
            message=(
                f"{what} in pipeline hot-set function `{qn}` discards "
                f"in-flight sample state with no ledger credit or "
                f"forward on its path — credit a counter "
                f"(LaneLedger/Quarantine, *_dropped_total, requeue) or "
                f"annotate `# lint: ok(silent-drop) <why>`")))

    seen_trunc = 0
    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not fn:
            continue  # nested defs get their own hot-set entry if hot
        if isinstance(node, ast.Continue):
            if not _discharged(node, fn, parents):
                flag(node, "continue")
        elif isinstance(node, ast.Return) and (
                node.value is None
                or (isinstance(node.value, ast.Constant)
                    and node.value.value is None)):
            # a bare return INSIDE a loop abandons the current item and
            # the unprocessed remainder; a pre-loop guard return is not
            # yet holding per-item state
            if _enclosing_loop(node, fn, parents) is not None \
                    and not _discharged(node, fn, parents):
                flag(node, "bare return inside loop")
        else:
            name = _is_trunc_slice(node)
            if name is not None and seen_trunc < 50:
                seen_trunc += 1
                # truncation is usually credited right next to the
                # slice — accept a credit in the preceding path OR in
                # the same block's following statements
                if not _discharged(node, fn, parents):
                    parent = parents.get(node)
                    after = []
                    for field in _BLOCK_FIELDS:
                        block = getattr(parent, field, None) \
                            if parent is not None else None
                        if isinstance(block, list) and node in block:
                            after = block[block.index(node) + 1:]
                            break
                    if not any(_stmt_discharges(s) for s in after):
                        flag(node, f"truncating slice of `{name}`")
    return out


@register("drop-flow")
def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for sf, fn, qn in iter_hot_functions(project):
        findings.extend(_check_function(sf, fn, qn))
    findings.sort(key=lambda f: (f.file, f.line, f.code))
    return findings


# ---------------------------------------------------------------------------
# the credit-API registry table (docs/static-analysis.md; drift-checked
# by the ledger-registry pass)
# ---------------------------------------------------------------------------

_MARKER_BEGIN = "<!-- generated: credit-registry begin -->"
_MARKER_END = "<!-- generated: credit-registry end -->"


def _call_sites(project: Project, test) -> int:
    n = 0
    for sf in project.files.values():
        for node in sf.nodes:
            if test(node):
                n += 1
    return n


def credit_table(project: Project) -> str:
    """Markdown registry: every credit/forward/source API the drop-flow
    pass recognizes, with live call-site counts (regen with
    ``--credit-table``)."""
    lines = ["| kind | API | recognized as | call sites |",
             "|---|---|---|---|"]

    def count_call(name):
        return _call_sites(project, lambda n: isinstance(n, ast.Call)
                           and _base_name(n.func) == name)

    for name in sorted(SOURCES):
        lines.append(f"| source | `{name}` | intake point "
                     f"| {count_call(name)} |")
    for name in sorted(CREDIT_CALLS):
        lines.append(f"| credit | `{name}()` | ledger credit call "
                     f"| {count_call(name)} |")
    for tok in CREDIT_RECEIVER_TOKENS:
        lines.append(f"| credit | `*{tok}*.count()` | ledger receiver "
                     f"| — |")
    for tok in CREDIT_COUNTER_TOKENS:
        lines.append(f"| credit | `*{tok}* +=` | counter attribute "
                     f"| — |")
    for tok in CREDIT_METRIC_TOKENS:
        lines.append(f"| credit | `\"*{tok}*\"` | self-metric emission "
                     f"| — |")
    hot = sum(1 for _ in iter_hot_functions(project))
    lines.append(f"| hot set | {len(HOT_SET)} files | "
                 f"{hot} analyzed functions | — |")
    return "\n".join(lines)


@register("ledger-registry")
def run_registry(project: Project) -> List[Finding]:
    """The credit-API registry table in docs/static-analysis.md must
    match the generated one (same shape as the compiled-program
    inventory drift check)."""
    docs_rel = "docs/static-analysis.md"
    docs = project.read(docs_rel)
    table = credit_table(project)
    current = None
    if docs and _MARKER_BEGIN in docs and _MARKER_END in docs:
        current = docs.split(_MARKER_BEGIN, 1)[1] \
            .split(_MARKER_END, 1)[0].strip()
    if current is None or current != table.strip():
        return [Finding(
            pass_name="ledger-registry", code="credit-registry-drift",
            file=docs_rel, line=1, anchor="credit-registry",
            message=(
                f"the credit-API registry in {docs_rel} is "
                f"{'missing' if current is None else 'stale'}: regenerate "
                f"with `python -m veneur_tpu.lint --credit-table` and "
                f"paste between the credit-registry markers"))]
    return []

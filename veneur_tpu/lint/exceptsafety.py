"""Exception-edge audit for the pipeline hot set.

The drop-flow pass (``lint/dropflow.py``) proves the *explicit* discard
edges — ``continue``, bare in-loop ``return``, truncating slice — are
credited. This module covers the two *exception*-shaped ways in-flight
sample state can vanish:

``except-safety`` (code ``swallowed-exception``)
    An ``except`` handler in a hot-set function that swallows the
    exception — no re-raise, no ledger credit, no forward/requeue, and
    not even a log line — makes the samples that were mid-flight in the
    ``try`` body disappear with zero evidence. Every handler must
    re-raise, credit a counter (same registry as drop-flow), hand the
    state onward, or at minimum log; a deliberate silent swallow
    carries ``# lint: ok(swallowed-exception) <why>`` on the ``except``
    line (or its first body statement).

``swap-restore`` (code ``raise-between-swap``)
    Swap-on-flush retires a whole generation behind
    ``_swap_generation()``; until ``_flush_generation`` /
    ``restore_state`` / ``_requeue_group`` disposes of it, the retired
    groups are in-flight state owned by exactly one stack frame. An
    explicit ``raise`` on the path between the swap and its disposal
    strands the entire interval — the PR 9 checkpoint bug shape. The
    check is lexical within the function: any ``raise`` after a swap
    call with no restore/requeue call in between (and no enclosing
    ``finally`` that restores) is flagged.

Both passes share drop-flow's hot set and credit registry
(:func:`veneur_tpu.lint.dropflow.iter_hot_functions`,
:func:`~veneur_tpu.lint.dropflow._is_credit_node`) so the three passes
agree on the analyzed surface; the ledger-coverage pass pins that
surface against silent vacuity.
"""

from __future__ import annotations

import ast
from typing import List

from veneur_tpu.lint.dropflow import (_base_name, _is_credit_node,
                                      _stmt_discharges, iter_hot_functions)
from veneur_tpu.lint.framework import Finding, Project, SourceFile, dotted, \
    register

# -- except-safety ---------------------------------------------------------

#: ``<something log-ish>.<method>(...)`` counts as evidence the swallow
#: was deliberate and observable.
LOG_METHODS = frozenset({
    "debug", "info", "warning", "error", "exception", "critical", "log",
})


def _is_log_node(node: ast.AST) -> bool:
    if not (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in LOG_METHODS):
        return False
    path = dotted(node.func)
    if not path:
        return False
    recv = path.lower().split(".")[:-1]
    return any("log" in seg for seg in recv)


def _stmt_evidences(stmt: ast.AST) -> bool:
    """Credit / forward / raise / log anywhere under ``stmt``."""
    if _stmt_discharges(stmt):
        return True
    for node in ast.walk(stmt):
        if _is_log_node(node):
            return True
    return False


def _handler_suppressed(sf: SourceFile, handler: ast.excepthandler) -> bool:
    if sf.suppressed(handler.lineno, "swallowed-exception"):
        return True
    return bool(handler.body) and sf.suppressed(
        handler.body[0].lineno, "swallowed-exception")


@register("except-safety")
def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for sf, fn, qn in iter_hot_functions(project):
        for node in ast.walk(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not fn:
                continue
            if not isinstance(node, ast.ExceptHandler):
                continue
            if any(_stmt_evidences(s) for s in node.body):
                continue
            if _handler_suppressed(sf, node):
                continue
            if isinstance(node.type, ast.Tuple):
                exc = ", ".join(dotted(e) or "?" for e in node.type.elts)
            else:
                exc = (dotted(node.type) or "Exception") \
                    if node.type is not None else "Exception"
            findings.append(Finding(
                pass_name="except-safety", code="swallowed-exception",
                file=sf.relpath, line=node.lineno,
                anchor=f"{qn}:except {exc}",
                message=(
                    f"`except {exc}` in pipeline hot-set function `{qn}` "
                    f"swallows the exception with no re-raise, ledger "
                    f"credit, forward, or log — samples mid-flight in the "
                    f"try body vanish without evidence; credit/requeue, "
                    f"log, or annotate "
                    f"`# lint: ok(swallowed-exception) <why>`")))
    findings.sort(key=lambda f: (f.file, f.line, f.code))
    return findings


# -- swap-restore ----------------------------------------------------------

#: Retiring calls: after one of these the caller owns a detached
#: generation of sample state.
SWAP_CALLS = frozenset({"_swap_generation"})

#: Disposal calls: the retired generation has been drained, restored,
#: or requeued — ownership discharged.
RESTORE_CALLS = frozenset({
    "_flush_generation", "restore_state", "_restore_group",
    "_requeue_group", "_requeue_forward_part",
})


def _call_lines(fn: ast.AST, names: frozenset) -> List[int]:
    return sorted(
        node.lineno for node in ast.walk(fn)
        if isinstance(node, ast.Call) and _base_name(node.func) in names)


def _finally_restores(node: ast.AST, fn: ast.AST, parents) -> bool:
    """An enclosing try/finally whose finalbody restores covers any
    raise inside the try."""
    cur = parents.get(node)
    while cur is not None and cur is not fn:
        if isinstance(cur, ast.Try) and cur.finalbody:
            for s in cur.finalbody:
                for sub in ast.walk(s):
                    if isinstance(sub, ast.Call) \
                            and _base_name(sub.func) in RESTORE_CALLS:
                        return True
        cur = parents.get(cur)
    return False


@register("swap-restore")
def run_swap(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for sf, fn, qn in iter_hot_functions(project):
        swaps = _call_lines(fn, SWAP_CALLS)
        if not swaps:
            continue
        restores = _call_lines(fn, RESTORE_CALLS)
        nth = 0
        for node in ast.walk(fn):
            if not isinstance(node, ast.Raise):
                continue
            if sf.suppressed(node.lineno, "raise-between-swap"):
                continue
            live_swaps = [s for s in swaps if s < node.lineno]
            if not live_swaps:
                continue
            last_swap = max(live_swaps)
            if any(last_swap < r < node.lineno for r in restores):
                continue
            if _finally_restores(node, fn, sf.parents):
                continue
            nth += 1
            findings.append(Finding(
                pass_name="swap-restore", code="raise-between-swap",
                file=sf.relpath, line=node.lineno,
                anchor=f"{qn}:raise-after-swap#{nth}",
                message=(
                    f"explicit raise in `{qn}` after the generation swap "
                    f"(line {last_swap}) with no restore/requeue in "
                    f"between — the retired generation's entire interval "
                    f"strands; requeue it first, restore in a `finally`, "
                    f"or annotate `# lint: ok(raise-between-swap) <why>`")))
    findings.sort(key=lambda f: (f.file, f.line, f.code))
    return findings

"""Core of ``veneur_tpu.lint``: findings, sources, baseline, pass registry.

Self-contained (stdlib ``ast`` + ``yaml`` which the package already
requires); no third-party lint dependency. Each pass is a callable
``(Project) -> List[Finding]`` registered in ``PASSES``; the runner in
``__main__.py`` diff's findings against a *file-anchored* baseline so
grandfathered findings can be carried explicitly (and justified in the
baseline file) without pinning line numbers.

Inline suppression: append ``# lint: ok(<code>)`` to the offending line
(optionally followed by a reason). The pragma is per-line and per-code,
so a suppression can never silently widen.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

_PRAGMA_RE = re.compile(r"#\s*lint:\s*ok\(([a-z0-9_,\- ]+)\)")


@dataclass(frozen=True)
class Finding:
    """One violation. ``anchor`` is a stable, line-free identifier inside
    the file (usually the enclosing function or the offending symbol) so
    baseline entries survive unrelated edits."""

    pass_name: str
    code: str
    file: str       # repo-relative path
    line: int       # 1-based; informational, not part of the baseline key
    anchor: str
    message: str

    def key(self) -> str:
        return f"{self.pass_name}:{self.code}:{self.file}:{self.anchor}"

    def render(self) -> str:
        return (f"{self.file}:{self.line}: [{self.pass_name}/{self.code}] "
                f"{self.message}")

    def as_json(self) -> dict:
        return {"pass": self.pass_name, "code": self.code, "file": self.file,
                "line": self.line, "anchor": self.anchor,
                "message": self.message}


class SourceFile:
    """A parsed python source: AST plus per-line pragma suppressions."""

    def __init__(self, path: str, relpath: str, text: str):
        self.path = path
        self.relpath = relpath
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        self._parents: Optional[Dict[ast.AST, ast.AST]] = None
        self._nodes: Optional[List[ast.AST]] = None
        self._aliases: Optional[Dict[str, str]] = None
        # pragmas live in actual COMMENT tokens only — pragma-shaped
        # text inside a string/docstring must not become a suppression.
        # The text AFTER the closing paren is the written justification
        # the pragma-justify pass insists on.
        self._pragmas: Dict[int, set] = {}
        self._pragma_reasons: Dict[int, str] = {}
        import io
        import tokenize

        try:
            for tok in tokenize.generate_tokens(io.StringIO(text).readline):
                if tok.type != tokenize.COMMENT:
                    continue
                m = _PRAGMA_RE.search(tok.string)
                if m:
                    self._pragmas.setdefault(tok.start[0], set()).update(
                        c.strip() for c in m.group(1).split(","))
                    self._pragma_reasons[tok.start[0]] = \
                        tok.string[m.end():].strip(" -:")
        except tokenize.TokenError:  # pragma: no cover - ast.parse passed
            pass

    def suppressed(self, line: int, code: str) -> bool:
        return code in self._pragmas.get(line, ())

    def pragma_lines(self) -> Dict[int, set]:
        """line -> suppressed codes, for the pragma-justify pass."""
        return self._pragmas

    def pragma_reason(self, line: int) -> str:
        """The free-text justification following the pragma, if any."""
        return self._pragma_reasons.get(line, "")

    @property
    def parents(self) -> Dict[ast.AST, ast.AST]:
        """Child -> parent map for this file's AST, built once."""
        if self._parents is None:
            self._parents = parent_map(self.tree)
        return self._parents

    @property
    def nodes(self) -> List[ast.AST]:
        """Flat ``ast.walk`` order of this file's tree, built once and
        shared by every pass (several passes re-walked independently
        before the per-Project cache landed)."""
        if self._nodes is None:
            self._nodes = list(ast.walk(self.tree))
        return self._nodes

    @property
    def aliases(self) -> Dict[str, str]:
        """:func:`import_aliases` of this file, computed once."""
        if self._aliases is None:
            self._aliases = import_aliases(self.tree)
        return self._aliases


class Project:
    """The analyzed tree: every ``veneur_tpu/**/*.py`` parsed once, plus
    the repo-level artifacts (example yamls, markdown docs) the drift
    passes compare against."""

    def __init__(self, root: str, package: str = "veneur_tpu"):
        self.root = os.path.abspath(root)
        self.package = package
        self.files: Dict[str, SourceFile] = {}
        pkg_dir = os.path.join(self.root, package)
        for dirpath, dirnames, filenames in os.walk(pkg_dir):
            dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
            # generated protobuf modules are not ours to lint; match on
            # the package-RELATIVE path so a checkout under some
            # /home/gen/... prefix doesn't skip everything
            rel_dir = os.path.relpath(dirpath, pkg_dir).replace(os.sep, "/")
            if rel_dir == "gen" or rel_dir.startswith("gen/") \
                    or "/gen/" in rel_dir or rel_dir.endswith("/gen"):
                continue
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                rel = os.path.relpath(path, self.root).replace(os.sep, "/")
                with open(path, encoding="utf-8") as f:
                    text = f.read()
                try:
                    self.files[rel] = SourceFile(path, rel, text)
                except SyntaxError as e:  # pragma: no cover - never ships
                    raise SyntaxError(f"{rel}: {e}") from e

    # -- repo artifacts ----------------------------------------------------

    def read(self, relpath: str) -> Optional[str]:
        path = os.path.join(self.root, relpath)
        if not os.path.exists(path):
            return None
        with open(path, encoding="utf-8") as f:
            return f.read()

    def docs_text(self) -> str:
        """Concatenated markdown the drift passes treat as "the docs":
        README.md plus everything under docs/."""
        parts = []
        for rel in ["README.md"]:
            t = self.read(rel)
            if t:
                parts.append(t)
        docs_dir = os.path.join(self.root, "docs")
        if os.path.isdir(docs_dir):
            for fn in sorted(os.listdir(docs_dir)):
                if fn.endswith(".md"):
                    t = self.read(os.path.join("docs", fn))
                    if t:
                        parts.append(t)
        return "\n".join(parts)

    def module_name(self, relpath: str) -> str:
        """veneur_tpu/ops/tdigest.py -> veneur_tpu.ops.tdigest"""
        mod = relpath[:-3].replace("/", ".")
        if mod.endswith(".__init__"):
            mod = mod[: -len(".__init__")]
        return mod


@dataclass
class Baseline:
    """Explicit grandfathered findings. Each entry keys a finding by
    (pass, code, file, anchor) — file-anchored, line-free — and carries a
    human justification that the runner refuses to leave empty."""

    path: str
    entries: Dict[str, str] = field(default_factory=dict)  # key -> reason

    @classmethod
    def load(cls, path: str) -> "Baseline":
        bl = cls(path=path)
        if os.path.exists(path):
            with open(path, encoding="utf-8") as f:
                data = json.load(f)
            for e in data.get("findings", []):
                key = (f"{e['pass']}:{e['code']}:{e['file']}:{e['anchor']}")
                bl.entries[key] = e.get("reason", "")
        return bl

    def save(self, findings: List[Finding]):
        data = {
            "_comment": (
                "Grandfathered veneur_tpu.lint findings. Every entry MUST "
                "carry a non-empty 'reason'; remove entries as the code "
                "they excuse is fixed (stale entries fail the run)."),
            "findings": [
                {"pass": f.pass_name, "code": f.code, "file": f.file,
                 "anchor": f.anchor,
                 "reason": self.entries.get(f.key(), "TODO: justify")}
                for f in sorted(findings, key=lambda f: f.key())
            ],
        }
        with open(self.path, "w", encoding="utf-8") as f:
            json.dump(data, f, indent=2, sort_keys=False)
            f.write("\n")

    def split(self, findings: List[Finding],
              live_files: Optional[set] = None):
        """(new, grandfathered, stale_baseline_keys). An entry whose
        reason is empty or still the "TODO" placeholder does NOT
        grandfather anything — justification is the price of entry.

        ``live_files`` (the analyzed relpaths) enables **rename
        re-anchoring**: when a justified entry's file no longer exists
        and exactly one otherwise-identical finding (same pass, code,
        and anchor) appears in some other file, the entry follows the
        file — a pure rename must not resurface a grandfathered finding
        as new, nor report the old entry as stale. Ambiguous matches
        (two candidate findings, or the old file still present) fall
        through to the strict behavior."""
        keys = {f.key() for f in findings}
        # key -> reason, for entries eligible to re-anchor
        moved: Dict[str, str] = {}
        if live_files is not None:
            orphans: Dict[str, List[str]] = {}  # pass:code:anchor -> keys
            for k in self.entries:
                if k in keys:
                    continue
                # key layout pass:code:file:anchor — only the anchor can
                # itself contain ':', so a bounded split is exact
                try:
                    p, code, file, anchor = k.split(":", 3)
                except ValueError:  # pragma: no cover - malformed entry
                    continue
                reason = self.entries[k].strip()
                if file not in live_files and reason \
                        and not reason.startswith("TODO"):
                    orphans.setdefault(f"{p}:{code}:{anchor}", []).append(k)
            claims: Dict[str, int] = {}
            for f in findings:
                if f.key() not in self.entries:
                    sig = f"{f.pass_name}:{f.code}:{f.anchor}"
                    claims[sig] = claims.get(sig, 0) + 1
            for f in findings:
                if f.key() in self.entries:
                    continue
                sig = f"{f.pass_name}:{f.code}:{f.anchor}"
                cands = orphans.get(sig, [])
                # 1:1 only — two same-anchor findings (a copy) or two
                # orphaned entries cannot be disambiguated as a rename
                if len(cands) == 1 and claims.get(sig) == 1:
                    moved[f.key()] = self.entries[cands[0]]
                    moved[cands[0]] = ""  # consumed: not stale
        new, old = [], []
        for f in findings:
            reason = self.entries.get(f.key(), moved.get(f.key(), "")) \
                .strip()
            if reason and not reason.startswith("TODO"):
                old.append(f)
            else:
                new.append(f)
        stale = sorted(k for k in self.entries
                       if k not in keys and k not in moved)
        return new, old, stale


# -- shared AST helpers ---------------------------------------------------

def parent_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def enclosing_function(node: ast.AST, parents: Dict[ast.AST, ast.AST]):
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur
        cur = parents.get(cur)
    return None


def qualname(node: ast.AST, parents: Dict[ast.AST, ast.AST]) -> str:
    """Dotted path of classes/functions enclosing (and including) node."""
    names = []
    cur: Optional[ast.AST] = node
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)):
            names.append(cur.name)
        cur = parents.get(cur)
    return ".".join(reversed(names)) or "<module>"


def dotted(node: ast.AST) -> Optional[str]:
    """ast.Attribute/Name chain -> "a.b.c", or None for anything else."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def import_aliases(tree: ast.Module) -> Dict[str, str]:
    """Import name -> fully qualified module/symbol path, collected from
    the WHOLE file (module level, ``if TYPE_CHECKING:``/``try:`` blocks,
    and function-local imports — the lazy-import idiom the hot modules
    use to break cycles). Scoping is flattened: a name means the same
    target everywhere in one file, which holds across this codebase."""
    aliases: Dict[str, str] = {}
    for stmt in ast.walk(tree):
        if isinstance(stmt, ast.Import):
            for a in stmt.names:
                aliases[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(stmt, ast.ImportFrom) and stmt.module \
                and stmt.level == 0:
            for a in stmt.names:
                aliases[a.asname or a.name] = f"{stmt.module}.{a.name}"
    return aliases


PassFn = Callable[[Project], List[Finding]]
PASSES: Dict[str, PassFn] = {}


def register(name: str):
    def deco(fn: PassFn) -> PassFn:
        PASSES[name] = fn
        return fn

    return deco


def run_passes(project: Project,
               only: Optional[List[str]] = None,
               timings: Optional[Dict[str, float]] = None) -> List[Finding]:
    """Run the (selected) passes over one shared parsed project.

    ``timings``, when given, is filled with per-pass wall-clock seconds
    (the ``--json`` runner and the ``16_lint`` bench lane both ride it,
    so a pass that goes quadratic shows up as a number, not a hunch).
    """
    import time

    names = only if only else list(PASSES)
    unknown = [n for n in names if n not in PASSES]
    if unknown:
        raise KeyError(f"unknown lint pass(es) {unknown}; "
                       f"known: {sorted(PASSES)}")
    findings: List[Finding] = []
    for name in names:
        t0 = time.monotonic()
        findings.extend(PASSES[name](project))
        if timings is not None:
            timings[name] = time.monotonic() - t0
    findings.sort(key=lambda f: (f.file, f.line, f.code))
    return findings

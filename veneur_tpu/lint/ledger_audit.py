"""LedgerAudit: runtime twin of the drop-flow conservation pass.

The static pass (``lint/dropflow.py``) proves every *lexical* discard
edge in the pipeline hot set credits a ledger counter. What it cannot
see — dynamic dispatch, a credit of the *wrong amount*, a native-path
drop below the AST — this recorder catches with live traffic, the same
static+runtime pairing as lock-discipline/TSan-lite
(``lint/tsan.py``).

An audit is a two-sided sum over registered **terms**::

    audit = LedgerAudit("ingest")
    audit.register("parsed",      "in",  lambda: fleet_totals()["parsed"])
    audit.register("merged",      "out", lambda: fleet_totals()["merged"])
    audit.register("quarantined", "out", ...)
    ...
    audit.snapshot(settled=False)   # record the timeline, don't assert
    audit.snapshot(settled=True)    # boundary: sum(in) must == sum(out)
    audit.assert_clean()

Each snapshot records every term's value and its delta since the
previous snapshot; a **settled** snapshot (an interval boundary where
the pipeline is drained) additionally checks the conservation identity
``sum(in) == sum(out)`` cumulatively and, on mismatch, records a
:class:`LedgerViolation` naming the per-term deltas — the diverging
counter is visible by inspection, not archaeology. Un-settled
snapshots exist because the strict identity is *false* mid-chaos
(requeued state in flight, a sink outage holding emissions back); the
exact invariant is cumulative-at-settled-points, which is also what
the soak gates assert (docs/resilience.md).

Wired in three places: the ``ledger_audit`` pytest fixture
(tests/conftest.py — auto-asserts at teardown, like ``tsan_lite``),
:func:`veneur_tpu.soak.orchestrator.run_soak` (per-interval timeline
snapshots, settled at terminal settlement), and the ``14_soak`` bench
smoke. :func:`for_fleet` and :func:`for_soak_ledger` build the two
standard term sets.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional


@dataclass
class LedgerViolation:
    """One failed conservation check at a settled snapshot."""

    audit: str
    snapshot_idx: int
    label: str
    total_in: int
    total_out: int
    values: Dict[str, int]
    deltas: Dict[str, int]

    def __str__(self):
        terms = ", ".join(
            f"{name}={self.values[name]:+d} (Δ{self.deltas.get(name, 0):+d})"
            for name in sorted(self.values))
        return (f"ledger audit '{self.audit}' snapshot #{self.snapshot_idx}"
                f"{f' [{self.label}]' if self.label else ''}: "
                f"sum(in)={self.total_in} != sum(out)={self.total_out} "
                f"(unaccounted {self.total_in - self.total_out:+d}); "
                f"terms: {terms}")


@dataclass
class LedgerSnapshot:
    idx: int
    label: str
    settled: bool
    values: Dict[str, int]
    deltas: Dict[str, int]
    ok: Optional[bool]  # None on un-settled snapshots


@dataclass
class _Term:
    name: str
    side: str  # "in" | "out"
    fn: Callable[[], int]


class LedgerAudit:
    """Conservation recorder over a set of (side, counter-fn) terms."""

    def __init__(self, name: str = "ledger"):
        self.name = name
        self._terms: List[_Term] = []
        self._lock = threading.Lock()
        self.snapshots: List[LedgerSnapshot] = []
        self.violations: List[LedgerViolation] = []

    def register(self, name: str, side: str,
                 fn: Callable[[], int]) -> "LedgerAudit":
        if side not in ("in", "out"):
            raise ValueError(f"side must be 'in' or 'out', got {side!r}")
        with self._lock:
            if any(t.name == name for t in self._terms):
                raise ValueError(f"duplicate audit term {name!r}")
            self._terms.append(_Term(name, side, fn))
        return self

    # -- snapshots ---------------------------------------------------------

    def snapshot(self, label: str = "",
                 settled: bool = True) -> LedgerSnapshot:
        """Read every term once. ``settled=True`` asserts the cumulative
        identity ``sum(in) == sum(out)`` and records a violation on
        mismatch; ``settled=False`` only extends the timeline (the
        identity is legitimately false mid-interval)."""
        with self._lock:
            values = {t.name: int(t.fn()) for t in self._terms}
            prev = self.snapshots[-1].values if self.snapshots else {}
            deltas = {n: v - prev.get(n, 0) for n, v in values.items()}
            total_in = sum(values[t.name] for t in self._terms
                           if t.side == "in")
            total_out = sum(values[t.name] for t in self._terms
                            if t.side == "out")
            ok: Optional[bool] = None
            if settled:
                ok = total_in == total_out
                if not ok:
                    self.violations.append(LedgerViolation(
                        audit=self.name, snapshot_idx=len(self.snapshots),
                        label=label, total_in=total_in, total_out=total_out,
                        values=values, deltas=deltas))
            snap = LedgerSnapshot(idx=len(self.snapshots), label=label,
                                  settled=settled, values=values,
                                  deltas=deltas, ok=ok)
            self.snapshots.append(snap)
            return snap

    def assert_clean(self):
        if self.violations:
            raise AssertionError(
                f"{len(self.violations)} ledger conservation violation(s):"
                + "".join(f"\n  {v}" for v in self.violations))

    def timeline(self) -> List[dict]:
        """JSON-shaped snapshot history (soak reports, bench lanes)."""
        return [{"idx": s.idx, "label": s.label, "settled": s.settled,
                 "ok": s.ok, "values": dict(s.values),
                 "deltas": dict(s.deltas)} for s in self.snapshots]


# -- standard term sets ----------------------------------------------------

def for_fleet(fleet, name: str = "ingest-fleet") -> LedgerAudit:
    """The ingest-lane conservation identity, fleet-aggregated
    (``IngestFleet.balance()``'s invariant as an audit): everything the
    lanes parsed is merged, quarantined, shed, or still pending at the
    group boundary. Settled snapshots belong after ``merge_sealed``
    with traffic paused."""
    audit = LedgerAudit(name)

    def total(key: str) -> Callable[[], int]:
        return lambda: int(fleet.totals().get(key, 0))

    def pending() -> int:
        n = 0
        for lane in fleet.lanes:
            n += sum(c.records for c in list(lane.sealed))
            n += lane._staged_total
        return n

    audit.register("parsed", "in", total("parsed"))
    audit.register("merged", "out", total("merged"))
    audit.register("quarantined", "out", total("quarantined"))
    audit.register("shed", "out", total("shed_records"))
    audit.register("pending", "out", pending)
    return audit


def for_soak_ledger(ledger, name: str = "soak-global") -> LedgerAudit:
    """The soak plane's global conservation identity
    (``soak/gates.py::conservation_global``) as a live audit:
    ``sent == emitted + shed + quarantined + accounted_lost``. Settled
    only after terminal settlement (the per-interval timeline rides
    along un-asserted)."""
    audit = LedgerAudit(name)
    audit.register("sent_global", "in", lambda: ledger.sent_global)
    audit.register("emitted_global", "out", lambda: ledger.emitted_global)
    audit.register("shed", "out", lambda: ledger.shed)
    audit.register("quarantined", "out", lambda: ledger.quarantined)
    audit.register("accounted_lost", "out",
                   lambda: ledger.accounted_lost)
    return audit

"""Ledger-coverage: the drop-flow surface cannot silently go vacuous.

Drop-flow and except-safety analyze an *explicit* registry — the
:data:`~veneur_tpu.lint.dropflow.HOT_SET` patterns and the credit/source
API names. A registry is only as good as its liveness: rename
``merge_sealed`` and the hot-set entry matches nothing, the pass checks
nothing, and every report stays green while the pipeline's core path is
unanalyzed. (Exactly the failure mode the lock passes hit in PR 12 when
``_flush_locked`` became ``_flush_generation``.)

This pass pins every registry entry to live code:

- ``dead-hot-file``: a :data:`HOT_SET` file that is not in the analyzed
  tree — the file moved or was deleted; follow it.
- ``dead-hot-pattern``: a hot-set qualname pattern matching zero
  functions in its file — the function was renamed; follow it.
- ``dead-registry-entry``: a :data:`CREDIT_CALLS` / :data:`SOURCES`
  name with neither a definition nor a call site anywhere in the tree —
  the credit API is gone, so the discharge it used to recognize is a
  phantom.

The companion *count* floors (≥N hot functions, ≥N credit sites) live
in test_lint's non-vacuity guards — a lint pass should flag structural
drift exactly, not re-litigate magnitudes.
"""

from __future__ import annotations

import ast
import fnmatch
from typing import List, Set

from veneur_tpu.lint.framework import Finding, Project, qualname, register
from veneur_tpu.lint.dropflow import (CREDIT_CALLS, HOT_SET, SOURCES,
                                      _base_name)


def _live_names(project: Project) -> Set[str]:
    """Every function-def name and every callee base name in the tree."""
    names: Set[str] = set()
    for sf in project.files.values():
        for node in sf.nodes:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                names.add(node.name)
            elif isinstance(node, ast.Call):
                base = _base_name(node.func)
                if base:
                    names.add(base)
    return names


@register("ledger-coverage")
def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for relpath in sorted(HOT_SET):
        sf = project.files.get(relpath)
        if sf is None:
            findings.append(Finding(
                pass_name="ledger-coverage", code="dead-hot-file",
                file="veneur_tpu/lint/dropflow.py", line=1,
                anchor=f"hot-file:{relpath}",
                message=(
                    f"HOT_SET names `{relpath}` but the analyzed tree has "
                    f"no such file — the drop-flow surface silently lost "
                    f"a whole module; follow the move in HOT_SET")))
            continue
        qns = [qualname(node, sf.parents) for node in sf.nodes
               if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))]
        for pat in HOT_SET[relpath]:
            if not any(fnmatch.fnmatchcase(qn, pat) for qn in qns):
                findings.append(Finding(
                    pass_name="ledger-coverage", code="dead-hot-pattern",
                    file=relpath, line=1,
                    anchor=f"hot-pattern:{pat}",
                    message=(
                        f"HOT_SET pattern `{pat}` matches no function in "
                        f"{relpath} — the function was renamed or removed "
                        f"and the drop-flow pass silently stopped "
                        f"analyzing it; follow the rename in HOT_SET")))
    live = _live_names(project)
    for kind, names in (("credit", CREDIT_CALLS), ("source", SOURCES)):
        for name in sorted(names):
            if name not in live:
                findings.append(Finding(
                    pass_name="ledger-coverage", code="dead-registry-entry",
                    file="veneur_tpu/lint/dropflow.py", line=1,
                    anchor=f"{kind}:{name}",
                    message=(
                        f"registry {kind} API `{name}` has no definition "
                        f"or call site anywhere in the tree — the "
                        f"discharge it recognizes is a phantom; remove or "
                        f"update the registry entry")))
    findings.sort(key=lambda f: (f.file, f.anchor))
    return findings

"""Lock-order pass: deadlock cycles and locks held across blocking ops.

The lock-discipline pass (``lint/locks.py``) checks that annotated
mutators are *called* under the right lock; it says nothing about what
happens *while* a lock is held. This pass builds the whole-program
lock-acquisition graph and checks the two properties Go's toolchain
would have caught dynamically (``go test -race`` plus the runtime's
deadlock detector):

1. **``lock-cycle``** — two locks acquired in opposite orders on
   different code paths (A→B somewhere, B→A elsewhere) can deadlock the
   moment the two paths run concurrently. Edges come from ``with``
   acquisitions (and ``.acquire()`` calls) reached — directly or through
   the call graph — while another lock is lexically held, plus
   ``@acquires_lock`` annotations. Re-acquiring the *same* lock is not
   an edge: the store lock is an RLock and ``with``-scoped reacquire is
   a supported idiom.

2. **``lock-across-blocking``** — a lock held across a blocking
   operation (``jax.device_get`` / ``.block_until_ready()`` — a full
   device sync, multi-second on a busy chip —, ``os.fsync``, socket
   send/recv verbs, ``urllib.request.urlopen`` — the streamed-POST
   path every sink chunk and forward part rides —, ``time.sleep``)
   turns every waiter on that lock into a waiter on the slow
   operation. The flush/ingest SLO rides on the store lock being held
   only for host-memory work, so any annotated region that
   transitively reaches a blocking op is flagged. The ``urlopen``
   verb is what machine-checks the egress pipeline's off-lock
   guarantee: the chunk-stream workers (core/pipeline.py) POST while
   the store keeps ingesting, and a lock held into their call graph
   would re-serialize flush behind the network.

3. **``hot-path-lock``** — the inverse assertion: a function declared
   ``@lockfree_hot_path`` (core/locking.py) must reach NO lock through
   its whole closed call graph — not an ``@acquires_lock`` callee, not
   a ``with self.<lock>``, not an ``.acquire()``. The ingest reader
   lanes (``veneur_tpu/ingest/lanes.py``) declare their
   recv->decode->stage loop this way: the design point is zero shared
   locks per packet, hand-off at the group boundary only, and a
   regression — someone "just" adding a counter under
   ``Server._counter_lock`` to the lane loop — fails lint instead of
   silently re-serializing every reader core.

Lock identity: the ``@requires_lock``/``@acquires_lock`` registry names
the store lock ``"store"`` (rendered ``<store>``); any other ``with
self.<attr>`` on a lock-shaped attribute is identified as
``ClassName.<attr>`` (falling back to a site-unique id when the
receiver cannot be resolved, so unrelated locks never alias into a
false cycle). Call-graph reach reuses the purity pass's resolver plus
the lock pass's light receiver inference; an unresolvable *method*
call unions the summaries of every same-named method in the package
when that set is small and unambiguous (bounded fan-out keeps this
from flagging generic names).

Suppress a deliberate hold with ``# lint: ok(lock-across-blocking)``
on the ``with`` line (e.g. the checkpoint IO lock, whose entire job is
to serialize a multi-second write+fsync behind a non-blocking probe),
or a known-safe ordering with ``# lint: ok(lock-cycle)`` on one of the
cycle's acquisition sites.

``lock_graph(project)`` exposes the edges (and the lock→blocking-op
reach) for ``python -m veneur_tpu.lint --json`` so future tooling can
diff the graph per PR.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from veneur_tpu.lint.framework import (Finding, Project, SourceFile, dotted,
                                       qualname, register)
from veneur_tpu.lint import locks as locks_pass
from veneur_tpu.lint import purity
from veneur_tpu.lint.purity import walk_shallow

# attribute-name shapes treated as locks even without a visible ctor
import re

_LOCK_ATTR_RE = re.compile(r"(^|_)(lock|gate|mutex)$")

# method names too generic to union across classes when the receiver
# cannot be resolved (unioning `.flush()` would drag every sink and
# group flush into every region)
_UNION_STOPLIST = {"flush", "run", "close", "start", "stop", "write",
                   "read", "send", "get", "put", "add", "reset", "clear",
                   "update", "append", "acquire", "release", "items",
                   "values", "keys", "pop", "join", "wait", "count"}
_UNION_MAX_DEFS = 8

# socket verbs that block on the peer / kernel buffers ('.send' itself
# is excluded: too many non-socket objects expose it)
_SOCKET_VERBS = {"sendall", "sendto", "recvfrom", "recv_into", "recv",
                 "accept", "connect"}

FnKey = Tuple[str, str]


def _hot_path_decoration(node: ast.FunctionDef
                         ) -> Optional[Tuple[str, int]]:
    """(region, decorator line) if ``node`` carries
    ``@lockfree_hot_path("...")``. The decorator's own line is where a
    ``# lint: ok(hot-path-lock)`` pragma lives (node.lineno is the
    ``def`` line, which a reader would not annotate)."""
    for deco in node.decorator_list:
        target = deco.func if isinstance(deco, ast.Call) else deco
        name = dotted(target)
        if name and name.split(".")[-1] == "lockfree_hot_path":
            if isinstance(deco, ast.Call) and deco.args and \
                    isinstance(deco.args[0], ast.Constant):
                return str(deco.args[0].value), deco.lineno
            return "", deco.lineno
    return None


def _blocking_op(node: ast.Call, jax_names: Set[str]) -> Optional[str]:
    """Human-readable op name if this call blocks, else None."""
    if not isinstance(node.func, ast.Attribute):
        return None
    attr = node.func.attr
    name = dotted(node.func)
    prefix = name.split(".")[0] if name else None
    if attr == "block_until_ready":
        return ".block_until_ready()"
    if attr == "device_get" and (prefix in jax_names or prefix == "jax"):
        return "jax.device_get()"
    if attr == "fsync" and prefix == "os":
        return "os.fsync()"
    if attr == "sleep" and prefix == "time":
        return "time.sleep()"
    if attr in _SOCKET_VERBS:
        return f"socket .{attr}()"
    if attr == "urlopen":
        # the streamed-POST verb (PostHelper / sink chunk workers /
        # forward parts): an HTTP round trip under a lock re-serializes
        # the egress pipeline behind the network
        return "urllib urlopen()"
    return None


class _FnSummary:
    __slots__ = ("acquires", "blocking", "callees")

    def __init__(self):
        # lock id -> (file, line) witness of the acquisition
        self.acquires: Dict[str, Tuple[str, int]] = {}
        # op name -> (file, line) witness
        self.blocking: Dict[str, Tuple[str, int]] = {}
        self.callees: Set[FnKey] = set()


class _Analysis:
    """One full lock-order analysis over a project."""

    def __init__(self, project: Project):
        self.project = project
        self.fns = purity._collect_functions(project)
        self.resolver = purity._Resolver(project, self.fns)
        # class name -> annotation lock name ("store") when any method
        # carries a locking decorator naming it
        self.ann_lock: Dict[str, str] = {}
        # class name -> known lock attribute names
        self.lock_attrs: Dict[str, Set[str]] = {}
        # method name -> FnKeys of class methods bearing it (union fallback)
        self.method_defs: Dict[str, List[FnKey]] = {}
        # plain (non-method) defs sharing a name make a union unsafe
        self.plain_defs: Set[str] = set()
        self._attr_types_cache: Dict[str, Dict] = {}
        self._local_env_cache: Dict[ast.FunctionDef, Dict] = {}
        self._jax_cache: Dict[str, Set[str]] = {}
        self._collect_classes()
        # (key, region, decorator line) of every @lockfree_hot_path fn
        self.hot_paths: List[Tuple[FnKey, str, int]] = []
        self.summaries: Dict[FnKey, _FnSummary] = {}
        self._build_summaries()
        self._close_summaries()

    def _jax_names(self, sf: SourceFile) -> Set[str]:
        """Per-file jax import aliases (import_aliases re-walks the
        whole module AST — far too hot to call once per function)."""
        if sf.relpath not in self._jax_cache:
            self._jax_cache[sf.relpath] = purity._jax_aliases(sf)
        return self._jax_cache[sf.relpath]

    # -- class / lock discovery -------------------------------------------

    def _collect_classes(self):
        for sf in self.project.files.values():
            parents = sf.parents
            for node in sf.nodes:
                if isinstance(node, ast.FunctionDef):
                    owner = parents.get(node)
                    if isinstance(owner, ast.ClassDef):
                        self.method_defs.setdefault(node.name, []).append(
                            (sf.relpath, qualname(node, parents)))
                        deco = locks_pass._lock_decoration(node)
                        if deco:
                            self.ann_lock.setdefault(owner.name, deco[1])
                    else:
                        self.plain_defs.add(node.name)
                if not isinstance(node, ast.ClassDef):
                    continue
                self.lock_attrs.setdefault(node.name, set()).update(
                    locks_pass.class_lock_attrs(node))

    def lock_id(self, cls: Optional[str], attr: str, sf: SourceFile,
                line: int) -> str:
        """Stable identity for a lock acquisition site."""
        if cls is not None:
            ann = self.ann_lock.get(cls)
            if ann and attr == "_lock":
                return f"<{ann}>"
            return f"{cls}.{attr}"
        # unresolved receiver: site-unique id; never aliases two
        # different locks into a fake cycle
        return f"?{sf.relpath}:{line}.{attr}"

    def _is_lock_expr(self, expr: ast.AST, cls: Optional[str]) -> bool:
        name = dotted(expr)
        if name is None:
            return False
        attr = name.split(".")[-1]
        if _LOCK_ATTR_RE.search(attr):
            return True
        return cls is not None and attr in self.lock_attrs.get(cls, ())

    def _with_locks(self, node: ast.With, cls: Optional[str],
                    sf: SourceFile) -> List[str]:
        out = []
        for item in node.items:
            expr = item.context_expr
            if not self._is_lock_expr(expr, cls):
                continue
            name = dotted(expr)
            attr = name.split(".")[-1]
            parts = name.split(".")
            owner = cls if (len(parts) == 2 and parts[0] == "self") else None
            out.append(self.lock_id(owner, attr, sf, node.lineno))
        return out

    # -- call resolution ---------------------------------------------------

    def _receiver_classes(self, call: ast.Call, sf: SourceFile,
                          encl: Optional[ast.FunctionDef],
                          cls: Optional[str]) -> Set[str]:
        """Light receiver type inference (borrowed from lint/locks.py)."""
        if not isinstance(call.func, ast.Attribute):
            return set()
        recv = call.func.value
        if sf.relpath not in self._attr_types_cache:
            self._attr_types_cache[sf.relpath] = \
                locks_pass._class_attr_types(sf)
        self_attrs = self._attr_types_cache[sf.relpath].get(cls, {}) \
            if cls else {}
        if isinstance(recv, ast.Attribute) \
                and isinstance(recv.value, ast.Name) \
                and recv.value.id == "self":
            return set(self_attrs.get(recv.attr, set()))
        if isinstance(recv, ast.Name) and encl is not None:
            if encl not in self._local_env_cache:
                all_classes = set(self.lock_attrs) | {
                    k[1].split(".")[0] for k in self.fns if "." in k[1]}
                self._local_env_cache[encl] = locks_pass._infer_locals(
                    encl, self_attrs, all_classes)
            return set(self._local_env_cache[encl].get(recv.id, set()))
        return set()

    def _callees(self, call: ast.Call, sf: SourceFile,
                 encl: Optional[ast.FunctionDef], cls: Optional[str],
                 scope: Optional[str]) -> List[FnKey]:
        key = self.resolver.resolve(call.func, sf, cls, scope=scope)
        if key is not None:
            return [key]
        if not isinstance(call.func, ast.Attribute):
            return []
        method = call.func.attr
        rtypes = self._receiver_classes(call, sf, encl, cls)
        if rtypes:
            found = []
            for t in rtypes:
                for k in self.method_defs.get(method, ()):
                    if k[1].split(".")[0] == t \
                            and k[1].endswith("." + method):
                        found.append(k)
            if found:
                return found
            return []  # resolved to classes that don't define it
        # unresolvable receiver: bounded union of same-named methods
        if method in _UNION_STOPLIST or method in self.plain_defs:
            return []
        defs = self.method_defs.get(method, ())
        if 0 < len(defs) <= _UNION_MAX_DEFS:
            return list(defs)
        return []

    # -- summaries ---------------------------------------------------------

    def _build_summaries(self):
        for key, info in self.fns.items():
            sf = info.sf
            jax_names = self._jax_names(sf)
            s = _FnSummary()
            hot = _hot_path_decoration(info.node)
            if hot is not None:
                self.hot_paths.append((key, hot[0], hot[1]))
            deco = locks_pass._lock_decoration(info.node)
            if deco and deco[0] == "acquires":
                s.acquires.setdefault(f"<{deco[1]}>",
                                      (sf.relpath, info.node.lineno))
            for node in walk_shallow(info.node):
                if isinstance(node, ast.With):
                    for lock in self._with_locks(node, info.cls, sf):
                        s.acquires.setdefault(lock,
                                              (sf.relpath, node.lineno))
                elif isinstance(node, ast.Call):
                    if isinstance(node.func, ast.Attribute) \
                            and node.func.attr == "acquire" \
                            and self._is_lock_expr(node.func.value,
                                                   info.cls):
                        name = dotted(node.func.value)
                        parts = name.split(".")
                        owner = info.cls if (len(parts) == 2
                                             and parts[0] == "self") \
                            else None
                        lock = self.lock_id(owner, parts[-1], sf,
                                            node.lineno)
                        s.acquires.setdefault(lock,
                                              (sf.relpath, node.lineno))
                        continue
                    op = _blocking_op(node, jax_names)
                    if op:
                        s.blocking.setdefault(op, (sf.relpath, node.lineno))
                    else:
                        encl = info.node
                        for c in self._callees(node, sf, encl, info.cls,
                                               info.qual):
                            if c != key:
                                s.callees.add(c)
            self.summaries[key] = s

    def _close_summaries(self):
        """Propagate acquires/blocking up the call graph to a fixed
        point (reverse-edge worklist)."""
        callers: Dict[FnKey, Set[FnKey]] = {}
        for key, s in self.summaries.items():
            for c in s.callees:
                if c in self.summaries:
                    callers.setdefault(c, set()).add(key)
        work = list(self.summaries)
        pending = set(work)
        while work:
            key = work.pop()
            pending.discard(key)
            s = self.summaries[key]
            changed = False
            for c in s.callees:
                cs = self.summaries.get(c)
                if cs is None:
                    continue
                for lock, wit in cs.acquires.items():
                    if lock not in s.acquires:
                        s.acquires[lock] = wit
                        changed = True
                for op, wit in cs.blocking.items():
                    if op not in s.blocking:
                        s.blocking[op] = wit
                        changed = True
            if changed:
                for caller in callers.get(key, ()):
                    if caller not in pending:
                        pending.add(caller)
                        work.append(caller)

    # -- regions -----------------------------------------------------------

    def regions(self):
        """Yield (held_lock_id, region_stmts, sf, cls, fn_info,
        with_line_or_None) for every lexical lock-holding region."""
        for key, info in self.fns.items():
            sf = info.sf
            deco = locks_pass._lock_decoration(info.node)
            if deco and deco[0] == "requires":
                yield (f"<{deco[1]}>", list(info.node.body), sf, info,
                       info.node.lineno)
            for node in walk_shallow(info.node):
                if not isinstance(node, ast.With):
                    continue
                for lock in self._with_locks(node, info.cls, sf):
                    yield lock, list(node.body), sf, info, node.lineno

    def region_reach(self, held: str, body: List[ast.stmt],
                     sf: SourceFile, info) -> Tuple[
                         Dict[str, Tuple[str, int]],
                         Dict[str, Tuple[str, int]]]:
        """(acquired_locks, blocking_ops) reached from a held region,
        each mapped to a (file, line) witness AT the region."""
        acquired: Dict[str, Tuple[str, int]] = {}
        blocking: Dict[str, Tuple[str, int]] = {}
        jax_names = self._jax_names(sf)

        def visit(stmts):
            stack = list(stmts)
            while stack:
                node = stack.pop()
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.Lambda)):
                    continue  # deferred execution: not under this hold
                if isinstance(node, ast.With):
                    for lock in self._with_locks(node, info.cls, sf):
                        if lock != held:
                            acquired.setdefault(
                                lock, (sf.relpath, node.lineno))
                if isinstance(node, ast.Call):
                    op = _blocking_op(node, jax_names)
                    if op:
                        blocking.setdefault(op, (sf.relpath, node.lineno))
                    elif isinstance(node.func, ast.Attribute) \
                            and node.func.attr == "acquire" \
                            and self._is_lock_expr(node.func.value,
                                                   info.cls):
                        name = dotted(node.func.value)
                        parts = name.split(".")
                        owner = info.cls if (len(parts) == 2
                                             and parts[0] == "self") \
                            else None
                        lock = self.lock_id(owner, parts[-1], sf,
                                            node.lineno)
                        if lock != held:
                            acquired.setdefault(
                                lock, (sf.relpath, node.lineno))
                    else:
                        for c in self._callees(node, sf, info.node,
                                               info.cls, info.qual):
                            cs = self.summaries.get(c)
                            if cs is None:
                                continue
                            for lock in cs.acquires:
                                if lock != held:
                                    acquired.setdefault(
                                        lock, (sf.relpath, node.lineno))
                            for op in cs.blocking:
                                blocking.setdefault(
                                    op, (sf.relpath, node.lineno))
                stack.extend(ast.iter_child_nodes(node))

        visit(body)
        return acquired, blocking


def _analyze(project: Project):
    """(findings, graph) for one project; graph is the --json payload.
    Memoized on the project instance: the runner needs both the
    findings (the pass) and the graph (--json) from one traversal."""
    cached = getattr(project, "_lockorder_result", None)
    if cached is not None:
        return cached
    an = _Analysis(project)
    findings: List[Finding] = []
    # edge (A, B) -> witness dict
    edges: Dict[Tuple[str, str], dict] = {}
    blocked: Dict[Tuple[str, str, str], dict] = {}
    suppressed_edges: Set[Tuple[str, str]] = set()

    for held, body, sf, info, line in an.regions():
        acquired, blocking = an.region_reach(held, body, sf, info)
        for lock, (wfile, wline) in sorted(acquired.items()):
            edge = (held, lock)
            if edge not in edges:
                edges[edge] = {"from": held, "to": lock, "file": wfile,
                               "line": wline, "via": info.qual}
            if sf.suppressed(line, "lock-cycle") \
                    or sf.suppressed(wline, "lock-cycle"):
                suppressed_edges.add(edge)
        for op, (wfile, wline) in sorted(blocking.items()):
            key = (held, info.qual, op)
            if key in blocked:
                continue
            acknowledged = sf.suppressed(line, "lock-across-blocking") \
                or sf.suppressed(wline, "lock-across-blocking")
            # acknowledged holds stay in the diffable graph — they are
            # real, just justified — but raise no finding
            blocked[key] = {"lock": held, "op": op, "file": wfile,
                            "line": wline, "via": info.qual,
                            "acknowledged": acknowledged}
            if acknowledged:
                continue
            findings.append(Finding(
                pass_name="lock-order", code="lock-across-blocking",
                file=sf.relpath, line=wline,
                anchor=f"{info.qual}:{held}->{op}",
                message=(f"{held} is held across {op} (reached from "
                         f"{info.qual}); every waiter on the lock now "
                         f"waits on the blocking op — move it outside "
                         f"the hold or justify with "
                         f"`# lint: ok(lock-across-blocking)`")))

    # hot-path lock-freedom: a @lockfree_hot_path function whose CLOSED
    # summary reaches any lock acquisition breaks the share-nothing
    # ingest contract (lanes hand off at the group boundary only)
    hot_report = []
    for key, region, deco_line in sorted(an.hot_paths,
                                         key=lambda h: h[0]):
        s = an.summaries.get(key)
        if s is None:
            continue
        qual = an.fns[key].qual
        sf = an.fns[key].sf
        reached = []
        for lock, (wfile, wline) in sorted(s.acquires.items()):
            reached.append(lock)
            # the acquisition witness may live in ANOTHER module than
            # the decorated function: anchor the finding at the
            # decorator (this file, stable line) and honor a pragma at
            # either the decorator or the actual acquisition site
            wsf = project.files.get(wfile)
            if sf.suppressed(deco_line, "hot-path-lock") \
                    or (wsf is not None
                        and wsf.suppressed(wline, "hot-path-lock")):
                continue
            findings.append(Finding(
                pass_name="lock-order", code="hot-path-lock",
                file=sf.relpath, line=deco_line,
                anchor=f"{qual}:{region or 'hot'}->{lock}",
                message=(f"{qual} is declared @lockfree_hot_path"
                         f"({region!r}) but its call graph reaches "
                         f"lock {lock} (acquired at {wfile}:{wline}); "
                         f"the hot path must stay lock-free — stage "
                         f"into lane-local state and hand off at the "
                         f"group boundary instead")))
        hot_report.append({"fn": qual, "region": region,
                           "file": sf.relpath, "line": deco_line,
                           "locks": reached})

    # cycle detection over the lock edges (unique locks only; the
    # site-unique '?' ids can never complete a cycle by construction)
    adj: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        adj.setdefault(a, set()).add(b)
    seen_cycles: Set[frozenset] = set()
    for start in sorted(adj):
        # DFS bounded by the tiny lock alphabet
        stack = [(start, [start])]
        while stack:
            node, path = stack.pop()
            for nxt in sorted(adj.get(node, ())):
                if nxt == start and len(path) > 1:
                    cyc_edges = [(path[i], path[(i + 1) % len(path)])
                                 for i in range(len(path))]
                    # dedup on the EDGE set: A->B->C->A and its reverse
                    # are distinct cycles over the same locks, and a
                    # suppressed cycle must not shadow an unsuppressed
                    # twin
                    cyc = frozenset(cyc_edges)
                    if cyc in seen_cycles:
                        continue
                    seen_cycles.add(cyc)
                    if any(e in suppressed_edges for e in cyc_edges):
                        continue
                    w = edges[cyc_edges[0]]
                    order = " -> ".join(path + [start])
                    locks_in_cycle = sorted({a for a, _ in cyc_edges})
                    findings.append(Finding(
                        pass_name="lock-order", code="lock-cycle",
                        file=w["file"], line=w["line"],
                        anchor=f"cycle:{'->'.join(locks_in_cycle)}",
                        message=(f"lock acquisition cycle {order}: these "
                                 f"locks are taken in conflicting orders "
                                 f"on different paths "
                                 + "; ".join(
                                     f"{a}->{b} at {edges[(a, b)]['file']}:"
                                     f"{edges[(a, b)]['line']}"
                                     for a, b in cyc_edges))))
                elif nxt not in path:
                    stack.append((nxt, path + [nxt]))

    graph = {"edges": sorted(edges.values(),
                             key=lambda e: (e["from"], e["to"])),
             "blocking": sorted(blocked.values(),
                                key=lambda e: (e["lock"], e["op"],
                                               e["via"])),
             # every asserted-lock-free hot path and what (if anything)
             # it reaches — diffable per PR like the edges
             "hot_paths": hot_report}
    project._lockorder_result = (findings, graph)
    return findings, graph


def lock_graph(project: Project) -> dict:
    """The acquisition graph for --json output / future diff tooling."""
    return _analyze(project)[1]


@register("lock-order")
def run(project: Project) -> List[Finding]:
    return _analyze(project)[0]

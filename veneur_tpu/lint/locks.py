"""Lock-discipline pass: ``@requires_lock`` call sites must hold the lock.

The aggregation store's concurrency contract (core/store.py): group
state mutates only under ``MetricStore._lock``; flushes mutate only
*retired* generations they exclusively own. Go's race detector enforced
this in the reference — here the contract is spelled as annotations
(``veneur_tpu/core/locking.py``) and this pass walks every call site:

A call to a ``@requires_lock(L)``-annotated function is legal when it is

  1. lexically inside a ``with <expr>._lock:`` block (the convention:
     the owning object's ``_lock`` attribute IS lock ``L``), or
  2. inside a function annotated ``@requires_lock(L)`` itself — the
     obligation propagates to *that* function's call sites, which this
     pass checks in turn (the call-graph walk), or
  3. suppressed inline (``# lint: ok(unlocked-call)`` — e.g. a retired
     flush generation the caller exclusively owns) or baselined.

Receiver resolution is a light, conservative type inference
(``self.attr = GroupClass(...)`` bindings, local aliases, annotated
parameters, conditional/tuple assignments). Where the receiver cannot
be resolved, the bare method name matches only when it is unambiguous —
i.e. no *unannotated or lock-acquiring* definition elsewhere in the
package shares the name (so ``store.snapshot_state()``, which acquires
internally, never false-positives against the groups' snapshot_state).

What the static walk cannot see (dynamic dispatch, getattr) is covered
at runtime by the TSan-lite fixture (``veneur_tpu/lint/tsan.py``).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from veneur_tpu.lint.framework import (Finding, Project, SourceFile, dotted,
                                       qualname, register)

_DECOS = {"requires_lock": "requires", "acquires_lock": "acquires"}

# constructors that make a self-attribute a lock; shared by the
# lock-order and lockset passes so they can never disagree about which
# classes own locks
LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}


def class_lock_attrs(cls: ast.ClassDef) -> Set[str]:
    """Lock attributes ``cls`` assigns to self anywhere in its body."""
    out: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            ctor = dotted(node.value.func)
            if ctor and ctor.split(".")[-1] in LOCK_CTORS:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Attribute) \
                            and isinstance(tgt.value, ast.Name) \
                            and tgt.value.id == "self":
                        out.add(tgt.attr)
    return out


def _lock_decoration(fn: ast.FunctionDef) -> Optional[Tuple[str, str]]:
    """('requires'|'acquires', lock_name) if the def carries one."""
    for dec in fn.decorator_list:
        if not isinstance(dec, ast.Call):
            continue
        name = dotted(dec.func)
        if name is None:
            continue
        kind = _DECOS.get(name.split(".")[-1])
        if kind and dec.args and isinstance(dec.args[0], ast.Constant) \
                and isinstance(dec.args[0].value, str):
            return kind, dec.args[0].value
    return None


class _Registry:
    """Annotated definitions across the whole package."""

    def __init__(self):
        # method name -> set of lock names it may require
        self.requires: Dict[str, Set[str]] = {}
        # (class name, method name) -> lock name, for resolved receivers
        self.by_class: Dict[Tuple[str, str], str] = {}
        # class names owning at least one @requires_lock method
        self.group_classes: Set[str] = set()
        # module-level @requires_lock functions: bare name -> lock
        self.functions: Dict[str, str] = {}
        # names that ALSO exist as unannotated/acquiring defs somewhere,
        # making a bare-name match unsafe
        self.ambiguous: Set[str] = set()


def _build_registry(project: Project) -> _Registry:
    reg = _Registry()
    plain_defs: Set[str] = set()
    for sf in project.files.values():
        parents = sf.parents
        for node in sf.nodes:
            if not isinstance(node, ast.FunctionDef):
                continue
            deco = _lock_decoration(node)
            owner = parents.get(node)
            in_class = isinstance(owner, ast.ClassDef)
            if deco and deco[0] == "requires":
                lock = deco[1]
                reg.requires.setdefault(node.name, set()).add(lock)
                if in_class:
                    reg.by_class[(owner.name, node.name)] = lock
                    reg.group_classes.add(owner.name)
                else:
                    reg.functions[node.name] = lock
            else:
                plain_defs.add(node.name)
    reg.ambiguous = set(reg.requires) & plain_defs
    return reg


def _class_attr_types(sf: SourceFile) -> Dict[str, Dict[str, Set[str]]]:
    """class name -> {self-attribute -> possible class names} from
    ``self.attr = ClassName(...)`` assignments anywhere in the class."""
    out: Dict[str, Dict[str, Set[str]]] = {}

    def ctor_names(value: ast.AST) -> Set[str]:
        names: Set[str] = set()
        if isinstance(value, ast.Call):
            name = dotted(value.func)
            if name:
                names.add(name.split(".")[-1])
        elif isinstance(value, ast.IfExp):
            names |= ctor_names(value.body)
            names |= ctor_names(value.orelse)
        return names

    for cls in sf.nodes:
        if not isinstance(cls, ast.ClassDef):
            continue
        attrs = out.setdefault(cls.name, {})
        for node in ast.walk(cls):
            if not isinstance(node, ast.Assign):
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Attribute) \
                        and isinstance(tgt.value, ast.Name) \
                        and tgt.value.id == "self":
                    got = ctor_names(node.value)
                    if got:
                        attrs.setdefault(tgt.attr, set()).update(got)
    return out


def _infer_locals(fn: ast.FunctionDef, self_attrs: Dict[str, Set[str]],
                  known_classes: Set[str]) -> Dict[str, Set[str]]:
    """variable -> possible class names, for receivers local to ``fn``."""
    env: Dict[str, Set[str]] = {}

    for arg in list(fn.args.args) + list(fn.args.kwonlyargs):
        ann = arg.annotation
        name = None
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            name = ann.value.strip('"')
        elif ann is not None:
            name = dotted(ann)
        if name and name.split(".")[-1] in known_classes:
            env[arg.arg] = {name.split(".")[-1]}

    def expr_types(value: ast.AST) -> Set[str]:
        types: Set[str] = set()
        if isinstance(value, ast.Call):
            name = dotted(value.func)
            if name and name.split(".")[-1] in known_classes:
                types.add(name.split(".")[-1])
        elif isinstance(value, ast.Attribute) \
                and isinstance(value.value, ast.Name) \
                and value.value.id == "self":
            types |= self_attrs.get(value.attr, set())
        elif isinstance(value, ast.Name):
            types |= env.get(value.id, set())
        elif isinstance(value, ast.IfExp):
            types |= expr_types(value.body)
            types |= expr_types(value.orelse)
        return types

    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign):
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                got = expr_types(node.value)
                if got:
                    env.setdefault(tgt.id, set()).update(got)
            elif isinstance(tgt, ast.Tuple) \
                    and isinstance(node.value, ast.Tuple) \
                    and len(tgt.elts) == len(node.value.elts):
                for t, v in zip(tgt.elts, node.value.elts):
                    if isinstance(t, ast.Name):
                        got = expr_types(v)
                        if got:
                            env.setdefault(t.id, set()).update(got)
    return env


def _holds_lock(node: ast.AST, parents: Dict[ast.AST, ast.AST],
                lock: str) -> bool:
    """Inside ``with <expr>._lock:`` or inside a function that itself
    ``@requires_lock`` the same lock. An ``@acquires_lock`` function
    does NOT blanket-exempt its body — only its actual ``with`` blocks
    hold the lock (code before/after them is exactly where an unlocked
    mutation would hide)."""
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, ast.With):
            for item in cur.items:
                name = dotted(item.context_expr)
                if name and name.split(".")[-1] == "_lock":
                    return True
        if isinstance(cur, ast.FunctionDef):
            deco = _lock_decoration(cur)
            if deco and deco[0] == "requires" and deco[1] == lock:
                return True
        cur = parents.get(cur)
    return False


@register("lock-discipline")
def run(project: Project) -> List[Finding]:
    reg = _build_registry(project)
    findings: List[Finding] = []
    if not reg.requires and not reg.functions:
        return findings

    for sf in project.files.values():
        parents = sf.parents
        attr_types = _class_attr_types(sf)
        local_env_cache: Dict[ast.FunctionDef, Dict[str, Set[str]]] = {}

        def receiver_types(call: ast.Call) -> Set[str]:
            recv = call.func.value  # type: ignore[union-attr]
            encl = parents.get(call)
            while encl is not None and not isinstance(encl, ast.FunctionDef):
                encl = parents.get(encl)
            cls = parents.get(encl) if encl is not None else None
            self_attrs = attr_types.get(cls.name, {}) \
                if isinstance(cls, ast.ClassDef) else {}
            if isinstance(recv, ast.Attribute) \
                    and isinstance(recv.value, ast.Name) \
                    and recv.value.id == "self":
                return self_attrs.get(recv.attr, set())
            if isinstance(recv, ast.Name) and encl is not None:
                if encl not in local_env_cache:
                    local_env_cache[encl] = _infer_locals(
                        encl, self_attrs, reg.group_classes)
                return local_env_cache[encl].get(recv.id, set())
            return set()

        for node in sf.nodes:
            if not isinstance(node, ast.Call):
                continue
            lock = None
            method = None
            if isinstance(node.func, ast.Attribute):
                method = node.func.attr
                locks = reg.requires.get(method)
                if not locks:
                    continue
                rtypes = receiver_types(node)
                resolved = {reg.by_class[(t, method)] for t in rtypes
                            if (t, method) in reg.by_class}
                if resolved:
                    lock = sorted(resolved)[0]
                elif rtypes:
                    continue  # resolved to a class without the contract
                elif method not in reg.ambiguous:
                    lock = sorted(locks)[0]
                else:
                    continue  # ambiguous bare name, unresolvable receiver
            elif isinstance(node.func, ast.Name):
                method = node.func.id
                lock = reg.functions.get(method)
                if lock is None:
                    continue
            else:
                continue
            if _holds_lock(node, parents, lock):
                continue
            if sf.suppressed(node.lineno, "unlocked-call"):
                continue
            anchor = f"{qualname(node, parents)}->{method}"
            findings.append(Finding(
                pass_name="lock-discipline", code="unlocked-call",
                file=sf.relpath, line=node.lineno, anchor=anchor,
                message=(f"call to @requires_lock({lock!r}) method "
                         f"{method}() outside a `with ..._lock:` block and "
                         f"outside any @requires_lock({lock!r}) function")))
    return findings

"""Eraser-style lockset analysis: one static pass, one runtime detector.

The lock-discipline pass checks *annotated* mutators; TSan-lite v1
checked the same contract at runtime. Neither could see a race on a
field that has no annotated accessor at all — a telemetry counter
bumped from two threads, a controller watermark rewritten from a reader
loop. This module closes that hole with the classic lockset algorithm
(Savage et al., "Eraser: A Dynamic Data Race Detector for Multithreaded
Programs", TOCS 1997): every shared field has a *candidate lockset*,
refined to the intersection of the locks held at each access; an empty
lockset on a shared, written field means no lock consistently protects
it.

**Static half** (the ``lockset`` pass): for every class that owns a
lock (a ``threading.Lock``/``RLock``/... assigned to ``self`` in the
class body), every ``self.<field>`` write site outside ``__init__`` is
collected with the set of the class's locks *lexically* held there
(``with self._lock:`` blocks; ``@requires_lock`` bodies count as
holding the annotated lock). The candidate lockset of a field is the
intersection across its write sites; a field whose lockset is empty
even though SOME site holds a lock is flagged ``inconsistent-lockset``
— the classic "mostly locked" bug shape. Fields written only unlocked
are presumed thread-confined (flagging them would bury the signal);
establishing writes in ``__init__`` are ignored, as Eraser's
initialization state machine prescribes. Suppress a deliberate
off-lock write with ``# lint: ok(inconsistent-lockset) <why>``.

**Runtime half** (:class:`FieldRaceRecorder`): instruments live
objects (store groups, ``OverloadController``, ``ComputeBreaker``,
``Checkpointer`` — anything handed to :meth:`instrument`) by swapping
in a subclass whose ``__getattribute__``/``__setattr__`` feed every
tracked-field access into the per-field Eraser state machine
(virgin → exclusive → shared → shared-modified), with lock ownership
observed through :class:`TrackedLock` proxies. A write to a shared
field with an empty candidate lockset is reported with BOTH stacks —
the remembered prior access and the racing write. Reporting is
write-biased: a lone unlocked *read* only refines the lockset (under
the GIL a single attribute read cannot tear, and flagging the
read-after-join idiom would drown real races). Mutations on retired
flush generations are exempt (``_retired``), mirroring TSan-lite.
``lint/tsan.py``'s :class:`LockStateRecorder` arms one of these over
the store automatically, so the tier-1 TSan tests run genuine data-race
detection across the generation-swap and requeue paths.
"""

from __future__ import annotations

import ast
import sys
import threading
from dataclasses import dataclass, field as dc_field
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from veneur_tpu.lint.framework import (Finding, Project, dotted, qualname,
                                       register)
from veneur_tpu.lint import locks as locks_pass
from veneur_tpu.lint.locks import class_lock_attrs as _class_locks


# ---------------------------------------------------------------------------
# static pass
# ---------------------------------------------------------------------------


def _held_at(node: ast.AST, parents, lock_attrs: Set[str],
             ann_lock_attr: Optional[str]) -> FrozenSet[str]:
    """The class's locks lexically held at ``node``."""
    held: Set[str] = set()
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, ast.With):
            for item in cur.items:
                name = dotted(item.context_expr)
                if name and name.startswith("self.") \
                        and name.split(".")[-1] in lock_attrs:
                    held.add(name.split(".")[-1])
        if isinstance(cur, ast.FunctionDef):
            deco = locks_pass._lock_decoration(cur)
            if deco and deco[0] == "requires" and ann_lock_attr:
                held.add(ann_lock_attr)
        cur = parents.get(cur)
    return frozenset(held)


def _self_field_writes(fn: ast.FunctionDef):
    """(field, node) pairs for every ``self.X`` write (incl. augmented
    and subscript/content writes) inside ``fn``."""
    for node in ast.walk(fn):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for tgt in targets:
            base = tgt
            while isinstance(base, ast.Subscript):
                base = base.value
            if isinstance(base, ast.Attribute) \
                    and isinstance(base.value, ast.Name) \
                    and base.value.id == "self":
                yield base.attr, node


@register("lockset")
def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for sf in project.files.values():
        parents = sf.parents
        for cls in sf.nodes:
            if not isinstance(cls, ast.ClassDef):
                continue
            lock_attrs = _class_locks(cls)
            if not lock_attrs:
                continue
            # a @requires_lock/@acquires_lock class maps its annotation
            # onto "_lock" by convention (see lint/locks.py)
            ann_attr = None
            for m in cls.body:
                if isinstance(m, ast.FunctionDef) \
                        and locks_pass._lock_decoration(m) \
                        and "_lock" in lock_attrs:
                    ann_attr = "_lock"
                    break
            # field -> [(held, line, qual, suppressed)]
            sites: Dict[str, List[Tuple[FrozenSet[str], int, str, bool]]] = {}
            for m in cls.body:
                if not isinstance(m, ast.FunctionDef):
                    continue
                if m.name in ("__init__", "__new__", "__post_init__"):
                    continue  # establishing writes (Eraser's init state)
                for fieldname, node in _self_field_writes(m):
                    if fieldname in lock_attrs:
                        continue  # rebinding a lock is plumbing, not data
                    held = _held_at(node, parents, lock_attrs, ann_attr)
                    supp = sf.suppressed(node.lineno, "inconsistent-lockset")
                    sites.setdefault(fieldname, []).append(
                        (held, node.lineno, qualname(node, parents), supp))
            for fieldname, accesses in sorted(sites.items()):
                live = [a for a in accesses if not a[3]]
                if not live:
                    continue
                lockset = frozenset.intersection(*[a[0] for a in live])
                ever_locked = any(a[0] for a in live)
                if lockset or not ever_locked:
                    continue
                unlocked = [a for a in live if not a[0]] or live
                lines = ", ".join(f"{a[2]}:{a[1]}" for a in unlocked[:4])
                findings.append(Finding(
                    pass_name="lockset", code="inconsistent-lockset",
                    file=sf.relpath, line=unlocked[0][1],
                    anchor=f"{cls.name}.{fieldname}",
                    message=(
                        f"{cls.name}.{fieldname} has an empty candidate "
                        f"lockset: written under {sorted(lock_attrs)} at "
                        f"some sites but with no common lock at {lines} — "
                        f"hold the lock there or justify with "
                        f"`# lint: ok(inconsistent-lockset)`")))
    return findings


# ---------------------------------------------------------------------------
# runtime detector
# ---------------------------------------------------------------------------


class TrackedLock:
    """Delegating lock proxy that records per-thread ownership so the
    recorder can compute the lockset at each field access. Supports the
    full Lock/RLock surface the codebase uses (``with``, ``acquire``
    with blocking/timeout, ``_is_owned`` for TSan-lite)."""

    def __init__(self, inner, name: str, recorder: "FieldRaceRecorder"):
        self._inner = inner
        self._name = name
        self._rec = recorder

    def acquire(self, *args, **kwargs):
        got = self._inner.acquire(*args, **kwargs)
        if got:
            self._rec._note_acquire(self._name)
        return got

    def release(self):
        self._inner.release()
        self._rec._note_release(self._name)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked()

    def _is_owned(self):
        is_owned = getattr(self._inner, "_is_owned", None)
        if is_owned is not None:
            return is_owned()
        return self._inner.locked()


@dataclass
class RaceReport:
    """One racy pair, with both sides' stacks (Eraser's report shape)."""

    owner: str          # instrumented object label
    field: str
    first_thread: str
    first_op: str       # "read" | "write"
    first_stack: List[str]
    second_thread: str
    second_stack: List[str]
    locks_held: FrozenSet[str] = dc_field(default_factory=frozenset)

    def __str__(self):
        a = "\n      ".join(self.first_stack[-4:])
        b = "\n      ".join(self.second_stack[-4:])
        return (f"race on {self.owner}.{self.field}: no common lock "
                f"protects it\n  first:  {self.first_op} on thread "
                f"{self.first_thread}\n      {a}\n  second: write on "
                f"thread {self.second_thread} (locks held: "
                f"{sorted(self.locks_held) or 'none'})\n      {b}")


_VIRGIN, _EXCLUSIVE, _SHARED, _SHARED_MOD = range(4)


class _FieldState:
    __slots__ = ("state", "owner", "lockset", "last_thread", "last_stack",
                 "last_was_write", "other_thread", "other_stack",
                 "other_was_write", "reported")

    def __init__(self):
        self.state = _VIRGIN
        self.owner = None
        self.lockset: Optional[FrozenSet[str]] = None  # None == top (all)
        self.last_thread = ""
        self.last_stack: List[str] = []
        self.last_was_write = False
        # most recent access by a thread OTHER than the current one —
        # the "first" side of a reported racy pair
        self.other_thread = ""
        self.other_stack: List[str] = []
        self.other_was_write = False
        self.reported = False


def _stack(skip: int = 3) -> List[str]:
    """Innermost-last caller stack, cheap enough for per-access capture
    (sys._getframe walk, no linecache / traceback machinery)."""
    out: List[str] = []
    try:
        f = sys._getframe(skip)
    except ValueError:  # pragma: no cover - shallow stack
        return out
    while f is not None and len(out) < 8:
        code = f.f_code
        out.append(f"{code.co_filename.rsplit('/', 1)[-1]}:{f.f_lineno} "
                   f"in {code.co_name}")
        f = f.f_back
    out.reverse()
    return out


class FieldRaceRecorder:
    """Eraser-style per-field lockset refinement over live objects."""

    def __init__(self):
        self._slock = threading.Lock()
        self._tls = threading.local()
        self._state: Dict[Tuple[int, str], _FieldState] = {}
        self._labels: Dict[int, str] = {}
        self.races: List[RaceReport] = []
        self._instrumented: List[tuple] = []   # (obj, original class)
        self._locks: List[tuple] = []          # (owner, attr, original)

    # -- lock tracking -----------------------------------------------------

    def track_lock(self, owner, attr: str, name: str) -> TrackedLock:
        """Replace ``owner.<attr>`` with a TrackedLock proxy named
        ``name``; restored by :meth:`restore`."""
        inner = object.__getattribute__(owner, "__dict__").get(attr) \
            if hasattr(owner, "__dict__") else getattr(owner, attr)
        if isinstance(inner, TrackedLock):
            return inner
        proxy = TrackedLock(inner, name, self)
        object.__setattr__(owner, attr, proxy)
        self._locks.append((owner, attr, inner))
        return proxy

    def _held_map(self) -> Dict[str, int]:
        m = getattr(self._tls, "held", None)
        if m is None:
            m = self._tls.held = {}
        return m

    def _note_acquire(self, name: str):
        m = self._held_map()
        m[name] = m.get(name, 0) + 1

    def _note_release(self, name: str):
        m = self._held_map()
        depth = m.get(name, 0) - 1
        if depth <= 0:
            m.pop(name, None)
        else:
            m[name] = depth

    def held(self) -> FrozenSet[str]:
        return frozenset(self._held_map())

    # -- instrumentation ---------------------------------------------------

    def instrument(self, obj, label: Optional[str] = None,
                   fields: Optional[Set[str]] = None):
        """Track ``obj``'s data fields. Default: every non-callable,
        non-lock entry in its ``__dict__`` right now, plus simple-data
        class-attribute defaults (the ``spilled = 0`` lazy-counter
        idiom) — those materialize as instance fields on first write.
        ``_retired`` is never tracked: it is the exemption flag the
        state machine itself consults."""
        if fields is None:
            fields = set()
            candidates: Dict[str, object] = {}
            for klass in reversed(type(obj).__mro__):
                candidates.update(vars(klass))
            candidates.update(vars(obj))
            for k, v in candidates.items():
                if k.startswith("_eraser") or k.startswith("__") \
                        or k == "_retired":
                    continue
                if callable(v) or isinstance(v, (property, classmethod,
                                                 staticmethod)):
                    continue
                if hasattr(v, "acquire") and hasattr(v, "release"):
                    continue  # locks are the instruments, not the data
                if k in vars(obj) or isinstance(
                        v, (int, float, bool, str, bytes, type(None))):
                    fields.add(k)
        cls = type(obj)
        if getattr(cls, "_eraser_shim_", False):
            cls = cls.__mro__[1]
        shim = _shim_class(cls)
        self._labels[id(obj)] = label or cls.__name__
        object.__setattr__(obj, "_eraser_fields_", frozenset(fields))
        object.__setattr__(obj, "_eraser_rec_", self)
        object.__setattr__(obj, "__class__", shim)
        self._instrumented.append((obj, cls))

    def restore(self):
        for obj, cls in self._instrumented:
            object.__setattr__(obj, "__class__", cls)
            for k in ("_eraser_fields_", "_eraser_rec_"):
                try:
                    object.__delattr__(obj, k)
                except AttributeError:
                    pass
        self._instrumented.clear()
        for owner, attr, inner in self._locks:
            object.__setattr__(owner, attr, inner)
        self._locks.clear()

    # -- the Eraser state machine -----------------------------------------

    def _on_access(self, obj, fieldname: str, is_write: bool):
        if getattr(self._tls, "busy", False):
            return  # re-entrant access from our own bookkeeping
        self._tls.busy = True
        try:
            self._record(obj, fieldname, is_write)
        finally:
            self._tls.busy = False

    def _record(self, obj, fieldname: str, is_write: bool):
        if getattr(obj, "_retired", False):
            return  # retired generations are exclusively owned by design
        thread = threading.current_thread().name
        held = self.held()
        key = (id(obj), fieldname)
        with self._slock:
            st = self._state.get(key)
            if st is None:
                st = self._state[key] = _FieldState()
            if st.state == _VIRGIN:
                st.state = _EXCLUSIVE
                st.owner = thread
            if st.last_thread and st.last_thread != thread:
                st.other_thread = st.last_thread
                st.other_stack = st.last_stack
                st.other_was_write = st.last_was_write
            if st.state == _EXCLUSIVE:
                if thread == st.owner:
                    st.last_thread = thread
                    st.last_stack = _stack()
                    st.last_was_write = is_write
                    return
                # second thread: leave the initialization state and
                # start refining from this access's lockset
                st.state = _SHARED_MOD if (is_write or st.last_was_write) \
                    else _SHARED
                st.lockset = held
            else:
                st.lockset = (st.lockset & held
                              if st.lockset is not None else held)
                if is_write:
                    st.state = _SHARED_MOD
            race = (is_write and st.state == _SHARED_MOD
                    and st.lockset is not None and not st.lockset
                    and not st.reported)
            if race:
                st.reported = True
                self.races.append(RaceReport(
                    owner=self._labels.get(id(obj), type(obj).__name__),
                    field=fieldname,
                    first_thread=st.other_thread,
                    first_op="write" if st.other_was_write else "read",
                    first_stack=list(st.other_stack),
                    second_thread=thread,
                    second_stack=_stack(),
                    locks_held=held))
            st.last_thread = thread
            st.last_stack = _stack()
            st.last_was_write = is_write

    def assert_no_races(self):
        if self.races:
            lines = "\n".join(str(r) for r in self.races[:10])
            raise AssertionError(
                f"lockset detector: {len(self.races)} data race(s):\n"
                f"{lines}")


_SHIM_CACHE: Dict[type, type] = {}


def _shim_class(cls: type) -> type:
    """Subclass of ``cls`` routing tracked-field access through the
    instance's recorder (stored via object.__setattr__, so the shim
    itself never recurses)."""
    shim = _SHIM_CACHE.get(cls)
    if shim is not None:
        return shim

    def __getattribute__(self, name):
        if not name.startswith("_eraser"):
            d = object.__getattribute__(self, "__dict__")
            rec = d.get("_eraser_rec_")
            if rec is not None and name in d.get("_eraser_fields_", ()):
                rec._on_access(self, name, False)
        return object.__getattribute__(self, name)

    def __setattr__(self, name, value):
        if not name.startswith("_eraser"):
            d = object.__getattribute__(self, "__dict__")
            rec = d.get("_eraser_rec_")
            if rec is not None and name in d.get("_eraser_fields_", ()):
                rec._on_access(self, name, True)
        object.__setattr__(self, name, value)

    shim = type(f"Eraser{cls.__name__}", (cls,), {
        "__getattribute__": __getattribute__,
        "__setattr__": __setattr__,
        "_eraser_shim_": True,
    })
    _SHIM_CACHE[cls] = shim
    return shim

"""SPMD sharding soundness: collective axes, state specs, stable ids.

The mesh tier expresses the fleet merge as ``shard_map`` programs over
the two named axes (``series`` × ``hosts``, parallel/mesh.py) with
``psum``/``pmax`` collectives inside. Three disciplines hold the design
together and previously lived only in comments; this pass
(``sharding-soundness``, whole-program) machine-checks them:

* ``unknown-collective-axis`` — every axis named in a collective
  (``lax.psum``/``pmax``/``pmin``/``ppermute``/``all_gather``/
  ``axis_index`` and the ``parallel.collectives`` merge helpers) must
  resolve to a mesh axis actually declared in ``parallel/mesh.py``.
  Axis arguments that are function parameters are skipped — the caller
  binds them — but a resolved literal/constant that is not a declared
  axis is a guaranteed runtime ``NameError``-at-trace on real silicon.

* ``shardstate-mismatch`` — :data:`SHARD_STATE` declares, per
  ``shard_map`` local-program parameter, whether that state plane is
  series-sharded, hosts-sharded, or replicated BY DESIGN, and the pass
  resolves the actual ``in_specs`` pytree at the call boundary
  (through local spec assignments, spec-factory returns and NamedTuple
  constructors) and compares. :data:`DEVICE_PLACEMENTS` does the same
  for ``jax.device_put`` placements that bypass ``shard_map`` — the
  count-min table is replicated on purpose (sharding it would change
  the collision population), while the top-k planes ride the series
  axis.

* ``phys-bypass`` — physical-row arithmetic (``shard * block + local``)
  belongs to ``ShardPlacement``/``PoolPlacement`` in fleet/router.py
  alone; any other file multiplying by a ``.block`` stride is
  reinventing the stable-id contract (PR 9's hardening) and will break
  the moment a grow() re-blocks the placement.

The declared registry renders as a generated, drift-checked docs table:
``python -m veneur_tpu.lint --shardstate-table``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from veneur_tpu.lint.framework import (Finding, Project, SourceFile,
                                       dotted, enclosing_function,
                                       qualname, register)

# ---------------------------------------------------------------------------
# Declared state registry (devregistry.py pins every entry to live code)
# ---------------------------------------------------------------------------

S_SERIES = "series-sharded"
S_HOSTS = "hosts-sharded"
S_REP = "replicated"

#: (relpath, local-program name, parameter) -> declared placement of
#: that state plane at the shard_map call boundary.
SHARD_STATE: Dict[Tuple[str, str, str], str] = {
    # digest/HLL planes are series-sharded: after ingest, each device
    # owns its rows outright and no collective touches them
    ("veneur_tpu/core/mesh_store.py", "local_ingest", "temp"): S_SERIES,
    ("veneur_tpu/core/mesh_store.py", "local_ingest", "digest"): S_SERIES,
    ("veneur_tpu/core/mesh_store.py", "local_flush", "digest"): S_SERIES,
    ("veneur_tpu/core/mesh_store.py", "local_flush", "qs"): S_REP,
    ("veneur_tpu/core/mesh_store.py", "local_hash", "regs"): S_SERIES,
    ("veneur_tpu/core/mesh_store.py", "local_hash", "rows"): S_HOSTS,
    ("veneur_tpu/core/mesh_store.py", "local_merge", "regs"): S_SERIES,
    ("veneur_tpu/core/mesh_store.py", "local_estimate", "regs"): S_SERIES,
    # tiered pool slabs ride the series axis end to end
    ("veneur_tpu/fleet/mesh_tiered.py", "local_ingest", "pool"): S_SERIES,
    ("veneur_tpu/fleet/mesh_tiered.py", "local_flush", "pool"): S_SERIES,
    ("veneur_tpu/fleet/mesh_tiered.py", "local_flush", "qs"): S_REP,
    ("veneur_tpu/fleet/mesh_tiered.py", "local_promote", "pool"): S_SERIES,
    ("veneur_tpu/fleet/mesh_tiered.py", "local_promote", "slots"): S_REP,
    # the global-tier step: state series-sharded, per-host batches
    # hosts-sharded (fan-in), quantile grid replicated
    ("veneur_tpu/parallel/global_agg.py", "_local_step", "state"): S_SERIES,
    ("veneur_tpu/parallel/global_agg.py", "_local_step", "batch"): S_HOSTS,
    ("veneur_tpu/parallel/global_agg.py", "_local_step", "qs"): S_REP,
}

#: (relpath, class, plane-field, declared) for jax.device_put
#: placements outside shard_map. The count-min table is replicated BY
#: DESIGN: every series shard must hash into the SAME table or the
#: collision population (and so the error bound) changes per shard.
DEVICE_PLACEMENTS: Tuple[Tuple[str, str, str, str], ...] = (
    ("veneur_tpu/core/mesh_store.py", "MeshHeavyHitterGroup",
     "table", S_REP),
    ("veneur_tpu/core/mesh_store.py", "MeshHeavyHitterGroup",
     "topk_hi", S_SERIES),
    ("veneur_tpu/core/mesh_store.py", "MeshHeavyHitterGroup",
     "topk_lo", S_SERIES),
    ("veneur_tpu/core/mesh_store.py", "MeshSetGroup",
     "registers", S_SERIES),
)

#: file owning the physical-row arithmetic (ShardPlacement.to_phys)
_PHYS_OWNER = "veneur_tpu/fleet/router.py"

#: collective call name -> (positional index of the axis-name arg,
#: keyword names that carry it). NB all_gather's ``axis=`` kwarg is the
#: CONCAT dimension, not the axis name — only axis_name counts there.
_AXIS_SPEC: Dict[str, Tuple[int, Tuple[str, ...]]] = {
    "psum": (1, ("axis_name",)),
    "pmax": (1, ("axis_name",)),
    "pmin": (1, ("axis_name",)),
    "ppermute": (1, ("axis_name",)),
    "psum_scatter": (1, ("axis_name",)),
    "all_gather": (1, ("axis_name",)),
    "axis_index": (0, ("axis_name",)),
    "merge_counters": (1, ("axis",)),
    "merge_registers": (1, ("axis",)),
    "merge_temp": (1, ("axis",)),
    "allmerge_digest": (1, ("axis",)),
}

_MESH_FILE = "veneur_tpu/parallel/mesh.py"


def known_axes(project: Project) -> Dict[str, str]:
    """``*_AXIS`` constant name -> axis string, parsed from the mesh
    module (the single source of truth for axis vocabulary)."""
    out: Dict[str, str] = {}
    sf = project.files.get(_MESH_FILE)
    if sf is None:  # pragma: no cover - mesh module always ships
        return {"SERIES_AXIS": "series", "HOSTS_AXIS": "hosts"}
    for node in sf.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id.endswith("_AXIS") \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            out[node.targets[0].id] = node.value.value
    return out


# ---------------------------------------------------------------------------
# Axis-argument resolution
# ---------------------------------------------------------------------------


def _fn_params(fn) -> set:
    a = fn.args
    return {p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)}


def _module_consts(sf: SourceFile) -> Dict[str, str]:
    out = {}
    for node in sf.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            out[node.targets[0].id] = node.value.value
    return out


def _resolve_axes(expr, sf: SourceFile, fn, axes: Dict[str, str]
                  ) -> List[str]:
    """Axis strings an axis-name argument resolves to; [] when the
    value cannot be resolved statically (a parameter, a conditional)."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return [expr.value]
    if isinstance(expr, (ast.Tuple, ast.List)):
        out: List[str] = []
        for e in expr.elts:
            out.extend(_resolve_axes(e, sf, fn, axes))
        return out
    if isinstance(expr, ast.Name):
        if fn is not None and expr.id in _fn_params(fn):
            return []  # the caller binds it
        target = sf.aliases.get(expr.id)
        if target is not None:
            const = axes.get(target.split(".")[-1])
            if const is not None:
                return [const]
        if expr.id in axes:  # defined in this very file (mesh.py)
            return [axes[expr.id]]
        consts = _module_consts(sf)
        if expr.id in consts:
            return [consts[expr.id]]
        # one-hop local constant assignment
        if fn is not None:
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and node.targets[0].id == expr.id \
                        and isinstance(node.value, ast.Constant) \
                        and isinstance(node.value.value, str):
                    return [node.value.value]
    return []


def _collective_calls(sf: SourceFile):
    for node in sf.nodes:
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func)
        if name is None:
            continue
        base = name.split(".")[-1]
        spec = _AXIS_SPEC.get(base)
        if spec is None:
            continue
        pos, kwnames = spec
        arg = None
        if pos < len(node.args):
            arg = node.args[pos]
        else:
            for kw in node.keywords:
                if kw.arg in kwnames:
                    arg = kw.value
        if arg is not None:
            yield node, base, arg


# ---------------------------------------------------------------------------
# Spec-pytree classification
# ---------------------------------------------------------------------------


def _is_pspec(call: ast.Call, sf: SourceFile) -> bool:
    name = dotted(call.func)
    if name is None:
        return False
    base = name.split(".")[-1]
    if base == "PartitionSpec":
        return True
    if base == "P":
        target = sf.aliases.get("P", "")
        return target.endswith("PartitionSpec") or target == ""
    return False


def _combine(states: List[Optional[str]]) -> Optional[str]:
    got = {s for s in states if s is not None}
    if len(got) == 1:
        return got.pop()
    if got == {S_SERIES, S_REP}:
        # a pytree mixing sharded planes with replicated scalars is a
        # sharded plane overall (the tiered PoolSlab carries a
        # replicated epoch scalar next to its series-sharded rows)
        return S_SERIES
    return None


def _local_def(sf: SourceFile, name: str):
    for node in sf.nodes:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == name:
            return node
    return None


def classify_spec(expr, sf: SourceFile, fn, axes: Dict[str, str],
                  depth: int = 0) -> Optional[str]:
    """Placement class of a spec expression: replicated /
    series-sharded / hosts-sharded, or None when unresolvable.
    Follows local assignments, tuple unpacks, same-file spec-factory
    returns, and NamedTuple spec constructors."""
    if depth > 6:
        return None
    if isinstance(expr, ast.Starred):
        return classify_spec(expr.value, sf, fn, axes, depth + 1)
    if isinstance(expr, (ast.Tuple, ast.List)):
        return _combine([classify_spec(e, sf, fn, axes, depth + 1)
                         for e in expr.elts])
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Mult):
        # the `[spec] * 8` replication idiom
        return _combine([classify_spec(expr.left, sf, fn, axes,
                                       depth + 1),
                         classify_spec(expr.right, sf, fn, axes,
                                       depth + 1)])
    if isinstance(expr, ast.Constant):
        return None  # the scalar in `[spec] * 8`, or a None filler
    if isinstance(expr, ast.Call):
        if _is_pspec(expr, sf):
            named = []
            for a in expr.args:
                if isinstance(a, ast.Constant) and a.value is None:
                    continue
                named.extend(_resolve_axes(a, sf, fn, axes))
            if not named:
                non_none = [a for a in expr.args
                            if not (isinstance(a, ast.Constant)
                                    and a.value is None)]
                return S_REP if not non_none else None
            if "hosts" in named:
                return S_HOSTS
            if "series" in named:
                return S_SERIES
            return None
        callee = expr.func
        if isinstance(callee, ast.Name):
            local = _local_def(sf, callee.id)
            if local is not None:
                # a spec factory: classify its return expression in
                # the FACTORY's own scope
                for node in ast.walk(local):
                    if isinstance(node, ast.Return) \
                            and node.value is not None:
                        return classify_spec(node.value, sf, local,
                                             axes, depth + 1)
                return None
        # a NamedTuple spec constructor (AggState/TDigest/HostBatch/
        # PoolSlab): the pytree's placement is its leaves' placement
        leaves = list(expr.args) + [kw.value for kw in expr.keywords]
        if leaves:
            return _combine([classify_spec(e, sf, fn, axes, depth + 1)
                             for e in leaves])
        return None
    if isinstance(expr, ast.Name):
        if fn is None:
            return None
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == expr.id:
                    return classify_spec(node.value, sf, fn, axes,
                                         depth + 1)
                if isinstance(tgt, ast.Tuple):
                    for i, e in enumerate(tgt.elts):
                        if not (isinstance(e, ast.Name)
                                and e.id == expr.id):
                            continue
                        if isinstance(node.value, ast.Tuple) \
                                and i < len(node.value.elts):
                            return classify_spec(
                                node.value.elts[i], sf, fn, axes,
                                depth + 1)
                        if isinstance(node.value, ast.Call) \
                                and isinstance(node.value.func,
                                               ast.Name):
                            factory = _local_def(
                                sf, node.value.func.id)
                            if factory is None:
                                return None
                            for rnode in ast.walk(factory):
                                if isinstance(rnode, ast.Return) \
                                        and isinstance(rnode.value,
                                                       ast.Tuple) \
                                        and i < len(rnode.value.elts):
                                    return classify_spec(
                                        rnode.value.elts[i], sf,
                                        factory, axes, depth + 1)
                            return None
        return None
    return None


# ---------------------------------------------------------------------------
# shard_map boundary discovery
# ---------------------------------------------------------------------------


def _shard_map_calls(sf: SourceFile):
    for node in sf.nodes:
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func)
        if name is None or name.split(".")[-1] != "shard_map":
            continue
        if not node.args:
            continue
        target = node.args[0]
        if isinstance(target, ast.Name):
            local_name = target.id
        elif isinstance(target, ast.Attribute):
            local_name = target.attr
        else:
            continue
        in_specs = None
        for kw in node.keywords:
            if kw.arg == "in_specs":
                in_specs = kw.value
        yield node, local_name, in_specs


def _param_index(sf: SourceFile, fn_name: str,
                 param: str) -> Optional[int]:
    local = _local_def(sf, fn_name)
    if local is None:
        return None
    params = [a.arg for a in (local.args.posonlyargs + local.args.args)
              if a.arg != "self"]
    if param in params:
        return params.index(param)
    return None


def shard_map_boundaries(project: Project):
    """Every shard_map call boundary: (relpath, local program name,
    call node, in_specs expr, enclosing fn). Shared with the registry
    table and the liveness pass."""
    out = []
    for rel in sorted(project.files):
        sf = project.files[rel]
        for call, local_name, in_specs in _shard_map_calls(sf):
            fn = enclosing_function(call, sf.parents)
            out.append((rel, local_name, call, in_specs, fn))
    return out


# ---------------------------------------------------------------------------
# the pass
# ---------------------------------------------------------------------------


@register("sharding-soundness")
def run(project: Project) -> List[Finding]:
    axes = known_axes(project)
    valid = set(axes.values())
    findings: List[Finding] = []

    # collective axis vocabulary
    for rel in sorted(project.files):
        sf = project.files[rel]
        for call, base, arg in _collective_calls(sf):
            fn = enclosing_function(call, sf.parents)
            for resolved in _resolve_axes(arg, sf, fn, axes):
                if resolved in valid:
                    continue
                if sf.suppressed(call.lineno, "unknown-collective-axis"):
                    continue
                findings.append(Finding(
                    pass_name="sharding-soundness",
                    code="unknown-collective-axis", file=rel,
                    line=call.lineno,
                    anchor=f"{qualname(call, sf.parents)}:{base}",
                    message=(
                        f"`{base}` names collective axis "
                        f"{resolved!r}, which is not a mesh axis "
                        f"declared in parallel/mesh.py "
                        f"({sorted(valid)}) — this traces into an "
                        f"unbound-axis error on silicon")))

    # declared state registry vs actual in_specs
    boundaries = shard_map_boundaries(project)
    for (rel, fn_name, param), declared in sorted(SHARD_STATE.items()):
        sf = project.files.get(rel)
        if sf is None:
            continue
        idx = _param_index(sf, fn_name, param)
        if idx is None:
            continue  # devregistry reports the dead entry
        for brel, bname, call, in_specs, fn in boundaries:
            if brel != rel or bname != fn_name:
                continue
            if not isinstance(in_specs, (ast.Tuple, ast.List)) \
                    or idx >= len(in_specs.elts):
                continue
            actual = classify_spec(in_specs.elts[idx], sf, fn, axes)
            if actual is None or actual == declared:
                continue
            if sf.suppressed(call.lineno, "shardstate-mismatch"):
                continue
            findings.append(Finding(
                pass_name="sharding-soundness",
                code="shardstate-mismatch", file=rel,
                line=call.lineno, anchor=f"{fn_name}:{param}",
                message=(
                    f"`{fn_name}({param}=...)` is declared "
                    f"{declared} in lint/meshflow.py SHARD_STATE but "
                    f"the shard_map in_specs bind it {actual} — fix "
                    f"the spec or the declaration, never silently")))

    # device_put placements outside shard_map
    for rel, cls, plane, declared in DEVICE_PLACEMENTS:
        sf = project.files.get(rel)
        if sf is None:
            continue
        for node in sf.nodes:
            if not (isinstance(node, ast.ClassDef) and node.name == cls):
                continue
            for call in ast.walk(node):
                if not (isinstance(call, ast.Call)
                        and dotted(call.func) is not None
                        and dotted(call.func).split(".")[-1]
                        == "device_put" and len(call.args) >= 2):
                    continue
                try:
                    src = ast.unparse(call.args[0])
                except Exception:  # pragma: no cover
                    continue
                if not (src == f"self.{plane}"
                        or src.endswith(f".{plane}")):
                    continue
                actual = _classify_placement(call.args[1], sf, node,
                                             axes)
                if actual is None or actual == declared:
                    continue
                if sf.suppressed(call.lineno, "shardstate-mismatch"):
                    continue
                findings.append(Finding(
                    pass_name="sharding-soundness",
                    code="shardstate-mismatch", file=rel,
                    line=call.lineno, anchor=f"{cls}:{plane}",
                    message=(
                        f"{cls}.{plane} is declared {declared} "
                        f"(lint/meshflow.py DEVICE_PLACEMENTS) but "
                        f"this device_put places it {actual}")))

    # stable-id contract: physical-row arithmetic outside the owner
    for rel in sorted(project.files):
        if rel == _PHYS_OWNER:
            continue
        sf = project.files[rel]
        for node in sf.nodes:
            if not (isinstance(node, ast.BinOp)
                    and isinstance(node.op, ast.Mult)):
                continue
            for side in (node.left, node.right):
                try:
                    text = ast.unparse(side)
                except Exception:  # pragma: no cover
                    continue
                if not text.endswith(".block"):
                    continue
                if sf.suppressed(node.lineno, "phys-bypass"):
                    continue
                findings.append(Finding(
                    pass_name="sharding-soundness", code="phys-bypass",
                    file=rel, line=node.lineno,
                    anchor=f"{qualname(node, sf.parents)}:{text}",
                    message=(
                        f"physical-row arithmetic `... * {text}` "
                        f"outside fleet/router.py — go through "
                        f"ShardPlacement.to_phys (the stable-id "
                        f"contract); hand-rolled strides break when "
                        f"grow() re-blocks the placement")))
                break
    findings.sort(key=lambda f: (f.file, f.line, f.code))
    return findings


def _classify_placement(expr, sf: SourceFile, cls_node,
                        axes: Dict[str, str]) -> Optional[str]:
    """Placement of a device_put sharding argument: a direct
    ``NamedSharding(mesh, P(...))`` or a ``self._attr`` bound to one
    anywhere in the class."""
    if isinstance(expr, ast.Call):
        name = dotted(expr.func)
        if name and name.split(".")[-1] == "NamedSharding" \
                and len(expr.args) >= 2:
            return classify_spec(expr.args[1], sf,
                                 enclosing_function(expr, sf.parents),
                                 axes)
        return None
    if isinstance(expr, ast.Attribute) \
            and isinstance(expr.value, ast.Name) \
            and expr.value.id == "self":
        for node in ast.walk(cls_node):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Attribute) \
                            and isinstance(tgt.value, ast.Name) \
                            and tgt.value.id == "self" \
                            and tgt.attr == expr.attr:
                        return _classify_placement(node.value, sf,
                                                   cls_node, axes)
    return None


# ---------------------------------------------------------------------------
# The generated registry table
# ---------------------------------------------------------------------------


def shardstate_table(project: Project) -> str:
    """Markdown render of the declared shard-state registry with the
    live resolution next to each declaration; regenerate with
    ``python -m veneur_tpu.lint --shardstate-table``."""
    axes = known_axes(project)
    boundaries = shard_map_boundaries(project)
    lines = [
        "| shard_map program | file | param | declared | resolved |",
        "|---|---|---|---|---|",
    ]
    for (rel, fn_name, param), declared in sorted(SHARD_STATE.items()):
        resolved = "—"
        sf = project.files.get(rel)
        idx = _param_index(sf, fn_name, param) if sf else None
        if sf is not None and idx is not None:
            for brel, bname, call, in_specs, fn in boundaries:
                if brel == rel and bname == fn_name \
                        and isinstance(in_specs, (ast.Tuple, ast.List)) \
                        and idx < len(in_specs.elts):
                    got = classify_spec(in_specs.elts[idx], sf, fn,
                                        axes)
                    if got:
                        resolved = got
        lines.append(f"| `{fn_name}` | {rel} | {param} | {declared} "
                     f"| {resolved} |")
    lines.append("")
    lines.append("| device_put plane | class | declared | design note |")
    lines.append("|---|---|---|---|")
    notes = {
        ("MeshHeavyHitterGroup", "table"):
            "replicated BY DESIGN — sharding the count-min table "
            "would change the collision population per shard",
    }
    for rel, cls, plane, declared in DEVICE_PLACEMENTS:
        note = notes.get((cls, plane), "series plane, owned per shard")
        lines.append(f"| `{plane}` | {cls} ({rel}) | {declared} "
                     f"| {note} |")
    return "\n".join(lines)

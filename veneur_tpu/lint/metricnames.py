"""Self-metric registry pass: one name, one tag schema, all documented.

Every ``veneur.*`` self-metric is emitted through the SSF sample
constructors (``veneur_tpu/trace/samples.py``: ``count`` / ``gauge`` /
``timing`` / ``histogram`` / ``set_sample`` / ``status``). This pass
collects every such call site whose name literal (or f-string, with
placeholders normalized to ``<name>``-style holes) starts with
``veneur.`` and enforces:

- **tag-schema coherence**: a name emitted from several sites must use
  compatible tag-key sets — identical, or one a subset of the other
  (optional tags like ``part`` are fine; two sites with *disjoint* keys
  are two different metrics wearing one name). Sites passing a
  non-literal tags expression are skipped (unknowable statically).
- **documentation**: every emitted name appears in README.md or
  docs/*.md. ``docs/static-analysis.md`` carries the generated registry
  table (``python -m veneur_tpu.lint --metrics-table``), so the fix for
  a finding here is one regeneration away.

The collected registry also backs ``metrics_table()``.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from veneur_tpu.lint.framework import Finding, Project, dotted, register

_SAMPLE_FNS = {"count": "counter", "gauge": "gauge", "timing": "timer",
               "histogram": "histogram", "set_sample": "set",
               "status": "status"}
_SAMPLES_MODULE = "veneur_tpu.trace.samples"


def _name_in_docs(name: str, docs: str) -> bool:
    """Exact-name match: `veneur.flush` must NOT count as documented just
    because `veneur.flush.age_seconds` is (dots are name separators)."""
    import re

    return re.search(
        rf"(?<![A-Za-z0-9_.]){re.escape(name)}(?![A-Za-z0-9_.])",
        docs) is not None


@dataclass
class Emission:
    name: str                    # normalized: f-string holes -> <expr>
    kind: str                    # counter/gauge/...
    file: str
    line: int
    tag_keys: Optional[Set[str]]  # None = not statically knowable


@dataclass
class Registry:
    emissions: List[Emission] = field(default_factory=list)

    def by_name(self) -> Dict[str, List[Emission]]:
        out: Dict[str, List[Emission]] = {}
        for e in self.emissions:
            out.setdefault(e.name, []).append(e)
        return out


def _normalize_name(node: ast.AST) -> Optional[str]:
    """String constant or f-string -> normalized metric name."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant):
                parts.append(str(v.value))
            elif isinstance(v, ast.FormattedValue):
                inner = dotted(v.value)
                hole = inner.split(".")[-1] if inner else "..."
                parts.append(f"<{hole}>")
        return "".join(parts)
    return None


def _tag_keys(node: Optional[ast.AST]) -> Optional[Set[str]]:
    if node is None or (isinstance(node, ast.Constant)
                        and node.value is None):
        return set()
    if isinstance(node, ast.Dict):
        keys: Set[str] = set()
        for k in node.keys:
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                keys.add(k.value)
            else:
                return None
        return keys
    return None


def collect(project: Project) -> Registry:
    reg = Registry()
    for sf in project.files.values():
        aliases = sf.aliases
        sample_aliases = {a for a, target in aliases.items()
                          if target == _SAMPLES_MODULE}
        # `from veneur_tpu.trace.samples import count` style
        fn_aliases = {a: target.rsplit(".", 1)[1]
                      for a, target in aliases.items()
                      if target.startswith(_SAMPLES_MODULE + ".")}
        for node in sf.nodes:
            if not isinstance(node, ast.Call):
                continue
            kind = None
            if isinstance(node.func, ast.Attribute) \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id in sample_aliases:
                kind = _SAMPLE_FNS.get(node.func.attr)
            elif isinstance(node.func, ast.Name):
                kind = _SAMPLE_FNS.get(fn_aliases.get(node.func.id, ""))
            if kind is None or not node.args:
                continue
            name = _normalize_name(node.args[0])
            if name is None or not name.startswith("veneur."):
                continue
            tags_node = node.args[2] if len(node.args) >= 3 else None
            for kw in node.keywords:
                if kw.arg == "tags":
                    tags_node = kw.value
            reg.emissions.append(Emission(
                name=name, kind=kind, file=sf.relpath, line=node.lineno,
                tag_keys=_tag_keys(tags_node)))
    return reg


@register("metric-registry")
def run(project: Project) -> List[Finding]:
    reg = collect(project)
    docs = project.docs_text()
    findings: List[Finding] = []
    for name, emissions in sorted(reg.by_name().items()):
        known = [e for e in emissions if e.tag_keys is not None]
        # tag-schema coherence: every pair must be subset-compatible
        conflict = None
        for i, a in enumerate(known):
            for b in known[i + 1:]:
                if not (a.tag_keys <= b.tag_keys
                        or b.tag_keys <= a.tag_keys):
                    conflict = (a, b)
                    break
            if conflict:
                break
        first = emissions[0]
        sf = project.files[first.file]
        if conflict:
            a, b = conflict
            # a pragma on EITHER conflicting site (its own file) suppresses
            if not (project.files[a.file].suppressed(a.line, "tag-conflict")
                    or project.files[b.file].suppressed(b.line,
                                                        "tag-conflict")):
                findings.append(Finding(
                    pass_name="metric-registry", code="tag-conflict",
                    file=a.file, line=a.line, anchor=name,
                    message=(f"`{name}` emitted with conflicting tag sets: "
                             f"{sorted(a.tag_keys)} ({a.file}:{a.line}) vs "
                             f"{sorted(b.tag_keys)} ({b.file}:{b.line}) — "
                             f"same name, two schemas")))
        if not _name_in_docs(name, docs) \
                and not sf.suppressed(first.line, "undocumented"):
            findings.append(Finding(
                pass_name="metric-registry", code="undocumented",
                file=first.file, line=first.line, anchor=name,
                message=(f"self-metric `{name}` is not documented in "
                         f"README.md or docs/*.md — regenerate the registry "
                         f"table (`python -m veneur_tpu.lint "
                         f"--metrics-table`) into docs/static-analysis.md")))
    return findings


def metrics_table(project: Project) -> str:
    """Markdown self-metrics registry (for docs/static-analysis.md)."""
    reg = collect(project)
    lines = ["| name | type | tags | emitted from |", "|---|---|---|---|"]
    for name, emissions in sorted(reg.by_name().items()):
        kinds = sorted({e.kind for e in emissions})
        tag_union: Set[str] = set()
        unknown = False
        for e in emissions:
            if e.tag_keys is None:
                unknown = True
            else:
                tag_union |= e.tag_keys
        tags = ", ".join(f"`{t}`" for t in sorted(tag_union)) or "—"
        if unknown:
            tags += " (+dynamic)"
        sites = sorted({e.file for e in emissions})
        lines.append(f"| `{name}` | {'/'.join(kinds)} | {tags} | "
                     f"{', '.join(f'`{s}`' for s in sites)} |")
    return "\n".join(lines)

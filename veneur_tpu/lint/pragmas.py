"""Pragma-justify: every inline suppression must say *why*.

``# lint: ok(<code>)`` is the escape hatch for every pass in this
suite — which makes a bare pragma the cheapest possible way to make a
real finding disappear. This pass closes that hole: the text after the
closing paren is a mandatory written justification (the same policy the
baseline file enforces for grandfathered findings — justification is
the price of suppression, everywhere). A pragma whose reason is empty,
a "TODO", or too short to say anything is itself a finding.

The reason is whatever follows the pragma on the same comment, e.g.::

    x = fetch()  # lint: ok(host-sync) one scalar at interval end

Codes must also be *known*: a typo'd code (``ok(silent-drp)``)
suppresses nothing today and rots into a confusing no-op — flagged as
``unknown-pragma-code``.
"""

from __future__ import annotations

from typing import List

from veneur_tpu.lint.framework import Finding, Project, register

#: Every suppression code any pass can emit. Keep in lockstep with the
#: passes (test_lint pins this against the codes used in the tree).
KNOWN_CODES = frozenset({
    # locks.py / lockorder.py / lockset.py
    "unlocked-call", "lock-across-blocking", "inconsistent-lockset",
    "lock-cycle", "hot-path-lock",
    # purity.py
    "host-sync", "traced-branch", "unbounded-static-arg",
    "unbounded-shape",
    # deadcode.py
    "dead-code",
    # dropflow.py / exceptsafety.py
    "silent-drop", "swallowed-exception", "raise-between-swap",
    # deviceflow.py
    "stale-donated-read", "raw-donated-capture", "donated-param-escape",
    "duplicate-donation", "shared-init-buffer",
    "preflight-after-dispatch", "per-row-transfer",
    # meshflow.py
    "unknown-collective-axis", "shardstate-mismatch", "phys-bypass",
})

_MIN_REASON = 8  # chars; "why not" is not a justification


@register("pragma-justify")
def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for rel in sorted(project.files):
        sf = project.files[rel]
        nth = 0
        for line in sorted(sf.pragma_lines()):
            codes = sorted(sf.pragma_lines()[line])
            reason = sf.pragma_reason(line)
            unknown = [c for c in codes if c not in KNOWN_CODES]
            if unknown:
                findings.append(Finding(
                    pass_name="pragma-justify", code="unknown-pragma-code",
                    file=rel, line=line,
                    anchor=f"unknown:{','.join(unknown)}",
                    message=(
                        f"pragma suppresses unknown code(s) "
                        f"{unknown} — no pass emits these, so the "
                        f"suppression is a typo'd no-op; known codes: "
                        f"{sorted(KNOWN_CODES)}")))
            if len(reason) < _MIN_REASON or reason.upper().startswith("TODO"):
                nth += 1
                findings.append(Finding(
                    pass_name="pragma-justify", code="unjustified-pragma",
                    file=rel, line=line,
                    anchor=f"bare:{','.join(codes)}#{nth}",
                    message=(
                        f"`# lint: ok({', '.join(codes)})` carries no "
                        f"written justification — append WHY the "
                        f"suppression is sound (same policy as baseline "
                        f"entries: justification is the price of "
                        f"suppression)")))
    findings.sort(key=lambda f: (f.file, f.line, f.code))
    return findings

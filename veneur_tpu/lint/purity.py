"""JAX hot-path purity pass: no host syncs inside traced code.

The per-interval flush is one batched device program (ops/, parallel/,
core/ jit programs); a single host sync inside traced code — ``.item()``,
``float()`` on an array, ``np.asarray`` on a tracer, ``block_until_ready``,
Python ``if`` on a traced value — either breaks tracing outright or, via
implicit ``__bool__``/``__array__`` fallbacks, stalls the whole merge on
a device round-trip. Go's vet has no analogue for this; this pass is ours.

Mechanics:

1. **Hot roots**: functions decorated ``@jax.jit`` / ``@jit`` /
   ``@(functools.)partial(jax.jit, ...)``, plus every function referenced
   inside a ``jax.jit(...)`` call expression (covers
   ``jax.jit(shard_map(self._local_step, ...))`` and
   ``jax.jit(cm_ops.update, ...)``). ``static_argnums``/``static_argnames``
   mark parameters as trace-time constants.
2. **Call-graph propagation**: a function called from hot code with at
   least one traced argument becomes hot itself, with exactly the
   parameters that received traced values marked traced (so a helper
   that only ever receives static config — ``size_bound(compression)``
   under ``static_argnums`` — is NOT flagged for its ``int()``).
   Resolution covers same-module names, ``self.method``, and
   cross-module aliases (``td_ops.ingest_chunk``).
3. **Taint**: traced parameters taint expressions derived from them;
   ``.shape``/``.ndim``/``.dtype``/``len()`` and friends launder the
   taint (they are static under tracing).

Findings: ``host-sync`` (sync calls on tainted values) and
``traced-branch`` (``if``/``while`` on a tainted test). Suppress a
deliberate edge with ``# lint: ok(host-sync)`` / ``# lint: ok(traced-branch)``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from veneur_tpu.lint.framework import (Finding, Project, SourceFile,
                                       dotted, qualname, register)

# attribute reads that are static under tracing (shapes are compile-time)
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "capacity", "batch_shape",
                 "at"}
# receiver methods whose call is a host sync
_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
# numpy calls that materialize (and therefore fetch) their argument
_NP_MATERIALIZERS = {"asarray", "array", "ascontiguousarray", "copy",
                     "concatenate", "stack", "frombuffer", "copyto"}
# builtins whose call on a traced value forces __bool__/__float__ sync
_SYNC_BUILTINS = {"float", "int", "bool", "complex"}
# builtins that return static values even on traced args
_TAINT_LAUNDERING = {"len", "range", "isinstance", "hasattr", "type",
                     "enumerate"}

FnKey = Tuple[str, str]  # (relpath, qualified function name)

# jax.lax combinators whose function-valued arguments trace with fully
# traced parameters (cond/scan callbacks etc.)
_LAX_HOFS = {"cond", "switch", "scan", "while_loop", "fori_loop", "map",
             "associative_scan", "custom_root"}


def walk_shallow(fn: ast.AST):
    """ast.walk that does not descend into nested function/lambda bodies
    (those are analyzed as functions of their own)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


class _FnInfo:
    def __init__(self, sf: SourceFile, node: ast.FunctionDef, qual: str,
                 cls: Optional[str]):
        self.sf = sf
        self.node = node
        self.qual = qual
        self.cls = cls
        self.params = [a.arg for a in (node.args.posonlyargs + node.args.args)
                       if a.arg != "self"]
        self.kwonly = [a.arg for a in node.args.kwonlyargs]
        self.traced: Set[str] = set()


def _collect_functions(project: Project) -> Dict[FnKey, _FnInfo]:
    fns: Dict[FnKey, _FnInfo] = {}
    for sf in project.files.values():
        parents = sf.parents
        for node in sf.nodes:
            if isinstance(node, ast.FunctionDef):
                owner = parents.get(node)
                cls = owner.name if isinstance(owner, ast.ClassDef) else None
                fns[(sf.relpath, qualname(node, parents))] = _FnInfo(
                    sf, node, qualname(node, parents), cls)
    return fns


def _np_aliases(sf: SourceFile) -> Set[str]:
    return {alias for alias, target in sf.aliases.items()
            if target == "numpy" or target.startswith("numpy.")}


def _jax_aliases(sf: SourceFile) -> Set[str]:
    return {alias for alias, target in sf.aliases.items()
            if target == "jax"}


def _static_params(call_kwargs: List[ast.keyword],
                   params: List[str]) -> Set[str]:
    """Map static_argnums/static_argnames keywords onto parameter names."""
    static: Set[str] = set()

    def const_values(node) -> list:
        if isinstance(node, ast.Constant):
            return [node.value]
        if isinstance(node, (ast.Tuple, ast.List)):
            return [e.value for e in node.elts if isinstance(e, ast.Constant)]
        return []

    for kw in call_kwargs:
        if kw.arg == "static_argnums":
            for idx in const_values(kw.value):
                if isinstance(idx, int) and 0 <= idx < len(params):
                    static.add(params[idx])
        elif kw.arg == "static_argnames":
            for name in const_values(kw.value):
                if isinstance(name, str):
                    static.add(name)
    return static


def _jit_decoration(fn: ast.FunctionDef) -> Optional[List[ast.keyword]]:
    """The jit kwargs if the def is jit-decorated, else None."""
    for dec in fn.decorator_list:
        name = dotted(dec) if not isinstance(dec, ast.Call) else \
            dotted(dec.func)
        if name is None:
            continue
        base = name.split(".")[-1]
        if base in ("jit", "pmap"):
            return dec.keywords if isinstance(dec, ast.Call) else []
        if base == "partial" and isinstance(dec, ast.Call) and dec.args:
            inner = dotted(dec.args[0])
            if inner and inner.split(".")[-1] in ("jit", "pmap"):
                return dec.keywords
    return None


def _fn_refs(expr: ast.AST) -> List[ast.AST]:
    """Name/Attribute nodes inside ``expr`` that could reference functions
    (direct refs plus callees/args of wrapper calls like shard_map)."""
    refs: List[ast.AST] = []
    for node in ast.walk(expr):
        if isinstance(node, (ast.Name, ast.Attribute)):
            refs.append(node)
    return refs


class _Resolver:
    """Resolve a call/function reference to a FnKey."""

    def __init__(self, project: Project, fns: Dict[FnKey, _FnInfo]):
        self.project = project
        self.fns = fns
        self.mod_of_rel = {rel: project.module_name(rel)
                           for rel in project.files}
        self.rel_of_mod = {m: r for r, m in self.mod_of_rel.items()}
        self._alias_cache: Dict[str, Dict[str, str]] = {}

    def aliases(self, sf: SourceFile) -> Dict[str, str]:
        if sf.relpath not in self._alias_cache:
            self._alias_cache[sf.relpath] = sf.aliases
        return self._alias_cache[sf.relpath]

    def resolve(self, ref: ast.AST, sf: SourceFile, cls: Optional[str],
                scope: Optional[str] = None) -> Optional[FnKey]:
        name = dotted(ref)
        if name is None:
            return None
        parts = name.split(".")
        if parts[0] == "self" and len(parts) == 2 and cls:
            key = (sf.relpath, f"{cls}.{parts[1]}")
            return key if key in self.fns else None
        if len(parts) == 1:
            # innermost enclosing scope first (closures), then module level
            prefix = scope.split(".") if scope else []
            while prefix:
                key = (sf.relpath, ".".join(prefix + [parts[0]]))
                if key in self.fns:
                    return key
                prefix.pop()
            key = (sf.relpath, parts[0])
            if key in self.fns:
                return key
            # `from mod import fn` alias
            target = self.aliases(sf).get(parts[0])
            if target and "." in target:
                mod, fn = target.rsplit(".", 1)
                rel = self.rel_of_mod.get(mod)
                if rel:
                    key = (rel, fn)
                    return key if key in self.fns else None
            return None
        if len(parts) == 2:
            # module alias:  td_ops.ingest_chunk
            target = self.aliases(sf).get(parts[0])
            if target:
                rel = self.rel_of_mod.get(target)
                if rel:
                    key = (rel, parts[1])
                    return key if key in self.fns else None
        return None


def _assignment_order(fn: ast.FunctionDef):
    nodes = [n for n in walk_shallow(fn)
             if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign,
                               ast.For))]
    return sorted(nodes, key=lambda n: n.lineno)


class _Summaries:
    """Per-function return-taint summaries: does taint on the parameters
    ever reach a ``return``? Functions that only read static facts of
    their arguments (``pallas_ok(x)`` checking shapes and the backend,
    ``_precision_of(registers)`` reading ``shape``) return trace-time
    constants, and callers must not treat their results as traced."""

    def __init__(self, fns: Dict[FnKey, "_FnInfo"], resolver: "_Resolver"):
        self.fns = fns
        self.resolver = resolver
        self._cache: Dict[FnKey, bool] = {}
        self._in_progress: Set[FnKey] = set()

    def returns_tainted(self, key: FnKey) -> bool:
        if key in self._cache:
            return self._cache[key]
        if key in self._in_progress:
            return True  # recursion: stay conservative
        self._in_progress.add(key)
        try:
            info = self.fns[key]
            probe = _FnInfo(info.sf, info.node, info.qual, info.cls)
            probe.traced = set(info.params) | set(info.kwonly)
            taint = _Taint(probe, set(), summaries=self)
            result = False
            for node in walk_shallow(info.node):
                if isinstance(node, ast.Return) and node.value is not None \
                        and taint.is_tainted(node.value):
                    result = True
                    break
        finally:
            self._in_progress.discard(key)
        self._cache[key] = result
        return result

    def call_returns_static(self, call: ast.Call, sf: SourceFile,
                            cls: Optional[str]) -> bool:
        key = self.resolver.resolve(call.func, sf, cls)
        return key is not None and not self.returns_tainted(key)


class _Taint:
    """Forward may-taint analysis over one function body."""

    def __init__(self, info: _FnInfo, np_names: Set[str],
                 summaries: Optional[_Summaries] = None):
        self.tainted: Set[str] = set(info.traced)
        self.np_names = np_names
        self._summaries = summaries
        self._sf = info.sf
        self._cls = info.cls
        for _ in range(2):  # two passes to cover loop-carried taint
            for node in _assignment_order(info.node):
                self._transfer(node)

    def _transfer(self, node):
        if isinstance(node, ast.For):
            if self.is_tainted(node.iter):
                for t in ast.walk(node.target):
                    if isinstance(t, ast.Name):
                        self.tainted.add(t.id)
            return
        value = node.value
        if value is None:
            return
        if not self.is_tainted(value):
            return
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        for tgt in targets:
            for t in ast.walk(tgt):
                if isinstance(t, ast.Name):
                    self.tainted.add(t.id)

    def is_tainted(self, expr: ast.AST) -> bool:
        if isinstance(expr, ast.Name):
            return expr.id in self.tainted
        if isinstance(expr, ast.Attribute):
            if expr.attr in _STATIC_ATTRS:
                return False
            return self.is_tainted(expr.value)
        if isinstance(expr, ast.Subscript):
            return self.is_tainted(expr.value)
        if isinstance(expr, ast.Call):
            fname = dotted(expr.func)
            if fname and fname.split(".")[-1] in _TAINT_LAUNDERING:
                return False
            if self._summaries is not None and self._summaries \
                    .call_returns_static(expr, self._sf, self._cls):
                return False
            if isinstance(expr.func, ast.Attribute) \
                    and self.is_tainted(expr.func.value):
                return True
            return any(self.is_tainted(a) for a in expr.args) or \
                any(self.is_tainted(k.value) for k in expr.keywords)
        if isinstance(expr, ast.BinOp):
            return self.is_tainted(expr.left) or self.is_tainted(expr.right)
        if isinstance(expr, ast.UnaryOp):
            return self.is_tainted(expr.operand)
        if isinstance(expr, ast.BoolOp):
            return any(self.is_tainted(v) for v in expr.values)
        if isinstance(expr, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in expr.ops):
                return False  # `x is None` is a trace-time constant test
            return self.is_tainted(expr.left) or \
                any(self.is_tainted(c) for c in expr.comparators)
        if isinstance(expr, ast.IfExp):
            return self.is_tainted(expr.body) or \
                self.is_tainted(expr.orelse) or self.is_tainted(expr.test)
        if isinstance(expr, (ast.Tuple, ast.List)):
            return any(self.is_tainted(e) for e in expr.elts)
        if isinstance(expr, ast.Starred):
            return self.is_tainted(expr.value)
        return False


def _find_hot_roots(project: Project, fns: Dict[FnKey, _FnInfo],
                    resolver: _Resolver) -> Dict[FnKey, Set[str]]:
    """FnKey -> traced param names, for every jit/pmap entry point."""
    hot: Dict[FnKey, Set[str]] = {}

    def mark(key: FnKey, static: Set[str]):
        info = fns[key]
        traced = {p for p in info.params if p not in static}
        hot.setdefault(key, set()).update(traced)

    for sf in project.files.values():
        jax_names = _jax_aliases(sf)
        parents = sf.parents
        for node in sf.nodes:
            if isinstance(node, ast.FunctionDef):
                kwargs = _jit_decoration(node)
                if kwargs is not None:
                    key = (sf.relpath, qualname(node, parents))
                    mark(key, _static_params(
                        kwargs, fns[key].params + fns[key].kwonly))
            elif isinstance(node, ast.Call):
                fname = dotted(node.func)
                if fname is None:
                    continue
                parts = fname.split(".")
                is_jit = (parts[-1] in ("jit", "pmap")
                          and (len(parts) == 1 or parts[0] in jax_names
                               or parts[0] == "jax"))
                if not is_jit or not node.args:
                    continue
                encl_cls = None
                cur = parents.get(node)
                while cur is not None:
                    if isinstance(cur, ast.ClassDef):
                        encl_cls = cur.name
                        break
                    cur = parents.get(cur)
                scope = qualname(node, parents)
                for ref in _fn_refs(node.args[0]):
                    key = resolver.resolve(ref, sf, encl_cls,
                                           scope=scope or None)
                    if key is not None:
                        mark(key, _static_params(
                            node.keywords,
                            fns[key].params + fns[key].kwonly))
    return hot


def _propagate(fns: Dict[FnKey, _FnInfo], hot: Dict[FnKey, Set[str]],
               resolver: _Resolver, summaries: _Summaries):
    """Spread hotness through calls that pass traced values."""
    for key, traced in hot.items():
        fns[key].traced = set(traced)
    work = list(hot)
    np_cache: Dict[str, Set[str]] = {}
    while work:
        key = work.pop()
        info = fns[key]
        sf = info.sf
        if sf.relpath not in np_cache:
            np_cache[sf.relpath] = _np_aliases(sf)
        taint = _Taint(info, np_cache[sf.relpath], summaries=summaries)
        for node in walk_shallow(info.node):
            if not isinstance(node, ast.Call):
                continue
            fname = dotted(node.func)
            if fname and fname.split(".")[-1] in _LAX_HOFS:
                # cond/scan/while_loop callbacks trace with every
                # parameter traced
                for arg in node.args:
                    for ref in _fn_refs(arg):
                        cb = resolver.resolve(ref, sf, info.cls,
                                              scope=info.qual)
                        if cb is None:
                            continue
                        cb_info = fns[cb]
                        cb_params = set(cb_info.params)
                        if not cb_params <= cb_info.traced:
                            cb_info.traced |= cb_params
                            hot.setdefault(cb, set()).update(cb_params)
                            work.append(cb)
                continue
            callee = resolver.resolve(node.func, sf, info.cls,
                                      scope=info.qual)
            if callee is None:
                continue
            cinfo = fns[callee]
            traced_params: Set[str] = set()
            for i, arg in enumerate(node.args):
                if isinstance(arg, ast.Starred):
                    continue
                if i < len(cinfo.params) and taint.is_tainted(arg):
                    traced_params.add(cinfo.params[i])
            for kw in node.keywords:
                if kw.arg and taint.is_tainted(kw.value):
                    traced_params.add(kw.arg)
            if traced_params and not traced_params <= cinfo.traced:
                cinfo.traced |= traced_params
                hot.setdefault(callee, set()).update(traced_params)
                work.append(callee)


@register("jax-purity")
def run(project: Project) -> List[Finding]:
    fns = _collect_functions(project)
    resolver = _Resolver(project, fns)
    summaries = _Summaries(fns, resolver)
    hot = _find_hot_roots(project, fns, resolver)
    _propagate(fns, hot, resolver, summaries)

    findings: List[Finding] = []
    for key in sorted(hot):
        info = fns[key]
        if not info.traced:
            continue
        sf = info.sf
        np_names = _np_aliases(sf)
        jax_names = _jax_aliases(sf) | {"jax"}
        taint = _Taint(info, np_names, summaries=summaries)

        def emit(node, code: str, what: str):
            if sf.suppressed(node.lineno, code):
                return
            findings.append(Finding(
                pass_name="jax-purity", code=code, file=sf.relpath,
                line=node.lineno, anchor=f"{info.qual}:{what}",
                message=(f"{what} inside jit-traced {info.qual}() — this "
                         f"host-syncs (stalls) the batched device program"
                         if code == "host-sync" else
                         f"{what} inside jit-traced {info.qual}() — Python "
                         f"control flow on a traced value fails or "
                         f"retraces; use lax.cond/select/where")))

        for node in walk_shallow(info.node):
            if isinstance(node, ast.Call):
                fname = dotted(node.func)
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr in _SYNC_METHODS \
                        and taint.is_tainted(node.func.value):
                    emit(node, "host-sync", f".{node.func.attr}() call")
                elif fname:
                    parts = fname.split(".")
                    tainted_arg = any(taint.is_tainted(a)
                                      for a in node.args)
                    if len(parts) == 2 and parts[0] in np_names \
                            and parts[1] in _NP_MATERIALIZERS \
                            and tainted_arg:
                        emit(node, "host-sync",
                             f"{fname}() on a traced value")
                    elif len(parts) == 2 and parts[0] in jax_names \
                            and parts[1] in ("device_get",
                                             "block_until_ready") \
                            and tainted_arg:
                        emit(node, "host-sync", f"{fname}() call")
                    elif len(parts) == 1 and parts[0] in _SYNC_BUILTINS \
                            and node.args \
                            and taint.is_tainted(node.args[0]):
                        emit(node, "host-sync",
                             f"{parts[0]}() on a traced value")
            elif isinstance(node, (ast.If, ast.While)):
                if taint.is_tainted(node.test):
                    kind = "if" if isinstance(node, ast.If) else "while"
                    emit(node, "traced-branch",
                         f"`{kind}` on a traced value (line {node.lineno})")
    return findings

"""Recompile-hazard pass: static args must come from bounded value sets.

Every distinct static-argument tuple (and every distinct input shape)
handed to a ``jax.jit``/``pmap``/Pallas program compiles a fresh XLA
executable — ~20-40s on TPU — and lives in the trace cache forever.
A static arg derived from an *unbounded* runtime value (a batch length,
a queue depth, a live-row count) therefore turns production traffic
into a compile storm: the classic trace-cache-explosion failure mode of
JAX serving stacks. The codebase's defense is the **bucketing ladder**
(``veneur_tpu/core/bucketing.py``): pow2 rounding collapses any integer
into a log-bounded set, so the compiled-variant count stays ~log2 of
the largest value ever seen.

This pass walks every call site of every compiled program — functions
decorated ``@jax.jit`` / ``@partial(jax.jit, ...)`` and programs bound
via ``name = jax.jit(fn, static_argnums=...)`` (module-level,
function-local, and ``self._prog = jax.jit(...)`` bindings) — and
classifies each expression flowing into a ``static_argnums``/
``static_argnames`` position:

====================  ==================================================
``const``             literals; module-level constants
``bool``              ``bool()``, ``not``, comparisons — two values
``config``            ``self.<attr>`` reads (set at construction, pow2-
                      grown capacities included: the growers are
                      bucketed)
``bucketed``          flows through an ``@bucketed`` ladder function or
                      ``.bit_length()`` (log-bounded by construction)
``opaque``            can't be traced further (unresolvable call,
                      foreign param) — NOT flagged; listed in the
                      inventory so reviewers see the blind spot
``UNBOUNDED``         derived from ``len()`` / ``.shape`` / ``.size`` /
                      ``.sum()`` / ``.qsize()`` … with no bucketing
                      ladder on the path — **flagged**
====================  ==================================================

Findings: ``unbounded-static-arg`` for a hazardous static arg, and
``unbounded-shape`` for a *traced* argument sliced to a hazardous
length at the call site (``prog(x[:n])`` retraces per distinct ``n``;
slice staging buffers to a pow2 prefix instead, as the drains do).
Parameters of ordinary functions classify by joining their own call
sites, so a helper threading a bucketed length through to the program
does not flag. Suppress a deliberate edge with
``# lint: ok(unbounded-static-arg)`` / ``# lint: ok(unbounded-shape)``.

The pass also renders the **compiled-program inventory** — program ×
static-arg × observed source classes — and checks it into
``docs/static-analysis.md`` between the ``programs-inventory`` markers
(``python -m veneur_tpu.lint --programs-table`` regenerates it), so
trace-cache growth is reviewable per PR; drift is the
``inventory-drift`` finding.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from veneur_tpu.lint.framework import (Finding, Project, SourceFile, dotted,
                                       qualname, register)
from veneur_tpu.lint import purity
from veneur_tpu.lint.purity import walk_shallow

FnKey = Tuple[str, str]

# severity-ordered classification lattice
CONST, BOOL, CONFIG, BUCKETED, OPAQUE, UNBOUNDED = (
    "const", "bool", "config", "bucketed", "opaque", "UNBOUNDED")
_RANK = {CONST: 0, BOOL: 0, CONFIG: 1, BUCKETED: 1, OPAQUE: 2, UNBOUNDED: 3}

# attribute reads that yield runtime-data-dependent integers
_HAZARD_ATTRS = {"shape", "size", "nbytes"}
# method calls on arbitrary receivers that yield data-dependent values
_HAZARD_METHODS = {"sum", "max", "min", "qsize", "item", "tolist",
                   "__len__"}
# builtins whose result is data-sized
_HAZARD_BUILTINS = {"len"}
# bounded regardless of argument (rank / dtype / log-bounded)
_BOUNDED_ATTRS = {"ndim", "dtype"}

_MARKER_BEGIN = "<!-- generated: programs-inventory begin -->"
_MARKER_END = "<!-- generated: programs-inventory end -->"


def _is_jit_call(node: ast.Call, jax_names: Set[str]) -> bool:
    fname = dotted(node.func)
    if fname is None:
        return False
    parts = fname.split(".")
    return parts[-1] in ("jit", "pmap") and (
        len(parts) == 1 or parts[0] in jax_names or parts[0] == "jax")


class _Program:
    """One compiled program: the target function + its static params."""

    def __init__(self, key: FnKey, static: Set[str], via: str):
        self.key = key
        self.static = static          # static parameter NAMES
        self.via = via                # how it compiles (decorator/binding)
        # param name -> {classification labels observed at call sites}
        self.observed: Dict[str, Set[str]] = {p: set() for p in
                                              sorted(static)}
        self.call_sites = 0


class _Pass:
    def __init__(self, project: Project):
        self.project = project
        self.fns = purity._collect_functions(project)
        self.resolver = purity._Resolver(project, self.fns)
        self._jax_cache: Dict[str, Set[str]] = {}
        self._mconst_cache: Dict[str, Set[str]] = {}
        self.programs: Dict[FnKey, _Program] = {}
        # (relpath, scope_qual_or_None, name) -> program key for
        # name-bound programs;  (relpath, class, attr) for self-bindings
        self.name_bindings: Dict[Tuple[str, Optional[str], str], FnKey] = {}
        self.attr_bindings: Dict[Tuple[str, str, str], FnKey] = {}
        # bucketed ladder functions: FnKey -> scheme
        self.bucketed: Dict[FnKey, str] = {}
        self.bucketed_names: Set[str] = set()
        # reverse call index for param classification
        self._callers: Dict[FnKey, List[Tuple[ast.Call, "_Ctx"]]] = {}
        self._param_memo: Dict[Tuple[FnKey, str], str] = {}
        self._param_stack: Set[Tuple[FnKey, str]] = set()
        self.findings: List[Finding] = []
        self._collect()
        # functions that execute under a trace (jit roots + everything
        # they call with traced args, per the purity pass): inside them
        # `.shape` & friends are trace-time CONSTANTS — the enclosing
        # program's own trace key already bounds them — not new hazards
        summaries = purity._Summaries(self.fns, self.resolver)
        hot = purity._find_hot_roots(self.project, self.fns, self.resolver)
        purity._propagate(self.fns, hot, self.resolver, summaries)
        self.traced_fns: Set[FnKey] = set(hot) | set(self.programs)

    def _jax_names(self, sf: SourceFile) -> Set[str]:
        if sf.relpath not in self._jax_cache:
            self._jax_cache[sf.relpath] = purity._jax_aliases(sf)
        return self._jax_cache[sf.relpath]

    # -- collection --------------------------------------------------------

    def _collect(self):
        for sf in self.project.files.values():
            parents = sf.parents
            jax_names = self._jax_names(sf)
            for node in sf.nodes:
                if isinstance(node, ast.FunctionDef):
                    for dec in node.decorator_list:
                        name = dotted(dec) if not isinstance(dec, ast.Call) \
                            else dotted(dec.func)
                        if name and name.split(".")[-1] == "bucketed":
                            key = (sf.relpath, qualname(node, parents))
                            scheme = "custom"
                            if isinstance(dec, ast.Call) and dec.args and \
                                    isinstance(dec.args[0], ast.Constant):
                                scheme = str(dec.args[0].value)
                            self.bucketed[key] = scheme
                            self.bucketed_names.add(node.name)
                    kwargs = purity._jit_decoration(node)
                    if kwargs is not None:
                        key = (sf.relpath, qualname(node, parents))
                        info = self.fns[key]
                        static = purity._static_params(
                            kwargs, info.params + info.kwonly)
                        if static:
                            self.programs.setdefault(key, _Program(
                                key, static, via="decorator"))
                elif isinstance(node, ast.Assign) \
                        and isinstance(node.value, ast.Call) \
                        and _is_jit_call(node.value, jax_names) \
                        and node.value.args:
                    self._bind(node, sf, parents)

    def _bind(self, node: ast.Assign, sf: SourceFile, parents):
        call = node.value
        encl_cls = None
        cur = parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                encl_cls = cur.name
                break
            cur = parents.get(cur)
        scope = qualname(node, parents)
        target_key = None
        for ref in purity._fn_refs(call.args[0]):
            target_key = self.resolver.resolve(
                ref, sf, encl_cls, scope=scope or None)
            if target_key is not None:
                break
        if target_key is None:
            return
        info = self.fns[target_key]
        static = purity._static_params(call.keywords,
                                       info.params + info.kwonly)
        if not static:
            return
        prog = self.programs.setdefault(
            target_key, _Program(target_key, static, via="binding"))
        prog.static |= static
        for p in sorted(static):
            prog.observed.setdefault(p, set())
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                self.name_bindings[(sf.relpath,
                                    scope if scope != "<module>" else None,
                                    tgt.id)] = target_key
            elif isinstance(tgt, ast.Attribute) \
                    and isinstance(tgt.value, ast.Name) \
                    and tgt.value.id == "self" and encl_cls:
                self.attr_bindings[(sf.relpath, encl_cls,
                                    tgt.attr)] = target_key

    # -- classification ----------------------------------------------------

    def _module_consts(self, sf: SourceFile) -> Set[str]:
        cached = self._mconst_cache.get(sf.relpath)
        if cached is not None:
            return cached
        out = set()
        for stmt in sf.tree.body:
            if isinstance(stmt, ast.Assign) \
                    and isinstance(stmt.value, ast.Constant):
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name):
                        out.add(tgt.id)
        self._mconst_cache[sf.relpath] = out
        return out

    def classify(self, expr: ast.AST, ctx: "_Ctx", depth: int = 0) -> str:
        if depth > 12:
            return OPAQUE
        c = lambda e: self.classify(e, ctx, depth + 1)
        if isinstance(expr, ast.Constant):
            return CONST
        if isinstance(expr, ast.Attribute):
            if expr.attr in _HAZARD_ATTRS:
                return CONST if ctx.key in self.traced_fns else UNBOUNDED
            if expr.attr in _BOUNDED_ATTRS:
                return CONST
            if isinstance(expr.value, ast.Name) \
                    and expr.value.id == "self":
                return CONFIG
            return OPAQUE
        if isinstance(expr, ast.Name):
            return self._classify_name(expr.id, ctx, depth)
        if isinstance(expr, ast.Subscript):
            # cfg["key"] / shape[0]: the container's class carries over
            return c(expr.value)
        if isinstance(expr, (ast.Compare, ast.BoolOp)):
            return BOOL
        if isinstance(expr, ast.UnaryOp):
            if isinstance(expr.op, ast.Not):
                return BOOL
            return c(expr.operand)
        if isinstance(expr, ast.IfExp):
            return _join(c(expr.body), c(expr.orelse))
        if isinstance(expr, ast.BinOp):
            return _join(c(expr.left), c(expr.right))
        if isinstance(expr, (ast.Tuple, ast.List)):
            return _join(*[c(e) for e in expr.elts]) if expr.elts else CONST
        if isinstance(expr, ast.Call):
            return self._classify_call(expr, ctx, depth)
        return OPAQUE

    def _classify_name(self, name: str, ctx: "_Ctx", depth: int) -> str:
        bound = ctx._param_classes.get(name)
        if bound is not None:
            # a one-level callee analysis bound this param to the class
            # of the actual argument at the call site
            return bound
        assigns = ctx.assignments().get(name)
        if assigns:
            return _join(*[self.classify(v, ctx, depth + 1)
                           for v in assigns])
        if name in ctx.fn_params():
            return self._classify_param(ctx.key, name)
        if name in self._module_consts(ctx.sf):
            return CONST
        if name in ("True", "False", "None"):
            return CONST
        return OPAQUE

    def _classify_call(self, call: ast.Call, ctx: "_Ctx",
                       depth: int) -> str:
        fname = dotted(call.func)
        base = fname.split(".")[-1] if fname else None
        if base == "bool":
            return BOOL
        if base in _HAZARD_BUILTINS:
            return UNBOUNDED
        if isinstance(call.func, ast.Attribute):
            if call.func.attr == "bit_length":
                return BUCKETED
            if call.func.attr in _HAZARD_METHODS:
                return UNBOUNDED
        if base == "int" and call.args:
            return self.classify(call.args[0], ctx, depth + 1)
        args = [self.classify(a, ctx, depth + 1) for a in call.args]
        if base == "min" and len(call.args) > 1:
            # min(unbounded, bounded) is bounded by the smaller set
            if any(_RANK[a] <= _RANK[CONFIG] for a in args):
                return BUCKETED if BUCKETED in args else \
                    min(args, key=lambda a: _RANK[a])
            return _join(*args)
        if base == "max" and len(call.args) > 1:
            return _join(*args)
        key = self.resolver.resolve(call.func, ctx.sf, ctx.cls,
                                    scope=ctx.qual)
        if key is None:
            if base in self.bucketed_names:
                return BUCKETED
            return OPAQUE
        if key in self.bucketed:
            return BUCKETED
        info = self.fns.get(key)
        if info is None:
            return OPAQUE
        # one-level return-expression classification in the callee,
        # with the callee's params bound to this call's arg classes
        bound = {}
        for i, a in enumerate(call.args):
            if i < len(info.params):
                bound[info.params[i]] = (
                    args[i] if i < len(args)
                    else self.classify(a, ctx, depth + 1))
        for kw in call.keywords:
            if kw.arg:
                bound[kw.arg] = self.classify(kw.value, ctx, depth + 1)
        callee_ctx = _Ctx(self, info, param_classes=bound)
        results = []
        for node in walk_shallow(info.node):
            if isinstance(node, ast.Return) and node.value is not None:
                results.append(self.classify(node.value, callee_ctx,
                                             depth + 1))
        return _join(*results) if results else OPAQUE

    def _classify_param(self, key: FnKey, param: str) -> str:
        memo_key = (key, param)
        if memo_key in self._param_memo:
            return self._param_memo[memo_key]
        if memo_key in self._param_stack:
            return OPAQUE
        self._param_stack.add(memo_key)
        try:
            info = self.fns.get(key)
            sites = self._callers.get(key, ())
            results = []
            for call, ctx in sites:
                idx = None
                for i, p in enumerate(info.params):
                    if p == param:
                        idx = i
                        break
                expr = None
                if idx is not None and idx < len(call.args) \
                        and not isinstance(call.args[idx], ast.Starred):
                    expr = call.args[idx]
                else:
                    for kw in call.keywords:
                        if kw.arg == param:
                            expr = kw.value
                if expr is not None:
                    results.append(self.classify(expr, ctx, 1))
            out = _join(*results) if results else OPAQUE
        finally:
            self._param_stack.discard(memo_key)
        self._param_memo[memo_key] = out
        return out

    # -- call-site walk ----------------------------------------------------

    def _program_for_call(self, call: ast.Call, sf: SourceFile,
                          cls: Optional[str],
                          scope: Optional[str]) -> Optional[_Program]:
        key = self.resolver.resolve(call.func, sf, cls, scope=scope)
        if key is not None and key in self.programs:
            return self.programs[key]
        if isinstance(call.func, ast.Name):
            # innermost binding scope first, then module level
            prefix = scope.split(".") if scope else []
            while prefix:
                b = self.name_bindings.get(
                    (sf.relpath, ".".join(prefix), call.func.id))
                if b is not None:
                    return self.programs.get(b)
                prefix.pop()
            b = self.name_bindings.get((sf.relpath, None, call.func.id))
            if b is not None:
                return self.programs.get(b)
        if isinstance(call.func, ast.Attribute) \
                and isinstance(call.func.value, ast.Name) \
                and call.func.value.id == "self" and cls:
            b = self.attr_bindings.get((sf.relpath, cls, call.func.attr))
            if b is not None:
                return self.programs.get(b)
        return None

    def analyze(self):
        # reverse call index first (param classification needs it)
        contexts: List[Tuple[ast.Call, _Ctx, _Program]] = []
        for key, info in self.fns.items():
            ctx = _Ctx(self, info)
            for node in walk_shallow(info.node):
                if not isinstance(node, ast.Call):
                    continue
                callee = self.resolver.resolve(node.func, info.sf,
                                               info.cls, scope=info.qual)
                if callee is not None and callee in self.fns:
                    self._callers.setdefault(callee, []).append((node, ctx))
                prog = self._program_for_call(node, info.sf, info.cls,
                                              info.qual)
                if prog is not None:
                    contexts.append((node, ctx, prog))

        for call, ctx, prog in contexts:
            prog.call_sites += 1
            info = self.fns[prog.key]
            sf = ctx.sf
            for i, arg in enumerate(call.args):
                if isinstance(arg, ast.Starred) or i >= len(info.params):
                    continue
                p = info.params[i]
                if p in prog.static:
                    label = self.classify(arg, ctx)
                    prog.observed.setdefault(p, set()).add(label)
                    if label == UNBOUNDED \
                            and not sf.suppressed(call.lineno,
                                                  "unbounded-static-arg"):
                        self.findings.append(Finding(
                            pass_name="recompile-hazard",
                            code="unbounded-static-arg",
                            file=sf.relpath, line=call.lineno,
                            anchor=f"{ctx.qual}->{info.qual}:{p}",
                            message=(
                                f"static arg {p!r} of compiled program "
                                f"{info.qual}() derives from an unbounded "
                                f"runtime value — every distinct value "
                                f"compiles a new XLA executable; route it "
                                f"through a registered bucketing ladder "
                                f"(core/bucketing.py)")))
                else:
                    self._check_shape(arg, call, ctx, info, p)
            for kw in call.keywords:
                if kw.arg and kw.arg in prog.static:
                    label = self.classify(kw.value, ctx)
                    prog.observed.setdefault(kw.arg, set()).add(label)
                    if label == UNBOUNDED \
                            and not sf.suppressed(call.lineno,
                                                  "unbounded-static-arg"):
                        self.findings.append(Finding(
                            pass_name="recompile-hazard",
                            code="unbounded-static-arg",
                            file=sf.relpath, line=call.lineno,
                            anchor=f"{ctx.qual}->{info.qual}:{kw.arg}",
                            message=(
                                f"static arg {kw.arg!r} of compiled "
                                f"program {info.qual}() derives from an "
                                f"unbounded runtime value — route it "
                                f"through a registered bucketing ladder "
                                f"(core/bucketing.py)")))
                elif kw.arg:
                    # traced args pass by keyword too: prog(x=buf[:n])
                    self._check_shape(kw.value, call, ctx, info, kw.arg)

    def _check_shape(self, arg: ast.AST, call: ast.Call, ctx: "_Ctx",
                     info, param: str):
        """A traced arg sliced to a hazardous length retraces per
        distinct length: prog(x[:n]) with runtime n."""
        sf = ctx.sf
        exprs = [arg]
        if isinstance(arg, ast.Name):
            exprs.extend(ctx.assignments().get(arg.id, ()))
        for e in exprs:
            for node in ast.walk(e if isinstance(e, ast.AST) else arg):
                if not (isinstance(node, ast.Subscript)
                        and isinstance(node.slice, ast.Slice)
                        and node.slice.upper is not None):
                    continue
                if self.classify(node.slice.upper, ctx) != UNBOUNDED:
                    continue
                if sf.suppressed(call.lineno, "unbounded-shape") or \
                        sf.suppressed(node.lineno, "unbounded-shape"):
                    continue
                self.findings.append(Finding(
                    pass_name="recompile-hazard", code="unbounded-shape",
                    file=sf.relpath, line=call.lineno,
                    anchor=f"{ctx.qual}->{info.qual}:{param}",
                    message=(
                        f"traced arg {param!r} of compiled program "
                        f"{info.qual}() is sliced to an unbounded runtime "
                        f"length — each distinct length retraces; pad to "
                        f"a pow2 bucket (core/bucketing.py) like the "
                        f"staging drains do")))
                return


def _join(*labels: str) -> str:
    if not labels:
        return OPAQUE
    return max(labels, key=lambda l: _RANK[l])


class _Ctx:
    """Classification context: one function body."""

    def __init__(self, p: _Pass, info, param_classes=None):
        self.p = p
        self.sf = info.sf
        self.cls = info.cls
        self.qual = info.qual
        self.key = (info.sf.relpath, info.qual)
        self.info = info
        self._assigns: Optional[Dict[str, List[ast.AST]]] = None
        self._param_classes = param_classes or {}

    def fn_params(self) -> Set[str]:
        return set(self.info.params) | set(self.info.kwonly)

    def assignments(self) -> Dict[str, List[ast.AST]]:
        if self._assigns is None:
            out: Dict[str, List[ast.AST]] = {}
            for node in walk_shallow(self.info.node):
                if isinstance(node, ast.Assign):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            out.setdefault(tgt.id, []).append(node.value)
                        elif isinstance(tgt, ast.Tuple) \
                                and isinstance(node.value, ast.Tuple) \
                                and len(tgt.elts) == len(node.value.elts):
                            for t, v in zip(tgt.elts, node.value.elts):
                                if isinstance(t, ast.Name):
                                    out.setdefault(t.id, []).append(v)
                elif isinstance(node, ast.AnnAssign) \
                        and node.value is not None \
                        and isinstance(node.target, ast.Name):
                    out.setdefault(node.target.id, []).append(node.value)
            self._assigns = out
        return self._assigns


# ---------------------------------------------------------------------------
# inventory table + drift check
# ---------------------------------------------------------------------------


def _build(project: Project) -> _Pass:
    p = _Pass(project)
    p.analyze()
    return p


def programs_table(project: Project, prebuilt: Optional[_Pass] = None
                   ) -> str:
    """Markdown inventory: compiled program × static arg × observed
    source classes (regen with --programs-table)."""
    p = prebuilt if prebuilt is not None else _build(project)
    lines = ["| program | static arg | call sites | sources |",
             "|---|---|---|---|"]
    for key in sorted(p.programs):
        prog = p.programs[key]
        name = f"`{key[0]}::{key[1]}`"
        for param in sorted(prog.static):
            seen = prog.observed.get(param) or {OPAQUE}
            lines.append(
                f"| {name} | `{param}` | {prog.call_sites} | "
                f"{', '.join(sorted(seen, key=lambda l: _RANK[l]))} |")
            name = ""  # group rows visually per program
    return "\n".join(lines)


@register("recompile-hazard")
def run(project: Project) -> List[Finding]:
    p = _build(project)
    findings = list(p.findings)

    # inventory drift: the docs table must match the generated one
    docs_rel = "docs/static-analysis.md"
    docs = project.read(docs_rel)
    table = programs_table(project, prebuilt=p)
    current = None
    if docs and _MARKER_BEGIN in docs and _MARKER_END in docs:
        current = docs.split(_MARKER_BEGIN, 1)[1] \
            .split(_MARKER_END, 1)[0].strip()
    if current is None or current != table.strip():
        findings.append(Finding(
            pass_name="recompile-hazard", code="inventory-drift",
            file=docs_rel, line=1, anchor="programs-inventory",
            message=(
                f"the compiled-program inventory in {docs_rel} is "
                f"{'missing' if current is None else 'stale'}: regenerate "
                f"with `python -m veneur_tpu.lint --programs-table` and "
                f"paste between the programs-inventory markers")))
    findings.sort(key=lambda f: (f.file, f.line, f.code))
    return findings

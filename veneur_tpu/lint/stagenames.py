"""Stage-name registry pass: every stage string documented, every
trace-bearing route contracted.

The flush-timeline's value rests on its vocabulary staying legible:
``docs/observability.md`` carries the stage table operators read a
timeline against, and the fleet trace plane's header contract lists
which routes carry ``X-Veneur-Trace``. Both drift silently — a new
``maybe_stage("...")`` call ships a stage nobody can look up, a new
traced route ships an undocumented contract — so this pass walks the
package for:

- every **stage string literal** passed to the StageRecorder surface
  (``stage`` / ``maybe_stage`` / ``record_abs`` / ``record_late``) and
  to ``sample_self_timing`` (the self-telemetry stage vocabulary).
  F-string holes normalize to ``<hole>`` and match any documented
  ``<...>`` placeholder (``f"post.{sink.name}"`` ↔ ``post.<sink>``).
  Nested calls record leaf names (``fetch``), which match as trailing
  path segments of documented dotted stages (``store.<group>.fetch``).
- every route in ``obs/tracectx.py``'s ``TRACED_ROUTES`` registry (the
  declared set of ``X-Veneur-Trace``-bearing endpoints).

Each must appear in ``docs/observability.md``; a miss is an
``undocumented-stage`` / ``undocumented-route`` finding against the
empty baseline. Non-literal stage names (variables like the per-group
``gen_name``) are unknowable statically and skipped — their documented
form is the ``<group>``-holed row.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from typing import List, Optional

from veneur_tpu.lint.framework import Finding, Project, dotted, register

_STAGE_FNS = ("stage", "maybe_stage", "record_abs", "record_late",
              "sample_self_timing")
_TRACECTX_FILE = "veneur_tpu/obs/tracectx.py"
_DOCS_FILE = "docs/observability.md"


@dataclass
class StageSite:
    name: str       # normalized: f-string holes -> <hole>
    file: str
    line: int
    fn: str


def _normalize(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant):
                parts.append(str(v.value))
            elif isinstance(v, ast.FormattedValue):
                inner = dotted(v.value)
                hole = inner.split(".")[-1] if inner else "hole"
                parts.append(f"<{hole}>")
        return "".join(parts)
    return None


def _call_fn_name(node: ast.Call) -> Optional[str]:
    if isinstance(node.func, ast.Name):
        return node.func.id
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    return None


def collect_stages(project: Project) -> List[StageSite]:
    sites: List[StageSite] = []
    for sf in project.files.values():
        if sf.relpath.startswith("veneur_tpu/lint/"):
            continue  # this pass's own fixtures/docstrings don't count
        for node in sf.nodes:
            if not isinstance(node, ast.Call) or not node.args:
                continue
            fn = _call_fn_name(node)
            if fn not in _STAGE_FNS:
                continue
            name = _normalize(node.args[0])
            if name is None or not name:
                continue
            sites.append(StageSite(name=name, file=sf.relpath,
                                   line=node.lineno, fn=fn))
    return sites


def collect_traced_routes(project: Project) -> List[StageSite]:
    """The TRACED_ROUTES registry (obs/tracectx.py) via AST — the
    declared list of X-Veneur-Trace-bearing endpoints."""
    sf = project.files.get(_TRACECTX_FILE)
    if sf is None:
        return []
    out: List[StageSite] = []
    for node in sf.nodes:
        if not isinstance(node, ast.Assign):
            continue
        targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
        if "TRACED_ROUTES" not in targets:
            continue
        if isinstance(node.value, (ast.Tuple, ast.List)):
            for elt in node.value.elts:
                if isinstance(elt, ast.Constant) \
                        and isinstance(elt.value, str):
                    out.append(StageSite(name=elt.value,
                                         file=sf.relpath,
                                         line=elt.lineno,
                                         fn="TRACED_ROUTES"))
    return out


def _doc_pattern(name: str) -> "re.Pattern":
    """A stage name as a docs regex: literal segments escaped, ``<x>``
    holes match any documented ``<...>`` placeholder, and the whole
    name may sit as a trailing segment of a longer dotted stage (leaf
    names nest under their runtime parents)."""
    body = "".join(
        r"<[A-Za-z0-9_*]+>" if part.startswith("<") else re.escape(part)
        for part in re.split(r"(<[A-Za-z0-9_]+>)", name))
    return re.compile(r"(?<![A-Za-z0-9_])" + body + r"(?![A-Za-z0-9_])")


@register("stage-registry")
def run(project: Project) -> List[Finding]:
    docs = project.read(_DOCS_FILE) or ""
    findings: List[Finding] = []
    seen = set()
    for site in collect_stages(project):
        if site.name in seen:
            continue
        seen.add(site.name)
        if _doc_pattern(site.name).search(docs):
            continue
        sf = project.files[site.file]
        if sf.suppressed(site.line, "undocumented-stage"):
            continue
        findings.append(Finding(
            pass_name="stage-registry", code="undocumented-stage",
            file=site.file, line=site.line, anchor=site.name,
            message=(f"stage `{site.name}` ({site.fn} call) is not in "
                     f"the {_DOCS_FILE} stage table — every stage an "
                     f"operator can see in /debug/flush-timeline must "
                     f"be documented there")))
    for site in collect_traced_routes(project):
        if _doc_pattern(site.name).search(docs):
            continue
        sf = project.files[site.file]
        if sf.suppressed(site.line, "undocumented-route"):
            continue
        findings.append(Finding(
            pass_name="stage-registry", code="undocumented-route",
            file=site.file, line=site.line, anchor=site.name,
            message=(f"X-Veneur-Trace route `{site.name}` "
                     f"(TRACED_ROUTES) is not in the {_DOCS_FILE} "
                     f"header-contract table — the hop contract cannot "
                     f"grow undocumented")))
    return findings

"""TSan-lite: runtime lock-state recorder for the static pass's blind spots.

The lock-discipline pass walks lexical call sites; it cannot see dynamic
dispatch (``getattr``, callables passed around) or verify that the
``with self._lock`` it accepted is the *store's* lock. This shim closes
the loop at test time, the way the reference leans on ``go test -race``:

    rec = LockStateRecorder(store)
    with rec:
        ... drive ingest/flush/checkpoint threads ...
    rec.assert_clean()

While armed, every ``@requires_lock("store")``-annotated method on every
group object owned by the store is wrapped; each call records whether
the calling thread actually holds ``store._lock`` at that moment
(``RLock._is_owned``). Mutations on *retired* flush generations are
exempt by design (swap-on-flush hands the flusher exclusive ownership)
— the wrapper honors the ``_retired`` flag the store already sets.

v2 additionally arms an Eraser-style lockset detector
(``lint/lockset.py``) over the store object and every group: the store
lock is proxied through a :class:`~veneur_tpu.lint.lockset.TrackedLock`
and every tracked *field* access — not just annotated method calls —
refines a per-field candidate lockset, so an unannotated mutator racing
the generation swap or the requeue path is reported as a genuine data
race with both stacks (``rec.races``). ``assert_clean()`` covers both
detectors.

Wrapping is per-instance (bound attributes on the group objects), so
parallel tests and the ingest fast path outside the context manager pay
nothing. The pytest fixture ``tsan_lite`` (tests/conftest.py) wires
this up; see docs/static-analysis.md.
"""

from __future__ import annotations

import functools
import threading
from dataclasses import dataclass
from typing import List

from veneur_tpu.core.locking import REQUIRES_LOCK_ATTR
from veneur_tpu.lint.lockset import FieldRaceRecorder


@dataclass
class UnlockedMutation:
    group: str
    method: str
    thread: str

    def __str__(self):
        return (f"{self.group}.{self.method}() called on thread "
                f"{self.thread} without holding the store lock")


class LockStateRecorder:
    """Wraps a MetricStore's group mutators; records unlocked calls."""

    def __init__(self, store, eraser: bool = True):
        self.store = store
        self.violations: List[UnlockedMutation] = []
        self._vlock = threading.Lock()
        self._wrapped: List[tuple] = []
        # one violation per outermost annotated call: sample() calling
        # _row() unlocked is ONE mutation, not two
        self._tls = threading.local()
        # the lockset detector rides along by default (eraser=False
        # opts a test out, e.g. to demonstrate exactly what v1 caught)
        self.eraser = FieldRaceRecorder() if eraser else None

    # -- arm / disarm ------------------------------------------------------

    def __enter__(self):
        self.arm()
        return self

    def __exit__(self, *exc):
        self.disarm()
        return False

    def arm(self):
        from veneur_tpu.core.store import MetricStore

        gen_groups = getattr(type(self.store), "_GEN_GROUPS",
                             MetricStore._GEN_GROUPS)
        if self.eraser is not None:
            self.eraser.track_lock(self.store, "_lock", "store")
            self.eraser.instrument(self.store, "store")
        for attr in gen_groups:
            group = getattr(self.store, attr, None)
            if group is not None:
                self._wrap_group(attr, group)
                if self.eraser is not None:
                    self.eraser.instrument(group, attr)
        # a flush swaps every group for a fresh (unwrapped) twin; hook
        # the swap so coverage survives flushes instead of silently
        # ending at the first one
        rec = self
        orig_swap = self.store._swap_generation

        @functools.wraps(orig_swap)
        def swap_and_rearm(*args, **kwargs):
            gen = orig_swap(*args, **kwargs)
            for attr in gen_groups:
                group = getattr(rec.store, attr, None)
                if group is not None:
                    rec._wrap_group(attr, group)
                    if rec.eraser is not None:
                        rec.eraser.instrument(group, attr)
            return gen

        self.store._swap_generation = swap_and_rearm
        self._wrapped.append((self.store, "_swap_generation",
                              swap_and_rearm))

    def disarm(self):
        for obj, name, _wrapper in self._wrapped:
            try:
                delattr(obj, name)
            except AttributeError:
                pass
        self._wrapped.clear()
        if self.eraser is not None:
            self.eraser.restore()

    def _wrap_group(self, group_name: str, group):
        for name in dir(type(group)):
            fn = getattr(type(group), name, None)
            if not callable(fn) \
                    or getattr(fn, REQUIRES_LOCK_ATTR, None) is None:
                continue
            bound = getattr(group, name)
            wrapper = self._make_wrapper(group_name, name, bound, group)
            setattr(group, name, wrapper)
            self._wrapped.append((group, name, wrapper))

    def _make_wrapper(self, group_name: str, method: str, bound, group):
        rec = self

        @functools.wraps(bound)
        def wrapper(*args, **kwargs):
            depth = getattr(rec._tls, "depth", 0)
            # retired generations are exclusively owned by the flusher;
            # off-lock mutation there is the design, not a race
            if depth == 0 and not getattr(group, "_retired", False) \
                    and not rec._lock_held():
                with rec._vlock:
                    rec.violations.append(UnlockedMutation(
                        group=group_name, method=method,
                        thread=threading.current_thread().name))
            rec._tls.depth = depth + 1
            try:
                return bound(*args, **kwargs)
            finally:
                rec._tls.depth = depth

        return wrapper

    def _lock_held(self) -> bool:
        lock = self.store._lock
        is_owned = getattr(lock, "_is_owned", None)
        if is_owned is not None:  # RLock: exact ownership check
            return bool(is_owned())
        return bool(lock.locked())  # plain Lock: held by *someone*

    # -- assertions --------------------------------------------------------

    @property
    def races(self):
        """Field-level data races from the lockset detector (empty when
        armed with eraser=False)."""
        return self.eraser.races if self.eraser is not None else []

    def assert_clean(self):
        if self.violations:
            lines = "\n  ".join(str(v) for v in self.violations[:20])
            raise AssertionError(
                f"TSan-lite: {len(self.violations)} unlocked group "
                f"mutation(s):\n  {lines}")
        if self.eraser is not None:
            self.eraser.assert_no_races()

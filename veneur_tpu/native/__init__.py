"""ctypes bindings for the C++ native ingest library.

Builds ``libveneur_ingest.so`` from ``veneur_ingest.cpp`` on first use
(g++ -O2, cached beside the source) and exposes:

- ``parse_lines(data)`` — parse a byte buffer of DogStatsD lines into a
  ``ParsedBatch`` of numpy arrays + arena (one FFI call per batch).
- ``NativeUDPReader`` — the SO_REUSEPORT reader pool: N kernel-balanced
  sockets drained with recvmmsg on C++ threads, handing Python packed
  parsed batches via double-buffer swaps.
- ``frame_scan(buf)`` — framed-SSF boundary scanner (wire.go:42-108).

``available()`` gates everything: without a compiler the pure-Python
path (veneur_tpu.samplers.parser + veneur_tpu.networking) is used.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import List, Optional, Tuple

import numpy as np

log = logging.getLogger("veneur.native")

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "veneur_ingest.cpp")
_SO = os.path.join(_HERE, "libveneur_ingest.so")

# record types (RecordType in veneur_ingest.cpp)
TYPE_NAMES = ["counter", "gauge", "histogram", "timer", "set", "raw"]
RAW = 5

_lib = None
_lib_lock = threading.Lock()
_build_error: Optional[str] = None


class _VsBatch(ctypes.Structure):
    pass  # fields set after _VtBatch (holds a VtBatch* for its metrics)


class _VtBatch(ctypes.Structure):
    _fields_ = [
        ("capacity", ctypes.c_uint32),
        ("arena_cap", ctypes.c_uint32),
        ("count", ctypes.c_uint32),
        ("arena_len", ctypes.c_uint32),
        ("parse_errors", ctypes.c_uint64),
        ("type", ctypes.POINTER(ctypes.c_uint8)),
        ("scope", ctypes.POINTER(ctypes.c_uint8)),
        ("value", ctypes.POINTER(ctypes.c_double)),
        ("sample_rate", ctypes.POINTER(ctypes.c_float)),
        ("digest", ctypes.POINTER(ctypes.c_uint32)),
        ("name_off", ctypes.POINTER(ctypes.c_uint32)),
        ("name_len", ctypes.POINTER(ctypes.c_uint32)),
        ("tags_off", ctypes.POINTER(ctypes.c_uint32)),
        ("tags_len", ctypes.POINTER(ctypes.c_uint32)),
        ("aux_off", ctypes.POINTER(ctypes.c_uint32)),
        ("aux_len", ctypes.POINTER(ctypes.c_uint32)),
        ("arena", ctypes.POINTER(ctypes.c_char)),
    ]


_VsBatch._fields_ = [
    ("capacity", ctypes.c_uint32),
    ("count", ctypes.c_uint32),
    ("arena_cap", ctypes.c_uint32),
    ("arena_len", ctypes.c_uint32),
    ("decode_errors", ctypes.c_uint64),
    ("invalid_samples", ctypes.c_uint64),
    ("version", ctypes.POINTER(ctypes.c_int32)),
    ("trace_id", ctypes.POINTER(ctypes.c_int64)),
    ("span_id", ctypes.POINTER(ctypes.c_int64)),
    ("parent_id", ctypes.POINTER(ctypes.c_int64)),
    ("start_ns", ctypes.POINTER(ctypes.c_int64)),
    ("end_ns", ctypes.POINTER(ctypes.c_int64)),
    ("error", ctypes.POINTER(ctypes.c_uint8)),
    ("indicator", ctypes.POINTER(ctypes.c_uint8)),
    ("service_off", ctypes.POINTER(ctypes.c_uint32)),
    ("service_len", ctypes.POINTER(ctypes.c_uint32)),
    ("name_off", ctypes.POINTER(ctypes.c_uint32)),
    ("name_len", ctypes.POINTER(ctypes.c_uint32)),
    ("raw_off", ctypes.POINTER(ctypes.c_uint32)),
    ("raw_len", ctypes.POINTER(ctypes.c_uint32)),
    ("arena", ctypes.POINTER(ctypes.c_char)),
    ("metrics", ctypes.POINTER(_VtBatch)),
    ("slow_cap", ctypes.c_uint32),
    ("slow_count", ctypes.c_uint32),
    ("slow_off", ctypes.POINTER(ctypes.c_uint32)),
    ("slow_len", ctypes.POINTER(ctypes.c_uint32)),
]


def _build() -> Optional[str]:
    """Compile the shared library; returns an error string on failure."""
    try:
        subprocess.run(
            ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
             "-o", _SO, _SRC, "-ldl"],
            check=True, capture_output=True, timeout=120)
        return None
    except FileNotFoundError:
        return "g++ not found"
    except subprocess.TimeoutExpired:
        return "native build timed out"
    except subprocess.CalledProcessError as e:
        return f"native build failed: {e.stderr.decode(errors='replace')}"


def _load():
    global _lib, _build_error
    with _lib_lock:
        if _lib is not None or _build_error is not None:
            return _lib
        if not os.path.exists(_SO) or (
                os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
            err = _build()
            if err is not None:
                _build_error = err
                log.warning("native ingest unavailable: %s", err)
                return None
        try:
            lib = _bind(ctypes.CDLL(_SO))
        except OSError as e:
            # a stale or foreign-platform .so (git preserves no mtimes, so
            # the staleness check above can miss): rebuild once, then give
            # up — available() must never raise
            log.warning("native library load failed (%s); rebuilding", e)
            err = _build()
            if err is None:
                try:
                    lib = _bind(ctypes.CDLL(_SO))
                except OSError as e2:
                    err = f"rebuilt library still unloadable: {e2}"
            if err is not None:
                _build_error = err
                log.warning("native ingest unavailable: %s", err)
                return None
        _lib = lib
        return _lib


def _bind(lib):
    lib.vt_batch_new.restype = ctypes.POINTER(_VtBatch)
    lib.vt_batch_new.argtypes = [ctypes.c_uint32, ctypes.c_uint32]
    lib.vt_batch_free.argtypes = [ctypes.POINTER(_VtBatch)]
    lib.vt_batch_reset.argtypes = [ctypes.POINTER(_VtBatch)]
    lib.vt_parse_lines.restype = ctypes.c_uint32
    lib.vt_parse_lines.argtypes = [ctypes.c_char_p, ctypes.c_size_t,
                                   ctypes.POINTER(_VtBatch)]
    lib.vt_frame_scan.restype = ctypes.c_uint32
    lib.vt_frame_scan.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_uint32),
        ctypes.c_uint32, ctypes.POINTER(ctypes.c_size_t),
        ctypes.POINTER(ctypes.c_int)]
    lib.vt_reader_start.restype = ctypes.c_void_p
    lib.vt_reader_start.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_uint32, ctypes.c_uint32, ctypes.c_int]
    lib.vt_reader_port.restype = ctypes.c_int
    lib.vt_reader_port.argtypes = [ctypes.c_void_p]
    lib.vt_reader_count.restype = ctypes.c_int
    lib.vt_reader_count.argtypes = [ctypes.c_void_p]
    lib.vt_reader_swap.restype = ctypes.POINTER(_VtBatch)
    lib.vt_reader_swap.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.vt_reader_packets.restype = ctypes.c_uint64
    lib.vt_reader_packets.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.vt_reader_drops.restype = ctypes.c_uint64
    lib.vt_reader_drops.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.vt_reader_stop.argtypes = [ctypes.c_void_p]
    lib.vt_intern_new.restype = ctypes.c_void_p
    lib.vt_intern_free.argtypes = [ctypes.c_void_p]
    lib.vt_intern_reset.argtypes = [ctypes.c_void_p]
    lib.vt_intern_put.argtypes = [
        ctypes.c_void_p, ctypes.c_uint8, ctypes.c_char_p, ctypes.c_uint32,
        ctypes.c_char_p, ctypes.c_uint32, ctypes.c_uint32]
    lib.vt_intern_assign.restype = ctypes.c_uint32
    lib.vt_intern_assign.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(_VtBatch),
        ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_uint8),
        ctypes.POINTER(ctypes.c_uint32)]
    lib.vs_batch_new.restype = ctypes.POINTER(_VsBatch)
    lib.vs_batch_new.argtypes = [ctypes.c_uint32, ctypes.c_uint32,
                                 ctypes.c_uint32, ctypes.c_uint32]
    lib.vs_batch_free.argtypes = [ctypes.POINTER(_VsBatch)]
    lib.vs_batch_reset.argtypes = [ctypes.POINTER(_VsBatch)]
    lib.vs_decode_span.restype = ctypes.c_int
    lib.vs_decode_span.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.POINTER(_VsBatch),
        ctypes.c_char_p, ctypes.c_uint32]
    lib.vs_reader_start.restype = ctypes.c_void_p
    lib.vs_reader_start.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_uint32, ctypes.c_uint32, ctypes.c_uint32,
        ctypes.c_uint32, ctypes.c_int, ctypes.c_char_p]
    lib.vs_reader_port.restype = ctypes.c_int
    lib.vs_reader_port.argtypes = [ctypes.c_void_p]
    lib.vs_reader_count.restype = ctypes.c_int
    lib.vs_reader_count.argtypes = [ctypes.c_void_p]
    lib.vs_reader_swap.restype = ctypes.POINTER(_VsBatch)
    lib.vs_reader_swap.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.vs_reader_packets.restype = ctypes.c_uint64
    lib.vs_reader_packets.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.vs_reader_drops.restype = ctypes.c_uint64
    lib.vs_reader_drops.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.vs_reader_stop.argtypes = [ctypes.c_void_p]
    lib.vt_tls_available.restype = ctypes.c_int
    lib.vt_tls_server_start.restype = ctypes.c_void_p
    lib.vt_tls_server_start.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_char_p, ctypes.c_uint32, ctypes.c_uint32, ctypes.c_int]
    lib.vt_tls_server_port.restype = ctypes.c_int
    lib.vt_tls_server_port.argtypes = [ctypes.c_void_p]
    lib.vt_tls_server_swap.restype = ctypes.POINTER(_VtBatch)
    lib.vt_tls_server_swap.argtypes = [ctypes.c_void_p]
    lib.vt_tls_server_conns.restype = ctypes.c_uint64
    lib.vt_tls_server_conns.argtypes = [ctypes.c_void_p]
    lib.vt_tls_server_handshake_failures.restype = ctypes.c_uint64
    lib.vt_tls_server_handshake_failures.argtypes = [ctypes.c_void_p]
    lib.vt_tls_server_drops.restype = ctypes.c_uint64
    lib.vt_tls_server_drops.argtypes = [ctypes.c_void_p]
    lib.vt_tls_server_stop.argtypes = [ctypes.c_void_p]
    return lib


def available() -> bool:
    return _load() is not None


class ParsedBatch:
    """numpy views over a VtBatch. Arrays are COPIES (safe after the
    underlying batch is reused); the arena is one bytes object."""

    __slots__ = ("count", "parse_errors", "type", "scope", "value",
                 "sample_rate", "digest", "name_off", "name_len",
                 "tags_off", "tags_len", "aux_off", "aux_len", "arena")

    def __init__(self, b: "_VtBatch"):
        n = b.count
        self.count = n
        self.parse_errors = b.parse_errors

        def arr(ptr, dtype):
            if n == 0:
                return np.empty(0, dtype)
            return np.ctypeslib.as_array(ptr, shape=(n,)).astype(dtype,
                                                                 copy=True)

        self.type = arr(b.type, np.uint8)
        self.scope = arr(b.scope, np.uint8)
        self.value = arr(b.value, np.float64)
        self.sample_rate = arr(b.sample_rate, np.float32)
        self.digest = arr(b.digest, np.uint32)
        self.name_off = arr(b.name_off, np.uint32)
        self.name_len = arr(b.name_len, np.uint32)
        self.tags_off = arr(b.tags_off, np.uint32)
        self.tags_len = arr(b.tags_len, np.uint32)
        self.aux_off = arr(b.aux_off, np.uint32)
        self.aux_len = arr(b.aux_len, np.uint32)
        self.arena = ctypes.string_at(b.arena, b.arena_len)

    def name(self, i: int) -> str:
        o, l = self.name_off[i], self.name_len[i]
        return self.arena[o:o + l].decode("utf-8", "replace")

    def joined_tags(self, i: int) -> str:
        o, l = self.tags_off[i], self.tags_len[i]
        return self.arena[o:o + l].decode("utf-8", "replace")

    def aux(self, i: int) -> bytes:
        o, l = self.aux_off[i], self.aux_len[i]
        return self.arena[o:o + l]

    def member_hashes(self) -> np.ndarray:
        """uint64 set-member hashes carried in the value slot's bit
        pattern (only meaningful for records of type set)."""
        return self.value.view(np.uint64)

    def raw_view(self) -> "_VtBatch":
        """A VtBatch struct pointing at this batch's numpy arrays/arena,
        for C calls that re-read the batch (vt_intern_assign). The struct
        only borrows; keep the ParsedBatch alive across the call."""
        b = _VtBatch()
        b.count = self.count
        b.arena_len = len(self.arena)
        u8, u32 = ctypes.c_uint8, ctypes.c_uint32
        b.type = self.type.ctypes.data_as(ctypes.POINTER(u8))
        b.scope = self.scope.ctypes.data_as(ctypes.POINTER(u8))
        b.name_off = self.name_off.ctypes.data_as(ctypes.POINTER(u32))
        b.name_len = self.name_len.ctypes.data_as(ctypes.POINTER(u32))
        b.tags_off = self.tags_off.ctypes.data_as(ctypes.POINTER(u32))
        b.tags_len = self.tags_len.ctypes.data_as(ctypes.POINTER(u32))
        b.arena = ctypes.cast(ctypes.c_char_p(self.arena),
                              ctypes.POINTER(ctypes.c_char))
        return b


def parse_lines(data: bytes, max_records: int = 0,
                arena_cap: int = 0) -> ParsedBatch:
    """Parse a buffer of newline-separated DogStatsD lines natively."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native ingest unavailable: {_build_error}")
    max_records = max_records or max(16, data.count(b"\n") + 1)
    arena_cap = arena_cap or (len(data) + 64)
    b = lib.vt_batch_new(max_records, arena_cap)
    try:
        lib.vt_parse_lines(data, len(data), b)
        return ParsedBatch(b.contents)
    finally:
        lib.vt_batch_free(b)


def frame_scan(buf: bytes, max_frames: int = 4096
               ) -> Tuple[List[Tuple[int, int]], int, bool]:
    """Scan for complete SSF frames: returns ([(payload_off, payload_len)],
    bytes_consumed, poisoned)."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native ingest unavailable: {_build_error}")
    offs = (ctypes.c_uint32 * max_frames)()
    lens = (ctypes.c_uint32 * max_frames)()
    consumed = ctypes.c_size_t(0)
    poisoned = ctypes.c_int(0)
    n = lib.vt_frame_scan(buf, len(buf), offs, lens, max_frames,
                          ctypes.byref(consumed), ctypes.byref(poisoned))
    return ([(offs[i], lens[i]) for i in range(n)], consumed.value,
            bool(poisoned.value))


MISS = 0xFFFFFFFF  # vt_intern_assign's "unknown series" row sentinel


class InternTable:
    """The C++ series-interning table: (kind, name, tags) -> row. Only
    memoizes rows the Python Interner assigned; unknown keys come back as
    MISS for the caller to resolve and teach back with put()."""

    def __init__(self):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native ingest unavailable: {_build_error}")
        self._lib = lib
        self._handle = lib.vt_intern_new()

    def assign(self, batch: "ParsedBatch"):
        """Returns (rows uint32[count], kinds uint8[count],
        miss_indices uint32[nmiss]); misses hold MISS in rows."""
        count = batch.count
        rows = np.empty(count, np.uint32)
        kinds = np.empty(count, np.uint8)
        miss = np.empty(count, np.uint32)
        view = batch.raw_view()
        nmiss = self._lib.vt_intern_assign(
            self._handle, ctypes.byref(view),
            rows.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            kinds.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            miss.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)))
        return rows, kinds, miss[:nmiss]

    def put(self, kind: int, name: bytes, tags: bytes, row: int):
        self._lib.vt_intern_put(self._handle, kind, name, len(name),
                                tags, len(tags), row)

    def reset(self):
        self._lib.vt_intern_reset(self._handle)

    def close(self):
        if self._handle:
            self._lib.vt_intern_free(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class LazySpan:
    """A decoded SSF span: hot header fields preloaded from the C++
    span batch, everything else (tags map, embedded metrics, version)
    materialized from the raw protobuf bytes on first touch — span
    sinks that never read the cold fields (blackhole, counters-only)
    never pay the Python protobuf decode. ``metrics_extracted`` tells
    the metric-extraction sink the C++ lane already converted the
    embedded samples (sinks/ssfmetrics.py)."""

    __slots__ = ("trace_id", "id", "parent_id", "start_timestamp",
                 "end_timestamp", "error", "indicator", "service",
                 "name", "metrics_extracted", "_raw", "_pb")

    def __init__(self, trace_id, id, parent_id, start_timestamp,
                 end_timestamp, error, indicator, service, name, raw):
        self.trace_id = trace_id
        self.id = id
        self.parent_id = parent_id
        self.start_timestamp = start_timestamp
        self.end_timestamp = end_timestamp
        self.error = error
        self.indicator = indicator
        self.service = service
        self.name = name
        self.metrics_extracted = True
        self._raw = raw
        self._pb = None

    @property
    def pb(self):
        if self._pb is None:
            from veneur_tpu.protocol.gen.ssf import sample_pb2

            span = sample_pb2.SSFSpan()
            span.ParseFromString(self._raw)
            self._pb = span
        return self._pb

    def SerializeToString(self):  # noqa: N802 - protobuf naming
        return self._raw

    def __getattr__(self, item):
        # cold fields (tags, metrics, version, ...) delegate to the
        # materialized protobuf; __getattr__ only fires for names not
        # covered by __slots__
        if item.startswith("_"):
            raise AttributeError(item)
        return getattr(self.pb, item)


class SpanBatch:
    """numpy/bytes copies of a VsBatch (safe after the C++ batch is
    reused): span headers, the embedded-metric records as an ordinary
    ParsedBatch (ready for MetricStore.process_batch), and the raw
    bytes of slow-lane samples (STATUS / undecodable) for the Python
    parser."""

    __slots__ = ("count", "decode_errors", "invalid_samples",
                 "metrics", "slow_samples", "_trace_id", "_span_id",
                 "_parent_id", "_start", "_end", "_error", "_indicator",
                 "_svc_off", "_svc_len", "_name_off", "_name_len",
                 "_raw_off", "_raw_len", "_arena")

    def __init__(self, b: "_VsBatch"):
        n = b.count
        self.count = n
        self.decode_errors = b.decode_errors
        self.invalid_samples = b.invalid_samples

        def arr(ptr, dtype):
            if n == 0:
                return np.empty(0, dtype)
            return np.ctypeslib.as_array(ptr, shape=(n,)).astype(
                dtype, copy=True)

        self._trace_id = arr(b.trace_id, np.int64)
        self._span_id = arr(b.span_id, np.int64)
        self._parent_id = arr(b.parent_id, np.int64)
        self._start = arr(b.start_ns, np.int64)
        self._end = arr(b.end_ns, np.int64)
        self._error = arr(b.error, np.uint8)
        self._indicator = arr(b.indicator, np.uint8)
        self._svc_off = arr(b.service_off, np.uint32)
        self._svc_len = arr(b.service_len, np.uint32)
        self._name_off = arr(b.name_off, np.uint32)
        self._name_len = arr(b.name_len, np.uint32)
        self._raw_off = arr(b.raw_off, np.uint32)
        self._raw_len = arr(b.raw_len, np.uint32)
        self._arena = ctypes.string_at(b.arena, b.arena_len)
        self.metrics = ParsedBatch(b.metrics.contents)
        ns = b.slow_count
        self.slow_samples = []
        for i in range(ns):
            off, ln = b.slow_off[i], b.slow_len[i]
            self.slow_samples.append(self._arena[off:off + ln])

    def span(self, i: int) -> LazySpan:
        ro, rl = self._raw_off[i], self._raw_len[i]
        so, sl = self._svc_off[i], self._svc_len[i]
        no, nl = self._name_off[i], self._name_len[i]
        return LazySpan(
            int(self._trace_id[i]), int(self._span_id[i]),
            int(self._parent_id[i]), int(self._start[i]),
            int(self._end[i]), bool(self._error[i]),
            bool(self._indicator[i]),
            self._arena[so:so + sl].decode("utf-8", "replace"),
            self._arena[no:no + nl].decode("utf-8", "replace"),
            self._arena[ro:ro + rl])

    def spans(self) -> List[LazySpan]:
        return [self.span(i) for i in range(self.count)]


def decode_spans(datagrams: List[bytes],
                 indicator_timer_name: str = "") -> SpanBatch:
    """Batch-decode bare SSFSpan datagrams natively (tests and the
    direct-call path; the server uses NativeSSFReader)."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native ingest unavailable: {_build_error}")
    total = sum(len(d) for d in datagrams)
    ind = indicator_timer_name.encode()
    b = lib.vs_batch_new(max(len(datagrams), 16), total + 64,
                         max(32, len(datagrams) * 9),
                         total * 2 + 1024)
    try:
        for d in datagrams:
            lib.vs_decode_span(d, len(d), b, ind, len(ind))
        return SpanBatch(b.contents)
    finally:
        lib.vs_batch_free(b)


class NativeSSFReader:
    """The C++ SSF reader pool: SO_REUSEPORT sockets drained with
    recvmmsg, one SSFSpan decoded per datagram ON THE C++ THREADS (off
    the GIL), embedded metric samples converted to parsed records
    in-line. ``drain()`` swaps every reader's batch."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 num_readers: int = 1, rcvbuf: int = 2 * 1024 * 1024,
                 span_cap: int = 32768, arena_cap: int = 32 * 1024 * 1024,
                 metric_cap: int = 262144,
                 metric_arena: int = 32 * 1024 * 1024,
                 dgram_max: int = 8192,
                 indicator_timer_name: str = ""):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native ingest unavailable: {_build_error}")
        self._lib = lib
        self._handle = lib.vs_reader_start(
            host.encode(), port, num_readers, rcvbuf, span_cap,
            arena_cap, metric_cap, metric_arena, dgram_max,
            indicator_timer_name.encode())
        if not self._handle:
            raise OSError(f"could not bind native SSF readers on "
                          f"{host}:{port}")
        self.port = lib.vs_reader_port(self._handle)
        self.num_readers = lib.vs_reader_count(self._handle)

    def drain(self) -> List[SpanBatch]:
        out = []
        for i in range(self.num_readers):
            b = self._lib.vs_reader_swap(self._handle, i)
            if b.contents.count or b.contents.decode_errors:
                out.append(SpanBatch(b.contents))
        return out

    def packets(self) -> int:
        return sum(self._lib.vs_reader_packets(self._handle, i)
                   for i in range(self.num_readers))

    def drops(self) -> int:
        return sum(self._lib.vs_reader_drops(self._handle, i)
                   for i in range(self.num_readers))

    def stop(self) -> None:
        if self._handle:
            self._lib.vs_reader_stop(self._handle)
            self._handle = None

    def leak(self) -> None:
        """See NativeUDPReader.leak."""
        self._handle = None

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


def tls_available() -> bool:
    """True when the runtime libssl loaded (the TLS listener dlopens
    the stable OpenSSL 3 C ABI — no headers needed at build time)."""
    lib = _load()
    return bool(lib is not None and lib.vt_tls_available())


class NativeTLSReader:
    """The C++ TCP/TLS statsd listener: accept, handshake, newline
    framing and DogStatsD parsing all happen off the GIL; Python
    drains parsed batches through the same swap protocol as the UDP
    pool. Empty ``cert_path`` serves plaintext TCP; ``ca_path`` turns
    on required client-cert auth (make_server_tls_context parity)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 cert_path: str = "", key_path: str = "",
                 ca_path: str = "", batch_records: int = 262144,
                 batch_arena: int = 32 * 1024 * 1024,
                 max_line: int = 4096):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native ingest unavailable: {_build_error}")
        if cert_path and not lib.vt_tls_available():
            raise RuntimeError("libssl runtime unavailable")
        self._lib = lib
        self._handle = lib.vt_tls_server_start(
            host.encode(), port, cert_path.encode(), key_path.encode(),
            ca_path.encode(), batch_records, batch_arena, max_line)
        if not self._handle:
            raise OSError(
                f"could not start native TLS listener on {host}:{port}")
        self.port = lib.vt_tls_server_port(self._handle)
        self.num_readers = 1

    def drain(self) -> List[ParsedBatch]:
        b = self._lib.vt_tls_server_swap(self._handle)
        if b.contents.count or b.contents.parse_errors:
            return [ParsedBatch(b.contents)]
        return []

    def conns(self) -> int:
        return self._lib.vt_tls_server_conns(self._handle)

    def handshake_failures(self) -> int:
        return self._lib.vt_tls_server_handshake_failures(self._handle)

    def packets(self) -> int:
        return self.conns()

    def drops(self) -> int:
        return self._lib.vt_tls_server_drops(self._handle)

    def stop(self) -> None:
        if self._handle:
            self._lib.vt_tls_server_stop(self._handle)
            self._handle = None

    def leak(self) -> None:
        """See NativeUDPReader.leak."""
        self._handle = None

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


class NativeUDPReader:
    """The C++ SO_REUSEPORT reader pool (networking.go:37-87 rebuilt
    native). ``drain()`` swaps every reader's batch and returns the
    non-empty ones."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 num_readers: int = 1, rcvbuf: int = 2 * 1024 * 1024,
                 batch_records: int = 262144,
                 batch_arena: int = 32 * 1024 * 1024,
                 dgram_max: int = 8192):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native ingest unavailable: {_build_error}")
        self._lib = lib
        self._handle = lib.vt_reader_start(
            host.encode(), port, num_readers, rcvbuf, batch_records,
            batch_arena, dgram_max)
        if not self._handle:
            raise OSError(f"could not bind native UDP readers on "
                          f"{host}:{port}")
        self.port = lib.vt_reader_port(self._handle)
        self.num_readers = lib.vt_reader_count(self._handle)

    def drain(self) -> List[ParsedBatch]:
        out = []
        for i in range(self.num_readers):
            b = self._lib.vt_reader_swap(self._handle, i)
            if b.contents.count or b.contents.parse_errors:
                out.append(ParsedBatch(b.contents))
        return out

    def packets(self) -> int:
        return sum(self._lib.vt_reader_packets(self._handle, i)
                   for i in range(self.num_readers))

    def drops(self) -> int:
        return sum(self._lib.vt_reader_drops(self._handle, i)
                   for i in range(self.num_readers))

    def stop(self) -> None:
        if self._handle:
            self._lib.vt_reader_stop(self._handle)
            self._handle = None

    def leak(self) -> None:
        """Deliberately abandon the pool WITHOUT freeing it: disarms
        stop() and the GC finalizer. Used when a consumer thread may
        still be touching the pool's batches at shutdown — a bounded
        memory leak at process exit beats a use-after-free."""
        self._handle = None

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass

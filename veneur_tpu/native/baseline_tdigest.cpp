// Sequential merging t-digest baseline: a faithful C++ reimplementation
// of the reference's per-series flush algorithm (Dunning's merging
// t-digest: /root/reference/tdigest/merging_digest.go — Add :111,
// mergeAllTemps :135, Quantile :297), used by bench.py to MEASURE the
// scalar single-core baseline instead of guessing one. No Go toolchain
// ships in this image; C++ -O2 is within ~1.0-1.5x of Go for this kind
// of tight float loop, which we note in the bench output.
//
// Implemented from the published algorithm, not translated: weight-
// ordered greedy scan with the k-scale k(q) = C(asin(2q-1)/pi + 1/2),
// temp buffer of ~32 entries merged when full, uniform-centroid
// interpolation for quantiles.

#include <time.h>

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <vector>

namespace {

struct Centroid {
  double mean;
  double weight;
};

struct MergingDigest {
  double compression;
  std::vector<Centroid> main;
  std::vector<Centroid> temp;
  double temp_weight = 0.0;
  double main_weight = 0.0;
  double mn = HUGE_VAL;
  double mx = -HUGE_VAL;

  explicit MergingDigest(double c) : compression(c) {
    main.reserve(static_cast<size_t>(M_PI * c / 2) + 2);
    temp.reserve(32);
  }

  double index_estimate(double q) const {
    return compression * (std::asin(2.0 * q - 1.0) / M_PI + 0.5);
  }

  void merge_all_temps() {
    if (temp.empty()) return;
    std::sort(temp.begin(), temp.end(),
              [](const Centroid& a, const Centroid& b) {
                return a.mean < b.mean;
              });
    double total = main_weight + temp_weight;
    std::vector<Centroid> merged;
    merged.reserve(main.size() + temp.size());
    size_t ti = 0, mi = 0;
    double so_far = 0.0;
    double bound = 0.0;
    bool have_bound = false;
    auto push = [&](const Centroid& c) {
      double proposed = so_far + c.weight;
      if (!have_bound || proposed > bound) {
        // start a new output centroid at the next k boundary
        double k = index_estimate(so_far / total);
        bound = total *
                (std::sin(M_PI * ((std::floor(k) + 1.0) / compression - 0.5))
                 + 1.0) / 2.0;
        have_bound = true;
        merged.push_back(c);
      } else {
        Centroid& last = merged.back();
        double w = last.weight + c.weight;
        last.mean = (last.mean * last.weight + c.mean * c.weight) / w;
        last.weight = w;
      }
      so_far = proposed;
    };
    while (ti < temp.size() && mi < main.size()) {
      if (temp[ti].mean <= main[mi].mean) push(temp[ti++]);
      else push(main[mi++]);
    }
    while (ti < temp.size()) push(temp[ti++]);
    while (mi < main.size()) push(main[mi++]);
    main.swap(merged);
    main_weight = total;
    temp.clear();
    temp_weight = 0.0;
  }

  void add(double v, double w) {
    if (temp.size() >= 32) merge_all_temps();
    temp.push_back({v, w});
    temp_weight += w;
    mn = std::min(mn, v);
    mx = std::max(mx, v);
  }

  double quantile(double q) {
    merge_all_temps();
    if (main.empty()) return NAN;
    double target = q * main_weight;
    double so_far = 0.0;
    for (size_t i = 0; i < main.size(); i++) {
      const Centroid& c = main[i];
      if (target <= so_far + c.weight) {
        double lb = (i == 0) ? mn : 0.5 * (main[i - 1].mean + c.mean);
        double ub = (i + 1 == main.size())
                        ? mx
                        : 0.5 * (c.mean + main[i + 1].mean);
        double prop = (target - so_far) / c.weight;
        return lb + prop * (ub - lb);
      }
      so_far += c.weight;
    }
    return mx;
  }
};

}  // namespace

// Benchmark: per-series FLUSH work — drain the pending temp buffer into
// the main list and evaluate nq quantiles (Histo.Flush + mergeAllTemps,
// the reference's own BenchmarkServerFlush shape: ingest happens during
// the interval and is NOT part of the timed flush). Each iteration
// refills every digest's temp buffer with `per_interval` samples
// untimed; keep per_interval <= 32 so no merge work escapes the timed
// region through mid-add temp drains.
extern "C" double vt_baseline_flush_ns(uint32_t num_series,
                                       uint32_t per_interval,
                                       const double* qs, uint32_t nq,
                                       uint32_t iters) {
  std::vector<MergingDigest> digests;
  digests.reserve(num_series);
  uint64_t seed = 0x243F6A8885A308D3ULL;
  auto rnd = [&seed]() {
    seed ^= seed << 13;
    seed ^= seed >> 7;
    seed ^= seed << 17;
    return static_cast<double>(seed >> 11) / 9007199254740992.0;
  };
  for (uint32_t s = 0; s < num_series; s++) {
    digests.emplace_back(100.0);
    for (int i = 0; i < 64; i++) digests[s].add(rnd() * 100.0, 1.0);
    digests[s].merge_all_temps();
  }
  double best_ns = HUGE_VAL;
  volatile double sink = 0.0;
  for (uint32_t it = 0; it < iters; it++) {
    // untimed: stage this interval's samples into the temp buffers
    for (uint32_t s = 0; s < num_series; s++) {
      for (uint32_t i = 0; i < per_interval; i++) {
        digests[s].add(rnd() * 100.0, 1.0);
      }
    }
    struct timespec t0, t1;
    clock_gettime(CLOCK_MONOTONIC, &t0);
    for (uint32_t s = 0; s < num_series; s++) {
      MergingDigest& d = digests[s];
      d.merge_all_temps();
      for (uint32_t p = 0; p < nq; p++) sink += d.quantile(qs[p]);
    }
    clock_gettime(CLOCK_MONOTONIC, &t1);
    double ns = (t1.tv_sec - t0.tv_sec) * 1e9 + (t1.tv_nsec - t0.tv_nsec);
    best_ns = std::min(best_ns, ns / num_series);
  }
  (void)sink;
  return best_ns;
}

"""ctypes bindings for the C++ egress library (veneur_egress.cpp).

The flush-egress twin of the ingest bindings in ``__init__.py``:

- ``dd_series_bodies`` — columnar flush block → Datadog ``/api/v1/series``
  JSON bodies, deflated in C++ (the vectorized finalize+serialize of
  ``sinks/datadog/datadog.go:245-330``).
- ``decode_metric_list`` / ``MListInternTable`` — forwardrpc.MetricList
  bytes → struct-of-arrays batch + series interning (the import-side
  equivalent of ``parse_lines`` + ``InternTable``; reference path
  ``importsrv/server.go:101-132``).
- ``encode_digest_metrics`` — columnar digest planes → serialized
  MetricList chunks for the gRPC forward path (``flusher.go:424-473``).

``available()`` gates everything; callers fall back to the pure-Python
paths when no compiler is present.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import List, Optional, Tuple

import numpy as np

log = logging.getLogger("veneur.native.egress")

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "veneur_egress.cpp")
_SO = os.path.join(_HERE, "libveneur_egress.so")

_lib = None
_lib_lock = threading.Lock()
_build_error: Optional[str] = None

MISS = 0xFFFFFFFF

# VtMetricBatch payload kinds
PAYLOAD_NONE = 0
PAYLOAD_COUNTER = 1
PAYLOAD_GAUGE = 2
PAYLOAD_HISTOGRAM = 3
PAYLOAD_SET = 4


class _VtBodies(ctypes.Structure):
    # ptr as void* — c_char_p would convert to bytes truncated at the
    # first NUL, and deflate bodies contain NULs
    _fields_ = [
        ("count", ctypes.c_uint32),
        ("ptr", ctypes.POINTER(ctypes.c_void_p)),
        ("len", ctypes.POINTER(ctypes.c_uint64)),
        ("impl", ctypes.c_void_p),
    ]


class _VtMetricBatch(ctypes.Structure):
    _fields_ = [
        ("count", ctypes.c_uint32),
        ("arena_len", ctypes.c_uint64),
        ("ncent", ctypes.c_uint64),
        ("topk_off", ctypes.c_uint64),
        ("topk_len", ctypes.c_uint64),
        ("type", ctypes.POINTER(ctypes.c_uint8)),
        ("payload", ctypes.POINTER(ctypes.c_uint8)),
        ("name_off", ctypes.POINTER(ctypes.c_uint32)),
        ("name_len", ctypes.POINTER(ctypes.c_uint32)),
        ("tags_off", ctypes.POINTER(ctypes.c_uint32)),
        ("tags_len", ctypes.POINTER(ctypes.c_uint32)),
        ("ivalue", ctypes.POINTER(ctypes.c_int64)),
        ("dvalue", ctypes.POINTER(ctypes.c_double)),
        ("compression", ctypes.POINTER(ctypes.c_double)),
        ("dmin", ctypes.POINTER(ctypes.c_double)),
        ("dmax", ctypes.POINTER(ctypes.c_double)),
        ("cent_off", ctypes.POINTER(ctypes.c_uint64)),
        ("cent_len", ctypes.POINTER(ctypes.c_uint32)),
        ("hll_off", ctypes.POINTER(ctypes.c_uint64)),
        ("hll_len", ctypes.POINTER(ctypes.c_uint64)),
        ("arena", ctypes.POINTER(ctypes.c_char)),
        ("means", ctypes.POINTER(ctypes.c_double)),
        ("weights", ctypes.POINTER(ctypes.c_double)),
        ("impl", ctypes.c_void_p),
    ]


def _build() -> Optional[str]:
    try:
        subprocess.run(
            ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
             "-o", _SO, _SRC, "-lz"],
            check=True, capture_output=True, timeout=120)
        return None
    except FileNotFoundError:
        return "g++ not found"
    except subprocess.TimeoutExpired:
        return "native egress build timed out"
    except subprocess.CalledProcessError as e:
        return f"native egress build failed: {e.stderr.decode(errors='replace')}"


def _load():
    global _lib, _build_error
    with _lib_lock:
        if _lib is not None or _build_error is not None:
            return _lib
        if not os.path.exists(_SO) or (
                os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
            err = _build()
            if err is not None:
                _build_error = err
                log.warning("native egress unavailable: %s", err)
                return None
        try:
            lib = _bind(ctypes.CDLL(_SO))
        except OSError as e:
            log.warning("native egress load failed (%s); rebuilding", e)
            err = _build()
            lib = None
            if err is None:
                try:
                    lib = _bind(ctypes.CDLL(_SO))
                except OSError as e2:
                    err = f"rebuilt library still unloadable: {e2}"
            if err is not None:
                _build_error = err
                log.warning("native egress unavailable: %s", err)
                return None
        _lib = lib
        return _lib


def _bind(lib):
    u32p = ctypes.POINTER(ctypes.c_uint32)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    f32p = ctypes.POINTER(ctypes.c_float)
    f64p = ctypes.POINTER(ctypes.c_double)

    lib.vt_dd_series_json.restype = ctypes.POINTER(_VtBodies)
    lib.vt_dd_series_json.argtypes = [
        ctypes.c_char_p, u32p, u32p,            # names
        ctypes.c_char_p, u32p, u32p,            # tags
        ctypes.c_uint32,                        # nrows
        ctypes.c_char_p, u32p, u32p, ctypes.c_uint32,  # suffixes
        u32p, u8p, f64p, u8p, ctypes.c_uint64,  # emissions
        ctypes.c_int64, ctypes.c_int32,         # timestamp, interval
        ctypes.c_char_p, ctypes.c_char_p,       # host, common tags json
        ctypes.c_uint32, ctypes.c_int,          # max_per_body, level
    ]
    lib.vt_bodies_free.argtypes = [ctypes.POINTER(_VtBodies)]

    lib.vt_sfx_datapoints_json.restype = ctypes.POINTER(_VtBodies)
    lib.vt_sfx_datapoints_json.argtypes = [
        ctypes.c_char_p, u32p, u32p,            # names
        ctypes.c_char_p, u32p, u32p,            # tags
        ctypes.c_uint32,                        # nrows
        ctypes.c_char_p, u32p, u32p, ctypes.c_uint32,  # suffixes
        u32p, u8p, f64p, u8p, ctypes.c_uint64,  # emissions
        ctypes.c_int64,                         # timestamp ms
        ctypes.c_char_p, ctypes.c_char_p,       # hostname tag, hostname
        ctypes.c_char_p,                        # common dims json
        ctypes.c_char_p, u32p, u32p, ctypes.c_uint32,  # common keys
        ctypes.c_char_p, u32p, u32p, ctypes.c_uint32,  # excluded keys
    ]

    lib.vt_tsv_rows.restype = ctypes.POINTER(_VtBodies)
    lib.vt_tsv_rows.argtypes = [
        ctypes.c_char_p, u32p, u32p,            # names
        ctypes.c_char_p, u32p, u32p,            # tags
        ctypes.c_uint32,                        # nrows
        ctypes.c_char_p, u32p, u32p, ctypes.c_uint32,  # suffixes
        u32p, u8p, f64p, u8p, ctypes.c_uint64,  # emissions
        ctypes.c_char_p, ctypes.c_char_p,       # hostname, interval str
        ctypes.c_char_p, ctypes.c_char_p,       # timestamp, partition
    ]

    lib.vt_mlist_decode.restype = ctypes.POINTER(_VtMetricBatch)
    lib.vt_mlist_decode.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
    lib.vt_mbatch_free.argtypes = [ctypes.POINTER(_VtMetricBatch)]

    lib.vt_mintern_new.restype = ctypes.c_void_p
    lib.vt_mintern_free.argtypes = [ctypes.c_void_p]
    lib.vt_mintern_reset.argtypes = [ctypes.c_void_p]
    lib.vt_mintern_put.argtypes = [
        ctypes.c_void_p, ctypes.c_uint8, ctypes.c_uint8, ctypes.c_char_p,
        ctypes.c_uint32, ctypes.c_char_p, ctypes.c_uint32, ctypes.c_uint32]
    lib.vt_mintern_assign.restype = ctypes.c_uint32
    lib.vt_mintern_assign.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(_VtMetricBatch), u32p, u32p]

    lib.vt_mlist_encode_digests.restype = ctypes.POINTER(_VtBodies)
    lib.vt_mlist_encode_digests.argtypes = [
        ctypes.c_char_p, u32p, u32p,            # names
        ctypes.c_char_p, u32p, u32p,            # tags
        f32p, f32p, ctypes.c_uint32,            # means, weights, K
        f32p, f32p,                             # dmins, dmaxs
        ctypes.c_uint32, ctypes.c_uint8,        # nrows, pb type
        ctypes.c_double, ctypes.c_uint64, ctypes.c_int,
    ]

    u16p = ctypes.POINTER(ctypes.c_uint16)
    lib.vt_mlist_encode_digests_packed.restype = ctypes.POINTER(_VtBodies)
    lib.vt_mlist_encode_digests_packed.argtypes = [
        ctypes.c_char_p, u32p, u32p,            # names
        ctypes.c_char_p, u32p, u32p,            # tags
        u16p, u16p, u16p,                       # counts, means_q, weights_bf
        f32p, f32p,                             # dmins, dmaxs
        ctypes.c_uint32, ctypes.c_uint8,        # nrows, pb type
        ctypes.c_double, ctypes.c_uint64, ctypes.c_int,
    ]
    return lib


def available() -> bool:
    return _load() is not None


def _take_bodies(lib, bp) -> List[bytes]:
    try:
        b = bp.contents
        return [ctypes.string_at(b.ptr[i], b.len[i])
                for i in range(b.count)]
    finally:
        lib.vt_bodies_free(bp)


def _u32a(a: np.ndarray) -> np.ndarray:
    """Contiguous u32 copy the CALLER must keep referenced across the C
    call (data_as on a temporary would dangle)."""
    return np.ascontiguousarray(a, np.uint32)


def _p(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


# ---------------------------------------------------------------------------
# Datadog series JSON
# ---------------------------------------------------------------------------


def dd_series_bodies(names: Tuple[bytes, np.ndarray, np.ndarray],
                     tags: Tuple[bytes, np.ndarray, np.ndarray],
                     suffixes: List[bytes],
                     em_rows: np.ndarray, em_suffix: np.ndarray,
                     em_values: np.ndarray, em_type: np.ndarray,
                     timestamp: int, interval: int, default_host: str,
                     common_tags_json: bytes = b"",
                     max_per_body: int = 0,
                     compress_level: int = 1) -> List[bytes]:
    """Serialize one columnar emission block into chunked (optionally
    deflated) ``{"series": [...]}`` bodies.

    names/tags: (arena bytes, offsets u32[S], lengths u32[S]).
    emissions: parallel arrays — row index u32, suffix index u8 (into
    ``suffixes``), finalized value f64 (counters already divided by the
    interval), type code u8 (0 gauge / 1 rate).
    """
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native egress unavailable: {_build_error}")
    if len(suffixes) > 255:
        raise ValueError("more than 255 emission suffixes")
    suffix_blob = b"".join(suffixes)
    s_off = np.zeros(max(len(suffixes), 1), np.uint32)
    s_len = np.zeros(max(len(suffixes), 1), np.uint32)
    pos = 0
    for i, s in enumerate(suffixes):
        s_off[i] = pos
        s_len[i] = len(s)
        pos += len(s)
    em_rows = _u32a(em_rows)
    em_suffix = np.ascontiguousarray(em_suffix, np.uint8)
    em_values = np.ascontiguousarray(em_values, np.float64)
    em_type = np.ascontiguousarray(em_type, np.uint8)
    n = len(em_rows)
    assert len(em_suffix) == n and len(em_values) == n and len(em_type) == n
    name_arena, name_off, name_len = names
    tags_arena, tags_off, tags_len = tags
    name_off, name_len = _u32a(name_off), _u32a(name_len)
    tags_off, tags_len = _u32a(tags_off), _u32a(tags_len)
    u32, u8, f64 = ctypes.c_uint32, ctypes.c_uint8, ctypes.c_double
    bp = lib.vt_dd_series_json(
        name_arena, _p(name_off, u32), _p(name_len, u32),
        tags_arena, _p(tags_off, u32), _p(tags_len, u32),
        len(name_off),
        suffix_blob, _p(s_off, u32), _p(s_len, u32), len(suffixes),
        _p(em_rows, u32), _p(em_suffix, u8), _p(em_values, f64),
        _p(em_type, u8),
        n, timestamp, interval, default_host.encode("utf-8"),
        common_tags_json, max_per_body, compress_level)
    return _take_bodies(lib, bp)


def _key_list(keys: List[bytes]):
    """(blob, off-array, len-array, count) for a small key set."""
    blob = b"".join(keys)
    n = max(len(keys), 1)
    offs = np.zeros(n, np.uint32)
    lens = np.zeros(n, np.uint32)
    pos = 0
    for i, k in enumerate(keys):
        offs[i] = pos
        lens[i] = len(k)
        pos += len(k)
    return blob, offs, lens, len(keys)


def sfx_datapoint_bodies(names: Tuple[bytes, np.ndarray, np.ndarray],
                         tags: Tuple[bytes, np.ndarray, np.ndarray],
                         suffixes: List[bytes],
                         em_rows: np.ndarray, em_suffix: np.ndarray,
                         em_values: np.ndarray, em_type: np.ndarray,
                         timestamp_ms: int, hostname_tag: str,
                         hostname: str,
                         common_dims_json: bytes = b"",
                         common_keys: Optional[List[bytes]] = None,
                         excluded_keys: Optional[List[bytes]] = None
                         ) -> List[bytes]:
    """Serialize one columnar emission block into a SignalFx
    ``/v2/datapoint`` body (``{"gauge": [...], "counter": [...]}``,
    uncompressed). Dimension semantics mirror SignalFxSink._dimensions;
    common_dims_json is the pre-escaped ``"k":"v",...`` fragment whose
    keys are listed in common_keys (tag dims with those keys are
    dropped — common dimensions override)."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native egress unavailable: {_build_error}")
    if len(suffixes) > 255:
        raise ValueError("more than 255 emission suffixes")
    suffix_blob, s_off, s_len, _ = _key_list(suffixes)
    em_rows = _u32a(em_rows)
    em_suffix = np.ascontiguousarray(em_suffix, np.uint8)
    em_values = np.ascontiguousarray(em_values, np.float64)
    em_type = np.ascontiguousarray(em_type, np.uint8)
    n = len(em_rows)
    assert len(em_suffix) == n and len(em_values) == n and len(em_type) == n
    name_arena, name_off, name_len = names
    tags_arena, tags_off, tags_len = tags
    name_off, name_len = _u32a(name_off), _u32a(name_len)
    tags_off, tags_len = _u32a(tags_off), _u32a(tags_len)
    ck_blob, ck_off, ck_len, ck_n = _key_list(common_keys or [])
    ex_blob, ex_off, ex_len, ex_n = _key_list(excluded_keys or [])
    u32, u8, f64 = ctypes.c_uint32, ctypes.c_uint8, ctypes.c_double
    bp = lib.vt_sfx_datapoints_json(
        name_arena, _p(name_off, u32), _p(name_len, u32),
        tags_arena, _p(tags_off, u32), _p(tags_len, u32),
        len(name_off),
        suffix_blob, _p(s_off, u32), _p(s_len, u32), len(suffixes),
        _p(em_rows, u32), _p(em_suffix, u8), _p(em_values, f64),
        _p(em_type, u8), n, timestamp_ms,
        hostname_tag.encode("utf-8"), hostname.encode("utf-8"),
        common_dims_json,
        ck_blob, _p(ck_off, u32), _p(ck_len, u32), ck_n,
        ex_blob, _p(ex_off, u32), _p(ex_len, u32), ex_n)
    return _take_bodies(lib, bp)


def tsv_rows(names: Tuple[bytes, np.ndarray, np.ndarray],
             tags: Tuple[bytes, np.ndarray, np.ndarray],
             suffixes: List[bytes],
             em_rows: np.ndarray, em_suffix: np.ndarray,
             em_values: np.ndarray, em_type: np.ndarray,
             hostname: str, interval: int, timestamp_str: str,
             partition_str: str) -> bytes:
    """Serialize one columnar emission block into the archival TSV rows
    the s3/localfile plugins write (plugins/csv_encode.py column order;
    reference csv.go:17-92). Counter values must arrive already divided
    by the interval (em_type picks the rate/gauge column only)."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native egress unavailable: {_build_error}")
    if len(suffixes) > 255:
        raise ValueError("more than 255 emission suffixes")
    suffix_blob, s_off, s_len, _ = _key_list(suffixes)
    em_rows = _u32a(em_rows)
    em_suffix = np.ascontiguousarray(em_suffix, np.uint8)
    em_values = np.ascontiguousarray(em_values, np.float64)
    em_type = np.ascontiguousarray(em_type, np.uint8)
    n = len(em_rows)
    assert len(em_suffix) == n and len(em_values) == n and len(em_type) == n
    name_arena, name_off, name_len = names
    tags_arena, tags_off, tags_len = tags
    name_off, name_len = _u32a(name_off), _u32a(name_len)
    tags_off, tags_len = _u32a(tags_off), _u32a(tags_len)
    u32, u8, f64 = ctypes.c_uint32, ctypes.c_uint8, ctypes.c_double
    bp = lib.vt_tsv_rows(
        name_arena, _p(name_off, u32), _p(name_len, u32),
        tags_arena, _p(tags_off, u32), _p(tags_len, u32),
        len(name_off),
        suffix_blob, _p(s_off, u32), _p(s_len, u32), len(suffixes),
        _p(em_rows, u32), _p(em_suffix, u8), _p(em_values, f64),
        _p(em_type, u8), n,
        hostname.encode("utf-8"), str(int(interval)).encode(),
        timestamp_str.encode(), partition_str.encode())
    (body,) = _take_bodies(lib, bp)
    return body


# ---------------------------------------------------------------------------
# MetricList decode + interning
# ---------------------------------------------------------------------------


class DecodedMetricList:
    """numpy views over a decoded MetricList. Arrays are COPIES by
    default; ``copy=False`` returns zero-copy VIEWS into the C++ batch —
    the import hot path uses it (saves a ~10 MB memcpy per 20k-digest
    message) but the views die with :meth:`close`. hll spans index into
    the ORIGINAL request bytes (keep them alive)."""

    __slots__ = ("count", "type", "payload", "name_off", "name_len",
                 "tags_off", "tags_len", "ivalue", "dvalue", "compression",
                 "dmin", "dmax", "cent_off", "cent_len", "hll_off",
                 "hll_len", "arena", "means", "weights", "topk_off",
                 "topk_len", "_ptr", "_lib")

    def __init__(self, lib, ptr, copy: bool = True):
        self._lib = lib
        self._ptr = ptr
        b = ptr.contents
        n = b.count
        self.topk_off = b.topk_off
        self.topk_len = b.topk_len

        def arr(p, dtype, count=n):
            if count == 0:
                return np.empty(0, dtype)
            return np.ctypeslib.as_array(p, shape=(count,)).astype(
                dtype, copy=copy)

        self.count = n
        self.type = arr(b.type, np.uint8)
        self.payload = arr(b.payload, np.uint8)
        self.name_off = arr(b.name_off, np.uint32)
        self.name_len = arr(b.name_len, np.uint32)
        self.tags_off = arr(b.tags_off, np.uint32)
        self.tags_len = arr(b.tags_len, np.uint32)
        self.ivalue = arr(b.ivalue, np.int64)
        self.dvalue = arr(b.dvalue, np.float64)
        self.compression = arr(b.compression, np.float64)
        self.dmin = arr(b.dmin, np.float64)
        self.dmax = arr(b.dmax, np.float64)
        self.cent_off = arr(b.cent_off, np.uint64)
        self.cent_len = arr(b.cent_len, np.uint32)
        self.hll_off = arr(b.hll_off, np.uint64)
        self.hll_len = arr(b.hll_len, np.uint64)
        self.arena = ctypes.string_at(b.arena, b.arena_len) \
            if b.arena_len else b""
        self.means = arr(b.means, np.float64, b.ncent)
        self.weights = arr(b.weights, np.float64, b.ncent)

    def name(self, i: int) -> str:
        o, l = self.name_off[i], self.name_len[i]
        return self.arena[o:o + l].decode("utf-8", "replace")

    def joined_tags(self, i: int) -> str:
        o, l = self.tags_off[i], self.tags_len[i]
        return self.arena[o:o + l].decode("utf-8", "replace")

    def raw_view(self) -> "_VtMetricBatch":
        """A struct borrowing this batch's numpy arrays for C calls
        (vt_mintern_assign). Keep self alive across the call."""
        b = _VtMetricBatch()
        b.count = self.count
        b.arena_len = len(self.arena)
        u8, u32 = ctypes.c_uint8, ctypes.c_uint32
        b.type = self.type.ctypes.data_as(ctypes.POINTER(u8))
        b.payload = self.payload.ctypes.data_as(ctypes.POINTER(u8))
        b.name_off = self.name_off.ctypes.data_as(ctypes.POINTER(u32))
        b.name_len = self.name_len.ctypes.data_as(ctypes.POINTER(u32))
        b.tags_off = self.tags_off.ctypes.data_as(ctypes.POINTER(u32))
        b.tags_len = self.tags_len.ctypes.data_as(ctypes.POINTER(u32))
        b.arena = ctypes.cast(ctypes.c_char_p(self.arena),
                              ctypes.POINTER(ctypes.c_char))
        return b

    def close(self):
        if self._ptr:
            self._lib.vt_mbatch_free(self._ptr)
            self._ptr = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def decode_metric_list(data: bytes, copy: bool = True) -> DecodedMetricList:
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native egress unavailable: {_build_error}")
    ptr = lib.vt_mlist_decode(data, len(data))
    return DecodedMetricList(lib, ptr, copy=copy)


class MListInternTable:
    """(metricpb type, payload kind, name, joined tags) -> store row,
    memoized in C++. Misses come back for Python to resolve and teach
    with put(). The payload kind is part of the key because row indices
    are only meaningful within one group and the applying group is chosen
    by the value-oneof: a repeated (type, name, tags) with a different
    oneof must MISS, not reuse a foreign group's row (ADVICE round-3)."""

    def __init__(self):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native egress unavailable: {_build_error}")
        self._lib = lib
        self._handle = lib.vt_mintern_new()

    def assign(self, batch: DecodedMetricList):
        n = batch.count
        rows = np.empty(n, np.uint32)
        miss = np.empty(n, np.uint32)
        view = batch.raw_view()
        nmiss = self._lib.vt_mintern_assign(
            self._handle, ctypes.byref(view),
            rows.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            miss.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)))
        return rows, miss[:nmiss]

    def put(self, pb_type: int, payload: int, name: bytes, tags: bytes,
            row: int):
        self._lib.vt_mintern_put(self._handle, pb_type, payload, name,
                                 len(name), tags, len(tags), row)

    def reset(self):
        self._lib.vt_mintern_reset(self._handle)

    def close(self):
        if self._handle:
            self._lib.vt_mintern_free(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# MetricList encode
# ---------------------------------------------------------------------------


def encode_digest_metrics(names: Tuple[bytes, np.ndarray, np.ndarray],
                          tags: Tuple[bytes, np.ndarray, np.ndarray],
                          means: np.ndarray, weights: np.ndarray,
                          dmins: np.ndarray, dmaxs: np.ndarray,
                          pb_type: int, compression: float = 100.0,
                          max_body_bytes: int = 0,
                          reference_compat: bool = False) -> List[bytes]:
    """Columnar digest planes → serialized MetricList chunks.

    means/weights: [S, K] float32 (weight <= 0 marks padding); each
    returned chunk is a complete MetricList serialization.
    """
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native egress unavailable: {_build_error}")
    means = np.ascontiguousarray(means, np.float32)
    weights = np.ascontiguousarray(weights, np.float32)
    dmins = np.ascontiguousarray(dmins, np.float32)
    dmaxs = np.ascontiguousarray(dmaxs, np.float32)
    nrows, k = means.shape
    assert weights.shape == (nrows, k)
    name_arena, name_off, name_len = names
    tags_arena, tags_off, tags_len = tags
    name_off, name_len = _u32a(name_off), _u32a(name_len)
    tags_off, tags_len = _u32a(tags_off), _u32a(tags_len)
    u32, f32 = ctypes.c_uint32, ctypes.c_float
    bp = lib.vt_mlist_encode_digests(
        name_arena, _p(name_off, u32), _p(name_len, u32),
        tags_arena, _p(tags_off, u32), _p(tags_len, u32),
        _p(means, f32), _p(weights, f32), k,
        _p(dmins, f32), _p(dmaxs, f32),
        nrows, pb_type, compression, max_body_bytes,
        1 if reference_compat else 0)
    return _take_bodies(lib, bp)


def encode_digest_metrics_packed(names: Tuple[bytes, np.ndarray, np.ndarray],
                                 tags: Tuple[bytes, np.ndarray, np.ndarray],
                                 planes, pb_type: int,
                                 compression: float = 100.0,
                                 max_body_bytes: int = 0,
                                 reference_compat: bool = False
                                 ) -> List[bytes]:
    """Device-compacted digest planes (core.store.PackedDigestPlanes) →
    serialized MetricList chunks. Non-compat chunks carry the quantized
    u16 arrays verbatim (tdigest fields 16/17, 4 bytes/centroid);
    reference_compat dequantizes in C++ and emits the reference's
    repeated-Centroid layout."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native egress unavailable: {_build_error}")
    counts = np.ascontiguousarray(planes.counts, np.uint16)
    means_q = np.ascontiguousarray(planes.means_q, np.uint16)
    weights_bf = np.ascontiguousarray(planes.weights_bf, np.uint16)
    dmins = np.ascontiguousarray(planes.dmin, np.float32)
    dmaxs = np.ascontiguousarray(planes.dmax, np.float32)
    nrows = len(counts)
    total = int(counts.astype(np.int64).sum())
    if not (total == len(means_q) == len(weights_bf)):
        # wire-boundary invariant: the C++ walker advances by counts and
        # would read out of bounds (must survive python -O)
        raise ValueError(
            f"packed planes inconsistent: sum(counts)={total}, "
            f"means={len(means_q)}, weights={len(weights_bf)}")
    name_arena, name_off, name_len = names
    tags_arena, tags_off, tags_len = tags
    name_off, name_len = _u32a(name_off), _u32a(name_len)
    tags_off, tags_len = _u32a(tags_off), _u32a(tags_len)
    u16, u32, f32 = ctypes.c_uint16, ctypes.c_uint32, ctypes.c_float
    bp = lib.vt_mlist_encode_digests_packed(
        name_arena, _p(name_off, u32), _p(name_len, u32),
        tags_arena, _p(tags_off, u32), _p(tags_len, u32),
        _p(counts, u16), _p(means_q, u16), _p(weights_bf, u16),
        _p(dmins, f32), _p(dmaxs, f32),
        nrows, pb_type, compression, max_body_bytes,
        1 if reference_compat else 0)
    return _take_bodies(lib, bp)

// AddressSanitizer robustness driver for the native egress codecs.
//
// vt_mlist_decode parses UNTRUSTED network bytes (the gRPC import
// server's request body); vt_mintern_assign walks the decoded batch.
// This driver hammers them with deterministic mutations of a valid
// MetricList plus structured garbage, under ASan — the memory-safety
// counterpart of tsan_driver.cpp for the ingest path. Exit 0 = no
// leaks/overflows surfaced; any ASan report aborts the process.
//
// Built and run by tests/test_native_fuzz.py:
//   g++ -O1 -g -std=c++17 -fsanitize=address,undefined \
//       fuzz_driver.cpp veneur_egress.cpp -lz -o fuzz_driver
//
// The valid seed buffer is passed in as a file (the test writes one
// with python-protobuf); mutations are xorshift-deterministic so a
// failure reproduces.

#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include <vector>

extern "C" {
struct VtMetricBatch;
VtMetricBatch* vt_mlist_decode(const char* buf, size_t len);
uint32_t vt_mbatch_count(const VtMetricBatch* m);
void vt_mbatch_free(VtMetricBatch* m);
void* vt_mintern_new();
void vt_mintern_free(void* t);
uint32_t vt_mintern_assign(void* t, const VtMetricBatch* b,
                           uint32_t* rows_out, uint32_t* miss_out);
// ingest codecs (veneur_ingest.cpp) — same untrusted-byte surface
struct VtBatch;
VtBatch* vt_batch_new(uint32_t capacity, uint32_t arena_cap);
void vt_batch_free(VtBatch* b);
void vt_batch_reset(VtBatch* b);
uint32_t vt_parse_lines(const char* buf, size_t len, VtBatch* b);
uint32_t vt_frame_scan(const char* buf, size_t len, uint32_t* offs,
                       uint32_t* lens, uint32_t max_frames,
                       size_t* consumed, int* poisoned);
}

static uint64_t rng_state = 0x9E3779B97F4A7C15ULL;
static uint64_t xorshift() {
  rng_state ^= rng_state << 13;
  rng_state ^= rng_state >> 7;
  rng_state ^= rng_state << 17;
  return rng_state;
}

static VtBatch* g_ingest_batch = nullptr;

static void exercise(const char* buf, size_t len) {
  VtMetricBatch* b = vt_mlist_decode(buf, len);
  if (!b) return;
  uint32_t count = vt_mbatch_count(b);
  if (count > 0 && count < (1u << 24)) {
    std::vector<uint32_t> rows(count), miss(count);
    void* t = vt_mintern_new();
    vt_mintern_assign(t, b, rows.data(), miss.data());
    vt_mintern_free(t);
  }
  vt_mbatch_free(b);

  // the same bytes through the DogStatsD line parser and the framed-SSF
  // scanner (both consume raw socket data)
  vt_batch_reset(g_ingest_batch);
  vt_parse_lines(buf, len, g_ingest_batch);
  uint32_t offs[64], lens[64];
  size_t consumed = 0;
  int poisoned = 0;
  vt_frame_scan(buf, len, offs, lens, 64, &consumed, &poisoned);
}

int main(int argc, char** argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: fuzz_driver <seed-file> [iterations]\n");
    return 2;
  }
  FILE* f = fopen(argv[1], "rb");
  if (!f) {
    perror("seed");
    return 2;
  }
  fseek(f, 0, SEEK_END);
  long n = ftell(f);
  fseek(f, 0, SEEK_SET);
  if (n <= 0) {
    fprintf(stderr, "seed file is empty or unreadable\n");
    return 2;
  }
  std::vector<char> seed(n);
  if (fread(seed.data(), 1, n, f) != static_cast<size_t>(n)) return 2;
  fclose(f);
  int iters = argc > 2 ? atoi(argv[2]) : 4000;
  g_ingest_batch = vt_batch_new(4096, 1 << 20);

  // 1. the pristine seed
  exercise(seed.data(), seed.size());

  // 2. every truncation length (catches length-field overreads)
  for (long cut = 0; cut <= n; cut += (n > 512 ? 7 : 1))
    exercise(seed.data(), cut);

  // 3. deterministic point mutations: flip random bytes, re-parse
  std::vector<char> mut = seed;
  for (int i = 0; i < iters; i++) {
    size_t at = xorshift() % mut.size();
    char old = mut[at];
    mut[at] = static_cast<char>(xorshift());
    exercise(mut.data(), mut.size());
    if (xorshift() % 4) mut[at] = old;  // mostly revert, sometimes keep
  }

  // 4. structured garbage: varint storms, giant length prefixes
  for (int i = 0; i < 256; i++) {
    std::vector<char> g(64 + (xorshift() % 512));
    for (char& c : g) c = static_cast<char>(xorshift());
    g[0] = 0x0A;  // field 1, wire type 2 — plausible MetricList start
    g[1] = static_cast<char>(0xFF);  // huge/invalid length varints
    exercise(g.data(), g.size());
  }
  printf("fuzz_driver: OK\n");
  return 0;
}

// ThreadSanitizer driver for the native ingest path.
//
// The reference leans on Go's race detector in CI for its reader
// goroutines (SURVEY §5); this is the C++ equivalent for our
// SO_REUSEPORT reader pool: N reader threads recvmmsg + parse into
// mutex-guarded batches while the main thread swaps batches out and
// polls the atomic counters, with sender threads blasting DogStatsD
// datagrams at the shared port the whole time.
//
// Built single-TU (includes veneur_ingest.cpp) so every function is
// instrumented. Run by tests/test_native_tsan.py with
// TSAN_OPTIONS=halt_on_error=1; any data race fails the test via the
// sanitizer's exit code.

#include "veneur_ingest.cpp"

#include <arpa/inet.h>
#include <cstdio>
#include <string>
#include <thread>
#include <vector>

namespace {

void sender_loop(int port, int ndatagrams, int seed) {
  int fd = socket(AF_INET, SOCK_DGRAM, 0);
  if (fd < 0) return;
  sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  addr.sin_addr.s_addr = inet_addr("127.0.0.1");
  for (int i = 0; i < ndatagrams; i++) {
    char buf[512];
    int n = snprintf(buf, sizeof(buf),
                     "svc.req.time:%d|ms|@0.5|#env:prod,shard:%d\n"
                     "svc.req.count:1|c|#env:prod\n"
                     "svc.users:%d|s\n"
                     "svc.gauge:%d.5|g|#host:h%d",
                     (seed + i) % 1000, i % 8, seed + i, i, seed % 4);
    sendto(fd, buf, n, 0, reinterpret_cast<sockaddr*>(&addr), sizeof(addr));
    if (i % 64 == 0) usleep(100);  // let readers keep up; drops are fine too
  }
  close(fd);
}

}  // namespace

int main() {
  void* pool = vt_reader_start("127.0.0.1", 0, /*nreaders=*/4,
                               /*rcvbuf=*/1 << 20, /*batch_records=*/4096,
                               /*batch_arena=*/1 << 20, /*dgram_max=*/8192);
  if (!pool) {
    fprintf(stderr, "vt_reader_start failed\n");
    return 2;
  }
  int port = vt_reader_port(pool);
  int nreaders = vt_reader_count(pool);

  std::vector<std::thread> senders;
  for (int s = 0; s < 3; s++) {
    senders.emplace_back(sender_loop, port, 4000, s * 100000);
  }

  // concurrent swap + counter polling while senders and readers run
  uint64_t records = 0;
  for (int iter = 0; iter < 150; iter++) {
    for (int i = 0; i < nreaders; i++) {
      VtBatch* b = vt_reader_swap(pool, i);
      records += b->count;
      (void)vt_reader_packets(pool, i);
      (void)vt_reader_drops(pool, i);
    }
    usleep(2000);
  }
  for (auto& t : senders) t.join();
  usleep(50000);  // drain the tail
  for (int i = 0; i < nreaders; i++) {
    records += vt_reader_swap(pool, i)->count;
  }
  vt_reader_stop(pool);

  fprintf(stderr, "tsan driver parsed %llu records\n",
          static_cast<unsigned long long>(records));
  if (records == 0) {
    fprintf(stderr, "no records parsed — sender or reader broken\n");
    return 3;
  }
  return 0;
}

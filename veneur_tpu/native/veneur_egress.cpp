// Native flush egress + MetricList wire codecs: the import/export twin of
// veneur_ingest.cpp's batch parser.
//
// The round-2 finding was that the kernels were fast but the server was
// not: the flush assembled ~15 Python InterMetric objects per series and
// the gRPC import decoded protobuf per metric in Python — minutes of
// GIL-bound work at multi-million-series scale. This file moves the three
// byte-bound egress paths native, operating on the store's columnar flush
// output (flat numpy arrays + interner arenas) without per-row Python:
//
//  1. vt_dd_series_json — Datadog /api/v1/series bodies straight from
//     columns, streaming zlib-deflated, chunked like the reference's
//     flushMaxPerBody split (sinks/datadog/datadog.go:62-68 field layout
//     incl. omitempty, :245-330 finalize rules: magic host:/device: tags,
//     counters→rates).
//  2. vt_mlist_decode / vt_mintern_* — forwardrpc.MetricList protobuf →
//     struct-of-arrays batch + (type,name,tags)→row interning, feeding the
//     store's bulk import staging (the import-side twin of
//     veneur_ingest.cpp's parse + InternTable.assign; reference merge path
//     importsrv/server.go:101-132, worker.go:354-398).
//  3. vt_mlist_encode_digests — columnar digest planes [S,K] → serialized
//     MetricList bytes, chunked by body size, with the packed parallel
//     centroid arrays (tdigestpb fields 14/15) and optionally the
//     reference's repeated Centroid schema (samplers/metricpb/metric.proto,
//     flusher.go:424-473).
//
// Wire format notes: hand-rolled proto3 — varints, length-delimited
// submessages, fields in any order, unknown fields skipped, repeated
// doubles accepted both packed (wire type 2) and unpacked (wire type 1).

#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <zlib.h>

#include <cmath>
#include <cstring>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// growable byte buffer
// ---------------------------------------------------------------------------

struct Buf {
  char* p = nullptr;
  size_t len = 0, cap = 0;

  void reserve(size_t need) {
    if (len + need <= cap) return;
    size_t ncap = cap ? cap * 2 : 4096;
    while (ncap < len + need) ncap *= 2;
    p = static_cast<char*>(realloc(p, ncap));
    cap = ncap;
  }
  void put(const void* d, size_t n) {
    if (n == 0) return;  // memcpy on a never-allocated buffer is UB
    reserve(n);
    memcpy(p + len, d, n);
    len += n;
  }
  void put_str(const char* s) { put(s, strlen(s)); }
  void put_ch(char c) {
    reserve(1);
    p[len++] = c;
  }
  char* take() {  // ownership out; buffer resets
    char* out = p;
    p = nullptr;
    len = cap = 0;
    return out;
  }
};

// ---------------------------------------------------------------------------
// number formatting (JSON)
// ---------------------------------------------------------------------------

// itoa into caller buffer (backward fill); returns length
int fmt_i64(char* dst, int64_t v) {
  char tmp[24];
  char* p = tmp + 24;
  bool neg = v < 0;
  uint64_t u = neg ? 0 - static_cast<uint64_t>(v) : static_cast<uint64_t>(v);
  do {
    *--p = '0' + static_cast<char>(u % 10);
    u /= 10;
  } while (u);
  if (neg) *--p = '-';
  int n = static_cast<int>(tmp + 24 - p);
  memcpy(dst, p, n);
  return n;
}

void put_i64(Buf& b, int64_t v) {
  b.reserve(24);
  b.len += fmt_i64(b.p + b.len, v);
}

// Fast metric-value formatter. Integers print exact; fractional values in
// a sane magnitude range print with 9 significant digits, VERIFIED to
// round-trip (digest-derived values come from float32 device planes where
// 9 digits always suffice, but counter rates and gauges are host-side
// float64 — those fall back to a 17-digit render when 9 digits lose
// precision). Extreme magnitudes fall back to snprintf scientific.
// snprintf+strtod per value was the serializer's bottleneck (~0.6us each).
void put_double(Buf& b, double v) {
  if (!std::isfinite(v)) {  // JSON has no inf/nan; Datadog rejects them
    b.put_ch('0');
    return;
  }
  double r = nearbyint(v);
  if (r == v && fabs(v) < 9.007199254740992e15) {
    put_i64(b, static_cast<int64_t>(r));
    return;
  }
  double a = fabs(v);
  if (a >= 1e-4 && a < 1e15) {
    b.reserve(40);
    char* dst = b.p + b.len;
    int n = 0;
    if (v < 0) {
      dst[n++] = '-';
      a = -v;
    }
    // split integer/fraction; fraction scaled so int+frac carry >= 9
    // significant digits, trailing zeros trimmed
    uint64_t ip = static_cast<uint64_t>(a);
    int int_digits = 1;
    for (uint64_t t = ip; t >= 10; t /= 10) int_digits++;
    int frac_digits = ip ? (int_digits >= 9 ? 1 : 9 - int_digits) : 12;
    static const double kPow10[13] = {1,    1e1,  1e2,  1e3,  1e4,
                                      1e5,  1e6,  1e7,  1e8,  1e9,
                                      1e10, 1e11, 1e12};
    double scale = kPow10[frac_digits];
    uint64_t fp = static_cast<uint64_t>(nearbyint((a - ip) * scale));
    if (fp >= static_cast<uint64_t>(scale)) {  // rounded up to next int
      ip += 1;
      fp = 0;
    }
    // round-trip check: the emitted decimal is exactly ip + fp/scale;
    // only commit the fast render when that reconstructs the input
    if (static_cast<double>(ip) + static_cast<double>(fp) / scale == a) {
      n += fmt_i64(dst + n, static_cast<int64_t>(ip));
      if (fp) {
        dst[n++] = '.';
        // zero-padded fraction, then trim trailing zeros
        char tmp[16];
        int fn = fmt_i64(tmp, static_cast<int64_t>(fp));
        for (int z = fn; z < frac_digits; z++) dst[n++] = '0';
        while (fn > 0 && tmp[fn - 1] == '0') fn--;
        memcpy(dst + n, tmp, fn);
        n += fn;
      }
      b.len += n;
      return;
    }
    char tmp[32];
    int fn = snprintf(tmp, sizeof tmp, "%.17g", v);
    b.put(tmp, fn);
    return;
  }
  char tmp[32];
  int n = snprintf(tmp, sizeof tmp, "%.9g", v);
  if (strtod(tmp, nullptr) != v)  // rare branch: strtod check is fine
    n = snprintf(tmp, sizeof tmp, "%.17g", v);
  b.put(tmp, n);
}

// ---------------------------------------------------------------------------
// JSON string escaping
// ---------------------------------------------------------------------------

bool needs_escape(const char* s, uint32_t n) {
  for (uint32_t i = 0; i < n; i++) {
    unsigned char c = s[i];
    if (c == '"' || c == '\\' || c < 0x20) return true;
  }
  return false;
}

void put_json_escaped(Buf& b, const char* s, uint32_t n) {
  for (uint32_t i = 0; i < n; i++) {
    unsigned char c = s[i];
    if (c == '"' || c == '\\') {
      b.put_ch('\\');
      b.put_ch(c);
    } else if (c < 0x20) {
      char tmp[8];
      int m = snprintf(tmp, sizeof tmp, "\\u%04x", c);
      b.put(tmp, m);
    } else {
      b.put_ch(c);
    }
  }
}

void put_json_str_body(Buf& b, const char* s, uint32_t n) {
  if (needs_escape(s, n))
    put_json_escaped(b, s, n);
  else
    b.put(s, n);
}

// ---------------------------------------------------------------------------
// body list handed back to Python
// ---------------------------------------------------------------------------

struct VtBodiesImpl {
  std::vector<char*> ptrs;
  std::vector<uint64_t> lens;
};

}  // namespace

extern "C" struct VtBodies {
  uint32_t count;
  char** ptr;
  uint64_t* len;
  void* impl;
};

static VtBodies* bodies_finish(VtBodiesImpl* impl) {
  VtBodies* out = new VtBodies();
  out->count = static_cast<uint32_t>(impl->ptrs.size());
  out->ptr = impl->ptrs.data();
  out->len = impl->lens.data();
  out->impl = impl;
  return out;
}

extern "C" void vt_bodies_free(VtBodies* b) {
  if (!b) return;
  VtBodiesImpl* impl = static_cast<VtBodiesImpl*>(b->impl);
  for (char* p : impl->ptrs) free(p);
  delete impl;
  delete b;
}

namespace {

// streaming JSON→deflate writer: JSON accumulates in a scratch buffer and
// deflates in cache-sized slabs, so serialize+compress run in one pass
struct BodyWriter {
  int level;  // 0 = no compression (raw JSON body)
  Buf out;
  Buf scratch;
  z_stream zs;
  bool open = false;
  static constexpr size_t kSlab = 1 << 20;

  void begin(int lvl) {
    level = lvl;
    open = true;
    out = Buf();
    scratch = Buf();
    if (level > 0) {
      memset(&zs, 0, sizeof zs);
      deflateInit(&zs, level);
    }
  }
  void flush_scratch(bool final_block) {
    if (level <= 0) return;
    zs.next_in = reinterpret_cast<Bytef*>(scratch.p);
    zs.avail_in = static_cast<uInt>(scratch.len);
    do {
      out.reserve(deflateBound(&zs, zs.avail_in) + 64);
      zs.next_out = reinterpret_cast<Bytef*>(out.p + out.len);
      zs.avail_out = static_cast<uInt>(out.cap - out.len);
      int rc = deflate(&zs, final_block ? Z_FINISH : Z_NO_FLUSH);
      out.len = out.cap - zs.avail_out;
      if (rc == Z_STREAM_END) break;
    } while (zs.avail_in > 0 || (final_block && zs.avail_out == 0));
    scratch.len = 0;
  }
  Buf& sink() { return level > 0 ? scratch : out; }
  void maybe_drain() {
    if (level > 0 && scratch.len >= kSlab) flush_scratch(false);
  }
  // finish one body, append to the list
  void end(VtBodiesImpl* impl) {
    if (level > 0) {
      flush_scratch(true);
      deflateEnd(&zs);
      free(scratch.p);
    }
    impl->lens.push_back(out.len);
    impl->ptrs.push_back(out.take());
    open = false;
  }
};

}  // namespace

// ---------------------------------------------------------------------------
// 1. Datadog series JSON from columns
// ---------------------------------------------------------------------------
//
// Emissions are flat parallel arrays (row, suffix index, value, type code)
// produced by vectorized numpy masking on the flush results. Per-row
// fragments (escaped name, finalized tags JSON, host, device) are
// precomputed once and reused across that row's emissions.

extern "C" VtBodies* vt_dd_series_json(
    const char* name_arena, const uint32_t* name_off, const uint32_t* name_len,
    const char* tags_arena, const uint32_t* tags_off, const uint32_t* tags_len,
    uint32_t nrows, const char* suffix_blob, const uint32_t* suffix_off,
    const uint32_t* suffix_len, uint32_t nsuffix, const uint32_t* em_rows,
    const uint8_t* em_suffix, const double* em_values, const uint8_t* em_type,
    uint64_t nem, int64_t timestamp, int32_t interval,
    const char* default_host, const char* common_tags_json,
    uint32_t max_per_body, int compress_level) {
  (void)nsuffix;
  // per-row finalized fragments, all offsets into one scratch arena
  Buf frag;
  std::vector<uint64_t> tag_o(nrows), host_o(nrows), dev_o(nrows);
  std::vector<uint32_t> tag_l(nrows), host_l(nrows), dev_l(nrows);
  uint32_t dh_len = static_cast<uint32_t>(strlen(default_host));
  uint32_t common_len = static_cast<uint32_t>(strlen(common_tags_json));
  for (uint32_t r = 0; r < nrows; r++) {
    const char* tags = tags_arena + tags_off[r];
    uint32_t tlen = tags_len[r];
    // tags fragment: `"t1","t2"` with host:/device: extracted
    // (datadog.go:257-271); common tags (pre-escaped) come first
    uint64_t t0 = frag.len;
    frag.put(common_tags_json, common_len);
    bool any = common_len > 0;
    uint64_t host_at = UINT64_MAX, dev_at = UINT64_MAX;
    uint32_t host_n = 0, dev_n = 0;
    uint32_t i = 0;
    while (i < tlen) {
      uint32_t j = i;
      while (j < tlen && tags[j] != ',') j++;
      uint32_t n = j - i;
      if (n >= 5 && memcmp(tags + i, "host:", 5) == 0) {
        host_at = tags_off[r] + i + 5;
        host_n = n - 5;
      } else if (n >= 7 && memcmp(tags + i, "device:", 7) == 0) {
        dev_at = tags_off[r] + i + 7;
        dev_n = n - 7;
      } else if (n > 0) {
        if (any) frag.put_ch(',');
        frag.put_ch('"');
        put_json_str_body(frag, tags + i, n);
        frag.put_ch('"');
        any = true;
      }
      i = j + 1;
    }
    tag_o[r] = t0;
    tag_l[r] = static_cast<uint32_t>(frag.len - t0);
    // host: magic tag else default (escaped)
    uint64_t h0 = frag.len;
    if (host_at != UINT64_MAX)
      put_json_str_body(frag, tags_arena + host_at, host_n);
    else
      put_json_str_body(frag, default_host, dh_len);
    host_o[r] = h0;
    host_l[r] = static_cast<uint32_t>(frag.len - h0);
    uint64_t d0 = frag.len;
    if (dev_at != UINT64_MAX)
      put_json_str_body(frag, tags_arena + dev_at, dev_n);
    dev_o[r] = d0;
    dev_l[r] = static_cast<uint32_t>(frag.len - d0);
  }

  char ts_str[24];
  int ts_n = snprintf(ts_str, sizeof ts_str, "%lld",
                      static_cast<long long>(timestamp));
  char interval_str[16];
  int interval_n =
      snprintf(interval_str, sizeof interval_str, "%d", interval);

  VtBodiesImpl* impl = new VtBodiesImpl();
  BodyWriter w;
  uint32_t in_body = 0;
  if (max_per_body == 0) max_per_body = UINT32_MAX;
// literal append with compile-time length (put_str's strlen doesn't
// constant-fold through the out-of-line call and shows in profiles)
#define PUT_LIT(buf, lit) (buf).put(lit, sizeof(lit) - 1)
  for (uint64_t e = 0; e < nem; e++) {
    if (!w.open) {
      w.begin(compress_level);
      PUT_LIT(w.sink(), "{\"series\":[");
      in_body = 0;
    }
    Buf& b = w.sink();
    uint32_t r = em_rows[e];
    uint8_t s = em_suffix[e];
    // one reserve for everything this emission can write, then raw puts
    b.reserve(128 + name_len[r] + suffix_len[s] + tag_l[r] + host_l[r] +
              dev_l[r]);
    if (in_body) b.put_ch(',');
    PUT_LIT(b, "{\"metric\":\"");
    put_json_str_body(b, name_arena + name_off[r], name_len[r]);
    if (suffix_len[s]) b.put(suffix_blob + suffix_off[s], suffix_len[s]);
    PUT_LIT(b, "\",\"points\":[[");
    b.put(ts_str, ts_n);
    b.put_ch(',');
    put_double(b, em_values[e]);
    PUT_LIT(b, "]]");
    if (tag_l[r]) {  // omitempty, like the reference's DDMetric
      PUT_LIT(b, ",\"tags\":[");
      b.put(frag.p + tag_o[r], tag_l[r]);
      b.put_ch(']');
    }
    if (em_type[e])
      PUT_LIT(b, ",\"type\":\"rate\"");
    else
      PUT_LIT(b, ",\"type\":\"gauge\"");
    if (host_l[r]) {
      PUT_LIT(b, ",\"host\":\"");
      b.put(frag.p + host_o[r], host_l[r]);
      b.put_ch('"');
    }
    if (dev_l[r]) {
      PUT_LIT(b, ",\"device_name\":\"");
      b.put(frag.p + dev_o[r], dev_l[r]);
      b.put_ch('"');
    }
    PUT_LIT(b, ",\"interval\":");
    b.put(interval_str, interval_n);
    b.put_ch('}');
    in_body++;
    w.maybe_drain();
    if (in_body >= max_per_body) {
      PUT_LIT(w.sink(), "]}");
      w.end(impl);
    }
  }
  if (w.open) {
    PUT_LIT(w.sink(), "]}");
    w.end(impl);
  }
#undef PUT_LIT
  free(frag.p);
  return bodies_finish(impl);
}

// ---------------------------------------------------------------------------
// 1b. SignalFx datapoint JSON from columns
// ---------------------------------------------------------------------------
//
// Body shape: {"gauge":[{...}],"counter":[{...}]} (v2/datapoint), each
// point {"metric","value","timestamp" (ms),"dimensions":{k:v,...}}.
// Dimension semantics mirror the Python sink's _dimensions(): tag
// "k:v" pairs with LAST duplicate winning, the hostname dim unless a
// tag/common dim overrides it, common dimensions overriding tag dims,
// excluded keys (and "veneursinkonly") dropped. The vary-by client
// fanout is NOT handled here — the caller falls back to the per-row
// path when that is configured.

namespace {

struct KeyList {  // small (few entries): linear scan is fine
  const char* blob;
  const uint32_t* off;
  const uint32_t* len;
  uint32_t n;

  bool contains(const char* k, uint32_t kn) const {
    for (uint32_t i = 0; i < n; i++)
      if (len[i] == kn && memcmp(blob + off[i], k, kn) == 0) return true;
    return false;
  }
};

}  // namespace

extern "C" VtBodies* vt_sfx_datapoints_json(
    const char* name_arena, const uint32_t* name_off, const uint32_t* name_len,
    const char* tags_arena, const uint32_t* tags_off, const uint32_t* tags_len,
    uint32_t nrows, const char* suffix_blob, const uint32_t* suffix_off,
    const uint32_t* suffix_len, uint32_t nsuffix, const uint32_t* em_rows,
    const uint8_t* em_suffix, const double* em_values, const uint8_t* em_type,
    uint64_t nem, int64_t timestamp_ms, const char* hostname_tag,
    const char* hostname, const char* common_dims_json,
    const char* common_keys_blob, const uint32_t* common_keys_off,
    const uint32_t* common_keys_len, uint32_t n_common_keys,
    const char* excl_blob, const uint32_t* excl_off, const uint32_t* excl_len,
    uint32_t n_excl) {
  (void)nsuffix;
  KeyList common{common_keys_blob, common_keys_off, common_keys_len,
                 n_common_keys};
  KeyList excl{excl_blob, excl_off, excl_len, n_excl};
  uint32_t ht_len = static_cast<uint32_t>(strlen(hostname_tag));
  uint32_t common_len = static_cast<uint32_t>(strlen(common_dims_json));

  // per-row dimensions fragment: `"k":"v","k2":"v2"` (no braces)
  Buf frag;
  std::vector<uint64_t> dim_o(nrows);
  std::vector<uint32_t> dim_l(nrows);
  std::vector<std::pair<uint32_t, uint32_t>> kv;  // (off,len) spans in tags
  for (uint32_t r = 0; r < nrows; r++) {
    const char* tags = tags_arena + tags_off[r];
    uint32_t tlen = tags_len[r];
    kv.clear();
    uint32_t i = 0;
    while (i < tlen) {
      uint32_t j = i;
      while (j < tlen && tags[j] != ',') j++;
      if (j > i) kv.emplace_back(i, j - i);
      i = j + 1;
    }
    uint64_t f0 = frag.len;
    bool any = false;
    bool host_overridden = false;
    // LAST duplicate wins: walk in reverse, skip keys already emitted
    // (tracked as spans into this row's emitted region)
    std::vector<std::pair<uint32_t, uint32_t>> seen;  // key spans in tags
    for (size_t t = kv.size(); t-- > 0;) {
      const char* tag = tags + kv[t].first;
      uint32_t n = kv[t].second;
      uint32_t kn = 0;
      while (kn < n && tag[kn] != ':') kn++;
      bool has_sep = kn < n;
      const char* val = has_sep ? tag + kn + 1 : tag + n;
      uint32_t vn = has_sep ? n - kn - 1 : 0;
      bool dup = false;
      for (auto& s : seen)
        if (s.second == kn && memcmp(tags + s.first, tag, kn) == 0) {
          dup = true;
          break;
        }
      if (dup) continue;
      seen.emplace_back(kv[t].first, kn);
      if (kn == ht_len && memcmp(tag, hostname_tag, kn) == 0)
        host_overridden = true;
      if ((kn == 14 && memcmp(tag, "veneursinkonly", 14) == 0)
          || excl.contains(tag, kn) || common.contains(tag, kn))
        continue;
      if (any) frag.put_ch(',');
      frag.put_ch('"');
      put_json_str_body(frag, tag, kn);
      frag.put(&"\":\""[0], 3);
      put_json_str_body(frag, val, vn);
      frag.put_ch('"');
      any = true;
    }
    if (!host_overridden && ht_len && !excl.contains(hostname_tag, ht_len)
        && !common.contains(hostname_tag, ht_len)) {
      if (any) frag.put_ch(',');
      frag.put_ch('"');
      put_json_str_body(frag, hostname_tag, ht_len);
      frag.put(&"\":\""[0], 3);
      put_json_str_body(frag, hostname,
                        static_cast<uint32_t>(strlen(hostname)));
      frag.put_ch('"');
      any = true;
    }
    if (common_len) {
      if (any) frag.put_ch(',');
      frag.put(common_dims_json, common_len);
    }
    dim_o[r] = f0;
    dim_l[r] = static_cast<uint32_t>(frag.len - f0);
  }

  char ts_str[24];
  int ts_n = snprintf(ts_str, sizeof ts_str, "%lld",
                      static_cast<long long>(timestamp_ms));

  // two passes: gauges then counters, one body
  VtBodiesImpl* impl = new VtBodiesImpl();
  BodyWriter w;
  w.begin(0);  // the SignalFx client posts uncompressed
  Buf& b = w.sink();
#define PUT_LIT(buf, lit) (buf).put(lit, sizeof(lit) - 1)
  PUT_LIT(b, "{");
  const char* section_names[2] = {"\"gauge\":[", "\"counter\":["};
  bool wrote_section = false;
  for (int want_counter = 0; want_counter < 2; want_counter++) {
    bool opened = false;
    uint64_t in_section = 0;
    for (uint64_t e = 0; e < nem; e++) {
      if ((em_type[e] != 0) != (want_counter != 0)) continue;
      if (!opened) {
        if (wrote_section) b.put_ch(',');
        b.put_str(section_names[want_counter]);
        opened = true;
        wrote_section = true;
      }
      uint32_t r = em_rows[e];
      uint8_t s = em_suffix[e];
      b.reserve(96 + name_len[r] + suffix_len[s] + dim_l[r]);
      if (in_section++) b.put_ch(',');
      PUT_LIT(b, "{\"metric\":\"");
      put_json_str_body(b, name_arena + name_off[r], name_len[r]);
      if (suffix_len[s]) b.put(suffix_blob + suffix_off[s], suffix_len[s]);
      PUT_LIT(b, "\",\"value\":");
      if (want_counter)  // counters submit as integers
        put_i64(b, static_cast<int64_t>(em_values[e]));
      else
        put_double(b, em_values[e]);
      PUT_LIT(b, ",\"timestamp\":");
      b.put(ts_str, ts_n);
      PUT_LIT(b, ",\"dimensions\":{");
      b.put(frag.p + dim_o[r], dim_l[r]);
      PUT_LIT(b, "}}");
    }
    if (opened) b.put_ch(']');
  }
  PUT_LIT(b, "}");
#undef PUT_LIT
  w.end(impl);
  free(frag.p);
  return bodies_finish(impl);
}

// ---------------------------------------------------------------------------
// 1c. archival TSV rows from columns (plugins/s3 + localfile)
// ---------------------------------------------------------------------------
//
// Column order and semantics mirror the reference's csv.go:17-92 (via
// plugins/csv_encode.py): Name, {tags}, rate|gauge (counters divided by
// the interval on the Python side), hostname, interval, timestamp
// string, value, partition string. Fields containing a tab, newline,
// quote, or CR are quoted with "" doubling, like csv.Writer.

namespace {

// full-precision, never-exponential value formatting matching the
// Python encoder's _format_value (Go FormatFloat(v,'f',-1,64) parity):
// shortest round-trip decimal, NaN/+Inf/-Inf spellings, plain notation
void put_tsv_value(Buf& b, double v) {
  if (std::isnan(v)) {
    b.put("NaN", 3);
    return;
  }
  if (std::isinf(v)) {
    b.put(v > 0 ? "+Inf" : "-Inf", 4);
    return;
  }
  double r = nearbyint(v);
  if (r == v && fabs(v) < 1e16) {
    put_i64(b, static_cast<int64_t>(r));
    return;
  }
  char tmp[40];
  int n = 0;
  for (int prec = 15; prec <= 17; prec++) {  // shortest that round-trips
    n = snprintf(tmp, sizeof tmp, "%.*g", prec, v);
    if (strtod(tmp, nullptr) == v) break;
  }
  if (!memchr(tmp, 'e', n)) {
    b.put(tmp, n);
    return;
  }
  // %g went scientific: re-render plain and trim, like the Python
  // fallback format(v, ".17f").rstrip("0").rstrip(".")
  char big[512];
  n = snprintf(big, sizeof big, "%.17f", v);
  while (n > 0 && big[n - 1] == '0') n--;
  if (n > 0 && big[n - 1] == '.') n--;
  b.put(big, n);
}

void put_tsv_field(Buf& b, const char* s, uint32_t n) {
  bool needs_quote = false;
  for (uint32_t i = 0; i < n; i++) {
    char c = s[i];
    if (c == '\t' || c == '\n' || c == '\r' || c == '"') {
      needs_quote = true;
      break;
    }
  }
  if (!needs_quote) {
    b.put(s, n);
    return;
  }
  b.put_ch('"');
  for (uint32_t i = 0; i < n; i++) {
    if (s[i] == '"') b.put_ch('"');
    b.put_ch(s[i]);
  }
  b.put_ch('"');
}

}  // namespace

extern "C" VtBodies* vt_tsv_rows(
    const char* name_arena, const uint32_t* name_off, const uint32_t* name_len,
    const char* tags_arena, const uint32_t* tags_off, const uint32_t* tags_len,
    uint32_t nrows, const char* suffix_blob, const uint32_t* suffix_off,
    const uint32_t* suffix_len, uint32_t nsuffix, const uint32_t* em_rows,
    const uint8_t* em_suffix, const double* em_values, const uint8_t* em_type,
    uint64_t nem, const char* hostname, const char* interval_str,
    const char* timestamp_str, const char* partition_str) {
  (void)nsuffix;
  // shared trailing fragment: \t hostname \t interval \t timestamp \t
  // (dynamic: hostnames can approach the 253-char FQDN bound)
  Buf tailb;
  tailb.put_ch('\t');
  tailb.put_str(hostname);
  tailb.put_ch('\t');
  tailb.put_str(interval_str);
  tailb.put_ch('\t');
  tailb.put_str(timestamp_str);
  tailb.put_ch('\t');
  const char* tail = tailb.p;
  int tail_n = static_cast<int>(tailb.len);
  uint32_t part_n = static_cast<uint32_t>(strlen(partition_str));
  VtBodiesImpl* impl = new VtBodiesImpl();
  BodyWriter w;
  w.begin(0);
  Buf& b = w.sink();
  for (uint64_t e = 0; e < nem; e++) {
    uint32_t r = em_rows[e];
    uint8_t s = em_suffix[e];
    b.reserve(96 + name_len[r] + suffix_len[s] + tags_len[r] + tail_n
              + part_n);
    // Name (+suffix): the parsers reject tabs/quotes in names, but
    // imported names are untrusted — quote when needed
    {
      Buf tmp;  // suffix concat for quoting; fast path avoids the copy
      const char* np = name_arena + name_off[r];
      if (suffix_len[s] == 0) {
        put_tsv_field(b, np, name_len[r]);
      } else {
        tmp.put(np, name_len[r]);
        tmp.put(suffix_blob + suffix_off[s], suffix_len[s]);
        put_tsv_field(b, tmp.p, static_cast<uint32_t>(tmp.len));
        free(tmp.p);
      }
    }
    b.put_ch('\t');
    // {tags}
    {
      Buf tmp;
      tmp.put_ch('{');
      tmp.put(tags_arena + tags_off[r], tags_len[r]);
      tmp.put_ch('}');
      put_tsv_field(b, tmp.p, static_cast<uint32_t>(tmp.len));
      free(tmp.p);
    }
    b.put_ch('\t');
    if (em_type[e])
      b.put("rate", 4);
    else
      b.put("gauge", 5);
    b.put(tail, tail_n);
    put_tsv_value(b, em_values[e]);
    b.put_ch('\t');
    b.put(partition_str, part_n);
    b.put_ch('\n');
  }
  w.end(impl);
  free(tailb.p);
  return bodies_finish(impl);
}

// ---------------------------------------------------------------------------
// protobuf primitives
// ---------------------------------------------------------------------------

namespace {

struct Cursor {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;

  uint64_t varint() {
    uint64_t v = 0;
    int shift = 0;
    while (p < end && shift < 64) {
      uint8_t b = *p++;
      v |= static_cast<uint64_t>(b & 0x7F) << shift;
      if (!(b & 0x80)) return v;
      shift += 7;
    }
    ok = false;
    return 0;
  }
  uint64_t fixed64() {
    if (end - p < 8) {
      ok = false;
      return 0;
    }
    uint64_t v;
    memcpy(&v, p, 8);
    p += 8;
    return v;
  }
  double f64() {
    uint64_t v = fixed64();
    double d;
    memcpy(&d, &v, 8);
    return d;
  }
  // returns (field_number << 3 | wire_type), 0 at end/error
  uint32_t tag() {
    if (p >= end) return 0;
    uint64_t t = varint();
    return ok ? static_cast<uint32_t>(t) : 0;
  }
  Cursor sub() {  // length-delimited submessage
    uint64_t n = varint();
    if (!ok || static_cast<uint64_t>(end - p) < n) {
      ok = false;
      return {p, p};
    }
    Cursor c{p, p + n};
    p += n;
    return c;
  }
  void skip(uint32_t wire_type) {
    switch (wire_type) {
      case 0:
        varint();
        break;
      case 1:
        if (end - p >= 8)
          p += 8;
        else
          ok = false;
        break;
      case 2: {
        uint64_t n = varint();
        if (ok && static_cast<uint64_t>(end - p) >= n)
          p += n;
        else
          ok = false;
        break;
      }
      case 5:
        if (end - p >= 4)
          p += 4;
        else
          ok = false;
        break;
      default:
        ok = false;
    }
  }
};

size_t varint_size(uint64_t v) {
  size_t n = 1;
  while (v >= 0x80) {
    v >>= 7;
    n++;
  }
  return n;
}

void put_varint(Buf& b, uint64_t v) {
  b.reserve(10);
  while (v >= 0x80) {
    b.p[b.len++] = static_cast<char>(v) | 0x80;
    v >>= 7;
  }
  b.p[b.len++] = static_cast<char>(v);
}

void put_f64_field(Buf& b, uint32_t field, double v) {
  put_varint(b, (field << 3) | 1);
  b.put(&v, 8);
}

}  // namespace

// ---------------------------------------------------------------------------
// 2. MetricList decode to a struct-of-arrays batch
// ---------------------------------------------------------------------------

// payload kinds (which oneof was present)
enum : uint8_t {
  kPayloadNone = 0,
  kPayloadCounter = 1,
  kPayloadGauge = 2,
  kPayloadHistogram = 3,
  kPayloadSet = 4,
};

extern "C" struct VtMetricBatch {
  uint32_t count;
  uint64_t arena_len;
  uint64_t ncent;
  // MetricList.topk extension (field 14): span into the INPUT buffer,
  // len 0 when absent — Python parses the small submessage itself
  uint64_t topk_off;
  uint64_t topk_len;
  uint8_t* type;     // metricpb.Type enum value
  uint8_t* payload;  // kPayload*
  uint32_t* name_off;
  uint32_t* name_len;
  uint32_t* tags_off;  // tags joined with ',' in the arena
  uint32_t* tags_len;
  int64_t* ivalue;      // counter value
  double* dvalue;       // gauge value
  double* compression;  // digest metadata
  double* dmin;
  double* dmax;
  uint64_t* cent_off;  // span into means/weights
  uint32_t* cent_len;
  uint64_t* hll_off;  // span into the INPUT buffer (zero copy)
  uint64_t* hll_len;
  char* arena;
  double* means;
  double* weights;
  void* impl;
};

namespace {

struct VtMetricBatchImpl {
  std::vector<uint8_t> type, payload;
  std::vector<uint32_t> name_off, name_len, tags_off, tags_len, cent_len;
  std::vector<int64_t> ivalue;
  std::vector<double> dvalue, compression, dmin, dmax, means, weights;
  std::vector<uint64_t> cent_off, hll_off, hll_len;
  Buf arena;
};

// one t_digest submessage → centroid arrays; prefers the packed parallel
// arrays (fields 14/15: one memcpy) over repeated Centroid messages
void parse_tdigest(Cursor td, VtMetricBatchImpl* b) {
  const uint8_t* packed_means = nullptr;
  const uint8_t* packed_weights = nullptr;
  const uint8_t* quant_means = nullptr;
  const uint8_t* quant_weights = nullptr;
  uint64_t pm_n = 0, pw_n = 0, qm_n = 0, qw_n = 0;
  // proto3 omits zero-valued scalar fields, so an absent min/max means
  // 0.0 (a perfectly valid extremum), NOT "unknown" — only an EMPTY
  // digest normalizes to (inf, -inf), matching the Python decoder
  double comp = 0, mn = 0.0, mx = 0.0;
  Cursor scan = td;
  std::vector<Cursor> main_cents;
  while (scan.ok) {
    uint32_t t = scan.tag();
    if (!t) break;
    uint32_t field = t >> 3, wt = t & 7;
    if (field == 14 && wt == 2) {
      Cursor s = scan.sub();
      packed_means = s.p;
      pm_n = (s.end - s.p) / 8;
    } else if (field == 15 && wt == 2) {
      Cursor s = scan.sub();
      packed_weights = s.p;
      pw_n = (s.end - s.p) / 8;
    } else if (field == 16 && wt == 2) {
      // framework extension v2: u16 range-quantized means (LE)
      Cursor s = scan.sub();
      quant_means = s.p;
      qm_n = (s.end - s.p) / 2;
    } else if (field == 17 && wt == 2) {
      // framework extension v2: u16 bfloat16 weight bit patterns (LE)
      Cursor s = scan.sub();
      quant_weights = s.p;
      qw_n = (s.end - s.p) / 2;
    } else if (field == 2 && wt == 1) {
      comp = scan.f64();
    } else if (field == 3 && wt == 1) {
      mn = scan.f64();
    } else if (field == 4 && wt == 1) {
      mx = scan.f64();
    } else if (field == 1 && wt == 2) {
      main_cents.push_back(scan.sub());
    } else {
      scan.skip(wt);
    }
  }
  uint64_t c0 = b->means.size();
  if (quant_means && quant_weights && qm_n == qw_n && qm_n > 0) {
    // dequantize AFTER the scan: min/max may serialize after fields
    // 16/17, and mean = min + q/65535 * (max-min)
    b->means.resize(c0 + qm_n);
    b->weights.resize(c0 + qm_n);
    double span = (mx - mn) / 65535.0;
    if (!std::isfinite(span)) span = 0.0;
    for (uint64_t i = 0; i < qm_n; i++) {
      uint16_t q, wbits;
      memcpy(&q, quant_means + i * 2, 2);
      memcpy(&wbits, quant_weights + i * 2, 2);
      uint32_t f32bits = static_cast<uint32_t>(wbits) << 16;
      float w;
      memcpy(&w, &f32bits, 4);
      b->means[c0 + i] = mn + q * span;
      b->weights[c0 + i] = w;
    }
  } else if (packed_means && packed_weights && pm_n == pw_n && pm_n > 0) {
    b->means.resize(c0 + pm_n);
    b->weights.resize(c0 + pm_n);
    memcpy(b->means.data() + c0, packed_means, pm_n * 8);
    memcpy(b->weights.data() + c0, packed_weights, pw_n * 8);
  } else {
    for (Cursor c : main_cents) {
      double mean = 0, weight = 0;
      while (c.ok) {
        uint32_t t = c.tag();
        if (!t) break;
        uint32_t field = t >> 3, wt = t & 7;
        if (field == 1 && wt == 1)
          mean = c.f64();
        else if (field == 2 && wt == 1)
          weight = c.f64();
        else
          c.skip(wt);
      }
      b->means.push_back(mean);
      b->weights.push_back(weight);
    }
  }
  uint64_t n = b->means.size() - c0;
  b->cent_off.push_back(c0);
  b->cent_len.push_back(static_cast<uint32_t>(n));
  b->compression.push_back(comp);
  // empty digests normalize to (inf, -inf) like the Python decoder
  b->dmin.push_back(n ? mn : HUGE_VAL);
  b->dmax.push_back(n ? mx : -HUGE_VAL);
}

}  // namespace

extern "C" VtMetricBatch* vt_mlist_decode(const char* buf, size_t len) {
  VtMetricBatchImpl* b = new VtMetricBatchImpl();
  const uint8_t* base = reinterpret_cast<const uint8_t*>(buf);
  uint64_t topk_off = 0, topk_len = 0;
  Cursor top{base, base + len};
  while (top.ok) {
    uint32_t t = top.tag();
    if (!t) break;
    if ((t >> 3) == 14 && (t & 7) == 2) {  // MetricList.topk extension
      Cursor s = top.sub();
      topk_off = static_cast<uint64_t>(s.p - base);
      topk_len = static_cast<uint64_t>(s.end - s.p);
      continue;
    }
    if ((t >> 3) != 1 || (t & 7) != 2) {  // MetricList.metrics
      top.skip(t & 7);
      continue;
    }
    Cursor m = top.sub();
    uint32_t name_o = static_cast<uint32_t>(b->arena.len), name_n = 0;
    // tag spans collect first and join after the field loop: a
    // nonstandard encoder may interleave other fields between tag
    // entries, which would corrupt an incrementally-joined arena span
    std::vector<std::pair<const uint8_t*, uint32_t>> tag_spans;
    uint8_t mtype = 0, payload = kPayloadNone;
    int64_t ival = 0;
    double dval = 0;
    uint64_t hll_o = 0, hll_n = 0;
    bool have_digest = false;
    Cursor digest_cur{nullptr, nullptr};
    while (m.ok) {
      uint32_t mt = m.tag();
      if (!mt) break;
      uint32_t field = mt >> 3, wt = mt & 7;
      if (field == 1 && wt == 2) {  // name
        Cursor s = m.sub();
        name_o = static_cast<uint32_t>(b->arena.len);
        name_n = static_cast<uint32_t>(s.end - s.p);
        b->arena.put(s.p, name_n);
      } else if (field == 2 && wt == 2) {  // tags
        Cursor s = m.sub();
        tag_spans.emplace_back(s.p, static_cast<uint32_t>(s.end - s.p));
      } else if (field == 3 && wt == 0) {  // type enum
        mtype = static_cast<uint8_t>(m.varint());
      } else if (field == 5 && wt == 2) {  // counter
        Cursor s = m.sub();
        while (s.ok) {
          uint32_t st = s.tag();
          if (!st) break;
          if ((st >> 3) == 1 && (st & 7) == 0)
            ival = static_cast<int64_t>(s.varint());
          else
            s.skip(st & 7);
        }
        payload = kPayloadCounter;
      } else if (field == 6 && wt == 2) {  // gauge
        Cursor s = m.sub();
        while (s.ok) {
          uint32_t st = s.tag();
          if (!st) break;
          if ((st >> 3) == 1 && (st & 7) == 1)
            dval = s.f64();
          else
            s.skip(st & 7);
        }
        payload = kPayloadGauge;
      } else if (field == 7 && wt == 2) {  // histogram{t_digest}
        Cursor s = m.sub();
        while (s.ok) {
          uint32_t st = s.tag();
          if (!st) break;
          if ((st >> 3) == 1 && (st & 7) == 2) {
            digest_cur = s.sub();
            have_digest = true;
          } else {
            s.skip(st & 7);
          }
        }
        payload = kPayloadHistogram;
      } else if (field == 8 && wt == 2) {  // set{hyper_log_log}
        Cursor s = m.sub();
        while (s.ok) {
          uint32_t st = s.tag();
          if (!st) break;
          if ((st >> 3) == 1 && (st & 7) == 2) {
            Cursor h = s.sub();
            hll_o = static_cast<uint64_t>(h.p - base);
            hll_n = static_cast<uint64_t>(h.end - h.p);
          } else {
            s.skip(st & 7);
          }
        }
        payload = kPayloadSet;
      } else {
        m.skip(wt);
      }
    }
    uint32_t tags_o = static_cast<uint32_t>(b->arena.len);
    for (size_t k = 0; k < tag_spans.size(); k++) {
      if (k) b->arena.put_ch(',');
      b->arena.put(tag_spans[k].first, tag_spans[k].second);
    }
    uint32_t tags_n = static_cast<uint32_t>(b->arena.len) - tags_o;
    b->type.push_back(mtype);
    b->payload.push_back(payload);
    b->name_off.push_back(name_o);
    b->name_len.push_back(name_n);
    b->tags_off.push_back(tags_n ? tags_o : 0);
    b->tags_len.push_back(tags_n);
    b->ivalue.push_back(ival);
    b->dvalue.push_back(dval);
    b->hll_off.push_back(hll_o);
    b->hll_len.push_back(hll_n);
    if (payload == kPayloadHistogram && have_digest) {
      parse_tdigest(digest_cur, b);
    } else {
      b->cent_off.push_back(b->means.size());
      b->cent_len.push_back(0);
      b->compression.push_back(0);
      b->dmin.push_back(HUGE_VAL);
      b->dmax.push_back(-HUGE_VAL);
    }
  }

  VtMetricBatch* out = new VtMetricBatch();
  out->count = static_cast<uint32_t>(b->type.size());
  out->arena_len = b->arena.len;
  out->ncent = b->means.size();
  out->topk_off = topk_off;
  out->topk_len = topk_len;
  out->type = b->type.data();
  out->payload = b->payload.data();
  out->name_off = b->name_off.data();
  out->name_len = b->name_len.data();
  out->tags_off = b->tags_off.data();
  out->tags_len = b->tags_len.data();
  out->ivalue = b->ivalue.data();
  out->dvalue = b->dvalue.data();
  out->compression = b->compression.data();
  out->dmin = b->dmin.data();
  out->dmax = b->dmax.data();
  out->cent_off = b->cent_off.data();
  out->cent_len = b->cent_len.data();
  out->hll_off = b->hll_off.data();
  out->hll_len = b->hll_len.data();
  out->arena = b->arena.p;
  out->means = b->means.data();
  out->weights = b->weights.data();
  out->impl = b;
  return out;
}

// layout-independent accessor (the fuzz driver must not depend on the
// struct's field order)
extern "C" uint32_t vt_mbatch_count(const VtMetricBatch* m) {
  return m ? m->count : 0;
}

extern "C" void vt_mbatch_free(VtMetricBatch* m) {
  if (!m) return;
  VtMetricBatchImpl* impl = static_cast<VtMetricBatchImpl*>(m->impl);
  free(impl->arena.p);
  delete impl;
  delete m;
}

// ---------------------------------------------------------------------------
// import interning: (type, name, tags) -> row
// ---------------------------------------------------------------------------
//
// Same memoization contract as veneur_ingest.cpp's InternTable: only rows
// Python assigned are known; misses come back for Python to resolve and
// teach with put. Open addressing, fnv1a-64, power-of-two sizing.

namespace {

struct MEntry {
  uint64_t hash = 0;
  uint32_t key_off = 0;  // key bytes: [type u8][name][0x1f][tags]
  uint32_t key_len = 0;
  uint32_t row = 0;
  bool used = false;
};

struct MTable {
  std::vector<MEntry> slots;
  Buf arena;
  size_t count = 0;

  MTable() { slots.resize(1 << 12); }
};

uint64_t fnv1a64(const void* data, size_t n, uint64_t h = 1469598103934665603ULL) {
  const uint8_t* p = static_cast<const uint8_t*>(data);
  for (size_t i = 0; i < n; i++) {
    h ^= p[i];
    h *= 1099511628211ULL;
  }
  return h;
}

// The key includes the PAYLOAD kind (which value-oneof was present), not
// just the type enum: row indices are only meaningful within one group,
// and the group applied to is chosen by the payload at apply time — a
// malformed/adversarial forwarder repeating (type, name, tags) with a
// different oneof must MISS here so Python re-resolves against the right
// group's interner instead of writing through a foreign row index
// (ADVICE round-3, medium).
uint64_t mkey_hash(uint8_t type, uint8_t payload, const char* name,
                   uint32_t name_n, const char* tags, uint32_t tags_n) {
  uint64_t h = fnv1a64(&type, 1);
  h = fnv1a64(&payload, 1, h);
  h = fnv1a64(name, name_n, h);
  uint8_t sep = 0x1f;
  h = fnv1a64(&sep, 1, h);
  return fnv1a64(tags, tags_n, h);
}

bool mkey_eq(const MTable* t, const MEntry& e, uint8_t type, uint8_t payload,
             const char* name, uint32_t name_n, const char* tags,
             uint32_t tags_n) {
  if (e.key_len != 2 + name_n + 1 + tags_n) return false;
  const char* k = t->arena.p + e.key_off;
  if (static_cast<uint8_t>(k[0]) != type) return false;
  if (static_cast<uint8_t>(k[1]) != payload) return false;
  if (memcmp(k + 2, name, name_n) != 0) return false;
  if (k[2 + name_n] != 0x1f) return false;
  return memcmp(k + 3 + name_n, tags, tags_n) == 0;
}

void mtable_grow(MTable* t) {
  std::vector<MEntry> old = std::move(t->slots);
  t->slots.assign(old.size() * 2, MEntry{});
  size_t mask = t->slots.size() - 1;
  for (const MEntry& e : old) {
    if (!e.used) continue;
    size_t i = e.hash & mask;
    while (t->slots[i].used) i = (i + 1) & mask;
    t->slots[i] = e;
  }
}

}  // namespace

extern "C" MTable* vt_mintern_new() { return new MTable(); }

extern "C" void vt_mintern_free(MTable* t) {
  if (t) free(t->arena.p);
  delete t;
}

extern "C" void vt_mintern_reset(MTable* t) {
  t->slots.assign(t->slots.size(), MEntry{});
  t->arena.len = 0;
  t->count = 0;
}

extern "C" void vt_mintern_put(MTable* t, uint8_t type, uint8_t payload,
                               const char* name, uint32_t name_n,
                               const char* tags, uint32_t tags_n,
                               uint32_t row) {
  if (t->count * 2 >= t->slots.size()) mtable_grow(t);
  uint64_t h = mkey_hash(type, payload, name, name_n, tags, tags_n);
  size_t mask = t->slots.size() - 1;
  size_t i = h & mask;
  while (t->slots[i].used) {
    if (t->slots[i].hash == h &&
        mkey_eq(t, t->slots[i], type, payload, name, name_n, tags, tags_n)) {
      t->slots[i].row = row;
      return;
    }
    i = (i + 1) & mask;
  }
  MEntry& e = t->slots[i];
  e.used = true;
  e.hash = h;
  e.row = row;
  e.key_off = static_cast<uint32_t>(t->arena.len);
  e.key_len = 2 + name_n + 1 + tags_n;
  char sep = 0x1f;
  t->arena.put(&type, 1);
  t->arena.put(&payload, 1);
  t->arena.put(name, name_n);
  t->arena.put(&sep, 1);
  t->arena.put(tags, tags_n);
  t->count++;
}

// rows_out[i] = row or UINT32_MAX on miss; returns number of misses (their
// indices in miss_out)
extern "C" uint32_t vt_mintern_assign(MTable* t, const VtMetricBatch* b,
                                      uint32_t* rows_out,
                                      uint32_t* miss_out) {
  uint32_t nmiss = 0;
  size_t mask = t->slots.size() - 1;
  for (uint32_t i = 0; i < b->count; i++) {
    const char* name = b->arena + b->name_off[i];
    const char* tags = b->arena + b->tags_off[i];
    uint8_t type = b->type[i];
    uint8_t payload = b->payload[i];
    uint64_t h =
        mkey_hash(type, payload, name, b->name_len[i], tags, b->tags_len[i]);
    size_t s = h & mask;
    uint32_t row = UINT32_MAX;
    while (t->slots[s].used) {
      if (t->slots[s].hash == h &&
          mkey_eq(t, t->slots[s], type, payload, name, b->name_len[i], tags,
                  b->tags_len[i])) {
        row = t->slots[s].row;
        break;
      }
      s = (s + 1) & mask;
    }
    rows_out[i] = row;
    if (row == UINT32_MAX) miss_out[nmiss++] = i;
  }
  return nmiss;
}

// ---------------------------------------------------------------------------
// 3. MetricList encode from columnar digest planes
// ---------------------------------------------------------------------------
//
// means/weights are the store's flushed [S, K] float32 planes; centroids
// with weight <= 0 are padding and are skipped on the wire. Bodies split
// at max_body_bytes; each body is a complete MetricList serialization
// (protobuf messages concatenate, so the Python side can append scalar/set
// metrics serialized by protobuf to any one body).

namespace {

// shared Metric framing for the two digest encoders: the size pass and
// the write pass MUST stay byte-exact with each other, so both live here

uint64_t metric_header_size(uint32_t name_n, const char* tags, uint32_t tlen,
                            uint8_t pb_type) {
  uint64_t sz = 1 + varint_size(name_n) + name_n;
  uint32_t i = 0;
  while (i < tlen) {  // tags: split joined on ','
    uint32_t j = i;
    while (j < tlen && tags[j] != ',') j++;
    uint32_t n = j - i;
    sz += 1 + varint_size(n) + n;
    i = j + 1;
  }
  if (pb_type) sz += 1 + varint_size(pb_type);
  return sz;
}

// chunk-split check + MetricList.metrics record open
void open_metric_record(Buf& body, VtBodiesImpl* impl, uint64_t metric_sz,
                        uint64_t max_body_bytes) {
  if (body.len &&
      body.len + metric_sz + 1 + varint_size(metric_sz) > max_body_bytes) {
    impl->lens.push_back(body.len);
    impl->ptrs.push_back(body.take());
  }
  put_varint(body, (1 << 3) | 2);  // MetricList.metrics
  put_varint(body, metric_sz);
}

// Metric.name + Metric.tags + Metric.type, then the t_digest envelope
void write_digest_metric_header(Buf& body, const char* name, uint32_t name_n,
                                const char* tags, uint32_t tlen,
                                uint8_t pb_type, uint64_t td_sz) {
  put_varint(body, (1 << 3) | 2);  // Metric.name
  put_varint(body, name_n);
  body.put(name, name_n);
  uint32_t i = 0;
  while (i < tlen) {
    uint32_t j = i;
    while (j < tlen && tags[j] != ',') j++;
    uint32_t n = j - i;
    put_varint(body, (2 << 3) | 2);  // Metric.tags
    put_varint(body, n);
    body.put(tags + i, n);
    i = j + 1;
  }
  if (pb_type) {
    put_varint(body, (3 << 3) | 0);  // Metric.type
    put_varint(body, pb_type);
  }
  uint64_t hv_sz = 1 + varint_size(td_sz) + td_sz;
  put_varint(body, (7 << 3) | 2);  // Metric.histogram
  put_varint(body, hv_sz);
  put_varint(body, (1 << 3) | 2);  // HistogramValue.t_digest
  put_varint(body, td_sz);
}

}  // namespace

extern "C" VtBodies* vt_mlist_encode_digests(
    const char* name_arena, const uint32_t* name_off, const uint32_t* name_len,
    const char* tags_arena, const uint32_t* tags_off, const uint32_t* tags_len,
    const float* means, const float* weights, uint32_t K, const float* dmins,
    const float* dmaxs, uint32_t nrows, uint8_t pb_type, double compression,
    uint64_t max_body_bytes, int reference_compat) {
  VtBodiesImpl* impl = new VtBodiesImpl();
  Buf body;
  if (max_body_bytes == 0) max_body_bytes = UINT64_MAX;
  std::vector<uint32_t> live;
  live.reserve(K);
  for (uint32_t r = 0; r < nrows; r++) {
    const float* wrow = weights + static_cast<uint64_t>(r) * K;
    const float* mrow = means + static_cast<uint64_t>(r) * K;
    live.clear();
    for (uint32_t k = 0; k < K; k++)
      if (wrow[k] > 0.0f) live.push_back(k);
    uint64_t nc = live.size();

    // --- sizes, inside out
    // t_digest body: compression(9) + min(9) + max(9) + packed arrays
    uint64_t packed_bytes = nc * 8;
    uint64_t td_sz = 9 + 9 + 9;
    if (nc) {
      td_sz += 1 + varint_size(packed_bytes) + packed_bytes;  // field 14
      td_sz += 1 + varint_size(packed_bytes) + packed_bytes;  // field 15
      if (reference_compat) td_sz += nc * 20;  // Centroid{mean,weight} = 18+2
    }
    uint64_t hv_sz = 1 + varint_size(td_sz) + td_sz;  // HistogramValue.t_digest
    const char* tags = tags_arena + tags_off[r];
    uint32_t tlen = tags_len[r];
    uint64_t metric_sz = metric_header_size(name_len[r], tags, tlen, pb_type)
                         + 1 + varint_size(hv_sz) + hv_sz;

    // --- write
    open_metric_record(body, impl, metric_sz, max_body_bytes);
    write_digest_metric_header(body, name_arena + name_off[r], name_len[r],
                               tags, tlen, pb_type, td_sz);
    if (nc && reference_compat) {
      for (uint32_t k : live) {  // tdigest.main_centroids (reference schema)
        put_varint(body, (1 << 3) | 2);
        put_varint(body, 18);
        put_f64_field(body, 1, static_cast<double>(mrow[k]));
        put_f64_field(body, 2, static_cast<double>(wrow[k]));
      }
    }
    put_f64_field(body, 2, compression);
    put_f64_field(body, 3, static_cast<double>(dmins[r]));
    put_f64_field(body, 4, static_cast<double>(dmaxs[r]));
    if (nc) {
      put_varint(body, (14 << 3) | 2);  // packed_means
      put_varint(body, packed_bytes);
      body.reserve(packed_bytes);
      for (uint32_t k : live) {
        double d = static_cast<double>(mrow[k]);
        memcpy(body.p + body.len, &d, 8);
        body.len += 8;
      }
      put_varint(body, (15 << 3) | 2);  // packed_weights
      put_varint(body, packed_bytes);
      body.reserve(packed_bytes);
      for (uint32_t k : live) {
        double d = static_cast<double>(wrow[k]);
        memcpy(body.p + body.len, &d, 8);
        body.len += 8;
      }
    }
  }
  if (body.len) {
    impl->lens.push_back(body.len);
    impl->ptrs.push_back(body.take());
  }
  free(body.p);
  return bodies_finish(impl);
}

// Packed-plane variant: input is the device-compacted layout (per-row
// live-centroid counts + flat u16 quantized means / bfloat16 weight bit
// patterns) produced by core/slab.py:_pack_slab — the forward path that
// never fetches raw [S, K] f32 planes. Wire format:
//   reference_compat=0: tdigest fields 16/17 (the quantized arrays
//     verbatim, 4 bytes/centroid; decoded by parse_tdigest above) —
//   reference_compat=1: dequantized repeated Centroid messages plus the
//     packed f64 arrays, byte-layout-identical to what
//     vt_mlist_encode_digests emits for a reference global.
extern "C" VtBodies* vt_mlist_encode_digests_packed(
    const char* name_arena, const uint32_t* name_off, const uint32_t* name_len,
    const char* tags_arena, const uint32_t* tags_off, const uint32_t* tags_len,
    const uint16_t* counts, const uint16_t* means_q, const uint16_t* weights_bf,
    const float* dmins, const float* dmaxs, uint32_t nrows, uint8_t pb_type,
    double compression, uint64_t max_body_bytes, int reference_compat) {
  VtBodiesImpl* impl = new VtBodiesImpl();
  Buf body;
  if (max_body_bytes == 0) max_body_bytes = UINT64_MAX;
  uint64_t c0 = 0;
  for (uint32_t r = 0; r < nrows; r++) {
    uint64_t nc = counts[r];
    const uint16_t* mq = means_q + c0;
    const uint16_t* wb = weights_bf + c0;
    c0 += nc;

    // --- sizes, inside out
    uint64_t td_sz = 9 + 9 + 9;  // compression + min + max
    if (nc) {
      if (reference_compat) {
        uint64_t packed_bytes = nc * 8;
        td_sz += 1 + varint_size(packed_bytes) + packed_bytes;  // field 14
        td_sz += 1 + varint_size(packed_bytes) + packed_bytes;  // field 15
        td_sz += nc * 20;  // Centroid{mean,weight} = 18+2
      } else {
        uint64_t quant_bytes = nc * 2;
        td_sz += 2 + varint_size(quant_bytes) + quant_bytes;  // field 16
        td_sz += 2 + varint_size(quant_bytes) + quant_bytes;  // field 17
      }
    }
    uint64_t hv_sz = 1 + varint_size(td_sz) + td_sz;  // HistogramValue.t_digest
    const char* tags = tags_arena + tags_off[r];
    uint32_t tlen = tags_len[r];
    uint64_t metric_sz = metric_header_size(name_len[r], tags, tlen, pb_type)
                         + 1 + varint_size(hv_sz) + hv_sz;

    // --- write
    open_metric_record(body, impl, metric_sz, max_body_bytes);
    write_digest_metric_header(body, name_arena + name_off[r], name_len[r],
                               tags, tlen, pb_type, td_sz);
    double mn = static_cast<double>(dmins[r]);
    double span = (static_cast<double>(dmaxs[r]) - mn) / 65535.0;
    if (!std::isfinite(span)) span = 0.0;
    if (nc && reference_compat) {
      for (uint64_t k = 0; k < nc; k++) {  // tdigest.main_centroids
        uint32_t f32bits = static_cast<uint32_t>(wb[k]) << 16;
        float w;
        memcpy(&w, &f32bits, 4);
        put_varint(body, (1 << 3) | 2);
        put_varint(body, 18);
        put_f64_field(body, 1, mn + mq[k] * span);
        put_f64_field(body, 2, static_cast<double>(w));
      }
    }
    put_f64_field(body, 2, compression);
    put_f64_field(body, 3, static_cast<double>(dmins[r]));
    put_f64_field(body, 4, static_cast<double>(dmaxs[r]));
    if (nc) {
      if (reference_compat) {
        uint64_t packed_bytes = nc * 8;
        put_varint(body, (14 << 3) | 2);  // packed_means (f64)
        put_varint(body, packed_bytes);
        body.reserve(packed_bytes);
        for (uint64_t k = 0; k < nc; k++) {
          double d = mn + mq[k] * span;
          memcpy(body.p + body.len, &d, 8);
          body.len += 8;
        }
        put_varint(body, (15 << 3) | 2);  // packed_weights (f64)
        put_varint(body, packed_bytes);
        body.reserve(packed_bytes);
        for (uint64_t k = 0; k < nc; k++) {
          uint32_t f32bits = static_cast<uint32_t>(wb[k]) << 16;
          float w;
          memcpy(&w, &f32bits, 4);
          double d = static_cast<double>(w);
          memcpy(body.p + body.len, &d, 8);
          body.len += 8;
        }
      } else {
        uint64_t quant_bytes = nc * 2;
        put_varint(body, (16 << 3) | 2);  // quantized_means (u16 LE)
        put_varint(body, quant_bytes);
        body.put(reinterpret_cast<const char*>(mq), quant_bytes);
        put_varint(body, (17 << 3) | 2);  // quantized_weights (bf16 LE)
        put_varint(body, quant_bytes);
        body.put(reinterpret_cast<const char*>(wb), quant_bytes);
      }
    }
  }
  if (body.len) {
    impl->lens.push_back(body.len);
    impl->ptrs.push_back(body.take());
  }
  free(body.p);
  return bodies_finish(impl);
}

// Native ingest hot path: SO_REUSEPORT UDP reader pool + DogStatsD parser
// + framed-SSF scanner.
//
// The reference reaches native ingest performance with Go + raw syscalls
// (/root/reference/socket_linux.go:12-76 SO_REUSEPORT/SO_RCVBUF,
// server.go:795-825 read loop, samplers/parser.go:232-363 parser,
// samplers/split_bytes.go splitter). This file is the C++ equivalent for
// the TPU build: N reader threads each own a SO_REUSEPORT socket, drain
// it with recvmmsg, split datagrams on '\n', and parse each DogStatsD
// line into a packed struct-of-arrays batch that Python drains wholesale
// — one FFI call per batch instead of one parse per line.
//
// Parsed-record grammar and validation mirror parser.go:232-363 exactly:
//   name:value|type[|@rate][|#tag1,tag2]   (sections in any order, once)
// with byte-wise tag sorting (Go sort.Strings), first-match
// veneurlocalonly/veneurglobalonly scope-tag extraction
// (parser.go:326-342), the fnv1a-32 digest over name+type+joined-tags
// (parser.go:259-354), NaN/Inf rejection, and (0,1] sample rates.
// Events (_e{) and service checks (_sc) are surfaced as RAW records for
// the Python parser — they are rare control-plane packets.
//
// The framed-SSF scanner mirrors protocol/wire.go:42-108: frames are
// 1 version byte (0x00) + 4-byte big-endian length + protobuf, 16 MiB
// cap; a bad version/length is a poison framing error.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <poll.h>
#include <pthread.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kFnvInit = 0x811C9DC5u;
constexpr uint32_t kFnvPrime = 0x01000193u;

inline uint32_t fnv1a(const char* data, size_t len, uint32_t h) {
  for (size_t i = 0; i < len; i++) {
    h = (h ^ static_cast<unsigned char>(data[i])) * kFnvPrime;
  }
  return h;
}

// Record types (order matches veneur_tpu/native/__init__.py)
enum RecordType : uint8_t {
  kCounter = 0,
  kGauge = 1,
  kHistogram = 2,
  kTimer = 3,
  kSet = 4,
  kRaw = 5,  // _e{ / _sc lines, passed through for the Python parser
};

const char* kTypeNames[5] = {"counter", "gauge", "histogram", "timer", "set"};
const size_t kTypeNameLens[5] = {7, 5, 9, 5, 3};

// Scopes (parser.go:34-40); kTopK marks a set carrying the veneurtopk
// magic tag (heavy-hitter sampler, this framework's extension)
enum Scope : uint8_t { kMixed = 0, kLocalOnly = 1, kGlobalOnly = 2,
                       kTopK = 3 };

}  // namespace

// One batch of parsed records, struct-of-arrays. All offsets index into
// `arena`. Python mirrors this layout with ctypes.
extern "C" struct VtBatch {
  uint32_t capacity;     // max records
  uint32_t arena_cap;    // arena bytes
  uint32_t count;        // records filled
  uint32_t arena_len;    // arena bytes used
  uint64_t parse_errors; // lines rejected since batch reset
  uint8_t* type;
  uint8_t* scope;
  double* value;
  float* sample_rate;
  uint32_t* digest;
  uint32_t* name_off;
  uint32_t* name_len;
  uint32_t* tags_off;    // comma-joined sorted tags
  uint32_t* tags_len;
  uint32_t* aux_off;     // set member / raw line bytes
  uint32_t* aux_len;
  char* arena;
};

extern "C" VtBatch* vt_batch_new(uint32_t capacity, uint32_t arena_cap) {
  VtBatch* b = static_cast<VtBatch*>(calloc(1, sizeof(VtBatch)));
  b->capacity = capacity;
  b->arena_cap = arena_cap;
  b->type = static_cast<uint8_t*>(malloc(capacity));
  b->scope = static_cast<uint8_t*>(malloc(capacity));
  b->value = static_cast<double*>(malloc(capacity * sizeof(double)));
  b->sample_rate = static_cast<float*>(malloc(capacity * sizeof(float)));
  b->digest = static_cast<uint32_t*>(malloc(capacity * sizeof(uint32_t)));
  b->name_off = static_cast<uint32_t*>(malloc(capacity * sizeof(uint32_t)));
  b->name_len = static_cast<uint32_t*>(malloc(capacity * sizeof(uint32_t)));
  b->tags_off = static_cast<uint32_t*>(malloc(capacity * sizeof(uint32_t)));
  b->tags_len = static_cast<uint32_t*>(malloc(capacity * sizeof(uint32_t)));
  b->aux_off = static_cast<uint32_t*>(malloc(capacity * sizeof(uint32_t)));
  b->aux_len = static_cast<uint32_t*>(malloc(capacity * sizeof(uint32_t)));
  b->arena = static_cast<char*>(malloc(arena_cap));
  return b;
}

extern "C" void vt_batch_free(VtBatch* b) {
  if (!b) return;
  free(b->type); free(b->scope); free(b->value); free(b->sample_rate);
  free(b->digest); free(b->name_off); free(b->name_len);
  free(b->tags_off); free(b->tags_len); free(b->aux_off); free(b->aux_len);
  free(b->arena);
  free(b);
}

extern "C" void vt_batch_reset(VtBatch* b) {
  b->count = 0;
  b->arena_len = 0;
  b->parse_errors = 0;
}

namespace {

// Append bytes to the batch arena; returns offset or UINT32_MAX when full.
inline uint32_t arena_put(VtBatch* b, const char* data, size_t len) {
  if (b->arena_len + len > b->arena_cap) return UINT32_MAX;
  memcpy(b->arena + b->arena_len, data, len);
  uint32_t off = b->arena_len;
  b->arena_len += static_cast<uint32_t>(len);
  return off;
}

struct TagView {
  const char* p;
  size_t len;
  bool operator<(const TagView& o) const {
    int c = memcmp(p, o.p, std::min(len, o.len));
    if (c != 0) return c < 0;
    return len < o.len;
  }
};

inline bool has_prefix(const TagView& t, const char* pre, size_t n) {
  return t.len >= n && memcmp(t.p, pre, n) == 0;
}

// Parse one line into the batch. Returns false on a parse error (counted
// by the caller). Mirrors parse_metric (parser.go:232-363).
bool parse_line(const char* line, size_t len, VtBatch* b) {
  if (b->count >= b->capacity) return false;
  uint32_t idx = b->count;

  // events / service checks pass through as raw records
  if ((len >= 3 && memcmp(line, "_e{", 3) == 0) ||
      (len >= 3 && memcmp(line, "_sc", 3) == 0)) {
    uint32_t off = arena_put(b, line, len);
    if (off == UINT32_MAX) return false;
    b->type[idx] = kRaw;
    b->scope[idx] = kMixed;
    b->value[idx] = 0.0;
    b->sample_rate[idx] = 1.0f;
    b->digest[idx] = 0;
    b->name_off[idx] = b->name_len[idx] = 0;
    b->tags_off[idx] = b->tags_len[idx] = 0;
    b->aux_off[idx] = off;
    b->aux_len[idx] = static_cast<uint32_t>(len);
    b->count++;
    return true;
  }

  // a trailing pipe is an empty final section (parser.go rejects it)
  if (line[len - 1] == '|') return false;

  // head section: name:value
  const char* pipe = static_cast<const char*>(memchr(line, '|', len));
  if (!pipe) return false;
  size_t head_len = pipe - line;
  const char* colon =
      static_cast<const char*>(memchr(line, ':', head_len));
  if (!colon) return false;
  size_t name_len = colon - line;
  if (name_len == 0) return false;
  const char* value_p = colon + 1;
  size_t value_len = head_len - name_len - 1;

  // type section
  const char* rest = pipe + 1;
  size_t rest_len = len - head_len - 1;
  const char* type_end =
      static_cast<const char*>(memchr(rest, '|', rest_len));
  size_t type_len = type_end ? static_cast<size_t>(type_end - rest)
                             : rest_len;
  if (type_len == 0) return false;
  uint8_t rtype;
  switch (rest[0]) {  // only the first byte is inspected (parser.go:281)
    case 'c': rtype = kCounter; break;
    case 'g': rtype = kGauge; break;
    case 'h': rtype = kHistogram; break;
    case 'm': rtype = kTimer; break;
    case 's': rtype = kSet; break;
    default: return false;
  }

  double value = 0.0;
  if (rtype != kSet) {
    char tmp[64];
    if (value_len == 0 || value_len >= sizeof(tmp)) return false;
    memcpy(tmp, value_p, value_len);
    tmp[value_len] = 0;
    char* endp = nullptr;
    value = strtod(tmp, &endp);
    if (endp != tmp + value_len) return false;
    if (std::isnan(value) || std::isinf(value)) return false;
  }

  // optional sections: @rate and #tags, any order, at most once
  float sample_rate = 1.0f;
  bool found_rate = false;
  // tags grow without bound, matching the pure-Python parser (the Go
  // reference imposes no tag-count limit either)
  std::vector<TagView> tags;
  bool found_tags = false;
  uint8_t scope = kMixed;

  const char* p = type_end ? type_end + 1 : rest + rest_len;
  const char* end = line + len;
  while (p < end) {
    const char* next = static_cast<const char*>(memchr(p, '|', end - p));
    size_t sec_len = next ? static_cast<size_t>(next - p)
                          : static_cast<size_t>(end - p);
    if (sec_len == 0) return false;  // empty string between pipes
    if (p[0] == '@') {
      if (found_rate) return false;
      char tmp[32];
      if (sec_len - 1 == 0 || sec_len - 1 >= sizeof(tmp)) return false;
      memcpy(tmp, p + 1, sec_len - 1);
      tmp[sec_len - 1] = 0;
      char* endp = nullptr;
      double r = strtod(tmp, &endp);
      if (endp != tmp + sec_len - 1) return false;
      if (!(r > 0.0 && r <= 1.0)) return false;
      sample_rate = static_cast<float>(r);
      found_rate = true;
    } else if (p[0] == '#') {
      if (found_tags) return false;
      found_tags = true;
      const char* tp = p + 1;
      const char* tend = p + sec_len;
      while (tp <= tend) {
        const char* comma =
            static_cast<const char*>(memchr(tp, ',', tend - tp));
        size_t tlen = comma ? static_cast<size_t>(comma - tp)
                            : static_cast<size_t>(tend - tp);
        tags.push_back(TagView{tp, tlen});
        if (!comma) break;
        tp = comma + 1;
      }
      std::sort(tags.begin(), tags.end());
      // first-match scope-tag extraction (parser.go:326-342)
      for (size_t i = 0; i < tags.size(); i++) {
        bool local = has_prefix(tags[i], "veneurlocalonly", 15);
        bool global = has_prefix(tags[i], "veneurglobalonly", 16);
        if (local || global) {
          scope = local ? kLocalOnly : kGlobalOnly;
          tags.erase(tags.begin() + i);
          break;
        }
      }
      // heavy-hitter routing tag: stays in the tag list (and digest),
      // and only flips the scope byte for SETS — other types keep their
      // local/global scope even if the tag is present
      if (rtype == kSet) {
        for (size_t i = 0; i < tags.size(); i++) {
          if (tags[i].len == 10 &&
              memcmp(tags[i].p, "veneurtopk", 10) == 0) {
            scope = kTopK;
            break;
          }
        }
      }
    } else {
      return false;  // unknown section
    }
    p = next ? next + 1 : end;
    if (!next) break;
  }

  // write the record
  uint32_t noff = arena_put(b, line, name_len);
  if (noff == UINT32_MAX) return false;

  uint32_t h = fnv1a(line, name_len, kFnvInit);
  h = fnv1a(kTypeNames[rtype], kTypeNameLens[rtype], h);

  uint32_t toff = b->arena_len;
  uint32_t tlen = 0;
  if (found_tags) {
    for (size_t i = 0; i < tags.size(); i++) {
      if (i > 0) {
        if (arena_put(b, ",", 1) == UINT32_MAX) return false;
        tlen += 1;
      }
      if (arena_put(b, tags[i].p, tags[i].len) == UINT32_MAX) return false;
      tlen += static_cast<uint32_t>(tags[i].len);
    }
    h = fnv1a(b->arena + toff, tlen, h);
  }

  uint32_t aoff = 0, alen = 0;
  if (rtype == kSet) {
    aoff = arena_put(b, value_p, value_len);
    if (aoff == UINT32_MAX) return false;
    alen = static_cast<uint32_t>(value_len);
    // 64-bit member hash (FNV-1a core + murmur3 fmix64), bit-identical to
    // ops/hll.py hash_member; carried through the value slot's bit pattern
    uint64_t mh = 14695981039346656037ULL;
    for (size_t vi = 0; vi < value_len; vi++) {
      mh = (mh ^ static_cast<uint8_t>(value_p[vi])) * 1099511628211ULL;
    }
    mh ^= mh >> 33;
    mh *= 0xFF51AFD7ED558CCDULL;
    mh ^= mh >> 33;
    mh *= 0xC4CEB9FE1A85EC53ULL;
    mh ^= mh >> 33;
    memcpy(&value, &mh, sizeof(value));
  }

  b->type[idx] = rtype;
  b->scope[idx] = scope;
  b->value[idx] = value;
  b->sample_rate[idx] = sample_rate;
  b->digest[idx] = h;
  b->name_off[idx] = noff;
  b->name_len[idx] = static_cast<uint32_t>(name_len);
  b->tags_off[idx] = toff;
  b->tags_len[idx] = tlen;
  b->aux_off[idx] = aoff;
  b->aux_len[idx] = alen;
  b->count++;
  return true;
}

}  // namespace

// Split a buffer on '\n' and parse every non-empty line
// (split_bytes.go:17-56). Returns records appended.
extern "C" uint32_t vt_parse_lines(const char* buf, size_t len, VtBatch* b) {
  uint32_t before = b->count;
  const char* p = buf;
  const char* end = buf + len;
  while (p < end) {
    const char* nl = static_cast<const char*>(memchr(p, '\n', end - p));
    size_t line_len = nl ? static_cast<size_t>(nl - p)
                         : static_cast<size_t>(end - p);
    if (line_len > 0) {
      if (!parse_line(p, line_len, b)) b->parse_errors++;
    }
    p = nl ? nl + 1 : end;
  }
  return b->count - before;
}

// ---------------------------------------------------------------------------
// Framed-SSF scanner (protocol/wire.go:42-108)

// Scans `buf` for complete frames. Writes (offset,length) pairs of the
// protobuf payloads into out_off/out_len (up to out_cap). Returns the
// number of complete frames; *consumed is the byte count of whole frames
// scanned past; *poisoned is set on a framing error (bad version or
// oversized length) — the stream must be closed (wire.go:26-28).
extern "C" uint32_t vt_frame_scan(const char* buf, size_t len,
                                  uint32_t* out_off, uint32_t* out_len,
                                  uint32_t out_cap, size_t* consumed,
                                  int* poisoned) {
  constexpr size_t kMaxFrame = 16 * 1024 * 1024;
  uint32_t n = 0;
  size_t pos = 0;
  *poisoned = 0;
  while (n < out_cap && pos + 5 <= len) {
    if (buf[pos] != 0) {  // version byte (wire.go:31-40)
      *poisoned = 1;
      break;
    }
    uint32_t flen = (static_cast<uint32_t>(
                         static_cast<unsigned char>(buf[pos + 1])) << 24) |
                    (static_cast<uint32_t>(
                         static_cast<unsigned char>(buf[pos + 2])) << 16) |
                    (static_cast<uint32_t>(
                         static_cast<unsigned char>(buf[pos + 3])) << 8) |
                    static_cast<uint32_t>(
                        static_cast<unsigned char>(buf[pos + 4]));
    if (flen > kMaxFrame) {
      *poisoned = 1;
      break;
    }
    if (pos + 5 + flen > len) break;  // incomplete frame: wait for more
    out_off[n] = static_cast<uint32_t>(pos + 5);
    out_len[n] = flen;
    n++;
    pos += 5 + flen;
  }
  *consumed = pos;
  return n;
}

// ---------------------------------------------------------------------------
// Series interning table: (scope-class kind, name, tags) -> dense row id.
// The host-side hot hash path (string-keyed series -> row indices) that
// the reference pays inside map[MetricKey]*sampler lookups per sample
// (worker.go:96-157). The table only MEMOIZES rows assigned by the Python
// Interner: vt_intern_assign leaves unknown keys as misses (row =
// UINT32_MAX) for Python to resolve and teach back via vt_intern_put, so
// both sides always agree on row numbering.

namespace {

// scope-class kinds, mirroring veneur_tpu/core/store.py _K_* constants
inline uint8_t kind_of(uint8_t rtype, uint8_t scope) {
  switch (rtype) {
    case kCounter: return scope == kGlobalOnly ? 1 : 0;
    case kGauge: return scope == kGlobalOnly ? 3 : 2;
    case kHistogram: return scope == kLocalOnly ? 5 : 4;
    case kTimer: return scope == kLocalOnly ? 7 : 6;
    case kSet:
      if (scope == kTopK) return 10;  // heavy hitters
      return scope == kLocalOnly ? 9 : 8;
    default: return 255;  // raw
  }
}

struct InternEntry {
  uint64_t hash;
  uint32_t key_off;
  uint32_t key_len;
  uint32_t row;
  uint32_t used;
};

struct InternTable {
  InternEntry* slots;
  size_t cap;  // power of two
  size_t count;
  char* arena;
  size_t arena_len;
  size_t arena_cap;
};

inline uint64_t fnv1a64(const char* data, size_t len, uint64_t h) {
  for (size_t i = 0; i < len; i++) {
    h = (h ^ static_cast<unsigned char>(data[i])) * 1099511628211ULL;
  }
  return h;
}

inline uint64_t intern_hash(uint8_t kind, const char* name, size_t nlen,
                            const char* tags, size_t tlen) {
  uint64_t h = 14695981039346656037ULL;
  char k = static_cast<char>(kind);
  h = fnv1a64(&k, 1, h);
  h = fnv1a64(name, nlen, h);
  char sep = 0x1f;
  h = fnv1a64(&sep, 1, h);
  return fnv1a64(tags, tlen, h);
}

inline bool intern_key_eq(const InternTable* t, const InternEntry* e,
                          uint8_t kind, const char* name, size_t nlen,
                          const char* tags, size_t tlen) {
  if (e->key_len != 1 + nlen + 1 + tlen) return false;
  const char* k = t->arena + e->key_off;
  if (static_cast<uint8_t>(k[0]) != kind) return false;
  if (memcmp(k + 1, name, nlen) != 0) return false;
  if (k[1 + nlen] != 0x1f) return false;
  return memcmp(k + 2 + nlen, tags, tlen) == 0;
}

void intern_grow(InternTable* t) {
  size_t ncap = t->cap * 2;
  InternEntry* ns = static_cast<InternEntry*>(
      calloc(ncap, sizeof(InternEntry)));
  for (size_t i = 0; i < t->cap; i++) {
    InternEntry* e = &t->slots[i];
    if (!e->used) continue;
    size_t j = e->hash & (ncap - 1);
    while (ns[j].used) j = (j + 1) & (ncap - 1);
    ns[j] = *e;
  }
  free(t->slots);
  t->slots = ns;
  t->cap = ncap;
}

}  // namespace

extern "C" InternTable* vt_intern_new() {
  InternTable* t = new InternTable();
  t->cap = 1 << 12;
  t->slots = static_cast<InternEntry*>(calloc(t->cap, sizeof(InternEntry)));
  t->count = 0;
  t->arena_cap = 1 << 16;
  t->arena = static_cast<char*>(malloc(t->arena_cap));
  t->arena_len = 0;
  return t;
}

extern "C" void vt_intern_free(InternTable* t) {
  free(t->slots);
  free(t->arena);
  delete t;
}

// Flush-time reset: rows restart from zero (the Python interners were
// swapped out), allocations are kept.
extern "C" void vt_intern_reset(InternTable* t) {
  memset(t->slots, 0, t->cap * sizeof(InternEntry));
  t->count = 0;
  t->arena_len = 0;
}

extern "C" void vt_intern_put(InternTable* t, uint8_t kind,
                              const char* name, uint32_t nlen,
                              const char* tags, uint32_t tlen,
                              uint32_t row) {
  if (t->count * 10 >= t->cap * 7) intern_grow(t);
  uint64_t h = intern_hash(kind, name, nlen, tags, tlen);
  size_t j = h & (t->cap - 1);
  while (t->slots[j].used) {
    InternEntry* e = &t->slots[j];
    if (e->hash == h && intern_key_eq(t, e, kind, name, nlen, tags, tlen)) {
      e->row = row;  // overwrite (python is authoritative)
      return;
    }
    j = (j + 1) & (t->cap - 1);
  }
  size_t klen = 1 + nlen + 1 + tlen;
  if (t->arena_len + klen > t->arena_cap) {
    while (t->arena_len + klen > t->arena_cap) t->arena_cap *= 2;
    t->arena = static_cast<char*>(realloc(t->arena, t->arena_cap));
  }
  char* k = t->arena + t->arena_len;
  k[0] = static_cast<char>(kind);
  memcpy(k + 1, name, nlen);
  k[1 + nlen] = 0x1f;
  memcpy(k + 2 + nlen, tags, tlen);
  InternEntry* e = &t->slots[j];
  e->hash = h;
  e->key_off = static_cast<uint32_t>(t->arena_len);
  e->key_len = static_cast<uint32_t>(klen);
  e->row = row;
  e->used = 1;
  t->arena_len += klen;
  t->count++;
}

// For every record: out_kinds[i] = scope-class kind (255 for raw),
// out_rows[i] = memoized row or UINT32_MAX on miss. Miss record indices
// are appended to out_miss; returns the miss count.
extern "C" uint32_t vt_intern_assign(InternTable* t, const VtBatch* b,
                                     uint32_t* out_rows, uint8_t* out_kinds,
                                     uint32_t* out_miss) {
  uint32_t nmiss = 0;
  for (uint32_t i = 0; i < b->count; i++) {
    uint8_t kind = kind_of(b->type[i], b->scope[i]);
    out_kinds[i] = kind;
    if (kind == 255) {
      out_rows[i] = UINT32_MAX;
      continue;
    }
    const char* name = b->arena + b->name_off[i];
    size_t nlen = b->name_len[i];
    const char* tags = b->arena + b->tags_off[i];
    size_t tlen = b->tags_len[i];
    uint64_t h = intern_hash(kind, name, nlen, tags, tlen);
    size_t j = h & (t->cap - 1);
    uint32_t row = UINT32_MAX;
    while (t->slots[j].used) {
      InternEntry* e = &t->slots[j];
      if (e->hash == h &&
          intern_key_eq(t, e, kind, name, nlen, tags, tlen)) {
        row = e->row;
        break;
      }
      j = (j + 1) & (t->cap - 1);
    }
    out_rows[i] = row;
    if (row == UINT32_MAX) out_miss[nmiss++] = i;
  }
  return nmiss;
}

// ---------------------------------------------------------------------------
// SO_REUSEPORT UDP reader pool (networking.go:37-87, socket_linux.go:12-76)

namespace {

struct Reader {
  int fd = -1;
  std::thread thread;
  std::mutex mu;
  VtBatch* active;   // parser writes here under mu
  VtBatch* standby;  // handed to Python on swap
  std::atomic<uint64_t> packets{0};
  std::atomic<uint64_t> dropped_batches{0};
};

struct ReaderPool {
  std::vector<Reader*> readers;
  std::atomic<bool> stop{false};
  int port = 0;
};

int make_udp_socket(const char* ip, int port, int rcvbuf) {
  int fd = socket(AF_INET, SOCK_DGRAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  // SO_REUSEPORT kernel load-balancing (socket_linux.go:25-31)
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  setsockopt(fd, SOL_SOCKET, SO_REUSEPORT, &one, sizeof(one));
  if (rcvbuf > 0) {
    setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &rcvbuf, sizeof(rcvbuf));
  }
  sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  addr.sin_addr.s_addr = ip && *ip ? inet_addr(ip) : INADDR_ANY;
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    close(fd);
    return -1;
  }
  return fd;
}

constexpr int kVlen = 64;  // datagrams per recvmmsg

void reader_loop(ReaderPool* pool, Reader* r, int dgram_max) {
  std::vector<char> bufs(static_cast<size_t>(kVlen) * dgram_max);
  mmsghdr msgs[kVlen];
  iovec iovs[kVlen];
  for (int i = 0; i < kVlen; i++) {
    iovs[i].iov_base = bufs.data() + static_cast<size_t>(i) * dgram_max;
    iovs[i].iov_len = dgram_max;
    memset(&msgs[i], 0, sizeof(mmsghdr));
    msgs[i].msg_hdr.msg_iov = &iovs[i];
    msgs[i].msg_hdr.msg_iovlen = 1;
  }
  pollfd pfd = {r->fd, POLLIN, 0};
  while (!pool->stop.load(std::memory_order_relaxed)) {
    int pr = poll(&pfd, 1, 100);
    if (pr <= 0) continue;
    int got = recvmmsg(r->fd, msgs, kVlen, MSG_DONTWAIT, nullptr);
    if (got <= 0) continue;
    std::lock_guard<std::mutex> lock(r->mu);
    for (int i = 0; i < got; i++) {
      const char* data = bufs.data() + static_cast<size_t>(i) * dgram_max;
      size_t dlen = msgs[i].msg_len;
      if (r->active->count >= r->active->capacity ||
          r->active->arena_len + dlen > r->active->arena_cap) {
        // batch full and Python hasn't swapped: drop the datagram
        // (the kernel socket buffer is the real backpressure here,
        // like the reference's packet drops under overload)
        r->dropped_batches.fetch_add(1, std::memory_order_relaxed);
        continue;
      }
      vt_parse_lines(data, dlen, r->active);
    }
    r->packets.fetch_add(got, std::memory_order_relaxed);
  }
}

}  // namespace

extern "C" void* vt_reader_start(const char* ip, int port, int nreaders,
                                 int rcvbuf, uint32_t batch_records,
                                 uint32_t batch_arena, int dgram_max) {
  if (dgram_max <= 0) dgram_max = 8192;
  ReaderPool* pool = new ReaderPool();
  for (int i = 0; i < nreaders; i++) {
    int fd = make_udp_socket(ip, port, rcvbuf);
    if (fd < 0) {
      // threads are not started yet: release every reader created so far
      for (Reader* r : pool->readers) {
        close(r->fd);
        vt_batch_free(r->active);
        vt_batch_free(r->standby);
        delete r;
      }
      delete pool;
      return nullptr;
    }
    if (pool->port == 0) {
      sockaddr_in bound;
      socklen_t blen = sizeof(bound);
      getsockname(fd, reinterpret_cast<sockaddr*>(&bound), &blen);
      pool->port = ntohs(bound.sin_port);
      port = pool->port;  // later readers share the resolved port
    }
    Reader* r = new Reader();
    r->fd = fd;
    r->active = vt_batch_new(batch_records, batch_arena);
    r->standby = vt_batch_new(batch_records, batch_arena);
    pool->readers.push_back(r);
  }
  for (Reader* r : pool->readers) {
    r->thread = std::thread(reader_loop, pool, r, dgram_max);
  }
  return pool;
}

extern "C" int vt_reader_port(void* handle) {
  return static_cast<ReaderPool*>(handle)->port;
}

extern "C" int vt_reader_count(void* handle) {
  return static_cast<int>(static_cast<ReaderPool*>(handle)->readers.size());
}

// Swap a reader's active batch for its (reset) standby and return the
// filled batch. Python owns the returned pointer until the next swap of
// the same reader.
extern "C" VtBatch* vt_reader_swap(void* handle, int idx) {
  ReaderPool* pool = static_cast<ReaderPool*>(handle);
  Reader* r = pool->readers[idx];
  std::lock_guard<std::mutex> lock(r->mu);
  VtBatch* filled = r->active;
  vt_batch_reset(r->standby);
  r->active = r->standby;
  r->standby = filled;
  return filled;
}

extern "C" uint64_t vt_reader_packets(void* handle, int idx) {
  return static_cast<ReaderPool*>(handle)
      ->readers[idx]->packets.load(std::memory_order_relaxed);
}

extern "C" uint64_t vt_reader_drops(void* handle, int idx) {
  return static_cast<ReaderPool*>(handle)
      ->readers[idx]->dropped_batches.load(std::memory_order_relaxed);
}

extern "C" void vt_reader_stop(void* handle) {
  ReaderPool* pool = static_cast<ReaderPool*>(handle);
  pool->stop.store(true);
  for (Reader* r : pool->readers) {
    if (r->thread.joinable()) r->thread.join();
    close(r->fd);
    vt_batch_free(r->active);
    vt_batch_free(r->standby);
    delete r;
  }
  delete pool;
}

// ---------------------------------------------------------------------------
// SSF span batch lane (server.go:827-899, ssf/sample.proto)
//
// UDP SSF datagrams each carry one bare SSFSpan protobuf. The Python
// path decodes them one ParseFromString at a time on the reader thread
// — the round-4 verdict's last hot ingest lane without a batch twin.
// Here the reader pool decodes spans on its C++ threads (off the GIL)
// into a struct-of-arrays span batch whose EMBEDDED METRICS are
// appended directly as VtBatch records, bit-identical to the Python
// parse_metric_ssf conversion (parser.py:198-233 / parser.go:179-230):
// "k:v" tags sorted bytewise, exact-key veneurlocalonly/globalonly
// scope extraction, fnv1a(name+type+joined-tags) digest, set members
// hashed with the FNV+fmix64 member hash. Indicator spans synthesize
// the configured duration timer natively (parser.go:94-121). STATUS
// samples (rare control-plane) and undecodable samples are surfaced as
// raw byte ranges for the Python slow lane. The raw span bytes stay in
// the arena so Python can materialize the full protobuf lazily for
// span sinks that need it.

namespace {

// minimal proto3 walker (same shape as veneur_egress.cpp's Cursor —
// the two .so files are compiled standalone, so a local copy)
struct PbCursor {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;

  uint64_t varint() {
    uint64_t v = 0;
    int shift = 0;
    while (p < end && shift < 64) {
      uint8_t b = *p++;
      v |= static_cast<uint64_t>(b & 0x7F) << shift;
      if (!(b & 0x80)) return v;
      shift += 7;
    }
    ok = false;
    return 0;
  }
  uint32_t fixed32() {
    if (end - p < 4) { ok = false; return 0; }
    uint32_t v;
    memcpy(&v, p, 4);
    p += 4;
    return v;
  }
  float f32() {
    uint32_t v = fixed32();
    float f;
    memcpy(&f, &v, 4);
    return f;
  }
  uint32_t tag() {
    if (p >= end) return 0;
    uint64_t t = varint();
    return ok ? static_cast<uint32_t>(t) : 0;
  }
  PbCursor sub() {
    uint64_t n = varint();
    if (!ok || static_cast<uint64_t>(end - p) < n) {
      ok = false;
      return {p, p};
    }
    PbCursor c{p, p + n};
    p += n;
    return c;
  }
  void skip(uint32_t wire_type) {
    switch (wire_type) {
      case 0: varint(); break;
      case 1: if (end - p >= 8) p += 8; else ok = false; break;
      case 2: {
        uint64_t n = varint();
        if (ok && static_cast<uint64_t>(end - p) >= n) p += n;
        else ok = false;
        break;
      }
      case 5: if (end - p >= 4) p += 4; else ok = false; break;
      default: ok = false;
    }
  }
};

}  // namespace

// Decoded span batch. Span string fields (service/name) are offsets into
// `arena`, pointing INSIDE the span's raw bytes (raw_off/raw_len), which
// hold the whole datagram for lazy full-protobuf materialization.
// Embedded metric samples land in `metrics` as ordinary parsed records.
extern "C" struct VsBatch {
  uint32_t capacity;
  uint32_t count;
  uint32_t arena_cap;
  uint32_t arena_len;
  uint64_t decode_errors;    // undecodable datagrams
  uint64_t invalid_samples;  // samples failing parse_metric_ssf validity
  int32_t* version;
  int64_t* trace_id;
  int64_t* span_id;
  int64_t* parent_id;
  int64_t* start_ns;
  int64_t* end_ns;
  uint8_t* error;
  uint8_t* indicator;
  uint32_t* service_off;
  uint32_t* service_len;
  uint32_t* name_off;
  uint32_t* name_len;
  uint32_t* raw_off;
  uint32_t* raw_len;
  char* arena;
  VtBatch* metrics;
  // slow lane: STATUS / otherwise Python-only samples, raw bytes
  uint32_t slow_cap;
  uint32_t slow_count;
  uint32_t* slow_off;
  uint32_t* slow_len;
};

extern "C" VsBatch* vs_batch_new(uint32_t spans_cap, uint32_t arena_cap,
                                 uint32_t metric_cap,
                                 uint32_t metric_arena_cap) {
  VsBatch* b = static_cast<VsBatch*>(calloc(1, sizeof(VsBatch)));
  b->capacity = spans_cap;
  b->arena_cap = arena_cap;
  b->version = static_cast<int32_t*>(malloc(spans_cap * 4));
  b->trace_id = static_cast<int64_t*>(malloc(spans_cap * 8));
  b->span_id = static_cast<int64_t*>(malloc(spans_cap * 8));
  b->parent_id = static_cast<int64_t*>(malloc(spans_cap * 8));
  b->start_ns = static_cast<int64_t*>(malloc(spans_cap * 8));
  b->end_ns = static_cast<int64_t*>(malloc(spans_cap * 8));
  b->error = static_cast<uint8_t*>(malloc(spans_cap));
  b->indicator = static_cast<uint8_t*>(malloc(spans_cap));
  b->service_off = static_cast<uint32_t*>(malloc(spans_cap * 4));
  b->service_len = static_cast<uint32_t*>(malloc(spans_cap * 4));
  b->name_off = static_cast<uint32_t*>(malloc(spans_cap * 4));
  b->name_len = static_cast<uint32_t*>(malloc(spans_cap * 4));
  b->raw_off = static_cast<uint32_t*>(malloc(spans_cap * 4));
  b->raw_len = static_cast<uint32_t*>(malloc(spans_cap * 4));
  b->arena = static_cast<char*>(malloc(arena_cap));
  b->metrics = vt_batch_new(metric_cap, metric_arena_cap);
  b->slow_cap = spans_cap;
  b->slow_off = static_cast<uint32_t*>(malloc(b->slow_cap * 4));
  b->slow_len = static_cast<uint32_t*>(malloc(b->slow_cap * 4));
  return b;
}

extern "C" void vs_batch_free(VsBatch* b) {
  if (!b) return;
  free(b->version); free(b->trace_id); free(b->span_id);
  free(b->parent_id); free(b->start_ns); free(b->end_ns);
  free(b->error); free(b->indicator);
  free(b->service_off); free(b->service_len);
  free(b->name_off); free(b->name_len);
  free(b->raw_off); free(b->raw_len);
  free(b->arena);
  vt_batch_free(b->metrics);
  free(b->slow_off); free(b->slow_len);
  free(b);
}

extern "C" void vs_batch_reset(VsBatch* b) {
  b->count = 0;
  b->arena_len = 0;
  b->decode_errors = 0;
  b->invalid_samples = 0;
  b->slow_count = 0;
  vt_batch_reset(b->metrics);
}

namespace {

inline uint32_t vs_arena_put(VsBatch* b, const char* data, size_t len) {
  if (b->arena_len + len > b->arena_cap) return UINT32_MAX;
  memcpy(b->arena + b->arena_len, data, len);
  uint32_t off = b->arena_len;
  b->arena_len += static_cast<uint32_t>(len);
  return off;
}

// Append one decoded SSFSample as a parsed metric record, mirroring
// parse_metric_ssf + valid_metric (parser.py:198-238). Returns false
// only when the metrics batch/arena is full (caller drops the batch
// accounting); invalid samples bump the counter and "succeed".
bool append_ssf_sample(VsBatch* vb, uint32_t sample_metric,
                       const char* name_p, size_t name_n,
                       float value, float sample_rate,
                       const char* member_p, size_t member_n,
                       const std::vector<std::string>& kv_tags) {
  VtBatch* mb = vb->metrics;
  uint8_t rtype;
  switch (sample_metric) {
    case 0: rtype = kCounter; break;
    case 1: rtype = kGauge; break;
    case 2: rtype = kHistogram; break;
    case 3: rtype = kSet; break;
    default:
      // unknown enum: parse error in the Python path too
      vb->invalid_samples++;
      return true;
  }
  if (name_n == 0 || (rtype == kSet && member_n == 0)) {
    vb->invalid_samples++;  // valid_metric: name and value required
    return true;
  }
  if (mb->count >= mb->capacity) return false;
  uint32_t idx = mb->count;

  // exact-key scope extraction; every matching key is removed and the
  // LAST one seen wins, matching the dict iteration in parser.py:215-222
  uint8_t scope = kMixed;
  std::vector<const std::string*> keep;
  keep.reserve(kv_tags.size());
  for (const std::string& kv : kv_tags) {
    size_t colon = kv.find(':');
    size_t klen = colon == std::string::npos ? kv.size() : colon;
    if (klen == 15 && memcmp(kv.data(), "veneurlocalonly", 15) == 0) {
      scope = kLocalOnly;
      continue;
    }
    if (klen == 16 && memcmp(kv.data(), "veneurglobalonly", 16) == 0) {
      scope = kGlobalOnly;
      continue;
    }
    keep.push_back(&kv);
  }
  std::sort(keep.begin(), keep.end(),
            [](const std::string* a, const std::string* b) {
              return *a < *b;
            });
  if (rtype == kSet) {
    for (const std::string* kv : keep) {
      // the SSF "k:v" encoding makes the tag "veneurtopk:<value>";
      // match the KEY (parser.py parse_metric_ssf does the same)
      if (kv->size() >= 10 && memcmp(kv->data(), "veneurtopk", 10) == 0 &&
          (kv->size() == 10 || (*kv)[10] == ':')) {
        scope = kTopK;
        break;
      }
    }
  }

  uint32_t noff = arena_put(mb, name_p, name_n);
  if (noff == UINT32_MAX) return false;
  uint32_t h = fnv1a(name_p, name_n, kFnvInit);
  h = fnv1a(kTypeNames[rtype], kTypeNameLens[rtype], h);

  uint32_t toff = mb->arena_len;
  uint32_t tlen = 0;
  for (size_t i = 0; i < keep.size(); i++) {
    if (i > 0) {
      if (arena_put(mb, ",", 1) == UINT32_MAX) return false;
      tlen += 1;
    }
    if (arena_put(mb, keep[i]->data(), keep[i]->size()) == UINT32_MAX)
      return false;
    tlen += static_cast<uint32_t>(keep[i]->size());
  }
  h = fnv1a(mb->arena + toff, tlen, h);

  double dvalue = static_cast<double>(value);
  uint32_t aoff = 0, alen = 0;
  if (rtype == kSet) {
    aoff = arena_put(mb, member_p, member_n);
    if (aoff == UINT32_MAX) return false;
    alen = static_cast<uint32_t>(member_n);
    uint64_t mh = 14695981039346656037ULL;
    for (size_t vi = 0; vi < member_n; vi++) {
      mh = (mh ^ static_cast<uint8_t>(member_p[vi])) * 1099511628211ULL;
    }
    mh ^= mh >> 33;
    mh *= 0xFF51AFD7ED558CCDULL;
    mh ^= mh >> 33;
    mh *= 0xC4CEB9FE1A85EC53ULL;
    mh ^= mh >> 33;
    memcpy(&dvalue, &mh, sizeof(dvalue));
  }

  mb->type[idx] = rtype;
  mb->scope[idx] = scope;
  mb->value[idx] = dvalue;
  mb->sample_rate[idx] = sample_rate;
  mb->digest[idx] = h;
  mb->name_off[idx] = noff;
  mb->name_len[idx] = static_cast<uint32_t>(name_n);
  mb->tags_off[idx] = toff;
  mb->tags_len[idx] = tlen;
  mb->aux_off[idx] = aoff;
  mb->aux_len[idx] = alen;
  mb->count++;
  return true;
}

}  // namespace

// Decode one SSFSpan datagram into the batch. Returns 1 on success,
// 0 when the batch is full or the bytes are not a decodable span (the
// caller distinguishes via decode_errors).
extern "C" int vs_decode_span(const char* data, size_t len, VsBatch* b,
                              const char* ind_name, uint32_t ind_len) {
  if (b->count >= b->capacity) return 0;
  uint32_t roff = vs_arena_put(b, data, len);
  if (roff == UINT32_MAX) return 0;

  uint32_t idx = b->count;
  int32_t version = 0;
  int64_t trace_id = 0, span_id = 0, parent_id = 0, start_ns = 0,
          end_ns = 0;
  uint8_t err = 0, indicator = 0;
  uint32_t svc_off = 0, svc_len = 0, nm_off = 0, nm_len = 0;

  const uint8_t* base = reinterpret_cast<const uint8_t*>(data);
  PbCursor c{base, base + len};
  // sample submessage ranges, decoded after the span header so the
  // indicator synthesis has service/error available
  std::vector<std::pair<uint32_t, uint32_t>> samples;
  while (c.ok) {
    uint32_t t = c.tag();
    if (t == 0) break;
    uint32_t field = t >> 3, wt = t & 7;
    switch (field) {
      case 1: if (wt == 0) version = static_cast<int32_t>(c.varint());
              else c.skip(wt); break;
      case 2: if (wt == 0) trace_id = static_cast<int64_t>(c.varint());
              else c.skip(wt); break;
      case 3: if (wt == 0) span_id = static_cast<int64_t>(c.varint());
              else c.skip(wt); break;
      case 4: if (wt == 0) parent_id = static_cast<int64_t>(c.varint());
              else c.skip(wt); break;
      case 5: if (wt == 0) start_ns = static_cast<int64_t>(c.varint());
              else c.skip(wt); break;
      case 6: if (wt == 0) end_ns = static_cast<int64_t>(c.varint());
              else c.skip(wt); break;
      case 7: if (wt == 0) err = c.varint() ? 1 : 0;
              else c.skip(wt); break;
      case 8: {
        if (wt != 2) { c.skip(wt); break; }
        PbCursor s = c.sub();
        svc_off = roff + static_cast<uint32_t>(s.p - base);
        svc_len = static_cast<uint32_t>(s.end - s.p);
        break;
      }
      case 10: {
        if (wt != 2) { c.skip(wt); break; }
        PbCursor s = c.sub();
        samples.emplace_back(static_cast<uint32_t>(s.p - base),
                             static_cast<uint32_t>(s.end - s.p));
        break;
      }
      case 12: if (wt == 0) indicator = c.varint() ? 1 : 0;
               else c.skip(wt); break;
      case 13: {
        if (wt != 2) { c.skip(wt); break; }
        PbCursor s = c.sub();
        nm_off = roff + static_cast<uint32_t>(s.p - base);
        nm_len = static_cast<uint32_t>(s.end - s.p);
        break;
      }
      default: c.skip(wt); break;
    }
  }
  if (!c.ok) {
    b->arena_len = roff;  // roll back the raw copy
    b->decode_errors++;
    return 0;
  }

  // embedded samples -> metric records (STATUS and broken samples go
  // to the Python slow lane as raw bytes)
  for (const auto& [soff, slen] : samples) {
    PbCursor s{base + soff, base + soff + slen};
    uint32_t metric = 0;
    const char* name_p = nullptr;
    size_t name_n = 0;
    // absent sample_rate (proto3 default 0) means unsampled: weight
    // 1.0, never 1/0 (matches parser.py parse_metric_ssf)
    float value = 0.0f, rate = 0.0f;
    const char* member_p = nullptr;
    size_t member_n = 0;
    std::vector<std::string> kv_tags;
    bool slow = false;
    while (s.ok) {
      uint32_t t = s.tag();
      if (t == 0) break;
      uint32_t field = t >> 3, wt = t & 7;
      switch (field) {
        case 1: if (wt == 0) metric = static_cast<uint32_t>(s.varint());
                else s.skip(wt); break;
        case 2: {
          if (wt != 2) { s.skip(wt); break; }
          PbCursor ss = s.sub();
          name_p = reinterpret_cast<const char*>(ss.p);
          name_n = ss.end - ss.p;
          break;
        }
        case 3: if (wt == 5) value = s.f32(); else s.skip(wt); break;
        case 5: {
          if (wt != 2) { s.skip(wt); break; }
          PbCursor ss = s.sub();
          member_p = reinterpret_cast<const char*>(ss.p);
          member_n = ss.end - ss.p;
          break;
        }
        case 7: if (wt == 5) rate = s.f32(); else s.skip(wt); break;
        case 8: {
          if (wt != 2) { s.skip(wt); break; }
          PbCursor entry = s.sub();
          const char* kp = nullptr; size_t kn = 0;
          const char* vp = nullptr; size_t vn = 0;
          while (entry.ok) {
            uint32_t et = entry.tag();
            if (et == 0) break;
            uint32_t ef = et >> 3, ew = et & 7;
            if (ef == 1 && ew == 2) {
              PbCursor ks = entry.sub();
              kp = reinterpret_cast<const char*>(ks.p);
              kn = ks.end - ks.p;
            } else if (ef == 2 && ew == 2) {
              PbCursor vs = entry.sub();
              vp = reinterpret_cast<const char*>(vs.p);
              vn = vs.end - vs.p;
            } else {
              entry.skip(ew);
            }
          }
          std::string kv;
          kv.reserve(kn + 1 + vn);
          kv.append(kp ? kp : "", kn);
          kv.push_back(':');
          kv.append(vp ? vp : "", vn);
          kv_tags.push_back(std::move(kv));
          break;
        }
        default: s.skip(wt); break;
      }
    }
    if (!s.ok || metric == 4 || metric > 4) {
      // STATUS (needs the status enum + message) or undecodable:
      // Python slow lane on the raw sample bytes
      slow = true;
    }
    if (slow) {
      if (b->slow_count < b->slow_cap) {
        b->slow_off[b->slow_count] = roff + soff;
        b->slow_len[b->slow_count] = slen;
        b->slow_count++;
      } else {
        b->invalid_samples++;
      }
      continue;
    }
    if (rate <= 0.0f) rate = 1.0f;
    if (!append_ssf_sample(b, metric, name_p, name_n, value, rate,
                           member_p, member_n, kv_tags)) {
      // metrics batch full: surface the sample on the slow lane rather
      // than dropping it silently
      if (b->slow_count < b->slow_cap) {
        b->slow_off[b->slow_count] = roff + soff;
        b->slow_len[b->slow_count] = slen;
        b->slow_count++;
      } else {
        b->invalid_samples++;
      }
    }
  }

  // indicator duration timer (parser.go:94-121): HISTOGRAM ns duration
  // tagged error:bool + service, unit ns, rate 1.0
  if (indicator && ind_len > 0) {
    std::vector<std::string> tags;
    std::string et("error:");
    et += err ? "true" : "false";
    tags.push_back(std::move(et));
    std::string st("service:");
    st.append(b->arena + svc_off, svc_len);
    tags.push_back(std::move(st));
    double dur = static_cast<double>(end_ns - start_ns);
    // append via the shared helper; value passes through float, which
    // would truncate long durations — write the record directly
    VtBatch* mb = b->metrics;
    if (mb->count < mb->capacity) {
      uint32_t mi = mb->count;
      uint32_t noff2 = arena_put(mb, ind_name, ind_len);
      uint32_t toff2 = mb->arena_len;
      uint32_t tlen2 = 0;
      bool okp = noff2 != UINT32_MAX;
      for (size_t i = 0; okp && i < tags.size(); i++) {
        if (i > 0) {
          okp = arena_put(mb, ",", 1) != UINT32_MAX;
          tlen2 += 1;
        }
        if (okp) {
          okp = arena_put(mb, tags[i].data(), tags[i].size())
                != UINT32_MAX;
          tlen2 += static_cast<uint32_t>(tags[i].size());
        }
      }
      if (okp) {
        uint32_t h = fnv1a(ind_name, ind_len, kFnvInit);
        h = fnv1a(kTypeNames[kHistogram], kTypeNameLens[kHistogram], h);
        h = fnv1a(mb->arena + toff2, tlen2, h);
        mb->type[mi] = kHistogram;
        mb->scope[mi] = kMixed;
        mb->value[mi] = dur;
        mb->sample_rate[mi] = 1.0f;
        mb->digest[mi] = h;
        mb->name_off[mi] = noff2;
        mb->name_len[mi] = ind_len;
        mb->tags_off[mi] = toff2;
        mb->tags_len[mi] = tlen2;
        mb->aux_off[mi] = 0;
        mb->aux_len[mi] = 0;
        mb->count++;
      }
    }
  }

  b->version[idx] = version;
  b->trace_id[idx] = trace_id;
  b->span_id[idx] = span_id;
  b->parent_id[idx] = parent_id;
  b->start_ns[idx] = start_ns;
  b->end_ns[idx] = end_ns;
  b->error[idx] = err;
  b->indicator[idx] = indicator;
  b->service_off[idx] = svc_off;
  b->service_len[idx] = svc_len;
  b->name_off[idx] = nm_off;
  b->name_len[idx] = nm_len;
  b->raw_off[idx] = roff;
  b->raw_len[idx] = static_cast<uint32_t>(len);
  b->count++;
  return 1;
}

// ---------------------------------------------------------------------------
// SSF reader pool: same recvmmsg/SO_REUSEPORT shape as the metric pool,
// but each datagram decodes as one SSFSpan on the reader thread.

namespace {

struct SsfReader {
  int fd = -1;
  std::thread thread;
  std::mutex mu;
  VsBatch* active;
  VsBatch* standby;
  std::atomic<uint64_t> packets{0};
  std::atomic<uint64_t> dropped_batches{0};
};

struct SsfReaderPool {
  std::vector<SsfReader*> readers;
  std::atomic<bool> stop{false};
  int port = 0;
  std::string indicator_name;
};

void ssf_reader_loop(SsfReaderPool* pool, SsfReader* r, int dgram_max) {
  std::vector<char> bufs(static_cast<size_t>(kVlen) * dgram_max);
  mmsghdr msgs[kVlen];
  iovec iovs[kVlen];
  for (int i = 0; i < kVlen; i++) {
    iovs[i].iov_base = bufs.data() + static_cast<size_t>(i) * dgram_max;
    iovs[i].iov_len = dgram_max;
    memset(&msgs[i], 0, sizeof(mmsghdr));
    msgs[i].msg_hdr.msg_iov = &iovs[i];
    msgs[i].msg_hdr.msg_iovlen = 1;
  }
  const char* ind = pool->indicator_name.c_str();
  uint32_t ind_len = static_cast<uint32_t>(pool->indicator_name.size());
  pollfd pfd = {r->fd, POLLIN, 0};
  while (!pool->stop.load(std::memory_order_relaxed)) {
    int pr = poll(&pfd, 1, 100);
    if (pr <= 0) continue;
    int got = recvmmsg(r->fd, msgs, kVlen, MSG_DONTWAIT, nullptr);
    if (got <= 0) continue;
    std::lock_guard<std::mutex> lock(r->mu);
    for (int i = 0; i < got; i++) {
      const char* data = bufs.data() + static_cast<size_t>(i) * dgram_max;
      size_t dlen = msgs[i].msg_len;
      VsBatch* b = r->active;
      if (b->count >= b->capacity ||
          b->arena_len + dlen > b->arena_cap ||
          b->metrics->count + 8 > b->metrics->capacity) {
        // batch full and Python hasn't swapped: shed, like the metric
        // pool (the kernel socket buffer is the real backpressure)
        r->dropped_batches.fetch_add(1, std::memory_order_relaxed);
        continue;
      }
      vs_decode_span(data, dlen, b, ind, ind_len);
    }
    r->packets.fetch_add(got, std::memory_order_relaxed);
  }
}

}  // namespace

extern "C" void* vs_reader_start(const char* ip, int port, int nreaders,
                                 int rcvbuf, uint32_t span_cap,
                                 uint32_t arena_cap, uint32_t metric_cap,
                                 uint32_t metric_arena, int dgram_max,
                                 const char* ind_name) {
  if (dgram_max <= 0) dgram_max = 8192;
  SsfReaderPool* pool = new SsfReaderPool();
  pool->indicator_name = ind_name ? ind_name : "";
  for (int i = 0; i < nreaders; i++) {
    int fd = make_udp_socket(ip, port, rcvbuf);
    if (fd < 0) {
      for (SsfReader* r : pool->readers) {
        close(r->fd);
        vs_batch_free(r->active);
        vs_batch_free(r->standby);
        delete r;
      }
      delete pool;
      return nullptr;
    }
    if (pool->port == 0) {
      sockaddr_in bound;
      socklen_t blen = sizeof(bound);
      getsockname(fd, reinterpret_cast<sockaddr*>(&bound), &blen);
      pool->port = ntohs(bound.sin_port);
      port = pool->port;
    }
    SsfReader* r = new SsfReader();
    r->fd = fd;
    r->active = vs_batch_new(span_cap, arena_cap, metric_cap,
                             metric_arena);
    r->standby = vs_batch_new(span_cap, arena_cap, metric_cap,
                              metric_arena);
    pool->readers.push_back(r);
  }
  for (SsfReader* r : pool->readers) {
    r->thread = std::thread(ssf_reader_loop, pool, r, dgram_max);
  }
  return pool;
}

extern "C" int vs_reader_port(void* handle) {
  return static_cast<SsfReaderPool*>(handle)->port;
}

extern "C" int vs_reader_count(void* handle) {
  return static_cast<int>(
      static_cast<SsfReaderPool*>(handle)->readers.size());
}

extern "C" VsBatch* vs_reader_swap(void* handle, int idx) {
  SsfReaderPool* pool = static_cast<SsfReaderPool*>(handle);
  SsfReader* r = pool->readers[idx];
  std::lock_guard<std::mutex> lock(r->mu);
  VsBatch* filled = r->active;
  vs_batch_reset(r->standby);
  r->active = r->standby;
  r->standby = filled;
  return filled;
}

extern "C" uint64_t vs_reader_packets(void* handle, int idx) {
  return static_cast<SsfReaderPool*>(handle)
      ->readers[idx]->packets.load(std::memory_order_relaxed);
}

extern "C" uint64_t vs_reader_drops(void* handle, int idx) {
  return static_cast<SsfReaderPool*>(handle)
      ->readers[idx]->dropped_batches.load(std::memory_order_relaxed);
}

extern "C" void vs_reader_stop(void* handle) {
  SsfReaderPool* pool = static_cast<SsfReaderPool*>(handle);
  pool->stop.store(true);
  for (SsfReader* r : pool->readers) {
    if (r->thread.joinable()) r->thread.join();
    close(r->fd);
    vs_batch_free(r->active);
    vs_batch_free(r->standby);
    delete r;
  }
  delete pool;
}

// ---------------------------------------------------------------------------
// Native TCP/TLS statsd listener (server.go:901-1001 + the TLS config of
// server.go:314-348, rebuilt native)
//
// The Python TLS accept path tops out well under the reference's
// published ~700 conn/s (ECDH prime256v1, localhost, one CPU): OpenSSL
// 3.0's per-connection setup plus the Python ssl-module wrapper and
// per-connection thread spawn eat the budget. This listener terminates
// TLS in C++ — accept, handshake, newline framing and DogStatsD parsing
// all happen off the GIL, feeding the same VtBatch swap protocol the
// UDP pool uses (one Python FFI drain per batch).
//
// libssl is loaded at runtime with dlopen/dlsym against the stable
// OpenSSL 3 C ABI (the image ships libssl.so.3 but no headers); when
// the library or a symbol is missing, vt_tls_available() reports 0 and
// Python keeps its own TLS path. Client-cert auth mirrors
// make_server_tls_context: a CA path turns on required verification.
// Session tickets are disabled: statsd TLS clients hold connections
// long-term, and full-handshake capacity (the number the reference
// publishes) beats resumption for reconnect storms.

#include <dlfcn.h>

namespace {

// --- minimal OpenSSL 3 ABI (stable exported C symbols) ---
struct OsslApi {
  void* ssl_handle = nullptr;
  void* crypto_handle = nullptr;
  const void* (*TLS_server_method)();
  void* (*SSL_CTX_new)(const void*);
  void (*SSL_CTX_free)(void*);
  int (*SSL_CTX_use_certificate_chain_file)(void*, const char*);
  int (*SSL_CTX_use_PrivateKey_file)(void*, const char*, int);
  int (*SSL_CTX_check_private_key)(const void*);
  void (*SSL_CTX_set_verify)(void*, int, void*);
  int (*SSL_CTX_load_verify_locations)(void*, const char*, const char*);
  int (*SSL_CTX_set_num_tickets)(void*, size_t);
  void* (*SSL_new)(void*);
  void (*SSL_free)(void*);
  int (*SSL_set_fd)(void*, int);
  int (*SSL_accept)(void*);
  int (*SSL_read)(void*, void*, int);
  int (*SSL_get_error)(const void*, int);
  int (*SSL_shutdown)(void*);
  unsigned long (*ERR_get_error)();
  bool ok = false;
};

OsslApi* ossl() {
  static OsslApi api;
  static std::once_flag once;
  std::call_once(once, [] {
    // RTLD_LOCAL: every symbol is fetched via dlsym, and a GLOBAL
    // promotion could interpose these OpenSSL 3 symbols onto a Python
    // _ssl built against a different OpenSSL in the same process
    void* h = dlopen("libssl.so.3", RTLD_NOW | RTLD_LOCAL);
    if (!h) h = dlopen("libssl.so.1.1", RTLD_NOW | RTLD_LOCAL);
    if (!h) return;
    void* hc = dlopen("libcrypto.so.3", RTLD_NOW | RTLD_LOCAL);
    if (!hc) hc = dlopen("libcrypto.so.1.1", RTLD_NOW | RTLD_LOCAL);
    api.ssl_handle = h;
    api.crypto_handle = hc;
    bool all = true;
    auto grab = [&](const char* name) -> void* {
      void* p = dlsym(h, name);
      if (!p && hc) p = dlsym(hc, name);
      if (!p) all = false;
      return p;
    };
    api.TLS_server_method = reinterpret_cast<const void* (*)()>(
        grab("TLS_server_method"));
    api.SSL_CTX_new = reinterpret_cast<void* (*)(const void*)>(
        grab("SSL_CTX_new"));
    api.SSL_CTX_free = reinterpret_cast<void (*)(void*)>(
        grab("SSL_CTX_free"));
    api.SSL_CTX_use_certificate_chain_file =
        reinterpret_cast<int (*)(void*, const char*)>(
            grab("SSL_CTX_use_certificate_chain_file"));
    api.SSL_CTX_use_PrivateKey_file =
        reinterpret_cast<int (*)(void*, const char*, int)>(
            grab("SSL_CTX_use_PrivateKey_file"));
    api.SSL_CTX_check_private_key = reinterpret_cast<int (*)(const void*)>(
        grab("SSL_CTX_check_private_key"));
    api.SSL_CTX_set_verify = reinterpret_cast<void (*)(void*, int, void*)>(
        grab("SSL_CTX_set_verify"));
    api.SSL_CTX_load_verify_locations =
        reinterpret_cast<int (*)(void*, const char*, const char*)>(
            grab("SSL_CTX_load_verify_locations"));
    api.SSL_CTX_set_num_tickets = reinterpret_cast<int (*)(void*, size_t)>(
        grab("SSL_CTX_set_num_tickets"));
    api.SSL_new = reinterpret_cast<void* (*)(void*)>(grab("SSL_new"));
    api.SSL_free = reinterpret_cast<void (*)(void*)>(grab("SSL_free"));
    api.SSL_set_fd = reinterpret_cast<int (*)(void*, int)>(
        grab("SSL_set_fd"));
    api.SSL_accept = reinterpret_cast<int (*)(void*)>(grab("SSL_accept"));
    api.SSL_read = reinterpret_cast<int (*)(void*, void*, int)>(
        grab("SSL_read"));
    api.SSL_get_error = reinterpret_cast<int (*)(const void*, int)>(
        grab("SSL_get_error"));
    api.SSL_shutdown = reinterpret_cast<int (*)(void*)>(
        grab("SSL_shutdown"));
    api.ERR_get_error = reinterpret_cast<unsigned long (*)()>(
        grab("ERR_get_error"));
    api.ok = all;
  });
  return &api;
}

constexpr int kSslFiletypePem = 1;       // SSL_FILETYPE_PEM
constexpr int kSslVerifyPeer = 0x01;     // SSL_VERIFY_PEER
constexpr int kSslVerifyFailNoPeer = 0x02;

struct TlsServer {
  int listen_fd = -1;
  void* ssl_ctx = nullptr;  // null = plain TCP
  std::thread acceptor;
  std::atomic<bool> stop{false};
  std::atomic<uint64_t> conns{0};
  std::atomic<uint64_t> handshake_failures{0};
  std::atomic<uint64_t> dropped{0};
  // load-bearing for shutdown: stop() waits for the detached
  // connection threads to drain before freeing this struct
  std::atomic<int> live_conns{0};
  std::mutex mu;  // guards active/standby
  VtBatch* active = nullptr;
  VtBatch* standby = nullptr;
  int port = 0;
  int max_line = 4096;
  int handshake_timeout_ms = 10000;
};

void tls_conn_loop(TlsServer* srv, int fd) {
  OsslApi* api = ossl();
  void* ssl = nullptr;
  if (srv->ssl_ctx) {
    // bound handshake + reads: a silent client wedges only itself
    // (the Python path's slowloris posture, networking.py)
    timeval tv{srv->handshake_timeout_ms / 1000,
               (srv->handshake_timeout_ms % 1000) * 1000};
    setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    ssl = api->SSL_new(srv->ssl_ctx);
    if (!ssl || api->SSL_set_fd(ssl, fd) != 1 ||
        api->SSL_accept(ssl) != 1) {
      srv->handshake_failures.fetch_add(1, std::memory_order_relaxed);
      if (ssl) api->SSL_free(ssl);
      close(fd);
      srv->live_conns.fetch_add(-1, std::memory_order_relaxed);
      return;
    }
  }
  // post-handshake read timeout: 500ms poll-equivalent granularity
  timeval rv{0, 500000};
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &rv, sizeof(rv));
  std::vector<char> buf;
  buf.reserve(srv->max_line + 65536);
  char tmp[65536];
  while (!srv->stop.load(std::memory_order_relaxed)) {
    int n;
    if (ssl) {
      n = api->SSL_read(ssl, tmp, sizeof(tmp));
      if (n <= 0) {
        int err = api->SSL_get_error(ssl, n);
        // 2 = WANT_READ (timeout tick): keep waiting unless stopping
        if (err == 2) continue;
        break;  // clean close (ZERO_RETURN) or error: drop the conn
      }
    } else {
      n = static_cast<int>(recv(fd, tmp, sizeof(tmp), 0));
      if (n == 0) break;
      if (n < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR)
          continue;
        break;
      }
    }
    buf.insert(buf.end(), tmp, tmp + n);
    // parse every complete line; keep the tail
    size_t last_nl = buf.size();
    while (last_nl > 0 && buf[last_nl - 1] != '\n') last_nl--;
    if (last_nl > 0) {
      std::lock_guard<std::mutex> lock(srv->mu);
      if (srv->active->count >= srv->active->capacity ||
          srv->active->arena_len + last_nl > srv->active->arena_cap) {
        srv->dropped.fetch_add(1, std::memory_order_relaxed);
      } else {
        // parse errors reach Python via the batch's own counter
        vt_parse_lines(buf.data(), last_nl, srv->active);
      }
      buf.erase(buf.begin(), buf.begin() + last_nl);
    }
    if (buf.size() > static_cast<size_t>(srv->max_line)) {
      // a single line beyond max_length poisons the connection
      // (server.go:920-983)
      break;
    }
  }
  if (ssl) {
    api->SSL_shutdown(ssl);
    api->SSL_free(ssl);
  }
  close(fd);
  srv->live_conns.fetch_add(-1, std::memory_order_relaxed);
}

void tls_accept_loop(TlsServer* srv) {
  pollfd pfd = {srv->listen_fd, POLLIN, 0};
  while (!srv->stop.load(std::memory_order_relaxed)) {
    int pr = poll(&pfd, 1, 100);
    if (pr <= 0) continue;
    int fd = accept(srv->listen_fd, nullptr, nullptr);
    if (fd < 0) continue;
    srv->conns.fetch_add(1, std::memory_order_relaxed);
    srv->live_conns.fetch_add(1, std::memory_order_relaxed);
    // detached: statsd TLS connections are long-lived, so joining
    // live threads from the accept loop would wedge accepts; stop()
    // synchronizes on live_conns instead
    std::thread(tls_conn_loop, srv, fd).detach();
  }
}

}  // namespace

extern "C" int vt_tls_available() { return ossl()->ok ? 1 : 0; }

// Start a TCP (cert_path empty -> plaintext) or TLS statsd listener.
// Returns null on failure. ca_path non-empty turns on required
// client-cert verification, mirroring make_server_tls_context.
extern "C" void* vt_tls_server_start(const char* ip, int port,
                                     const char* cert_path,
                                     const char* key_path,
                                     const char* ca_path,
                                     uint32_t batch_records,
                                     uint32_t batch_arena,
                                     int max_line) {
  OsslApi* api = ossl();
  bool want_tls = cert_path && *cert_path;
  if (want_tls && !api->ok) return nullptr;

  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  setsockopt(fd, SOL_SOCKET, SO_REUSEPORT, &one, sizeof(one));
  sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  addr.sin_addr.s_addr = ip && *ip ? inet_addr(ip) : INADDR_ANY;
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      listen(fd, 128) != 0) {
    close(fd);
    return nullptr;
  }

  void* ctx = nullptr;
  if (want_tls) {
    ctx = api->SSL_CTX_new(api->TLS_server_method());
    if (!ctx ||
        api->SSL_CTX_use_certificate_chain_file(ctx, cert_path) != 1 ||
        api->SSL_CTX_use_PrivateKey_file(ctx, key_path,
                                         kSslFiletypePem) != 1 ||
        api->SSL_CTX_check_private_key(ctx) != 1) {
      if (ctx) api->SSL_CTX_free(ctx);
      close(fd);
      return nullptr;
    }
    if (ca_path && *ca_path) {
      if (api->SSL_CTX_load_verify_locations(ctx, ca_path, nullptr) != 1) {
        api->SSL_CTX_free(ctx);
        close(fd);
        return nullptr;
      }
      api->SSL_CTX_set_verify(
          ctx, kSslVerifyPeer | kSslVerifyFailNoPeer, nullptr);
    }
    if (api->SSL_CTX_set_num_tickets) {
      api->SSL_CTX_set_num_tickets(ctx, 0);
    }
  }

  TlsServer* srv = new TlsServer();
  srv->listen_fd = fd;
  srv->ssl_ctx = ctx;
  srv->max_line = max_line > 0 ? max_line : 4096;
  srv->active = vt_batch_new(batch_records, batch_arena);
  srv->standby = vt_batch_new(batch_records, batch_arena);
  sockaddr_in bound;
  socklen_t blen = sizeof(bound);
  getsockname(fd, reinterpret_cast<sockaddr*>(&bound), &blen);
  srv->port = ntohs(bound.sin_port);
  srv->acceptor = std::thread(tls_accept_loop, srv);
  return srv;
}

extern "C" int vt_tls_server_port(void* handle) {
  return static_cast<TlsServer*>(handle)->port;
}

extern "C" VtBatch* vt_tls_server_swap(void* handle) {
  TlsServer* srv = static_cast<TlsServer*>(handle);
  std::lock_guard<std::mutex> lock(srv->mu);
  VtBatch* filled = srv->active;
  vt_batch_reset(srv->standby);
  srv->active = srv->standby;
  srv->standby = filled;
  return filled;
}

extern "C" uint64_t vt_tls_server_conns(void* handle) {
  return static_cast<TlsServer*>(handle)
      ->conns.load(std::memory_order_relaxed);
}

extern "C" uint64_t vt_tls_server_handshake_failures(void* handle) {
  return static_cast<TlsServer*>(handle)
      ->handshake_failures.load(std::memory_order_relaxed);
}

extern "C" uint64_t vt_tls_server_drops(void* handle) {
  return static_cast<TlsServer*>(handle)
      ->dropped.load(std::memory_order_relaxed);
}

extern "C" void vt_tls_server_stop(void* handle) {
  TlsServer* srv = static_cast<TlsServer*>(handle);
  srv->stop.store(true);
  if (srv->acceptor.joinable()) srv->acceptor.join();
  close(srv->listen_fd);
  // connection threads are detached; they observe `stop` within one
  // 500ms read tick (a mid-handshake thread within the handshake
  // timeout) and decrement live_conns on exit. Wait bounded; if a
  // thread is still alive after that, LEAK the server struct — a
  // bounded leak at shutdown beats a use-after-free from a thread
  // still touching the batches.
  for (int i = 0; i < 1200 && srv->live_conns.load() > 0; i++) {
    usleep(10 * 1000);
  }
  if (srv->live_conns.load() > 0) {
    fprintf(stderr,
            "veneur-native: leaking TLS listener (%d connections still "
            "draining at shutdown)\n", srv->live_conns.load());
    return;
  }
  if (srv->ssl_ctx) ossl()->SSL_CTX_free(srv->ssl_ctx);
  vt_batch_free(srv->active);
  vt_batch_free(srv->standby);
  delete srv;
}

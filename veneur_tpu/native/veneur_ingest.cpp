// Native ingest hot path: SO_REUSEPORT UDP reader pool + DogStatsD parser
// + framed-SSF scanner.
//
// The reference reaches native ingest performance with Go + raw syscalls
// (/root/reference/socket_linux.go:12-76 SO_REUSEPORT/SO_RCVBUF,
// server.go:795-825 read loop, samplers/parser.go:232-363 parser,
// samplers/split_bytes.go splitter). This file is the C++ equivalent for
// the TPU build: N reader threads each own a SO_REUSEPORT socket, drain
// it with recvmmsg, split datagrams on '\n', and parse each DogStatsD
// line into a packed struct-of-arrays batch that Python drains wholesale
// — one FFI call per batch instead of one parse per line.
//
// Parsed-record grammar and validation mirror parser.go:232-363 exactly:
//   name:value|type[|@rate][|#tag1,tag2]   (sections in any order, once)
// with byte-wise tag sorting (Go sort.Strings), first-match
// veneurlocalonly/veneurglobalonly scope-tag extraction
// (parser.go:326-342), the fnv1a-32 digest over name+type+joined-tags
// (parser.go:259-354), NaN/Inf rejection, and (0,1] sample rates.
// Events (_e{) and service checks (_sc) are surfaced as RAW records for
// the Python parser — they are rare control-plane packets.
//
// The framed-SSF scanner mirrors protocol/wire.go:42-108: frames are
// 1 version byte (0x00) + 4-byte big-endian length + protobuf, 16 MiB
// cap; a bad version/length is a poison framing error.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <poll.h>
#include <pthread.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kFnvInit = 0x811C9DC5u;
constexpr uint32_t kFnvPrime = 0x01000193u;

inline uint32_t fnv1a(const char* data, size_t len, uint32_t h) {
  for (size_t i = 0; i < len; i++) {
    h = (h ^ static_cast<unsigned char>(data[i])) * kFnvPrime;
  }
  return h;
}

// Record types (order matches veneur_tpu/native/__init__.py)
enum RecordType : uint8_t {
  kCounter = 0,
  kGauge = 1,
  kHistogram = 2,
  kTimer = 3,
  kSet = 4,
  kRaw = 5,  // _e{ / _sc lines, passed through for the Python parser
};

const char* kTypeNames[5] = {"counter", "gauge", "histogram", "timer", "set"};
const size_t kTypeNameLens[5] = {7, 5, 9, 5, 3};

// Scopes (parser.go:34-40); kTopK marks a set carrying the veneurtopk
// magic tag (heavy-hitter sampler, this framework's extension)
enum Scope : uint8_t { kMixed = 0, kLocalOnly = 1, kGlobalOnly = 2,
                       kTopK = 3 };

}  // namespace

// One batch of parsed records, struct-of-arrays. All offsets index into
// `arena`. Python mirrors this layout with ctypes.
extern "C" struct VtBatch {
  uint32_t capacity;     // max records
  uint32_t arena_cap;    // arena bytes
  uint32_t count;        // records filled
  uint32_t arena_len;    // arena bytes used
  uint64_t parse_errors; // lines rejected since batch reset
  uint8_t* type;
  uint8_t* scope;
  double* value;
  float* sample_rate;
  uint32_t* digest;
  uint32_t* name_off;
  uint32_t* name_len;
  uint32_t* tags_off;    // comma-joined sorted tags
  uint32_t* tags_len;
  uint32_t* aux_off;     // set member / raw line bytes
  uint32_t* aux_len;
  char* arena;
};

extern "C" VtBatch* vt_batch_new(uint32_t capacity, uint32_t arena_cap) {
  VtBatch* b = static_cast<VtBatch*>(calloc(1, sizeof(VtBatch)));
  b->capacity = capacity;
  b->arena_cap = arena_cap;
  b->type = static_cast<uint8_t*>(malloc(capacity));
  b->scope = static_cast<uint8_t*>(malloc(capacity));
  b->value = static_cast<double*>(malloc(capacity * sizeof(double)));
  b->sample_rate = static_cast<float*>(malloc(capacity * sizeof(float)));
  b->digest = static_cast<uint32_t*>(malloc(capacity * sizeof(uint32_t)));
  b->name_off = static_cast<uint32_t*>(malloc(capacity * sizeof(uint32_t)));
  b->name_len = static_cast<uint32_t*>(malloc(capacity * sizeof(uint32_t)));
  b->tags_off = static_cast<uint32_t*>(malloc(capacity * sizeof(uint32_t)));
  b->tags_len = static_cast<uint32_t*>(malloc(capacity * sizeof(uint32_t)));
  b->aux_off = static_cast<uint32_t*>(malloc(capacity * sizeof(uint32_t)));
  b->aux_len = static_cast<uint32_t*>(malloc(capacity * sizeof(uint32_t)));
  b->arena = static_cast<char*>(malloc(arena_cap));
  return b;
}

extern "C" void vt_batch_free(VtBatch* b) {
  if (!b) return;
  free(b->type); free(b->scope); free(b->value); free(b->sample_rate);
  free(b->digest); free(b->name_off); free(b->name_len);
  free(b->tags_off); free(b->tags_len); free(b->aux_off); free(b->aux_len);
  free(b->arena);
  free(b);
}

extern "C" void vt_batch_reset(VtBatch* b) {
  b->count = 0;
  b->arena_len = 0;
  b->parse_errors = 0;
}

namespace {

// Append bytes to the batch arena; returns offset or UINT32_MAX when full.
inline uint32_t arena_put(VtBatch* b, const char* data, size_t len) {
  if (b->arena_len + len > b->arena_cap) return UINT32_MAX;
  memcpy(b->arena + b->arena_len, data, len);
  uint32_t off = b->arena_len;
  b->arena_len += static_cast<uint32_t>(len);
  return off;
}

struct TagView {
  const char* p;
  size_t len;
  bool operator<(const TagView& o) const {
    int c = memcmp(p, o.p, std::min(len, o.len));
    if (c != 0) return c < 0;
    return len < o.len;
  }
};

inline bool has_prefix(const TagView& t, const char* pre, size_t n) {
  return t.len >= n && memcmp(t.p, pre, n) == 0;
}

// Parse one line into the batch. Returns false on a parse error (counted
// by the caller). Mirrors parse_metric (parser.go:232-363).
bool parse_line(const char* line, size_t len, VtBatch* b) {
  if (b->count >= b->capacity) return false;
  uint32_t idx = b->count;

  // events / service checks pass through as raw records
  if ((len >= 3 && memcmp(line, "_e{", 3) == 0) ||
      (len >= 3 && memcmp(line, "_sc", 3) == 0)) {
    uint32_t off = arena_put(b, line, len);
    if (off == UINT32_MAX) return false;
    b->type[idx] = kRaw;
    b->scope[idx] = kMixed;
    b->value[idx] = 0.0;
    b->sample_rate[idx] = 1.0f;
    b->digest[idx] = 0;
    b->name_off[idx] = b->name_len[idx] = 0;
    b->tags_off[idx] = b->tags_len[idx] = 0;
    b->aux_off[idx] = off;
    b->aux_len[idx] = static_cast<uint32_t>(len);
    b->count++;
    return true;
  }

  // a trailing pipe is an empty final section (parser.go rejects it)
  if (line[len - 1] == '|') return false;

  // head section: name:value
  const char* pipe = static_cast<const char*>(memchr(line, '|', len));
  if (!pipe) return false;
  size_t head_len = pipe - line;
  const char* colon =
      static_cast<const char*>(memchr(line, ':', head_len));
  if (!colon) return false;
  size_t name_len = colon - line;
  if (name_len == 0) return false;
  const char* value_p = colon + 1;
  size_t value_len = head_len - name_len - 1;

  // type section
  const char* rest = pipe + 1;
  size_t rest_len = len - head_len - 1;
  const char* type_end =
      static_cast<const char*>(memchr(rest, '|', rest_len));
  size_t type_len = type_end ? static_cast<size_t>(type_end - rest)
                             : rest_len;
  if (type_len == 0) return false;
  uint8_t rtype;
  switch (rest[0]) {  // only the first byte is inspected (parser.go:281)
    case 'c': rtype = kCounter; break;
    case 'g': rtype = kGauge; break;
    case 'h': rtype = kHistogram; break;
    case 'm': rtype = kTimer; break;
    case 's': rtype = kSet; break;
    default: return false;
  }

  double value = 0.0;
  if (rtype != kSet) {
    char tmp[64];
    if (value_len == 0 || value_len >= sizeof(tmp)) return false;
    memcpy(tmp, value_p, value_len);
    tmp[value_len] = 0;
    char* endp = nullptr;
    value = strtod(tmp, &endp);
    if (endp != tmp + value_len) return false;
    if (std::isnan(value) || std::isinf(value)) return false;
  }

  // optional sections: @rate and #tags, any order, at most once
  float sample_rate = 1.0f;
  bool found_rate = false;
  // tags grow without bound, matching the pure-Python parser (the Go
  // reference imposes no tag-count limit either)
  std::vector<TagView> tags;
  bool found_tags = false;
  uint8_t scope = kMixed;

  const char* p = type_end ? type_end + 1 : rest + rest_len;
  const char* end = line + len;
  while (p < end) {
    const char* next = static_cast<const char*>(memchr(p, '|', end - p));
    size_t sec_len = next ? static_cast<size_t>(next - p)
                          : static_cast<size_t>(end - p);
    if (sec_len == 0) return false;  // empty string between pipes
    if (p[0] == '@') {
      if (found_rate) return false;
      char tmp[32];
      if (sec_len - 1 == 0 || sec_len - 1 >= sizeof(tmp)) return false;
      memcpy(tmp, p + 1, sec_len - 1);
      tmp[sec_len - 1] = 0;
      char* endp = nullptr;
      double r = strtod(tmp, &endp);
      if (endp != tmp + sec_len - 1) return false;
      if (!(r > 0.0 && r <= 1.0)) return false;
      sample_rate = static_cast<float>(r);
      found_rate = true;
    } else if (p[0] == '#') {
      if (found_tags) return false;
      found_tags = true;
      const char* tp = p + 1;
      const char* tend = p + sec_len;
      while (tp <= tend) {
        const char* comma =
            static_cast<const char*>(memchr(tp, ',', tend - tp));
        size_t tlen = comma ? static_cast<size_t>(comma - tp)
                            : static_cast<size_t>(tend - tp);
        tags.push_back(TagView{tp, tlen});
        if (!comma) break;
        tp = comma + 1;
      }
      std::sort(tags.begin(), tags.end());
      // first-match scope-tag extraction (parser.go:326-342)
      for (size_t i = 0; i < tags.size(); i++) {
        bool local = has_prefix(tags[i], "veneurlocalonly", 15);
        bool global = has_prefix(tags[i], "veneurglobalonly", 16);
        if (local || global) {
          scope = local ? kLocalOnly : kGlobalOnly;
          tags.erase(tags.begin() + i);
          break;
        }
      }
      // heavy-hitter routing tag: stays in the tag list (and digest),
      // and only flips the scope byte for SETS — other types keep their
      // local/global scope even if the tag is present
      if (rtype == kSet) {
        for (size_t i = 0; i < tags.size(); i++) {
          if (tags[i].len == 10 &&
              memcmp(tags[i].p, "veneurtopk", 10) == 0) {
            scope = kTopK;
            break;
          }
        }
      }
    } else {
      return false;  // unknown section
    }
    p = next ? next + 1 : end;
    if (!next) break;
  }

  // write the record
  uint32_t noff = arena_put(b, line, name_len);
  if (noff == UINT32_MAX) return false;

  uint32_t h = fnv1a(line, name_len, kFnvInit);
  h = fnv1a(kTypeNames[rtype], kTypeNameLens[rtype], h);

  uint32_t toff = b->arena_len;
  uint32_t tlen = 0;
  if (found_tags) {
    for (size_t i = 0; i < tags.size(); i++) {
      if (i > 0) {
        if (arena_put(b, ",", 1) == UINT32_MAX) return false;
        tlen += 1;
      }
      if (arena_put(b, tags[i].p, tags[i].len) == UINT32_MAX) return false;
      tlen += static_cast<uint32_t>(tags[i].len);
    }
    h = fnv1a(b->arena + toff, tlen, h);
  }

  uint32_t aoff = 0, alen = 0;
  if (rtype == kSet) {
    aoff = arena_put(b, value_p, value_len);
    if (aoff == UINT32_MAX) return false;
    alen = static_cast<uint32_t>(value_len);
    // 64-bit member hash (FNV-1a core + murmur3 fmix64), bit-identical to
    // ops/hll.py hash_member; carried through the value slot's bit pattern
    uint64_t mh = 14695981039346656037ULL;
    for (size_t vi = 0; vi < value_len; vi++) {
      mh = (mh ^ static_cast<uint8_t>(value_p[vi])) * 1099511628211ULL;
    }
    mh ^= mh >> 33;
    mh *= 0xFF51AFD7ED558CCDULL;
    mh ^= mh >> 33;
    mh *= 0xC4CEB9FE1A85EC53ULL;
    mh ^= mh >> 33;
    memcpy(&value, &mh, sizeof(value));
  }

  b->type[idx] = rtype;
  b->scope[idx] = scope;
  b->value[idx] = value;
  b->sample_rate[idx] = sample_rate;
  b->digest[idx] = h;
  b->name_off[idx] = noff;
  b->name_len[idx] = static_cast<uint32_t>(name_len);
  b->tags_off[idx] = toff;
  b->tags_len[idx] = tlen;
  b->aux_off[idx] = aoff;
  b->aux_len[idx] = alen;
  b->count++;
  return true;
}

}  // namespace

// Split a buffer on '\n' and parse every non-empty line
// (split_bytes.go:17-56). Returns records appended.
extern "C" uint32_t vt_parse_lines(const char* buf, size_t len, VtBatch* b) {
  uint32_t before = b->count;
  const char* p = buf;
  const char* end = buf + len;
  while (p < end) {
    const char* nl = static_cast<const char*>(memchr(p, '\n', end - p));
    size_t line_len = nl ? static_cast<size_t>(nl - p)
                         : static_cast<size_t>(end - p);
    if (line_len > 0) {
      if (!parse_line(p, line_len, b)) b->parse_errors++;
    }
    p = nl ? nl + 1 : end;
  }
  return b->count - before;
}

// ---------------------------------------------------------------------------
// Framed-SSF scanner (protocol/wire.go:42-108)

// Scans `buf` for complete frames. Writes (offset,length) pairs of the
// protobuf payloads into out_off/out_len (up to out_cap). Returns the
// number of complete frames; *consumed is the byte count of whole frames
// scanned past; *poisoned is set on a framing error (bad version or
// oversized length) — the stream must be closed (wire.go:26-28).
extern "C" uint32_t vt_frame_scan(const char* buf, size_t len,
                                  uint32_t* out_off, uint32_t* out_len,
                                  uint32_t out_cap, size_t* consumed,
                                  int* poisoned) {
  constexpr size_t kMaxFrame = 16 * 1024 * 1024;
  uint32_t n = 0;
  size_t pos = 0;
  *poisoned = 0;
  while (n < out_cap && pos + 5 <= len) {
    if (buf[pos] != 0) {  // version byte (wire.go:31-40)
      *poisoned = 1;
      break;
    }
    uint32_t flen = (static_cast<uint32_t>(
                         static_cast<unsigned char>(buf[pos + 1])) << 24) |
                    (static_cast<uint32_t>(
                         static_cast<unsigned char>(buf[pos + 2])) << 16) |
                    (static_cast<uint32_t>(
                         static_cast<unsigned char>(buf[pos + 3])) << 8) |
                    static_cast<uint32_t>(
                        static_cast<unsigned char>(buf[pos + 4]));
    if (flen > kMaxFrame) {
      *poisoned = 1;
      break;
    }
    if (pos + 5 + flen > len) break;  // incomplete frame: wait for more
    out_off[n] = static_cast<uint32_t>(pos + 5);
    out_len[n] = flen;
    n++;
    pos += 5 + flen;
  }
  *consumed = pos;
  return n;
}

// ---------------------------------------------------------------------------
// Series interning table: (scope-class kind, name, tags) -> dense row id.
// The host-side hot hash path (string-keyed series -> row indices) that
// the reference pays inside map[MetricKey]*sampler lookups per sample
// (worker.go:96-157). The table only MEMOIZES rows assigned by the Python
// Interner: vt_intern_assign leaves unknown keys as misses (row =
// UINT32_MAX) for Python to resolve and teach back via vt_intern_put, so
// both sides always agree on row numbering.

namespace {

// scope-class kinds, mirroring veneur_tpu/core/store.py _K_* constants
inline uint8_t kind_of(uint8_t rtype, uint8_t scope) {
  switch (rtype) {
    case kCounter: return scope == kGlobalOnly ? 1 : 0;
    case kGauge: return scope == kGlobalOnly ? 3 : 2;
    case kHistogram: return scope == kLocalOnly ? 5 : 4;
    case kTimer: return scope == kLocalOnly ? 7 : 6;
    case kSet:
      if (scope == kTopK) return 10;  // heavy hitters
      return scope == kLocalOnly ? 9 : 8;
    default: return 255;  // raw
  }
}

struct InternEntry {
  uint64_t hash;
  uint32_t key_off;
  uint32_t key_len;
  uint32_t row;
  uint32_t used;
};

struct InternTable {
  InternEntry* slots;
  size_t cap;  // power of two
  size_t count;
  char* arena;
  size_t arena_len;
  size_t arena_cap;
};

inline uint64_t fnv1a64(const char* data, size_t len, uint64_t h) {
  for (size_t i = 0; i < len; i++) {
    h = (h ^ static_cast<unsigned char>(data[i])) * 1099511628211ULL;
  }
  return h;
}

inline uint64_t intern_hash(uint8_t kind, const char* name, size_t nlen,
                            const char* tags, size_t tlen) {
  uint64_t h = 14695981039346656037ULL;
  char k = static_cast<char>(kind);
  h = fnv1a64(&k, 1, h);
  h = fnv1a64(name, nlen, h);
  char sep = 0x1f;
  h = fnv1a64(&sep, 1, h);
  return fnv1a64(tags, tlen, h);
}

inline bool intern_key_eq(const InternTable* t, const InternEntry* e,
                          uint8_t kind, const char* name, size_t nlen,
                          const char* tags, size_t tlen) {
  if (e->key_len != 1 + nlen + 1 + tlen) return false;
  const char* k = t->arena + e->key_off;
  if (static_cast<uint8_t>(k[0]) != kind) return false;
  if (memcmp(k + 1, name, nlen) != 0) return false;
  if (k[1 + nlen] != 0x1f) return false;
  return memcmp(k + 2 + nlen, tags, tlen) == 0;
}

void intern_grow(InternTable* t) {
  size_t ncap = t->cap * 2;
  InternEntry* ns = static_cast<InternEntry*>(
      calloc(ncap, sizeof(InternEntry)));
  for (size_t i = 0; i < t->cap; i++) {
    InternEntry* e = &t->slots[i];
    if (!e->used) continue;
    size_t j = e->hash & (ncap - 1);
    while (ns[j].used) j = (j + 1) & (ncap - 1);
    ns[j] = *e;
  }
  free(t->slots);
  t->slots = ns;
  t->cap = ncap;
}

}  // namespace

extern "C" InternTable* vt_intern_new() {
  InternTable* t = new InternTable();
  t->cap = 1 << 12;
  t->slots = static_cast<InternEntry*>(calloc(t->cap, sizeof(InternEntry)));
  t->count = 0;
  t->arena_cap = 1 << 16;
  t->arena = static_cast<char*>(malloc(t->arena_cap));
  t->arena_len = 0;
  return t;
}

extern "C" void vt_intern_free(InternTable* t) {
  free(t->slots);
  free(t->arena);
  delete t;
}

// Flush-time reset: rows restart from zero (the Python interners were
// swapped out), allocations are kept.
extern "C" void vt_intern_reset(InternTable* t) {
  memset(t->slots, 0, t->cap * sizeof(InternEntry));
  t->count = 0;
  t->arena_len = 0;
}

extern "C" void vt_intern_put(InternTable* t, uint8_t kind,
                              const char* name, uint32_t nlen,
                              const char* tags, uint32_t tlen,
                              uint32_t row) {
  if (t->count * 10 >= t->cap * 7) intern_grow(t);
  uint64_t h = intern_hash(kind, name, nlen, tags, tlen);
  size_t j = h & (t->cap - 1);
  while (t->slots[j].used) {
    InternEntry* e = &t->slots[j];
    if (e->hash == h && intern_key_eq(t, e, kind, name, nlen, tags, tlen)) {
      e->row = row;  // overwrite (python is authoritative)
      return;
    }
    j = (j + 1) & (t->cap - 1);
  }
  size_t klen = 1 + nlen + 1 + tlen;
  if (t->arena_len + klen > t->arena_cap) {
    while (t->arena_len + klen > t->arena_cap) t->arena_cap *= 2;
    t->arena = static_cast<char*>(realloc(t->arena, t->arena_cap));
  }
  char* k = t->arena + t->arena_len;
  k[0] = static_cast<char>(kind);
  memcpy(k + 1, name, nlen);
  k[1 + nlen] = 0x1f;
  memcpy(k + 2 + nlen, tags, tlen);
  InternEntry* e = &t->slots[j];
  e->hash = h;
  e->key_off = static_cast<uint32_t>(t->arena_len);
  e->key_len = static_cast<uint32_t>(klen);
  e->row = row;
  e->used = 1;
  t->arena_len += klen;
  t->count++;
}

// For every record: out_kinds[i] = scope-class kind (255 for raw),
// out_rows[i] = memoized row or UINT32_MAX on miss. Miss record indices
// are appended to out_miss; returns the miss count.
extern "C" uint32_t vt_intern_assign(InternTable* t, const VtBatch* b,
                                     uint32_t* out_rows, uint8_t* out_kinds,
                                     uint32_t* out_miss) {
  uint32_t nmiss = 0;
  for (uint32_t i = 0; i < b->count; i++) {
    uint8_t kind = kind_of(b->type[i], b->scope[i]);
    out_kinds[i] = kind;
    if (kind == 255) {
      out_rows[i] = UINT32_MAX;
      continue;
    }
    const char* name = b->arena + b->name_off[i];
    size_t nlen = b->name_len[i];
    const char* tags = b->arena + b->tags_off[i];
    size_t tlen = b->tags_len[i];
    uint64_t h = intern_hash(kind, name, nlen, tags, tlen);
    size_t j = h & (t->cap - 1);
    uint32_t row = UINT32_MAX;
    while (t->slots[j].used) {
      InternEntry* e = &t->slots[j];
      if (e->hash == h &&
          intern_key_eq(t, e, kind, name, nlen, tags, tlen)) {
        row = e->row;
        break;
      }
      j = (j + 1) & (t->cap - 1);
    }
    out_rows[i] = row;
    if (row == UINT32_MAX) out_miss[nmiss++] = i;
  }
  return nmiss;
}

// ---------------------------------------------------------------------------
// SO_REUSEPORT UDP reader pool (networking.go:37-87, socket_linux.go:12-76)

namespace {

struct Reader {
  int fd = -1;
  std::thread thread;
  std::mutex mu;
  VtBatch* active;   // parser writes here under mu
  VtBatch* standby;  // handed to Python on swap
  std::atomic<uint64_t> packets{0};
  std::atomic<uint64_t> dropped_batches{0};
};

struct ReaderPool {
  std::vector<Reader*> readers;
  std::atomic<bool> stop{false};
  int port = 0;
};

int make_udp_socket(const char* ip, int port, int rcvbuf) {
  int fd = socket(AF_INET, SOCK_DGRAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  // SO_REUSEPORT kernel load-balancing (socket_linux.go:25-31)
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  setsockopt(fd, SOL_SOCKET, SO_REUSEPORT, &one, sizeof(one));
  if (rcvbuf > 0) {
    setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &rcvbuf, sizeof(rcvbuf));
  }
  sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  addr.sin_addr.s_addr = ip && *ip ? inet_addr(ip) : INADDR_ANY;
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    close(fd);
    return -1;
  }
  return fd;
}

constexpr int kVlen = 64;  // datagrams per recvmmsg

void reader_loop(ReaderPool* pool, Reader* r, int dgram_max) {
  std::vector<char> bufs(static_cast<size_t>(kVlen) * dgram_max);
  mmsghdr msgs[kVlen];
  iovec iovs[kVlen];
  for (int i = 0; i < kVlen; i++) {
    iovs[i].iov_base = bufs.data() + static_cast<size_t>(i) * dgram_max;
    iovs[i].iov_len = dgram_max;
    memset(&msgs[i], 0, sizeof(mmsghdr));
    msgs[i].msg_hdr.msg_iov = &iovs[i];
    msgs[i].msg_hdr.msg_iovlen = 1;
  }
  pollfd pfd = {r->fd, POLLIN, 0};
  while (!pool->stop.load(std::memory_order_relaxed)) {
    int pr = poll(&pfd, 1, 100);
    if (pr <= 0) continue;
    int got = recvmmsg(r->fd, msgs, kVlen, MSG_DONTWAIT, nullptr);
    if (got <= 0) continue;
    std::lock_guard<std::mutex> lock(r->mu);
    for (int i = 0; i < got; i++) {
      const char* data = bufs.data() + static_cast<size_t>(i) * dgram_max;
      size_t dlen = msgs[i].msg_len;
      if (r->active->count >= r->active->capacity ||
          r->active->arena_len + dlen > r->active->arena_cap) {
        // batch full and Python hasn't swapped: drop the datagram
        // (the kernel socket buffer is the real backpressure here,
        // like the reference's packet drops under overload)
        r->dropped_batches.fetch_add(1, std::memory_order_relaxed);
        continue;
      }
      vt_parse_lines(data, dlen, r->active);
    }
    r->packets.fetch_add(got, std::memory_order_relaxed);
  }
}

}  // namespace

extern "C" void* vt_reader_start(const char* ip, int port, int nreaders,
                                 int rcvbuf, uint32_t batch_records,
                                 uint32_t batch_arena, int dgram_max) {
  if (dgram_max <= 0) dgram_max = 8192;
  ReaderPool* pool = new ReaderPool();
  for (int i = 0; i < nreaders; i++) {
    int fd = make_udp_socket(ip, port, rcvbuf);
    if (fd < 0) {
      // threads are not started yet: release every reader created so far
      for (Reader* r : pool->readers) {
        close(r->fd);
        vt_batch_free(r->active);
        vt_batch_free(r->standby);
        delete r;
      }
      delete pool;
      return nullptr;
    }
    if (pool->port == 0) {
      sockaddr_in bound;
      socklen_t blen = sizeof(bound);
      getsockname(fd, reinterpret_cast<sockaddr*>(&bound), &blen);
      pool->port = ntohs(bound.sin_port);
      port = pool->port;  // later readers share the resolved port
    }
    Reader* r = new Reader();
    r->fd = fd;
    r->active = vt_batch_new(batch_records, batch_arena);
    r->standby = vt_batch_new(batch_records, batch_arena);
    pool->readers.push_back(r);
  }
  for (Reader* r : pool->readers) {
    r->thread = std::thread(reader_loop, pool, r, dgram_max);
  }
  return pool;
}

extern "C" int vt_reader_port(void* handle) {
  return static_cast<ReaderPool*>(handle)->port;
}

extern "C" int vt_reader_count(void* handle) {
  return static_cast<int>(static_cast<ReaderPool*>(handle)->readers.size());
}

// Swap a reader's active batch for its (reset) standby and return the
// filled batch. Python owns the returned pointer until the next swap of
// the same reader.
extern "C" VtBatch* vt_reader_swap(void* handle, int idx) {
  ReaderPool* pool = static_cast<ReaderPool*>(handle);
  Reader* r = pool->readers[idx];
  std::lock_guard<std::mutex> lock(r->mu);
  VtBatch* filled = r->active;
  vt_batch_reset(r->standby);
  r->active = r->standby;
  r->standby = filled;
  return filled;
}

extern "C" uint64_t vt_reader_packets(void* handle, int idx) {
  return static_cast<ReaderPool*>(handle)
      ->readers[idx]->packets.load(std::memory_order_relaxed);
}

extern "C" uint64_t vt_reader_drops(void* handle, int idx) {
  return static_cast<ReaderPool*>(handle)
      ->readers[idx]->dropped_batches.load(std::memory_order_relaxed);
}

extern "C" void vt_reader_stop(void* handle) {
  ReaderPool* pool = static_cast<ReaderPool*>(handle);
  pool->stop.store(true);
  for (Reader* r : pool->readers) {
    if (r->thread.joinable()) r->thread.join();
    close(r->fd);
    vt_batch_free(r->active);
    vt_batch_free(r->standby);
    delete r;
  }
  delete pool;
}

"""Listener bring-up: UDP (SO_REUSEPORT multi-reader), TCP (+TLS), UNIX SSF.

Behavioral port of ``/root/reference/networking.go`` + ``socket_linux.go``:
``num_readers`` UDP sockets bound to one port with SO_REUSEPORT so the
kernel load-balances packets across reader threads (networking.go:37-87,
socket_linux.go:12-76); TCP listeners with optional TLS client-cert
authentication (networking.go:93-134); UNIX-domain stream listeners for
framed SSF (networking.go:162-223).
"""

from __future__ import annotations

import errno
import logging
import os
import socket
import ssl
import threading
import time
from typing import Callable, List, Optional

from veneur_tpu.protocol.addr import ResolvedAddr, resolve_addr

log = logging.getLogger("veneur.networking")

# read-loop error logging is rate-limited to one warning per flush
# interval: a persistent socket error (dead NIC, revoked netns) would
# otherwise log at packet rate — exactly when the GIL is scarcest
DEFAULT_ERROR_LOG_INTERVAL = 10.0


class _LogLimiter:
    """At most one warning per ``interval`` seconds; interleaving calls
    fold into a suppressed-count carried on the next emitted line.
    Thread-safe (one limiter is shared across a listener's readers)."""

    def __init__(self, interval: float = DEFAULT_ERROR_LOG_INTERVAL,
                 clock: Callable[[], float] = time.monotonic):
        self.interval = interval
        self._clock = clock
        self._lock = threading.Lock()
        self._last = -interval
        self.suppressed = 0
        self.emitted = 0

    def warn(self, fmt: str, *args) -> None:
        with self._lock:
            now = self._clock()
            if now - self._last < self.interval:
                self.suppressed += 1
                return
            self._last = now
            suppressed, self.suppressed = self.suppressed, 0
            self.emitted += 1
        if suppressed:
            log.warning(fmt + " (%d similar suppressed in the last "
                        "%.0fs)", *(args + (suppressed, self.interval)))
        else:
            log.warning(fmt, *args)


def warn_if_port_already_served(family: int, kind: int, host: str,
                                port: int) -> None:
    """SO_REUSEPORT on every listener trades the EADDRINUSE fail-fast
    for upgrade/rolling-restart overlap, so an accidental second
    instance would otherwise *silently* split ingest with the first.
    Probe the port with a plain (non-reuseport) bind before our real
    bind: if someone is already serving it, say so loudly. Deliberate
    overlaps — an upgrade replacement (VENEUR_READY_FD in the
    environment) — stay quiet; a manual rolling restart gets one
    informational line."""
    if port == 0:
        return
    probe = None
    try:
        # The probe is strictly best-effort: socket creation itself can
        # fail (e.g. EAFNOSUPPORT for an IPv6 wildcard on a v6-disabled
        # host) and must never break startup — the real bind reports
        # the accurate error. REUSEADDR only for TCP, where server-side
        # TIME_WAIT from an ordinary restart would otherwise read as a
        # live second instance; for UDP there is no TIME_WAIT, and a
        # REUSEADDR probe would bind *alongside* a live listener that
        # also set REUSEADDR (ours all do) — silencing exactly the
        # split-ingest warning this probe exists to raise. EACCES etc.
        # stay quiet too.
        probe = socket.socket(family, kind)
        if kind == socket.SOCK_STREAM:
            probe.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        probe.bind((host, port))
    except OSError as e:
        if e.errno == errno.EADDRINUSE:
            from veneur_tpu.cli.upgrade import READY_ENV

            if os.environ.get(READY_ENV):
                return  # upgrade replacement: overlap is the protocol
            log.warning(
                "port %s:%d is already being served by another process; "
                "binding alongside it (SO_REUSEPORT). If this is not a "
                "deliberate rolling restart, ingest will be split "
                "between the two instances.", host, port)
    finally:
        if probe is not None:
            probe.close()


def warn_for_stream_addr(addr_str: str) -> None:
    """The probe above for callers holding a raw ``host:port`` /
    ``[v6]:port`` string (the gRPC listener's address format) rather
    than a resolved family+host+port."""
    host, _, port_s = addr_str.rpartition(":")
    host = host.strip("[]")
    try:
        port = int(port_s)
    except ValueError:
        return
    if not port:
        return
    if ":" in host or host in ("", "::"):
        family, wildcard = socket.AF_INET6, "::"
    else:
        family, wildcard = socket.AF_INET, "0.0.0.0"
    warn_if_port_already_served(family, socket.SOCK_STREAM,
                                host or wildcard, port)


def new_tcp_listener(family: int, host: str, port: int,
                     backlog: int = 128) -> socket.socket:
    """A bound+listening TCP socket with the upgrade-overlap treatment
    every stream listener gets: SO_REUSEPORT (where available) plus the
    accidental-second-instance probe above."""
    listener = socket.socket(family, socket.SOCK_STREAM)
    listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    if hasattr(socket, "SO_REUSEPORT"):
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        warn_if_port_already_served(family, socket.SOCK_STREAM, host, port)
    listener.bind((host, port))
    listener.listen(backlog)
    return listener


def new_udp_socket(addr: ResolvedAddr, recv_buf: int,
                   reuse_port: bool) -> socket.socket:
    """A bound UDP socket with SO_REUSEPORT + SO_RCVBUF
    (socket_linux.go:12-76)."""
    sock = socket.socket(addr.socket_family, socket.SOCK_DGRAM)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    if reuse_port and hasattr(socket, "SO_REUSEPORT"):
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
    if recv_buf:
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, recv_buf)
    sock.bind((addr.host, addr.port))
    return sock


def start_statsd(addr_spec: str, num_readers: int, recv_buf: int,
                 metric_max_length: int,
                 handle_packet: Callable[[bytes], None],
                 stop: threading.Event,
                 handle_tcp_line: Optional[Callable[[bytes], None]] = None,
                 tls_config: Optional[ssl.SSLContext] = None,
                 admit: Optional[Callable[[], bool]] = None,
                 error_log_interval: float = DEFAULT_ERROR_LOG_INTERVAL,
                 receivers: Optional[list] = None,
                 ):
    """Start DogStatsD listeners for one address spec (networking.go:18-35).

    UDP: num_readers reader threads each with its own SO_REUSEPORT socket.
    TCP: an accept loop spawning per-connection line readers.
    Returns (reader threads — daemons, already started; bound addresses).

    Every listener binds with SO_REUSEPORT even when a single reader
    needs no kernel balancing: a SIGUSR2 upgrade (cli/upgrade.py) and a
    rolling restart both briefly run two generations on the same port.

    ``admit`` is the overload governor's watermark gate
    (veneur_tpu/overload.py): when it returns False the datagram is
    dropped AT the socket — the governor accounts the shed — instead of
    costing parse + store work the saturated pipeline cannot spend.
    Recv-error logging is rate-limited to one warning per
    ``error_log_interval`` (the flush interval, when the server wires
    it) with a suppressed-count, shared across this listener's readers.
    """
    addr = resolve_addr(addr_spec)
    threads: List[threading.Thread] = []
    bound: List[tuple] = []
    limiter = _LogLimiter(error_log_interval)
    if addr.family == "udp":
        warn_if_port_already_served(addr.socket_family, socket.SOCK_DGRAM,
                                    addr.host, addr.port)
        for i in range(num_readers):
            sock = new_udp_socket(addr, recv_buf, reuse_port=True)
            bound.append(sock.getsockname())
            # with an ephemeral port (":0"), later readers must share the
            # port the first one got
            if addr.port == 0:
                addr = ResolvedAddr(scheme=addr.scheme, family="udp",
                                    host=addr.host, port=sock.getsockname()[1])
            t = threading.Thread(
                target=_udp_read_loop,
                args=(sock, metric_max_length, handle_packet, stop,
                      admit, limiter, receivers),
                name=f"statsd-udp-reader-{i}", daemon=True)
            t.start()
            threads.append(t)
    elif addr.family == "tcp":
        listener = new_tcp_listener(addr.socket_family, addr.host, addr.port)
        bound.append(listener.getsockname())
        t = threading.Thread(
            target=_tcp_accept_loop,
            args=(listener, metric_max_length,
                  handle_tcp_line or handle_packet, stop, tls_config,
                  limiter, admit),
            name="statsd-tcp-listener", daemon=True)
        t.start()
        threads.append(t)
    else:
        raise ValueError(f"statsd listen address must be udp or tcp: {addr_spec}")
    return threads, bound


def _udp_read_loop(sock: socket.socket, max_len: int,
                   handle_packet: Callable[[bytes], None],
                   stop: threading.Event,
                   admit: Optional[Callable[[], bool]] = None,
                   limiter: Optional[_LogLimiter] = None,
                   receivers: Optional[list] = None):
    """Per-reader receive loop (server.go:795-825). Each datagram may hold
    several newline-separated metrics; oversize datagrams are truncated by
    the OS and the tail line is dropped by the parser.

    Datagrams arrive in ``recvmmsg`` batches where the platform has it
    (veneur_tpu/ingest/recvmmsg.py — one syscall for up to a batch of
    datagrams instead of one each; portable ``recv`` fallback
    otherwise). ``receivers``, when given, collects the BatchReceiver
    so the caller can read syscalls-per-packet telemetry."""
    from veneur_tpu.ingest.recvmmsg import BatchReceiver

    if limiter is None:
        limiter = _LogLimiter()
    receiver = BatchReceiver(sock, max_len)
    if receivers is not None:
        receivers.append(receiver)
    while not stop.is_set():
        try:
            datagrams = receiver.recv_batch(timeout=0.5)
        except OSError as e:
            if stop.is_set() or e.errno in (errno.EBADF,):
                break
            limiter.warn("UDP recv error: %s", e)
            continue
        for data in datagrams:
            if not data:
                continue  # zero-length datagrams are valid UDP; ignore
            if admit is not None and not admit():
                continue  # shed at the socket; the governor accounts it
            handle_packet(data)
    sock.close()


def _tcp_accept_loop(listener: socket.socket, max_len: int,
                     handle_line: Callable[[bytes], None],
                     stop: threading.Event,
                     tls_config: Optional[ssl.SSLContext],
                     limiter: Optional[_LogLimiter] = None,
                     admit: Optional[Callable[[], bool]] = None):
    """Accept loop + per-connection readers (server.go:901-1001)."""
    listener.settimeout(0.5)
    while not stop.is_set():
        try:
            conn, peer = listener.accept()
        except socket.timeout:
            continue
        except OSError:
            break
        t = threading.Thread(target=_tcp_conn_loop,
                             args=(conn, max_len, handle_line, stop,
                                   tls_config, peer, limiter, admit),
                             daemon=True)
        t.start()
    listener.close()


def _tcp_conn_loop(conn: socket.socket, max_len: int,
                   handle_line: Callable[[bytes], None],
                   stop: threading.Event,
                   tls_config: Optional[ssl.SSLContext] = None,
                   peer=None, limiter: Optional[_LogLimiter] = None,
                   admit: Optional[Callable[[], bool]] = None):
    """Newline-scan a TCP connection; a single line longer than max_len
    poisons the connection (server.go:920-983).

    The TLS handshake happens HERE, on the per-connection thread — in
    the accept loop a client that connects and sends nothing would
    wedge wrap_socket and with it every other connection (slowloris);
    on this thread it can only wedge itself, and the timeout bounds
    even that. socket.timeout is an OSError."""
    if limiter is None:
        limiter = _LogLimiter()
    if tls_config is not None:
        try:
            conn.settimeout(10.0)
            conn = tls_config.wrap_socket(conn, server_side=True)
        except (ssl.SSLError, OSError) as e:
            limiter.warn("TLS handshake failed from %s: %s", peer, e)
            conn.close()
            return
    conn.settimeout(0.5)
    buf = bytearray()
    while not stop.is_set():
        try:
            data = conn.recv(65536)
        except socket.timeout:
            continue
        except OSError as e:
            if not stop.is_set() and e.errno not in (errno.EBADF,):
                limiter.warn("TCP recv error from %s: %s", peer, e)
            break
        if not data:
            break
        buf.extend(data)
        while True:
            nl = buf.find(b"\n")
            if nl == -1:
                break
            line = bytes(buf[:nl])
            del buf[:nl + 1]
            if line:
                # the same hard-ceiling admission gate the UDP readers
                # apply: TCP statsd must not bypass level-3 shedding
                if admit is not None and not admit():
                    continue
                handle_line(line)
        if len(buf) > max_len:
            limiter.warn("Line longer than max_length, closing connection")
            break
    conn.close()


def make_server_tls_context(cert_path: str, key_path: str,
                            ca_path: str = "") -> ssl.SSLContext:
    """TLS listener context; a CA cert turns on required client-cert auth
    (server.go:314-348)."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert_path, key_path)
    if ca_path:
        ctx.verify_mode = ssl.CERT_REQUIRED
        ctx.load_verify_locations(ca_path)
    return ctx


def start_ssf(addr_spec: str, num_readers: int, recv_buf: int,
              trace_max_length: int,
              handle_ssf_packet: Callable[[bytes], None],
              handle_ssf_stream: Callable[[socket.socket], None],
              stop: threading.Event,
              admit: Optional[Callable[[], bool]] = None,
              error_log_interval: float = DEFAULT_ERROR_LOG_INTERVAL,
              receivers: Optional[list] = None):
    """Start SSF listeners (networking.go:138-223): UDP datagrams carry one
    bare SSFSpan protobuf each; UNIX/TCP streams carry framed spans.
    Returns (threads, bound addresses). ``admit``/``error_log_interval``
    as in :func:`start_statsd` (spans are the governor's second shed
    tier — they drop before statsd aggregates do)."""
    addr = resolve_addr(addr_spec)
    threads: List[threading.Thread] = []
    bound: List = []
    limiter = _LogLimiter(error_log_interval)
    if addr.family == "udp":
        warn_if_port_already_served(addr.socket_family, socket.SOCK_DGRAM,
                                    addr.host, addr.port)
        for i in range(num_readers):
            sock = new_udp_socket(addr, recv_buf, reuse_port=True)
            bound.append(sock.getsockname())
            if addr.port == 0:
                addr = ResolvedAddr(scheme=addr.scheme, family="udp",
                                    host=addr.host, port=sock.getsockname()[1])
            t = threading.Thread(
                target=_udp_read_loop,
                args=(sock, trace_max_length, handle_ssf_packet, stop,
                      admit, limiter, receivers),
                name=f"ssf-udp-reader-{i}", daemon=True)
            t.start()
            threads.append(t)
    elif addr.family == "unix":
        if os.path.exists(addr.path):
            os.unlink(addr.path)
        listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        listener.bind(addr.path)
        listener.listen(128)
        bound.append(addr.path)
        t = threading.Thread(
            target=_stream_accept_loop,
            args=(listener, handle_ssf_stream, stop),
            name="ssf-unix-listener", daemon=True)
        t.start()
        threads.append(t)
    elif addr.family == "tcp":
        listener = new_tcp_listener(addr.socket_family, addr.host, addr.port)
        bound.append(listener.getsockname())
        t = threading.Thread(
            target=_stream_accept_loop,
            args=(listener, handle_ssf_stream, stop),
            name="ssf-tcp-listener", daemon=True)
        t.start()
        threads.append(t)
    else:
        raise ValueError(f"unsupported SSF listen address {addr_spec}")
    return threads, bound


def _stream_accept_loop(listener: socket.socket,
                        handle_stream: Callable[[socket.socket], None],
                        stop: threading.Event):
    listener.settimeout(0.5)
    while not stop.is_set():
        try:
            conn, _ = listener.accept()
        except socket.timeout:
            continue
        except OSError:
            break
        t = threading.Thread(target=handle_stream, args=(conn,), daemon=True)
        t.start()
    listener.close()

"""Interval-timeline observability: per-stage self-tracing for the
flush path, kernel-level profiling hooks, and the dogfooded
self-telemetry plumbing.

The reference traces its own flush with one SSF span per interval
(``/root/reference/flusher.go:26-29``) and mounts pprof everywhere; the
layer here goes further and makes the pipeline's interior visible:

- :mod:`veneur_tpu.obs.recorder` — ``StageRecorder``, a lock-cheap
  (monotonic-ns stamps, single-writer-per-thread deque appends, merged
  at interval end like the ingest lanes) begin/end tracer the flusher
  threads through the whole hot path.
- :mod:`veneur_tpu.obs.timeline` — the bounded per-interval ring buffer
  behind ``GET /debug/flush-timeline``.
- :mod:`veneur_tpu.obs.kernels` — ``jax.profiler`` named scopes over
  every compiled program in the static-analysis inventory, live
  compile/dispatch counters, and the on-demand ``/debug/xprof``
  capture.
- :mod:`veneur_tpu.obs.tracectx` — the fleet trace plane's cross-hop
  contract: ``TraceContext`` + the ``X-Veneur-Trace`` header stamped
  into every forward/proxy/import/handoff envelope, and the receiving
  side's ``HopLog``.
- :mod:`veneur_tpu.obs.fleet` — the global's fleet aggregation view:
  ``GET /debug/fleet`` (peer timelines, keep-last-good) and
  ``GET /debug/trace?id=…`` (the stitched per-trace hop view).

``docs/observability.md`` is the reading guide.
"""

from __future__ import annotations

from veneur_tpu.obs.recorder import (StageRecorder, activate, current,
                                     maybe_stage, note)
from veneur_tpu.obs.timeline import FlushTimeline
from veneur_tpu.obs.tracectx import HopLog, TraceContext

__all__ = ["StageRecorder", "FlushTimeline", "HopLog", "TraceContext",
           "activate", "current", "maybe_stage", "note"]

"""The fleet aggregation view: one observability plane across instances.

``GET /debug/flush-timeline`` answers "where did THIS instance's
interval go"; production asks "why was this interval's GLOBAL
percentile late", whose answer spans a local's flush, a proxy's
fan-out, the global's import and the global's own flush. The
:class:`FleetAggregator` (mounted on any obs-enabled instance, most
usefully the global) closes the gap:

- ``GET /debug/fleet`` — pulls every peer's ``/debug/flush-timeline``
  + ``/debug/vars`` and serves the merged view. Peer membership comes
  through a :class:`~veneur_tpu.discovery.RingWatcher` (the same
  keep-last-good ladder discovery refresh uses: a failed or empty
  resolve keeps the previous set), and each peer's last good pull is
  kept and served ``stale: true`` when a fresh pull fails — a dead
  peer degrades the view, never empties it.
- ``GET /debug/trace?id=…`` — the stitched per-trace hop view: every
  entry/hop carrying the trace id (``obs/tracectx.py``), across this
  instance's timeline + pending hop log + the cached peer timelines,
  ordered by wall clock with per-hop durations, the end-to-end wall
  clock, and ``hop_coverage_ratio`` (the union of hop intervals over
  the e2e span — the ≥0.9 acceptance tripwire for the trace plane,
  the cross-instance twin of the flush timeline's coverage_ratio).

Pulls are rate-limited (``fleet_pull_interval``) so a dashboard
hammering /debug/fleet costs the peers one pull per window, and a
trace lookup that misses triggers at most one forced refresh.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.parse
import urllib.request
from typing import Dict, List, Optional, Tuple

log = logging.getLogger("veneur.obs.fleet")

# how many intervals to pull per peer: enough to cover a trace spread
# across a few flush ticks without shipping whole rings around
PULL_INTERVALS = 16


def _base_url(addr: str) -> str:
    url = addr.rstrip("/")
    if not url.startswith(("http://", "https://")):
        url = "http://" + url
    return url


class FleetAggregator:
    """Keep-last-good peer puller + per-trace stitcher (see module
    docstring). ``timeline`` / ``hop_log`` are this instance's own
    sources; ``watcher`` is a discovery RingWatcher (None = no peers,
    the aggregator still serves its own entries)."""

    def __init__(self, self_addr: str = "", watcher=None, timeline=None,
                 hop_log=None, pull_timeout: float = 2.0,
                 pull_interval: float = 5.0, clock=time.monotonic):
        self.self_addr = self_addr
        self.watcher = watcher
        self.timeline = timeline
        self.hop_log = hop_log
        self.pull_timeout = pull_timeout
        self.pull_interval = pull_interval
        self._clock = clock
        self._lock = threading.Lock()  # cache + refresh gate
        self._cache: Dict[str, dict] = {}  # peer -> last good pull
        self._last_pull = float("-inf")
        self._last_forced = float("-inf")
        self.pulls_total = 0
        self.pull_errors_total = 0

    # -- pulling -------------------------------------------------------------

    def peers(self) -> List[str]:
        """Current membership, minus this instance (served locally).
        Keep-last-good lives in the watcher: a failed refresh keeps
        the previous member set."""
        if self.watcher is None:
            return []
        self.watcher.refresh()
        return [m for m in self.watcher.members if m != self.self_addr]

    def _pull_one(self, peer: str) -> dict:
        base = _base_url(peer)
        with urllib.request.urlopen(
                f"{base}/debug/flush-timeline?n={PULL_INTERVALS}",
                timeout=self.pull_timeout) as resp:
            tl = json.loads(resp.read())
        dvars: dict = {}
        try:
            with urllib.request.urlopen(f"{base}/debug/vars",
                                        timeout=self.pull_timeout) as resp:
                dvars = json.loads(resp.read())
        except Exception:
            # a peer without /debug/vars (or a slow one) still
            # contributes its timeline
            pass
        return {"ok": True, "stale": False, "error": "",
                "pulled_at": time.time(), "timeline": tl, "vars": dvars}

    def refresh(self, force: bool = False) -> None:
        """One pull round across the current peer set, rate-limited.
        Per-peer failures keep that peer's last good pull, marked
        stale — the same keep-last-good ladder discovery refresh
        applies to membership. Peers are pulled CONCURRENTLY: these
        endpoints matter most during a partition, exactly when peers
        time out, and a sequential round would stall the debug request
        up to pull_timeout × peers instead of ~one pull_timeout."""
        with self._lock:
            now = self._clock()
            if not force and now - self._last_pull < self.pull_interval:
                return
            self._last_pull = now
        peers = self.peers()

        def pull(peer: str) -> None:
            try:
                pulled = self._pull_one(peer)
            except Exception as e:
                with self._lock:
                    self.pull_errors_total += 1
                    old = self._cache.get(peer)
                    if old is not None:
                        old["ok"] = False
                        old["stale"] = True
                        old["error"] = str(e)[:160]
                    else:
                        self._cache[peer] = {
                            "ok": False, "stale": True,
                            "error": str(e)[:160], "pulled_at": None,
                            "timeline": {"intervals": []}, "vars": {}}
                return
            with self._lock:
                self.pulls_total += 1
                self._cache[peer] = pulled

        if len(peers) == 1:
            pull(peers[0])
        elif peers:
            threads = [threading.Thread(target=pull, args=(p,),
                                        daemon=True) for p in peers]
            for t in threads:
                t.start()
            for t in threads:
                # urllib enforces pull_timeout per request; the join
                # bound is a backstop, not the budget
                t.join(timeout=2 * self.pull_timeout + 1.0)
        # prune departed peers (membership is keep-last-good, so a
        # peer only leaves the cache once discovery really dropped it)
        with self._lock:
            for gone in set(self._cache) - set(peers):
                del self._cache[gone]

    # -- sources -------------------------------------------------------------

    def _is_self(self, pulled: dict) -> bool:
        """A pull of THIS instance (fleet_peers lists every instance,
        including the puller; handoff_self is empty in tracing-only
        deployments, so the address can't tell) — recognized by the
        timeline's per-process uid, and dropped from stitching so no
        hop ever appears twice."""
        if self.timeline is None:
            return False
        uid = (pulled.get("timeline") or {}).get("instance_uid")
        return bool(uid) and uid == self.timeline.uid

    def _sources(self) -> List[Tuple[str, List[dict], List[dict]]]:
        """(origin, timeline entries, pending hops) per instance —
        self first, then each cached peer."""
        out: List[Tuple[str, List[dict], List[dict]]] = []
        own_entries = self.timeline.entries() if self.timeline else []
        own_hops = self.hop_log.peek() if self.hop_log else []
        out.append((self.self_addr or "self", own_entries, own_hops))
        with self._lock:
            cache = dict(self._cache)
        for peer, pulled in sorted(cache.items()):
            if self._is_self(pulled):
                continue  # own entries are already source[0]
            entries = (pulled.get("timeline") or {}).get("intervals") \
                or []
            out.append((peer, entries, []))
        return out

    # -- routes --------------------------------------------------------------

    def fleet_route(self, query) -> Tuple[int, str, str]:
        """``GET /debug/fleet``: the merged per-peer view. ``?n=K``
        includes each peer's last K raw intervals (default: summaries
        only)."""
        try:
            n = int(query.get("n", "0") or 0)
        except ValueError:
            return 400, "n must be an integer", "text/plain"
        self.refresh(force=query.get("refresh") == "1")
        body: dict = {"self": self.self_addr,
                      "members": (list(self.watcher.members)
                                  if self.watcher else []),
                      "pulls_total": self.pulls_total,
                      "pull_errors_total": self.pull_errors_total,
                      "peers": {}}
        with self._lock:
            cache = dict(self._cache)
        for peer, pulled in sorted(cache.items()):
            tl = pulled.get("timeline") or {}
            intervals = tl.get("intervals") or []
            last = intervals[-1] if intervals else None
            summary = {
                "ok": pulled.get("ok", False),
                "self": self._is_self(pulled),
                "stale": pulled.get("stale", False),
                "error": pulled.get("error", ""),
                "pulled_at": pulled.get("pulled_at"),
                "published_total": tl.get("published_total"),
                "last_interval": {
                    "interval": last.get("interval"),
                    "total_duration_ns": last.get("total_duration_ns"),
                    "coverage_ratio": last.get("coverage_ratio"),
                    "e2e_age_ns": last.get("e2e_age_ns"),
                } if last else None,
            }
            if n > 0:
                summary["intervals"] = intervals[-n:]
            body["peers"][peer] = summary
        if self.timeline is not None:
            body["own_timeline"] = self.timeline.snapshot()
        if self.hop_log is not None:
            body["own_hops"] = self.hop_log.snapshot()
        return 200, json.dumps(body, default=str), "application/json"

    def trace_route(self, query) -> Tuple[int, str, str]:
        """``GET /debug/trace?id=…``: the stitched hop view."""
        raw = query.get("id", "")
        try:
            trace_id = int(raw)
        except ValueError:
            return 400, "id must be a trace id (integer)", "text/plain"
        self.refresh()  # rate-limited; keeps the peer caches warm
        stitched = stitch_trace(trace_id, self._sources())
        if not stitched["hops"]:
            # maybe the peers flushed since the last pull window —
            # but an id that stays unknown (expired out of the rings,
            # or a typo polled by a dashboard) must not let every miss
            # bypass the rate limit: at most ONE forced pull per
            # pull_interval window across all misses
            with self._lock:
                now = self._clock()
                may_force = now - self._last_forced >= self.pull_interval
                if may_force:
                    self._last_forced = now
            if may_force:
                self.refresh(force=True)
                stitched = stitch_trace(trace_id, self._sources())
        status = 200 if stitched["hops"] else 404
        return status, json.dumps(stitched, default=str), \
            "application/json"

    def snapshot(self) -> dict:
        with self._lock:
            peers = {p: {"ok": c.get("ok"), "stale": c.get("stale")}
                     for p, c in self._cache.items()}
        return {"members": (list(self.watcher.members)
                            if self.watcher else []),
                "pulls_total": self.pulls_total,
                "pull_errors_total": self.pull_errors_total,
                "peers": peers}


# ---------------------------------------------------------------------------
# stitching
# ---------------------------------------------------------------------------


def _entry_hop(entry: dict, origin: str) -> dict:
    return {"hop": entry.get("hop") or "flush",
            "origin": origin,
            "wall_start": entry["wall_start"],
            "wall_end": entry["wall_end"],
            "duration_ns": int(entry.get("total_duration_ns") or 0),
            "span_id": entry.get("span_id"),
            "parent_span_id": entry.get("parent_span_id"),
            "interval": entry.get("interval"),
            "coverage_ratio": entry.get("coverage_ratio")}


def _stage_hop(entry: dict, stage: dict, origin: str,
               hop: Optional[str] = None) -> dict:
    if "wall_start" in stage and "wall_end" in stage:
        # a drained hop record carries its TRUE wall times as attrs —
        # the entry-relative frame clamps anything that landed before
        # the interval started
        start, end = stage["wall_start"], stage["wall_end"]
    else:
        start = entry["wall_start"] + stage["start_ns"] / 1e9
        end = start + stage["duration_ns"] / 1e9
    out = {k: v for k, v in stage.items()
           if k not in ("name", "start_ns", "duration_ns", "off_path",
                        "wall_start", "wall_end")}
    out["hop"] = hop or stage["name"]
    out["origin"] = origin
    out["wall_start"] = start
    out["wall_end"] = end
    out["duration_ns"] = max(0, int((end - start) * 1e9))
    return out


def stitch_trace(trace_id: int, sources) -> dict:
    """Gather every hop carrying ``trace_id`` across ``sources``
    ((origin, entries, pending_hops) triples) into one ordered view:

    - a timeline entry published UNDER the id (a local flush, a proxy
      fan-out, a handoff send) is one hop spanning the entry;
    - the off-path ``forward`` stage inside such an entry is its own
      hop (it outlives the flush that launched it);
    - stages inside ANY entry stamped with the id (drained import /
      handoff hop records) are hops;
    - an entry whose ``import_traces`` includes the id is the
      aggregating flush — one hop covering swap → sink POSTs;
    - pending (not-yet-drained) hop-log records round it out.

    ``hop_coverage_ratio`` is the union of hop wall intervals over the
    end-to-end span (first hop start → last hop end): overlap never
    inflates it past 1, and a gap nobody instrumented (e.g. state
    waiting for the global's next tick — reported per-gap in
    ``gaps``) pulls it down honestly."""
    hops: List[dict] = []
    for origin, entries, pending in sources:
        for e in entries:
            if e.get("trace_id") == trace_id:
                hops.append(_entry_hop(e, origin))
                for s in e.get("stages", ()):
                    if s.get("off_path") and s.get("name") == "forward":
                        hops.append(_stage_hop(e, s, origin,
                                               hop="forward"))
            if trace_id in (e.get("import_traces") or ()):
                agg = _entry_hop(e, origin)
                agg["hop"] = e.get("hop") or "global.flush"
                agg["aggregated"] = True
                hops.append(agg)
            for s in e.get("stages", ()):
                if s.get("trace_id") == trace_id:
                    hops.append(_stage_hop(e, s, origin))
        for h in pending:
            if h.get("trace_id") == trace_id:
                hops.append(dict(h, origin=origin, pending=True))
    hops.sort(key=lambda h: h["wall_start"])
    out: dict = {"trace_id": trace_id, "hops": hops}
    if not hops:
        return out
    t0 = min(h["wall_start"] for h in hops)
    t1 = max(h["wall_end"] for h in hops)
    e2e_ns = max(0, int((t1 - t0) * 1e9))
    out["e2e_wall_ns"] = e2e_ns
    # union coverage + the uncovered gaps
    covered = 0.0
    gaps: List[dict] = []
    cursor = t0
    for h in hops:  # already wall_start-sorted above
        start, end = h["wall_start"], h["wall_end"]
        if start > cursor:
            gaps.append({"after_wall": cursor,
                         "gap_ns": int((start - cursor) * 1e9)})
            cursor = start
        if end > cursor:
            covered += end - cursor
            cursor = end
    out["hop_coverage_ratio"] = round(covered * 1e9 / e2e_ns, 4) \
        if e2e_ns else 1.0
    if gaps:
        out["gaps"] = gaps
    ingest = [h.get("ingest_ns") for h in hops if h.get("ingest_ns")]
    if ingest:
        out["ingest_ns"] = min(ingest)
        out["e2e_age_ns"] = max(0, int(t1 * 1e9) - min(ingest))
    return out

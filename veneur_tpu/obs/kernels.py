"""Device observability: profiler scopes, compile/dispatch counters,
and the on-demand xprof capture.

The recompile lint pass (lint/recompile.py) proves statically which
compiled programs exist and what can retrigger their compilation; this
module surfaces the same inventory LIVE:

- :func:`scope` wraps every host-side dispatch choke point in a
  ``jax.profiler.TraceAnnotation`` named scope, so an xprof capture of
  a running server labels device work by pipeline stage instead of by
  mangled HLO module names. Entering a scope also counts a dispatch.
- :data:`PROGRAM_SCOPES` maps every program in the generated
  compiled-program inventory (docs/static-analysis.md) to the scope
  that covers its dispatches; tests drift-check the mapping against
  the lint pass exactly like the docs table, so a new program cannot
  ship unannotated.
- :func:`compile_snapshot` reads each program's live compiled-variant
  count (``PjitFunction._cache_size``), turning the lint pass's
  "bounded static args" proof into an observable number: a variant
  count that grows interval over interval is a recompile leak.
- :func:`capture_xprof` runs a bounded ``jax.profiler``
  start/stop_trace capture for ``GET /debug/xprof?seconds=N`` —
  one at a time, clamped, like ``/debug/profile``.
"""

from __future__ import annotations

import importlib
import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Dict, Optional, Tuple

try:  # pragma: no cover - jax is present everywhere we run
    from jax.profiler import TraceAnnotation as _TraceAnnotation
except Exception:  # pragma: no cover
    _TraceAnnotation = None

# profiler scope names carry this prefix in xprof captures
SCOPE_PREFIX = "veneur."

MAX_XPROF_SECONDS = 30.0

# one capture at a time (mirrors debug._profile_lock for /debug/profile)
_xprof_lock = threading.Lock()

# scope -> dispatch count. Plain dict int bumps: every writer holds the
# GIL across the read-modify-write (single bytecode effects are close
# enough for telemetry; dispatches are chunk-scale, not packet-scale).
_dispatches: Dict[str, int] = {}

# ---------------------------------------------------------------------------
# the scope coverage map — drift-checked against the lint inventory
# ---------------------------------------------------------------------------

# Every compiled program in the static-analysis inventory, mapped to
# the named scope whose dispatch site covers it (tests/test_obs.py
# fails when the inventory and this map drift apart — same contract as
# the generated docs table). Third field: the importable module-level
# jit binding for compile counting, or None when the program has no
# module-level PjitFunction (ingest_chunk_guarded is jitted inline by
# its callers and inside enclosing programs).
PROGRAM_SCOPES: Dict[str, Tuple[str, Optional[Tuple[str, str]]]] = {
    "veneur_tpu/core/store.py::_flush_digests":
        ("flush.digest.dense", ("veneur_tpu.core.store", "_flush_digests")),
    "veneur_tpu/core/store.py::_ingest_samples":
        ("drain.digest.dense", ("veneur_tpu.core.store", "_ingest_samples")),
    "veneur_tpu/core/store.py::_ingest_centroids":
        ("drain.digest.dense",
         ("veneur_tpu.core.store", "_ingest_centroids")),
    "veneur_tpu/ops/tdigest.py::ingest_chunk_guarded":
        ("drain.digest.dense", None),
    "veneur_tpu/ops/tdigest_pallas.py::_compress_presorted_pallas":
        ("flush.digest.dense",
         ("veneur_tpu.ops.tdigest_pallas", "_compress_presorted_pallas")),
    "veneur_tpu/ops/tdigest_pallas.py::_drain_quantile_pallas":
        ("flush.digest.dense",
         ("veneur_tpu.ops.tdigest_pallas", "_drain_quantile_pallas")),
    "veneur_tpu/core/slab.py::_ingest_slab":
        ("drain.digest.slab", ("veneur_tpu.core.slab", "_ingest_slab")),
    "veneur_tpu/core/slab.py::_import_slab":
        ("drain.digest.slab", ("veneur_tpu.core.slab", "_import_slab")),
    "veneur_tpu/core/slab.py::_merge_slab":
        ("drain.digest.slab", ("veneur_tpu.core.slab", "_merge_slab")),
    "veneur_tpu/core/slab.py::_flush_slab":
        ("flush.digest.slab", ("veneur_tpu.core.slab", "_flush_slab")),
    "veneur_tpu/core/slab.py::_quantile_slab":
        ("flush.digest.slab", ("veneur_tpu.core.slab", "_quantile_slab")),
    "veneur_tpu/core/slab.py::_pack_slab":
        ("flush.digest.slab", ("veneur_tpu.core.slab", "_pack_slab")),
    "veneur_tpu/core/slab.py::_slice_pack":
        ("flush.digest.slab", ("veneur_tpu.core.slab", "_slice_pack")),
    "veneur_tpu/core/slab.py::_gather_pack":
        ("flush.digest.slab", ("veneur_tpu.core.slab", "_gather_pack")),
    "veneur_tpu/core/tiered.py::_pool_ingest":
        ("drain.digest.tiered", ("veneur_tpu.core.tiered", "_pool_ingest")),
    "veneur_tpu/core/tiered.py::_pool_import":
        ("drain.digest.tiered", ("veneur_tpu.core.tiered", "_pool_import")),
    "veneur_tpu/core/tiered.py::_pool_restore_stats":
        ("drain.digest.tiered",
         ("veneur_tpu.core.tiered", "_pool_restore_stats")),
    "veneur_tpu/core/tiered.py::_promote_rows":
        ("drain.digest.tiered", ("veneur_tpu.core.tiered", "_promote_rows")),
    "veneur_tpu/core/tiered.py::_pool_flush":
        ("flush.digest.tiered", ("veneur_tpu.core.tiered", "_pool_flush")),
    # fleet mode (veneur_tpu/fleet/, core/mesh_store.py): the sharded
    # shard_map programs — module-level jit defs with the Mesh static,
    # so the inventory drift-check covers them like any other program
    "veneur_tpu/core/mesh_store.py::_mesh_ingest_samples":
        ("drain.digest.mesh",
         ("veneur_tpu.core.mesh_store", "_mesh_ingest_samples")),
    "veneur_tpu/core/mesh_store.py::_mesh_import_routed":
        ("drain.digest.mesh",
         ("veneur_tpu.core.mesh_store", "_mesh_import_routed")),
    "veneur_tpu/core/mesh_store.py::_mesh_flush_digests":
        ("flush.digest.mesh",
         ("veneur_tpu.core.mesh_store", "_mesh_flush_digests")),
    "veneur_tpu/core/mesh_store.py::_mesh_ingest_hashes":
        ("drain.set.mesh",
         ("veneur_tpu.core.mesh_store", "_mesh_ingest_hashes")),
    "veneur_tpu/core/mesh_store.py::_mesh_merge_registers":
        ("drain.set.mesh",
         ("veneur_tpu.core.mesh_store", "_mesh_merge_registers")),
    "veneur_tpu/core/mesh_store.py::_mesh_estimate":
        ("flush.set.mesh",
         ("veneur_tpu.core.mesh_store", "_mesh_estimate")),
    "veneur_tpu/fleet/mesh_tiered.py::_mesh_pool_ingest":
        ("drain.digest.mesh_tiered",
         ("veneur_tpu.fleet.mesh_tiered", "_mesh_pool_ingest")),
    "veneur_tpu/fleet/mesh_tiered.py::_mesh_pool_import":
        ("drain.digest.mesh_tiered",
         ("veneur_tpu.fleet.mesh_tiered", "_mesh_pool_import")),
    "veneur_tpu/fleet/mesh_tiered.py::_mesh_promote_rows":
        ("drain.digest.mesh_tiered",
         ("veneur_tpu.fleet.mesh_tiered", "_mesh_promote_rows")),
    "veneur_tpu/fleet/mesh_tiered.py::_mesh_pool_restore_stats":
        ("drain.digest.mesh_tiered",
         ("veneur_tpu.fleet.mesh_tiered", "_mesh_pool_restore_stats")),
    "veneur_tpu/fleet/mesh_tiered.py::_mesh_pool_flush":
        ("flush.digest.mesh_tiered",
         ("veneur_tpu.fleet.mesh_tiered", "_mesh_pool_flush")),
}


@contextmanager
def scope(name: str):
    """One named dispatch region: counts the dispatch and, when the
    profiler is importable, labels the region in xprof captures. Cheap
    enough for the per-chunk drain paths (a dict bump + one context
    object); NOT for per-packet paths."""
    _dispatches[name] = _dispatches.get(name, 0) + 1
    if _TraceAnnotation is None:  # pragma: no cover - jax always present
        yield
        return
    with _TraceAnnotation(SCOPE_PREFIX + name):
        yield


def dispatch_snapshot() -> Dict[str, int]:
    return dict(_dispatches)


def compile_snapshot() -> Dict[str, Optional[int]]:
    """program -> live compiled-variant count (None = the program has
    no module-level jit binding to read). Only programs whose module is
    ALREADY imported are counted — a debug read must not pull the slab
    or tiered stack into a dense-only process."""
    import sys

    out: Dict[str, Optional[int]] = {}
    for program, (_scope_name, binding) in PROGRAM_SCOPES.items():
        count: Optional[int] = None
        if binding is not None and binding[0] in sys.modules:
            fn = getattr(importlib.import_module(binding[0]), binding[1],
                         None)
            cache_size = getattr(fn, "_cache_size", None)
            if cache_size is not None:
                try:
                    count = int(cache_size())
                except Exception:  # pragma: no cover - jax API drift
                    count = None
        out[program] = count
    return out


def compiles_total() -> int:
    """Sum of live compiled variants across tracked programs (the
    interval-delta self-metric veneur.obs.kernel_compiles_total)."""
    return sum(v for v in compile_snapshot().values() if v)


def snapshot() -> dict:
    """The /debug/vars "kernels" section: dispatches per scope plus
    compiled-variant counts per inventory program."""
    return {"dispatches": dispatch_snapshot(),
            "compiled_variants": compile_snapshot()}


def capture_xprof(seconds: float, base_dir: Optional[str] = None) -> tuple:
    """Run one bounded xprof capture; returns the (status, body, ctype)
    triple for the /debug/xprof route. The trace lands on local disk
    (xprof traces are directory trees, not a streamable body) and the
    response names the directory + files so an operator can pull them
    with scp / TensorBoard's profile plugin."""
    seconds = max(0.05, min(float(seconds), MAX_XPROF_SECONDS))
    if not _xprof_lock.acquire(blocking=False):
        return 409, "another xprof capture is already running", "text/plain"
    try:
        import tempfile

        import jax

        trace_dir = tempfile.mkdtemp(prefix="veneur-xprof-", dir=base_dir)
        t0 = time.perf_counter()
        jax.profiler.start_trace(trace_dir)
        try:
            time.sleep(seconds)
        finally:
            jax.profiler.stop_trace()
        took = time.perf_counter() - t0
        files = []
        for root, _dirs, names in os.walk(trace_dir):
            for name in names:
                path = os.path.join(root, name)
                files.append({"path": path,
                              "bytes": os.path.getsize(path)})
        body = json.dumps({"trace_dir": trace_dir,
                           "seconds": round(took, 3),
                           "files": files,
                           "scopes": sorted({s for s, _ in
                                             PROGRAM_SCOPES.values()})})
        return 200, body, "application/json"
    except Exception as e:  # profiler unavailable / double-start etc.
        return 500, f"xprof capture failed: {e!r}", "text/plain"
    finally:
        _xprof_lock.release()

"""StageRecorder: lock-cheap per-interval stage tracing.

One recorder lives for exactly one flush interval. Every instrumented
region records ``(path, t0_ns, t1_ns, attrs)`` with monotonic-ns
stamps; the write side is a ``collections.deque`` append (GIL-atomic,
no lock — the same single-writer-then-merge shape as the ingest
lanes), and the merge into a stage tree happens once, at interval end
(:meth:`StageRecorder.finish`).

Stage nesting is carried by the recording thread's own open-stage
stack (``threading.local``): ``stage("fetch")`` entered while
``stage("histograms")`` is open under ``stage("store")`` records as
``store.histograms.fetch``. Threads that aren't part of the flusher's
call tree (sink POST threads, the off-path forward) record absolute
paths with :meth:`StageRecorder.record_abs`.

The flusher parks the interval's recorder in a thread-local slot
(:func:`activate`) so deep call sites — the store's generation swap,
each digest group's compute/fetch, the breaker ladder's rung choice —
can attach stages and notes without threading a parameter through
every signature. When observability is off (``obs_enabled: false``)
the slot is empty and every hook costs one thread-local read.
"""

from __future__ import annotations

import collections
import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional

_NS = 1_000_000_000

_tls = threading.local()


def current() -> Optional["StageRecorder"]:
    """The interval recorder active on this thread tree, or None."""
    return getattr(_tls, "recorder", None)


@contextmanager
def activate(rec: Optional["StageRecorder"]):
    """Park ``rec`` as the current recorder for this thread (the
    flusher wraps the whole interval in this). None deactivates."""
    prev = getattr(_tls, "recorder", None)
    _tls.recorder = rec
    try:
        yield rec
    finally:
        _tls.recorder = prev


@contextmanager
def maybe_stage(name: str, **attrs):
    """``rec.stage(name)`` against the current recorder, or a no-op
    when observability is off — the one-line hook for deep call
    sites."""
    rec = current()
    if rec is None:
        yield None
        return
    with rec.stage(name, **attrs) as frame:
        yield frame


def note(**attrs) -> None:
    """Attach attrs to the innermost open stage of the current
    recorder (e.g. which breaker rung a flush ran); no-op without
    one."""
    rec = current()
    if rec is not None:
        rec.note(**attrs)


class _Frame:
    __slots__ = ("name", "path", "attrs")

    def __init__(self, name: str, path: str, attrs: dict):
        self.name = name
        self.path = path
        self.attrs = attrs


class StageRecorder:
    """Begin/end stage tracer for ONE flush interval."""

    def __init__(self, clock_ns=time.monotonic_ns):
        self._clock = clock_ns
        # (path, t0_ns, t1_ns, attrs) — append is GIL-atomic
        self._events: "collections.deque" = collections.deque()
        self._amends: "collections.deque" = collections.deque()
        self._stacks = threading.local()
        self.t0_ns = clock_ns()
        self.wall_start = time.time()
        self.entry: Optional[dict] = None  # set by finish()
        # fleet trace plane (obs/tracectx.py): the distributed-trace
        # identity this interval's stage tree publishes under. Zero =
        # unstitched (a bare recorder outside the hop contract).
        self.trace_id = 0
        self.span_id = 0
        self.parent_span_id = 0
        self.hop = ""

    def adopt_trace(self, trace_id: int, span_id: int = 0,
                    parent_id: int = 0, hop: str = "") -> None:
        """Join this recorder's stage tree into a distributed trace:
        the published entry gains ``trace_id``/``span_id``/
        ``parent_span_id``/``hop``, which is what ``GET /debug/trace``
        stitches on. The flusher adopts its flush span's ids; a
        receiving hop adopts the ids off the ``X-Veneur-Trace``
        header."""
        from veneur_tpu.obs import tracectx

        self.trace_id = int(trace_id)
        self.span_id = int(span_id) or tracectx.new_span_id()
        self.parent_span_id = int(parent_id)
        self.hop = hop

    # -- recording ---------------------------------------------------------

    def _stack(self) -> list:
        st = getattr(self._stacks, "stack", None)
        if st is None:
            st = self._stacks.stack = []
        return st

    @contextmanager
    def stage(self, name: str, **attrs):
        """Record one nested stage around the with-body."""
        stack = self._stack()
        path = stack[-1].path + "." + name if stack else name
        frame = _Frame(name, path, attrs)
        stack.append(frame)
        t0 = self._clock()
        try:
            yield frame
        finally:
            t1 = self._clock()
            stack.pop()
            self._events.append((path, t0, t1, frame.attrs))

    def note(self, **attrs) -> None:
        stack = self._stack()
        if stack:
            stack[-1].attrs.update(attrs)

    def record_abs(self, path: str, t0_ns: int, t1_ns: int,
                   **attrs) -> None:
        """Record a stage at an absolute dotted path — for threads
        outside the flusher's stage stack (per-sink POSTs)."""
        self._events.append((path, t0_ns, t1_ns, attrs))

    def amend(self, path: str, **attrs) -> None:
        """Merge attrs into an already-recorded stage at finish time
        (sink telemetry drains after the POST threads joined)."""
        self._amends.append((path, attrs))

    def record_late(self, path: str, t0_ns: int, t1_ns: int,
                    **attrs) -> None:
        """Record a stage AFTER the interval published (the off-path
        forward): the entry already in the ring gains the stage in
        place, so ``/debug/flush-timeline`` shows it once it lands."""
        entry = self.entry
        if entry is None:
            # finish() has not run yet (a fast forward): land in the
            # normal event stream, keeping the off-path marker so
            # coverage accounting excludes it either way
            attrs = dict(attrs, off_path=True)
            self._events.append((path, t0_ns, t1_ns, attrs))
            return
        stage = dict(attrs)
        stage["name"] = path
        stage["start_ns"] = max(0, t0_ns - self.t0_ns)
        stage["duration_ns"] = max(0, t1_ns - t0_ns)
        stage["off_path"] = True
        entry["stages"].append(stage)
        entry["tree"].append(dict(stage, children=[]))

    # -- merge -------------------------------------------------------------

    def finish(self, total_ns: Optional[int] = None) -> dict:
        """Merge the recorded events into the interval record: a flat
        ``stages`` list plus a nested ``tree``, both ordered by start.
        ``coverage_ratio`` is the fraction of ``total_duration_ns``
        accounted for by top-level stages (off-path stages like the
        forward are excluded from both sides)."""
        end_ns = self._clock()
        if total_ns is None:
            total_ns = end_ns - self.t0_ns
        amends: Dict[str, dict] = {}
        # drain both deques destructively: a late sink/forward thread
        # may still be appending while this merge runs (deque ops are
        # GIL-atomic; iterating a mutating deque raises) — anything
        # appended after this drain is swept up by the straggler pass
        # below once ``self.entry`` is published
        events = _drain(self._events)
        for path, attrs in _drain(self._amends):
            amends.setdefault(path, {}).update(attrs)
        stages: List[dict] = []
        for path, t0, t1, attrs in events:
            stage = dict(attrs)
            stage["name"] = path
            stage["start_ns"] = max(0, t0 - self.t0_ns)
            stage["duration_ns"] = max(0, t1 - t0)
            extra = amends.pop(path, None)
            if extra:
                stage.update(extra)
            stages.append(stage)
        stages.sort(key=lambda s: (s["start_ns"], s["name"]))
        top_ns = sum(s["duration_ns"] for s in stages
                     if "." not in s["name"] and not s.get("off_path"))
        entry = {
            "wall_start": self.wall_start,
            "wall_end": self.wall_start + (end_ns - self.t0_ns) / _NS,
            "total_duration_ns": int(total_ns),
            "coverage_ratio": round(top_ns / total_ns, 4)
            if total_ns else 0.0,
            "stages": stages,
            "tree": _build_tree(stages),
        }
        if self.trace_id:
            entry["trace_id"] = self.trace_id
            entry["span_id"] = self.span_id
            entry["parent_span_id"] = self.parent_span_id
            entry["hop"] = self.hop
        self.entry = entry
        # straggler pass: events recorded between the drain above and
        # the entry publication (record_late saw entry None and fell
        # back to the stream) land in the published entry after all —
        # nothing recorded is ever silently lost
        for path, t0, t1, attrs in _drain(self._events):
            self.record_late(path, t0, t1, **attrs)
        return entry


def _drain(dq: "collections.deque") -> list:
    out = []
    while True:
        try:
            out.append(dq.popleft())
        except IndexError:
            return out


def _build_tree(stages: List[dict]) -> List[dict]:
    """Nest the flat dotted-path stage list: ``store.histograms.fetch``
    hangs under ``store.histograms`` under ``store``. A child whose
    parent path was never recorded attaches at the root (keeps the
    tree total — nothing is dropped)."""
    roots: List[dict] = []
    by_path: Dict[str, dict] = {}
    for stage in stages:
        node = dict(stage, children=[])
        path = stage["name"]
        # the LAST recorded node wins the path slot for parenting;
        # repeated stages (several sinks, retried groups) all stay in
        # the tree, later ones just can't adopt children
        by_path[path] = node
        parent = None
        if "." in path:
            parent = by_path.get(path.rsplit(".", 1)[0])
        if parent is not None:
            parent["children"].append(node)
        else:
            roots.append(node)
    return roots

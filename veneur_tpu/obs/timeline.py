"""The flush-interval timeline ring: last-N interval records as JSON.

Each completed flush publishes its :class:`StageRecorder` record here;
``GET /debug/flush-timeline`` (debug.py) serves the ring. The ring is
bounded (``obs_timeline_intervals``, default 64) so a long-lived
server's timeline costs fixed memory, and entries are plain dicts so
the late off-path forward stage can land in an already-published
interval (recorder.record_late)."""

from __future__ import annotations

import collections
import json
import threading
import uuid
from typing import List, Optional

DEFAULT_INTERVALS = 64

# the four egress pipeline lanes (docs/observability.md "Overlap"):
# device compute, device→host transfer, host serialize/deflate, POST
LANES = ("compute", "fetch", "serialize", "post")


def annotate_overlap(entry: dict) -> dict:
    """Bucket one interval's stage durations into the four egress
    pipeline lanes and stamp the overlap measures the `6_egress_1m`
    gate reads straight off the timeline:

    - ``lanes`` — summed ns per lane. Leaf classification: a
      ``*.compute`` / ``*.fetch`` stage is device dispatch / transfer;
      ``serialize.<group>`` and ``post.<sink>.serialize`` are the
      serialize lane; ``post.<sink>.post`` (streamed chunks) and the
      ``post.<sink>`` fan-out stages (their amended ``post_ns`` /
      ``serialize_ns`` when present, wall-clock otherwise) are POST.
    - ``egress_wall_ns`` — wall-clock from the store drain's start to
      the last POST's end: what the interval actually costs.
    - ``overlap_ratio`` — egress_wall / Σlanes. A fully sequential
      flush sits near 1.0 (the interval is the SUM of its lanes); a
      pipelined one approaches max(lane)/Σlanes (the interval is their
      MAX — overlap absorbed the rest).
    - ``sum_vs_max_gap_ns`` — Σlanes − max(lane): the headroom overlap
      can still reclaim.

    Off-path stages (forward, ingest, hops) are excluded — they do not
    spend the interval's wall-clock."""
    lanes = dict.fromkeys(LANES, 0)
    wall_start = None
    wall_end = None
    for s in entry.get("stages", ()):
        if s.get("off_path"):
            continue
        name = s["name"]
        segs = name.split(".")
        leaf = segs[-1]
        dur = s["duration_ns"]
        end = s["start_ns"] + dur
        if name == "store" or segs[0] == "post":
            wall_start = s["start_ns"] if wall_start is None \
                else min(wall_start, s["start_ns"])
            wall_end = end if wall_end is None else max(wall_end, end)
        if leaf == "compute":
            lanes["compute"] += dur
        elif leaf == "fetch":
            lanes["fetch"] += dur
        elif leaf == "serialize" or segs[0] == "serialize":
            lanes["serialize"] += dur
        elif segs[0] == "post" and len(segs) == 3 and leaf == "post":
            # streamed chunk POST (post.<sink>.post)
            lanes["post"] += dur
        elif segs[0] == "post" and len(segs) == 2:
            # one sink's batch fan-out thread: prefer the amended
            # marshal/post split so serialize time is not double-billed
            if "post_ns" in s or "serialize_ns" in s:
                lanes["post"] += int(s.get("post_ns", 0))
                lanes["serialize"] += int(s.get("serialize_ns", 0))
            else:
                lanes["post"] += dur
    total = sum(lanes.values())
    if total <= 0 or wall_start is None:
        return entry
    entry["lanes"] = lanes
    wall = max(0, wall_end - wall_start)
    entry["egress_wall_ns"] = wall
    entry["overlap_ratio"] = round(wall / total, 4)
    entry["sum_vs_max_gap_ns"] = total - max(lanes.values())
    return entry


class FlushTimeline:
    """Bounded ring of per-interval stage records."""

    def __init__(self, intervals: int = DEFAULT_INTERVALS):
        self.capacity = max(1, int(intervals))
        # per-process identity served at /debug/flush-timeline: how
        # the fleet aggregator recognizes a pull of ITSELF (fleet_peers
        # lists every instance, including the puller)
        self.uid = uuid.uuid4().hex
        self._ring: "collections.deque" = collections.deque(
            maxlen=self.capacity)
        # shared by publish and the read side: list(deque) raises
        # RuntimeError if an append lands mid-iteration, and the debug
        # endpoints read from arbitrary request threads while the
        # flusher (and the fleet aggregator's pulls) publish
        self._lock = threading.Lock()
        self.published_total = 0

    def publish(self, entry: dict) -> dict:
        with self._lock:
            entry["interval"] = self.published_total
            self.published_total += 1
            self._ring.append(entry)
        return entry

    def entries(self, last: Optional[int] = None) -> List[dict]:
        with self._lock:
            snap = list(self._ring)
        if last is not None and last > 0:
            snap = snap[-last:]
        return snap

    def snapshot(self) -> dict:
        """Summary for /debug/vars (the full ring rides its own
        endpoint)."""
        with self._lock:
            snap = list(self._ring)
        return {"published_total": self.published_total,
                "ring_capacity": self.capacity,
                "last_total_duration_ns":
                    snap[-1]["total_duration_ns"] if snap else None,
                "last_coverage_ratio":
                    snap[-1]["coverage_ratio"] if snap else None}

    def handler(self, query) -> tuple:
        """The GET /debug/flush-timeline route body: ``?n=K`` limits to
        the most recent K intervals. ``instance_uid`` identifies this
        process: the fleet aggregator (obs/fleet.py) drops a pulled
        peer whose uid matches its own timeline's, so an operator
        listing every instance in one shared ``fleet_peers`` never
        gets its hops stitched twice."""
        try:
            last = int(query.get("n", "0") or 0)
        except ValueError:
            return 400, "n must be an integer", "text/plain"
        body = json.dumps({
            "published_total": self.published_total,
            "ring_capacity": self.capacity,
            "instance_uid": self.uid,
            "intervals": self.entries(last or None),
        }, default=str)
        return 200, body, "application/json"

"""The flush-interval timeline ring: last-N interval records as JSON.

Each completed flush publishes its :class:`StageRecorder` record here;
``GET /debug/flush-timeline`` (debug.py) serves the ring. The ring is
bounded (``obs_timeline_intervals``, default 64) so a long-lived
server's timeline costs fixed memory, and entries are plain dicts so
the late off-path forward stage can land in an already-published
interval (recorder.record_late)."""

from __future__ import annotations

import collections
import json
import threading
import uuid
from typing import List, Optional

DEFAULT_INTERVALS = 64


class FlushTimeline:
    """Bounded ring of per-interval stage records."""

    def __init__(self, intervals: int = DEFAULT_INTERVALS):
        self.capacity = max(1, int(intervals))
        # per-process identity served at /debug/flush-timeline: how
        # the fleet aggregator recognizes a pull of ITSELF (fleet_peers
        # lists every instance, including the puller)
        self.uid = uuid.uuid4().hex
        self._ring: "collections.deque" = collections.deque(
            maxlen=self.capacity)
        # shared by publish and the read side: list(deque) raises
        # RuntimeError if an append lands mid-iteration, and the debug
        # endpoints read from arbitrary request threads while the
        # flusher (and the fleet aggregator's pulls) publish
        self._lock = threading.Lock()
        self.published_total = 0

    def publish(self, entry: dict) -> dict:
        with self._lock:
            entry["interval"] = self.published_total
            self.published_total += 1
            self._ring.append(entry)
        return entry

    def entries(self, last: Optional[int] = None) -> List[dict]:
        with self._lock:
            snap = list(self._ring)
        if last is not None and last > 0:
            snap = snap[-last:]
        return snap

    def snapshot(self) -> dict:
        """Summary for /debug/vars (the full ring rides its own
        endpoint)."""
        with self._lock:
            snap = list(self._ring)
        return {"published_total": self.published_total,
                "ring_capacity": self.capacity,
                "last_total_duration_ns":
                    snap[-1]["total_duration_ns"] if snap else None,
                "last_coverage_ratio":
                    snap[-1]["coverage_ratio"] if snap else None}

    def handler(self, query) -> tuple:
        """The GET /debug/flush-timeline route body: ``?n=K`` limits to
        the most recent K intervals. ``instance_uid`` identifies this
        process: the fleet aggregator (obs/fleet.py) drops a pulled
        peer whose uid matches its own timeline's, so an operator
        listing every instance in one shared ``fleet_peers`` never
        gets its hops stitched twice."""
        try:
            last = int(query.get("n", "0") or 0)
        except ValueError:
            return 400, "n must be an integer", "text/plain"
        body = json.dumps({
            "published_total": self.published_total,
            "ring_capacity": self.capacity,
            "instance_uid": self.uid,
            "intervals": self.entries(last or None),
        }, default=str)
        return 200, body, "application/json"

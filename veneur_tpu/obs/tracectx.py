"""The cross-hop trace contract: one context, one header, every hop.

PR 8 gave each instance a per-interval stage timeline; this module is
what lets those timelines *join*. A :class:`TraceContext` — SSF trace
id + parent span id (the 63-bit id space of ``veneur_tpu/trace``) plus
the **ingest-era stamp** (wall-clock ns of the oldest sample riding
the envelope) — is stamped into every cross-hop body:

    local forward  → ``POST /import``   (forward/http_forward.py, grpc)
    proxy fan-out  → ``POST /import``   (proxy/proxy.py, re-parented)
    resharding     → ``POST /handoff``  (fleet/handoff.py)

carried by ONE header, ``X-Veneur-Trace``, and adopted by the
receiving side: the receiver's :class:`~veneur_tpu.obs.StageRecorder`
(or its :class:`HopLog`, for merges that happen between flushes)
parents its stage tree under the sender's span, so
``GET /debug/trace?id=…`` (obs/fleet.py) can stitch local flush →
proxy fan-out → global import → global flush → sink POST into one
distributed trace. The ingest stamp survives every hop untouched — at
the global's sink 2xx it becomes ``veneur.fleet.e2e_age_ns``, the true
ingest-to-emission freshness of the fleet.

Wire format (ASCII, order-insensitive, unknown fields ignored so the
contract can grow):

    X-Veneur-Trace: trace=<u63>;parent=<u63>;ingest=<unix ns>

The stamp is WALL clock (monotonic clocks don't compare across hosts);
freshness therefore inherits fleet clock skew — same trade NTP-synced
production fleets already make for log correlation.
"""

from __future__ import annotations

import collections
import random
import threading
import time
from typing import Dict, List, Optional

HEADER = "X-Veneur-Trace"
_HEADER_LOWER = HEADER.lower()

# Every HTTP route that carries (or must accept) the X-Veneur-Trace
# header. The stage-registry lint pass (lint/stagenames.py) reads this
# list via AST and fails the build unless each route appears in
# docs/observability.md — the header contract cannot silently grow.
TRACED_ROUTES = ("/import", "/handoff")


def new_span_id() -> int:
    """A fresh 63-bit span id — the SSF id space (trace/__init__.py)."""
    return random.getrandbits(63)


class TraceContext:
    """One hop's worth of trace baggage: which distributed trace this
    envelope belongs to (``trace_id``), which span to parent the
    receiving hop under (``parent_id``), and the wall-clock ns of the
    oldest sample aboard (``ingest_ns``; 0 = unknown)."""

    __slots__ = ("trace_id", "parent_id", "ingest_ns")

    def __init__(self, trace_id: int = 0, parent_id: int = 0,
                 ingest_ns: int = 0):
        self.trace_id = int(trace_id)
        self.parent_id = int(parent_id)
        self.ingest_ns = int(ingest_ns)

    def encode(self) -> str:
        return (f"trace={self.trace_id};parent={self.parent_id};"
                f"ingest={self.ingest_ns}")

    @classmethod
    def decode(cls, value: str) -> Optional["TraceContext"]:
        """Parse a header value; None on anything unusable. Unknown
        ``k=v`` fields are ignored (forward compatibility)."""
        if not value:
            return None
        fields: Dict[str, int] = {}
        for part in value.split(";"):
            key, sep, raw = part.strip().partition("=")
            if not sep:
                continue
            try:
                fields[key] = int(raw)
            except ValueError:
                continue
        tid = fields.get("trace", 0)
        if tid <= 0:
            return None
        return cls(trace_id=tid, parent_id=max(0, fields.get("parent", 0)),
                   ingest_ns=max(0, fields.get("ingest", 0)))

    @classmethod
    def from_headers(cls, headers) -> Optional["TraceContext"]:
        """Extract from any mapping of header names (case-insensitive:
        the import carrier lowercases, http.client preserves case)."""
        if headers is None:
            return None
        value = None
        get = getattr(headers, "get", None)
        if get is not None:
            value = get(HEADER) or get(_HEADER_LOWER)
        if not value:
            for key in headers:
                if str(key).lower() == _HEADER_LOWER:
                    value = headers[key]
                    break
        return cls.decode(value) if value else None

    def child(self, parent_id: int) -> "TraceContext":
        """The context the NEXT hop should carry: same trace, same
        ingest stamp, re-parented under this hop's span (the proxy
        does this so the global's import parents under the fan-out,
        not under the local flush it already left)."""
        return TraceContext(self.trace_id, parent_id, self.ingest_ns)

    def __repr__(self):
        return f"TraceContext({self.encode()})"


class HopLog:
    """Bounded buffer of completed cross-hop records on the RECEIVING
    side — merges (``POST /import``, ``POST /handoff``) land between
    flushes, when no interval recorder is active, so they park here and
    the next flush drains them into its published timeline entry (as
    off-path stages carrying ``trace_id``), stamping the entry with the
    set of contributing trace ids (``import_traces``).

    Also the fleet-freshness accumulator: every recorded context's
    ``ingest_ns`` folds into a min, read-and-reset once per flush —
    the oldest sample whose state this instance aggregated since the
    last emission."""

    def __init__(self, capacity: int = 4096):
        self._lock = threading.Lock()
        self._hops: "collections.deque" = collections.deque(
            maxlen=max(16, capacity))
        self._oldest_ingest_ns: Optional[int] = None
        self.recorded_total = 0
        self.dropped_total = 0

    def record(self, hop: str, ctx: Optional[TraceContext],
               wall_start: float, wall_end: float, **attrs) -> dict:
        """One completed hop (wall-clock seconds, like timeline
        entries). ``ctx`` None still records (an un-traced legacy
        sender's import is real work), just unstitchable."""
        rec = dict(attrs)
        rec["hop"] = hop
        rec["span_id"] = new_span_id()
        rec["wall_start"] = wall_start
        rec["wall_end"] = wall_end
        rec["duration_ns"] = max(0, int((wall_end - wall_start) * 1e9))
        if ctx is not None:
            rec["trace_id"] = ctx.trace_id
            rec["parent_span_id"] = ctx.parent_id
            if ctx.ingest_ns:
                rec["ingest_ns"] = ctx.ingest_ns
        with self._lock:
            if len(self._hops) == self._hops.maxlen:
                self.dropped_total += 1
            self._hops.append(rec)
            self.recorded_total += 1
            if ctx is not None and ctx.ingest_ns:
                if (self._oldest_ingest_ns is None
                        or ctx.ingest_ns < self._oldest_ingest_ns):
                    self._oldest_ingest_ns = ctx.ingest_ns
        return rec

    def drain(self) -> List[dict]:
        """Take every pending hop (the flusher, once per interval)."""
        with self._lock:
            out = list(self._hops)
            self._hops.clear()
        return out

    def peek(self) -> List[dict]:
        """Read without consuming (/debug/trace between flushes)."""
        with self._lock:
            return list(self._hops)

    def take_oldest_ingest_ns(self) -> Optional[int]:
        """Read-and-reset the freshness min (once per flush; the next
        interval accumulates its own)."""
        with self._lock:
            oldest, self._oldest_ingest_ns = self._oldest_ingest_ns, None
        return oldest

    def snapshot(self) -> dict:
        with self._lock:
            return {"pending": len(self._hops),
                    "recorded_total": self.recorded_total,
                    "dropped_total": self.dropped_total,
                    "oldest_ingest_ns": self._oldest_ingest_ns}


def wall_to_mono_ns(rec, wall_s: float) -> int:
    """Map a wall-clock time onto a recorder's monotonic clock (hop
    records carry wall time; ``StageRecorder.record_abs`` wants the
    recorder's own ns base)."""
    return rec.t0_ns + int((wall_s - rec.wall_start) * 1e9)


def now_ns() -> int:
    return time.time_ns()

"""Pure JAX/Pallas sketch kernels: the device-side core of the framework."""

from veneur_tpu.ops import tdigest  # noqa: F401

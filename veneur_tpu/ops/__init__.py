"""Pure JAX/Pallas sketch kernels: the device-side core of the framework."""

from veneur_tpu.ops import hll, tdigest  # noqa: F401

"""axiomhq/hyperloglog wire codec — Set-metric interop with reference fleets.

The reference serializes Set state with the vendored axiomhq sketch's
``MarshalBinary`` (``/root/reference/samplers/samplers.go:441-465``,
``vendor/github.com/axiomhq/hyperloglog/hyperloglog.go:273-318``). Layout:

    byte 0   version (1)
    byte 1   p  (dense precision, 4..18)
    byte 2   b  (register base offset; registers store value-b clipped
                 to a 4-bit "tailcut", hyperloglog.go:166-186)
    byte 3   sparse flag

    dense  (flag 0): u32be size (= 2^p / 2), then size bytes, each
        packing registers 2i (high nibble) and 2i+1 (low nibble);
        true register value = b + nibble (after a rebase every register
        is >= b, and nibble 0 means exactly b; with b=0, 0 is empty)
    sparse (flag 1): u32be tmpSet count, count x u32be encoded hashes,
        then the compressedList: u32be count, u32be last, u32be byte
        length, varint-delta bytes (7-bit groups little-endian, high bit
        = continuation; value = previous + delta, compressed.go:102-124)

    sparse hash encoding (sparse.go:7-36, pp = 25):
        k & 1 == 1:  idx = top p bits of k[31:25+...]; rho carried in
                     bits 1..6 plus (pp - p)
        k & 1 == 0:  idx = bits [pp-p+1 : pp+1); rho = clz32 of
                     k << (32-pp+p-1), + 1

Decoding converts either representation to a dense uint8 register array
our ``SetGroup`` merges with elementwise max; encoding emits the dense
layout a reference global's ``UnmarshalBinary`` + ``Merge`` accepts.
"""

from __future__ import annotations

import struct
from typing import Tuple

import numpy as np

VERSION = 1
PP = 25  # the sparse precision constant (hyperloglog.go:13)
CAPACITY = 16  # tailcut range: nibble 0..15


class AxiomhqFormatError(ValueError):
    pass


def looks_like(blob: bytes) -> bool:
    """Cheap sniff: version 1, plausible precision, sparse flag 0/1."""
    return (len(blob) >= 4 and blob[0] == VERSION
            and 4 <= blob[1] <= 18 and blob[3] in (0, 1))


def decode(blob: bytes) -> Tuple[np.ndarray, int]:
    """axiomhq MarshalBinary bytes → (dense uint8 registers [2^p], p)."""
    if len(blob) < 4:
        raise AxiomhqFormatError("truncated axiomhq header")
    version, p, b, sparse = blob[0], blob[1], blob[2], blob[3]
    if version != VERSION:
        raise AxiomhqFormatError(f"unsupported axiomhq version {version}")
    if not 4 <= p <= 18:
        raise AxiomhqFormatError(f"precision {p} out of range")
    m = 1 << p
    if sparse == 0:
        (sz,) = struct.unpack_from(">I", blob, 4)
        if sz != m // 2:
            raise AxiomhqFormatError(
                f"dense register block is {sz} bytes, want {m // 2}")
        if len(blob) < 8 + sz:
            raise AxiomhqFormatError("truncated dense register block")
        packed = np.frombuffer(blob, np.uint8, count=sz, offset=8)
        regs = np.empty(m, np.uint8)
        regs[0::2] = packed >> 4
        regs[1::2] = packed & 0x0F
        if b:
            # after a rebase every register holds value-b; nibble 0 means
            # exactly b (registers.go:55-72 keeps relative zeros only at
            # the minimum)
            regs = regs + np.uint8(b)
        return regs, p
    # sparse: tmpSet then compressedList, every entry an encoded hash
    (ts_count,) = struct.unpack_from(">I", blob, 4)
    pos = 8
    end_ts = pos + 4 * ts_count
    if len(blob) < end_ts + 12:
        raise AxiomhqFormatError("truncated sparse tmpSet")
    keys = [np.frombuffer(blob, ">u4", count=ts_count, offset=pos)
            .astype(np.uint32)]
    pos = end_ts
    _count, _last, nbytes = struct.unpack_from(">III", blob, pos)
    pos += 12
    if len(blob) < pos + nbytes:
        raise AxiomhqFormatError("truncated sparse compressed list")
    data = blob[pos:pos + nbytes]
    # varint-delta walk (compressed.go:102-124 + 158-168)
    vals = []
    x = 0
    shift = 0
    last = 0
    for byte in data:
        if byte & 0x80:
            x |= (byte & 0x7F) << shift
            shift += 7
        else:
            x |= byte << shift
            last = (last + x) & 0xFFFFFFFF
            vals.append(last)
            x = 0
            shift = 0
    if shift:
        raise AxiomhqFormatError("dangling varint in sparse list")
    keys.append(np.asarray(vals, np.uint32))
    k = np.concatenate(keys)
    regs = np.zeros(m, np.uint8)
    if len(k):
        idx, rho = _decode_hashes(k, p)
        np.maximum.at(regs, idx, rho)
    return regs, p


def _decode_hashes(k: np.ndarray, p: int):
    """Vectorized decodeHash (sparse.go:25-36)."""
    odd = (k & 1) == 1
    idx = np.where(
        odd,
        (k >> np.uint32(32 - p)) & np.uint32((1 << p) - 1),
        (k >> np.uint32(PP - p + 1)) & np.uint32((1 << p) - 1),
    ).astype(np.int64)
    # odd: rho stored in bits 1..6, biased by pp-p
    rho_odd = ((k >> np.uint32(1)) & np.uint32(0x3F)) + np.uint32(PP - p)
    # even: rho = clz32(k << (32-pp+p-1)) + 1
    shifted = (k << np.uint32(32 - PP + p - 1)) & np.uint32(0xFFFFFFFF)
    # count leading zeros of a u32: 31 - floor(log2(x)); x==0 -> 32
    safe = np.maximum(shifted, 1)
    clz = np.uint32(31) - np.floor(np.log2(safe)).astype(np.uint32)
    clz = np.where(shifted == 0, np.uint32(32), clz)
    rho = np.where(odd, rho_odd, clz + np.uint32(1)).astype(np.uint8)
    return idx, rho


def encode_dense(registers: np.ndarray, p: int) -> bytes:
    """Dense uint8 registers → axiomhq dense MarshalBinary bytes.

    Chooses the base b the way the real sketch's rebase invariant ends
    up: b = min(register) when every register is nonzero, else 0 (a zero
    register with b > 0 would decode as b). Values past b + 15 clip to
    the 4-bit tailcut exactly as the reference's own inserts do
    (hyperloglog.go:180-186)."""
    regs = np.asarray(registers, np.uint8)
    m = 1 << p
    if regs.shape != (m,):
        raise ValueError(f"want {m} registers, got {regs.shape}")
    rmin = int(regs.min()) if m else 0
    b = rmin if rmin > 0 else 0
    rel = np.minimum(regs - np.uint8(b), np.uint8(CAPACITY - 1))
    packed = ((rel[0::2] << np.uint8(4)) | rel[1::2]).astype(np.uint8)
    return (bytes((VERSION, p, b, 0)) + struct.pack(">I", m // 2)
            + packed.tobytes())

"""Batched count-min sketch + top-k heavy hitters as dense XLA ops.

BASELINE.md config #5 asks for a streaming heavy-hitter sampler the
reference does not have: count-min (Cormode-Muthukrishnan) for frequency
estimates over an unbounded key space, plus a fixed-size top-k list.
TPU-first design:

- ONE shared ``[depth, width]`` float32 table serves every series: the
  per-row hash mixes the series row id in as a salt, so series never
  need per-series tables (the classic shared-sketch trick). Updates are
  scatter-adds; estimates are a min over ``depth`` gathered rows.
- the top-k list is per series, ``[S, K]`` id/count planes. Each drain
  concatenates (current top-k ++ batch candidates), deduplicates by id
  with a sort + segment-head mask (fixed shapes, no data-dependent
  control flow), and keeps the K largest counts via ``lax.top_k``.
- keys are 64-bit hashes carried as (hi, lo) uint32 pairs — uint64 is
  unavailable without jax x64 — and every mixing step is a murmur3
  finalizer, matching ops/hll.py's member hashing so the native parser's
  member hash feeds both sketches.

Estimates are upward-biased only (count-min guarantee); the top-k
therefore never misses a true heavy hitter whose count clears the
threshold, the property the golden tests assert against an exact dict.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

DEFAULT_DEPTH = 4
DEFAULT_WIDTH = 1 << 16
DEFAULT_TOPK = 32

# distinct odd constants per hash row (splitmix64-derived)
_ROW_SALTS = (0x9E3779B9, 0x85EBCA6B, 0xC2B2AE35, 0x27D4EB2F,
              0x165667B1, 0xD3A2646C, 0xFD7046C5, 0xB55A4F09)


class CountMin(NamedTuple):
    """table: [depth, width] f32 shared across series.
    topk_hi/lo: [S, K] uint32 key-id halves (0/0 = empty slot).
    topk_counts: [S, K] f32 estimated counts (0 = empty).
    sids: [S] uint32 INSTANCE-INDEPENDENT series ids (a stable hash of
    name+type+tags) — table columns are salted with these, NOT with the
    local row index, so tables forwarded between instances that interned
    the same series at different rows still align column-for-column."""

    table: jax.Array
    topk_hi: jax.Array
    topk_lo: jax.Array
    topk_counts: jax.Array
    sids: jax.Array

    @property
    def depth(self) -> int:
        return self.table.shape[0]

    @property
    def width(self) -> int:
        return self.table.shape[1]


def init(num_series: int = 1, depth: int = DEFAULT_DEPTH,
         width: int = DEFAULT_WIDTH, k: int = DEFAULT_TOPK) -> CountMin:
    assert depth <= len(_ROW_SALTS)
    return CountMin(
        table=jnp.zeros((depth, width), jnp.float32),
        topk_hi=jnp.zeros((num_series, k), jnp.uint32),
        topk_lo=jnp.zeros((num_series, k), jnp.uint32),
        topk_counts=jnp.zeros((num_series, k), jnp.float32),
        sids=jnp.zeros((num_series,), jnp.uint32),
    )


def _mix32(x: jax.Array) -> jax.Array:
    """murmur3 32-bit finalizer."""
    x = x.astype(jnp.uint32)
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return x


def _col_index(sids: jax.Array, hi: jax.Array, lo: jax.Array, salt: int,
               width: int) -> jax.Array:
    """Table column for one depth row: mixes (stable series id, key
    hash, row salt) so one table serves every series and depth row
    independently. The series component MUST be the instance-independent
    sid, never a local row index — forwarded tables merge elementwise
    and both ends have to hash a given (series, key) to the same column."""
    h = _mix32(hi ^ jnp.uint32(salt))
    h = _mix32(h ^ lo)
    h = _mix32(h ^ sids.astype(jnp.uint32) * jnp.uint32(0x9E3779B1))
    return (h % jnp.uint32(width)).astype(jnp.int32)


def update(sk: CountMin, rows: jax.Array, sids: jax.Array, hi: jax.Array,
           lo: jax.Array, counts: jax.Array) -> CountMin:
    """Fold one flat batch of (series row, series sid, key hash, count)
    increments into the table and refresh each touched series' top-k.

    rows: [N] int32; sids: [N] uint32 stable series ids (see CountMin);
    padding uses counts == 0 (its updates add zero and its candidates
    lose every top-k comparison).
    """
    depth, width = sk.depth, sk.width
    s, k = sk.topk_counts.shape
    counts = counts.astype(jnp.float32)
    # teach the sketch its rows' stable ids (idempotent writes)
    sk = sk._replace(sids=sk.sids.at[rows].set(sids, mode="drop"))
    table = sk.table
    idxs = []
    for d in range(depth):
        idx = _col_index(sids, hi, lo, _ROW_SALTS[d], width)
        idxs.append(idx)
        table = table.at[d, idx].add(counts)
    # conservative estimate after the adds: min over depth rows
    est = jnp.full(rows.shape, jnp.inf, jnp.float32)
    for d in range(depth):
        est = jnp.minimum(est, table[d, idxs[d]])
    est = jnp.where(counts > 0, est, 0.0)

    # refresh the standing top-k entries from the table: their counts
    # must track later increments even when the key loses its candidate
    # slot to a ring collision this drain
    cur_ct = jnp.full(sk.topk_counts.shape, jnp.inf, jnp.float32)
    for d in range(depth):
        idx = _col_index(jnp.broadcast_to(sk.sids[:, None],
                                          sk.topk_hi.shape),
                         sk.topk_hi, sk.topk_lo, _ROW_SALTS[d], width)
        cur_ct = jnp.minimum(cur_ct, table[d, idx])
    cur_ct = jnp.where(sk.topk_counts > 0, cur_ct, 0.0)

    # merge batch candidates into the per-series top-k lists:
    # scatter each candidate's (id, est) into its series' candidate slot
    # ring, then dedupe + select per series. A batch can carry more
    # candidates than ring slots per series; colliding candidates
    # overwrite (they re-enter on a later drain — top-k convergence only
    # needs repeated exposure, not completeness per batch; standing
    # members never rely on candidacy thanks to the refresh above).
    ring = 4 * k  # candidate slots per series this drain
    # salt the slot hash with the (monotonically growing) table mass so a
    # pair of keys colliding this drain lands apart on a later one —
    # a fixed slot hash would starve one of them forever
    rsalt = _mix32(jnp.sum(table[0]).astype(jnp.uint32))
    slot = _mix32(hi ^ lo ^ rsalt) % jnp.uint32(ring)
    srows = jnp.where(counts > 0, rows, s).astype(jnp.int32)
    cand_hi = jnp.zeros((s, ring), jnp.uint32).at[srows, slot].set(
        hi, mode="drop")
    cand_lo = jnp.zeros((s, ring), jnp.uint32).at[srows, slot].set(
        lo, mode="drop")
    cand_ct = jnp.zeros((s, ring), jnp.float32).at[srows, slot].set(
        est, mode="drop")

    all_hi = jnp.concatenate([sk.topk_hi, cand_hi], axis=1)
    all_lo = jnp.concatenate([sk.topk_lo, cand_lo], axis=1)
    all_ct = jnp.concatenate([cur_ct, cand_ct], axis=1)
    top_hi, top_lo, top_ct = _dedupe_topk(all_hi, all_lo, all_ct, k)
    return sk._replace(table=table, topk_hi=top_hi, topk_lo=top_lo,
                       topk_counts=top_ct)


def _dedupe_topk(all_hi, all_lo, all_ct, k: int):
    """Per-series candidate selection: sort by (hi, lo), keep each id's
    max count at its first occurrence, zero the duplicates, take top k."""
    shi, slo, sct = lax.sort((all_hi, all_lo, all_ct), dimension=-1,
                             num_keys=2, is_stable=False)
    same = jnp.concatenate(
        [jnp.zeros_like(shi[:, :1], bool),
         (shi[:, 1:] == shi[:, :-1]) & (slo[:, 1:] == slo[:, :-1])], axis=1)
    # max count within each equal-id run, propagated left to the head
    run_max = _rev_seg_max(sct, same)
    sct = jnp.where(same, 0.0, run_max)
    sct = jnp.where((shi == 0) & (slo == 0), 0.0, sct)  # empty slots
    top_ct, top_i = lax.top_k(sct, k)
    top_hi = jnp.take_along_axis(shi, top_i, axis=1)
    top_lo = jnp.take_along_axis(slo, top_i, axis=1)
    live = top_ct > 0
    return (jnp.where(live, top_hi, 0), jnp.where(live, top_lo, 0), top_ct)


def add_table(sk: CountMin, table: jax.Array) -> CountMin:
    """Merge another instance's count-min table: elementwise add (the
    sketch is additively mergeable — columns align across instances
    because both hash with stable sids), then refresh every standing
    top-k member's estimate against the combined table — a forwarded
    table can raise counts for keys this instance already tracks."""
    table = sk.table + table.astype(jnp.float32)
    cur_ct = jnp.full(sk.topk_counts.shape, jnp.inf, jnp.float32)
    for d in range(sk.depth):
        idx = _col_index(jnp.broadcast_to(sk.sids[:, None],
                                          sk.topk_hi.shape),
                         sk.topk_hi, sk.topk_lo, _ROW_SALTS[d],
                         sk.width)
        cur_ct = jnp.minimum(cur_ct, table[d, idx])
    cur_ct = jnp.where(sk.topk_counts > 0, cur_ct, 0.0)
    return sk._replace(table=table, topk_counts=cur_ct)


def inject_candidates(sk: CountMin, rows: jax.Array, sids: jax.Array,
                      hi: jax.Array, lo: jax.Array,
                      slots: jax.Array) -> CountMin:
    """Offer forwarded top-k candidates (no count contribution — their
    mass arrived via add_table): estimate each against the current table
    and merge into the per-series top-k lists.

    rows: [N] int32 with out-of-range = padding; sids: [N] uint32 stable
    series ids; (hi, lo) == (0, 0) is also padding. slots: [N] int32,
    the candidate's index within its series' forwarded list — callers
    know it exactly (a forwarded list has at most K entries), which
    makes the scatter collision-free without any ring hashing."""
    s, k = sk.topk_counts.shape
    live = (rows >= 0) & (rows < s) & ((hi != 0) | (lo != 0))
    sk = sk._replace(sids=sk.sids.at[rows].set(sids, mode="drop"))
    est = jnp.full(rows.shape, jnp.inf, jnp.float32)
    for d in range(sk.depth):
        idx = _col_index(sids, hi, lo, _ROW_SALTS[d], sk.width)
        est = jnp.minimum(est, sk.table[d, idx])
    est = jnp.where(live, est, 0.0)
    ring = k
    srows = jnp.where(live, rows, s).astype(jnp.int32)
    slot = jnp.minimum(slots.astype(jnp.int32), ring - 1)
    cand_hi = jnp.zeros((s, ring), jnp.uint32).at[srows, slot].set(
        hi, mode="drop")
    cand_lo = jnp.zeros((s, ring), jnp.uint32).at[srows, slot].set(
        lo, mode="drop")
    cand_ct = jnp.zeros((s, ring), jnp.float32).at[srows, slot].set(
        est, mode="drop")
    all_hi = jnp.concatenate([sk.topk_hi, cand_hi], axis=1)
    all_lo = jnp.concatenate([sk.topk_lo, cand_lo], axis=1)
    all_ct = jnp.concatenate([sk.topk_counts, cand_ct], axis=1)
    top_hi, top_lo, top_ct = _dedupe_topk(all_hi, all_lo, all_ct, k)
    return sk._replace(topk_hi=top_hi, topk_lo=top_lo,
                       topk_counts=top_ct)


def _rev_seg_max(x: jax.Array, same: jax.Array) -> jax.Array:
    """Per segment (runs where ``same`` is True continue the previous
    element's segment), the max of the whole run written at every element,
    via a right-to-left log-step segmented scan.

    same[i] says element i belongs to i-1's segment; prop[i] tracks
    whether position i can absorb from i+1 (initially same[i+1]), and
    composes as prop'[i] = prop[i] & prop[i+d] so absorption never
    crosses a segment boundary."""
    def shl(a, d, fill):
        pad = jnp.full(a.shape[:-1] + (d,), fill, a.dtype)
        return jnp.concatenate([a[:, d:], pad], axis=1)

    n = x.shape[-1]
    prop = shl(same, 1, False)
    val = x
    d = 1
    while d < n:
        val = jnp.where(prop, jnp.maximum(val, shl(val, d, 0.0)), val)
        prop = prop & shl(prop, d, False)
        d *= 2
    return val


def estimate(sk: CountMin, rows: jax.Array, hi: jax.Array,
             lo: jax.Array) -> jax.Array:
    """Point-query frequency estimates for (series row, key) pairs;
    rows resolve to stable sids through the sketch's sid plane."""
    sids = sk.sids[jnp.clip(rows, 0, sk.sids.shape[0] - 1)]
    est = jnp.full(rows.shape, jnp.inf, jnp.float32)
    for d in range(sk.depth):
        idx = _col_index(sids, hi, lo, _ROW_SALTS[d], sk.width)
        est = jnp.minimum(est, sk.table[d, idx])
    return est

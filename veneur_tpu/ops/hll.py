"""Batched dense HyperLogLog as XLA tensor ops.

The reference's ``Set`` sampler wraps the vendored axiomhq/hyperloglog
(``/root/reference/samplers/samplers.go:367-435``): a 2^14-register sketch
whose member-insert takes a 64-bit hash, indexes a register with the top ``p``
bits, and stores the max leading-zero-run (+1) of the remaining bits; merge is
an elementwise register ``max`` and the cardinality estimate is the classic
bias-corrected harmonic mean with linear-counting small-range correction.

Here the state for S series is one dense ``[S, m]`` (``m = 2^p``) int32 tensor
so that:

    * insert   = a scatter-max of (row, register, rho) triples — rho/idx are
      derived from the raw 64-bit hash *on device* from two uint32 halves
      (JAX runs without 64-bit types enabled) using ``lax.clz``;
    * merge    = ``jnp.maximum`` — and across a device mesh, ``pmax`` over ICI,
      which is the whole global-aggregation story for sets
      (cf. ``samplers.Set.Combine/Merge``, ``samplers.go:423-435``);
    * estimate = two row-reductions (harmonic sum + zero count), all series at
      once.

Registers are int32 rather than uint8: TPU vector ops prefer 32-bit lanes and
the value range is [0, 64-p+1].
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

DEFAULT_PRECISION = 14  # axiomhq New() default (hyperloglog.go:31-37)


def num_registers(precision: int = DEFAULT_PRECISION) -> int:
    if not 4 <= precision <= 18:
        raise ValueError("precision must be in [4, 18]")
    return 1 << precision


def init(batch_shape: Sequence[int] = (), precision: int = DEFAULT_PRECISION,
         dtype=jnp.int32) -> jax.Array:
    """Empty register tensors for a batch of series: [..., 2^p] zeros."""
    return jnp.zeros(tuple(batch_shape) + (num_registers(precision),), dtype)


def _clz32(x: jax.Array) -> jax.Array:
    """Count leading zeros of a uint32 array (clz(0) == 32)."""
    return lax.clz(x.astype(jnp.uint32)).astype(jnp.int32)


def idx_rho(hash_hi: jax.Array, hash_lo: jax.Array, precision: int = DEFAULT_PRECISION):
    """Split 64-bit hashes (as two uint32 halves) into (register index, rho).

    Mirrors the reference insert path: idx = top p bits, rho = leading zeros
    of the remaining 64-p bits + 1, capped at 64-p+1.
    """
    p = precision
    hi = hash_hi.astype(jnp.uint32)
    lo = hash_lo.astype(jnp.uint32)
    idx = (hi >> (32 - p)).astype(jnp.int32)
    # rest = (hash << p) in 64 bits, carried as two 32-bit halves.
    top = (hi << p) | (lo >> (32 - p))
    bot = lo << p
    clz = jnp.where(top != 0, _clz32(top), 32 + _clz32(bot))
    rho = jnp.minimum(clz + 1, 64 - p + 1)
    return idx, rho


def insert(registers: jax.Array, rows: jax.Array, hash_hi: jax.Array,
           hash_lo: jax.Array, mask: jax.Array | None = None,
           precision: int = DEFAULT_PRECISION) -> jax.Array:
    """Scatter a flat batch of hashed members into their series' sketches.

    registers: [S, m]; rows/hash_hi/hash_lo: [N] int32/uint32; mask: [N] bool
    (False = padding). Duplicate (row, idx) pairs resolve by max, so the op is
    idempotent and order-free like the reference's register update.
    """
    idx, rho = idx_rho(hash_hi, hash_lo, precision)
    if mask is not None:
        rho = jnp.where(mask, rho, 0)  # rho 0 never beats an existing register
        rows = jnp.where(mask, rows, 0)
        idx = jnp.where(mask, idx, 0)
    return registers.at[rows, idx].max(rho.astype(registers.dtype))


def merge(a: jax.Array, b: jax.Array) -> jax.Array:
    """Elementwise register max — the associative merge (samplers.go:423-435).
    Across a mesh this is simply ``lax.pmax`` on the same tensors."""
    return jnp.maximum(a, b)


def estimate(registers: jax.Array, precision: int = DEFAULT_PRECISION) -> jax.Array:
    """Batched cardinality estimate: [..., m] -> [...] float32.

    Classic HLL estimator with linear-counting small-range correction,
    matching ScalarHLL (the golden model for axiomhq's dense path).
    """
    p = precision
    m = float(1 << p)
    if p >= 7:
        alpha = 0.7213 / (1 + 1.079 / m)
    else:
        alpha = {4: 0.673, 5: 0.697, 6: 0.709}[p]
    r = registers.astype(jnp.float32)
    raw_inv = jnp.sum(jnp.exp2(-r), axis=-1)
    est = alpha * m * m / raw_inv
    zeros = jnp.sum((registers == 0).astype(jnp.float32), axis=-1)
    lc = m * jnp.log(m / jnp.maximum(zeros, 1.0))
    use_lc = (est <= 2.5 * m) & (zeros > 0)
    return jnp.where(use_lc, lc, est)


# ---------------------------------------------------------------------------
# Host-side helpers (not jitted): hashing members to 64-bit values.
# ---------------------------------------------------------------------------

_FNV64_OFFSET = 14695981039346656037
_FNV64_PRIME = 1099511628211
_MASK64 = (1 << 64) - 1


def _fmix64(h: int) -> int:
    """murmur3 64-bit finalizer: full avalanche so every input bit diffuses
    into the top p bits that pick the register."""
    h ^= h >> 33
    h = h * 0xFF51AFD7ED558CCD & _MASK64
    h ^= h >> 33
    h = h * 0xC4CEB9FE1A85EC53 & _MASK64
    h ^= h >> 33
    return h


def hash_member(member: bytes) -> int:
    """64-bit hash of a set member: FNV-1a core + murmur3 finalizer
    (host-side; the reference hashes members with metrohash inside axiomhq —
    any well-mixed 64-bit hash preserves the HLL accuracy contract). FNV-1a
    alone has weak high-bit avalanche for common-prefix names, which are the
    norm for metric members, so the finalizer is required."""
    h = _FNV64_OFFSET
    for byte in member:
        h = (h ^ byte) * _FNV64_PRIME & _MASK64
    return _fmix64(h)


def split_hashes(hashes: np.ndarray):
    """uint64 [N] -> (hi, lo) uint32 halves for device transfer."""
    hashes = np.asarray(hashes, np.uint64)
    hi = (hashes >> np.uint64(32)).astype(np.uint32)
    lo = (hashes & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    return hi, lo

"""Batched merging t-digest as dense XLA tensor ops.

The reference implementation (Dunning's merging t-digest,
``/root/reference/tdigest/merging_digest.go``) maintains, per metric series, a
sorted list of (mean, weight) centroids and merges new samples with an
inherently sequential greedy scan (``mergeAllTemps``, ``merging_digest.go:135``)
that walks centroids in mean order and fuses neighbours while the k-scale index
``k(q) = C * (asin(2q-1)/pi + 1/2)`` (``merging_digest.go:254-257``) advances by
less than one.

That scan does not vectorise. This module re-derives the merge for TPU as a
data-parallel program over *all* series at once:

    1. sort         -- per-row sort of the concatenated centroid/sample list
    2. prefix sum   -- cumulative weight gives each centroid its quantile q
    3. k-binning    -- cluster id = floor(k(q_mid)); k-width of every cluster
                       is <= 1, the same invariant the greedy scan enforces
    4. segmented reduce -- per-cluster weight and weighted-mean via two more
                       prefix sums + a row-wise binary search over the
                       (monotone) cluster ids

Everything is fixed-shape: a digest is a ``[..., K]`` pair of mean/weight
arrays (weight==0 marks an empty slot), so the whole state for S series is a
dense ``[S, K]`` tensor that jit/vmap/shard_map can slice across a device mesh.
Quantile/CDF queries mirror the uniform-centroid interpolation of the
reference (``merging_digest.go:261-327``) as gathers over cumulative weights.

Accuracy contract: same k-scale, same size bound (ceil(pi*C/2) slots), so
quantile error stays within the documented t-digest bounds used by the
reference's tests (eps=0.02, ``tdigest/histo_test.go:11-25``).
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
from jax import lax

DEFAULT_COMPRESSION = 100.0


def size_bound(compression: float) -> int:
    """Slots a digest needs under this module's floor(k) binning, rounded up
    to a multiple of 8 for TPU sublane alignment.

    The reference's greedy scan can pack up to ceil(pi*C/2) centroids
    (merging_digest.go:66-68); our re-derivation assigns cluster id
    floor(k(q_mid)) with k in [0, C), so at most C+1 bins are ever live
    (+1 more of fp headroom: the clipped asin can round k to exactly C).
    Tighter rows mean ~35% less HBM per digest plane and a narrower
    bitonic merge in the Pallas kernel, with bit-identical results: the
    extra slots were provably always empty."""
    raw = int(compression) + 2
    return (raw + 7) // 8 * 8


def temp_buffer_size(compression: float) -> int:
    """Heuristic ingest-buffer size per merge pass (merging_digest.go:101-107),
    rounded up to a multiple of 8."""
    c = min(925.0, max(20.0, compression))
    raw = int(7.5 + 0.37 * c - 2e-4 * c * c)
    return (raw + 7) // 8 * 8


class TDigest(NamedTuple):
    """A batch of t-digests as dense arrays.

    mean / weight: ``[..., K]``; liveness is defined SOLELY by
    weight > 0. Live means ascend within a row, but dead slots may sit
    anywhere with any placeholder mean (+inf from the sort-based compress,
    gap-filled running-max values or -inf from the Pallas compress) —
    consumers must mask on weight, never on the mean.
    min / max: ``[...]`` observed extrema (+inf/-inf when empty).
    """

    mean: jax.Array
    weight: jax.Array
    min: jax.Array
    max: jax.Array

    @property
    def batch_shape(self):
        return self.mean.shape[:-1]

    @property
    def capacity(self) -> int:
        return self.mean.shape[-1]

    def count(self) -> jax.Array:
        return jnp.sum(self.weight, axis=-1)


def init(batch_shape: Sequence[int] = (), compression: float = DEFAULT_COMPRESSION,
         capacity: int | None = None, dtype=jnp.float32) -> TDigest:
    """Create empty digests for a batch of series."""
    k = capacity if capacity is not None else size_bound(compression)
    shape = tuple(batch_shape)
    return TDigest(
        mean=jnp.full(shape + (k,), jnp.inf, dtype),
        weight=jnp.zeros(shape + (k,), dtype),
        min=jnp.full(shape, jnp.inf, dtype),
        max=jnp.full(shape, -jnp.inf, dtype),
    )


def _shift_last(x: jax.Array, d: int, fill) -> jax.Array:
    """out[..., i] = x[..., i-d], left-filled — building block for the
    log-step cumulative ops below."""
    pad_shape = x.shape[:-1] + (d,)
    pad = jnp.full(pad_shape, fill, x.dtype)
    return jnp.concatenate([pad, x[..., :-d]], axis=-1)


def _cumsum(x: jax.Array) -> jax.Array:
    """Inclusive prefix sum along the last axis via log-step shifted adds.
    XLA lowers cumsum through reduce-window on TPU, which for the short
    trailing axes used here costs ~10x more than these O(log n) passes."""
    d, n = 1, x.shape[-1]
    while d < n:
        x = x + _shift_last(x, d, 0)
        d *= 2
    return x


def _cummax(x: jax.Array) -> jax.Array:
    """Inclusive running max along the last axis (log-step)."""
    d, n = 1, x.shape[-1]
    while d < n:
        x = jnp.maximum(x, _shift_last(x, d, -jnp.inf))
        d *= 2
    return x


def _cummin_rev(x: jax.Array) -> jax.Array:
    """Suffix (right-to-left) running min along the last axis, without the
    flip-materializing lax.cummin formulation."""
    d, n = 1, x.shape[-1]
    while d < n:
        shifted = jnp.concatenate(
            [x[..., d:], jnp.full(x.shape[:-1] + (d,), jnp.inf, x.dtype)],
            axis=-1)
        x = jnp.minimum(x, shifted)
        d *= 2
    return x


def _rowwise_searchsorted(a: jax.Array, v: jax.Array, side: str) -> jax.Array:
    """searchsorted along the last axis for every row of a batch.

    a: [..., M] row-sorted values; v: [..., P] (or [P], broadcast) queries.

    Computed as a fused broadcast-compare-reduce (count of elements before
    the insertion point) rather than a vmapped binary search: the scan-based
    search lowers to ~1000x slower code on TPU, while the [.., P, M] compare
    fuses into one VPU reduction and never materializes.
    """
    batch = a.shape[:-1]
    if v.ndim == 1:
        v = jnp.broadcast_to(v, batch + v.shape)
    av = a[..., None, :]          # [..., 1, M]
    vv = v[..., :, None]          # [..., P, 1]
    before = (av < vv) if side == "left" else (av <= vv)
    return jnp.sum(before, axis=-1, dtype=jnp.int32)


def _select_at(arr: jax.Array, idx: jax.Array) -> jax.Array:
    """Fused per-row gather: out[..., p] = arr[..., idx[..., p]].

    arr: [..., M]; idx: [..., P] int32. TPU's native row-gather
    (take_along_axis) runs ~20x slower than this one-hot compare+reduce for
    small P, which fuses into a single VPU pass and never materializes the
    [..., P, M] intermediate.
    """
    return _select_many_at([arr], idx)[0]


def _select_many_at(arrs: Sequence[jax.Array], idx: jax.Array):
    """_select_at for several arrays sharing one index set: the one-hot
    compare is computed once and reused for every gather."""
    m = arrs[0].shape[-1]
    pos = jnp.arange(m, dtype=jnp.int32)
    hit = idx[..., :, None] == pos        # [..., P, M]
    return [jnp.sum(jnp.where(hit, a[..., None, :], 0), axis=-1)
            for a in arrs]


def _compress(mean: jax.Array, weight: jax.Array, compression: float,
              out_size: int) -> tuple[jax.Array, jax.Array]:
    """Re-cluster per-row centroid lists down to <= out_size centroids.

    mean/weight: [..., M] unsorted; weight==0 slots ignored. Returns sorted,
    front-compacted [..., out_size] arrays (empty slots mean=+inf, weight=0).
    """
    dtype = mean.dtype
    live = weight > 0
    key = jnp.where(live, mean, jnp.inf)
    # Sort each row by mean; empties ride to the back.
    key, w = lax.sort((key, weight), dimension=-1, num_keys=1, is_stable=True)
    live = w > 0
    m0 = jnp.where(live, key, 0.0)  # inf*0 would poison the weighted sums

    incl = _cumsum(w)
    total = incl[..., -1:]
    safe_total = jnp.maximum(total, jnp.finfo(dtype).tiny)
    q_mid = (incl - 0.5 * w) / safe_total
    # k-scale (merging_digest.go:254-257); arcsin arg clipped for fp safety.
    k = compression * (jnp.arcsin(jnp.clip(2.0 * q_mid - 1.0, -1.0, 1.0)) / jnp.pi + 0.5)
    cluster = jnp.clip(jnp.floor(k), 0, out_size - 1).astype(jnp.int32)
    cluster = jnp.where(live, cluster, out_size)  # park empties out of range

    # Segmented sums per cluster id as a fused mask-reduce: the [.., K, M]
    # compare broadcasts fuse into one VPU reduction. (The boundary-gather
    # formulation — prefix sums + searchsorted + take_along_axis — is ~20x
    # slower on TPU because row-gathers don't vectorize.)
    targets = jnp.arange(out_size, dtype=jnp.int32)
    hit = cluster[..., None, :] == targets[:, None]          # [.., K, M]
    sum_w = jnp.sum(jnp.where(hit, w[..., None, :], 0), axis=-1)
    sum_wm = jnp.sum(jnp.where(hit, (w * m0)[..., None, :], 0), axis=-1)

    new_live = sum_w > 0
    new_mean = jnp.where(new_live, sum_wm / jnp.where(new_live, sum_w, 1.0), jnp.inf)
    # Bins that floor(k) skipped are empty and interleave; one more sort
    # compacts live centroids (already in ascending mean order) to the front.
    new_mean, new_w = lax.sort((new_mean, sum_w), dimension=-1, num_keys=1, is_stable=True)
    return new_mean, new_w


def _dispatch_compress_presorted(mean_a, weight_a, mean_b, weight_b,
                                 compression: float, out_size: int,
                                 sort_b: bool = False,
                                 use_pallas: bool = True):
    """Compress the union of a row-ASCENDING centroid list with a second
    list (ascending, or any order with sort_b=True and +inf empties):
    the fused Pallas merge kernel on TPU, the sort-based _compress
    elsewhere (which orders everything itself). ``use_pallas=False``
    forces the sort-based path even on TPU — the compute breaker's
    fallback rung (resilience/compute.py); trace-time static, so each
    value compiles its own program variant."""
    from veneur_tpu.ops import tdigest_pallas

    if use_pallas and tdigest_pallas.pallas_ok(mean_a):
        return tdigest_pallas.compress_presorted(
            mean_a, weight_a, mean_b, weight_b, compression, out_size,
            sort_b=sort_b)
    mean = jnp.concatenate([mean_a, mean_b], axis=-1)
    weight = jnp.concatenate([weight_a, weight_b], axis=-1)
    return _compress(mean, weight, compression, out_size)


def merge_samples(state: TDigest, values: jax.Array, weights: jax.Array,
                  compression: float = DEFAULT_COMPRESSION) -> TDigest:
    """Fold a padded batch of raw samples into every digest.

    values/weights: [..., T]; weight==0 marks padding. The TPU analogue of
    draining tempCentroids (merging_digest.go:111-132 + mergeAllTemps).
    """
    values = values.astype(state.mean.dtype)
    weights = weights.astype(state.weight.dtype)
    live = weights > 0
    vmin = jnp.min(jnp.where(live, values, jnp.inf), axis=-1)
    vmax = jnp.max(jnp.where(live, values, -jnp.inf), axis=-1)
    mean = jnp.concatenate([state.mean, jnp.where(live, values, jnp.inf)], axis=-1)
    weight = jnp.concatenate([state.weight, weights], axis=-1)
    new_mean, new_weight = _compress(mean, weight, compression, state.capacity)
    return TDigest(
        mean=new_mean,
        weight=new_weight,
        min=jnp.minimum(state.min, vmin),
        max=jnp.maximum(state.max, vmax),
    )


def merge(a: TDigest, b: TDigest, compression: float = DEFAULT_COMPRESSION) -> TDigest:
    """Merge digest batches elementwise: the associative op behind the global
    aggregation tree (samplers.Histo.Combine / Merge, samplers.go:657-691).

    Deterministic (sorted merge order) unlike the reference's shuffled re-add
    (merging_digest.go:358-370); accuracy bound is the same.
    """
    new_mean, new_weight = _dispatch_compress_presorted(
        a.mean, a.weight, b.mean, b.weight, compression, a.capacity)
    return TDigest(
        mean=new_mean,
        weight=new_weight,
        min=jnp.minimum(a.min, b.min),
        max=jnp.maximum(a.max, b.max),
    )


def _upper_bounds(state: TDigest) -> jax.Array:
    """Per-centroid upper bound: midpoint to the next live centroid, or max
    for the last live one (merging_digest.go:339-354). [..., K].

    Rows may contain weight==0 gap slots anywhere (the compress skips the
    compaction sort), so "next" means the next LIVE centroid: a reversed
    running min over masked means, which the ascending-row invariant makes
    exact."""
    m, w = state.mean, state.weight
    live = w > 0
    masked = jnp.where(live, m, jnp.inf)
    suffix = _cummin_rev(masked)
    next_m = jnp.concatenate(
        [suffix[..., 1:], jnp.full_like(suffix[..., :1], jnp.inf)], axis=-1)
    mx = state.max[..., None]
    live_ub = jnp.where(jnp.isfinite(next_m), 0.5 * (m + next_m), mx)
    # gaps inherit the previous live slot's bound (leading gaps get -inf,
    # below every query) so cumulative searches stay monotone
    gapped = jnp.where(live, live_ub, -jnp.inf)
    return _cummax(gapped)


def quantile(state: TDigest, qs: jax.Array) -> jax.Array:
    """Batched inverse-CDF (merging_digest.go:297-327).

    qs: [P] in [0, 1] (shared across the batch). Returns [..., P]; NaN for
    empty digests.
    """
    qs = jnp.asarray(qs, state.mean.dtype)
    w = state.weight
    incl = _cumsum(w)                                   # [..., K]
    total = incl[..., -1:]                              # [..., 1]
    excl = incl - w
    ub = _upper_bounds(state)
    target = qs * total                                  # [..., P]
    # First centroid i with incl[i] >= target  <=>  Go's q <= weightSoFar + c.W
    idx = jnp.clip(_rowwise_searchsorted(incl, target, "left"), 0, state.capacity - 1)
    lb0 = state.min[..., None]
    # ub shifted right one slot: gathering it at idx yields ub[idx-1]
    ub_prev = jnp.concatenate([ub[..., :1], ub[..., :-1]], axis=-1)
    ub_i, prev_ub, w_i, excl_i = _select_many_at([ub, ub_prev, w, excl], idx)
    # leading gap slots carry ub == -inf; a query landing in the first
    # live centroid must fall back to min, not -inf
    lb = jnp.where(idx == 0, lb0, jnp.maximum(prev_ub, lb0))
    prop = (target - excl_i) / jnp.where(w_i > 0, w_i, 1.0)
    out = lb + prop * (ub_i - lb)
    return jnp.where(total > 0, out, jnp.nan)


def cdf(state: TDigest, xs: jax.Array) -> jax.Array:
    """Batched CDF (merging_digest.go:261-293). xs: [P] shared queries.
    Returns [..., P]; NaN for empty digests."""
    xs = jnp.asarray(xs, state.mean.dtype)
    w = state.weight
    incl = _cumsum(w)
    total = incl[..., -1:]
    excl = incl - w
    ub = _upper_bounds(state)
    # First centroid whose upper bound exceeds x (the one x falls inside).
    idx = jnp.clip(_rowwise_searchsorted(ub, xs, "right"), 0, state.capacity - 1)
    mn = state.min[..., None]
    mx = state.max[..., None]
    ub_prev = jnp.concatenate([ub[..., :1], ub[..., :-1]], axis=-1)
    ub_i, prev_ub, w_i, excl_i = _select_many_at([ub, ub_prev, w, excl], idx)
    lb = jnp.where(idx == 0, mn, jnp.maximum(prev_ub, mn))
    span = ub_i - lb
    frac = jnp.where(span > 0, (xs - lb) / jnp.where(span > 0, span, 1.0), 0.0)
    est = (excl_i + w_i * frac) / jnp.maximum(total, jnp.finfo(w.dtype).tiny)
    est = jnp.where(xs <= mn, 0.0, est)
    est = jnp.where(xs >= mx, 1.0, est)
    return jnp.where(total > 0, est, jnp.nan)


# 8 anchors = 64 B/row of f32 summary state: the 10M-series bf16
# capacity plan (core/slab.py) has ~3 GB of headroom, and 32 anchors'
# 256 B/row (2.6 GB at 10M) blew it — measured as RESOURCE_EXHAUSTED
# across the 10M bench configs. f32 stays: bf16 scatter-adds stop
# accumulating once a segment's mass crosses ~2^8 (8 mantissa bits),
# which would silently re-chunk-relativize the anchoring for hot rows.
BELOW_MASS_ANCHORS = 8


def seg_of_bins(bins: jax.Array, capacity: int) -> jax.Array:
    """Map k-bin ids onto the BELOW_MASS_ANCHORS quantile segments of
    the incremental anchor summary (seg planes in TempCentroids)."""
    return (bins * BELOW_MASS_ANCHORS) // max(capacity, 1)


def bin_flat_samples(rows: jax.Array, values: jax.Array, weights: jax.Array,
                     num_series: int, capacity: int,
                     compression: float = DEFAULT_COMPRESSION,
                     acc_seg_w: jax.Array | None = None,
                     acc_seg_wm: jax.Array | None = None,
                     acc_anchors: int = BELOW_MASS_ANCHORS):
    """Pre-cluster a flat batch of (row, value, weight) samples into k-bins.

    The streaming-ingest half of the TPU t-digest: instead of a per-digest
    temp buffer drained by a sequential scan (merging_digest.go:111-219),
    a whole chunk of samples — any mix of series, any skew — is

        1. sorted by (row, value),
        2. given within-row quantiles via one global prefix sum plus a
           cummax-propagated segment base (no data-dependent shapes),
        3. assigned cluster id floor(k(q_mid)) under the same k-scale the
           reference uses, so every bin spans k-width <= 1.

    rows: [N] int32 in [0, num_series); padding entries must use
    ``rows == num_series`` (they sort to the back and scatter with
    mode='drop'). Returns (rows, values, weights, bins) sorted by row.

    acc_seg_w / acc_seg_wm ([S, A] or flat [S*A], A=BELOW_MASS_ANCHORS):
    the temp's INCREMENTAL anchor summary as accumulated BEFORE this
    chunk (TempCentroids.seg_w/seg_wm — maintained by two extra
    scatters per ingest, so the correction never re-reads the full
    [S, K] bin planes). When given, each sample's quantile is
    estimated against the accumulated-plus-chunk distribution
    (interpolated below-mass from the summary + the exact within-chunk
    rank), so bins stay VALUE-COHERENT across chunks. Without the
    correction, bin ids are chunk-relative, and ordered arrival (a
    sorted replay, a step change, a strong in-interval trend) aliases
    low early values with high late values in the same bin — measured
    up to 0.44 rank error in the accuracy sweep
    (analysis/tdigest_sweep.py, the regression this argument fixes).
    On the first chunk the summary is empty and the behavior is
    exactly the uncorrected one.
    """
    values = values.astype(jnp.float32)
    weights = weights.astype(jnp.float32)
    r, v, w = lax.sort((rows, values, weights), dimension=-1, num_keys=2,
                       is_stable=False)
    cw = _cumsum(w)
    excl = cw - w
    seg_start = jnp.concatenate(
        [jnp.ones((1,), bool), r[1:] != r[:-1]])
    base = jnp.where(seg_start, excl, -jnp.inf)
    base = _cummax(base)
    q_excl = excl - base
    totals = jnp.zeros((num_series + 1,), w.dtype).at[r].add(w, mode="drop")
    tot = jnp.maximum(totals[jnp.minimum(r, num_series)], jnp.finfo(w.dtype).tiny)
    if acc_seg_w is not None:
        below, acc_tot = _acc_below_mass(
            r, v, acc_seg_w, acc_seg_wm, num_series, acc_anchors)
        q_mid = (below + q_excl + 0.5 * w) / jnp.maximum(
            tot + acc_tot, jnp.finfo(w.dtype).tiny)
    else:
        q_mid = (q_excl + 0.5 * w) / tot
    k = compression * (jnp.arcsin(jnp.clip(2.0 * q_mid - 1.0, -1.0, 1.0)) / jnp.pi + 0.5)
    bins = jnp.clip(jnp.floor(k), 0, capacity - 1).astype(jnp.int32)
    return r, v, w, bins


def _packed_below_mass(r: jax.Array, v: jax.Array, mq: jax.Array,
                       wb: jax.Array, fmin: jax.Array, fmax: jax.Array,
                       num_series: int, capacity: int):
    """Per-sample accumulated mass below its value from the PACKED
    centroid planes (step attribution at centroid granularity — the
    pool compression keeps centroid mass within the k-scale envelope,
    so the half-centroid error is the same bound a t-digest admits).
    Gathers only the chunk's rows before dequantizing: [N, PK] work,
    the same cost class as the bracket compares."""
    rc = jnp.minimum(r, num_series - 1)
    pm, pw = dequantize_centroids(
        mq.reshape(num_series, capacity)[rc],
        wb.reshape(num_series, capacity)[rc], fmin[rc], fmax[rc])
    live = pw > 0
    below = (jnp.sum(jnp.where(live & (pm < v[:, None]), pw, 0.0), axis=1)
             + 0.5 * jnp.sum(jnp.where(live & (pm == v[:, None]), pw, 0.0),
                             axis=1))
    ptot = jnp.sum(jnp.where(live, pw, 0.0), axis=1)
    return below, ptot


def bin_pool_samples(rows: jax.Array, values: jax.Array,
                     weights: jax.Array, num_series: int, capacity: int,
                     compression: float, acc_w: jax.Array,
                     acc_wm: jax.Array, mq: jax.Array | None = None,
                     wb: jax.Array | None = None,
                     fmin: jax.Array | None = None,
                     fmax: jax.Array | None = None):
    """Pool-tier binning: value-bracketed against the row's LIVE bin
    means for sparse arrival, merged-rank quantile-anchored when the
    chunk itself dominates the row's accumulated mass.

    The dense/slab temps bin by estimated global quantile against an
    [S, A] anchor *summary* (``bin_flat_samples``) — fine at K=48,
    where the k-scale leaves slack between consecutive order
    statistics. The tiered pool's PK (16) bins are too coarse for
    that: under one-sample-per-row chunks (the realistic fleet
    arrival shape) consecutive samples arrive with nearly the same
    *estimated* quantile, so value-distant samples alias into the same
    bin — measured up to 0.75 rank error on 4-sample rows. But in the
    pool the bins ARE the anchors (A == PK == capacity), so each
    sample can be placed directly against the live bin means instead:
    find the bracketing live bins (lo, hi), then

      * room in between -> value-interpolated bin inside the open gap
        (rows with <= PK spread-out samples get exact singleton bins),
      * no room -> the nearer-by-value neighbor (local smearing only,
        the same bound a t-digest centroid admits),
      * outside the envelope (a new row min/max) -> BISECT the open
        side's bin range: the quantile estimate would place every new
        extreme hard against the last-placed bin (estimated quantiles
        of consecutive order statistics nearly coincide), exhausting
        the side after two arrivals; halving the remaining range
        instead supports log2 more distinct extremes before any
        sharing, and keeps interior room for in-between arrivals,
      * empty summary -> the quantile-anchored bin (the first chunk
        degrades to exactly the uncorrected behavior, where the
        within-chunk ranks are exact).

    Value-bracketing exists to compensate for the MISSING relative-rank
    information of chunk-solo samples; when one chunk carries more of a
    row's mass than everything accumulated so far (a ramping series
    about to cross the promotion bar, the refill after a guard drain, a
    demotion re-import of a whole centroid run), the exact within-chunk
    ranks ARE that information, and the bracket scheme fails in the
    opposite direction: every sample of the run brackets against the
    same PRE-chunk state, so a run of new maxima all bisect onto the
    same bin (measured as a 43%-of-row-mass clump on promoted rows in
    the 2g bench shape). Such rows use the merged-rank estimate
    (accumulated below-mass + exact within-chunk rank) instead.

    The accumulated mass feeding both the estimate and the dominance
    test includes the PACKED planes (mq/wb/fmin/fmax, when given):
    after a guard drain compacts the bins the row's history lives
    there, and binning as though the row were empty re-anchored every
    post-drain arrival chunk-relative — the blindness that made the
    drain's "re-anchor" hurt the rows it meant to help.

    Bin ids track the k-scale position only approximately under the
    mixed scheme; the below-mass summary tolerates transient
    non-monotonicity (cummax) and the flush compact re-sorts bins by
    value, so correctness never depends on id order. All extra work is
    [N, PK] compares + reductions, the same cost class as the
    below-mass correction itself.

    Returns (rows, values, weights, bins) sorted by row, like
    ``bin_flat_samples``.
    """
    values = values.astype(jnp.float32)
    weights = weights.astype(jnp.float32)
    r, v, w = lax.sort((rows, values, weights), dimension=-1, num_keys=2,
                       is_stable=False)
    cw = _cumsum(w)
    excl = cw - w
    seg_start = jnp.concatenate([jnp.ones((1,), bool), r[1:] != r[:-1]])
    base = _cummax(jnp.where(seg_start, excl, -jnp.inf))
    q_excl = excl - base
    totals = jnp.zeros((num_series + 1,), w.dtype).at[r].add(w, mode="drop")
    tot = totals[jnp.minimum(r, num_series)]
    below, acc_tot = _acc_below_mass(r, v, acc_w, acc_wm, num_series,
                                     capacity)
    if mq is not None:
        pbelow, ptot = _packed_below_mass(r, v, mq, wb, fmin, fmax,
                                          num_series, capacity)
        below = below + pbelow
        acc_tot = acc_tot + ptot
    q_mid = (below + q_excl + 0.5 * w) / jnp.maximum(
        tot + acc_tot, jnp.finfo(w.dtype).tiny)
    kk = compression * (jnp.arcsin(jnp.clip(2.0 * q_mid - 1.0, -1.0, 1.0))
                        / jnp.pi + 0.5)
    qb = jnp.clip(jnp.floor(kk), 0, capacity - 1).astype(jnp.int32)
    a_w = acc_w.reshape(num_series, capacity)
    a_wm = acc_wm.reshape(num_series, capacity)
    live = a_w > 0
    means = jnp.where(live, a_wm / jnp.where(live, a_w, 1.0), jnp.nan)
    rc = jnp.minimum(r, num_series - 1)
    m_r = means[rc]                                   # [N, PK]
    live_r = live[rc]
    idx = jnp.arange(capacity, dtype=jnp.int32)
    below = live_r & (m_r < v[:, None])
    above = live_r & (m_r > v[:, None])
    lo = jnp.max(jnp.where(below, idx, -1), axis=1)
    hi = jnp.min(jnp.where(above, idx, capacity), axis=1)
    m_lo = jnp.max(jnp.where(below, m_r, -jnp.inf), axis=1)
    m_hi = jnp.min(jnp.where(above, m_r, jnp.inf), axis=1)
    gap = hi - lo - 1                                 # free/equal bins
    span = m_hi - m_lo
    interp_ok = jnp.isfinite(span) & (span > 0)
    frac = jnp.clip((v - m_lo) / jnp.where(interp_ok, span, 1.0),
                    0.0, 1.0)
    # frac 0 -> first free bin, 1 -> last; while >= 3 free bins remain,
    # keep off the bins ADJACENT to the brackets — placing v flush
    # against a bracket forecloses the whole value range between them
    # for later arrivals (the repeated two-samples-merge failures the
    # 4-sample rank-error sweep caught all reduce to this)
    off = jnp.round(frac * (gap - 1).astype(v.dtype)).astype(jnp.int32)
    off = jnp.clip(off, jnp.where(gap >= 3, 1, 0),
                   jnp.where(gap >= 3, gap - 2, gap - 1))
    b_interp = lo + 1 + off
    low_open = (lo < 0) & (hi < capacity)     # new row minimum
    high_open = (lo >= 0) & (hi >= capacity)  # new row maximum
    b_onesided = jnp.where(low_open, (hi - 1) // 2, (lo + capacity) // 2)
    b_room = jnp.where(interp_ok, b_interp,
                       jnp.where(low_open | high_open, b_onesided, qb))
    b_room = jnp.clip(b_room, lo + 1, hi - 1)
    # gap == 0: share with the nearer-by-value neighbor — UNLESS that
    # bin already holds more than the k-scale mid-q envelope
    # (~2*total/C) and the other bracket is lighter, in which case the
    # lighter bracket takes it. Nearest-only sharing has no mass cap:
    # under chunk-solo arrival a mode-concentrated distribution piles
    # every mid sample onto the single bin nearest the mode (measured
    # 7/44 of a promoted row's mass on one bin = 0.16 rank error at the
    # median, past the envelope the flush compact maintains). Both
    # brackets span the same value interval, so the switch stays the
    # local smearing a t-digest centroid admits; singleton/balanced
    # bins never switch (strict <), and the -inf/inf sentinels push a
    # value outside a one-sided envelope onto the single live
    # bracketing bin (never onto a dead side).
    wt_r = a_w[rc]
    w_lo = jnp.take_along_axis(wt_r, jnp.clip(lo, 0, capacity - 1)[:, None],
                               1)[:, 0]
    w_hi = jnp.take_along_axis(wt_r, jnp.clip(hi, 0, capacity - 1)[:, None],
                               1)[:, 0]
    nearer_lo = (v - m_lo) <= (m_hi - v)
    w_near = jnp.where(nearer_lo, w_lo, w_hi)
    w_far = jnp.where(nearer_lo, w_hi, w_lo)
    cap_w = 2.0 * (tot + acc_tot) / compression
    switch = ((lo >= 0) & (hi < capacity) & (w_near + w > cap_w)
              & (w_far < w_near))
    b_full = jnp.where(nearer_lo ^ switch, lo, hi)
    b = jnp.where(gap >= 1, b_room, b_full)
    # chunk-dominant rows: the exact within-chunk ranks carry more
    # information than the bracket state every run member shares
    # (tot/acc_tot are row-level, so the whole run switches together);
    # strict > keeps a second chunk-solo sample on the bracket path
    b = jnp.where(tot > acc_tot, qb, b)
    return r, v, w, jnp.clip(b, 0, capacity - 1).astype(jnp.int32)


def _acc_below_mass(r: jax.Array, v: jax.Array, acc_seg_w: jax.Array,
                    acc_seg_wm: jax.Array, num_series: int,
                    anchors: int = BELOW_MASS_ANCHORS):
    """Per-sample accumulated mass below its value, from the temp's
    incremental ``anchors``-segment summary (BELOW_MASS_ANCHORS for the
    dense/slab temps; the tiered pool passes its own bin planes, whose
    per-bin means are quantile-ordered by the same construction).

    Segments are quantile-ordered by construction (every previous
    chunk was binned by estimated global quantile and its mass
    scattered into seg_of_bins segments), so a cummax over the A
    segment means gives a monotone coarse CDF; LINEAR interpolation
    inside the segment a value falls in keeps the estimate sharp for
    stationary traffic (a step attribution would smear bins by a whole
    segment's mass as the accumulated total grows). All work is
    [S, A] + [N, A] — the full [S, K] bin planes are never read.

    Returns (below [N], acc_total [N]) with zeros for rows that have
    accumulated nothing (first chunk == uncorrected behavior).
    """
    a_w = acc_seg_w.reshape(num_series, anchors)
    a_wm = acc_seg_wm.reshape(num_series, anchors)
    live = a_w > 0
    means = jnp.where(live, a_wm / jnp.where(live, a_w, 1.0), -jnp.inf)
    mono = jax.lax.cummax(means, axis=1)              # [S, A] envelope
    rc = jnp.minimum(r, num_series - 1)
    s_mean = mono[rc]                                 # [N, A]
    s_dw = a_w[rc]                                    # [N, A]
    # segment j spans (mean_{j-1}, mean_j]; its mass counts fully below
    # v when v clears the segment, fractionally (linear in value) when
    # v falls inside it. -inf lower bounds (leading empty segments)
    # degrade to the step attribution.
    s_prev = jnp.concatenate(
        [jnp.full_like(s_mean[:, :1], -jnp.inf), s_mean[:, :-1]], axis=1)
    span = s_mean - s_prev
    frac = jnp.where(
        jnp.isfinite(span) & (span > 0),
        jnp.clip((v[:, None] - s_prev) / jnp.where(span > 0, span, 1.0),
                 0.0, 1.0),
        (s_mean < v[:, None]).astype(jnp.float32))
    below = jnp.sum(s_dw * frac, axis=1)
    # the summary's own accumulated mass, not temp.count: imports bin
    # with update_stats=False, so count and bin mass can differ
    acc_tot = jnp.sum(s_dw, axis=1)
    return below, acc_tot


class TempCentroids(NamedTuple):
    """Per-series accumulation of pre-clustered samples: the batched analogue
    of the reference's tempCentroids list, plus the Histo sampler's local
    scalar stats (samplers.go:467-494).

    seg_w/seg_wm are the incremental BELOW_MASS_ANCHORS-segment anchor
    summary (updated by the same scatters that fill the bins): the
    quantile-anchoring correction and the shift guard read ONLY these
    [S, A] planes, never the full [S, K] bins — keeping the per-chunk
    ingest cost at scatter level."""

    sum_w: jax.Array       # [S, K] per-bin weight
    sum_wm: jax.Array      # [S, K] per-bin weighted mean sum
    seg_w: jax.Array       # [S, A] anchor-segment weight
    seg_wm: jax.Array      # [S, A] anchor-segment weighted mean sum
    count: jax.Array       # [S] total weight
    vsum: jax.Array        # [S] weighted sample sum
    vmin: jax.Array        # [S]
    vmax: jax.Array        # [S]
    recip: jax.Array       # [S] weighted reciprocal sum (for hmean)


def init_temp(num_series: int, capacity: int | None = None,
              compression: float = DEFAULT_COMPRESSION) -> TempCentroids:
    k = capacity if capacity is not None else size_bound(compression)
    # NB: each field gets its own buffer — ingest donates the whole tuple,
    # and XLA rejects donating one buffer twice. Machine-checked: the
    # donation-safety pass (lint/deviceflow.py DISTINCT_BUFFER_INITS)
    # flags any field sharing a buffer name here.
    return TempCentroids(
        sum_w=jnp.zeros((num_series, k), jnp.float32),
        sum_wm=jnp.zeros((num_series, k), jnp.float32),
        seg_w=jnp.zeros((num_series, BELOW_MASS_ANCHORS), jnp.float32),
        seg_wm=jnp.zeros((num_series, BELOW_MASS_ANCHORS), jnp.float32),
        count=jnp.zeros((num_series,), jnp.float32),
        vsum=jnp.zeros((num_series,), jnp.float32),
        vmin=jnp.full((num_series,), jnp.inf, jnp.float32),
        vmax=jnp.full((num_series,), -jnp.inf, jnp.float32),
        recip=jnp.zeros((num_series,), jnp.float32),
    )


def ingest_chunk(temp: TempCentroids, rows: jax.Array, values: jax.Array,
                 weights: jax.Array,
                 compression: float = DEFAULT_COMPRESSION,
                 update_stats: bool = True,
                 acc_seg_w: jax.Array | None = None,
                 acc_seg_wm: jax.Array | None = None) -> TempCentroids:
    """Fold one flat chunk of samples into the temp accumulator.

    acc_seg_w/acc_seg_wm default to ``temp``'s own anchor summary (the
    quantile-anchoring state for bin coherence); the mesh store passes
    them explicitly because it bins each chunk into a FRESH temp and
    index-adds the delta after a hosts-axis collective.

    All scatters use mode='drop' so padding (rows == S) is free. Repeated
    chunks accumulate into the same bins, with bin ids anchored to the
    estimated GLOBAL quantile against the accumulated state (see
    bin_flat_samples' acc_* args), so bins stay value-coherent across
    chunks even under ordered arrival. The [S, A] anchor summary is
    maintained by two extra scatters here.

    update_stats=False skips the local scalar stats: used when re-binning
    *imported* digest centroids, which contribute to percentiles but not to
    the host-local min/max/sum/avg/count/hmean (samplers.go:473-480).
    """
    num_series, capacity = temp.sum_w.shape
    if acc_seg_w is None:
        acc_seg_w, acc_seg_wm = temp.seg_w, temp.seg_wm
    r, v, w, b = bin_flat_samples(rows, values, weights, num_series, capacity,
                                  compression, acc_seg_w=acc_seg_w,
                                  acc_seg_wm=acc_seg_wm)
    live = w > 0
    vz = jnp.where(live, v, 0.0)
    sg = seg_of_bins(b, capacity)
    temp = temp._replace(
        sum_w=temp.sum_w.at[r, b].add(w, mode="drop"),
        sum_wm=temp.sum_wm.at[r, b].add(w * vz, mode="drop"),
        seg_w=temp.seg_w.at[r, sg].add(w, mode="drop"),
        seg_wm=temp.seg_wm.at[r, sg].add(w * vz, mode="drop"),
    )
    if not update_stats:
        return temp
    return temp._replace(
        count=temp.count.at[r].add(w, mode="drop"),
        vsum=temp.vsum.at[r].add(w * vz, mode="drop"),
        vmin=temp.vmin.at[r].min(jnp.where(live, v, jnp.inf), mode="drop"),
        vmax=temp.vmax.at[r].max(jnp.where(live, v, -jnp.inf), mode="drop"),
        recip=temp.recip.at[r].add(jnp.where(live, w / v, 0.0), mode="drop"),
    )


SHIFT_GUARD_FRAC = 0.01
# a row votes "shifted" only once its bins hold this much mass: with
# 1-2 accumulated samples the summary's value range is a point, and
# ANY new value reads as disjoint — which made the guard drain on
# every chunk of ordinary traffic (a 4x ingest regression caught by
# the round-5 bench artifact). Rows this small cannot alias anyway:
# their handful of samples spread across distinct anchored bins.
SHIFT_GUARD_MIN_MASS = 8.0
# ... and only when the CHUNK brings this much mass for the row: a
# single stationary sample lands outside the accumulated segment-mean
# envelope with probability ~2/(n+1) (~20% at n=8), so 1-sample-per-row
# chunks — the realistic fleet shape — would re-trigger the churn at
# reduced frequency. Four samples all clearing the envelope on the
# same side by chance is ~(1/(n+1))^4; a genuine step change with
# >=4-sample chunks still fires, and sparser rows rely on the
# quantile anchoring, whose misassignments stay value-local.
SHIFT_GUARD_MIN_CHUNK_MASS = 4.0


def shift_masses(acc_seg_w: jax.Array, acc_seg_wm: jax.Array,
                 rows: jax.Array, values: jax.Array, weights: jax.Array,
                 num_series: int, anchors: int = BELOW_MASS_ANCHORS):
    """(shifted_mass, total_mass) of a chunk against the accumulated
    anchor summary — the raw inputs of ``shift_pred``, exposed
    separately so the mesh store can psum them over its axes before
    thresholding (every shard must take the SAME drain decision the
    dense store would). Reads only the [S, A] summary planes.

    rows may carry the padding sentinel (== num_series); padding and
    zero weights are excluded everywhere."""
    acc_w2 = acc_seg_w.reshape(num_series, anchors)
    acc_m2 = acc_seg_wm.reshape(num_series, anchors)
    live_b = acc_w2 > 0
    means = jnp.where(live_b, acc_m2 / jnp.where(live_b, acc_w2, 1.0),
                      jnp.nan)
    amin = jnp.min(jnp.where(live_b, means, jnp.inf), axis=1)
    amax = jnp.max(jnp.where(live_b, means, -jnp.inf), axis=1)
    acc_mass = acc_w2.sum(axis=1)
    live = weights > 0
    v_lo = jnp.where(live, values, jnp.inf)
    v_hi = jnp.where(live, values, -jnp.inf)
    w_live = jnp.where(live, weights, 0.0)
    cmin = jnp.full((num_series + 1,), jnp.inf,
                    jnp.float32).at[rows].min(v_lo, mode="drop")[:num_series]
    cmax = jnp.full((num_series + 1,), -jnp.inf,
                    jnp.float32).at[rows].max(v_hi, mode="drop")[:num_series]
    cmass = jnp.zeros((num_series + 1,),
                      jnp.float32).at[rows].add(w_live,
                                                mode="drop")[:num_series]
    disjoint = (acc_mass >= SHIFT_GUARD_MIN_MASS) \
        & (cmass >= SHIFT_GUARD_MIN_CHUNK_MASS) \
        & ((cmin > amax) | (cmax < amin))
    shifted = jnp.sum(jnp.where(disjoint, cmass, 0.0))
    total = jnp.sum(cmass)
    return shifted, total


def shift_pred(acc_seg_w: jax.Array, acc_seg_wm: jax.Array,
               rows: jax.Array, values: jax.Array, weights: jax.Array,
               num_series: int,
               frac: float = SHIFT_GUARD_FRAC,
               anchors: int = BELOW_MASS_ANCHORS) -> jax.Array:
    """True when >= ``frac`` of the chunk's mass lands in rows whose
    value range is DISJOINT from what those rows' accumulated bins
    cover — a distribution step/shift that per-bin accumulation cannot
    absorb (even quantile-anchored bins mix tails across a hard shift;
    see analysis/tdigest_sweep.py's ordered-arrival regime). Callers
    guard with lax.cond: drain the temp into the digest first, then
    ingest against fresh bins. Stationary traffic never triggers."""
    shifted, total = shift_masses(acc_seg_w, acc_seg_wm, rows, values,
                                  weights, num_series, anchors)
    return shifted > frac * jnp.maximum(total,
                                        jnp.finfo(jnp.float32).tiny)


def ingest_chunk_guarded(digest: TDigest, temp: TempCentroids,
                         rows: jax.Array, values: jax.Array,
                         weights: jax.Array,
                         compression: float = DEFAULT_COMPRESSION,
                         update_stats: bool = True,
                         use_pallas: bool = True):
    """Shift-guarded ingest: ``shift_pred`` -> drain the temp bins into
    the digest (lax.cond, so the drain costs nothing when not taken),
    then ingest the chunk against re-anchored bins. The temp's scalar
    stats (count/vsum/vmin/vmax/recip) survive a mid-interval guard
    drain — they are interval aggregates, only the BINS move into the
    digest. Returns (digest, temp). ``use_pallas=False`` keeps the
    guard drain off the Pallas kernel (compute-breaker degradation)."""
    num_series = temp.sum_w.shape[0]
    pred = shift_pred(temp.seg_w, temp.seg_wm, rows, values, weights,
                      num_series)

    def do_drain(args):
        d, t = args
        d2 = drain_temp(d, t, compression, use_pallas=use_pallas)
        t2 = t._replace(sum_w=jnp.zeros_like(t.sum_w),
                        sum_wm=jnp.zeros_like(t.sum_wm),
                        seg_w=jnp.zeros_like(t.seg_w),
                        seg_wm=jnp.zeros_like(t.seg_wm))
        return d2, t2

    digest, temp = lax.cond(pred, do_drain, lambda a: a, (digest, temp))
    temp = ingest_chunk(temp, rows, values, weights, compression,
                        update_stats)
    return digest, temp


def drain_temp(state: TDigest, temp: TempCentroids,
               compression: float = DEFAULT_COMPRESSION,
               use_pallas: bool = True) -> TDigest:
    """Merge the accumulated temp centroids into the digests (one compress
    per interval — the batched mergeAllTemps). ``use_pallas=False``
    forces the sort-based XLA path (compute-breaker fallback rung)."""
    from veneur_tpu.ops import tdigest_pallas

    t_live = temp.sum_w > 0
    t_mean = jnp.where(t_live, temp.sum_wm / jnp.where(t_live, temp.sum_w, 1.0),
                       jnp.inf)
    if use_pallas and tdigest_pallas.pallas_ok(state.mean):
        # bin means are NOT monotone in bin index once several chunks with
        # shifting distributions accumulate, so the temp half needs a real
        # sort. Measured on v5e: lax.sort + presorted kernel beats the
        # in-kernel bitonic sort (sort_b) in the fused pipeline — the
        # kernel is VMEM-temporary-bound, so the 28 extra in-VMEM stages
        # cost more than XLA's external sort passes.
        t_mean, t_w = lax.sort((t_mean, temp.sum_w), dimension=-1,
                               num_keys=1, is_stable=False)
        new_mean, new_weight = tdigest_pallas.compress_presorted(
            state.mean, state.weight, t_mean, t_w, compression,
            state.capacity)
    else:
        mean = jnp.concatenate([state.mean, t_mean], axis=-1)
        weight = jnp.concatenate([state.weight, temp.sum_w], axis=-1)
        new_mean, new_weight = _compress(mean, weight, compression,
                                         state.capacity)
    return TDigest(
        mean=new_mean,
        weight=new_weight,
        min=jnp.minimum(state.min, temp.vmin),
        max=jnp.maximum(state.max, temp.vmax),
    )


def drain_and_quantile(state: TDigest, temp: TempCentroids, dmin, dmax,
                       qs: jax.Array,
                       compression: float = DEFAULT_COMPRESSION,
                       use_pallas: bool = True):
    """The whole per-interval digest flush as one op: drain the temp bins
    into the digests, fold in the imported extrema (dmin/dmax), and return
    (drained digests, per-series percentiles). On TPU this is a single
    fused Pallas program; elsewhere — or with ``use_pallas=False``, the
    compute breaker's fallback rung — it composes drain_temp +
    quantile."""
    from veneur_tpu.ops import tdigest_pallas

    mn = jnp.minimum(jnp.minimum(state.min, temp.vmin), dmin)
    mx = jnp.maximum(jnp.maximum(state.max, temp.vmax), dmax)
    if use_pallas and tdigest_pallas.pallas_ok(state.mean):
        t_live = temp.sum_w > 0
        t_mean = jnp.where(
            t_live, temp.sum_wm / jnp.where(t_live, temp.sum_w, 1.0),
            jnp.inf)
        # external sort + presorted kernel: measured faster than sort_b
        # (see drain_temp)
        t_mean, t_w = lax.sort((t_mean, temp.sum_w), dimension=-1,
                               num_keys=1, is_stable=False)
        nm, nw, pcts = tdigest_pallas.drain_quantile(
            state.mean, state.weight, t_mean, t_w, mn, mx,
            jnp.asarray(qs, state.mean.dtype), compression, state.capacity)
        return TDigest(mean=nm, weight=nw, min=mn, max=mx), pcts
    drained = drain_temp(state, temp, compression, use_pallas=use_pallas)
    drained = drained._replace(min=mn, max=mx)
    return drained, quantile(drained, qs)


def from_centroids(mean: jax.Array, weight: jax.Array, mins: jax.Array,
                   maxs: jax.Array, compression: float = DEFAULT_COMPRESSION,
                   capacity: int | None = None) -> TDigest:
    """Build digests from imported centroid arrays (the deserialization path
    of forwarded sketch state, cf. NewMergingFromData, merging_digest.go:83-99).

    mean/weight: [..., M] with weight==0 padding; M may differ from capacity.
    """
    k = capacity if capacity is not None else size_bound(compression)
    new_mean, new_weight = _compress(mean, weight, compression, k)
    return TDigest(mean=new_mean, weight=new_weight,
                   min=jnp.asarray(mins, mean.dtype), max=jnp.asarray(maxs, mean.dtype))


# ---------------------------------------------------------------------------
# Quantized (packed) centroid storage — the tiered pool's resident format
# ---------------------------------------------------------------------------
#
# The packed wire format of core/slab.py:_pack_slab, promoted into a
# RESIDENT representation (core/tiered.py): per row, means quantize to
# uint16 against the row's own [fmin, fmax] frame (absolute error <=
# span/65535) and weights round to bfloat16 bit patterns (relative
# error <= 2^-9; exact counts ride separate f32 stats). Liveness is
# weight > 0 exactly as in TDigest — a wb of 0 is the empty slot.


def quantize_centroids(mean: jax.Array, weight: jax.Array):
    """Quantize sorted, front-compacted [..., P] f32 centroid planes into
    (means_q u16, weights_bf u16, fmin f32, fmax f32): the row frame is
    the live-mean span, so quantization never clips. Rows with no live
    centroids get an empty frame (+inf/-inf) and all-zero planes."""
    live = weight > 0
    fmin = jnp.min(jnp.where(live, mean, jnp.inf), axis=-1)
    fmax = jnp.max(jnp.where(live, mean, -jnp.inf), axis=-1)
    span = fmax - fmin
    scale = jnp.where(span > 0, 65535.0 / span, 0.0)
    mq = jnp.clip(jnp.round((jnp.where(live, mean, 0.0) - jnp.where(
        jnp.isfinite(fmin), fmin, 0.0)[..., None]) * scale[..., None]),
        0.0, 65535.0).astype(jnp.uint16)
    mq = jnp.where(live, mq, 0)
    wb = lax.bitcast_convert_type(
        jnp.where(live, weight, 0.0).astype(jnp.bfloat16), jnp.uint16)
    return mq, wb, fmin, fmax


def dequantize_centroids(mq: jax.Array, wb: jax.Array, fmin: jax.Array,
                         fmax: jax.Array):
    """Inverse of :func:`quantize_centroids`: (mean f32 [..., P] with
    +inf empties, weight f32). The one in-kernel place the packed
    residency contract is decoded (host consumers go through
    core.store.PackedDigestPlanes)."""
    weight = lax.bitcast_convert_type(wb, jnp.bfloat16).astype(jnp.float32)
    live = weight > 0
    base = jnp.where(jnp.isfinite(fmin), fmin, 0.0)[..., None]
    span = jnp.where(jnp.isfinite(fmax - fmin), fmax - fmin, 0.0)
    mean = base + mq.astype(jnp.float32) * (span[..., None] / 65535.0)
    return jnp.where(live, mean, jnp.inf), weight

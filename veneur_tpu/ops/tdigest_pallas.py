"""Fused Pallas TPU kernel for the t-digest flush-time compress.

The XLA expression of the compress (``tdigest._compress_presorted``) pays
HBM round trips between its stages — sort, prefix sum, k-binning,
segmented reduce — and the sort alone re-reads the [S, M] row set ~40
times. This kernel runs the whole pipeline per series-block in VMEM:

    1. bitonic MERGE (not sort): both inputs are row-ascending, so
       log2(L) compare-exchange stages suffice; implemented as static
       shift + select passes (Mosaic-friendly, no reshapes),
    2. log-step prefix sum for cumulative weights,
    3. k-scale binning with an Abramowitz-Stegun asin approximation
       (|err| <= 6.8e-5 rad => bin-edge shift < 0.003 of a bin, well
       inside the digest's accuracy envelope),
    4. chunked one-hot segmented reduce into the output bins.

One HBM read of the four input planes and one write of the two output
planes per row — everything else stays on-chip. The op it re-expresses
is the reference's mergeAllTemps scan (merging_digest.go:135-219).

The public entry ``compress_presorted`` falls back to the XLA path off
TPU (tests run on the CPU mesh) and for batch ranks other than 2.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from veneur_tpu.core.bucketing import bucketed

_ROWS = 128          # series rows per kernel block
_KCHUNK = 16         # output bins reduced per inner step
# Mosaic addresses kernel operands with 32-bit byte offsets, so any single
# pallas_call operand must stay under 2 GiB. Rows beyond this bound are
# processed in row-slabs (the padded [slab, 256] f32 plane at 1M rows is
# 1 GiB); the slab loop unrolls into a handful of kernel launches that XLA
# schedules back-to-back over the same HBM planes.
_MAX_SLAB_ROWS = 1 << 20


def _row_slabs(total: int):
    """Yield (start, size) row spans each small enough for one kernel call."""
    start = 0
    while start < total:
        size = min(_MAX_SLAB_ROWS, total - start)
        yield start, size
        start += size


@bucketed("pow2")
def _next_pow2(n: int) -> int:
    return 1 << (n - 1).bit_length()


def _shift_left(x: jax.Array, d: int, fill: float) -> jax.Array:
    """out[:, i] = x[:, i+d]; right-pads with fill."""
    pad = jnp.full((x.shape[0], d), fill, x.dtype)
    return jnp.concatenate([x[:, d:], pad], axis=1)


def _shift_right(x: jax.Array, d: int, fill: float) -> jax.Array:
    """out[:, i] = x[:, i-d]; left-pads with fill."""
    pad = jnp.full((x.shape[0], d), fill, x.dtype)
    return jnp.concatenate([pad, x[:, :-d]], axis=1)


def _bitonic_merge(key: jax.Array, w: jax.Array):
    """Merge a row-bitonic sequence ascending. Static log2(L) stages of
    shift + compare + select; lead positions of each 2d-block pair with
    i+d, trail positions with i-d."""
    l = key.shape[1]
    d = l // 2
    while d >= 1:
        lead = (jax.lax.broadcasted_iota(jnp.int32, key.shape, 1) // d) % 2 == 0
        k_up = _shift_left(key, d, jnp.inf)
        k_dn = _shift_right(key, d, -jnp.inf)
        w_up = _shift_left(w, d, 0.0)
        w_dn = _shift_right(w, d, 0.0)
        swap_lead = key > k_up          # lead keeps the min
        swap_trail = k_dn > key         # trail keeps the max
        new_key = jnp.where(lead,
                            jnp.where(swap_lead, k_up, key),
                            jnp.where(swap_trail, k_dn, key))
        new_w = jnp.where(lead,
                          jnp.where(swap_lead, w_up, w),
                          jnp.where(swap_trail, w_dn, w))
        key, w = new_key, new_w
        d //= 2
    return key, w


def _bitonic_sort_desc(key: jax.Array, w: jax.Array):
    """Full bitonic sort DESCENDING along axis 1 (length must be a power
    of two). Empty slots carry key=+inf and therefore sort to the FRONT —
    exactly the layout the merge stage expects for the b half (the
    pre-reversed ascending list). Replaces the callers' XLA lax.sort,
    which round-trips HBM on every one of its ~log^2 passes; here the
    whole network runs on the block in VMEM."""
    l = key.shape[1]
    iota = jax.lax.broadcasted_iota(jnp.int32, key.shape, 1)
    k = 2
    while k <= l:
        # bitonic direction per k-block, inverted for a descending result
        # (at k == l the sign is uniform: one final descending pass).
        # Encoded as a per-position key sign flip — "keep min of the
        # signed key" — because Mosaic cannot select between i1 vectors.
        sk_sign = jnp.where((iota & k) == 0, -1.0, 1.0)
        j = k // 2
        while j >= 1:
            lead = (iota & j) == 0
            sk = sk_sign * key
            sk_up = _shift_left(sk, j, jnp.inf)
            sk_dn = _shift_right(sk, j, -jnp.inf)
            k_up = _shift_left(key, j, jnp.inf)
            k_dn = _shift_right(key, j, -jnp.inf)
            w_up = _shift_left(w, j, 0.0)
            w_dn = _shift_right(w, j, 0.0)
            swap_lead = sk > sk_up          # lead keeps the signed min
            swap_trail = sk_dn > sk         # trail keeps the signed max
            new_key = jnp.where(lead,
                                jnp.where(swap_lead, k_up, key),
                                jnp.where(swap_trail, k_dn, key))
            new_w = jnp.where(lead,
                              jnp.where(swap_lead, w_up, w),
                              jnp.where(swap_trail, w_dn, w))
            key, w = new_key, new_w
            j //= 2
        k *= 2
    return key, w


def _prefix_sum(x: jax.Array) -> jax.Array:
    """Inclusive prefix sum along axis 1 via log-step shifts."""
    d = 1
    n = x.shape[1]
    while d < n:
        x = x + _shift_right(x, d, 0.0)
        d *= 2
    return x


def _asin_poly(x: jax.Array) -> jax.Array:
    """Abramowitz & Stegun 4.4.45 asin approximation, |err| <= 6.8e-5.
    Monotone on [-1, 1]; Mosaic has no native asin."""
    s = jnp.sign(x)
    a = jnp.abs(x)
    p = 1.5707288 + a * (-0.2121144 + a * (0.0742610 + a * -0.0187293))
    return s * (0.5 * jnp.pi - jnp.sqrt(jnp.maximum(1.0 - a, 0.0)) * p)


def _compress_kernel(ma_ref, wa_ref, mb_ref, wb_ref, om_ref, ow_ref, *,
                     compression: float, half: int, kout: int, m: int,
                     sort_b: bool):
    nm, sw = _merge_bin_reduce(ma_ref[...], wa_ref[...], mb_ref[...],
                               wb_ref[...], compression, half, kout, m,
                               sort_b)
    om_ref[...] = nm
    ow_ref[...] = sw


def _merge_bin_reduce(ma, wa, mb, wb, compression: float, half: int,
                      kout: int, m: int, sort_b: bool = False):
    """Shared kernel body: bitonic-merge the two halves (b pre-reversed —
    or, with sort_b, sorted descending right here in VMEM), assign
    k-scale bins, and segment-reduce into kout output bins.
    Returns (nm, sw) with dead bins carrying mean == -inf."""
    rows = ma.shape[0]

    def pad_to(x, width, fill):
        if x.shape[1] == width:
            return x
        return jnp.concatenate(
            [x, jnp.full((rows, width - x.shape[1]), fill, x.dtype)], axis=1)

    if sort_b:
        # unsorted b half (empties = +inf): descending in-kernel sort
        # lands +inf pads in front — the same layout the pre-reversed
        # path produces, at VMEM cost instead of ~log^2 HBM sort passes
        mb, wb = _bitonic_sort_desc(mb, wb)
    key = jnp.concatenate([pad_to(ma, half, jnp.inf), mb], axis=1)
    w = jnp.concatenate([pad_to(wa, half, 0.0), wb], axis=1)
    key, w = _bitonic_merge(key, w)
    key, w = key[:, :m], w[:, :m]   # +inf pads sort to the back

    live = w > 0
    m0 = jnp.where(live, key, 0.0)
    incl = _prefix_sum(w)
    total = jnp.max(incl, axis=1, keepdims=True)
    q_mid = (incl - 0.5 * w) / jnp.maximum(total, 1e-30)
    kq = compression * (_asin_poly(jnp.clip(2.0 * q_mid - 1.0, -1.0, 1.0))
                        / jnp.pi + 0.5)
    cluster = jnp.clip(jnp.floor(kq), 0.0, float(kout - 1))
    wm = w * m0

    sw_parts, swm_parts = [], []
    for c0 in range(0, kout, _KCHUNK):
        targets = (jax.lax.broadcasted_iota(jnp.int32, (_KCHUNK, 1), 0)
                   .astype(jnp.float32) + float(c0))
        hit = cluster[:, None, :] == targets[None, :, :]      # [R, KC, M]
        sw_parts.append(jnp.sum(jnp.where(hit, w[:, None, :], 0.0), axis=2))
        swm_parts.append(jnp.sum(jnp.where(hit, wm[:, None, :], 0.0), axis=2))
    # kout need not be a multiple of _KCHUNK; trim the overshoot (those
    # bins can never be hit — cluster ids are clipped to kout-1)
    sw = jnp.concatenate(sw_parts, axis=1)[:, :kout]          # [R, K]
    swm = jnp.concatenate(swm_parts, axis=1)[:, :kout]
    live_o = sw > 0
    nm = jnp.where(live_o, swm / jnp.where(live_o, sw, 1.0), -jnp.inf)
    return nm, sw


def _suffix_min(x: jax.Array) -> jax.Array:
    """Right-to-left running min along axis 1 (log-step)."""
    d, n = 1, x.shape[1]
    while d < n:
        x = jnp.minimum(x, _shift_left(x, d, jnp.inf))
        d *= 2
    return x


def _cummax(x: jax.Array) -> jax.Array:
    d, n = 1, x.shape[1]
    while d < n:
        x = jnp.maximum(x, _shift_right(x, d, -jnp.inf))
        d *= 2
    return x


def _kernel_quantiles(nm, sw, mn, mx, qs, kout: int, nq: int):
    """In-kernel batched inverse-CDF over the freshly reduced bins,
    mirroring tdigest.quantile/_upper_bounds exactly (the in-VMEM rows
    make the per-q one-hot gathers ~3% of the segmented-reduce cost)."""
    live = sw > 0
    masked = jnp.where(live, nm, jnp.inf)
    suffix = _suffix_min(masked)
    next_m = _shift_left(suffix, 1, jnp.inf)
    live_ub = jnp.where(jnp.isfinite(next_m), 0.5 * (nm + next_m), mx)
    ub = _cummax(jnp.where(live, live_ub, -jnp.inf))
    ub_prev = _shift_right(ub, 1, 0.0)
    incl = _prefix_sum(sw)
    total = jnp.max(incl, axis=1, keepdims=True)
    excl = incl - sw
    pos = (jax.lax.broadcasted_iota(jnp.int32, (1, kout), 1)
           .astype(jnp.float32))
    outs = []
    for p in range(nq):
        target = qs[0, p] * total                       # [R, 1]
        idx = jnp.sum((incl < target).astype(jnp.float32), axis=1,
                      keepdims=True)                    # [R, 1]
        idx = jnp.minimum(idx, float(kout - 1))
        hit = pos == idx                                # [R, K]
        gather = lambda a: jnp.sum(jnp.where(hit, a, 0.0), axis=1,
                                   keepdims=True)
        ub_i, prev_ub, w_i, excl_i = (gather(ub), gather(ub_prev),
                                      gather(sw), gather(excl))
        # leading gap bins carry ub == -inf; fall back to min
        lb = jnp.where(idx == 0, mn, jnp.maximum(prev_ub, mn))
        prop = (target - excl_i) / jnp.where(w_i > 0, w_i, 1.0)
        out = lb + prop * (ub_i - lb)
        outs.append(jnp.where(total > 0, out, jnp.nan))
    return jnp.concatenate(outs, axis=1)                # [R, P]


def _drain_kernel(ma_ref, wa_ref, mb_ref, wb_ref, mn_ref, mx_ref, qs_ref,
                  om_ref, ow_ref, pct_ref, *, compression: float, half: int,
                  kout: int, m: int, nq: int, sort_b: bool):
    """compress + quantile fused: one VMEM round for the whole flush."""
    nm, sw = _merge_bin_reduce(ma_ref[...], wa_ref[...], mb_ref[...],
                               wb_ref[...], compression, half, kout, m,
                               sort_b)
    om_ref[...] = nm
    ow_ref[...] = sw
    pct_ref[...] = _kernel_quantiles(nm, sw, mn_ref[...], mx_ref[...],
                                     qs_ref[...], kout, nq)


@functools.partial(jax.jit,
                   static_argnames=("compression", "out_size", "interpret",
                                    "sort_b"))
def _drain_quantile_pallas(mean_a, weight_a, mean_b, weight_b, mn, mx, qs,
                           compression: float, out_size: int,
                           interpret: bool = False, sort_b: bool = False):
    """Fused drain + percentile program. mean_b/weight_b must be
    row-ascending — or arbitrary-order with sort_b=True (empties = +inf),
    in which case the kernel sorts them in VMEM. mn/mx are the final
    per-row extrema [S]; qs is [P]. Rows are processed in <= 1M-row slabs
    to respect Mosaic's 32-bit operand addressing."""
    s = mean_a.shape[0]
    if s > _MAX_SLAB_ROWS:
        outs = [
            _drain_quantile_slab(
                mean_a[st:st + sz], weight_a[st:st + sz],
                mean_b[st:st + sz], weight_b[st:st + sz],
                mn[st:st + sz], mx[st:st + sz], qs, compression, out_size,
                interpret, sort_b)
            for st, sz in _row_slabs(s)]
        return tuple(jnp.concatenate(parts, axis=0) for parts in zip(*outs))
    return _drain_quantile_slab(mean_a, weight_a, mean_b, weight_b, mn, mx,
                                qs, compression, out_size, interpret, sort_b)


def _drain_quantile_slab(mean_a, weight_a, mean_b, weight_b, mn, mx, qs,
                         compression: float, out_size: int,
                         interpret: bool = False, sort_b: bool = False):
    s, ka = mean_a.shape
    kb = mean_b.shape[1]
    nq = qs.shape[0]
    half = _next_pow2(max(ka, kb))
    rows = _ROWS
    pad_rows = (-s) % rows
    if pad_rows:
        zf = lambda x, fill: jnp.concatenate(
            [x, jnp.full((pad_rows,) + x.shape[1:], fill, x.dtype)], axis=0)
        mean_a, weight_a = zf(mean_a, jnp.inf), zf(weight_a, 0.0)
        mean_b, weight_b = zf(mean_b, jnp.inf), zf(weight_b, 0.0)
        mn, mx = zf(mn, jnp.inf), zf(mx, -jnp.inf)
    sp = s + pad_rows
    kb_real = kb
    mean_b = jnp.pad(mean_b, ((0, 0), (0, half - kb)),
                     constant_values=jnp.inf)
    weight_b = jnp.pad(weight_b, ((0, 0), (0, half - kb)))
    if not sort_b:
        # pre-reversed ascending list: +inf pads land in front
        mean_b = jnp.flip(mean_b, axis=1)
        weight_b = jnp.flip(weight_b, axis=1)

    kernel = functools.partial(_drain_kernel, compression=compression,
                               half=half, kout=out_size, m=ka + kb_real,
                               nq=nq, sort_b=sort_b)
    out_mean, out_w, pcts = pl.pallas_call(
        kernel,
        grid=(sp // rows,),
        in_specs=[pl.BlockSpec((rows, ka), lambda i: (i, 0)),
                  pl.BlockSpec((rows, ka), lambda i: (i, 0)),
                  pl.BlockSpec((rows, half), lambda i: (i, 0)),
                  pl.BlockSpec((rows, half), lambda i: (i, 0)),
                  pl.BlockSpec((rows, 1), lambda i: (i, 0)),
                  pl.BlockSpec((rows, 1), lambda i: (i, 0)),
                  pl.BlockSpec((1, nq), lambda i: (0, 0))],
        out_specs=[pl.BlockSpec((rows, out_size), lambda i: (i, 0)),
                   pl.BlockSpec((rows, out_size), lambda i: (i, 0)),
                   pl.BlockSpec((rows, nq), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((sp, out_size), jnp.float32),
                   jax.ShapeDtypeStruct((sp, out_size), jnp.float32),
                   jax.ShapeDtypeStruct((sp, nq), jnp.float32)],
        interpret=interpret,
    )(mean_a, weight_a, mean_b, weight_b, mn[:, None], mx[:, None],
      qs[None, :])
    if pad_rows:
        out_mean, out_w, pcts = out_mean[:s], out_w[:s], pcts[:s]
    out_mean = lax.cummax(out_mean, axis=out_mean.ndim - 1)
    return out_mean, out_w, pcts


def drain_quantile(mean_a, weight_a, mean_b, weight_b, mn, mx,
                   qs, compression: float, out_size: int,
                   interpret: bool = False, sort_b: bool = False):
    """Public fused drain+quantile; the a half must be row-ascending and
    mn/mx the final extrema. The b half must be row-ascending too unless
    sort_b=True (then any order, empties carrying mean=+inf, sorted on
    the block in VMEM — cheaper than a caller-side lax.sort)."""
    return _drain_quantile_pallas(mean_a, weight_a, mean_b,
                                  weight_b, mn, mx, qs, compression,
                                  out_size, interpret=interpret,
                                  sort_b=sort_b)


@functools.partial(jax.jit,
                   static_argnames=("compression", "out_size", "interpret",
                                    "sort_b"))
def _compress_presorted_pallas(mean_a, weight_a, mean_b, weight_b,
                               compression: float, out_size: int,
                               interpret: bool = False,
                               sort_b: bool = False):
    s = mean_a.shape[0]
    if s > _MAX_SLAB_ROWS:
        outs = [
            _compress_presorted_slab(
                mean_a[st:st + sz], weight_a[st:st + sz],
                mean_b[st:st + sz], weight_b[st:st + sz],
                compression, out_size, interpret, sort_b)
            for st, sz in _row_slabs(s)]
        return tuple(jnp.concatenate(parts, axis=0) for parts in zip(*outs))
    return _compress_presorted_slab(mean_a, weight_a, mean_b, weight_b,
                                    compression, out_size, interpret, sort_b)


def _compress_presorted_slab(mean_a, weight_a, mean_b, weight_b,
                             compression: float, out_size: int,
                             interpret: bool = False, sort_b: bool = False):
    s, ka = mean_a.shape
    kb = mean_b.shape[1]
    half = _next_pow2(max(ka, kb))
    rows = _ROWS
    pad_rows = (-s) % rows
    if pad_rows:
        zf = lambda x, fill: jnp.concatenate(
            [x, jnp.full((pad_rows, x.shape[1]), fill, x.dtype)], axis=0)
        mean_a, weight_a = zf(mean_a, jnp.inf), zf(weight_a, 0.0)
        mean_b, weight_b = zf(mean_b, jnp.inf), zf(weight_b, 0.0)
    sp = s + pad_rows
    kb_real = kb
    mean_b = jnp.pad(mean_b, ((0, 0), (0, half - kb)),
                     constant_values=jnp.inf)
    weight_b = jnp.pad(weight_b, ((0, 0), (0, half - kb)))
    if not sort_b:
        # pre-reverse the (already ascending) half outside the kernel
        mean_b = jnp.flip(mean_b, axis=1)
        weight_b = jnp.flip(weight_b, axis=1)
    kb = half

    kernel = functools.partial(_compress_kernel, compression=compression,
                               half=half, kout=out_size, m=ka + kb_real,
                               sort_b=sort_b)
    out_mean, out_w = pl.pallas_call(
        kernel,
        grid=(sp // rows,),
        in_specs=[pl.BlockSpec((rows, ka), lambda i: (i, 0)),
                  pl.BlockSpec((rows, ka), lambda i: (i, 0)),
                  pl.BlockSpec((rows, kb), lambda i: (i, 0)),
                  pl.BlockSpec((rows, kb), lambda i: (i, 0))],
        out_specs=[pl.BlockSpec((rows, out_size), lambda i: (i, 0)),
                   pl.BlockSpec((rows, out_size), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((sp, out_size), jnp.float32),
                   jax.ShapeDtypeStruct((sp, out_size), jnp.float32)],
        interpret=interpret,
    )(mean_a, weight_a, mean_b, weight_b)
    if pad_rows:
        out_mean, out_w = out_mean[:s], out_w[:s]
    # gap-fill empty bins with the running max mean so rows stay ascending
    out_mean = lax.cummax(out_mean, axis=out_mean.ndim - 1)
    return out_mean, out_w


def pallas_ok(mean_a: jax.Array) -> bool:
    """The kernel applies to [S, K] f32 batches on a real TPU backend."""
    try:
        on_tpu = jax.default_backend() == "tpu" or any(
            d.platform == "tpu" for d in jax.devices())
    except RuntimeError:  # pragma: no cover - backend init failure
        return False
    return (on_tpu and mean_a.ndim == 2
            and mean_a.dtype == jnp.float32)


def compress_presorted(mean_a, weight_a, mean_b, weight_b,
                       compression: float, out_size: int,
                       interpret: bool = False, sort_b: bool = False):
    """Fused compress of a row-ascending list with a second list that is
    either row-ascending or (sort_b=True) arbitrary-order with empties at
    mean=+inf; falls back to the sort-based XLA compress off-TPU / for
    unsupported shapes (which sorts everything itself, so sort_b only
    matters on the kernel path)."""
    if interpret or pallas_ok(mean_a):
        return _compress_presorted_pallas(
            mean_a, weight_a, mean_b, weight_b, compression, out_size,
            interpret=interpret, sort_b=sort_b)
    from veneur_tpu.ops import tdigest as td

    return td._compress(jnp.concatenate([mean_a, mean_b], axis=-1),
                        jnp.concatenate([weight_a, weight_b], axis=-1),
                        compression, out_size)

"""Hot-path overload governance: admission watermarks + quarantine ledger.

Production overload systems degrade by priority instead of collapsing
(DAGOR, "Overload Control for Scaling WeChat Microservices", SoCC'18):
when the pipeline saturates, the cheapest-to-lose work is shed first and
every drop is accounted. The ladder here, lowest priority first:

    1. freshly-seen series   (level >= 1: first-sight series spill to the
                              per-group overflow row; existing series
                              keep aggregating — their memory is bounded)
    2. raw spans             (level >= 2: SSF datagrams/spans shed at the
                              reader loop and the span channel)
    3. statsd datagrams      (level >= 3, the hard ceiling: even
                              aggregate traffic sheds at the socket)

Self-metrics (the internal trace client writes the span channel
directly) and forwarded sketch state (the import servers have their own
bounded queues and 429 shedding) are never governed here — they outlive
everything, as the operator's only view INTO the overload.

The pressure signal is the max of the span-channel fill ratio, the
per-sink ingest-lane fill ratios, and each store group's occupancy
against its ``max_series`` cap. All reads are lock-free snapshots and
the level is recomputed at most every ``recompute_interval`` seconds, so
``admit_*`` costs an attribute read on the packet hot path.

Shed/spill/quarantine tallies surface as ``veneur.overload.*``
self-metrics (flusher.py) and in ``GET /debug/vars``.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, Optional

log = logging.getLogger("veneur.overload")

# the single per-group spill row new series collapse into past max_series
OVERFLOW_NAME = "veneur.overload.overflow"

# Self-telemetry carve-out: series under this prefix are the operator's
# only view INTO an overload, so the first-sight freeze (level >= 1)
# never applies to them (the hard per-group cap still does). The
# store's interners and the dedicated self-telemetry digest group
# (MetricStore.self_timers) both consult this ONE predicate.
SELF_TELEMETRY_PREFIX = "veneur."


def freeze_exempt(name: str) -> bool:
    """True when a first-sight series must survive the admission
    freeze (the ``veneur.*`` carve-out)."""
    return name.startswith(SELF_TELEMETRY_PREFIX)

# numeric bounds the quarantine enforces: values outside these ranges
# would silently launder into inf (f32 digest staging) or overflow the
# exact int64 counter lanes
F32_ABS_MAX = 3.4028235e38
INT64_ABS_MAX = float(1 << 63)
# smallest admissible sample rate: below this the float32 reciprocal
# weight (1/rate) overflows to inf — which would poison digest weights
# and raise OverflowError on the int64 counter lanes
MIN_SAMPLE_RATE = 1e-38

LEVEL_NORMAL = 0
LEVEL_SHED_NEW_SERIES = 1
LEVEL_SHED_SPANS = 2
LEVEL_SHED_PACKETS = 3

DEFAULT_LOW_WATERMARK = 0.7
DEFAULT_HIGH_WATERMARK = 0.85
DEFAULT_HARD_WATERMARK = 0.97
DEFAULT_MAX_SERIES = 1 << 20
DEFAULT_MAX_TAG_LENGTH = 1024


class Quarantine:
    """Per-reason counters for poisoned input that was caught instead of
    laundered into digest state. Thread-safe; reasons are a small fixed
    vocabulary so the self-metric tag set stays bounded."""

    REASONS = ("not_finite", "out_of_range", "bad_rate", "oversized_tags")

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {r: 0 for r in self.REASONS}

    def count(self, reason: str, n: int = 1) -> None:
        with self._lock:
            self._counts[reason] = self._counts.get(reason, 0) + n

    def total(self) -> int:
        with self._lock:
            return sum(self._counts.values())

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)


class OverloadController:
    """Watermark-based admission ladder over a cheap pressure signal.

    ``attach(server)`` wires the pressure sources (span channel, sink
    lanes, store groups); until then pressure is 0 and everything is
    admitted, so stores constructed without a server run ungoverned.
    """

    def __init__(self, low: float = DEFAULT_LOW_WATERMARK,
                 high: float = DEFAULT_HIGH_WATERMARK,
                 hard: float = DEFAULT_HARD_WATERMARK,
                 clock: Callable[[], float] = time.monotonic,
                 recompute_interval: float = 0.1):
        if not 0.0 < low < high < hard <= 1.0:
            raise ValueError(
                f"overload watermarks must satisfy 0 < low < high < hard "
                f"<= 1, got {low}/{high}/{hard}")
        self.low, self.high, self.hard = low, high, hard
        self._clock = clock
        self._recompute_interval = recompute_interval
        self._lock = threading.Lock()
        self._level = LEVEL_NORMAL
        self._pressure = 0.0
        self._next_recompute = 0.0
        self._server = None
        # drops by lane, read as interval deltas by the flusher
        self.shed: Dict[str, int] = {"statsd": 0, "ssf": 0, "spans": 0}
        self.level_changes = 0

    def attach(self, server) -> "OverloadController":
        self._server = server
        return self

    # -- pressure ----------------------------------------------------------

    def _compute_pressure(self) -> float:
        srv = self._server
        if srv is None:
            return 0.0
        p = 0.0
        chan = getattr(srv, "span_chan", None)
        if chan is not None and chan.maxsize > 0:
            p = max(p, chan.qsize() / chan.maxsize)
        workers = getattr(srv, "_span_workers", None) or ()
        for w in workers[:1]:  # lanes are shared across workers
            for lane in getattr(w, "_lanes", ()):
                q = lane.queue
                if q.maxsize > 0:
                    p = max(p, q.qsize() / q.maxsize)
        fleets = getattr(srv, "_ingest_fleets", None)
        if not fleets:
            fleet = getattr(srv, "ingest_fleet", None)
            fleets = [fleet] if fleet is not None else []
        for fleet in fleets:
            # per-lane fill: sealed chunks backing up against the
            # merger read as pipeline pressure exactly like a full
            # span channel does — EVERY fleet counts, not just the
            # first listener's
            p = max(p, fleet.pressure())
        store = getattr(srv, "store", None)
        if store is not None:
            occ = 0.0
            for name in getattr(store, "_GEN_GROUPS", ()):
                g = getattr(store, name, None)
                ms = getattr(g, "max_series", 0)
                if g is not None and ms:
                    occ = max(occ, len(g) / ms)
            # cardinality pressure can only ever reach the FREEZE tier:
            # the per-group cap already bounds memory (spill), so a
            # permanently-full group must not shed spans or datagrams —
            # only queue pressure escalates past level 1
            p = max(p, min(occ, (self.low + self.high) / 2.0))
        return min(p, 1.0)

    def pressure(self) -> float:
        self._maybe_recompute()
        return self._pressure

    def _maybe_recompute(self) -> None:
        now = self._clock()
        if now < self._next_recompute:
            return
        with self._lock:
            if now < self._next_recompute:
                return
            self._next_recompute = now + self._recompute_interval
            self._pressure = p = self._compute_pressure()
            if p >= self.hard:
                level = LEVEL_SHED_PACKETS
            elif p >= self.high:
                level = LEVEL_SHED_SPANS
            elif p >= self.low:
                level = LEVEL_SHED_NEW_SERIES
            else:
                level = LEVEL_NORMAL
            if level != self._level:
                self.level_changes += 1
                log.warning(
                    "overload level %d -> %d (pressure %.2f; watermarks "
                    "%.2f/%.2f/%.2f)", self._level, level, p, self.low,
                    self.high, self.hard)
                self._level = level

    def level(self) -> int:
        self._maybe_recompute()
        return self._level

    def level_nowait(self) -> int:
        """Lock-free level snapshot for the ingest-lane hot path: no
        recompute, no lock — the fleet merger drives ``level()`` on its
        tick, so this stays at most one tick stale. The lane loop's
        lock-freedom assertion (``@lockfree_hot_path``) depends on this
        read never touching ``_lock``."""
        return self._level

    def account_shed(self, lane: str, n: int) -> None:
        """Fold lane-local shed tallies into the shared ledger (the
        merger's roll-up; lanes count their own sheds lock-free)."""
        with self._lock:
            self.shed[lane] = self.shed.get(lane, 0) + n

    # -- admission ---------------------------------------------------------

    def freeze_new_series(self) -> bool:
        """True while first-sight series should spill to the overflow
        row regardless of the per-group cap (level >= 1)."""
        return self.level() >= LEVEL_SHED_NEW_SERIES

    def admit_span(self, n: int = 1) -> bool:
        """Raw external spans (the SSF stream/native lanes)."""
        if self.level() >= LEVEL_SHED_SPANS:
            with self._lock:
                self.shed["spans"] += n
            return False
        return True

    def admit_packet(self, lane: str) -> bool:
        """One datagram on a reader loop; ``lane`` is statsd or ssf.
        SSF datagrams shed with the spans tier; statsd only at the hard
        ceiling (aggregate traffic is memory-bounded by the caps)."""
        level = self.level()
        threshold = (LEVEL_SHED_SPANS if lane == "ssf"
                     else LEVEL_SHED_PACKETS)
        if level >= threshold:
            with self._lock:
                self.shed[lane] = self.shed.get(lane, 0) + 1
            return False
        return True

    def shed_total(self) -> int:
        with self._lock:
            return sum(self.shed.values())

    def snapshot(self) -> dict:
        """Best-effort state dump for /debug/vars and readiness."""
        return {"level": self.level(), "pressure": round(self._pressure, 4),
                "watermarks": [self.low, self.high, self.hard],
                "shed": dict(self.shed),
                "level_changes": self.level_changes}


def from_config(cfg, clock: Callable[[], float] = time.monotonic
                ) -> Optional[OverloadController]:
    """Build the configured controller (None never happens today — the
    governor always runs; kept Optional-shaped for symmetry with
    faults.from_config)."""
    return OverloadController(
        low=getattr(cfg, "overload_low_watermark", DEFAULT_LOW_WATERMARK)
        or DEFAULT_LOW_WATERMARK,
        high=getattr(cfg, "overload_high_watermark",
                     DEFAULT_HIGH_WATERMARK) or DEFAULT_HIGH_WATERMARK,
        hard=getattr(cfg, "overload_hard_watermark",
                     DEFAULT_HARD_WATERMARK) or DEFAULT_HARD_WATERMARK,
        clock=clock)

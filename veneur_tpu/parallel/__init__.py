"""Multi-chip parallelism: device meshes + global-aggregation collectives.

The reference scales its global tier with a consistent-hash proxy fanning
imports out over many single-threaded Go workers
(``/root/reference/proxy.go:437-505``, ``importsrv/server.go:101-132``).
Here the same two axes become a ``jax.sharding.Mesh``:

* ``series`` — data parallelism over metric series (the worker-shard axis:
  each device owns a contiguous slab of rows, the TPU analogue of
  ``Workers[digest % N]``, ``server.go:704``);
* ``hosts`` — the hierarchical-aggregation axis (the local→global forward
  fan-in, ``flusher.go:292-473``): per-host sketch contributions merge
  across devices with XLA collectives over ICI — ``psum`` for counters and
  t-digest bin accumulators, ``pmax`` for HLL registers, and a ppermute
  butterfly for pre-compressed centroid state.
"""

from veneur_tpu.parallel.mesh import fleet_mesh, series_sharding
from veneur_tpu.parallel.collectives import (
    merge_counters,
    merge_registers,
    merge_temp,
    allmerge_digest,
)
from veneur_tpu.parallel.global_agg import GlobalAggregator

__all__ = [
    "fleet_mesh",
    "series_sharding",
    "merge_counters",
    "merge_registers",
    "merge_temp",
    "allmerge_digest",
    "GlobalAggregator",
]

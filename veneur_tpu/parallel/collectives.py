"""Global-aggregation collectives: the fleet-wide sketch merge over ICI.

These functions run *inside* ``shard_map`` over a named mesh axis. They are
the TPU re-expression of the reference's global-aggregator merge loop
(``worker.go:313-398``: gob/proto decode + one-at-a-time ``Combine``/
``Merge`` per imported sketch) as single collective ops over dense state:

    counters            psum        (Counter.Combine adds, samplers.go:195-200)
    gauges              last-write  (host concern; not a collective)
    HLL registers       pmax        (Set.Combine register max, samplers.go:423-435)
    t-digest temp bins  psum        (bin accumulators are linear in samples)
    t-digest centroids  butterfly ppermute merge / all-gather + one compress
                        (MergingDigest.Merge, merging_digest.go:358-370)

The t-digest temp-bin trick is the load-bearing design point: because ingest
pre-clusters samples into k-scale bins whose (sum_w, sum_wm) accumulators are
*additive*, the cross-host merge of in-progress digest state is a plain
``psum`` — no sequential centroid walk crosses the wire, and ICI carries
``[S_shard, K]`` float32 tensors.
"""

from __future__ import annotations


import jax
from jax import lax

from veneur_tpu.ops import tdigest as td_ops
from veneur_tpu.ops.tdigest import TDigest, TempCentroids


def merge_counters(values: jax.Array, axis: str) -> jax.Array:
    """Fleet-wide counter totals: one psum (Counter.Combine, samplers.go:195)."""
    return lax.psum(values, axis)


def merge_registers(registers: jax.Array, axis: str) -> jax.Array:
    """Fleet-wide HLL union: elementwise pmax over the mesh axis
    (Set.Combine, samplers.go:423-435)."""
    return lax.pmax(registers, axis)


def merge_temp(temp: TempCentroids, axis: str) -> TempCentroids:
    """Merge in-progress digest state across hosts: additive fields psum,
    extrema pmin/pmax. Exact — no approximation is introduced by the
    collective itself (binning already happened per-host under the same
    k-scale the reference uses)."""
    return TempCentroids(
        sum_w=lax.psum(temp.sum_w, axis),
        sum_wm=lax.psum(temp.sum_wm, axis),
        seg_w=lax.psum(temp.seg_w, axis),
        seg_wm=lax.psum(temp.seg_wm, axis),
        count=lax.psum(temp.count, axis),
        vsum=lax.psum(temp.vsum, axis),
        vmin=lax.pmin(temp.vmin, axis),
        vmax=lax.pmax(temp.vmax, axis),
        recip=lax.psum(temp.recip, axis),
    )


def allmerge_digest(digest: TDigest, axis: str, axis_size: int,
                    compression: float = td_ops.DEFAULT_COMPRESSION) -> TDigest:
    """All-reduce pre-compressed digests over a mesh axis.

    Power-of-two axis: recursive-doubling butterfly — log2(N) ppermute
    rounds, each concatenating partner centroids ([S, 2K]) and compressing
    back to K. Every round's exchange is nearest-neighbour-friendly on ICI
    and the compress keeps wire volume constant per round.

    Non-power-of-two axis: one all_gather then a single [S, N*K] compress.

    Digest merge is associative and commutative (same k-scale invariant as
    MergingDigest.Merge, merging_digest.go:358-370), so the butterfly's
    pairing order does not change the accuracy bound.
    """
    if axis_size == 1:
        return digest
    if axis_size & (axis_size - 1) == 0:
        step = 1
        while step < axis_size:
            perm = [(i, i ^ step) for i in range(axis_size)]
            partner = TDigest(
                mean=lax.ppermute(digest.mean, axis, perm),
                weight=lax.ppermute(digest.weight, axis, perm),
                min=lax.ppermute(digest.min, axis, perm),
                max=lax.ppermute(digest.max, axis, perm),
            )
            digest = td_ops.merge(digest, partner, compression)
            step *= 2
        return digest
    # Fallback: gather every host's centroids and re-cluster once.
    mean = lax.all_gather(digest.mean, axis, axis=-2)    # [..., N, K]
    weight = lax.all_gather(digest.weight, axis, axis=-2)
    flat_mean = mean.reshape(mean.shape[:-2] + (axis_size * mean.shape[-1],))
    flat_w = weight.reshape(flat_mean.shape)
    return td_ops.from_centroids(
        flat_mean, flat_w,
        lax.pmin(digest.min, axis), lax.pmax(digest.max, axis),
        compression, digest.capacity)

"""The sharded global-aggregator interval step.

This is the multi-chip form of the reference's global veneur: N forwarding
hosts deliver sketch contributions each interval, the global tier merges
them and emits fleet-wide percentiles / cardinalities / totals
(``importsrv/server.go:101-132`` + ``flusher.go:26-132``, behavior; the
mechanics are re-designed for a TPU mesh).

Layout (see ``parallel/mesh.py``): a 2-D ``(series, hosts)`` mesh. Metric
series are sharded over the ``series`` axis — each device owns a contiguous
slab of rows, the analogue of one reference worker's sampler map
(``worker.go:54-91``). Per-host contributions are sharded over the ``hosts``
axis and replicated across series shards; every device filters the incoming
flat chunks down to its own row range (out-of-range rows scatter with
``mode='drop'``), accumulates locally, and one ``psum``/``pmax`` per state
kind completes the fleet-wide merge over ICI. No host↔device chatter happens
inside the interval: ingest is scatter-shaped, merge is collective-shaped,
flush is a batched quantile/estimate gather.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# version-compat shard_map wrapper (check_vma/check_rep rename)
from veneur_tpu.parallel.mesh import shard_map

from veneur_tpu.ops import hll as hll_ops
from veneur_tpu.ops import tdigest as td_ops
from veneur_tpu.ops.tdigest import TDigest
from veneur_tpu.parallel import collectives
from veneur_tpu.parallel.mesh import HOSTS_AXIS, SERIES_AXIS


class AggState(NamedTuple):
    """Device-resident global-tier state, all sharded over the series axis."""

    digest: TDigest          # [S, K] histogram/timer sketch state
    registers: jax.Array     # [S, m] HLL registers (int32)
    counters: jax.Array      # [S] int32 totals


class HostBatch(NamedTuple):
    """One interval's per-host contributions, sharded over the hosts axis.

    Flat padded chunks; padding rows must equal ``num_series`` (they drop in
    the scatter). Every leading dim is the total host count H.
    """

    h_rows: jax.Array        # [H, N] int32 histogram sample rows
    h_vals: jax.Array        # [H, N] float32 values
    h_wts: jax.Array         # [H, N] float32 weights (0 = padding)
    s_rows: jax.Array        # [H, M] int32 set rows
    s_hi: jax.Array          # [H, M] uint32 member-hash high halves
    s_lo: jax.Array          # [H, M] uint32 low halves
    c_rows: jax.Array        # [H, C] int32 counter rows
    c_incs: jax.Array        # [H, C] int32 increments (0 = padding)


class GlobalAggregator:
    """Compiles and runs the sharded interval step on a fleet mesh."""

    def __init__(self, mesh: Mesh, num_series: int,
                 compression: float = td_ops.DEFAULT_COMPRESSION,
                 precision: int = hll_ops.DEFAULT_PRECISION):
        self.mesh = mesh
        self.series_devices = mesh.shape[SERIES_AXIS]
        self.hosts = mesh.shape[HOSTS_AXIS]
        if num_series % self.series_devices != 0:
            raise ValueError(
                f"num_series={num_series} must divide over "
                f"{self.series_devices} series shards")
        self.num_series = num_series
        self.compression = compression
        self.precision = precision
        self.k = td_ops.size_bound(compression)
        self.m = hll_ops.num_registers(precision)

        s = P(SERIES_AXIS)
        sk = P(SERIES_AXIS, None)
        h = P(HOSTS_AXIS, None)
        state_spec = AggState(
            digest=TDigest(mean=sk, weight=sk, min=s, max=s),
            registers=sk, counters=s)
        batch_spec = HostBatch(*([h] * 8))

        self._step = jax.jit(
            shard_map(
                self._local_step, mesh=mesh,
                in_specs=(state_spec, batch_spec, P(None)),
                out_specs=(state_spec, sk, s, s),
                check_vma=False),
            donate_argnums=(0,))

        # the forwarded-digest butterfly merge, compiled once (calling
        # jax.jit on a fresh closure per flush would retrace every interval)
        hk = P(HOSTS_AXIS, None, None)
        hs = P(HOSTS_AXIS, None)

        def _merge_local(mean, weight, mins, maxs):
            d = TDigest(mean=mean[0], weight=weight[0], min=mins[0],
                        max=maxs[0])
            d = collectives.allmerge_digest(d, HOSTS_AXIS, self.hosts,
                                            self.compression)
            return d.mean, d.weight, d.min, d.max

        self._merge_forwarded = jax.jit(shard_map(
            _merge_local, mesh=mesh,
            in_specs=(hk, hk, hs, hs),
            out_specs=(P(None, None), P(None, None), P(None), P(None)),
            check_vma=False))

    # -- state construction -------------------------------------------------

    def init_state(self) -> AggState:
        sharding_sk = NamedSharding(self.mesh, P(SERIES_AXIS, None))
        sharding_s = NamedSharding(self.mesh, P(SERIES_AXIS))
        s, k, m = self.num_series, self.k, self.m
        return AggState(
            digest=TDigest(
                mean=jax.device_put(jnp.full((s, k), jnp.inf, jnp.float32),
                                    sharding_sk),
                weight=jax.device_put(jnp.zeros((s, k), jnp.float32),
                                      sharding_sk),
                min=jax.device_put(jnp.full((s,), jnp.inf, jnp.float32),
                                   sharding_s),
                max=jax.device_put(jnp.full((s,), -jnp.inf, jnp.float32),
                                   sharding_s),
            ),
            registers=jax.device_put(jnp.zeros((s, m), jnp.int32), sharding_sk),
            counters=jax.device_put(jnp.zeros((s,), jnp.int32), sharding_s),
        )

    def shard_batch(self, batch: HostBatch) -> HostBatch:
        sharding = NamedSharding(self.mesh, P(HOSTS_AXIS, None))
        return HostBatch(*(jax.device_put(jnp.asarray(x), sharding)
                           for x in batch))

    # -- the per-device program --------------------------------------------

    def _local_step(self, state: AggState, batch: HostBatch, qs: jax.Array):
        s_loc = state.digest.mean.shape[0]
        start = lax.axis_index(SERIES_AXIS) * s_loc

        def relocal(rows):
            r = rows.reshape(-1).astype(jnp.int32)
            in_range = (r >= start) & (r < start + s_loc)
            return jnp.where(in_range, r - start, s_loc)

        # t-digest path: bin this device's host chunk, psum bins over hosts,
        # one compress drains them into the owned digests.
        temp = td_ops.init_temp(s_loc, self.k, self.compression)
        temp = td_ops.ingest_chunk(
            temp, relocal(batch.h_rows), batch.h_vals.reshape(-1),
            batch.h_wts.reshape(-1), self.compression)
        temp = collectives.merge_temp(temp, HOSTS_AXIS)
        digest = td_ops.drain_temp(state.digest, temp, self.compression)
        pcts = td_ops.quantile(digest, qs)

        # HLL path: scatter-max locally, pmax completes the union.
        idx, rho = hll_ops.idx_rho(batch.s_hi.reshape(-1),
                                   batch.s_lo.reshape(-1), self.precision)
        registers = state.registers.at[relocal(batch.s_rows), idx].max(
            rho, mode="drop")
        registers = collectives.merge_registers(registers, HOSTS_AXIS)
        estimates = hll_ops.estimate(registers, self.precision)

        # counter path: scatter-add locally, psum totals.
        contrib = jnp.zeros((s_loc,), jnp.int32).at[relocal(batch.c_rows)].add(
            batch.c_incs.reshape(-1).astype(jnp.int32), mode="drop")
        counters = state.counters + collectives.merge_counters(
            contrib, HOSTS_AXIS)

        new_state = AggState(digest=digest, registers=registers,
                             counters=counters)
        return new_state, pcts, estimates, counters

    # -- public API ---------------------------------------------------------

    def step(self, state: AggState, batch: HostBatch, qs):
        """Run one interval: returns (new_state, percentiles [S, P],
        set estimates [S], counter totals [S]).

        CONSUMES ``state``: the dispatch donates its buffers
        (``donate_argnums=(0,)``) and they are deleted the moment it
        lands. The caller MUST rebind — ``state, *rest =
        agg.step(state, ...)`` — and never touch the old handle again;
        ``step`` cannot rebind for the caller because the pre-donation
        pytree is the caller's own local. Reviewed under the
        donation-safety pass (this was the one call boundary predating
        every audit)."""
        return self._step(state, batch, jnp.asarray(qs, jnp.float32))  # lint: ok(donated-param-escape) documented consume-and-rebind contract: the caller rebinds state to the returned pytree, as every call site in tests/test_parallel.py does

    def merge_forwarded_digests(self, mean, weight, mins, maxs):
        """All-reduce pre-compressed per-host digests over the hosts axis —
        the collective form of importing already-flushed centroid state
        (Histo.Merge, samplers.go:676-691). Inputs [H, S, K] / [H, S],
        sharded over hosts; returns the merged [S, K] digest replicated
        across the hosts axis (butterfly ppermute, log2(H) rounds)."""
        sharding_hk = NamedSharding(self.mesh, P(HOSTS_AXIS, None, None))
        sharding_hs = NamedSharding(self.mesh, P(HOSTS_AXIS, None))
        args = (jax.device_put(jnp.asarray(mean, jnp.float32), sharding_hk),
                jax.device_put(jnp.asarray(weight, jnp.float32), sharding_hk),
                jax.device_put(jnp.asarray(mins, jnp.float32), sharding_hs),
                jax.device_put(jnp.asarray(maxs, jnp.float32), sharding_hs))
        m, w, mn, mx = self._merge_forwarded(*args)
        return TDigest(mean=m, weight=w, min=mn, max=mx)


def make_host_batch(num_hosts: int, num_series: int, n: int = 64,
                    m: int = 64, c: int = 64, seed: int = 0) -> HostBatch:
    """Synthetic per-host contributions for tests/dryrun (host-side numpy)."""
    rng = np.random.default_rng(seed)
    return HostBatch(
        h_rows=rng.integers(0, num_series, (num_hosts, n)).astype(np.int32),
        h_vals=rng.normal(100.0, 25.0, (num_hosts, n)).astype(np.float32),
        h_wts=np.ones((num_hosts, n), np.float32),
        s_rows=rng.integers(0, num_series, (num_hosts, m)).astype(np.int32),
        s_hi=rng.integers(0, 1 << 32, (num_hosts, m), dtype=np.uint64
                          ).astype(np.uint32),
        s_lo=rng.integers(0, 1 << 32, (num_hosts, m), dtype=np.uint64
                          ).astype(np.uint32),
        c_rows=rng.integers(0, num_series, (num_hosts, c)).astype(np.int32),
        c_incs=rng.integers(1, 10, (num_hosts, c)).astype(np.int32),
    )

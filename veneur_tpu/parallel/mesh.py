"""Mesh construction for the two veneur axes: hosts (fan-in) × series (shard).

ICI-friendly layout: the ``hosts`` reduction axis is placed innermost so the
psum/pmax collectives ride neighbouring chips; the ``series`` axis never
communicates after ingest (each device owns its rows outright, like a
reference worker owns its ``map[MetricKey]*sampler``, ``worker.go:54-91``).
"""

from __future__ import annotations

from typing import Optional, Sequence

import inspect

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

HOSTS_AXIS = "hosts"
SERIES_AXIS = "series"

try:  # JAX >= 0.4.35 exports shard_map at top level
    from jax import shard_map as _shard_map  # type: ignore
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map  # type: ignore

# replication-check kwarg rename across JAX versions: new builds take
# ``check_vma``, 0.4.x takes ``check_rep``; translate so the mesh call
# sites work on both (keeps the multi-device lane runnable everywhere)
_SM_CHECK_KW = ("check_vma"
                if "check_vma" in inspect.signature(_shard_map).parameters
                else "check_rep")


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **{_SM_CHECK_KW: check_vma})


def _largest_pow2_divisor(n: int, cap: int) -> int:
    d = 1
    while d * 2 <= cap and n % (d * 2) == 0:
        d *= 2
    return d


def fleet_mesh(devices: Optional[Sequence[jax.Device]] = None,
               hosts: Optional[int] = None) -> Mesh:
    """Build a 2-D ``(series, hosts)`` mesh over the available devices.

    ``hosts`` defaults to the largest power-of-two divisor of the device
    count ≤ device_count (so an 8-chip slice becomes 1×8 pure fan-in by
    default when hosts=None is resolved to all devices); pass ``hosts=1``
    for a pure series-sharded layout.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if hosts is None:
        hosts = _largest_pow2_divisor(n, n)
    if n % hosts != 0:
        raise ValueError(f"{n} devices not divisible by hosts={hosts}")
    arr = np.asarray(devices).reshape(n // hosts, hosts)
    return Mesh(arr, (SERIES_AXIS, HOSTS_AXIS))


def series_sharding(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """Shard dim 0 (the series axis) across the mesh's series devices;
    replicate over hosts."""
    spec = P(SERIES_AXIS, *([None] * (ndim - 1)))
    return NamedSharding(mesh, spec)


def host_sharding(mesh: Mesh, ndim: int = 2) -> NamedSharding:
    """Shard dim 0 (per-host contributions) across the hosts axis;
    replicate over series devices (each series shard filters its rows)."""
    spec = P(HOSTS_AXIS, *([None] * (ndim - 1)))
    return NamedSharding(mesh, spec)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())

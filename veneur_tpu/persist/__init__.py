"""Crash-safe aggregation state: interval checkpointing + warm restart.

See ``persist/checkpoint.py`` for the model and ``persist/format.py``
for the on-disk layout; config surface is ``checkpoint_path`` /
``checkpoint_interval`` / ``checkpoint_max_age_intervals``
(``docs/resilience.md``).
"""

from veneur_tpu.persist.checkpoint import Checkpointer
from veneur_tpu.persist.format import (CheckpointInvalid, deserialize,
                                       read_file, serialize, write_atomic)

__all__ = ["Checkpointer", "CheckpointInvalid", "serialize",
           "deserialize", "write_atomic", "read_file"]

"""Interval checkpointing and warm-restart recovery for the store.

The other half of the fault-tolerance story next to the egress layer
(``docs/resilience.md``): all sketch state for the current interval
lives only in process memory, so an OOM/SIGKILL/TPU fault loses up to a
full interval of fleet-wide data. The :class:`Checkpointer` bounds that
loss at ``checkpoint_interval``:

* a background thread snapshots the store every ``checkpoint_interval``
  (``MetricStore.snapshot_state`` — the store lock is held only for the
  in-memory snapshot; serialization and the disk write run off-lock)
  and commits it atomically (``format.write_atomic``);
* a snapshot is committed only if no flush drained the store since it
  was taken (the ``flush_epoch`` guard) — and a successful flush
  truncates the checkpoint outright — so recovered data can NEVER
  double-flush;
* at startup, a valid non-stale checkpoint is *merged* into the fresh
  store with import-path semantics (``MetricStore.restore_state``) and
  immediately re-persisted from the merged store (a crash loop never
  destroys on-disk state); truncated, corrupt, wrong-version or stale
  files are discarded (counted, logged) — no checkpoint can prevent
  startup.

Self-metrics (``flusher._checkpoint_samples``):
``veneur.checkpoint.{write_duration_ns,bytes,age_seconds,restore_total,
discard_total}``.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Optional

from veneur_tpu.persist import format as ckpt_format
from veneur_tpu.persist.format import CheckpointInvalid

log = logging.getLogger("veneur.persist")


class Checkpointer:
    """Owns one checkpoint path for one store. All disk operations
    (commit, truncate) serialize on ``_io_lock``; the store lock is
    never held across IO."""

    def __init__(self, store, path: str, interval_s: float,
                 max_age_s: float, hostname: str = "",
                 write_fn=None):
        self.store = store
        self.path = path
        self.interval_s = interval_s
        self.max_age_s = max_age_s
        self.hostname = hostname
        # injectable commit (soak disk-full faults ride
        # FaultInjector.wrap_write here); None = the atomic
        # temp+fsync+rename writer, resolved at call time
        self._write_fn = write_fn
        self._io_lock = threading.Lock()
        # telemetry (read by flusher._checkpoint_samples)
        self.writes = 0
        self.write_errors = 0
        self.discarded_writes = 0  # lost the flush-epoch race
        self.truncates = 0
        self.restore_total = 0
        self.discard_total = 0
        self.restored_series = 0
        self.last_write_duration_s = 0.0
        self.last_write_bytes = 0
        self.last_write_at: Optional[float] = None
        # the last commit's disk error, None while writes succeed —
        # rides the degraded /healthcheck/ready body (Server.degradation)
        self.last_error: Optional[str] = None
        self._created_at = time.time()
        self._restored = False

    # -- write path --------------------------------------------------------

    def write_once(self) -> bool:
        """Snapshot → serialize → atomic commit. False when the commit
        was discarded because a flush drained the snapshotted state
        first (persisting it would double-count on restore), or when
        the disk refused the write (ENOSPC, short write, read-only
        volume) — counted (``write_errors``) and named
        (``last_error``), NEVER raised: a full disk must degrade the
        instance, not crash the flush thread or any direct caller."""
        t0 = time.perf_counter()
        groups, epoch = self.store.snapshot_state()  # store lock inside
        blob = ckpt_format.serialize(
            groups, created_at=time.time(), interval=self.interval_s,
            meta={"hostname": self.hostname})
        # the IO lock's entire job is to serialize this write+fsync
        # against truncation; the flush path never waits behind it
        # (truncate(blocking=False)) and the store lock is not held
        with self._io_lock:  # lint: ok(lock-across-blocking) the IO lock's entire job is to serialize this write+fsync against truncation; the flush path never waits behind it
            if self.store.flush_epoch != epoch:
                self.discarded_writes += 1
                return False
            try:
                # the direct default call keeps the fsync-under-lock
                # hold statically visible to the lock-order pass
                if self._write_fn is None:
                    n = ckpt_format.write_atomic(self.path, blob)
                else:
                    n = self._write_fn(self.path, blob)
            except OSError as e:
                self.write_errors += 1
                self.last_error = str(e)
                # an ENOSPC mid-write can strand a partial .tmp; the
                # stale previous checkpoint (if any) stays — still the
                # best recovery anchor the disk will hold
                try:
                    os.unlink(self.path + ".tmp")
                except OSError:
                    pass
                log.warning("checkpoint write to %s failed (%s); "
                            "degraded, retrying next interval",
                            self.path, e)
                return False
            if self.store.flush_epoch != epoch:
                # a flush drained (and is emitting) the snapshotted
                # state while the bytes were in flight; the flush-path
                # truncate may have skipped past the held lock
                # (non-blocking), so remove the stale file ourselves
                self._unlink_locked()
                self.discarded_writes += 1
                return False
        self.last_write_duration_s = time.perf_counter() - t0
        self.last_write_bytes = n
        self.last_write_at = time.time()
        self.writes += 1
        # single writer thread; readers (degradation()) tolerate a
        # stale value for one interval
        self.last_error = None  # lint: ok(inconsistent-lockset) single writer thread; readers (degradation()) tolerate a stale value for one interval
        return True

    def run(self, stop: threading.Event):
        """Background loop: one checkpoint per ``checkpoint_interval``
        until ``stop`` is set. A failed write never kills the thread."""
        while not stop.wait(self.interval_s):
            try:
                self.write_once()
            except Exception:
                # single writer thread; monotonic introspection counter
                self.write_errors += 1  # lint: ok(inconsistent-lockset) single writer thread; a monotonic introspection counter needs no lock
                log.exception("checkpoint write failed; retrying next "
                              "interval")

    def truncate(self, blocking: bool = True) -> bool:
        """Remove the checkpoint (and any scratch file): the state it
        captured has been flushed, restored, or proven unusable.

        blocking=False (the flush path) never waits behind an in-flight
        write — a multi-hundred-MB write+fsync holds the lock for
        seconds and must not eat the flush's egress budget. Skipping is
        safe: the writer re-checks the flush epoch after committing and
        removes its own file if a flush landed mid-write."""
        if not self._io_lock.acquire(blocking=blocking):
            return False
        try:
            removed = self._unlink_locked()
            if removed:
                self.truncates += 1
            return removed
        finally:
            self._io_lock.release()

    def _unlink_locked(self) -> bool:
        removed = False
        for p in (self.path, self.path + ".tmp"):
            try:
                os.unlink(p)
                removed = True
            except FileNotFoundError:
                pass
            except OSError as e:  # pragma: no cover - fs-dependent
                log.warning("could not remove checkpoint %s: %s", p, e)
        return removed

    def age_seconds(self) -> float:
        """Age of the last committed checkpoint — measured from startup
        before the first commit, so a checkpointer that can NEVER write
        (bad path, read-only disk) shows unbounded growth instead of a
        healthy-looking 0.0."""
        return max(0.0, time.time() - (self.last_write_at
                                       or self._created_at))

    # -- restore path ------------------------------------------------------

    def restore(self) -> int:
        """Merge a valid, fresh checkpoint into the store, then
        atomically RE-PERSIST the merged store over the consumed file —
        never delete it: a crash-looping process must not destroy
        on-disk state it has not yet re-written (the no-double-flush
        invariant rides on truncate-on-flush + the epoch guard, not on
        removing the file here, and re-merging a never-flushed
        checkpoint after another crash is correct). Unusable files are
        discarded (counted + logged + removed). NEVER raises: a
        malformed checkpoint must not prevent startup. Runs at most
        once per process. Returns the number of series merged."""
        if self._restored:
            return 0
        self._restored = True
        try:
            blob = ckpt_format.read_file(self.path)
            if blob is None:
                return 0
            groups, manifest = ckpt_format.deserialize(blob)
            age = time.time() - float(manifest.get("created_at", 0.0))
            if age > self.max_age_s:
                raise CheckpointInvalid(
                    "stale", f"{age:.1f}s old > {self.max_age_s:.1f}s")
        except CheckpointInvalid as e:
            self.discard_total += 1
            log.warning("discarding checkpoint %s (%s)", self.path, e)
            self.truncate()
            return 0
        except Exception:
            self.discard_total += 1
            log.exception("discarding unreadable checkpoint %s", self.path)
            self.truncate()
            return 0
        try:
            merged = self.store.restore_state(groups)
        except Exception:
            self.discard_total += 1
            log.exception("checkpoint %s failed to merge; discarding",
                          self.path)
            self.truncate()
            return 0
        self.restore_total += 1
        self.restored_series += merged
        try:
            # replaces the consumed file with a snapshot of the merged
            # store; if THIS fails the old checkpoint stays on disk,
            # which is still safe (it was never flushed)
            self.write_once()
        except Exception:
            log.exception("could not re-persist the restored state; "
                          "keeping the consumed checkpoint")
        log.info("recovered %d series from checkpoint %s (%.1fs old)",
                 merged, self.path, max(0.0, age))
        return merged

"""The checkpoint file format: versioned, CRC-guarded, atomically written.

One checkpoint file holds a complete host-side snapshot of the store's
dense state (``MetricStore.snapshot_state``): interner keys, scalar
arrays, digest centroid runs, HLL registers and count-min rows. Layout:

    offset 0   magic   b"VCKP"
    offset 4   u16     format version (1)
    offset 6   u16     flags (0)
    offset 8   u64     payload length (truncation check)
    offset 16  u32     CRC-32 of the payload (corruption check)
    offset 20  payload = u32 manifest length + JSON manifest + arena

The manifest is JSON (group structure, interner strings, metadata);
every numpy array is spilled into the binary arena and referenced as
``{"__a__": {"o": offset, "n": count, "d": dtype, "s": shape}}``.

Durability contract: ``write_atomic`` writes ``path + ".tmp"``, fsyncs,
then ``os.replace``s over ``path`` — a reader (including a recovering
process) can NEVER observe a partial file, only the previous complete
checkpoint or the new one. ``deserialize`` validates magic, version,
length and CRC before touching the manifest and raises
:class:`CheckpointInvalid` (with a telemetry ``reason``) on anything it
cannot prove whole — a malformed checkpoint is discarded, never
half-applied.
"""

from __future__ import annotations

import json
import logging
import os
import struct
import zlib
from typing import Dict, Optional, Tuple

import numpy as np

log = logging.getLogger("veneur.persist")

MAGIC = b"VCKP"
VERSION = 1
_HEADER = struct.Struct("<4sHHQI")  # magic, version, flags, payload, crc
_MANIFEST_LEN = struct.Struct("<I")


class CheckpointInvalid(Exception):
    """The file is not a usable checkpoint. ``reason`` is a short
    machine-friendly slug (truncated / corrupt / bad-magic /
    bad-version / malformed / stale) for discard telemetry."""

    def __init__(self, reason: str, detail: str = ""):
        self.reason = reason
        super().__init__(f"{reason}: {detail}" if detail else reason)


def serialize(groups: Dict[str, dict], created_at: float,
              interval: float, meta: Optional[dict] = None) -> bytes:
    """Snapshot dict (``MetricStore.snapshot_state``) → checkpoint bytes."""
    arena = bytearray()

    def ref(arr: np.ndarray) -> dict:
        arr = np.ascontiguousarray(arr)
        off = len(arena)
        arena.extend(arr.tobytes())
        return {"o": off, "n": int(arr.size), "d": arr.dtype.str,
                "s": list(arr.shape)}

    enc_groups: Dict[str, dict] = {}
    for name, snap in groups.items():
        enc_groups[name] = {
            k: ({"__a__": ref(v)} if isinstance(v, np.ndarray) else v)
            for k, v in snap.items()}
    manifest = {"created_at": float(created_at),
                "interval": float(interval), "groups": enc_groups,
                # nested so caller metadata can never clobber the
                # reserved keys above
                "meta": dict(meta or {})}
    mbytes = json.dumps(manifest, separators=(",", ":")).encode("utf-8")
    payload = _MANIFEST_LEN.pack(len(mbytes)) + mbytes + bytes(arena)
    header = _HEADER.pack(MAGIC, VERSION, 0, len(payload),
                          zlib.crc32(payload))
    return header + payload


def deserialize(blob: bytes) -> Tuple[Dict[str, dict], dict]:
    """Checkpoint bytes → (groups, manifest-metadata). Raises
    :class:`CheckpointInvalid`; never returns partially-decoded state."""
    if len(blob) < _HEADER.size:
        raise CheckpointInvalid("truncated",
                               f"{len(blob)} bytes < header")
    magic, version, _flags, payload_len, crc = _HEADER.unpack_from(blob)
    if magic != MAGIC:
        raise CheckpointInvalid("bad-magic", repr(magic))
    if version != VERSION:
        raise CheckpointInvalid("bad-version", str(version))
    payload = blob[_HEADER.size:]
    if len(payload) != payload_len:
        raise CheckpointInvalid(
            "truncated", f"payload {len(payload)} != {payload_len}")
    if zlib.crc32(payload) != crc:
        raise CheckpointInvalid("corrupt", "CRC mismatch")
    try:
        (mlen,) = _MANIFEST_LEN.unpack_from(payload)
        manifest = json.loads(
            payload[_MANIFEST_LEN.size:_MANIFEST_LEN.size + mlen])
        arena = payload[_MANIFEST_LEN.size + mlen:]
        groups: Dict[str, dict] = {}
        for name, enc in manifest.pop("groups").items():
            snap = {}
            for k, v in enc.items():
                if isinstance(v, dict) and "__a__" in v:
                    r = v["__a__"]
                    snap[k] = np.frombuffer(
                        arena, dtype=np.dtype(r["d"]), count=r["n"],
                        offset=r["o"]).reshape(r["s"]).copy()
                else:
                    snap[k] = v
            groups[name] = snap
    except CheckpointInvalid:
        raise
    except Exception as e:
        raise CheckpointInvalid("malformed", str(e))
    return groups, manifest


def write_atomic(path: str, blob: bytes) -> int:
    """temp + fsync + rename so readers never see a partial file."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    try:  # best-effort directory durability (the rename itself)
        dfd = os.open(os.path.dirname(os.path.abspath(path)), os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:  # pragma: no cover - platform-dependent
        pass
    return len(blob)


def read_file(path: str) -> Optional[bytes]:
    """Whole-file read; None when the checkpoint does not exist."""
    try:
        with open(path, "rb") as f:
            return f.read()
    except FileNotFoundError:
        return None

"""Flush-time archival plugins (``/root/reference/plugins/plugins.go:16-19``).

Plugins receive the full ``[InterMetric]`` batch after the sinks each
flush (flusher.go:95-109) and archive it (S3, local file).
"""

from __future__ import annotations

import abc
from typing import List

from veneur_tpu.samplers.intermetric import InterMetric


class Plugin(abc.ABC):
    """plugins.Plugin (plugins/plugins.go:16-19)."""

    @property
    @abc.abstractmethod
    def name(self) -> str: ...

    @abc.abstractmethod
    def flush(self, metrics: List[InterMetric]) -> None: ...

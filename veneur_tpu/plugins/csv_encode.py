"""TSV row encoding for archival plugins.

Port of ``/root/reference/plugins/s3/csv.go``: fixed column order
(Name, Tags, MetricType, VeneurHostname, Interval, Timestamp, Value,
Partition; csv.go:17-49), tags as ``{a,b}``, counters emitted as rates,
Redshift timestamp format, and a ``yyyymmdd`` partition column
(csv.go:55-92).
"""

from __future__ import annotations

import csv
import gzip
import io
import math
import time
from typing import List, Optional

from veneur_tpu.samplers.intermetric import InterMetric, MetricType

PARTITION_DATE_FORMAT = "%Y%m%d"
# Go's "2006-01-02 03:04:05" is a *12-hour* clock (03 not 15), and the
# reference uses it verbatim (csv.go:15) — match it, quirk included.
REDSHIFT_DATE_FORMAT = "%Y-%m-%d %I:%M:%S"

TSV_SCHEMA = ["Name", "Tags", "MetricType", "VeneurHostname", "Interval",
              "Timestamp", "Value", "Partition"]


def _format_value(v: float) -> str:
    """Shortest non-exponential decimal, like Go's FormatFloat(v,'f',-1,64)
    (csv.go:81), including its +Inf/-Inf/NaN spellings."""
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e16:
        return str(int(v))
    s = repr(v)
    if "e" in s or "E" in s:
        s = format(v, ".17f").rstrip("0").rstrip(".")
    return s


def encode_intermetric_row(m: InterMetric, hostname: str, interval: int,
                           partition_date: float) -> List[str]:
    """One TSV row (csv.go:55-92). Raises on unknown metric types."""
    tags = "{" + ",".join(m.tags) + "}"
    if m.type == MetricType.COUNTER:
        value = m.value / interval
        metric_type = "rate"
    elif m.type == MetricType.GAUGE:
        value = m.value
        metric_type = "gauge"
    else:
        raise ValueError(f"Encountered an unknown metric type {m.type}")
    return [
        m.name,
        tags,
        metric_type,
        hostname,
        str(interval),
        time.strftime(REDSHIFT_DATE_FORMAT, time.gmtime(m.timestamp)),
        _format_value(value),
        time.strftime(PARTITION_DATE_FORMAT, time.gmtime(partition_date)),
    ]


def encode_columnar_csv(batch, hostname: str, interval: int,
                        partition_date: Optional[float] = None) -> bytes:
    """Gzipped TSV of a ColumnarFlush: blocks serialize natively
    (native/veneur_egress.cpp vt_tsv_rows — no per-row objects), extras
    take the per-row encoder. Same bytes as encode_intermetrics_csv on
    the materialized batch."""
    import numpy as np

    from veneur_tpu.native import egress

    if partition_date is None:
        partition_date = time.time()
    ts_str = time.strftime(REDSHIFT_DATE_FORMAT,
                           time.gmtime(batch.timestamp))
    part_str = time.strftime(PARTITION_DATE_FORMAT,
                             time.gmtime(partition_date))
    buf = io.BytesIO()
    with gzip.GzipFile(fileobj=buf, mode="wb") as gz:
        for blk in batch.blocks:
            values = blk.values
            if (blk.type_codes == 1).any():
                values = np.where(blk.type_codes == 1,
                                  values / interval, values)
            gz.write(egress.tsv_rows(
                blk.names, blk.tags, blk.suffixes, blk.rows,
                blk.suffix_idx, values, blk.type_codes, hostname,
                interval, ts_str, part_str))
        if batch.extras:
            text = io.TextIOWrapper(gz, encoding="utf-8", newline="")
            w = csv.writer(text, delimiter="\t", lineterminator="\n")
            for m in batch.extras:
                try:
                    w.writerow(encode_intermetric_row(
                        m, hostname, interval, partition_date))
                except ValueError:
                    continue
            text.flush()
            text.detach()
    return buf.getvalue()


def encode_intermetrics_csv(metrics: List[InterMetric], hostname: str,
                            interval: int, delimiter: str = "\t",
                            include_headers: bool = False,
                            partition_date: Optional[float] = None) -> bytes:
    """Gzipped TSV of the whole batch (s3.go:99-135). Rows that fail to
    encode are skipped, matching the reference's unchecked write."""
    if partition_date is None:
        partition_date = time.time()
    buf = io.BytesIO()
    with gzip.GzipFile(fileobj=buf, mode="wb") as gz:
        text = io.TextIOWrapper(gz, encoding="utf-8", newline="")
        w = csv.writer(text, delimiter=delimiter, lineterminator="\n")
        if include_headers:
            w.writerow(TSV_SCHEMA)
        for m in metrics:
            try:
                w.writerow(encode_intermetric_row(m, hostname, interval,
                                                  partition_date))
            except ValueError:
                continue
        text.flush()
        text.detach()
    return buf.getvalue()

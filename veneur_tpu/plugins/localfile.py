"""Local-file archival plugin: gzip TSV append per flush.

Port of ``/root/reference/plugins/localfile/localfile.go:31-61``: each
flush appends one complete gzip member (TSV rows of the whole batch) to
``file_path`` — concatenated gzip members decompress as one stream.
"""

from __future__ import annotations

import logging
from typing import List

from veneur_tpu.plugins import Plugin
from veneur_tpu.plugins.csv_encode import (encode_columnar_csv,
                                           encode_intermetrics_csv)
from veneur_tpu.samplers.intermetric import InterMetric

log = logging.getLogger("veneur.plugins.localfile")


class LocalFilePlugin(Plugin):
    def __init__(self, file_path: str, hostname: str, interval: int = 10):
        self.file_path = file_path
        self.hostname = hostname
        self.interval = interval

    @property
    def name(self) -> str:
        return "localfile"

    def flush(self, metrics: List[InterMetric]) -> None:
        self._append(encode_intermetrics_csv(metrics, self.hostname,
                                             self.interval))

    def flush_columnar(self, batch) -> None:
        """Columnar archive: TSV rows serialize natively from the flush
        columns instead of per-row InterMetrics."""
        self._append(encode_columnar_csv(batch, self.hostname,
                                         self.interval))

    def _append(self, blob: bytes) -> None:
        try:
            with open(self.file_path, "ab") as f:
                f.write(blob)
        except OSError as e:
            raise RuntimeError(
                f"couldn't open {self.file_path} for appending: {e}") from e

"""S3 archival plugin: gzipped TSV object per flush.

Port of ``/root/reference/plugins/s3/s3.go:35-134``: the batch is
encoded as gzip TSV and PUT to
``{yyyy}/{mm}/{dd}/{hostname}/{unix}.tsv.gz`` in the configured bucket
(S3Path, s3.go:93-97). The client is injectable — any object with
``put_object(Bucket=, Key=, Body=)`` works (boto3's S3 client does);
flushing without one raises ``S3ClientUninitializedError``
(s3.go:76-79).
"""

from __future__ import annotations

import logging
import time
from typing import List, Optional

from veneur_tpu.plugins import Plugin
from veneur_tpu.plugins.csv_encode import encode_intermetrics_csv
from veneur_tpu.samplers.intermetric import InterMetric

log = logging.getLogger("veneur.plugins.s3")


class S3ClientUninitializedError(Exception):
    pass


def s3_path(hostname: str, ft: str = "tsv.gz",
            now: Optional[float] = None) -> str:
    """{yyyy}/{mm}/{dd}/{hostname}/{unix}.{ft} (s3.go:93-97)."""
    t = now if now is not None else time.time()
    return "%s/%s/%d.%s" % (time.strftime("%Y/%m/%d", time.gmtime(t)),
                            hostname, int(t), ft)


class S3Plugin(Plugin):
    def __init__(self, hostname: str, bucket: str = "stripe-veneur",
                 interval: int = 10, svc=None):
        self.hostname = hostname
        self.bucket = bucket
        self.interval = interval
        self.svc = svc  # boto3-style client, injected

    @property
    def name(self) -> str:
        return "s3"

    def flush(self, metrics: List[InterMetric]) -> None:
        if self.svc is None:
            raise S3ClientUninitializedError(
                "s3 client has not been initialized")
        blob = encode_intermetrics_csv(metrics, self.hostname, self.interval)
        self.svc.put_object(Bucket=self.bucket,
                            Key=s3_path(self.hostname),
                            Body=blob)
        log.debug("Completed flush to s3: %d metrics", len(metrics))

    def flush_columnar(self, batch) -> None:
        """Columnar archive: TSV rows serialize natively from the flush
        columns instead of per-row InterMetrics."""
        if self.svc is None:
            raise S3ClientUninitializedError(
                "s3 client has not been initialized")
        from veneur_tpu.plugins.csv_encode import encode_columnar_csv

        blob = encode_columnar_csv(batch, self.hostname, self.interval)
        self.svc.put_object(Bucket=self.bucket,
                            Key=s3_path(self.hostname),
                            Body=blob)
        log.debug("Completed columnar flush to s3: %d metrics", len(batch))

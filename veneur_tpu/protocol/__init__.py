"""Wire protocols: SSF protobuf schema, framed-SSF codec, forwarding schemas.

Generated protobuf modules live in ``gen/`` (regenerate with
``regen_protos.sh``); they are re-exported here under stable names:

    from veneur_tpu.protocol import ssf_pb2, metricpb_pb2, forward_pb2
"""

from veneur_tpu.protocol.gen.ssf import sample_pb2 as ssf_pb2
from veneur_tpu.protocol.gen.tdigestpb import tdigest_pb2 as tdigest_pb2
from veneur_tpu.protocol.gen.metricpb import metric_pb2 as metricpb_pb2
from veneur_tpu.protocol.gen.forwardrpc import forward_pb2 as forward_pb2
from veneur_tpu.protocol.gen.grpsink import grpc_sink_pb2 as grpsink_pb2

from .wire import (  # noqa: E402
    MAX_FRAME_LENGTH,
    FramingError,
    parse_ssf,
    read_ssf,
    write_ssf,
)
from .addr import resolve_addr  # noqa: E402

__all__ = [
    "ssf_pb2",
    "tdigest_pb2",
    "metricpb_pb2",
    "forward_pb2",
    "grpsink_pb2",
    "MAX_FRAME_LENGTH",
    "FramingError",
    "parse_ssf",
    "read_ssf",
    "write_ssf",
    "resolve_addr",
]

"""URL-style listen-address resolution (cf. /root/reference/protocol/addr.go).

Valid examples::

    udp://127.0.0.1:8126
    tcp6://[::1]:9002
    unix:///tmp/veneur.sock
"""

from __future__ import annotations

import socket
from dataclasses import dataclass
from urllib.parse import urlparse


@dataclass(frozen=True)
class ResolvedAddr:
    """A resolved listen/connect address.

    family: "udp" | "tcp" | "unix"  (udp4/udp6 collapse into udp, etc.)
    host/port for inet families; path for unix sockets.
    """

    scheme: str
    family: str
    host: str = ""
    port: int = 0
    path: str = ""

    @property
    def socket_family(self) -> int:
        if self.family == "unix":
            return socket.AF_UNIX
        if self.scheme.endswith("6"):
            return socket.AF_INET6
        return socket.AF_INET

    @property
    def socket_type(self) -> int:
        return socket.SOCK_DGRAM if self.family == "udp" else socket.SOCK_STREAM

    def connect_target(self):
        return self.path if self.family == "unix" else (self.host, self.port)


def resolve_addr(spec: str) -> ResolvedAddr:
    """Parse a URL-style address spec; raises ValueError on unknown schemes
    (addr.go:18-43)."""
    u = urlparse(spec)
    scheme = u.scheme
    if scheme in ("unix", "unixgram", "unixpacket"):
        if not u.path:
            raise ValueError(f"no path in unix address {spec!r}")
        return ResolvedAddr(scheme=scheme, family="unix", path=u.path)
    if scheme in ("tcp", "tcp4", "tcp6", "udp", "udp4", "udp6"):
        family = "tcp" if scheme.startswith("tcp") else "udp"
        host = u.hostname or ""
        if u.port is None:
            raise ValueError(f"no port in address {spec!r}")
        # Resolve the hostname eagerly, mirroring net.Resolve*Addr.
        af = socket.AF_INET6 if scheme.endswith("6") else socket.AF_UNSPEC
        if host:
            infos = socket.getaddrinfo(host, u.port, af,
                                       socket.SOCK_DGRAM if family == "udp"
                                       else socket.SOCK_STREAM)
            host = infos[0][4][0]
        return ResolvedAddr(scheme=scheme, family=family, host=host, port=u.port)
    raise ValueError(f"unknown address family {scheme!r} on address {spec!r}")

"""Shared protocol constants."""

# Magic tag keys used to conduct DogStatsD event fields through SSF samples
# (cf. /root/reference/protocol/dogstatsd/protocol.go).
EVENT_AGGREGATION_KEY_TAG = "vdogstatsd_ak"
EVENT_ALERT_TYPE_TAG = "vdogstatsd_at"
EVENT_HOSTNAME_TAG = "vdogstatsd_hostname"
EVENT_IDENTIFIER_KEY = "vdogstatsd_ev"
EVENT_PRIORITY_TAG = "vdogstatsd_pri"
EVENT_SOURCE_TYPE_TAG = "vdogstatsd_st"

"""Minimal Go ``encoding/gob`` stream reader — reference HTTP interop.

A reference (Go) local's ``POST /import`` body wraps each sketch in a
``JSONMetric`` whose ``value`` is the sampler's internal serialization
(``/root/reference/samplers/samplers.go``): counters are a little-endian
int64, gauges a little-endian float64, sets the axiomhq binary sketch
(handled by ``ops/axiomhq.py``), and histograms/timers a **gob stream**
of ``[]tdigest.Centroid`` + compression + min + max
(``tdigest/merging_digest.go:375-394``).

This module implements exactly the subset of the gob wire format those
streams use — unsigned/signed ints, byte-reversed floats, strings,
struct/slice type definitions and values — validated against the
reference's checked-in fixture (``fixtures/import.uncompressed``).

Format summary (the encoding/gob specification):

- unsigned int: one byte if < 128, else a byte holding the NEGATED count
  of the minimal big-endian bytes that follow.
- signed int i: unsigned (i<<1), low bit set and bits complemented when
  negative.
- float64: IEEE-754 bytes reversed, then sent as an unsigned int.
- string/[]byte: unsigned length + raw bytes.
- stream: messages of (unsigned byte count, body). A body starts with a
  signed type id — negative defines that type (a wireType value
  follows), positive sends a value of the type. Non-struct top-level
  values are preceded by one delta byte (as if field 0 of a struct);
  struct values are (field delta, value) pairs ending with delta 0.
"""

from __future__ import annotations

import struct
from typing import Dict, List, Tuple

# builtin gob type ids (gob/type.go)
BOOL, INT, UINT, FLOAT, BYTES, STRING = 1, 2, 3, 4, 5, 6


class GobError(ValueError):
    pass


class _Reader:
    __slots__ = ("data", "pos", "end")

    def __init__(self, data: bytes, pos: int = 0, end: int = -1):
        self.data = data
        self.pos = pos
        self.end = len(data) if end < 0 else end

    def byte(self) -> int:
        if self.pos >= self.end:
            raise GobError("truncated gob stream")
        b = self.data[self.pos]
        self.pos += 1
        return b

    def read_uint(self) -> int:
        b = self.byte()
        if b < 0x80:
            return b
        n = 256 - b
        if n > 8 or self.pos + n > self.end:
            raise GobError(f"bad uint byte count {n}")
        v = int.from_bytes(self.data[self.pos:self.pos + n], "big")
        self.pos += n
        return v

    def read_int(self) -> int:
        u = self.read_uint()
        return ~(u >> 1) if u & 1 else u >> 1

    def read_float(self) -> float:
        # the float64's bytes are REVERSED then sent as an unsigned int:
        # the wire number's big-endian bytes, read back least-significant
        # -first, are the original IEEE-754 bits
        u = self.read_uint()
        return struct.unpack("<d", u.to_bytes(8, "big"))[0]

    def read_bytes(self) -> bytes:
        n = self.read_uint()
        if self.pos + n > self.end:
            raise GobError("truncated gob bytes")
        out = self.data[self.pos:self.pos + n]
        self.pos += n
        return out


# wireType field indices (gob/type.go wireType struct)
_W_ARRAY, _W_SLICE, _W_STRUCT, _W_MAP = 0, 1, 2, 3


class _SliceType:
    __slots__ = ("elem",)

    def __init__(self, elem: int):
        self.elem = elem


class _StructType:
    __slots__ = ("name", "fields")  # fields: [(name, typeid)]

    def __init__(self, name: str, fields: List[Tuple[str, int]]):
        self.name = name
        self.fields = fields


class GobStream:
    """Decode one gob stream's values in order."""

    def __init__(self, data: bytes):
        self.r = _Reader(data)
        self.types: Dict[int, object] = {}

    def _read_common(self, r: _Reader) -> str:
        """CommonType{Name string, Id int} (as a struct value)."""
        name = ""
        field = -1
        while True:
            delta = r.read_uint()
            if delta == 0:
                return name
            field += delta
            if field == 0:
                name = r.read_bytes().decode("utf-8", "replace")
            elif field == 1:
                r.read_int()  # Id (redundant with the message's type id)
            else:
                raise GobError(f"unexpected CommonType field {field}")

    def _read_typedef(self, type_id: int, r: _Reader):
        field = -1
        wt = None
        while True:
            delta = r.read_uint()
            if delta == 0:
                break
            field += delta
            if field == _W_SLICE:
                # SliceType{CommonType, Elem typeId}
                elem = 0
                f2 = -1
                while True:
                    d2 = r.read_uint()
                    if d2 == 0:
                        break
                    f2 += d2
                    if f2 == 0:
                        self._read_common(r)
                    elif f2 == 1:
                        elem = r.read_int()
                    else:
                        raise GobError("unexpected SliceType field")
                wt = _SliceType(elem)
            elif field == _W_STRUCT:
                # StructType{CommonType, Field []fieldType}
                name = ""
                fields: List[Tuple[str, int]] = []
                f2 = -1
                while True:
                    d2 = r.read_uint()
                    if d2 == 0:
                        break
                    f2 += d2
                    if f2 == 0:
                        name = self._read_common(r)
                    elif f2 == 1:
                        for _ in range(r.read_uint()):
                            fname, fid, f3 = "", 0, -1
                            while True:
                                d3 = r.read_uint()
                                if d3 == 0:
                                    break
                                f3 += d3
                                if f3 == 0:
                                    fname = r.read_bytes().decode(
                                        "utf-8", "replace")
                                elif f3 == 1:
                                    fid = r.read_int()
                                else:
                                    raise GobError(
                                        "unexpected fieldType field")
                            fields.append((fname, fid))
                    else:
                        raise GobError("unexpected StructType field")
                wt = _StructType(name, fields)
            else:
                raise GobError(
                    f"unsupported wireType kind (field {field})")
        if wt is None:
            raise GobError("empty type definition")
        self.types[type_id] = wt

    # real streams nest ~3 deep ([]struct{...[]float64}); a crafted
    # self-referential typedef must hit GobError, not RecursionError
    MAX_DEPTH = 32

    def _read_value(self, type_id: int, r: _Reader, depth: int = 0):
        if depth > self.MAX_DEPTH:
            raise GobError("gob value nesting too deep")
        if type_id == BOOL:
            return bool(r.read_uint())
        if type_id == INT:
            return r.read_int()
        if type_id == UINT:
            return r.read_uint()
        if type_id == FLOAT:
            return r.read_float()
        if type_id in (BYTES, STRING):
            return r.read_bytes()
        wt = self.types.get(type_id)
        if wt is None:
            raise GobError(f"value of undefined type {type_id}")
        if isinstance(wt, _SliceType):
            return [self._read_value(wt.elem, r, depth + 1)
                    for _ in range(r.read_uint())]
        # struct: (delta, value) pairs, 0-terminated; omitted fields keep
        # their zero value
        out = {name: _zero(self, fid, depth + 1)
               for name, fid in wt.fields}
        field = -1
        while True:
            delta = r.read_uint()
            if delta == 0:
                return out
            field += delta
            if not 0 <= field < len(wt.fields):
                raise GobError(f"field {field} out of range for "
                               f"{wt.name}")
            name, fid = wt.fields[field]
            out[name] = self._read_value(fid, r, depth + 1)

    def next_value(self):
        """Read messages until the next VALUE (consuming type
        definitions); returns the decoded Python value."""
        while True:
            n = self.r.read_uint()
            end = self.r.pos + n
            if end > self.r.end:
                raise GobError("message length past end of stream")
            msg = _Reader(self.r.data, self.r.pos, end)
            self.r.pos = end
            type_id = msg.read_int()
            if type_id < 0:
                self._read_typedef(-type_id, msg)
                continue
            wt = self.types.get(type_id)
            if not isinstance(wt, _StructType):
                # non-struct top-level values carry one leading ZERO
                # delta byte (observed in the reference's golden fixture)
                if msg.read_uint() != 0:
                    raise GobError("expected singleton zero-delta byte")
            return self._read_value(type_id, msg)


def _zero(stream: GobStream, type_id: int, depth: int = 0):
    if depth > GobStream.MAX_DEPTH:
        raise GobError("gob type nesting too deep")
    if type_id == FLOAT:
        return 0.0
    if type_id in (INT, UINT):
        return 0
    if type_id == BOOL:
        return False
    if type_id in (BYTES, STRING):
        return b""
    wt = stream.types.get(type_id)
    if isinstance(wt, _SliceType):
        return []
    if isinstance(wt, _StructType):
        return {name: _zero(stream, fid, depth + 1)
                for name, fid in wt.fields}
    return None


def _enc_uint(v: int) -> bytes:
    if v < 128:
        return bytes([v])
    body = v.to_bytes((v.bit_length() + 7) // 8, "big")
    return bytes([256 - len(body)]) + body


def _enc_int(i: int) -> bytes:
    return _enc_uint((~i << 1) | 1 if i < 0 else i << 1)


def _enc_float(v: float) -> bytes:
    bits = struct.unpack("<Q", struct.pack("<d", v))[0]
    return _enc_uint(int.from_bytes(bits.to_bytes(8, "little"), "big"))


def _enc_msg(body: bytes) -> bytes:
    return _enc_uint(len(body)) + body


# The type-definition prologue MergingDigest.GobEncode's stream carries,
# byte-identical to the Go encoder's output (ids 68 = []Centroid,
# 66 = Centroid{Mean, Weight, Samples}, 67 = []float64, defined in that
# order; verified against the reference's fixtures/import.uncompressed).
_DIGEST_PROLOGUE = (
    _enc_msg(_enc_int(-68) + _enc_uint(2)
             + _enc_uint(1) + _enc_uint(2) + _enc_int(68) + _enc_uint(0)
             + _enc_uint(1) + _enc_int(66) + _enc_uint(0) + _enc_uint(0))
    + _enc_msg(_enc_int(-66) + _enc_uint(3)
               + _enc_uint(1) + _enc_uint(1) + _enc_uint(8) + b"Centroid"
               + _enc_uint(1) + _enc_int(66) + _enc_uint(0)
               + _enc_uint(1) + _enc_uint(3)
               + _enc_uint(1) + _enc_uint(4) + b"Mean"
               + _enc_uint(1) + _enc_int(FLOAT) + _enc_uint(0)
               + _enc_uint(1) + _enc_uint(6) + b"Weight"
               + _enc_uint(1) + _enc_int(FLOAT) + _enc_uint(0)
               + _enc_uint(1) + _enc_uint(7) + b"Samples"
               + _enc_uint(1) + _enc_int(67) + _enc_uint(0)
               + _enc_uint(0) + _enc_uint(0))
    + _enc_msg(_enc_int(-67) + _enc_uint(2)
               + _enc_uint(1) + _enc_uint(1) + _enc_uint(9) + b"[]float64"
               + _enc_uint(1) + _enc_int(67) + _enc_uint(0)
               + _enc_uint(1) + _enc_int(FLOAT) + _enc_uint(0)
               + _enc_uint(0)))


def encode_reference_digest(means, weights, compression: float,
                            dmin: float, dmax: float) -> bytes:
    """The inverse of ``decode_reference_digest``: produce the exact gob
    stream ``MergingDigest.GobDecode`` reads (merging_digest.go:396-426)
    — Encode([]Centroid), Encode(compression), Encode(min), Encode(max).
    Output is byte-identical to the Go encoder's for the same centroids
    (asserted against the reference's golden fixture in tests)."""
    cents = bytearray(_enc_uint(len(means)))
    for mean, weight in zip(means, weights):
        # gob omits zero-valued struct fields (field deltas skip them);
        # Samples stays empty (the reference's streams never populate it)
        mean, weight = float(mean), float(weight)
        delta = 1
        if mean != 0.0:
            cents += _enc_uint(1) + _enc_float(mean)
        else:
            delta = 2
        if weight != 0.0:
            cents += _enc_uint(delta) + _enc_float(weight)
        cents += _enc_uint(0)
    out = bytearray(_DIGEST_PROLOGUE)
    out += _enc_msg(_enc_int(68) + _enc_uint(0) + bytes(cents))
    for x in (compression, dmin, dmax):
        out += _enc_msg(_enc_int(FLOAT) + _enc_uint(0) + _enc_float(x))
    return bytes(out)


def decode_reference_digest(blob: bytes):
    """The reference's ``MergingDigest.GobEncode`` stream → (means,
    weights, compression, dmin, dmax) (merging_digest.go:375-394:
    Encode(mainCentroids), Encode(compression), Encode(min),
    Encode(max))."""
    s = GobStream(blob)
    centroids = s.next_value()
    compression = s.next_value()
    dmin = s.next_value()
    dmax = s.next_value()
    if not isinstance(centroids, list):
        raise GobError("first gob value is not a centroid slice")
    means = [c["Mean"] for c in centroids]
    weights = [c["Weight"] for c in centroids]
    return means, weights, float(compression), float(dmin), float(dmax)

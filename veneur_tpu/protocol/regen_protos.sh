#!/bin/sh
# Regenerate the Python protobuf modules in gen/ from proto/.
#
# protoc emits absolute imports ("from metricpb import metric_pb2"); the sed
# pass rewrites them to package-qualified imports so gen/ needs no sys.path
# manipulation.
set -e
cd "$(dirname "$0")"
protoc --python_out=gen --proto_path=proto \
    proto/ssf/sample.proto \
    proto/tdigestpb/tdigest.proto \
    proto/metricpb/metric.proto \
    proto/forwardrpc/forward.proto \
    proto/grpsink/grpc_sink.proto
for d in gen gen/ssf gen/tdigestpb gen/metricpb gen/forwardrpc gen/grpsink; do
    touch "$d/__init__.py"
done
sed -i -E 's/^from (ssf|tdigestpb|metricpb|forwardrpc|grpsink) import/from veneur_tpu.protocol.gen.\1 import/' \
    gen/*/*_pb2.py

"""Framed-SSF stream codec.

The SSF wire protocol (cf. /root/reference/protocol/wire.go:1-53) frames a
protobuf-encoded ``ssf.SSFSpan`` as::

    [ 8 bits  version/type, currently always 0 ]
    [ 32 bits big-endian content length        ]
    [ <length> octets of SSFSpan protobuf      ]

The protocol carries no resync hints, so any framing error poisons the
stream: callers must stop reading and close the connection
(``FramingError.poisons_stream``).
"""

from __future__ import annotations

import struct
from typing import BinaryIO

MAX_FRAME_LENGTH = 16 * 1024 * 1024  # MaxSSFPacketLength (wire.go:43)
FRAME_HEADER = struct.Struct(">BI")  # 1B version + 4B BE length (wire.go:46-48)
VERSION_0 = 0


class FramingError(Exception):
    """A wire-protocol framing error: the stream is poisoned and must be
    closed (wire.go:26-28, errors.go:31-41)."""

    poisons_stream = True


class FrameVersionError(FramingError):
    def __init__(self, version: int):
        super().__init__(f"SSF framing error: unexpected version number {version}")
        self.version = version


class FrameLengthError(FramingError):
    def __init__(self, length: int):
        super().__init__(f"SSF framing error: length {length} is too large")
        self.length = length


class FramingIOError(FramingError):
    pass


def _ssf_pb2():
    # Imported lazily to avoid a cycle with protocol/__init__.
    from veneur_tpu.protocol import ssf_pb2

    return ssf_pb2


def parse_ssf(packet: bytes):
    """Decode and normalize one SSFSpan protobuf (wire.go:138-174).

    Normalization: a span with an empty name adopts (and removes) its
    "name" tag; embedded metrics with sample_rate 0 get sample_rate 1.
    Raises ``google.protobuf.message.DecodeError`` on a bad payload.
    """
    span = _ssf_pb2().SSFSpan()
    span.ParseFromString(packet)
    if not span.name and "name" in span.tags:
        span.name = span.tags["name"]
        del span.tags["name"]
    for sample in span.metrics:
        if sample.sample_rate == 0:
            sample.sample_rate = 1.0
    return span


def valid_trace(span) -> bool:
    """A span is a valid trace span iff id, trace id and both timestamps are
    set (wire.go:80-87)."""
    return bool(span.id and span.trace_id and span.start_timestamp
                and span.end_timestamp)


def _read_exact(stream: BinaryIO, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = stream.read(n - len(buf))
        if not chunk:
            raise FramingIOError(f"EOF after {len(buf)}/{n} frame octets")
        buf.extend(chunk)
    return bytes(buf)


def read_ssf(stream: BinaryIO):
    """Read one framed span from a blocking stream (wire.go:109-135).

    Returns None on clean EOF at a frame boundary; raises FramingError
    subclasses when the stream is poisoned.
    """
    first = stream.read(1)
    if first == b"":
        return None  # clean hang-up between messages
    version = first[0]
    if version != VERSION_0:
        raise FrameVersionError(version)
    length = struct.unpack(">I", _read_exact(stream, 4))[0]
    if length > MAX_FRAME_LENGTH:
        raise FrameLengthError(length)
    return parse_ssf(_read_exact(stream, length))


def write_ssf(stream: BinaryIO, span) -> int:
    """Frame and write one span; returns the number of body bytes written
    (wire.go:187-219)."""
    body = span.SerializeToString()
    if len(body) > MAX_FRAME_LENGTH:
        raise FrameLengthError(len(body))
    try:
        stream.write(FRAME_HEADER.pack(VERSION_0, len(body)))
        stream.write(body)
    except OSError as e:
        raise FramingIOError(str(e)) from e
    return len(body)


def frame_bytes(span) -> bytes:
    """Return the complete frame for a span as bytes (for datagram sends)."""
    body = span.SerializeToString()
    if len(body) > MAX_FRAME_LENGTH:
        raise FrameLengthError(len(body))
    return FRAME_HEADER.pack(VERSION_0, len(body)) + body

"""The availability tier: consistent-hash proxying of forwarded metrics.

Rebuild of ``/root/reference/proxy.go`` + ``proxysrv/``: a stateless proxy
that hashes every forwarded metric onto a ring of discovered global veneur
instances, so a given series always merges on the same global node
(SURVEY §2.2 "parallelism strategy" 6).
"""

from veneur_tpu.proxy.consistent import ConsistentRing
from veneur_tpu.proxy.proxy import Proxy
from veneur_tpu.proxy.grpc_proxy import GRPCProxyServer

__all__ = ["ConsistentRing", "Proxy", "GRPCProxyServer"]

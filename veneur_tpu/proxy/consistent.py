"""Consistent hashing ring.

Same contract as the vendored ``stathat.com/c/consistent`` the reference
proxies with (``proxy.go:437-478``): members are replicated onto a ring of
CRC32 points; ``get(key)`` walks clockwise to the first point. Adding or
removing one member only remaps ~1/N of the keyspace.
"""

from __future__ import annotations

import bisect
import threading
import zlib
from typing import Dict, List, Optional, Sequence


class EmptyRingError(Exception):
    pass


def ring_key(name: str, mtype: str, joined_tags: str) -> str:
    """THE ownership hash rule, written once: ``MetricKey.String()``
    (``name + type + joined sorted tags``, samplers/parser.go:50-56).
    Proxy routing (``metric_ring_key``), device placement
    (``fleet.router.ShardRouter``) and the elastic-resharding
    moved-range computation (``fleet.router.RingTransition``) all hash
    this same string, so ownership agrees across every tier by
    construction. Lives here — the one module all three import —
    so none of them needs a cyclic or per-call import."""
    return name + mtype + joined_tags


class ConsistentRing:
    """Thread-safe consistent hash ring with virtual replicas."""

    def __init__(self, members: Optional[Sequence[str]] = None,
                 replicas: int = 20):
        self.replicas = replicas
        self._lock = threading.RLock()
        self._points: List[int] = []
        self._owner: Dict[int, str] = {}
        self._members: set = set()
        # bumped on every membership mutation; a routing consumer that
        # resolves a whole batch under one lock hold (get_many) routes
        # it by exactly one version of the ring
        self.version = 0
        if members:
            self.set_members(members)

    @staticmethod
    def _hash(key: str) -> int:
        return zlib.crc32(key.encode("utf-8")) & 0xFFFFFFFF

    def members(self) -> List[str]:
        with self._lock:
            return sorted(self._members)

    def __len__(self) -> int:
        return len(self._members)

    @staticmethod
    def _add_into(points: List[int], owner: Dict[int, str], members: set,
                  member: str, replicas: int):
        if member in members:
            return
        members.add(member)
        for i in range(replicas):
            h = ConsistentRing._hash(f"{member}{i}")
            # last-write-wins on the (rare) collision, like the original
            if h not in owner:
                bisect.insort(points, h)
            owner[h] = member

    @staticmethod
    def _remove_from(points: List[int], owner: Dict[int, str],
                     members: set, member: str, replicas: int):
        if member not in members:
            return
        members.discard(member)
        for i in range(replicas):
            h = ConsistentRing._hash(f"{member}{i}")
            if owner.get(h) == member:
                del owner[h]
                idx = bisect.bisect_left(points, h)
                if idx < len(points) and points[idx] == h:
                    points.pop(idx)

    def add(self, member: str):
        with self._lock:
            if member in self._members:
                return
            self._add_into(self._points, self._owner, self._members,
                           member, self.replicas)
            self.version += 1

    def remove(self, member: str):
        with self._lock:
            if member not in self._members:
                return
            self._remove_from(self._points, self._owner, self._members,
                              member, self.replicas)
            self.version += 1

    def set_members(self, members: Sequence[str]):
        """Replace the membership ATOMICALLY (RefreshDestinations,
        proxy.go:337-371): the removes and adds apply to private copies
        that swap in under one lock hold, so a concurrent ``get`` /
        ``get_many`` can never observe a half-transitioned ring — the
        window where a key routed to neither its old nor its new owner
        (the ring-transition double-count hazard; docs/resilience.md
        "Elastic resharding")."""
        with self._lock:
            want = set(members)
            if want == self._members:
                return
            points = list(self._points)
            owner = dict(self._owner)
            current = set(self._members)
            for m in sorted(current - want):
                self._remove_from(points, owner, current, m, self.replicas)
            for m in sorted(want - current):
                self._add_into(points, owner, current, m, self.replicas)
            self._points, self._owner, self._members = points, owner, current
            self.version += 1

    def _get_locked(self, key: str) -> str:
        h = self._hash(key)
        idx = bisect.bisect_right(self._points, h)
        if idx == len(self._points):
            idx = 0
        return self._owner[self._points[idx]]

    def get(self, key: str) -> str:
        """The member owning ``key`` (clockwise walk)."""
        with self._lock:
            if not self._points:
                raise EmptyRingError("ring has no members")
            return self._get_locked(key)

    def get_many(self, keys: Sequence[str]) -> List[str]:
        """Owners for a whole batch under ONE lock hold: every key
        routes by the same ring version, so a membership swap landing
        mid-batch cannot split the batch across two rings (the proxy's
        fan-out and the handoff router both route per-batch)."""
        with self._lock:
            if not self._points:
                raise EmptyRingError("ring has no members")
            return [self._get_locked(k) for k in keys]

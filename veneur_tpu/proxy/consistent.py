"""Consistent hashing ring.

Same contract as the vendored ``stathat.com/c/consistent`` the reference
proxies with (``proxy.go:437-478``): members are replicated onto a ring of
CRC32 points; ``get(key)`` walks clockwise to the first point. Adding or
removing one member only remaps ~1/N of the keyspace.
"""

from __future__ import annotations

import bisect
import threading
import zlib
from typing import Dict, List, Optional, Sequence


class EmptyRingError(Exception):
    pass


class ConsistentRing:
    """Thread-safe consistent hash ring with virtual replicas."""

    def __init__(self, members: Optional[Sequence[str]] = None,
                 replicas: int = 20):
        self.replicas = replicas
        self._lock = threading.RLock()
        self._points: List[int] = []
        self._owner: Dict[int, str] = {}
        self._members: set = set()
        if members:
            self.set_members(members)

    @staticmethod
    def _hash(key: str) -> int:
        return zlib.crc32(key.encode("utf-8")) & 0xFFFFFFFF

    def members(self) -> List[str]:
        with self._lock:
            return sorted(self._members)

    def __len__(self) -> int:
        return len(self._members)

    def add(self, member: str):
        with self._lock:
            if member in self._members:
                return
            self._members.add(member)
            for i in range(self.replicas):
                h = self._hash(f"{member}{i}")
                # last-write-wins on the (rare) collision, like the original
                if h not in self._owner:
                    bisect.insort(self._points, h)
                self._owner[h] = member

    def remove(self, member: str):
        with self._lock:
            if member not in self._members:
                return
            self._members.discard(member)
            for i in range(self.replicas):
                h = self._hash(f"{member}{i}")
                if self._owner.get(h) == member:
                    del self._owner[h]
                    idx = bisect.bisect_left(self._points, h)
                    if idx < len(self._points) and self._points[idx] == h:
                        self._points.pop(idx)

    def set_members(self, members: Sequence[str]):
        """Replace the membership (RefreshDestinations, proxy.go:337-371)."""
        with self._lock:
            want = set(members)
            for m in self._members - want:
                self.remove(m)
            for m in want - self._members:
                self.add(m)

    def get(self, key: str) -> str:
        """The member owning ``key`` (clockwise walk)."""
        with self._lock:
            if not self._points:
                raise EmptyRingError("ring has no members")
            h = self._hash(key)
            idx = bisect.bisect_right(self._points, h)
            if idx == len(self._points):
                idx = 0
            return self._owner[self._points[idx]]

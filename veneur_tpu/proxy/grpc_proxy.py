"""The gRPC proxy: Forward.SendMetrics fan-out over the consistent ring.

Behavioral port of ``/root/reference/proxysrv/server.go``: receive a
MetricList, hash each metric to a destination (``destForMetric``,
proxysrv/server.go:272-286), forward each group in parallel with error
aggregation (``sendMetrics``, :189-269), prune stale connections on
membership change (``SetDestinations``, :147-177). The reference answers
the RPC before forwarding completes (fire-and-forget, :179-187).
"""

from __future__ import annotations

import logging
import threading
from collections import defaultdict
from concurrent import futures
from typing import Dict, List, Optional, Sequence

import grpc
from google.protobuf import empty_pb2

from veneur_tpu.forward.grpc_forward import _MAX_MESSAGE

# a proxy between a big local and its global must pass the same message
# sizes as the forward tier — imported so they stay in lockstep
_GRPC_OPTIONS = [("grpc.max_receive_message_length", _MAX_MESSAGE),
                 ("grpc.max_send_message_length", _MAX_MESSAGE)]

from veneur_tpu.forward.convert import type_name
from veneur_tpu.protocol import forward_pb2
from veneur_tpu.proxy.consistent import ConsistentRing, EmptyRingError

log = logging.getLogger("veneur.proxy.grpc")

_METHOD = "/forwardrpc.Forward/SendMetrics"


class _ConnMap:
    """Destination → channel + stub, pruned on membership change
    (proxysrv/client_conn_map.go:13-60)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._conns: Dict[str, tuple] = {}

    def get(self, dest: str):
        with self._lock:
            entry = self._conns.get(dest)
            if entry is None:
                addr = dest.split("://", 1)[-1]
                channel = grpc.insecure_channel(addr, options=_GRPC_OPTIONS)
                send = channel.unary_unary(
                    _METHOD,
                    request_serializer=(
                        forward_pb2.MetricList.SerializeToString),
                    response_deserializer=empty_pb2.Empty.FromString)
                entry = (channel, send)
                self._conns[dest] = entry
            return entry[1]

    def prune(self, keep: Sequence[str]):
        with self._lock:
            for dest in list(self._conns):
                if dest not in keep:
                    channel, _ = self._conns.pop(dest)
                    channel.close()

    def close(self):
        self.prune([])


class GRPCProxyServer:
    """gRPC flavor of veneur-proxy (proxysrv.Server)."""

    def __init__(self, destinations: Optional[Sequence[str]] = None,
                 forward_timeout: float = 10.0, workers: int = 8):
        self.ring = ConsistentRing()
        self.conns = _ConnMap()
        self.forward_timeout = forward_timeout
        self.proxied = 0
        self.forward_errors = 0
        self._lock = threading.Lock()
        if destinations:
            self.set_destinations(destinations)

        self._grpc = grpc.server(futures.ThreadPoolExecutor(workers),
                                 options=_GRPC_OPTIONS)
        handler = grpc.method_handlers_generic_handler(
            "forwardrpc.Forward",
            {"SendMetrics": grpc.unary_unary_rpc_method_handler(
                self._recv,
                request_deserializer=forward_pb2.MetricList.FromString,
                response_serializer=empty_pb2.Empty.SerializeToString)})
        self._grpc.add_generic_rpc_handlers((handler,))
        self.port: Optional[int] = None

    def set_destinations(self, destinations: Sequence[str]):
        """Replace membership and drop connections to departed nodes
        (proxysrv/server.go:147-177)."""
        self.ring.set_members(destinations)
        self.conns.prune(list(destinations))

    # -- rpc ----------------------------------------------------------------

    def _recv(self, request: forward_pb2.MetricList, context):
        # answer immediately; forward on a worker thread (server.go:179-187)
        threading.Thread(target=self.send_metrics, args=(request,),
                         daemon=True).start()
        return empty_pb2.Empty()

    def send_metrics(self, mlist: forward_pb2.MetricList):
        by_dest = defaultdict(list)
        dropped = 0
        for m in mlist.metrics:
            # the SAME key as the HTTP proxy's metric_ring_key /
            # MetricKey.String(), so both transports route one series to
            # one global node (importsrv/server.go:34-36)
            try:
                key = m.name + type_name(m.type) + ",".join(m.tags)
                by_dest[self.ring.get(key)].append(m)
            except (EmptyRingError, ValueError):
                dropped += 1
        if dropped:
            log.warning("dropped %d unroutable metrics", dropped)
        threads = []
        for dest, batch in by_dest.items():
            t = threading.Thread(target=self._forward, args=(dest, batch),
                                 daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=self.forward_timeout + 1.0)

    def _forward(self, dest: str, batch: List):
        out = forward_pb2.MetricList()
        out.metrics.extend(batch)
        try:
            self.conns.get(dest)(out, timeout=self.forward_timeout)
            with self._lock:
                self.proxied += len(batch)
        except grpc.RpcError as e:
            with self._lock:
                self.forward_errors += 1
            log.warning("failed to forward %d metrics to %s: %s",
                        len(batch), dest, e)

    # -- lifecycle ----------------------------------------------------------

    def start(self, addr: str = "[::]:0") -> int:
        self.port = self._grpc.add_insecure_port(addr)
        if self.port == 0:
            raise RuntimeError(f"could not bind gRPC proxy to {addr}")
        self._grpc.start()
        log.info("gRPC proxy listening on port %d with %d destinations",
                 self.port, len(self.ring))
        return self.port

    def stop(self, grace: float = 1.0):
        self._grpc.stop(grace).wait(timeout=grace + 1.0)
        self.conns.close()

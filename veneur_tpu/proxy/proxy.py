"""The HTTP proxy: POST /import fan-out over the consistent ring.

Behavioral port of ``/root/reference/proxy.go``: discovery-driven ring
refresh (``Start``/``RefreshDestinations``, proxy.go:206-371), per-metric
consistent hashing on ``MetricKey.String()`` and parallel per-destination
POSTs (``ProxyMetrics``, proxy.go:437-505). The proxy is stateless: a
refresh failure keeps the last good ring (proxy.go:351-361), and starting
with zero destinations is fatal (proxy.go:232-243).
"""

from __future__ import annotations

import json
import logging
import threading
import zlib
from collections import defaultdict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from veneur_tpu.config import ProxyConfig
from veneur_tpu.discovery import (ConsulDiscoverer, Discoverer,
                                  RetryingDiscoverer, StaticDiscoverer)
from veneur_tpu.forward.http_forward import post_helper
from veneur_tpu.httpserv import (ImportError400, ReuseportHTTPServer,
                                 bounded_inflate,
                                 unmarshal_metrics_from_http)
from veneur_tpu.proxy.consistent import (ConsistentRing, EmptyRingError,
                                         ring_key)
from veneur_tpu.resilience import (BreakerRegistry, Deadline, RetryPolicy,
                                   faults_from_config, is_transient_status,
                                   post_with_retry)

log = logging.getLogger("veneur.proxy")


def metric_ring_key(d: dict) -> str:
    """The hash key for one JSON metric — MetricKey.String()
    (samplers/parser.go:50-56): the shared ``ring_key`` rule (name +
    type + joined sorted tags; ``proxy/consistent.py``), so proxy
    routing, shard placement and moved-range computation can never
    diverge."""
    return ring_key(d["name"], d["type"], ",".join(d.get("tags") or []))


class _ProxyHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        log.debug("proxy http: " + fmt, *args)

    def _reply(self, status: int, body: str = "", headers=None):
        data = body.encode()
        self.send_response(status)
        self.send_header("Content-Length", str(len(data)))
        for key, value in (headers or {}).items():
            self.send_header(key, value)
        self.end_headers()
        self.wfile.write(data)

    def _drain_body(self) -> bytes:
        # always consume the body: leftovers desync keep-alive connections
        length = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(length) if length else b""

    def do_GET(self):
        self._drain_body()
        path, _, qs = self.path.partition("?")
        if path == "/healthcheck":
            self._reply(200, "ok")
            return
        extra = getattr(self.server, "veneur_get_routes", {}).get(path)
        if extra is not None:
            import urllib.parse

            try:
                # handlers return (status, body, ctype[, headers])
                status, body, _, *rest = extra(
                    dict(urllib.parse.parse_qsl(qs)))
                self._reply(status, body,
                            headers=rest[0] if rest else None)
            except Exception as e:
                log.exception("handler for %s failed", path)
                self._reply(500, str(e))
        else:
            self._reply(404, "not found")

    def do_POST(self):
        body = self._drain_body()
        if self.path == "/import":
            try:
                metrics = unmarshal_metrics_from_http(self.headers, body)
            except ImportError400 as e:
                self._reply(400, str(e))
                return
            # the fleet trace plane rides through: the local's
            # X-Veneur-Trace header re-parents under this fan-out's
            # span and lands on every destination POST (obs/tracectx)
            trace_header = self.headers.get("X-Veneur-Trace")
            # accept, then fan out off the request thread
            # (handlers_global.go:28-43: "go p.ProxyMetrics")
            self._reply(202, "accepted")
            threading.Thread(target=self.server.veneur_proxy.proxy_metrics,
                             args=(metrics, trace_header),
                             daemon=True).start()
        elif self.path == "/spans":
            # Datadog trace spans fan out over their own ring
            # (handlers_global.go:45-56 → ProxyTraces, proxy.go:393-434)
            proxy = self.server.veneur_proxy
            if not proxy.accepting_traces:
                self._reply(404, "not accepting traces")
                return
            try:
                if (self.headers.get("Content-Encoding") or "") == "deflate":
                    body = bounded_inflate(body)
                traces = json.loads(body)
                if not isinstance(traces, list):
                    raise ValueError("expected a JSON array of spans")
            except (ValueError, zlib.error) as e:
                self._reply(400, f"bad trace body: {e}")
                return
            self._reply(202, "accepted")
            threading.Thread(target=proxy.proxy_traces, args=(traces,),
                             daemon=True).start()
        else:
            self._reply(404, "not found")


class Proxy:
    """veneur-proxy: consistent-hash availability layer for the global tier."""

    def __init__(self, config: ProxyConfig,
                 discoverer: Optional[Discoverer] = None):
        from veneur_tpu.config import parse_duration

        self.config = config
        if not hasattr(config, "forward_timeout_seconds"):
            # configs built directly (tests) skip read_proxy_config
            config.finalize()
        # parsed ONCE at load (config.finalize); never re-parsed here
        self.forward_timeout = config.forward_timeout_seconds
        self.refresh_interval = parse_duration(
            config.consul_refresh_interval or "30s")
        # egress resilience: retries inside the forward_timeout deadline
        # and one breaker per ring destination (docs/resilience.md)
        self.retry_policy = RetryPolicy.from_config(config)
        self.breakers = BreakerRegistry(
            failure_threshold=config.breaker_failure_threshold,
            reset_timeout=config.breaker_reset_timeout_seconds)
        self.fault_injector = faults_from_config(config)
        self._post = (self.fault_injector.wrap_post(post_helper,
                                                    "proxy.post")
                      if self.fault_injector is not None else post_helper)
        self.service_name = config.consul_forward_service_name
        if discoverer is not None:
            self.discoverer = discoverer
        elif self.service_name:
            self.discoverer = ConsulDiscoverer()
        elif config.forward_address:
            self.discoverer = StaticDiscoverer([config.forward_address])
            self.service_name = "static"
        else:
            raise ValueError(
                "proxy needs consul_forward_service_name or forward_address")

        self.ring = ConsistentRing()
        # trace spans ride their own ring (proxy.go:41,119-136): Consul
        # service when configured, else the static trace_address. The
        # trace ring needs its OWN discoverer: with a static
        # forward_address the metrics discoverer would hand the trace
        # ring the metrics destination instead of consulting the trace
        # service. An injected discoverer serves both rings (tests).
        self.trace_service_name = config.consul_trace_service_name
        self.trace_ring = ConsistentRing()
        self.accepting_traces = bool(self.trace_service_name
                                     or config.trace_address)
        if discoverer is not None:
            self.trace_discoverer: Optional[Discoverer] = discoverer
        elif self.trace_service_name:
            self.trace_discoverer = ConsulDiscoverer()
        else:
            self.trace_discoverer = None  # static trace_address, if any
            if config.trace_address:
                self.trace_ring.set_members([config.trace_address])
        # proxy hop visibility (the fleet trace plane, obs/tracectx.py):
        # every trace-bearing fan-out publishes a stage entry — one per
        # inbound batch, bounded ring — served at the proxy's own
        # GET /debug/flush-timeline so /debug/trace can stitch the
        # proxy hop between the local's flush and the global's import
        from veneur_tpu.obs import FlushTimeline

        self.obs_timeline = FlushTimeline(64)
        self._stop = threading.Event()
        self._httpd: Optional[ThreadingHTTPServer] = None
        # gRPC listener (proxysrv.Server flavor), started when
        # grpc_forward_address is configured; its ring follows the same
        # discovery refresh as the HTTP ring (proxysrv/server.go:147-177)
        self.grpc_server = None
        self._last_destinations: List[str] = []
        self._threads: List[threading.Thread] = []
        # telemetry
        self.proxied = 0
        self.traces_proxied = 0
        self.forward_errors = 0
        self.forward_retries = 0
        self.breaker_rejections = 0
        self.refresh_failures = 0
        self.refresh_retries = 0
        self._lock = threading.Lock()

    # -- discovery ----------------------------------------------------------

    def refresh_destinations(self):
        """Re-resolve membership for every configured ring
        (proxy.go:239-267)."""
        self._refresh_ring(self.discoverer, self.service_name, self.ring)
        if (self.accepting_traces and self.trace_service_name
                and self.trace_discoverer is not None):
            self._refresh_ring(self.trace_discoverer,
                               self.trace_service_name, self.trace_ring)

    def _refresh_ring(self, discoverer: Discoverer, service_name: str,
                      ring: ConsistentRing):
        """Re-resolve one ring's membership; a failure or empty result
        keeps the previous ring (proxy.go:337-371). A flaky discovery
        backend gets the shared retry/backoff (RetryingDiscoverer,
        bounded by the refresh interval) before we fall back to the
        last good ring."""

        def on_retry(retry_index, exc, pause):
            with self._lock:
                self.refresh_retries += 1

        retrying = RetryingDiscoverer(discoverer, self.retry_policy,
                                      budget=self.refresh_interval,
                                      on_retry=on_retry)
        try:
            destinations = retrying.get_destinations_for_service(
                service_name)
        except Exception as e:
            with self._lock:
                self.refresh_failures += 1
            log.warning("destination refresh failed, keeping %d known: %s",
                        len(ring), e)
            return
        if not destinations:
            with self._lock:
                self.refresh_failures += 1
            log.warning("discovery returned zero destinations, keeping %d",
                        len(ring))
            return
        ring.set_members(destinations)
        # breakers for departed destinations die with the membership
        # (bounds the registry under weeks of pod churn); both rings'
        # members stay retained
        self.breakers.retain(set(self.ring.members())
                             | set(self.trace_ring.members()))
        if ring is self.ring:
            self._last_destinations = list(destinations)
            if self.grpc_server is not None:
                # the gRPC flavor shares the metrics ring's membership
                self.grpc_server.set_destinations(destinations)

    def _refresh_loop(self):
        while not self._stop.wait(self.refresh_interval):
            self.refresh_destinations()

    # -- proxying -----------------------------------------------------------

    def proxy_metrics(self, metrics: List[dict], trace_header=None):
        """Hash each metric to its destination, batch, POST in parallel
        (proxy.go:437-505)."""
        self._fan_out(metrics, self.ring, metric_ring_key, "/import",
                      compress=True, counter="proxied", what="metrics",
                      trace_header=trace_header)

    def proxy_traces(self, traces: List[dict]):
        """Partition Datadog trace spans by trace id over the trace ring
        and POST each batch to ``{dest}/spans``; the /spans endpoint takes
        an array but not deflate (proxy.go:393-434)."""
        self._fan_out(traces, self.trace_ring,
                      lambda t: str(int(t["trace_id"])), "/spans",
                      compress=False, counter="traces_proxied",
                      what="trace spans")

    def _fan_out(self, items: List[dict], ring: ConsistentRing, key_fn,
                 path: str, compress: bool, counter: str, what: str,
                 trace_header=None):
        """The shared partition → parallel-POST machinery behind both
        fan-outs. The whole batch resolves through ONE ``get_many``
        call — one ring version — so a discovery refresh swapping the
        membership mid-batch can never split one batch's keys across
        the old and the new ring (the double-count window the
        ring-transition handoff closes; the swap itself is atomic in
        ``ConsistentRing.set_members``).

        A trace-bearing batch (``X-Veneur-Trace`` on the inbound POST)
        runs under a StageRecorder: the fan-out publishes a
        ``proxy.fan_out`` hop entry into the proxy's timeline ring,
        and every destination POST carries the context RE-PARENTED
        under this hop's span."""
        from veneur_tpu import obs
        from veneur_tpu.obs import tracectx

        ctx = tracectx.TraceContext.decode(trace_header) \
            if trace_header else None
        rec = None
        fwd_headers = None
        if ctx is not None:
            rec = obs.StageRecorder()
            rec.adopt_trace(ctx.trace_id, parent_id=ctx.parent_id,
                            hop="proxy.fan_out")
            fwd_headers = {tracectx.HEADER:
                           ctx.child(rec.span_id).encode()}
        by_dest: Dict[str, List[dict]] = defaultdict(list)
        dropped = 0
        keyed: List[tuple] = []
        for d in items:
            try:
                keyed.append((key_fn(d), d))
            except (KeyError, TypeError, ValueError):
                dropped += 1
        try:
            owners = ring.get_many([k for k, _ in keyed])
        except EmptyRingError:
            dropped += len(keyed)
            owners = []
            keyed = []
        for owner, (_, d) in zip(owners, keyed):
            by_dest[owner].append(d)
        if dropped:
            log.warning("dropped %d unroutable %s", dropped, what)
        threads = []
        for dest, batch in by_dest.items():
            t = threading.Thread(
                target=self._post_batch,
                args=(dest, batch, path, compress, counter, what),
                kwargs={"headers": fwd_headers, "rec": rec},
                daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=self.forward_timeout + 1.0)
        if rec is not None:
            try:
                entry = rec.finish()
                entry["what"] = what
                entry["items"] = len(items)
                entry["destinations"] = len(by_dest)
                self.obs_timeline.publish(entry)
            except Exception:  # telemetry must never fail a fan-out
                log.exception("proxy hop publication failed")

    def _post_batch(self, dest: str, batch: List[dict], path: str,
                    compress: bool, counter: str, what: str,
                    headers=None, rec=None):
        import time as _time

        t0_ns = _time.monotonic_ns() if rec is not None else 0
        try:
            self._post_batch_inner(dest, batch, path, compress, counter,
                                   what, headers)
        finally:
            if rec is not None:
                # each destination's POST is a child stage of the
                # fan-out hop, recorded from its own thread
                rec.record_abs(f"post.{dest}", t0_ns,
                               _time.monotonic_ns(), items=len(batch))

    def _post_batch_inner(self, dest: str, batch: List[dict], path: str,
                          compress: bool, counter: str, what: str,
                          headers=None):
        url = dest.rstrip("/")
        if not url.startswith(("http://", "https://")):
            url = "http://" + url
        # per-destination breaker: a black-holed global is rejected
        # instantly (its share of the interval is lost either way; the
        # healthy destinations' POSTs are not held hostage) and probed
        # again after the reset timeout. Ring membership is untouched —
        # keep-last-good-ring semantics stay with discovery.
        breaker = self.breakers.get(dest)
        if not breaker.allow():
            with self._lock:
                self.forward_errors += 1
                self.breaker_rejections += 1
            log.debug("skipping %d %s to %s: circuit breaker open",
                      len(batch), what, dest)
            return

        def on_retry(retry_index, exc, pause):
            with self._lock:
                self.forward_retries += 1

        deadline = Deadline.after(self.forward_timeout)
        try:
            status = post_with_retry(
                lambda: self._post(url + path, batch, compress=compress,
                                   timeout=deadline.clamp(
                                       self.forward_timeout),
                                   headers=headers),
                self.retry_policy, deadline=deadline, on_retry=on_retry)
        except Exception as e:
            breaker.record_failure()
            with self._lock:
                self.forward_errors += 1
            log.warning("failed to proxy %d %s to %s: %s",
                        len(batch), what, dest, e)
            return
        if 200 <= status < 300:
            breaker.record_success()
            with self._lock:
                setattr(self, counter, getattr(self, counter) + len(batch))
            return
        # a 4xx still proves the destination is alive; only transient
        # statuses (5xx/429) count toward tripping its breaker
        if is_transient_status(status):
            breaker.record_failure()
        else:
            breaker.record_success()
        with self._lock:
            self.forward_errors += 1
        log.warning("failed to proxy %d %s to %s: destination returned "
                    "HTTP %d", len(batch), what, dest, status)

    # -- lifecycle ----------------------------------------------------------

    @property
    def port(self) -> int:
        return self._httpd.server_address[1] if self._httpd else 0

    def start(self):
        """Initial refresh (fatal on empty), refresh loop, HTTP listener
        (proxy.go:206-287)."""
        self.refresh_destinations()
        if len(self.ring) == 0:
            raise RuntimeError(
                "refusing to start with zero destinations (proxy.go:232-243)")
        if (self.accepting_traces and self.trace_service_name
                and len(self.trace_ring) == 0):
            raise RuntimeError("refusing to start with zero trace "
                               "destinations (proxy.go:239-243)")
        needs_refresh = (
            not isinstance(self.discoverer, StaticDiscoverer)
            or (self.trace_discoverer is not None
                and not isinstance(self.trace_discoverer, StaticDiscoverer)))
        if needs_refresh:
            t = threading.Thread(target=self._refresh_loop,
                                 name="proxy-refresh", daemon=True)
            t.start()
            self._threads.append(t)
        host, _, port = (self.config.http_address or "0.0.0.0:8127"
                         ).rpartition(":")
        self._httpd = ReuseportHTTPServer((host or "0.0.0.0", int(port)),
                                          _ProxyHandler)
        self._httpd.daemon_threads = True
        self._httpd.veneur_proxy = self
        self._httpd.veneur_get_routes = {}
        # live debug endpoints on the proxy mux too (the reference
        # mounts pprof on it, proxy.go:383-388)
        from veneur_tpu import debug

        def ring_vars():
            return {"ring": {
                "destinations": len(self.ring),
                "version": self.ring.version,
                "trace_destinations": len(self.trace_ring),
                "proxied": self.proxied,
                "traces_proxied": self.traces_proxied,
                "forward_errors": self.forward_errors,
                "forward_retries": self.forward_retries,
                "breaker_rejections": self.breaker_rejections,
                "refresh_failures": self.refresh_failures,
                "refresh_retries": self.refresh_retries,
            }, "breakers": dict(self.breakers.states())}

        debug.mount(
            lambda path, fn: self._httpd.veneur_get_routes.__setitem__(
                path, fn),
            extra_vars=ring_vars)
        # the proxy-hop timeline (trace-bearing fan-outs) on the same
        # path the server uses, so the fleet aggregator pulls peers
        # uniformly
        self._httpd.veneur_get_routes["/debug/flush-timeline"] = \
            self.obs_timeline.handler
        t = threading.Thread(target=self._httpd.serve_forever,
                             name="proxy-http", daemon=True)
        t.start()
        self._threads.append(t)
        if self.config.grpc_forward_address:
            # gRPC flavor on its own listener, same membership + the
            # same destForMetric key as /import (proxy/grpc_proxy.py)
            from veneur_tpu.proxy.grpc_proxy import GRPCProxyServer

            self.grpc_server = GRPCProxyServer(
                destinations=self._last_destinations,
                forward_timeout=self.forward_timeout)
            self.grpc_server.start(self.config.grpc_forward_address)
        log.info("veneur-proxy listening on port %d with %d destinations",
                 self.port, len(self.ring))

    def shutdown(self):
        self._stop.set()
        if self.grpc_server is not None:
            self.grpc_server.stop()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()

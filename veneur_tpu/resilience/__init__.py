"""Unified egress resilience: retries, circuit breakers, deadline
propagation, and deterministic fault injection.

Every egress path — HTTP/gRPC/native forwarders, the proxy's ring
fan-out, the Datadog/SignalFx/Kafka/LightStep sinks, discovery refresh
— shares this substrate instead of hand-rolling its own failure
handling. See ``docs/resilience.md`` for the model.
"""

from veneur_tpu.resilience.breaker import (BreakerOpen, BreakerRegistry,
                                           CircuitBreaker)
from veneur_tpu.resilience.compute import ComputeBreaker
from veneur_tpu.resilience.compute import \
    from_config as compute_from_config
from veneur_tpu.resilience.deadline import Deadline, DeadlineExceeded
from veneur_tpu.resilience.faults import FaultInjector
from veneur_tpu.resilience.faults import from_config as faults_from_config
from veneur_tpu.resilience.retry import (RetryPolicy, TransientStatusError,
                                         call_with_retry, is_transient_status,
                                         post_with_retry)

__all__ = [
    "BreakerOpen",
    "BreakerRegistry",
    "CircuitBreaker",
    "ComputeBreaker",
    "compute_from_config",
    "Deadline",
    "DeadlineExceeded",
    "FaultInjector",
    "RetryPolicy",
    "TransientStatusError",
    "call_with_retry",
    "faults_from_config",
    "is_transient_status",
    "post_with_retry",
]

"""Per-destination circuit breakers.

Classic closed → open → half-open automaton: ``failure_threshold``
consecutive failures trip the breaker; while open every ``allow()`` is
rejected instantly (a black-holed destination costs nothing per flush
instead of a full timeout); after ``reset_timeout`` the breaker admits
``half_open_max`` probe requests — one success closes it, one failure
re-opens it and restarts the timer. State is exported as a gauge
(0=closed, 1=half-open, 2=open) through the flusher's self-metric path
and the proxy's ``/debug/vars``.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, List, Tuple

from veneur_tpu.core.locking import requires_lock

log = logging.getLogger("veneur.resilience.breaker")

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

_STATE_GAUGE = {CLOSED: 0.0, HALF_OPEN: 1.0, OPEN: 2.0}


class BreakerOpen(Exception):
    """The destination's breaker is open; the request was not attempted."""

    def __init__(self, name: str):
        super().__init__(f"circuit breaker open for {name or 'destination'}")
        self.destination = name


class CircuitBreaker:
    """One destination's failure automaton. Thread-safe; egress paths
    share a breaker across per-flush threads."""

    def __init__(self, failure_threshold: int = 5,
                 reset_timeout: float = 30.0, half_open_max: int = 1,
                 clock: Callable[[], float] = time.monotonic,
                 name: str = ""):
        self.failure_threshold = max(1, failure_threshold)
        self.reset_timeout = reset_timeout
        self.half_open_max = max(1, half_open_max)
        self.name = name
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probes = 0
        # lifetime counters for /debug/vars and tests
        self.rejections = 0
        self.trips = 0

    # -- state ---------------------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open()
            return self._state

    def state_gauge(self) -> float:
        """0=closed, 1=half-open, 2=open (veneur.breaker.state)."""
        return _STATE_GAUGE[self.state]

    @requires_lock("breaker")
    def _maybe_half_open(self) -> None:
        if (self._state == OPEN
                and self._clock() - self._opened_at >= self.reset_timeout):
            self._state = HALF_OPEN
            self._probes = 0

    @requires_lock("breaker")
    def _trip(self) -> None:
        self._state = OPEN
        self._opened_at = self._clock()
        self._probes = 0
        self.trips += 1
        log.warning("circuit breaker for %s opened after %d consecutive "
                    "failures", self.name or "destination", self._failures)

    # -- protocol ------------------------------------------------------------

    def allow(self) -> bool:
        """May a request go out right now? Counts half-open probes."""
        with self._lock:
            self._maybe_half_open()
            if self._state == CLOSED:
                return True
            if self._state == HALF_OPEN and self._probes < self.half_open_max:
                self._probes += 1
                return True
            self.rejections += 1
            return False

    def blocked(self) -> bool:
        """True iff the breaker is OPEN (not ready for a probe) —
        unlike ``allow`` this never consumes a half-open probe, so
        egress paths can reject BEFORE paying serialization cost
        without leaking the probe budget when they end up sending
        nothing. Counted as a rejection when True."""
        with self._lock:
            self._maybe_half_open()
            if self._state == OPEN:
                self.rejections += 1
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            if self._state != CLOSED:
                log.info("circuit breaker for %s closed",
                         self.name or "destination")
            self._state = CLOSED

    def record_failure(self) -> None:
        with self._lock:
            self._maybe_half_open()
            if self._state == HALF_OPEN:
                # a failed probe re-opens and restarts the reset timer
                self._trip()
                return
            self._failures += 1
            if self._state == CLOSED and \
                    self._failures >= self.failure_threshold:
                self._trip()

    def call(self, fn: Callable):
        """Run ``fn`` under the breaker: rejected with ``BreakerOpen``
        while open; outcome recorded otherwise."""
        if not self.allow():
            raise BreakerOpen(self.name)
        try:
            result = fn()
        except Exception:
            self.record_failure()
            raise
        self.record_success()
        return result


class BreakerRegistry:
    """Per-destination breakers created on demand — the proxy's ring
    fan-out keys this by destination URL, so ring membership changes
    (keep-last-good-ring semantics untouched) just stop consulting a
    departed destination's breaker."""

    def __init__(self, failure_threshold: int = 5,
                 reset_timeout: float = 30.0, half_open_max: int = 1,
                 clock: Callable[[], float] = time.monotonic):
        self.failure_threshold = failure_threshold
        self.reset_timeout = reset_timeout
        self.half_open_max = half_open_max
        self._clock = clock
        self._lock = threading.Lock()
        self._breakers: Dict[str, CircuitBreaker] = {}

    def get(self, name: str) -> CircuitBreaker:
        with self._lock:
            b = self._breakers.get(name)
            if b is None:
                b = CircuitBreaker(
                    failure_threshold=self.failure_threshold,
                    reset_timeout=self.reset_timeout,
                    half_open_max=self.half_open_max,
                    clock=self._clock, name=name)
                self._breakers[name] = b
            return b

    def states(self) -> List[Tuple[str, float]]:
        """Snapshot of (destination, state gauge) for telemetry."""
        with self._lock:
            breakers = list(self._breakers.items())
        return [(name, b.state_gauge()) for name, b in breakers]

    def retain(self, names) -> None:
        """Drop breakers for destinations no longer in ``names`` — the
        proxy calls this on every discovery refresh so weeks of ring
        churn (rescheduled pods, rotated IPs) cannot grow the registry
        without bound."""
        keep = set(names)
        with self._lock:
            for name in list(self._breakers):
                if name not in keep:
                    del self._breakers[name]

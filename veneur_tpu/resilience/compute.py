"""Per-kernel compute circuit breakers: the flush-kernel fallback ladder.

The egress breakers (breaker.py) protect the network edge; this wraps the
OTHER failure-prone edge, the batched XLA/Pallas device programs. A
runtime failure of the fused t-digest merge kernel (TPU preemption, a
Mosaic compile error after a config change, a driver wedge) must degrade
the flush, not lose the interval:

    rung 1  Pallas-fused program       (breaker closed, or half-open probe)
    rung 2  interpret/jnp program      (same math, XLA-only; ``use_pallas``
                                        statics retrace without the kernel)
    rung 3  re-merge the generation    (MetricStore re-imports the retired
            into the live store        group's snapshot — the interval
                                        emits LATE next flush, never lost;
                                        PR 2's checkpoint then persists it
                                        on its normal cadence)

``failure_threshold`` consecutive rung-1 failures open the kernel's
breaker: subsequent flushes (and the staging drains, which share the
kernel) go straight to the jnp path without paying a doomed dispatch.
After ``reset_timeout`` one flush probes the kernel again; success closes
the breaker. State rides ``veneur.breaker.state`` tagged with the kernel
name, next to the egress destinations.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, List, Optional, Tuple

from veneur_tpu.resilience.breaker import CLOSED, BreakerRegistry

log = logging.getLogger("veneur.resilience.compute")

# today's only governed kernel: the fused t-digest merge/quantile
# (ops/tdigest_pallas.py) every digest drain and flush dispatches
KERNEL_TDIGEST = "compute.tdigest_merge"

DEFAULT_FAILURE_THRESHOLD = 2
DEFAULT_RESET_TIMEOUT = 60.0


class ComputeBreaker:
    """Thread-safe per-kernel breaker bundle + degradation tallies."""

    def __init__(self, failure_threshold: int = DEFAULT_FAILURE_THRESHOLD,
                 reset_timeout: float = DEFAULT_RESET_TIMEOUT,
                 clock: Callable[[], float] = time.monotonic):
        self._registry = BreakerRegistry(
            failure_threshold=max(1, failure_threshold),
            reset_timeout=reset_timeout, half_open_max=1, clock=clock)
        self._lock = threading.Lock()
        # deterministic fault hook: when set, ``preflight`` consults it
        # before every rung-1 dispatch (resilience/faults.py semantics)
        self.injector = None
        self.fallback_total = 0   # group flushes completed on the jnp rung
        self.requeued_total = 0   # rung 3: generations re-merged, late
        self.lost_total = 0       # every rung failed; checkpoint bounds it

    def probe(self, kernel: str = KERNEL_TDIGEST) -> bool:
        """May this flush attempt the Pallas rung right now? Consumes the
        half-open probe budget, so only the flush path calls it."""
        return self._registry.get(kernel).allow()

    def degraded(self, kernel: str = KERNEL_TDIGEST) -> bool:
        """Cheap read for non-probing callers (the staging drains): stay
        on the jnp path while the kernel's breaker is not closed."""
        return self._registry.get(kernel).state != CLOSED

    def preflight(self, kernel: str = KERNEL_TDIGEST) -> None:
        """Raise the scheduled injected fault, if an injector is armed —
        BEFORE dispatch, so donated device buffers survive for rung 2.
        Machine-checked: the donation-safety pass (lint/deviceflow.py
        PREFLIGHT_CONTRACT) flags any registered compute ladder that
        dispatches before calling this."""
        inj = self.injector
        if inj is not None:
            inj.maybe_fail(kernel)

    def record_success(self, kernel: str = KERNEL_TDIGEST) -> None:
        self._registry.get(kernel).record_success()

    def record_failure(self, kernel: str = KERNEL_TDIGEST) -> None:
        self._registry.get(kernel).record_failure()

    def count_fallback(self, n: int = 1) -> None:
        with self._lock:
            self.fallback_total += n

    def count_requeued(self, n: int = 1) -> None:
        with self._lock:
            self.requeued_total += n

    def count_lost(self, n: int = 1) -> None:
        with self._lock:
            self.lost_total += n

    def states(self) -> List[Tuple[str, float]]:
        """(kernel, state gauge) pairs for telemetry; empty until a
        kernel has been consulted once."""
        return self._registry.states()

    def snapshot(self) -> dict:
        return {"kernels": {name: gauge for name, gauge in self.states()},
                "fallback_total": self.fallback_total,
                "requeued_total": self.requeued_total,
                "lost_total": self.lost_total}


def from_config(cfg, clock: Callable[[], float] = time.monotonic
                ) -> Optional["ComputeBreaker"]:
    """Build the configured compute breaker (always on; the knobs only
    tune it — a flush kernel without a fallback ladder is the round-4
    audit's definition of failing open)."""
    return ComputeBreaker(
        failure_threshold=int(getattr(
            cfg, "compute_breaker_failure_threshold", 0)
            or DEFAULT_FAILURE_THRESHOLD),
        reset_timeout=float(getattr(
            cfg, "compute_breaker_reset_timeout_seconds", 0.0)
            or DEFAULT_RESET_TIMEOUT),
        clock=clock)

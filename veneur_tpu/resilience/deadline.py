"""Flush-interval deadline propagation.

A ``Deadline`` is a point in monotonic time created once per flush
(``flusher._flush_once``) and threaded through forwarders and sinks so
that *no* retry loop can push a flush past the interval boundary: every
backoff sleep is clamped to ``remaining()`` and every per-attempt socket
timeout is clamped with ``clamp()``. The clock is injectable so backoff
and expiry tests run in milliseconds against the fake clock shim in
``tests/conftest.py``.
"""

from __future__ import annotations

import time
from typing import Callable, Optional


class DeadlineExceeded(Exception):
    """The flush budget ran out before the operation completed."""


# a socket timeout of exactly 0 means non-blocking (instant failure with
# a confusing error); an expired deadline clamps to this floor instead
# so the failure surfaces as an ordinary timeout
_MIN_TIMEOUT = 1e-3


class Deadline:
    """An absolute point in (monotonic) time a flush must not cross."""

    __slots__ = ("_at", "_clock")

    def __init__(self, at: Optional[float],
                 clock: Callable[[], float] = time.monotonic):
        self._at = at
        self._clock = clock

    @classmethod
    def after(cls, seconds: float,
              clock: Callable[[], float] = time.monotonic) -> "Deadline":
        return cls(clock() + seconds, clock)

    @classmethod
    def unbounded(cls) -> "Deadline":
        """No budget: ``remaining()`` is infinite, ``expired()`` never."""
        return cls(None)

    def remaining(self) -> float:
        if self._at is None:
            return float("inf")
        return max(0.0, self._at - self._clock())

    def expired(self) -> bool:
        return self.remaining() <= 0.0

    def clamp(self, timeout: float) -> float:
        """A per-attempt timeout that cannot outlive the deadline."""
        return max(_MIN_TIMEOUT, min(timeout, self.remaining()))

    def check(self) -> None:
        if self.expired():
            raise DeadlineExceeded("flush deadline exceeded")

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        if self._at is None:
            return "Deadline(unbounded)"
        return f"Deadline(remaining={self.remaining():.3f}s)"

"""Deterministic, seeded fault injection for egress transports.

Wraps any transport callable to inject connection errors, timeouts, 5xx
responses, and partial writes at a configured rate. The schedule is a
pure function of ``(seed, call index)`` — two runs with the same seed
see the same faults at the same calls, whatever the pass/fail pattern
in between — so soak runs and the ``tests/test_resilience.py`` suite
reproduce exactly.

Enabled via config (``fault_injection_rate`` > 0 on Config/ProxyConfig,
plus ``fault_injection_seed`` / ``_kinds`` / ``_scope``) or the matching
``VENEUR_FAULT_INJECTION_*`` env overrides the config loader already
applies. ``scope`` substring-filters the operation names egress paths
pass (``forward.http``, ``sink.datadog``, ``proxy.post``, ...), so a
soak can target one path at a time.
"""

from __future__ import annotations

import logging
import random
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

log = logging.getLogger("veneur.resilience.faults")

KIND_CONNECT = "connect"
KIND_TIMEOUT = "timeout"
KIND_HTTP_5XX = "http_5xx"
KIND_PARTIAL_WRITE = "partial_write"
ALL_KINDS = (KIND_CONNECT, KIND_TIMEOUT, KIND_HTTP_5XX, KIND_PARTIAL_WRITE)
# ingest-side faults (mangle_packet): a datagram cut mid-line, and one
# datagram amplified into a burst — the two shapes a hostile/overloaded
# UDP path actually produces. Deterministic like the transport kinds,
# but a SEPARATE vocabulary: adding them to ALL_KINDS would perturb the
# seeded schedules every existing transport soak reproduces.
KIND_TRUNCATE = "truncate"
KIND_BURST = "burst"
INGEST_KINDS = (KIND_TRUNCATE, KIND_BURST)
BURST_MAX_COPIES = 8
# membership-churn faults (mangle_members): discovery reports a member
# that does not exist, loses a member that does, or a member stays in
# the ring while the network to it is dead — the three shapes a fleet
# resize under failure actually produces. Like the ingest kinds these
# stay OUT of ALL_KINDS so the seeded schedules every existing
# transport soak reproduces are untouched.
KIND_MEMBER_ADD = "member_add"
KIND_MEMBER_REMOVE = "member_remove"
KIND_PARTITION = "partition"
CHURN_KINDS = (KIND_MEMBER_ADD, KIND_MEMBER_REMOVE, KIND_PARTITION)
# how many refresh intervals (mangle_members calls) a partition
# black-holes its destination before healing
PARTITION_INTERVALS = 3
# soak-plane faults (veneur_tpu/soak/): the two host-resource failures
# the egress/churn kinds cannot express — the checkpoint/spool disk
# filling up (wrap_write raises ENOSPC) and an interval whose egress
# deadline collapses (scale_deadline shrinks the flush budget, forcing
# the retry ladder to give up and the requeue paths to absorb the
# interval). A SEPARATE vocabulary, same reason as INGEST/CHURN: the
# seeded schedules existing soaks reproduce must not shift.
KIND_DISK_FULL = "disk_full"
KIND_DEADLINE_PRESSURE = "deadline_pressure"
SOAK_KINDS = (KIND_DISK_FULL, KIND_DEADLINE_PRESSURE)
# an interval under deadline_pressure keeps this fraction of its
# egress budget — small enough that any real POST's retry backoff
# blows it, large enough that the flush path itself completes
DEADLINE_PRESSURE_FACTOR = 0.05

# the status wrap_post returns for an injected 5xx
INJECTED_STATUS = 503


class InjectedFault(Exception):
    """Marker mixin so logs can distinguish injected from real faults."""


class InjectedConnectError(InjectedFault, ConnectionRefusedError):
    pass


class InjectedTimeout(InjectedFault, TimeoutError):
    pass


class InjectedPartialWrite(InjectedFault, BrokenPipeError):
    pass


class FaultInjector:
    """A seeded fault schedule over a stream of transport operations."""

    def __init__(self, rate: float, seed: int = 0,
                 kinds: Sequence[str] = ALL_KINDS, scope: str = ""):
        if not 0.0 <= rate <= 1.0:
            raise ValueError(f"fault rate must be in [0, 1], got {rate}")
        known = ALL_KINDS + INGEST_KINDS + CHURN_KINDS + SOAK_KINDS
        bad = [k for k in kinds if k not in known]
        if bad:
            raise ValueError(f"unknown fault kinds {bad}; known: "
                             f"{list(known)}")
        self.rate = rate
        self.seed = seed
        self.kinds = tuple(kinds) or ALL_KINDS
        self.scope = scope
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.calls = 0
        self.injected: Dict[str, int] = {k: 0 for k in self.kinds}
        # live partitions: destination -> refresh intervals left before
        # the network to it heals (KIND_PARTITION)
        self._partitions: Dict[str, int] = {}

    def should_fail(self, op: str) -> Optional[str]:
        """The kind to inject for this call, or None. Exactly two rng
        draws per in-scope call, fail or not, so the schedule depends
        only on the seed and the call index."""
        if self.scope and self.scope not in op:
            return None
        with self._lock:
            self.calls += 1
            roll = self._rng.random()
            kind = self.kinds[self._rng.randrange(len(self.kinds))]
            if roll >= self.rate:
                return None
            self.injected[kind] += 1
        log.debug("injecting %s fault into %s (call %d)",
                  kind, op, self.calls)
        return kind

    def maybe_fail(self, op: str) -> None:
        """Raise the scheduled fault, if any — the hook for socket-level
        transports, where an injected 5xx surfaces as the peer's NAK
        (an OSError) like a real one would. Ingest kinds pass through
        untouched (like wrap_post): a mixed-kind injector shared with
        egress hooks must not turn a scheduled packet mangle into a
        transport error the operator never configured."""
        kind = self.should_fail(op)
        if kind is None or kind in INGEST_KINDS or kind in CHURN_KINDS \
                or kind in SOAK_KINDS:
            return
        if kind == KIND_CONNECT:
            raise InjectedConnectError(f"injected connect error ({op})")
        if kind == KIND_TIMEOUT:
            raise InjectedTimeout(f"injected timeout ({op})")
        if kind == KIND_PARTIAL_WRITE:
            raise InjectedPartialWrite(f"injected partial write ({op})")
        raise OSError(f"injected upstream 5xx ({op})")

    def wrap_post(self, post: Callable[..., int], op: str) -> Callable[..., int]:
        """Wrap a post-style callable returning an HTTP status: an
        injected 5xx returns ``INJECTED_STATUS`` without touching the
        real transport; connect/timeout/partial-write raise before it."""

        def wrapped(*args, **kwargs) -> int:
            kind = self.should_fail(op)
            if kind == KIND_HTTP_5XX:
                return INJECTED_STATUS
            if kind == KIND_CONNECT:
                raise InjectedConnectError(f"injected connect error ({op})")
            if kind == KIND_TIMEOUT:
                raise InjectedTimeout(f"injected timeout ({op})")
            if kind == KIND_PARTIAL_WRITE:
                raise InjectedPartialWrite(f"injected partial write ({op})")
            return post(*args, **kwargs)

        return wrapped

    def mangle_packet(self, op: str, data: bytes) -> List[bytes]:
        """Apply the scheduled INGEST fault to one datagram, returning
        the datagram(s) the pipeline should actually see:

        * no fault → ``[data]`` untouched;
        * ``truncate`` → the datagram cut at a seeded offset (mid-line,
          the OS-truncation shape the parser must survive);
        * ``burst`` → 2..BURST_MAX_COPIES copies (amplification — the
          admission/overflow paths must absorb it, not OOM).

        Non-ingest scheduled kinds pass the packet through untouched so
        a mixed-kind injector can drive transport and ingest faults off
        one seed. One extra seeded draw per applied fault (the cut
        point / copy count), taken under the same lock so schedules
        stay reproducible across thread interleavings."""
        kind = self.should_fail(op)
        if kind == KIND_TRUNCATE and len(data) > 1:
            with self._lock:
                cut = self._rng.randrange(1, len(data))
            return [data[:cut]]
        if kind == KIND_BURST:
            with self._lock:
                copies = self._rng.randrange(2, BURST_MAX_COPIES + 1)
            return [data] * copies
        return [data]

    def mangle_members(self, op: str, members: List[str]) -> List[str]:
        """Apply the scheduled CHURN fault to one discovery refresh
        result, returning the membership the ring consumer should see:

        * no fault → ``members`` untouched;
        * ``member_add`` → one synthetic (black-hole) member appended —
          handoffs routed to it must ride the breaker/requeue ladder;
        * ``member_remove`` → a seeded member dropped (never the last
          one: churn must not empty the fleet and trip the
          keep-last-good path every refresh);
        * ``partition`` → membership untouched, but a seeded member is
          black-holed for ``PARTITION_INTERVALS`` refreshes —
          ``is_partitioned`` answers the transport hook.

        One call = one refresh interval: live partitions tick down here,
        so the heal schedule is as reproducible as the fault schedule.
        Non-churn scheduled kinds pass through untouched (one injector
        can drive transport, ingest and churn faults off one seed)."""
        with self._lock:
            for dest in list(self._partitions):
                self._partitions[dest] -= 1
                if self._partitions[dest] <= 0:
                    del self._partitions[dest]
        kind = self.should_fail(op)
        if kind == KIND_MEMBER_ADD:
            with self._lock:
                idx = self._rng.randrange(1 << 16)
            return list(members) + [f"fault://injected-{idx}"]
        if kind == KIND_MEMBER_REMOVE and len(members) > 1:
            with self._lock:
                idx = self._rng.randrange(len(members))
            return [m for i, m in enumerate(members) if i != idx]
        if kind == KIND_PARTITION and members:
            with self._lock:
                idx = self._rng.randrange(len(members))
                self._partitions[members[idx]] = PARTITION_INTERVALS
        return list(members)

    def wrap_write(self, write: Callable[..., int], op: str) -> Callable[..., int]:
        """Wrap a ``write_atomic``-style callable (persist/format.py):
        a scheduled ``disk_full`` raises ENOSPC before any bytes touch
        the real filesystem — the injected twin of the volume filling
        up mid-commit. Non-disk scheduled kinds pass through untouched
        so one injector can drive transport and disk faults off one
        seed."""
        import errno

        def wrapped(*args, **kwargs) -> int:
            if self.should_fail(op) == KIND_DISK_FULL:
                raise OSError(errno.ENOSPC,
                              f"injected disk full ({op})")
            return write(*args, **kwargs)

        return wrapped

    def scale_deadline(self, op: str, budget: float) -> float:
        """Apply a scheduled ``deadline_pressure`` fault to one
        interval's egress budget: the returned budget is the configured
        one, or ``DEADLINE_PRESSURE_FACTOR`` of it when the fault fires
        — the injected twin of a slow-device interval eating the flush
        window. One call per interval keeps the schedule aligned with
        the flush cadence."""
        if self.should_fail(op) == KIND_DEADLINE_PRESSURE:
            log.warning("deadline pressure injected: flush budget "
                        "%.2fs -> %.2fs (%s)", budget,
                        budget * DEADLINE_PRESSURE_FACTOR, op)
            return budget * DEADLINE_PRESSURE_FACTOR
        return budget

    def is_partitioned(self, dest: str) -> bool:
        """Whether a scheduled ``partition`` fault currently black-holes
        ``dest`` — transports consult this before the send and raise
        their connect error as if the peer were unreachable."""
        with self._lock:
            return dest in self._partitions

    def schedule(self, n: int) -> Tuple[Optional[str], ...]:
        """The next ``n`` outcomes, consumed — test/debug helper for
        asserting seeded determinism."""
        return tuple(self.should_fail("schedule") for _ in range(n))


def from_config(cfg) -> Optional[FaultInjector]:
    """Build the configured injector, or None when fault injection is
    off (the default; rate 0 means every transport runs clean)."""
    rate = float(getattr(cfg, "fault_injection_rate", 0.0) or 0.0)
    if rate <= 0.0:
        return None
    kinds_csv = getattr(cfg, "fault_injection_kinds", "") or ""
    kinds = tuple(k.strip() for k in kinds_csv.split(",") if k.strip()) \
        or ALL_KINDS
    injector = FaultInjector(
        rate=rate,
        seed=int(getattr(cfg, "fault_injection_seed", 0) or 0),
        kinds=kinds,
        scope=getattr(cfg, "fault_injection_scope", "") or "")
    log.warning("fault injection ACTIVE: rate=%.2f seed=%d kinds=%s "
                "scope=%r — this instance will deliberately fail egress",
                injector.rate, injector.seed, ",".join(injector.kinds),
                injector.scope)
    return injector

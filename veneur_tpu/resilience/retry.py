"""Retry with exponential backoff and full jitter, bounded by a deadline.

The one retry loop every egress path shares (forwarders, proxy fan-out,
sinks, discovery refresh) instead of the hand-rolled per-path variants
the round-1 audit flagged: attempt, sleep ``uniform(0, min(cap, base *
2**n))``, re-attempt — never sleeping past the flush deadline and never
exceeding the attempt budget. Sleep/clock/rng are injectable so tests
run in milliseconds and fault schedules stay deterministic.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional, Tuple, Type

from veneur_tpu.resilience.deadline import Deadline

log = logging.getLogger("veneur.resilience.retry")

# module-level rng for jitter; callers needing determinism pass their own
_jitter_rng = random.Random()
_jitter_lock = threading.Lock()


class TransientStatusError(Exception):
    """An HTTP status worth retrying (5xx, 429) raised by an attempt
    closure so ``call_with_retry`` treats it like a transport error."""

    def __init__(self, status: int):
        super().__init__(f"transient HTTP status {status}")
        self.status = status


def is_transient_status(status: int) -> bool:
    return status == 429 or 500 <= status < 600


@dataclass(frozen=True)
class RetryPolicy:
    """Attempt budget + backoff shape. ``max_attempts`` counts the first
    try: 1 means no retries at all."""

    max_attempts: int = 3
    base_interval: float = 0.1
    max_interval: float = 10.0

    def backoff(self, retry_index: int, rng=None) -> float:
        """Full-jitter sleep before retry ``retry_index`` (0-based):
        uniform over [0, min(max_interval, base * 2**n)]."""
        cap = min(self.max_interval, self.base_interval * (2 ** retry_index))
        if rng is None:
            with _jitter_lock:
                return _jitter_rng.uniform(0.0, cap)
        return rng.uniform(0.0, cap)

    @classmethod
    def from_config(cls, cfg) -> "RetryPolicy":
        """Policy from the shared config knobs (retry_max is the number
        of RE-tries, matching the kafka_retry_max convention)."""
        retries = getattr(cfg, "retry_max", 2)
        if retries is None or retries < 0:  # unset sentinel
            retries = 2
        base = getattr(cfg, "retry_base_interval_seconds", 0.1) or 0.1
        return cls(max_attempts=retries + 1, base_interval=base)


def call_with_retry(fn: Callable, policy: RetryPolicy, *,
                    deadline: Optional[Deadline] = None,
                    retryable: Tuple[Type[BaseException], ...] = (OSError,),
                    retry_if: Optional[Callable[[BaseException], bool]] = None,
                    on_retry: Optional[Callable] = None,
                    rng=None, sleep: Callable[[float], None] = time.sleep):
    """Run ``fn`` with up to ``policy.max_attempts`` attempts.

    Retries only exceptions matching ``retryable`` (and ``retry_if``,
    when given); anything else propagates immediately. Backoff sleeps
    are clamped to ``deadline.remaining()`` and an expired deadline
    re-raises the last attempt's exception rather than attempting again
    — a flush must degrade, never overrun its interval. ``on_retry``
    (if given) is called as ``on_retry(retry_index, exc, pause)`` before
    each backoff sleep; egress components use it to count
    ``*.retries_total`` self-metrics.
    """
    attempts = max(1, policy.max_attempts)
    attempt = 0
    while True:
        try:
            return fn()
        except retryable as e:
            if retry_if is not None and not retry_if(e):
                raise
            attempt += 1
            if attempt >= attempts:
                raise
            if deadline is not None and deadline.expired():
                raise
            pause = policy.backoff(attempt - 1, rng)
            if deadline is not None:
                pause = min(pause, deadline.remaining())
            if on_retry is not None:
                on_retry(attempt - 1, e, pause)
            sleep(pause)
            if deadline is not None and deadline.expired():
                raise


def post_with_retry(call: Callable[[], int], policy: RetryPolicy, *,
                    deadline: Optional[Deadline] = None,
                    on_retry: Optional[Callable] = None,
                    rng=None,
                    sleep: Callable[[float], None] = time.sleep) -> int:
    """Retry an HTTP POST closure returning a status code.

    Transport errors (``OSError``, which covers ``urllib.error.URLError``)
    and transient statuses (5xx/429) retry; the final status — transient
    or not — is RETURNED so call sites keep their existing
    log-the-status error handling, while a final transport error still
    raises.
    """

    def attempt() -> int:
        status = call()
        if is_transient_status(status):
            raise TransientStatusError(status)
        return status

    try:
        return call_with_retry(
            attempt, policy, deadline=deadline,
            retryable=(OSError, TransientStatusError),
            on_retry=on_retry, rng=rng, sleep=sleep)
    except TransientStatusError as e:
        return e.status

"""Sampler layer: batched device-resident metric state + scalar references."""

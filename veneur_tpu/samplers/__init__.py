"""Sampler-layer types: parsing, metric keys, InterMetrics, golden models."""

from .intermetric import (
    AGGREGATES_LOOKUP,
    AGGREGATE_SUFFIX,
    Aggregate,
    HistogramAggregates,
    InterMetric,
    MetricType,
    route_info,
)
from .parser import (
    GLOBAL_ONLY,
    LOCAL_ONLY,
    MIXED_SCOPE,
    MetricKey,
    ParseError,
    UDPMetric,
    fnv1a_32,
    parse_event,
    parse_metric,
    parse_metric_ssf,
    parse_service_check,
    split_lines,
)
from .scalar import ScalarHLL, ScalarTDigest

__all__ = [
    "AGGREGATES_LOOKUP",
    "AGGREGATE_SUFFIX",
    "Aggregate",
    "HistogramAggregates",
    "InterMetric",
    "MetricType",
    "route_info",
    "GLOBAL_ONLY",
    "LOCAL_ONLY",
    "MIXED_SCOPE",
    "MetricKey",
    "ParseError",
    "UDPMetric",
    "fnv1a_32",
    "parse_event",
    "parse_metric",
    "parse_metric_ssf",
    "parse_service_check",
    "split_lines",
    "ScalarHLL",
    "ScalarTDigest",
]

"""Flushed-metric types: InterMetric, aggregate selection, sink routing.

Mirrors the flush-side types of ``/root/reference/samplers/samplers.go``:
``InterMetric`` (samplers.go:48-61), the histogram-aggregate bitmask
(samplers.go:63-98) and the ``veneursinkonly:`` routing tag
(samplers.go:110-127).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import FrozenSet, List, Optional


class MetricType(enum.Enum):
    COUNTER = "counter"
    GAUGE = "gauge"
    STATUS = "status"


class Aggregate(enum.IntFlag):
    """Bitmask of histogram aggregates (samplers.go:63-77)."""

    MIN = 1 << 0
    MAX = 1 << 1
    MEDIAN = 1 << 2
    AVERAGE = 1 << 3
    COUNT = 1 << 4
    SUM = 1 << 5
    HARMONIC_MEAN = 1 << 6


AGGREGATES_LOOKUP = {
    "min": Aggregate.MIN,
    "max": Aggregate.MAX,
    "median": Aggregate.MEDIAN,
    "avg": Aggregate.AVERAGE,
    "count": Aggregate.COUNT,
    "sum": Aggregate.SUM,
    "hmean": Aggregate.HARMONIC_MEAN,
}

AGGREGATE_SUFFIX = {
    Aggregate.MIN: "min",
    Aggregate.MAX: "max",
    Aggregate.MEDIAN: "median",
    Aggregate.AVERAGE: "avg",
    Aggregate.COUNT: "count",
    Aggregate.SUM: "sum",
    Aggregate.HARMONIC_MEAN: "hmean",
}


@dataclass(frozen=True)
class HistogramAggregates:
    """The selected aggregates plus their count (samplers.go:85-88)."""

    value: Aggregate = (Aggregate.MIN | Aggregate.MAX | Aggregate.COUNT)

    @property
    def count(self) -> int:
        return bin(int(self.value)).count("1")

    @classmethod
    def from_names(cls, names: List[str]) -> "HistogramAggregates":
        agg = Aggregate(0)
        for name in names:
            flag = AGGREGATES_LOOKUP.get(name)
            if flag is not None:
                agg |= flag
        return cls(value=agg)


SINK_PREFIX = "veneursinkonly:"


def route_info(tags: List[str]) -> Optional[FrozenSet[str]]:
    """Extract the set of sink names a metric is restricted to, or None when
    it goes to every sink (samplers.go:110-127)."""
    info = None
    for tag in tags:
        if tag.startswith(SINK_PREFIX):
            if info is None:
                info = set()
            info.add(tag[len(SINK_PREFIX):])
    return frozenset(info) if info is not None else None


@dataclass
class InterMetric:
    """A completed metric ready for sink flushing (samplers.go:48-61)."""

    name: str
    timestamp: int
    value: float
    tags: List[str] = field(default_factory=list)
    type: MetricType = MetricType.GAUGE
    message: str = ""
    hostname: str = ""
    sinks: Optional[FrozenSet[str]] = None  # None = all sinks

    def is_acceptable_to(self, sink_name: str) -> bool:
        """Routing check (sinks/sinks.go:50-56)."""
        return self.sinks is None or sink_name in self.sinks

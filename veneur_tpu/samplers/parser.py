"""DogStatsD datagram and SSF-sample parsing.

Behavioral port of ``/root/reference/samplers/parser.go``: the same packet
grammar, validation rules, magic-tag scoping, and fnv1a-32 digest (computed
over name, type, and the comma-joined sorted tag list) used to shard series.

The digest doubles here as the *row-routing* hash: in the reference it picks
a worker goroutine (``server.go:704,715``); in the TPU build it picks a shard
of the dense series table, preserving the invariant that one series always
aggregates in one place (``importsrv/server.go:34-36``).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import List, Optional, Union

from veneur_tpu.protocol import ssf_pb2
from veneur_tpu.protocol import constants as dogstatsd

# Metric scopes (parser.go:34-40)
MIXED_SCOPE = 0
LOCAL_ONLY = 1
GLOBAL_ONLY = 2
TOPK_SCOPE = 3  # veneur_ingest.cpp Scope::kTopK / store._TOPK_SCOPE

_FNV1A_INIT32 = 0x811C9DC5
_FNV1A_PRIME32 = 0x01000193
_MASK32 = 0xFFFFFFFF


def fnv1a_32(data: Union[str, bytes], h: int = _FNV1A_INIT32) -> int:
    """32-bit FNV-1a (segmentio/fasthash-compatible), resumable."""
    if isinstance(data, str):
        data = data.encode("utf-8")
    for b in data:
        h = ((h ^ b) * _FNV1A_PRIME32) & _MASK32
    return h


@dataclass(frozen=True)
class MetricKey:
    """The identity of a series: all fields comparable/hashable
    (parser.go:42-48)."""

    name: str
    type: str
    joined_tags: str = ""

    def to_string(self) -> str:
        return self.name + self.type + self.joined_tags


@dataclass
class UDPMetric:
    """One parsed sample (parser.go:21-32)."""

    key: MetricKey
    digest: int
    value: object  # float, str (sets), or ssf status enum int
    sample_rate: float = 1.0
    tags: List[str] = field(default_factory=list)
    scope: int = MIXED_SCOPE
    timestamp: int = 0
    message: str = ""
    hostname: str = ""

    # Convenience accessors mirroring the embedded-MetricKey style.
    @property
    def name(self) -> str:
        return self.key.name

    @property
    def type(self) -> str:
        return self.key.type

    @property
    def joined_tags(self) -> str:
        return self.key.joined_tags


class ParseError(ValueError):
    pass


class QuarantineError(ParseError):
    """A line that parsed but carries a poisoned payload: NaN/Inf or
    out-of-range values, an absurd sample rate. Subclasses ParseError so
    every existing rejection path keeps working, but carries a machine
    ``reason`` the server counts into the per-reason quarantine ledger
    (``veneur.overload.quarantined_total``) — poison must be visibly
    quarantined, not silently laundered into percentiles."""

    def __init__(self, reason: str, message: str):
        super().__init__(message)
        self.reason = reason


# values a digest lane cannot hold: staging is float32, so anything past
# f32 range becomes inf AFTER parse — catch it here with a reason
from veneur_tpu.overload import F32_ABS_MAX, MIN_SAMPLE_RATE  # noqa: E402
# int64 counter lanes overflow (numpy OverflowError) past 2^63
_COUNTER_ABS_MAX = float(1 << 63)


_TYPE_BY_LEAD = {
    ord("c"): "counter",
    ord("g"): "gauge",
    ord("h"): "histogram",
    ord("m"): "timer",  # "ms"; only the first byte is inspected (parser.go:281)
    ord("s"): "set",
}


def _extract_scope_tags(tags: List[str], prefix_match: bool) -> tuple[List[str], int]:
    """Drop the first magic scope tag from a *sorted* tag list and return the
    scope it selects (parser.go:326-342). ``prefix_match`` mirrors the
    DogStatsD path's HasPrefix check; the service-check path compares exact."""
    scope = MIXED_SCOPE
    for i, tag in enumerate(tags):
        local = tag.startswith("veneurlocalonly") if prefix_match else tag == "veneurlocalonly"
        glob = tag.startswith("veneurglobalonly") if prefix_match else tag == "veneurglobalonly"
        if local:
            return tags[:i] + tags[i + 1:], LOCAL_ONLY
        if glob:
            return tags[:i] + tags[i + 1:], GLOBAL_ONLY
    return tags, scope


def _check_numeric(value: float, mtype: str, raw) -> None:
    """The numerics quarantine's parse-side gate: non-finite values and
    values the typed store lanes cannot represent (int64 counters, f32
    digest staging) raise QuarantineError with a reason instead of the
    bare ParseError — counted, never laundered."""
    if value != value or value in (float("inf"), float("-inf")):
        raise QuarantineError(
            "not_finite", f"Non-finite metric value: {raw!r}")
    if mtype == "counter" and abs(value) >= _COUNTER_ABS_MAX:
        raise QuarantineError(
            "out_of_range", f"Counter value overflows int64: {raw!r}")
    if mtype in ("histogram", "timer") and abs(value) > F32_ABS_MAX:
        raise QuarantineError(
            "out_of_range", f"Value exceeds float32 range: {raw!r}")


def truncate_joined_tags(joined: str, limit: int) -> str:
    """Cut a joined tag string at the last whole tag within ``limit``
    (the per-series tag-length cap; identities merge past it)."""
    if not limit or len(joined) <= limit:
        return joined
    cut = joined.rfind(",", 0, limit + 1)
    return joined[:cut] if cut > 0 else joined[:limit]


def parse_metric(packet: bytes, max_tag_length: int = 0,
                 quarantine=None) -> UDPMetric:
    """Parse one DogStatsD metric datagram line (parser.go:232-363).

    Grammar: ``name:value|type[|@rate][|#tag1,tag2]`` — sections after the
    type may appear in any order but at most once each.

    ``max_tag_length`` caps the joined tag string (oversized tag sets
    truncate at a tag boundary, counted into ``quarantine`` under
    ``oversized_tags``); poisoned values/rates raise
    :class:`QuarantineError` with a per-reason tag.
    """
    chunks = bytes(packet).split(b"|")
    head = chunks[0]
    colon = head.find(b":")
    if colon == -1:
        raise ParseError("Invalid metric packet, need at least 1 colon")
    name_b, value_b = head[:colon], head[colon + 1:]
    if not name_b:
        raise ParseError("Invalid metric packet, name cannot be empty")
    if len(chunks) < 2:
        raise ParseError("Invalid metric packet, need at least 1 pipe for type")
    type_b = chunks[1]
    if not type_b:
        raise ParseError("Invalid metric packet, metric type not specified")

    mtype = _TYPE_BY_LEAD.get(type_b[0])
    if mtype is None:
        raise ParseError("Invalid type for metric")

    name = name_b.decode("utf-8", "replace")
    h = fnv1a_32(name)
    h = fnv1a_32(mtype, h)

    value: object
    if mtype == "set":
        value = value_b.decode("utf-8", "replace")
    else:
        try:
            value = float(value_b)
        except ValueError:
            raise ParseError(f"Invalid number for metric value: {value_b!r}")
        _check_numeric(value, mtype, value_b)

    sample_rate = 1.0
    found_rate = False
    tags: Optional[List[str]] = None
    joined = ""
    scope = MIXED_SCOPE
    for chunk in chunks[2:]:
        if not chunk:
            raise ParseError("Invalid metric packet, empty string after/between pipes")
        lead = chunk[0]
        if lead == ord("@"):
            if found_rate:
                raise ParseError("Invalid metric packet, multiple sample rates specified")
            try:
                sample_rate = float(chunk[1:])
            except ValueError:
                raise ParseError(f"Invalid float for sample rate: {chunk[1:]!r}")
            # the lower bound also rejects denormal-tiny rates whose
            # float32 reciprocal weight overflows to inf downstream
            if not MIN_SAMPLE_RATE <= sample_rate <= 1:
                raise QuarantineError(
                    "bad_rate",
                    f"Sample rate {sample_rate} must be >0 and <=1")
            found_rate = True
        elif lead == ord("#"):
            if tags is not None:
                raise ParseError("Invalid metric packet, multiple tag sections specified")
            tags = sorted(chunk[1:].decode("utf-8", "replace").split(","))
            tags, scope = _extract_scope_tags(tags, prefix_match=True)
            joined = ",".join(tags)
            if max_tag_length and len(joined) > max_tag_length:
                if quarantine is not None:
                    quarantine.count("oversized_tags")
                joined = truncate_joined_tags(joined, max_tag_length)
                tags = joined.split(",") if joined else []
            h = fnv1a_32(joined, h)
        else:
            raise ParseError(
                f"Invalid metric packet, contains unknown section {chunk!r}")

    return UDPMetric(
        key=MetricKey(name=name, type=mtype, joined_tags=joined),
        digest=h,
        value=value,
        sample_rate=sample_rate,
        tags=tags or [],
        scope=scope,
    )


_SSF_TYPE_NAMES = {
    ssf_pb2.SSFSample.COUNTER: "counter",
    ssf_pb2.SSFSample.GAUGE: "gauge",
    ssf_pb2.SSFSample.HISTOGRAM: "histogram",
    ssf_pb2.SSFSample.SET: "set",
    ssf_pb2.SSFSample.STATUS: "status",
}


def parse_metric_ssf(sample) -> UDPMetric:
    """Convert one embedded SSFSample to a UDPMetric (parser.go:179-230)."""
    mtype = _SSF_TYPE_NAMES.get(sample.metric)
    if mtype is None:
        raise ParseError("Invalid type for metric")
    h = fnv1a_32(sample.name)
    h = fnv1a_32(mtype, h)

    if sample.metric == ssf_pb2.SSFSample.SET:
        value: object = sample.message
    elif sample.metric == ssf_pb2.SSFSample.STATUS:
        value = int(sample.status)
    else:
        value = float(sample.value)
        # the SSF lane historically skipped the DogStatsD lane's
        # non-finite rejection — the straightest NaN path into digest
        # state (quarantined with a reason now, same as statsd)
        _check_numeric(value, mtype, sample.value)

    scope = MIXED_SCOPE
    tags = []
    topk = False
    for k, v in sample.tags.items():
        if k == "veneurlocalonly":
            scope = LOCAL_ONLY
            continue
        if k == "veneurglobalonly":
            scope = GLOBAL_ONLY
            continue
        if k == "veneurtopk":
            topk = True
        tags.append(f"{k}:{v}")
    tags.sort()
    # heavy-hitter routing, matching the DogStatsD lane's veneurtopk
    # tag (parse_line): only sets re-route; the tag stays in the list
    if topk and sample.metric == ssf_pb2.SSFSample.SET:
        scope = TOPK_SCOPE
    joined = ",".join(tags)
    h = fnv1a_32(joined, h)
    return UDPMetric(
        key=MetricKey(name=sample.name, type=mtype, joined_tags=joined),
        digest=h,
        value=value,
        # proto3's absent-field default is 0; a zero rate would weight
        # samples 1/0 downstream — absent means unsampled, i.e. 1.0
        sample_rate=sample.sample_rate if sample.sample_rate > 0 else 1.0,
        tags=tags,
        scope=scope,
    )


def valid_metric(metric: UDPMetric) -> bool:
    """Name and value must both be present (parser.go:152-157)."""
    return bool(metric.key.name) and metric.value is not None and metric.value != ""


def convert_metrics(span) -> tuple[List[UDPMetric], List]:
    """Extract all valid metrics from a span; returns (metrics, invalid
    samples) (parser.go:70-92)."""
    out: List[UDPMetric] = []
    invalid = []
    for sample in span.metrics:
        try:
            m = parse_metric_ssf(sample)
        except ParseError:
            invalid.append(sample)
            continue
        if not valid_metric(m):
            invalid.append(sample)
            continue
        out.append(m)
    return out, invalid


def convert_indicator_metrics(span, timer_name: str) -> List[UDPMetric]:
    """Produce a duration timer from an indicator span (parser.go:94-121):
    nanosecond-resolution timing tagged with service and error status."""
    if not span.indicator or not timer_name:
        return []
    duration_ns = span.end_timestamp - span.start_timestamp
    sample = ssf_pb2.SSFSample(
        metric=ssf_pb2.SSFSample.HISTOGRAM,
        name=timer_name,
        value=float(duration_ns),
        unit="ns",
        sample_rate=1.0,
    )
    sample.tags["service"] = span.service
    sample.tags["error"] = "true" if span.error else "false"
    return [parse_metric_ssf(sample)]


def parse_tags_to_map(tags: List[str]) -> dict:
    """Split "k:v" tags into a map; tags without ':' map to "" (parser.go:628-640)."""
    out = {}
    for tag in tags:
        k, _, v = tag.partition(":")
        out[k] = v
    return out


def parse_event(packet: bytes, now: Optional[int] = None):
    """Parse a DogStatsD event packet into an SSFSample whose special
    ``vdogstatsd_*`` tags carry the Datadog-specific fields
    (parser.go:365-511)."""
    ret = ssf_pb2.SSFSample(timestamp=now if now is not None else int(time.time()))
    ret.tags[dogstatsd.EVENT_IDENTIFIER_KEY] = ""

    chunks = bytes(packet).split(b"|")
    head = chunks[0]
    colon = head.find(b":")
    if colon == -1:
        raise ParseError("Invalid event packet, need at least 1 colon")
    lengths = head[:colon]
    if not lengths.startswith(b"_e{") or not lengths.endswith(b"}"):
        raise ParseError("Invalid event packet, must have _e{} wrapper around length section")
    lengths = lengths[3:-1]
    comma = lengths.find(b",")
    if comma == -1:
        raise ParseError("Invalid event packet, length section requires comma divider")
    try:
        title_len = int(lengths[:comma])
    except ValueError as e:
        raise ParseError(f"Invalid event packet, title length is not an integer: {e}")
    if title_len <= 0:
        raise ParseError("Invalid event packet, title length must be positive")
    try:
        text_len = int(lengths[comma + 1:])
    except ValueError as e:
        raise ParseError(f"Invalid event packet, text length is not an integer: {e}")
    if text_len <= 0:
        raise ParseError("Invalid event packet, text length must be positive")

    title = head[colon + 1:]
    if len(title) != title_len:
        raise ParseError("Invalid event packet, actual title length did not match encoded length")
    ret.name = title.decode("utf-8", "replace")

    if len(chunks) < 2:
        raise ParseError("Invalid event packet, must have at least 1 pipe for text")
    text = chunks[1]
    if len(text) != text_len:
        raise ParseError("Invalid event packet, actual text length did not match encoded length")
    ret.message = text.decode("utf-8", "replace").replace("\\n", "\n")

    seen = set()

    def once(kind: str):
        if kind in seen:
            raise ParseError(f"Invalid event packet, multiple {kind} sections")
        seen.add(kind)

    for chunk in chunks[2:]:
        if not chunk:
            raise ParseError("Invalid event packet, empty string after/between pipes")
        if chunk.startswith(b"d:"):
            once("date")
            try:
                ret.timestamp = int(chunk[2:])
            except ValueError as e:
                raise ParseError(
                    f"Invalid event packet, could not parse date as unix timestamp: {e}")
        elif chunk.startswith(b"h:"):
            once("hostname")
            ret.tags[dogstatsd.EVENT_HOSTNAME_TAG] = chunk[2:].decode("utf-8", "replace")
        elif chunk.startswith(b"k:"):
            once("aggregation key")
            ret.tags[dogstatsd.EVENT_AGGREGATION_KEY_TAG] = chunk[2:].decode("utf-8", "replace")
        elif chunk.startswith(b"p:"):
            once("priority")
            pri = chunk[2:].decode("utf-8", "replace")
            if pri not in ("normal", "low"):
                raise ParseError("Invalid event packet, priority must be normal or low")
            ret.tags[dogstatsd.EVENT_PRIORITY_TAG] = pri
        elif chunk.startswith(b"s:"):
            once("source")
            ret.tags[dogstatsd.EVENT_SOURCE_TYPE_TAG] = chunk[2:].decode("utf-8", "replace")
        elif chunk.startswith(b"t:"):
            once("alert")
            alert = chunk[2:].decode("utf-8", "replace")
            if alert not in ("error", "warning", "info", "success"):
                raise ParseError(
                    "Invalid event packet, alert level must be error, warning, info or success")
            ret.tags[dogstatsd.EVENT_ALERT_TYPE_TAG] = alert
        elif chunk[0] == ord("#"):
            once("tags")
            for k, v in parse_tags_to_map(
                    chunk[1:].decode("utf-8", "replace").split(",")).items():
                ret.tags[k] = v
        else:
            raise ParseError("Invalid event packet, unrecognized metadata section")
    return ret


_STATUS_BY_BYTE = {
    b"0": ssf_pb2.SSFSample.OK,
    b"1": ssf_pb2.SSFSample.WARNING,
    b"2": ssf_pb2.SSFSample.CRITICAL,
    b"3": ssf_pb2.SSFSample.UNKNOWN,
}


def parse_service_check(packet: bytes, now: Optional[int] = None) -> UDPMetric:
    """Parse a DogStatsD service check (``_sc|name|status|...``)
    (parser.go:513-626)."""
    chunks = bytes(packet).split(b"|")
    if chunks[0] != b"_sc":
        raise ParseError("Invalid service check packet, no _sc prefix")
    if len(chunks) < 2:
        raise ParseError("Invalid service check packet, need name section")
    if not chunks[1]:
        raise ParseError("Invalid service check packet, empty name")
    name = chunks[1].decode("utf-8", "replace")
    if len(chunks) < 3:
        raise ParseError("Invalid service check packet, need status section")
    status = _STATUS_BY_BYTE.get(chunks[2])
    if status is None:
        raise ParseError("Invalid service check packet, must have status of 0, 1, 2, or 3")

    timestamp = now if now is not None else int(time.time())
    hostname = ""
    message = ""
    tags: List[str] = []
    scope = MIXED_SCOPE
    seen = set()

    def once(kind: str):
        if kind in seen:
            raise ParseError(f"Invalid service check packet, multiple {kind} sections")
        seen.add(kind)

    for chunk in chunks[3:]:
        if not chunk:
            raise ParseError("Invalid service packet packet, empty string after/between pipes")
        if "message" in seen:
            raise ParseError(
                "Invalid service check packet, message must be the last metadata section")
        if chunk.startswith(b"d:"):
            once("date")
            try:
                timestamp = int(chunk[2:])
            except ValueError as e:
                raise ParseError(
                    f"Invalid service check packet, could not parse date as unix timestamp: {e}")
        elif chunk.startswith(b"h:"):
            once("hostname")
            hostname = chunk[2:].decode("utf-8", "replace")
        elif chunk.startswith(b"m:"):
            once("message")
            message = chunk[2:].decode("utf-8", "replace").replace("\\n", "\n")
        elif chunk[0] == ord("#"):
            once("tags")
            tags = sorted(chunk[1:].decode("utf-8", "replace").split(","))
            tags, scope = _extract_scope_tags(tags, prefix_match=False)
        else:
            raise ParseError("Invalid service check packet, unrecognized metadata section")

    joined = ",".join(tags)
    h = fnv1a_32(name)
    h = fnv1a_32("status", h)
    h = fnv1a_32(joined, h)
    return UDPMetric(
        key=MetricKey(name=name, type="status", joined_tags=joined),
        digest=h,
        value=int(status),
        sample_rate=1.0,
        tags=tags,
        scope=scope,
        timestamp=timestamp,
        message=message,
        hostname=hostname,
    )


def split_lines(packet: bytes):
    """Split a multi-metric datagram on newlines, skipping a trailing
    newline's empty chunk (cf. SplitBytes, samplers/split_bytes.go:17-56 and
    its use at server.go:806-819)."""
    for line in packet.split(b"\n"):
        if line:
            yield line

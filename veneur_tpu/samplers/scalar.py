"""Scalar (pure-Python) reference samplers used as golden models in tests.

These mirror the reference implementations' algorithms one series at a time —
the merging t-digest of ``/root/reference/tdigest/merging_digest.go`` and the
dense HyperLogLog of the vendored axiomhq library — so the batched XLA kernels
in ``veneur_tpu.ops`` can be checked for epsilon-equivalence, playing the role
``tdigest/analysis/`` plays for the reference (SURVEY.md section 4).

They are NOT on any hot path.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field


def _k_scale(q: float, compression: float) -> float:
    return compression * (math.asin(2 * q - 1) / math.pi + 0.5)


@dataclass
class ScalarTDigest:
    """Greedy merging t-digest, one series (merging_digest.go:21-257)."""

    compression: float = 100.0
    means: list = field(default_factory=list)
    weights: list = field(default_factory=list)
    temp: list = field(default_factory=list)
    min: float = math.inf
    max: float = -math.inf

    def __post_init__(self):
        c = min(925.0, max(20.0, self.compression))
        self._temp_cap = int(7.5 + 0.37 * c - 2e-4 * c * c)

    def add(self, value: float, weight: float = 1.0) -> None:
        if math.isnan(value) or math.isinf(value) or weight <= 0:
            raise ValueError("invalid value added")
        if len(self.temp) >= self._temp_cap:
            self._merge_temps()
        self.min = min(self.min, value)
        self.max = max(self.max, value)
        self.temp.append((value, weight))

    def _merge_temps(self) -> None:
        if not self.temp:
            return
        allc = sorted(list(zip(self.means, self.weights)) + self.temp)
        self.temp = []
        total = sum(w for _, w in allc)
        merged_w = 0.0
        last_idx = 0.0
        out_m: list = []
        out_w: list = []
        for m, w in allc:
            next_idx = _k_scale((merged_w + w) / total, self.compression)
            if next_idx - last_idx > 1 or not out_m:
                # start a new centroid
                out_m.append(m)
                out_w.append(w)
                last_idx = _k_scale(merged_w / total, self.compression)
            else:
                # fold into the current centroid (Welford order: weight first)
                out_w[-1] += w
                out_m[-1] += (m - out_m[-1]) * w / out_w[-1]
            merged_w += w
        self.means, self.weights = out_m, out_w

    def count(self) -> float:
        return sum(self.weights) + sum(w for _, w in self.temp)

    def _upper_bound(self, i: int) -> float:
        if i != len(self.means) - 1:
            return (self.means[i + 1] + self.means[i]) / 2
        return self.max

    def quantile(self, q: float) -> float:
        if q < 0 or q > 1:
            raise ValueError("quantile out of bounds")
        self._merge_temps()
        if not self.means:
            return math.nan
        total = sum(self.weights)
        target = q * total
        wsf = 0.0
        lb = self.min
        for i, w in enumerate(self.weights):
            ubi = self._upper_bound(i)
            if target <= wsf + w:
                prop = (target - wsf) / w
                return lb + prop * (ubi - lb)
            wsf += w
            lb = ubi
        return math.nan

    def cdf(self, value: float) -> float:
        self._merge_temps()
        if not self.means:
            return math.nan
        if value <= self.min:
            return 0.0
        if value >= self.max:
            return 1.0
        total = sum(self.weights)
        wsf = 0.0
        lb = self.min
        for i, w in enumerate(self.weights):
            ubi = self._upper_bound(i)
            if value < ubi:
                wsf += w * (value - lb) / (ubi - lb)
                return wsf / total
            wsf += w
            lb = ubi
        return math.nan

    def merge(self, other: "ScalarTDigest") -> None:
        other._merge_temps()
        for m, w in zip(other.means, other.weights):
            self.add(m, w)


class ScalarHLL:
    """Dense HyperLogLog with linear-counting small-range correction,
    one series (cf. samplers.Set over axiomhq/hyperloglog, samplers.go:367-435).
    """

    def __init__(self, precision: int = 14):
        if not 4 <= precision <= 18:
            raise ValueError("precision must be in [4, 18]")
        self.p = precision
        self.m = 1 << precision
        self.registers = bytearray(self.m)

    def insert_hash(self, h: int) -> None:
        """Insert a 64-bit hash value."""
        idx = h >> (64 - self.p)
        rest = (h << self.p) & ((1 << 64) - 1)
        # rho = leading zeros of the remaining 64-p bits, +1
        rho = 1
        bit = 1 << 63
        while rho <= 64 - self.p and not (rest & bit):
            rho += 1
            bit >>= 1
        if rho > self.registers[idx]:
            self.registers[idx] = rho

    def merge(self, other: "ScalarHLL") -> None:
        if other.p != self.p:
            raise ValueError("precision mismatch")
        for i in range(self.m):
            if other.registers[i] > self.registers[i]:
                self.registers[i] = other.registers[i]

    def estimate(self) -> float:
        m = float(self.m)
        if self.p >= 7:
            alpha = 0.7213 / (1 + 1.079 / m)
        else:
            alpha = {4: 0.673, 5: 0.697, 6: 0.709}[self.p]
        raw_inv = sum(2.0 ** -r for r in self.registers)
        est = alpha * m * m / raw_inv
        zeros = sum(1 for r in self.registers if r == 0)
        if est <= 2.5 * m and zeros > 0:
            return m * math.log(m / zeros)
        return est

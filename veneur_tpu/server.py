"""Server lifecycle: config → listeners → store → flush loop.

Behavioral port of ``/root/reference/server.go``: ingest dispatch
(``handle_metric_packet``, server.go:670-720), SSF handling
(server.go:722-792), read loops (via ``networking.py``), the
interval-aligned flush ticker (server.go:638-665, ``calculate_tick_delay``
server.go:1163-1177), and lifecycle (``start``/``shutdown``,
server.go:555-666, 1095-1130).

Two process roles share this class (server.go:1132-1137): a **local**
instance (``forward_address`` set) flushes host-local aggregates to sinks
and forwards sketch state upstream; a **global** instance merges imported
sketches and emits percentiles.
"""

from __future__ import annotations

import logging
import math
import queue
import socket
import threading
import time
from typing import Callable, List, Optional

from veneur_tpu import networking
from veneur_tpu.config import Config, parse_duration
from veneur_tpu.core.store import MetricStore
from veneur_tpu.protocol import wire
from veneur_tpu.samplers import parser as p
from veneur_tpu.samplers.intermetric import HistogramAggregates
from veneur_tpu.sinks.base import MetricSink, SpanSink
from veneur_tpu.sinks.ssfmetrics import MetricExtractionSink

log = logging.getLogger("veneur.server")


class EventWorker:
    """Collects events (as SSFSamples) until flush (worker.go:439-485)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._samples: List = []

    def add(self, sample):
        with self._lock:
            self._samples.append(sample)

    def flush(self) -> List:
        with self._lock:
            out, self._samples = self._samples, []
        return out


class _SinkIngestor:
    """One span sink's bounded ingest lane: a dedicated thread drains a
    bounded queue into ``sink.ingest``.

    This is the thread-pool translation of the reference's
    goroutine-per-ingest with a 9 s timeout (worker.go:541-590): there a
    hung sink times out and the worker moves on (leaking the goroutine);
    here a hung sink wedges only its own lane — spans pile into its queue
    and, once full, drop with ``ingest_timeout_total`` — while every
    other sink (critically the metric-extraction sink, the main path to
    the store) keeps draining.
    """

    TIMEOUT = 9.0  # worker.go:523

    def __init__(self, sink: SpanSink, stop: threading.Event,
                 capacity: int = 4096):
        self.sink = sink
        self.stop = stop
        self.queue: "queue.Queue" = queue.Queue(capacity)
        self.ingest_errors = 0
        self.ingest_timeouts = 0
        # per-interval high watermark of the queue depth: queue pressure
        # must be visible (veneur.server.span_lane.depth) BEFORE
        # ingest_timeout_total drops begin; read-and-reset by the flusher
        self.depth_hwm = 0
        # offer() runs on every span-worker thread concurrently
        self._drop_lock = threading.Lock()
        self._flush_thread: Optional[threading.Thread] = None
        self._thread = threading.Thread(
            target=self._work, name=f"span-ingest-{sink.name}", daemon=True)
        self._thread.start()

    def offer(self, span) -> None:
        try:
            self.queue.put_nowait(span)
            self._note_depth()
        except queue.Full:
            # the lane is wedged (or 9s+ behind): drop, as the reference
            # does after its per-span timeout fires
            with self._drop_lock:
                self.ingest_timeouts += 1

    def offer_batch(self, spans: list) -> None:
        """One queue hop for a whole decoded batch (the native SSF
        lane): per-span queue ops would cap the pipeline far below the
        C++ decoder's rate."""
        try:
            self.queue.put_nowait(spans)
            self._note_depth()
        except queue.Full:
            with self._drop_lock:
                self.ingest_timeouts += len(spans)

    def _note_depth(self) -> None:
        # racy max is fine: the gauge is advisory and under-reporting by
        # one sample beats a lock acquisition on every span
        d = self.queue.qsize()
        if d > self.depth_hwm:
            self.depth_hwm = d

    def _work(self):
        while True:
            try:
                item = self.queue.get(timeout=0.5)
            except queue.Empty:  # lint: ok(swallowed-exception) empty-queue poll sentinel — nothing was dequeued, nothing in flight
                # exit only once stopped AND drained, so shutdown's final
                # flush never abandons spans already accepted off the
                # channel (the "at most one interval lost" contract)
                if self.stop.is_set():
                    return  # lint: ok(silent-drop) clean shutdown: stop is set AND the queue is drained, nothing in flight
                continue  # lint: ok(silent-drop) idle poll: the queue was empty, nothing in flight
            try:
                if type(item) is list:
                    for span in item:
                        try:
                            self.sink.ingest(span)
                        except Exception:
                            self.ingest_errors += 1
                            log.exception("span sink %s ingest failed",
                                          self.sink.name)
                else:
                    self.sink.ingest(item)
            except Exception:
                self.ingest_errors += 1
                log.exception("span sink %s ingest failed", self.sink.name)
            finally:
                self.queue.task_done()

    def drain(self, timeout: float = TIMEOUT) -> bool:
        """Wait (bounded) until every offered span has finished ingesting
        (not merely been popped); False if the lane is still wedged."""
        deadline = time.monotonic() + timeout
        while self.queue.unfinished_tasks:
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.01)
        return True

    def flush_sink(self, timeout: float = TIMEOUT) -> None:
        """Run ``sink.flush()`` bounded: on its own thread, joined up to
        ``timeout``. A sink whose flush blocks forever (same dead peer
        its ingest is wedged on) must pin only ITSELF — the next interval
        skips just this sink while every other sink keeps flushing."""
        if self._flush_thread is not None and self._flush_thread.is_alive():
            log.warning("span sink %s previous flush still running; "
                        "skipping", self.sink.name)
            return

        def run():
            try:
                self.sink.flush()
            except Exception:
                log.exception("span sink %s flush failed", self.sink.name)

        t = threading.Thread(target=run,
                             name=f"span-flush-{self.sink.name}",
                             daemon=True)
        self._flush_thread = t
        t.start()
        t.join(timeout)
        if t.is_alive():
            log.warning("span sink %s flush exceeded %.0fs; continuing "
                        "without it", self.sink.name, timeout)


def make_span_lanes(sinks: List[SpanSink],
                    stop: threading.Event) -> List[_SinkIngestor]:
    """One shared lane per sink — shared across every SpanWorker, so a
    sink has exactly one ingest thread and the flush barrier covers all
    workers' spans."""
    return [_SinkIngestor(s, stop) for s in sinks]


class SpanWorker:
    """Drains the span channel into every span sink (worker.go:487-592),
    through bounded per-sink ingest lanes (shared between workers) so a
    hung sink cannot stall the rest (see _SinkIngestor)."""

    def __init__(self, sinks: List[SpanSink], span_chan: "queue.Queue",
                 stop: threading.Event,
                 lanes: Optional[List[_SinkIngestor]] = None):
        self.sinks = sinks
        self.chan = span_chan
        self.stop = stop
        self.ingested = 0
        self._lanes = lanes if lanes is not None else make_span_lanes(
            sinks, stop)

    def work(self):
        while not self.stop.is_set():
            try:
                item = self.chan.get(timeout=0.5)
            except queue.Empty:  # lint: ok(swallowed-exception) empty-channel poll sentinel — nothing was dequeued, nothing in flight
                continue  # lint: ok(silent-drop) idle poll: the channel was empty, nothing in flight
            if type(item) is list:
                # a decoded native-lane batch: one channel hop for the
                # whole batch, one lane hop per sink
                self.ingested += len(item)
                for lane in self._lanes:
                    lane.offer_batch(item)
            else:
                self.ingested += 1
                for lane in self._lanes:
                    lane.offer(item)

    def flush(self):
        for lane in self._lanes:
            # flush-barrier: give in-flight spans a bounded chance to land
            # before the sink flushes (a wedged lane is skipped, not waited)
            if not lane.drain():
                log.warning("span sink %s still wedged at flush; %d drops "
                            "so far", lane.sink.name, lane.ingest_timeouts)
            lane.flush_sink()


def calculate_tick_delay(interval: float, now: float) -> float:
    """Seconds until the next interval boundary (server.go:1163-1177)."""
    return interval - math.fmod(now, interval)


class Server:
    """The aggregation server. Use ``Server(config)`` then ``start()``."""

    def __init__(self, config: Config,
                 metric_sinks: Optional[List[MetricSink]] = None,
                 span_sinks: Optional[List[SpanSink]] = None):
        config.apply_defaults()
        self.config = config
        self.interval = parse_duration(config.interval)
        self.hostname = config.hostname
        self.tags = list(config.tags)
        self.tags_exclude = set(config.tags_exclude)
        self.histogram_percentiles = list(config.percentiles)
        self.histogram_aggregates = HistogramAggregates.from_names(
            config.aggregates)

        # A global instance can shard its store over every visible chip
        # (the reference scales its global tier with more worker goroutines
        # + proxy hash rings; here the series axis shards over the mesh,
        # importsrv/server.go:101-132 → veneur_tpu/fleet/). A local with
        # mesh_enabled is a config contradiction: config.validate()
        # rejects it at load, and this re-check covers directly
        # constructed Configs (tests, embedders) — silently ignoring the
        # key hid mis-deployed fleets until someone read the logs.
        mesh = None
        if config.mesh_enabled and config.forward_address:
            raise ValueError(
                "mesh_enabled requires a GLOBAL instance, but "
                "forward_address is set; unset one of them "
                "(config.validate rejects this combination at load)")
        if config.mesh_enabled:
            from veneur_tpu.fleet import build_mesh

            mesh = build_mesh(config)
        # hot-path overload governance (veneur_tpu/overload.py,
        # docs/resilience.md "Degradation ladder"): bounded per-group
        # cardinality, the numerics quarantine ledger, the watermark
        # admission controller, and the flush-kernel compute breaker
        from veneur_tpu import overload
        from veneur_tpu.resilience import compute as rcompute

        self.overload = overload.from_config(config)
        self.store = MetricStore(
            initial_capacity=config.store_initial_capacity,
            chunk=config.store_chunk,
            compression=config.tdigest_compression,
            hll_precision=config.hll_precision,
            mesh=mesh,
            digest_storage=config.digest_storage,
            digest_dtype=config.digest_dtype,
            slab_rows=config.slab_rows,
            tier_pool_centroids=config.tier_pool_centroids,
            tier_promote_samples=config.tier_promote_samples,
            tier_promote_intervals=config.tier_promote_intervals,
            tier_demote_intervals=config.tier_demote_intervals,
            topk_depth=config.topk_depth,
            topk_width=config.topk_width,
            topk_k=config.topk_k,
            max_series=config.max_series,
            max_tag_length=config.max_tag_length,
            compute=rcompute.from_config(config),
            overload=self.overload,
            flush_pipeline_depth=config.flush_pipeline_depth,
        )
        self.quarantine = self.store.quarantine
        self.event_worker = EventWorker()
        self.span_chan: "queue.Queue" = queue.Queue(config.span_channel_capacity)
        # pressure sources (span channel, lanes, group occupancy) read
        # through the server; attach now that the channel exists
        self.overload.attach(self)
        # seeded ingest-side fault injection (resilience/faults.py
        # KIND_TRUNCATE/KIND_BURST): armed only when the configured kind
        # set includes an ingest kind — transport injectors stay in the
        # egress layer
        from veneur_tpu.resilience import faults as rfaults

        self.ingest_injector = None
        # CSV order preserved: the kind tuple indexes the seeded
        # schedule, so set ordering would break run-to-run reproduction
        cfg_kinds = [k.strip() for k in
                     (config.fault_injection_kinds or "").split(",")
                     if k.strip()]
        if config.fault_injection_rate > 0 and \
                any(k in rfaults.INGEST_KINDS for k in cfg_kinds):
            self.ingest_injector = rfaults.FaultInjector(
                rate=config.fault_injection_rate,
                seed=config.fault_injection_seed,
                kinds=tuple(cfg_kinds), scope=config.fault_injection_scope)
        # soak-plane faults (resilience/faults.py SOAK_KINDS): disk-full
        # on the checkpoint/spool commits and deadline pressure on the
        # flush budget — armed only when the configured kind set
        # includes one, like the ingest injector above
        self.soak_injector = None
        if config.fault_injection_rate > 0 and \
                any(k in rfaults.SOAK_KINDS for k in cfg_kinds):
            self.soak_injector = rfaults.FaultInjector(
                rate=config.fault_injection_rate,
                seed=config.fault_injection_seed,
                kinds=tuple(cfg_kinds), scope=config.fault_injection_scope)

        # config-driven backends (server.go:350-519) plus any injected ones
        from veneur_tpu.sinks.factory import create_sinks
        cfg_metric_sinks, cfg_span_sinks, cfg_plugins = create_sinks(config)
        # injected sinks survive a SIGHUP reload; config-driven ones
        # rebuild from the new file
        self._injected_metric_sinks = list(metric_sinks or [])
        self.metric_sinks: List[MetricSink] = (self._injected_metric_sinks
                                               + cfg_metric_sinks)
        self.span_sinks: List[SpanSink] = (list(span_sinks or [])
                                           + cfg_span_sinks)
        # the extraction sink is how SSF samples reach the store
        # (server.go:282-290)
        self.span_sinks.append(MetricExtractionSink(
            self.store.process_metric, config.indicator_span_timer_name))

        self.plugins: List = cfg_plugins

        # self-telemetry: a channel trace client feeding our own span
        # channel, so internal spans re-enter the pipeline
        # (server.go:196-202)
        from veneur_tpu.trace import new_channel_client
        self.trace_client = new_channel_client(self.span_chan)
        # flush-interval observability (veneur_tpu/obs/): the bounded
        # timeline ring behind GET /debug/flush-timeline; None when
        # obs_enabled is off — the flusher then allocates no recorder
        # and every stage hook is one thread-local read
        self.obs_timeline = None
        self.obs_hops = None         # cross-hop records (obs/tracectx.py)
        self.fleet_aggregator = None  # /debug/fleet + /debug/trace
        if config.obs_enabled:
            from veneur_tpu.obs import FlushTimeline, HopLog
            from veneur_tpu.obs.fleet import FleetAggregator

            # apply_defaults (above) already substituted the 0-means-64
            # default; config is the single source of truth here
            self.obs_timeline = FlushTimeline(
                config.obs_timeline_intervals)
            self.obs_hops = HopLog()
            # the fleet trace plane's aggregation view: peers come from
            # fleet_peers (falling back to the resharding membership),
            # pulled keep-last-good; with no peer source the aggregator
            # still serves this instance's own entries at /debug/trace
            self.fleet_aggregator = FleetAggregator(
                self_addr=config.handoff_self or "",
                watcher=self._build_fleet_watcher(config),
                timeline=self.obs_timeline, hop_log=self.obs_hops,
                pull_timeout=config.fleet_pull_timeout_seconds,
                pull_interval=config.fleet_pull_interval_seconds)
        # set by the forwarding layer (veneur_tpu.forward) when local
        self.forward_fn: Optional[Callable] = None
        self._forwarder = None
        self.ops_server = None      # HTTP /healthcheck,/version,/import
        self.import_server = None   # gRPC Forward.SendMetrics ingest
        self.native_import_server = None  # framed-TCP fast lane

        self._stop = threading.Event()
        self._reload_lock = threading.Lock()
        self._retired_sinks: List = []  # replaced on reload, closed later
        self._sentry = None
        self._profiler = None
        self._thread_profiles: List = []
        self._profiles_lock = threading.Lock()
        self._guard = lambda fn: fn  # replaced in start()
        self._threads: List[threading.Thread] = []
        self._native_readers: List = []
        self._native_ssf_readers: List = []  # subset of the above
        self._native_pumps: List[threading.Thread] = []
        self._span_workers: List[SpanWorker] = []
        self._flush_thread: Optional[threading.Thread] = None
        self._tls_context = None
        if config.tls_certificate and config.tls_key:
            self._tls_context = networking.make_server_tls_context(
                config.tls_certificate, config.tls_key,
                config.tls_authority_certificate)

        # flush-staleness readiness (GET /healthcheck/ready): wall-clock
        # of the last SUCCESSFUL flush (None until one lands; age is
        # measured from start() before that) and whether the last
        # attempt succeeded
        self.last_flush_time: Optional[float] = None
        self.last_flush_ok = True
        self._started_wall = time.time()
        # flush watchdog (veneur.flush.overrun_total)
        self.flush_overruns = 0
        self._last_overrun_warn = 0.0

        # crash-safe state: interval checkpointing + warm-restart
        # recovery (veneur_tpu/persist/, docs/resilience.md)
        self.checkpointer = None
        self._ckpt_thread: Optional[threading.Thread] = None
        if config.checkpoint_path:
            from veneur_tpu.persist import Checkpointer

            ckpt_interval = (config.checkpoint_interval_seconds
                             or self.interval / 4.0)
            ckpt_write_fn = None
            if self.soak_injector is not None:
                from veneur_tpu.persist import format as ckpt_format

                ckpt_write_fn = self.soak_injector.wrap_write(
                    ckpt_format.write_atomic, "checkpoint.write")
            self.checkpointer = Checkpointer(
                self.store, config.checkpoint_path,
                interval_s=ckpt_interval,
                max_age_s=(config.checkpoint_max_age_intervals
                           * self.interval),
                hostname=self.hostname,
                write_fn=ckpt_write_fn)

        # elastic fleet resharding (veneur_tpu/fleet/handoff.py,
        # docs/resilience.md "Elastic resharding"): membership watcher
        # + zero-loss packed-digest handoff, both roles (sender and
        # /handoff receiver). Built after the checkpointer and the
        # timeline — it anchors crash recovery on the former and
        # publishes its stage trees into the latter.
        self.handoff_manager = None
        if config.handoff_enabled:
            if config.forward_address:
                # mirrors config.validate for directly-built Configs
                raise ValueError(
                    "handoff_enabled requires a GLOBAL instance, but "
                    "forward_address is set (config.validate rejects "
                    "this combination at load)")
            from veneur_tpu.fleet.handoff import HandoffManager

            self.handoff_manager = HandoffManager.for_server(self)

        # global HA: warm-standby replication + leased failover
        # (fleet/standby.py, discovery/lease.py, docs/resilience.md
        # "Global HA"). The standby manager exists whenever either side
        # of the plane is configured: standby_peers (this instance
        # replicates out) or lease_path (this instance contends for
        # leadership / receives replication).
        self.standby_manager = None
        self.lease_elector = None
        if config.standby_peers or config.lease_path:
            if config.forward_address:
                # mirrors config.validate for directly-built Configs
                raise ValueError(
                    "standby_peers/lease_path require a GLOBAL "
                    "instance, but forward_address is set "
                    "(config.validate rejects this combination at load)")
            from veneur_tpu.fleet.standby import StandbyManager

            self.standby_manager = StandbyManager.for_server(self)
            if config.lease_path:
                from veneur_tpu.discovery import (LeaseElector,
                                                  lease_backend_from_url)

                backend = lease_backend_from_url(config.lease_path)
                self.lease_elector = LeaseElector(
                    backend,
                    holder=config.handoff_self or config.http_address,
                    ttl=config.lease_ttl_seconds,
                    renew_interval=config.lease_renew_interval_seconds,
                    on_promote=self.standby_manager.on_promote,
                    on_demote=self.standby_manager.on_demote)
            else:
                # no election configured: replicate unconditionally
                self.standby_manager.is_leader = True

        # ingest error/telemetry counters. packet_errors/spans_dropped
        # are SHARDED (veneur_tpu/ingest/counters.py): the hot paths —
        # every reader thread on every bad packet, every span shed —
        # write a per-thread cell lock-free and the totals sum
        # read-side at flush //debug/vars (the old _counter_lock
        # serialized all readers exactly during poison bursts)
        from veneur_tpu.ingest.counters import ShardedCounter

        self._packet_errors = ShardedCounter()
        self._spans_dropped = ShardedCounter()
        self._packet_errors_adjust = 0  # property-setter shim (tests)
        self._spans_dropped_adjust = 0
        self.packet_drops = 0
        self._last_spans_dropped = 0
        self._counter_lock = threading.Lock()  # cold-path counters
        self._last_span_drop_log = 0.0
        self._last_packet_errors = 0
        self._last_packet_drops = 0
        self._warned_no_forward = False
        # sharded ingest-lane fleets, one per UDP statsd address
        # (veneur_tpu/ingest/); the first one feeds overload pressure
        self.ingest_fleet = None
        self._ingest_fleets: List = []
        self._udp_receivers: List = []  # BatchReceivers of Python readers
        # bound listener addresses (useful when configured with port 0)
        self.statsd_addrs: List = []
        self.ssf_addrs: List = []

    # -- sharded ingest counters --------------------------------------------

    @property
    def packet_errors(self) -> int:
        """Bad-packet total: sharded reader cells + per-lane parse
        errors, summed read-side (no lock on the increment path)."""
        lanes = sum(f.parse_errors() for f in self._ingest_fleets)
        return (self._packet_errors.total() + lanes
                + self._packet_errors_adjust)

    @packet_errors.setter
    def packet_errors(self, value: int) -> None:
        # test/tooling shim: absolute assignment adjusts the offset; the
        # server itself only ever adds through the sharded counter
        self._packet_errors_adjust = 0
        self._packet_errors_adjust = value - self.packet_errors

    @property
    def spans_dropped(self) -> int:
        return self._spans_dropped.total() + self._spans_dropped_adjust

    @spans_dropped.setter
    def spans_dropped(self, value: int) -> None:
        self._spans_dropped_adjust = 0
        self._spans_dropped_adjust = value - self.spans_dropped

    @staticmethod
    def _build_fleet_watcher(config):
        """Membership source for the /debug/fleet aggregation
        (obs/fleet.py): fleet_peers (CSV or file://), falling back to
        the elastic-resharding peer list; None = own entries only."""
        peers = ((config.fleet_peers or "").strip()
                 or (config.handoff_peers or "").strip())
        if not peers:
            return None
        from veneur_tpu.discovery import (FilePeersDiscoverer,
                                          RingWatcher, StaticDiscoverer)

        if peers.startswith("file://"):
            discoverer = FilePeersDiscoverer(peers[len("file://"):])
        else:
            discoverer = StaticDiscoverer(
                [p.strip() for p in peers.split(",") if p.strip()])
        return RingWatcher(discoverer, "veneur-fleet-debug")

    # -- role ---------------------------------------------------------------

    def is_local(self) -> bool:
        """forward_address set ⇒ local role (server.go:1132-1137)."""
        return bool(self.config.forward_address)

    # -- ingest dispatch ----------------------------------------------------

    def handle_metric_packet(self, packet: bytes) -> bool:
        """Parse one line and route it (server.go:670-720). Returns False
        on a parse error (counted, logged at debug). Poisoned-but-
        parseable lines (NaN/Inf, out-of-range, absurd rates) count into
        the per-reason quarantine ledger instead of packet_errors —
        they are accounted load, not noise."""
        try:
            if packet.startswith(b"_e{"):
                self.event_worker.add(p.parse_event(packet))
            elif packet.startswith(b"_sc"):
                self.store.process_metric(p.parse_service_check(packet))
            else:
                self.store.process_metric(p.parse_metric(
                    packet, max_tag_length=self.store.max_tag_length,
                    quarantine=self.quarantine))
        except p.QuarantineError as e:
            self.quarantine.count(e.reason)
            log.debug("quarantined packet %r: %s", packet[:100], e)
            return False
        except p.ParseError as e:
            self._packet_errors.add(1)
            log.debug("rejected packet %r: %s", packet[:100], e)
            return False
        return True

    def handle_packet(self, datagram: bytes):
        """Split a datagram into metric lines (server.go:806-819)."""
        inj = self.ingest_injector
        if inj is not None:
            for mangled in inj.mangle_packet("ingest.statsd", datagram):
                for line in p.split_lines(mangled):
                    self.handle_metric_packet(line)
            return
        for line in p.split_lines(datagram):
            self.handle_metric_packet(line)

    def handle_ssf_packet(self, datagram: bytes):
        """One UDP datagram = one bare SSFSpan protobuf (server.go:827-860)."""
        try:
            span = wire.parse_ssf(datagram)
        except Exception as e:
            self._packet_errors.add(1)
            log.debug("rejected SSF packet: %s", e)
            return
        self.handle_ssf(span)

    def _shed_spans(self, count: int):
        """Shedding is the designed overload behavior; one warning per
        drop would flood the log (and the GIL) at exactly the moment
        the pipeline is saturated — count every drop (sharded: many
        reader/stream threads shed at once, and each writes its OWN
        cell, so no count is lost and no lock serializes the spike),
        log at most once a second (the timestamp race can at worst
        double-log; the old lock bought nothing more)."""
        self._spans_dropped.add(count)
        dropped = self.spans_dropped
        now = time.monotonic()
        if now - self._last_span_drop_log >= 1.0:
            self._last_span_drop_log = now
            log.warning("dropping spans; span channel is full "
                        "(%d dropped since start)", dropped)

    def handle_ssf(self, span):
        """Route a span to the span workers (server.go:753-792). Spans that
        aren't valid traces but carry metrics still get their metrics
        extracted; fully invalid spans are dropped. Under overload the
        governor sheds raw spans BEFORE the channel (priority tier 2:
        they outlive only freshly-seen series), accounted separately
        from the queue-full drops."""
        if not self.overload.admit_span():
            return
        try:
            self.span_chan.put_nowait(span)
        except queue.Full:
            self._shed_spans(1)

    def handle_ssf_batch(self, spans: list):
        """Batched form of handle_ssf for the native lane: one channel
        hop per decoded batch, shedding counted per span."""
        if not spans:
            return
        if not self.overload.admit_span(len(spans)):
            return
        try:
            self.span_chan.put_nowait(spans)
        except queue.Full:
            self._shed_spans(len(spans))

    def handle_ssf_stream(self, conn):
        """Framed-SSF stream pump; a framing error poisons the stream and
        closes the connection (server.go:862-899)."""
        stream = conn.makefile("rb")
        try:
            while not self._stop.is_set():
                try:
                    span = wire.read_ssf(stream)
                except wire.FramingError as e:
                    self._packet_errors.add(1)
                    log.warning("SSF framing error, closing stream: %s", e)
                    return
                except Exception as e:
                    # a whole frame was consumed, so the stream is at a clean
                    # boundary — keep reading (server.go:888-895)
                    self._packet_errors.add(1)
                    log.debug("bad SSF message: %s", e)
                    continue
                if span is None:
                    return  # lint: ok(silent-drop) clean EOF: read_ssf framed no span, nothing in flight
                self.handle_ssf(span)
        finally:
            try:
                conn.close()
            except OSError:
                pass  # lint: ok(swallowed-exception) socket close is cleanup — every framed span was already handed to handle_ssf

    # -- lifecycle ----------------------------------------------------------

    def start(self):
        """Bring up listeners, span workers and the flush ticker
        (server.go:555-666)."""
        cfg = self.config
        # crash surface: report-then-rethrow on every veneur thread
        # (ConsumePanic, sentry.go:17-52) + a process-wide excepthook
        from veneur_tpu import crash

        if cfg.sentry_dsn:
            self._sentry = crash.SentryReporter(cfg.sentry_dsn)
        crash.install_excepthook(self._sentry)
        self._guard = lambda fn: crash.guarded(fn, self._sentry)
        if cfg.enable_profiling:
            import cProfile

            # cProfile instruments only its own thread, so each guarded
            # veneur thread runs its own profiler; shutdown merges every
            # profile that finished by then (threads still running at
            # dump time are not included)
            self._profiler = cProfile.Profile()
            self._profiler.enable()
            base_guard = self._guard

            def profiled_guard(fn):
                wrapped = base_guard(fn)

                def run(*args, **kwargs):
                    prof = cProfile.Profile()
                    prof.enable()
                    try:
                        return wrapped(*args, **kwargs)
                    finally:
                        prof.disable()
                        with self._profiles_lock:
                            self._thread_profiles.append(prof)
                return run

            self._guard = profiled_guard
            log.info("profiling enabled; stats written on shutdown")
        # warm-restart recovery BEFORE any listener or worker ingests:
        # a valid, fresh checkpoint merges into the (still-empty) store
        # with import semantics and is re-persisted from the merged
        # state; malformed/stale files discard without ever failing
        # startup (persist/checkpoint.py)
        self._started_wall = time.time()
        if self.checkpointer is not None:
            self.checkpointer.restore()
        if self.handoff_manager is not None:
            # sent-but-unacked handoffs spooled by a crashed previous
            # life re-enter the live store (late, never lost)
            self.handoff_manager.recover_spool()

        # shared per-sink ingest lanes: every worker feeds the same lanes,
        # so each sink has one ingest thread and one flush barrier
        span_lanes = make_span_lanes(self.span_sinks, self._stop)
        for _ in range(max(1, cfg.num_span_workers)):
            w = SpanWorker(self.span_sinks, self.span_chan, self._stop,
                           lanes=span_lanes)
            t = threading.Thread(target=self._guard(w.work),
                                 name="span-worker", daemon=True)
            t.start()
            self._span_workers.append(w)
            self._threads.append(t)

        for sink in self.metric_sinks + self.span_sinks:
            sink.start(self.trace_client)

        for addr in cfg.statsd_listen_addresses:
            if self._try_ingest_lanes(addr):
                continue
            if self._try_native_statsd(addr):
                continue
            if self._try_native_tcp(addr):
                continue
            threads, bound = networking.start_statsd(
                addr, max(1, cfg.num_readers), cfg.read_buffer_size_bytes,
                cfg.metric_max_length, self.handle_packet, self._stop,
                handle_tcp_line=self.handle_metric_packet,
                tls_config=self._tls_context,
                admit=lambda: self.overload.admit_packet("statsd"),
                error_log_interval=self.interval,
                receivers=self._udp_receivers)
            self._threads.extend(threads)
            self.statsd_addrs.extend(bound)
        for addr in cfg.ssf_listen_addresses:
            if self._try_native_ssf(addr):
                continue
            threads, bound = networking.start_ssf(
                addr, max(1, cfg.num_readers), cfg.read_buffer_size_bytes,
                cfg.trace_max_length_bytes, self.handle_ssf_packet,
                self.handle_ssf_stream, self._stop,
                admit=lambda: self.overload.admit_packet("ssf"),
                error_log_interval=self.interval,
                receivers=self._udp_receivers)
            self._threads.extend(threads)
            self.ssf_addrs.extend(bound)

        # ops HTTP server; on a global instance it also serves POST /import
        # (server.go:1005-1077, http.go:21-51)
        if cfg.http_address:
            from veneur_tpu.httpserv import OpsServer

            self.ops_server = OpsServer.for_server(self, cfg.http_address)
            if self.handoff_manager is not None:
                # the receiver half: a peer's moved ranges merge here
                # synchronously — the 2xx IS the ack — with the id /
                # epoch guards making retries at-most-once
                mgr = self.handoff_manager
                self.ops_server.add_post_route(
                    "/handoff",
                    lambda headers, body: mgr.handle_handoff(
                        body, headers=headers))
                self.ops_server.add_route("/handoff-status",
                                          mgr.status_route)
            if self.standby_manager is not None:
                # the standby half: the active's retired flush
                # snapshots shadow here until promotion merges them
                sby = self.standby_manager
                self.ops_server.add_post_route(
                    "/replicate",
                    lambda headers, body: sby.handle_replicate(
                        body, headers=headers))
                self.ops_server.add_route("/ha-status", sby.status_route)
            self.ops_server.start()
        # gRPC import ingest (server.go:536-546, importsrv/)
        if cfg.grpc_address:
            from veneur_tpu.forward.grpc_forward import ImportServer

            self.import_server = ImportServer(
                self.store, trace_client=self.trace_client,
                hop_log=self.obs_hops)
            self.import_server.start(cfg.grpc_address)
        # framed-TCP import ingest (framework extension fast lane)
        if cfg.native_import_address:
            from veneur_tpu.forward.native_transport import \
                NativeImportServer

            self.native_import_server = NativeImportServer(self.store)
            self.native_import_server.start(cfg.native_import_address)
        # local → global forwarding client (server.go:626-635)
        if self.forward_fn is None:
            from veneur_tpu.forward import configure_forwarding

            self._forwarder = configure_forwarding(self)

        if self.handoff_manager is not None:
            self._handoff_thread = threading.Thread(
                target=self._guard(
                    lambda: self.handoff_manager.run(self._stop)),
                name="handoff-refresh", daemon=True)
            self._handoff_thread.start()
            self._threads.append(self._handoff_thread)
        if self.standby_manager is not None:
            self._replicator_thread = threading.Thread(
                target=self._guard(
                    lambda: self.standby_manager.run(self._stop)),
                name="ha-replicator", daemon=True)
            self._replicator_thread.start()
            self._threads.append(self._replicator_thread)
        if self.lease_elector is not None:
            self._elector_thread = threading.Thread(
                target=self._guard(
                    lambda: self.lease_elector.run(self._stop)),
                name="lease-elector", daemon=True)
            self._elector_thread.start()
            self._threads.append(self._elector_thread)
        self._flush_thread = threading.Thread(
            target=self._guard(self._flush_loop), name="flush-ticker",
            daemon=True)
        self._flush_thread.start()
        if self.checkpointer is not None:
            self._ckpt_thread = threading.Thread(
                target=self._guard(
                    lambda: self.checkpointer.run(self._stop)),
                name="checkpoint", daemon=True)
            self._ckpt_thread.start()
            log.info("checkpointing to %s every %.1fs",
                     self.checkpointer.path, self.checkpointer.interval_s)
        log.info("veneur server started (role=%s, interval=%.1fs)",
                 "local" if self.is_local() else "global", self.interval)

    def _flush_loop(self):
        """Interval ticker, optionally aligned to wall-clock interval
        boundaries (server.go:638-665)."""
        if self.config.synchronize_with_interval:
            delay = calculate_tick_delay(self.interval, time.time())
            if self._stop.wait(delay):
                return
        while not self._stop.is_set():
            # tickers fire *after* the interval elapses (server.go:643-665)
            start = time.time()
            if self._stop.wait(self.interval):
                return
            try:
                self.flush()
            except Exception:
                log.exception("flush failed")
            flush_took = (time.time() - start) - self.interval
            if flush_took > self.interval:
                log.warning("flush took %.2fs, %.2fs longer than the interval",
                            flush_took, flush_took - self.interval)

    def _try_ingest_lanes(self, addr_spec: str) -> bool:
        """Bring up the sharded ingest-lane fleet for a UDP statsd
        listener (veneur_tpu/ingest/): per-reader lock-free lanes —
        SO_REUSEPORT socket, recvmmsg batches, native parse, lane-local
        intern + columnar staging — merged into the store one chunk at
        a time at the group boundary. The DEFAULT UDP ingest path
        (``ingest_lanes: 0`` = one lane per reader); ``-1`` disables
        and falls through to the legacy readers."""
        cfg = self.config
        if cfg.ingest_lanes < 0:
            return False
        from veneur_tpu.protocol.addr import resolve_addr

        try:
            resolved = resolve_addr(addr_spec)
        except ValueError:
            return False
        if resolved.family != "udp":
            return False
        num_lanes = cfg.ingest_lanes or max(1, cfg.num_readers)
        from veneur_tpu.ingest import IngestFleet

        networking.warn_if_port_already_served(
            resolved.socket_family, socket.SOCK_DGRAM,
            resolved.host, resolved.port)
        try:
            fleet = IngestFleet(
                self.store, resolved, num_lanes,
                cfg.read_buffer_size_bytes, cfg.metric_max_length,
                chunk_records=cfg.store_chunk, stop=self._stop,
                overload=self.overload,
                raw_handler=self.handle_metric_packet,
                thread_wrap=self._guard,
                limiter=networking._LogLimiter(self.interval),
                trace_stages=bool(cfg.obs_enabled))
        except OSError as e:
            log.warning("ingest lanes failed to bind (%s); falling back "
                        "to the legacy readers", e)
            return False
        fleet.start()
        self._ingest_fleets.append(fleet)
        if self.ingest_fleet is None:
            self.ingest_fleet = fleet
        # sealed-but-unmerged chunks must reach checkpoints: every
        # fleet drains before a snapshot
        fleets = list(self._ingest_fleets)
        self.store.set_ingest_drain(
            lambda: [f.merge_sealed() for f in fleets])
        # one entry per LISTENER (every lane REUSEPORTs the same
        # address), matching the legacy paths' bookkeeping
        self.statsd_addrs.append(fleet.bound[0])
        log.info("ingest fleet on udp port %s: %d lanes (native "
                 "decode=%s, recvmmsg=%s)", fleet.bound[0][1], num_lanes,
                 fleet.lanes[0].using_native,
                 fleet.lanes[0]._receiver.using_recvmmsg)
        return True

    def _try_native_statsd(self, addr_spec: str) -> bool:
        """Bring up the C++ SO_REUSEPORT reader pool for a plain IPv4 UDP
        listener (socket_linux.go:12-76 + networking.go:37-87 rebuilt
        native); returns False to fall back to the Python readers."""
        cfg = self.config
        if not cfg.native_ingest:
            return False
        from veneur_tpu.protocol.addr import resolve_addr

        try:
            resolved = resolve_addr(addr_spec)
        except ValueError:
            return False
        if (resolved.family != "udp" or resolved.scheme.endswith("6")
                or ":" in (resolved.host or "")):
            return False  # the native pool is AF_INET only
        from veneur_tpu import native

        if not native.available():
            return False
        # same accidental-second-instance probe every other
        # SO_REUSEPORT listener gets (networking.py)
        from veneur_tpu.networking import warn_if_port_already_served

        warn_if_port_already_served(socket.AF_INET, socket.SOCK_DGRAM,
                                    resolved.host or "0.0.0.0",
                                    resolved.port)
        try:
            reader = native.NativeUDPReader(
                host=resolved.host or "0.0.0.0", port=resolved.port,
                num_readers=max(1, cfg.num_readers),
                rcvbuf=cfg.read_buffer_size_bytes,
                dgram_max=cfg.metric_max_length)
        except OSError as e:
            log.warning("native UDP readers failed (%s); using Python "
                        "readers", e)
            return False
        self._native_readers.append(reader)
        self.statsd_addrs.append((resolved.host or "0.0.0.0", reader.port))
        t = threading.Thread(target=self._guard(self._native_pump),
                             args=(reader,), name="native-udp-pump",
                             daemon=True)
        t.start()
        self._native_pumps.append(t)
        log.info("native ingest on udp port %d (%d readers)", reader.port,
                 reader.num_readers)
        return True

    def _try_native_tcp(self, addr_spec: str) -> bool:
        """Bring up the C++ TCP/TLS statsd listener for a plain IPv4
        TCP address: accept, TLS handshake (libssl via the stable C
        ABI), newline framing and parsing all run off the GIL — the
        fix for the Python TLS accept path topping out under the
        reference's ~700 conn/s localhost claim (README.md:346).
        Returns False to fall back to the Python readers (e.g. no
        libssl at runtime, IPv6, or a resolve failure)."""
        cfg = self.config
        if not cfg.native_ingest:
            return False
        from veneur_tpu.protocol.addr import resolve_addr

        try:
            resolved = resolve_addr(addr_spec)
        except ValueError:
            return False
        if (resolved.family != "tcp" or resolved.scheme.endswith("6")
                or ":" in (resolved.host or "")):
            return False
        from veneur_tpu import native

        if not native.available():
            return False
        use_tls = bool(cfg.tls_certificate and cfg.tls_key)
        if use_tls and not native.tls_available():
            return False
        from veneur_tpu.networking import warn_if_port_already_served

        warn_if_port_already_served(socket.AF_INET, socket.SOCK_STREAM,
                                    resolved.host or "0.0.0.0",
                                    resolved.port)
        try:
            reader = native.NativeTLSReader(
                host=resolved.host or "0.0.0.0", port=resolved.port,
                cert_path=cfg.tls_certificate if use_tls else "",
                key_path=cfg.tls_key if use_tls else "",
                ca_path=cfg.tls_authority_certificate if use_tls else "",
                max_line=cfg.metric_max_length)
        except (OSError, RuntimeError) as e:
            log.warning("native TCP/TLS listener failed (%s); using "
                        "Python readers", e)
            return False
        self._native_readers.append(reader)
        self.statsd_addrs.append((resolved.host or "0.0.0.0", reader.port))
        t = threading.Thread(target=self._guard(self._native_pump),
                             args=(reader,), name="native-tcp-pump",
                             daemon=True)
        t.start()
        self._native_pumps.append(t)
        log.info("native %s statsd listener on tcp port %d",
                 "TLS" if use_tls else "plaintext", reader.port)
        return True

    def _try_native_ssf(self, addr_spec: str) -> bool:
        """Bring up the C++ SSF reader pool for a plain IPv4 UDP SSF
        listener: datagrams decode as SSFSpan protobufs ON the C++
        reader threads (off the GIL) and their embedded metrics arrive
        as parsed records for the vectorized store path — the span
        twin of the metric lane (round-4 verdict item #5; reference
        path server.go:827-860). Returns False to fall back to the
        Python readers."""
        cfg = self.config
        if not cfg.native_ingest:
            return False
        from veneur_tpu.protocol.addr import resolve_addr

        try:
            resolved = resolve_addr(addr_spec)
        except ValueError:
            return False
        if (resolved.family != "udp" or resolved.scheme.endswith("6")
                or ":" in (resolved.host or "")):
            return False
        from veneur_tpu import native

        if not native.available():
            return False
        from veneur_tpu.networking import warn_if_port_already_served

        warn_if_port_already_served(socket.AF_INET, socket.SOCK_DGRAM,
                                    resolved.host or "0.0.0.0",
                                    resolved.port)
        try:
            reader = native.NativeSSFReader(
                host=resolved.host or "0.0.0.0", port=resolved.port,
                num_readers=max(1, cfg.num_readers),
                rcvbuf=cfg.read_buffer_size_bytes,
                dgram_max=cfg.trace_max_length_bytes,
                indicator_timer_name=cfg.indicator_span_timer_name)
        except OSError as e:
            log.warning("native SSF readers failed (%s); using Python "
                        "readers", e)
            return False
        self._native_readers.append(reader)
        self._native_ssf_readers.append(reader)
        self.ssf_addrs.append((resolved.host or "0.0.0.0", reader.port))
        t = threading.Thread(target=self._guard(self._native_ssf_pump),
                             args=(reader,), name="native-ssf-pump",
                             daemon=True)
        t.start()
        self._native_pumps.append(t)
        log.info("native SSF ingest on udp port %d (%d readers)",
                 reader.port, reader.num_readers)
        return True

    def _native_ssf_pump(self, reader):
        """Drain decoded span batches: embedded metrics ride the
        vectorized store path, spans go to the span workers as lazy
        facades (full protobuf only materialized for sinks that read
        cold fields), slow-lane samples (STATUS/undecodable) re-enter
        the Python parser."""
        from veneur_tpu.protocol.gen.ssf import sample_pb2

        last_drops = 0
        while not self._stop.is_set():
            try:
                batches = reader.drain()
                drops = reader.drops()
                if drops != last_drops:
                    with self._counter_lock:
                        self.packet_drops += drops - last_drops
                    log.warning("native SSF ingest dropped %d datagrams "
                                "(pump falling behind)",
                                drops - last_drops)
                    last_drops = drops
                if not batches:
                    self._stop.wait(0.005)
                    continue  # lint: ok(silent-drop) idle poll: the reader decoded no batches, nothing in flight
                for b in batches:
                    if b.decode_errors or b.invalid_samples:
                        self._packet_errors.add(int(b.decode_errors)
                                                + int(b.invalid_samples))
                    if b.metrics.count:
                        for line in self.store.process_batch(b.metrics):
                            self.handle_metric_packet(line)
                    for raw in b.slow_samples:
                        try:
                            sample = sample_pb2.SSFSample()
                            sample.ParseFromString(raw)
                            m = p.parse_metric_ssf(sample)
                            if p.valid_metric(m):
                                self.store.process_metric(m)
                        except p.QuarantineError as e:
                            # SSF-borne poison is accounted load, not
                            # noise — same ledger as the statsd lane
                            self.quarantine.count(e.reason)
                        except Exception:
                            self._packet_errors.add(1)
                    self.handle_ssf_batch(b.spans())
            except Exception:
                log.exception("native SSF pump iteration failed")
                self._stop.wait(0.05)

    def _native_pump(self, reader):
        """Drain the reader pool's parsed batches into the store; raw
        event/service-check records re-enter the Python parse path."""
        last_drops = 0
        while not self._stop.is_set():
            try:
                batches = reader.drain()
                drops = reader.drops()
                if drops != last_drops:
                    with self._counter_lock:
                        self.packet_drops += drops - last_drops
                    log.warning("native ingest dropped %d datagrams "
                                "(pump falling behind)", drops - last_drops)
                    last_drops = drops
                if not batches:
                    self._stop.wait(0.005)
                    continue  # lint: ok(silent-drop) idle poll: the reader decoded no batches, nothing in flight
                for b in batches:
                    self._packet_errors.add(int(b.parse_errors))
                    for line in self.store.process_batch(b):
                        self.handle_metric_packet(line)
            except Exception:
                # one bad batch must not kill the sole ingest thread
                log.exception("native pump iteration failed")
                self._stop.wait(0.05)

    def flush(self):
        """One flush pass; see veneur_tpu.flusher."""
        from veneur_tpu.flusher import flush_once

        flush_once(self)

    # -- flush-staleness readiness -----------------------------------------

    def flush_age_seconds(self) -> float:
        """Seconds since the last SUCCESSFUL flush (since start() before
        the first one) — what an orchestrator's readiness probe and the
        ``veneur.flush.age_seconds`` self-metric read."""
        base = self.last_flush_time or self._started_wall
        return max(0.0, time.time() - base)

    def readiness(self) -> tuple:
        """(ready, age_seconds, limit_seconds): the ONE place the
        flush-staleness policy lives — ready while the last successful
        flush is no older than 2x the interval. A wedged flush loop
        (hung device program, deadlocked sink) goes unready here while
        /healthcheck (liveness) stays ok, so an orchestrator routes
        away without killing the process."""
        age = self.flush_age_seconds()
        limit = 2.0 * self.interval
        return age <= limit, age, limit

    def is_ready(self) -> bool:
        return self.readiness()[0]

    def degradation(self) -> list:
        """Human-readable active degradations, [] when fully healthy.
        Degraded is NOT unready — a shedding-but-flushing instance must
        keep taking traffic (killing it would dogpile its peers) — so
        this rides the readiness body and /debug/vars instead of the
        status code."""
        out = []
        level = self.overload.level()
        if level > 0:
            out.append(f"overload level {level} "
                       f"(pressure {self.overload.pressure():.2f})")
        compute = getattr(self.store, "compute", None)
        if compute is not None:
            for kernel, gauge in compute.states():
                if gauge:
                    state = "half-open" if gauge == 1.0 else "open"
                    out.append(f"compute breaker {kernel} {state} "
                               f"(flush on XLA fallback)")
        # disk-refused persistence: the instance keeps aggregating and
        # flushing (degraded, NOT unready — killing it would lose the
        # very state the disk can no longer protect), but operators
        # must see crash protection is gone and why
        ckpt = self.checkpointer
        if ckpt is not None and ckpt.last_error:
            out.append(f"checkpoint writes failing ({ckpt.last_error})")
        mgr = self.handoff_manager
        if mgr is not None and mgr.last_spool_error:
            out.append(f"handoff spool writes failing "
                       f"({mgr.last_spool_error})")
        # HA replication failing means the standby's takeover window is
        # widening past one flush interval — degraded, not unready (the
        # active still aggregates and flushes)
        sby = self.standby_manager
        if sby is not None and sby.is_leader and sby.last_error:
            out.append(f"standby replication failing ({sby.last_error})")
        elector = self.lease_elector
        if elector is not None and elector.last_error:
            out.append(f"lease renewal failing ({elector.last_error})")
        return out

    # keys whose change a live reload cannot honor: sockets stay bound
    # (SO_REUSEPORT makes a rolling restart the path for these) and the
    # store's device geometry is allocated once
    _RELOAD_FROZEN = ("statsd_listen_addresses", "ssf_listen_addresses",
                      "ingest_lanes", "http_address", "grpc_address",
                      "native_import_address", "tls_certificate",
                      "tls_key", "tls_authority_certificate",
                      "digest_storage", "digest_dtype", "slab_rows",
                      # the pipeline depth is stamped onto the store and
                      # re-stamped onto every generation twin at swap;
                      # streaming off mid-run would also strand sinks'
                      # parked chunk-requeue bodies (their one retry
                      # fires from the stream workers)
                      "flush_pipeline_depth", "flush_streaming",
                      "tier_pool_centroids", "tier_promote_samples",
                      "tier_promote_intervals", "tier_demote_intervals",
                      "tdigest_compression", "hll_precision",
                      "mesh_enabled", "mesh_hosts",
                      "store_initial_capacity", "store_chunk",
                      "span_channel_capacity", "num_span_workers",
                      "enable_profiling", "sentry_dsn",
                      # the checkpointer binds its path/cadence at
                      # construction (its thread is already running)
                      "checkpoint_path", "checkpoint_interval",
                      "checkpoint_max_age_intervals",
                      # the standby manager and lease elector bind their
                      # peers/backend at construction (threads running);
                      # a file:// standby_peers list IS live-reloadable
                      # through the file itself
                      "standby_peers", "standby_shadow_epochs",
                      "lease_path", "lease_ttl", "lease_renew_interval",
                      # overload plumbing is stamped onto live groups and
                      # the attached controller at construction
                      "max_series", "max_tag_length",
                      "overload_low_watermark", "overload_high_watermark",
                      "overload_hard_watermark",
                      "compute_breaker_failure_threshold",
                      "compute_breaker_reset_timeout")

    def reload(self, config: "Config"):
        """SIGHUP graceful reload (the reference's HUP path,
        server.go:1048-1076): re-read config, rebuild the config-driven
        sinks/plugins and the forwarding client, pick up interval /
        percentiles / aggregates / tags — WITHOUT dropping sockets or
        store state. Frozen keys (listeners, TLS, store geometry) log a
        warning and keep their old values. Serialized: overlapping
        SIGHUPs apply one at a time, last one wins."""
        with self._reload_lock:
            self._reload_locked(config)

    def _reload_locked(self, config: "Config"):
        config.apply_defaults()
        for key in self._RELOAD_FROZEN:
            old, new = getattr(self.config, key), getattr(config, key)
            if old != new:
                log.warning("reload cannot change %r (%r -> %r); keeping "
                            "the old value — restart to apply", key, old,
                            new)
                setattr(config, key, old)
        if bool(config.forward_address) != bool(self.config.forward_address):
            log.warning("reload cannot change the instance ROLE "
                        "(local<->global); keeping forward_address=%r",
                        self.config.forward_address)
            config.forward_address = self.config.forward_address

        from veneur_tpu.sinks.factory import (create_sinks,
                                              span_sinks_configured)

        if span_sinks_configured(config) or span_sinks_configured(
                self.config):
            # span sinks are embedded in the running span-worker lanes;
            # swapping them live would strand queued spans — checked via
            # the config predicate, never by constructing throwaway
            # producers
            log.warning("reload keeps the existing span sinks (span "
                        "lanes rebuild only on restart)")

        # the previous reload's retired sinks have had >= one interval
        # to finish their in-flight flush threads; close them now
        self._close_retired_sinks()
        old_cfg_sinks = [s for s in self.metric_sinks
                         if s not in self._injected_metric_sinks]
        old_forwarder = self._forwarder
        cfg_metric_sinks, _, cfg_plugins = create_sinks(config)
        for sink in cfg_metric_sinks:
            try:
                sink.start(self.trace_client)
            except Exception:
                log.exception("sink %s failed to start after reload",
                              getattr(sink, "name", sink))
        self.config = config
        self.interval = parse_duration(config.interval)
        self.hostname = config.hostname
        self.tags = list(config.tags)
        self.tags_exclude = set(config.tags_exclude)
        self.histogram_percentiles = list(config.percentiles)
        self.histogram_aggregates = HistogramAggregates.from_names(
            config.aggregates)
        # new sink set takes effect next flush; in-flight flush threads
        # hold references to the old list, which stays valid — the old
        # sinks close on the NEXT reload (or shutdown), after their
        # flushes finished
        self.metric_sinks = self._injected_metric_sinks + cfg_metric_sinks
        self._retired_sinks = old_cfg_sinks
        self.plugins = cfg_plugins
        self._warned_no_forward = False
        if self.is_local():
            from veneur_tpu.forward import configure_forwarding

            self.forward_fn = None
            self._forwarder = configure_forwarding(self)
        if old_forwarder is not None and old_forwarder is not self._forwarder \
                and hasattr(old_forwarder, "close"):
            old_forwarder.close()
        log.info("config reloaded: %d metric sinks, %d plugins, "
                 "interval=%.1fs", len(self.metric_sinks),
                 len(self.plugins), self.interval)

    def _close_retired_sinks(self):
        for sink in self._retired_sinks:
            close = getattr(sink, "close", None)
            if close is None:
                continue
            try:
                close()
            except Exception:
                log.exception("retired sink %s close failed",
                              getattr(sink, "name", sink))
        self._retired_sinks = []

    def shutdown(self):
        """Graceful stop: quiesce ingest, drain one final flush so the
        current interval's data reaches the sinks, then tear down
        (server.go:1120-1130; the final drain is this framework's
        equivalent of the reference's graceful-restart guarantee that at
        most one interval is ever lost)."""
        self._stop.set()
        # pump threads must be fully dead before the reader pool is
        # freed AND before the final flush: a pump blocked inside
        # process_batch (e.g. a first-use device compile) can outlive a
        # short join, write records into the store after the flush reset,
        # and race vt_reader_stop freeing batches it still reads
        deadline = time.time() + 30.0  # one shared bound, not per pump
        pumps_dead = True
        for t in self._native_pumps:
            t.join(timeout=max(0.0, deadline - time.time()))
            if t.is_alive():
                pumps_dead = False
                log.warning("native pump %s did not exit in time", t.name)
        if pumps_dead:
            for reader in self._native_readers:
                reader.stop()
        else:
            # a stuck pump may still be reading pool batches: leak the
            # pool (and disarm its GC finalizer) rather than free memory
            # a live thread uses. The final flush below is still safe —
            # the store lock serializes it against process_batch — but
            # records the pump lands after the reset die with the
            # process (bounded loss, like any restart).
            log.warning("leaving native reader pool allocated (pump alive)")
            for reader in self._native_readers:
                reader.leak()
        # ingest lanes quiesce before the final flush: lane threads
        # seal their staged residue on exit and the fleet's final merge
        # folds every sealed chunk into the store — accepted samples
        # ride the last interval out instead of dying in staging
        for fleet in self._ingest_fleets:
            try:
                fleet.shutdown()
            except Exception:
                log.exception("ingest fleet shutdown failed")
        # the ticker must finish any in-flight flush before the final
        # drain runs, or two passes would drain the store concurrently
        if self._flush_thread is not None:
            self._flush_thread.join(timeout=5.0)
        # the checkpoint writer too: a snapshot in flight across the
        # final flush would either lose the epoch race (wasted) or
        # resurrect a post-flush file the clean shutdown then fails to
        # truncate
        if self._ckpt_thread is not None:
            self._ckpt_thread.join(timeout=10.0)
        # an in-flight handoff must finish (stream or requeue) before
        # the final flush, or a SIGTERM mid-resize would drain the
        # store while the moved ranges are still in the manager's
        # hands — they would miss this life's final emission. JOIN the
        # refresh thread first: quiesce alone is check-then-act — a
        # refresh blocked in discovery I/O when _stop was set could
        # still START a transition after quiesce returned
        if self.handoff_manager is not None:
            t = getattr(self, "_handoff_thread", None)
            if t is not None:
                t.join(timeout=30.0)
            if (t is not None and t.is_alive()) or \
                    not self.handoff_manager.quiesce(timeout=30.0):
                log.warning("handoff still in flight at shutdown; its "
                            "spool will recover on the next start")
        # hand the lease back BEFORE the final flush: a standby promotes
        # on its next poll instead of waiting out the ttl (a CRASH skips
        # this by definition — crash_stop never releases)
        for t in (getattr(self, "_elector_thread", None),
                  getattr(self, "_replicator_thread", None)):
            if t is not None:
                t.join(timeout=10.0)
        if self.lease_elector is not None:
            self.lease_elector.release()
        try:
            self.flush()
        except Exception:
            log.exception("final flush failed")
        if self._profiler is not None:
            import pstats

            self._profiler.disable()
            path = "veneur-profile.pstats"
            stats = pstats.Stats(self._profiler)
            with self._profiles_lock:
                for prof in self._thread_profiles:
                    stats.add(prof)
            stats.dump_stats(path)
            log.info("profile written to %s (%d thread profiles merged)",
                     path, len(self._thread_profiles))
            self._profiler = None
            self._thread_profiles = []
        if self.ops_server is not None:
            self.ops_server.stop()
        if self.import_server is not None:
            self.import_server.stop()
        if self.native_import_server is not None:
            self.native_import_server.stop()
        if self._forwarder is not None and hasattr(self._forwarder, "close"):
            self._forwarder.close()
        self._close_retired_sinks()
        self.trace_client.close()

    def crash_stop(self):
        """Abandon the process state WITHOUT the graceful drain: no
        final flush, no checkpoint truncation, no handoff quiesce —
        the in-process twin of SIGKILL for the soak plane
        (veneur_tpu/soak/), where a restart on the same
        ``checkpoint_path`` must recover exactly what the last
        checkpoint/spool committed and nothing else. Threads are still
        joined and sockets closed (a soak restarts hundreds of times
        in one process; leaking them would measure the harness, not
        the server), but none of the data-saving steps run: whatever
        only lived in this store dies here, like a real kill."""
        self._stop.set()
        deadline = time.time() + 30.0
        pumps_dead = True
        for t in self._native_pumps:
            t.join(timeout=max(0.0, deadline - time.time()))
            if t.is_alive():
                pumps_dead = False
        if pumps_dead:
            for reader in self._native_readers:
                reader.stop()
        else:  # pragma: no cover - wedged-pump path
            for reader in self._native_readers:
                reader.leak()
        for fleet in self._ingest_fleets:
            try:
                fleet.shutdown()
            except Exception:
                log.exception("ingest fleet shutdown failed in "
                              "crash_stop")
        # the lease is deliberately NOT released: a crash must make the
        # standby wait out the ttl, exactly like a real SIGKILL
        for t in (self._flush_thread, self._ckpt_thread,
                  getattr(self, "_handoff_thread", None),
                  getattr(self, "_replicator_thread", None),
                  getattr(self, "_elector_thread", None)):
            if t is not None:
                t.join(timeout=10.0)
        if self.ops_server is not None:
            self.ops_server.stop()
        if self.import_server is not None:
            self.import_server.stop()
        if self.native_import_server is not None:
            self.native_import_server.stop()
        if self._forwarder is not None and hasattr(self._forwarder, "close"):
            self._forwarder.close()
        self._close_retired_sinks()
        self.trace_client.close()

"""Egress: metric sinks and span sinks.

Mirrors ``/root/reference/sinks/sinks.go``: metric sinks receive the full
``[]InterMetric`` batch once per flush; span sinks ingest spans as they
arrive and flush periodically.
"""

from .base import MetricSink, SpanSink, is_acceptable_metric
from .blackhole import BlackholeMetricSink, BlackholeSpanSink
from .channel import ChannelMetricSink, ChannelSpanSink
from .debug import DebugMetricSink, DebugSpanSink
from .ssfmetrics import MetricExtractionSink

__all__ = [
    "MetricSink",
    "SpanSink",
    "is_acceptable_metric",
    "BlackholeMetricSink",
    "BlackholeSpanSink",
    "ChannelMetricSink",
    "ChannelSpanSink",
    "DebugMetricSink",
    "DebugSpanSink",
    "MetricExtractionSink",
]

"""Sink interfaces (cf. /root/reference/sinks/sinks.go:31-97)."""

from __future__ import annotations

import abc
from typing import Iterable, List

from veneur_tpu.samplers.intermetric import InterMetric

# Shared self-telemetry metric names (sinks.go:12-29,59-83)
METRIC_KEY_TOTAL_SPANS_FLUSHED = "sink.spans_flushed_total"
METRIC_KEY_TOTAL_SPANS_DROPPED = "sink.spans_dropped_total"
METRIC_KEY_TOTAL_METRICS_FLUSHED = "sink.metrics_flushed_total"
METRIC_KEY_TOTAL_METRICS_DROPPED = "sink.metrics_dropped_total"


class MetricSink(abc.ABC):
    """A backend receiving the full flushed-metric batch every interval."""

    # the current interval's egress budget, set by the flusher before the
    # sink's flush thread starts; retry loops clamp their backoff to it
    # so no sink can push a flush past the interval boundary
    # (veneur_tpu/resilience/deadline.py)
    flush_deadline = None

    @property
    @abc.abstractmethod
    def name(self) -> str: ...

    def start(self, trace_client=None) -> None:
        """Called once at server start."""

    def set_flush_deadline(self, deadline) -> None:
        self.flush_deadline = deadline

    @abc.abstractmethod
    def flush(self, metrics: List[InterMetric]) -> None: ...

    def flush_other_samples(self, samples: Iterable) -> None:
        """Receive non-metric samples (events, ...); default: drop."""


class SpanSink(abc.ABC):
    """A backend receiving SSF spans as they arrive (sinks.go:85-97)."""

    @property
    @abc.abstractmethod
    def name(self) -> str: ...

    def start(self, trace_client=None) -> None: ...

    @abc.abstractmethod
    def ingest(self, span) -> None: ...

    def flush(self) -> None: ...


def is_acceptable_metric(metric: InterMetric, sink_name: str) -> bool:
    """Routing check for veneursinkonly: tags (sinks.go:50-56)."""
    return metric.is_acceptable_to(sink_name)


def filter_acceptable(metrics: List[InterMetric],
                      sink_name: str) -> List[InterMetric]:
    return [m for m in metrics if m.is_acceptable_to(sink_name)]

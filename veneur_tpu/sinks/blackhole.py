"""No-op sinks, used as test defaults (cf. /root/reference/sinks/blackhole)."""

from __future__ import annotations

from .base import MetricSink, SpanSink


class BlackholeMetricSink(MetricSink):
    @property
    def name(self) -> str:
        return "blackhole"

    def flush(self, metrics) -> None:
        pass

    def flush_columnar(self, batch) -> None:
        pass

    def flush_other_samples(self, samples) -> None:
        pass


class BlackholeSpanSink(SpanSink):
    @property
    def name(self) -> str:
        return "blackhole"

    def ingest(self, span) -> None:
        pass

    def flush(self) -> None:
        pass

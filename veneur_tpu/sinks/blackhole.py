"""No-op sinks, used as test defaults (cf. /root/reference/sinks/blackhole)."""

from __future__ import annotations

from .base import MetricSink, SpanSink


class BlackholeMetricSink(MetricSink):
    def __init__(self):
        self.chunk_rows_acked = 0
        self.chunks_flushed = 0

    @property
    def name(self) -> str:
        return "blackhole"

    def flush(self, metrics) -> None:
        pass

    def flush_columnar(self, batch) -> None:
        pass

    def flush_chunk(self, chunk) -> None:
        """Streaming egress no-op: every chunk row acks instantly (the
        counters keep the conservation tests honest)."""
        self.chunks_flushed += 1
        self.chunk_rows_acked += chunk.rows

    def flush_other_samples(self, samples) -> None:
        pass


class BlackholeSpanSink(SpanSink):
    @property
    def name(self) -> str:
        return "blackhole"

    def ingest(self, span) -> None:
        pass

    def flush(self) -> None:
        pass

"""Queue-backed sinks for test assertions (cf. channelMetricSink,
/root/reference/server_test.go:170-200)."""

from __future__ import annotations

import queue
from typing import List

from .base import MetricSink, SpanSink


class ChannelMetricSink(MetricSink):
    """Delivers each flush batch to a queue the test can drain."""

    def __init__(self, maxsize: int = 0):
        self.queue: "queue.Queue[List]" = queue.Queue(maxsize)

    @property
    def name(self) -> str:
        return "channel"

    def flush(self, metrics) -> None:
        self.queue.put(list(metrics))

    def get_flush(self, timeout: float = 30.0):
        # generous default: the flush that feeds this sink may be paying
        # a first-use jit compile, which can exceed 5s on a loaded host
        return self.queue.get(timeout=timeout)


class ChannelSpanSink(SpanSink):
    def __init__(self, maxsize: int = 0):
        self.queue: "queue.Queue" = queue.Queue(maxsize)
        self.flushes = 0

    @property
    def name(self) -> str:
        return "channel"

    def ingest(self, span) -> None:
        self.queue.put(span)

    def flush(self) -> None:
        self.flushes += 1

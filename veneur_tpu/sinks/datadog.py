"""Datadog sinks: series/check/event metric sink + trace-agent span sink.

Behavioral port of ``/root/reference/sinks/datadog/datadog.go``:

- ``DatadogMetricSink.flush`` finalizes InterMetrics (magic ``host:`` /
  ``device:`` tags, counters→rates, status→service check;
  datadog.go:245-322) and POSTs them to ``/api/v1/series`` in
  approximately equal chunks of ≤ ``flush_max_per_body``, in parallel
  (datadog.go:324-330). Service checks go to ``/api/v1/check_run``
  uncompressed; DogStatsD events arrive via ``flush_other_samples`` and
  go to ``/intake`` (datadog.go:155-243).
- ``DatadogSpanSink`` keeps the newest ``buffer_size`` spans in a ring
  (datadog.go:387-397), and each flush groups them by trace id and PUTs
  ``[[span…]…]`` to the trace agent's ``/v0.3/traces`` (datadog.go:460-530).

Transport is injectable (``post``) so tests run against a local fixture,
the role ``httptest.Server`` plays in the reference's tests
(datadog_test.go).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from veneur_tpu.forward.http_forward import post_helper
from veneur_tpu.protocol import constants as dogstatsd
from veneur_tpu.protocol import wire
from veneur_tpu.resilience import RetryPolicy, post_with_retry
from veneur_tpu.samplers.intermetric import InterMetric, MetricType
from veneur_tpu.sinks.base import MetricSink, SpanSink

log = logging.getLogger("veneur.sinks.datadog")

DATADOG_NAME_KEY = "name"
DATADOG_RESOURCE_KEY = "resource"
DATADOG_SPAN_TYPE = "web"

# post(url, payload, compress, method) -> status
PostFn = Callable[..., int]


def _default_post(url: str, payload, compress: bool = True,
                  method: str = "POST", precompressed: bool = False,
                  out_info: dict = None) -> int:
    return post_helper(url, payload, compress=compress, method=method,
                       precompressed=precompressed, out_info=out_info)


def _ok(status: int) -> bool:
    """Success statuses per the reference's PostHelper
    (http/http.go:230-236): 200 or 202."""
    return status in (200, 202)


def _body_rows(n: int, max_per_body: int, n_bodies: int) -> list:
    """Per-body emission counts for one block's serialized bodies: the
    native serializer (veneur_egress.cpp vt_dd_series_json) closes a
    body at exactly ``max_per_body`` emissions, so every body holds
    max_per_body rows except the last — the split the per-chunk
    conservation accounting relies on."""
    if n_bodies <= 1:
        return [n]
    return [max_per_body] * (n_bodies - 1) + \
        [n - max_per_body * (n_bodies - 1)]


class DatadogMetricSink(MetricSink):
    """Flushes InterMetrics to the Datadog v1 series API
    (datadog.go:34-357)."""

    def __init__(self, interval: float, flush_max_per_body: int,
                 hostname: str, tags: Sequence[str], dd_hostname: str,
                 api_key: str, post: Optional[PostFn] = None,
                 compress_level: int = 1,
                 retry_policy: Optional[RetryPolicy] = None,
                 breaker=None, fault_injector=None,
                 requeue_max_bytes: int = 32 * 1048576):
        self.interval = interval
        self.flush_max_per_body = max(1, flush_max_per_body)
        self.hostname = hostname
        self.tags = list(tags)
        self.dd_hostname = dd_hostname.rstrip("/")
        self.api_key = api_key
        self.post = post or _default_post
        if fault_injector is not None:
            self.post = fault_injector.wrap_post(self.post, "sink.datadog")
        # resilience: transport errors and 5xx retry with backoff inside
        # the flush deadline the flusher sets each interval; a
        # black-holed API endpoint trips the breaker and is rejected
        # instantly until its half-open probe succeeds
        self.retry_policy = retry_policy or RetryPolicy()
        self.breaker = breaker
        self.retries = 0
        # deflate level for the native columnar serializer (level 1 runs
        # ~2x the throughput of zlib's default 6 at a ~12% ratio cost —
        # the single-core deflate IS the large-flush bottleneck)
        self.compress_level = compress_level
        self.metrics_flushed = 0
        self.flush_errors = 0
        self._common_json: Optional[bytes] = None
        # _flush_part runs on one thread per chunk; guard the counter
        self._err_lock = threading.Lock()
        # streaming egress (core/pipeline.py ChunkStream): serialized-
        # but-unacked chunk bodies park here and retry once per
        # interval until acked, bounded by a BYTES budget
        # (config sink_requeue_max_bytes) — per-chunk conservation:
        # every emission row is acked, pending requeue, or (evicted
        # past the budget) counted dropped. The budget evicts OLDEST
        # first: under a long outage the buffer stays fresh and the
        # loss is the counted old tail, never unbounded host growth.
        self._requeued: deque = deque()
        self.requeue_max_bytes = max(0, requeue_max_bytes)
        self.requeue_max_bodies = 256  # belt-and-braces count bound
        self._requeued_bytes = 0
        self._last_repost_ts = None
        self.chunks_flushed = 0
        self.chunks_requeued_total = 0
        self.chunk_rows_acked = 0
        self.chunk_rows_requeued = 0
        self.chunk_rows_dropped = 0
        # ("marshal_s"|"post_s"|"content_length_bytes", value) pairs the
        # flusher drains into the canonical veneur.flush.* self-metrics
        # (duration_ns part tags + content_length_bytes, README.md:260-264)
        self._telemetry: List = []

    def _count_error(self) -> None:
        with self._err_lock:
            self.flush_errors += 1

    def _count_retry(self, retry_index, exc, pause) -> None:
        with self._err_lock:
            self.retries += 1

    def _resilient_post(self, call) -> int:
        """Run a POST closure under the shared retry loop (transport
        errors and 5xx/429, backoff clamped to the flush deadline) and
        the destination breaker. An open breaker raises OSError so call
        sites count it through their existing error path."""
        from veneur_tpu.resilience import is_transient_status

        if self.breaker is not None and not self.breaker.allow():
            raise OSError("datadog circuit breaker open")
        try:
            status = post_with_retry(call, self.retry_policy,
                                     deadline=self.flush_deadline,
                                     on_retry=self._count_retry)
        except OSError:
            if self.breaker is not None:
                self.breaker.record_failure()
            raise
        if self.breaker is not None:
            # a 4xx still proves the destination is alive; only
            # transient statuses count toward tripping the breaker
            if is_transient_status(status):
                self.breaker.record_failure()
            else:
                self.breaker.record_success()
        return status

    def drain_flush_telemetry(self) -> List:
        with self._err_lock:
            out, self._telemetry = self._telemetry, []
        return out

    @property
    def name(self) -> str:
        return "datadog"

    def flush_columnar(self, batch) -> None:
        """Columnar flush: serialize emission blocks to deflated series
        bodies in C++ (native/veneur_egress.cpp — the vectorized twin of
        finalize_metrics + chunked POST, datadog.go:245-330) and POST
        them in parallel. Extras (status checks, routed metrics) take
        the per-row path."""
        bodies: List[bytes] = []
        n_metrics = 0
        t_marshal = time.perf_counter()
        for blk in batch.blocks:
            bodies.extend(self._serialize_block(blk, batch.timestamp))
            n_metrics += len(blk)
        t_marshal = time.perf_counter() - t_marshal
        threads = []
        t_post = time.perf_counter()
        for body in bodies:
            t = threading.Thread(target=self._flush_body, args=(body,),
                                 daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join()
        t_post = time.perf_counter() - t_post
        with self._err_lock:
            self._telemetry.append(("marshal_s", t_marshal))
            self._telemetry.append(("post_s", t_post))
            self._telemetry.extend(
                ("content_length_bytes", len(b)) for b in bodies)
        self.metrics_flushed += n_metrics
        if batch.extras:
            self.flush(batch.extras)

    def flush_chunk(self, chunk) -> None:
        """Streaming egress (docs/internals.md "Life of a flush"):
        serialize + deflate + POST ONE pipeline chunk the moment the
        store completes it, while later groups still compute/fetch.
        Runs on the interval's stream worker behind the same retry/
        breaker/deadline ladder as the batch path.

        Per-chunk conservation: every emission row either reaches a
        2xx body (``chunk_rows_acked``) or its serialized body parks
        for retry on later intervals (``chunk_rows_requeued``, late
        never lost) inside the ``requeue_max_bytes`` budget; past the
        budget the OLDEST parked bodies drop counted
        (``chunk_rows_dropped``), so memory stays bounded and a long
        outage degrades by counted drop."""
        from veneur_tpu import obs

        # normally a no-op: the stream worker already reposted for this
        # interval before any chunk flowed (core/pipeline.py); kept for
        # direct flush_chunk callers. The flush-cycle id is the dedup
        # key — the integer-second timestamp collides across sub-second
        # driven intervals (hand-built test chunks carry cycle 0 and
        # fall back to it)
        self.repost_requeued(getattr(chunk, "cycle", 0) or chunk.timestamp)
        rec = obs.current()
        t0_ns = time.monotonic_ns()
        t_marshal = time.perf_counter()
        bodies = []
        for blk in chunk.blocks:
            blk_bodies = self._serialize_block(blk, chunk.timestamp)
            bodies.extend(zip(blk_bodies,
                              _body_rows(len(blk), self.flush_max_per_body,
                                         len(blk_bodies))))
        t_marshal = time.perf_counter() - t_marshal
        if rec is not None:
            rec.record_abs(f"post.{self.name}.serialize", t0_ns,
                           time.monotonic_ns(), chunk=chunk.seq)
        t0_ns = time.monotonic_ns()
        t_post = time.perf_counter()
        for body, nrows in bodies:
            self._post_chunk_body(body, nrows)
        t_post = time.perf_counter() - t_post
        if rec is not None:
            rec.record_abs(f"post.{self.name}.post", t0_ns,
                           time.monotonic_ns(), chunk=chunk.seq,
                           rows=chunk.rows,
                           bytes=sum(len(b) for b, _ in bodies))
        with self._err_lock:
            # chunk_* kinds: same part-tagged duration self-metrics as
            # the batch path, but NOT amended onto the post.<sink>
            # stage — the chunk's own post.<sink>.serialize/.post
            # stages already carry the lanes, and an amend on top
            # would double-bill annotate_overlap
            self._telemetry.append(("chunk_marshal_s", t_marshal))
            self._telemetry.append(("chunk_post_s", t_post))
            self._telemetry.extend(("content_length_bytes", len(b))
                                   for b, _ in bodies)
            self.chunks_flushed += 1
        self.metrics_flushed += chunk.rows

    def _serialize_block(self, blk, timestamp: int) -> List[bytes]:
        """One emission block → deflated series bodies: the
        counter-to-rate finalization (datadog.go:295-297) + the native
        serializer call, shared by the batch and streamed paths so the
        wire format can never diverge between them."""
        from veneur_tpu.core.columnar import TYPE_COUNTER
        from veneur_tpu.native import egress

        values = blk.values
        if (blk.type_codes == TYPE_COUNTER).any():
            values = np.where(blk.type_codes == TYPE_COUNTER,
                              values / self.interval, values)
        return egress.dd_series_bodies(
            blk.names, blk.tags, blk.suffixes, blk.rows,
            blk.suffix_idx, values, blk.type_codes,
            timestamp=timestamp, interval=int(self.interval),
            default_host=self.hostname,
            common_tags_json=self._common_tags_json(),
            max_per_body=self.flush_max_per_body,
            compress_level=self.compress_level)

    def _post_chunk_body(self, body: bytes, nrows: int,
                         requeued: bool = False) -> bool:
        """POST one serialized chunk body; terminal failure parks it
        for retry on later intervals inside the requeue budget. The
        catch is deliberately broad — transport OSErrors AND
        protocol-level HTTPExceptions (BadStatusLine from a garbage
        proxy is not an OSError) — because ANY escape here would leave
        the body's rows neither acked, requeued, nor dropped, silently
        breaking the conservation invariant."""
        import http.client

        try:
            status = self._resilient_post(lambda: self.post(
                f"{self.dd_hostname}/api/v1/series"
                f"?api_key={self.api_key}", body, precompressed=True))
            if _ok(status):
                with self._err_lock:
                    self.chunk_rows_acked += nrows
                return True
            log.warning("Datadog chunk POST returned HTTP %d", status)
            self._count_error()
        except (OSError, http.client.HTTPException):
            log.warning("error POSTing chunk body to Datadog",
                        exc_info=True)
            self._count_error()
        with self._err_lock:
            self._park_locked(body, nrows)
        return False

    def _park_locked(self, body: bytes, nrows: int) -> None:
        """Park one unacked body for the next interval's repost,
        evicting OLDEST parked bodies (counted ``chunk_rows_dropped``)
        until the bytes budget and the body-count bound admit it; a
        body alone past the whole budget drops outright. Caller holds
        ``_err_lock``."""
        if len(body) > self.requeue_max_bytes:
            self.chunk_rows_dropped += nrows
            return
        while self._requeued and (
                self._requeued_bytes + len(body) > self.requeue_max_bytes
                or len(self._requeued) >= self.requeue_max_bodies):
            old_body, old_rows = self._requeued.popleft()
            # caller holds _err_lock (see docstring)
            self._requeued_bytes -= len(old_body)  # lint: ok(inconsistent-lockset) caller holds _err_lock (docstring contract) — the pass cannot see through the call boundary
            self.chunk_rows_dropped += old_rows
        self._requeued.append((body, nrows))
        self._requeued_bytes += len(body)  # lint: ok(inconsistent-lockset) caller holds _err_lock (docstring contract) — the pass cannot see through the call boundary
        self.chunk_rows_requeued += nrows

    def repost_requeued(self, timestamp: int) -> None:
        """Unacked bodies from previous intervals get one more POST
        per interval (``timestamp`` is the interval's dedup key — the
        stream's flush-cycle id, or the chunk timestamp for hand-built
        chunks); a body that
        fails again re-parks through the same bytes-budgeted path, so
        a multi-interval outage holds the freshest budget's worth and
        drops (counted) only past it. The stream worker fires this at
        interval start — even when the interval produces no chunks for
        this sink — so parked bodies can never strand un-retried."""
        with self._err_lock:
            if timestamp == self._last_repost_ts:
                return
            self._last_repost_ts = timestamp
            if not self._requeued:
                return
            pending, self._requeued = list(self._requeued), deque()
            self._requeued_bytes = 0
            self.chunks_requeued_total += len(pending)
        for body, nrows in pending:
            self._post_chunk_body(body, nrows, requeued=True)

    def chunk_rows_pending(self) -> int:
        """Rows currently parked for the next-interval retry (the
        conservation tests' requeued term)."""
        with self._err_lock:
            return sum(n for _b, n in self._requeued)

    def chunk_requeue_bytes(self) -> int:
        """Serialized bytes currently parked — the host-memory cost of
        the requeue buffer, bounded by ``requeue_max_bytes``."""
        with self._err_lock:
            return self._requeued_bytes

    def _common_tags_json(self) -> bytes:
        """The sink's fixed tags as a pre-escaped JSON fragment
        (``"a:1","b:2"``) the native serializer prepends per metric."""
        import json as _json

        if self._common_json is None:
            self._common_json = ",".join(
                _json.dumps(t) for t in self.tags).encode("utf-8")
        return self._common_json

    def _flush_body(self, body: bytes) -> None:
        try:
            status = self._resilient_post(lambda: self.post(
                f"{self.dd_hostname}/api/v1/series"
                f"?api_key={self.api_key}", body, precompressed=True))
            if not _ok(status):
                log.warning("Datadog series flush returned HTTP %d", status)
                self._count_error()
        except OSError:
            log.warning("error flushing metrics to Datadog", exc_info=True)
            self._count_error()

    def flush(self, metrics: List[InterMetric]) -> None:
        t_marshal = time.perf_counter()
        dd_metrics, checks = self.finalize_metrics(metrics)
        t_marshal = time.perf_counter() - t_marshal
        if checks:
            # check_run takes an array but not deflate (datadog.go:113-116)
            try:
                status = self._resilient_post(lambda: self.post(
                    f"{self.dd_hostname}/api/v1/check_run"
                    f"?api_key={self.api_key}", checks, compress=False))
                if not _ok(status):
                    log.warning("Datadog check_run returned HTTP %d", status)
                    self._count_error()
            except OSError:
                log.warning("error flushing checks to Datadog", exc_info=True)
                self._count_error()
        if not dd_metrics:
            return
        # equal-size chunks under flush_max_per_body, rounding-up division
        # (datadog.go:127-146)
        workers = ((len(dd_metrics) - 1) // self.flush_max_per_body) + 1
        chunk_size = ((len(dd_metrics) - 1) // workers) + 1
        threads = []
        t_post = time.perf_counter()
        for i in range(workers):
            chunk = dd_metrics[i * chunk_size:(i + 1) * chunk_size]
            t = threading.Thread(target=self._flush_part, args=(chunk,),
                                 daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join()
        t_post = time.perf_counter() - t_post
        # same part-tagged telemetry the columnar path records, so the
        # documented veneur.flush.* set does not depend on which flush
        # path a deployment runs
        with self._err_lock:
            self._telemetry.append(("marshal_s", t_marshal))
            self._telemetry.append(("post_s", t_post))
        self.metrics_flushed += len(dd_metrics)

    def _flush_part(self, chunk: List[dict]) -> None:
        info = {}
        try:
            status = self._resilient_post(
                lambda: self.post(f"{self.dd_hostname}/api/v1/series"
                                  f"?api_key={self.api_key}",
                                  {"series": chunk}, out_info=info))
            if not _ok(status):
                log.warning("Datadog series flush returned HTTP %d", status)
                self._count_error()
        except OSError:
            log.warning("error flushing metrics to Datadog", exc_info=True)
            self._count_error()
        finally:
            if "content_length" in info:
                with self._err_lock:
                    self._telemetry.append(
                        ("content_length_bytes", info["content_length"]))

    def finalize_metrics(self, metrics: List[InterMetric]):
        """InterMetric → DDMetric/DDServiceCheck dicts (datadog.go:245-322)."""
        dd_metrics: List[dict] = []
        checks: List[dict] = []
        for m in metrics:
            if not m.is_acceptable_to(self.name):
                continue
            tags = list(self.tags)
            hostname = ""
            devicename = ""
            for tag in m.tags:
                if tag.startswith("host:"):
                    hostname = tag[5:]
                elif tag.startswith("device:"):
                    devicename = tag[7:]
                else:
                    tags.append(tag)
            if not hostname:
                hostname = m.hostname or self.hostname

            if m.type == MetricType.STATUS:
                checks.append({
                    "check": m.name,
                    "status": int(m.value),
                    "timestamp": m.timestamp,
                    "message": m.message,
                    "host_name": hostname,
                    "tags": tags,
                })
                continue

            if m.type == MetricType.COUNTER:
                # counters become rates for Datadog (datadog.go:295-297)
                metric_type = "rate"
                value = m.value / self.interval
            elif m.type == MetricType.GAUGE:
                metric_type = "gauge"
                value = m.value
            else:
                log.warning("unknown metric type %s", m.type)
                continue

            dd_metrics.append({
                "metric": m.name,
                "points": [[float(m.timestamp), value]],
                "tags": tags,
                "type": metric_type,
                "interval": int(self.interval),
                "host": hostname,
                "device_name": devicename,
            })
        return dd_metrics, checks

    def flush_other_samples(self, samples) -> None:
        """DogStatsD events → ``/intake`` (datadog.go:155-243)."""
        events = []
        for sample in samples:
            tags = dict(sample.tags)
            if dogstatsd.EVENT_IDENTIFIER_KEY not in tags:
                log.warning("received a non-event SSF sample in "
                            "flush_other_samples")
                continue
            del tags[dogstatsd.EVENT_IDENTIFIER_KEY]
            event = {
                "msg_title": sample.name,
                "msg_text": sample.message,
                "timestamp": sample.timestamp,
                "priority": "normal",
                "alert_type": "info",
            }
            if dogstatsd.EVENT_AGGREGATION_KEY_TAG in tags:
                event["aggregation_key"] = tags.pop(
                    dogstatsd.EVENT_AGGREGATION_KEY_TAG)
            if dogstatsd.EVENT_PRIORITY_TAG in tags:
                event["priority"] = tags.pop(dogstatsd.EVENT_PRIORITY_TAG)
            if dogstatsd.EVENT_SOURCE_TYPE_TAG in tags:
                event["source_type_name"] = tags.pop(
                    dogstatsd.EVENT_SOURCE_TYPE_TAG)
            if dogstatsd.EVENT_ALERT_TYPE_TAG in tags:
                event["alert_type"] = tags.pop(dogstatsd.EVENT_ALERT_TYPE_TAG)
            if dogstatsd.EVENT_HOSTNAME_TAG in tags:
                event["host"] = tags.pop(dogstatsd.EVENT_HOSTNAME_TAG)
            else:
                event["host"] = self.hostname
            event["tags"] = [f"{k}:{v}" for k, v in tags.items()] + self.tags
            events.append(event)
        if not events:
            return
        try:
            status = self._resilient_post(lambda: self.post(
                f"{self.dd_hostname}/intake?api_key={self.api_key}",
                {"events": {"api": events}}))
            if not _ok(status):
                log.warning("Datadog event intake returned HTTP %d", status)
                self._count_error()
        except OSError:
            log.warning("error flushing events to Datadog", exc_info=True)
            self._count_error()


class DatadogSpanSink(SpanSink):
    """Ring-buffered span sink for the Datadog trace agent
    (datadog.go:359-530)."""

    def __init__(self, trace_address: str, buffer_size: int = 16384,
                 post: Optional[PostFn] = None,
                 retry_policy: Optional[RetryPolicy] = None):
        self.trace_address = trace_address.rstrip("/")
        self.buffer_size = buffer_size
        # deque(maxlen) == the reference's container/ring: newest
        # buffer_size spans win (datadog.go:395-397)
        self._buffer: deque = deque(maxlen=buffer_size)
        self._lock = threading.Lock()
        self.post = post or _default_post
        self.retry_policy = retry_policy or RetryPolicy()
        self.retries = 0
        self.spans_flushed = 0

    def _count_retry(self, retry_index, exc, pause) -> None:
        with self._lock:
            self.retries += 1

    @property
    def name(self) -> str:
        return "datadog"

    def ingest(self, span) -> None:
        if not wire.valid_trace(span):
            raise ValueError("invalid span for datadog sink")
        with self._lock:
            self._buffer.append(span)

    def flush(self) -> None:
        with self._lock:
            spans = list(self._buffer)
            self._buffer.clear()
        if not spans:
            return
        trace_map: Dict[int, List[dict]] = {}
        for span in spans:
            tags = dict(span.tags)
            resource = tags.pop(DATADOG_RESOURCE_KEY, "") or "unknown"
            trace_map.setdefault(span.trace_id, []).append({
                "trace_id": span.trace_id,
                "span_id": span.id,
                "parent_id": max(span.parent_id, 0),
                "service": span.service,
                "name": span.name or "unknown",
                "resource": resource,
                "start": span.start_timestamp,
                "duration": span.end_timestamp - span.start_timestamp,
                "type": DATADOG_SPAN_TYPE,
                "error": 2 if span.error else 0,
                "meta": tags,
            })
        # two-dimensional: spans grouped per trace (datadog.go:503-508)
        final_traces = list(trace_map.values())
        try:
            # /v0.3/traces takes PUT without deflate (datadog.go:510-515)
            status = post_with_retry(
                lambda: self.post(f"{self.trace_address}/v0.3/traces",
                                  final_traces, compress=False,
                                  method="PUT"),
                self.retry_policy, on_retry=self._count_retry)
            if _ok(status):
                self.spans_flushed += len(spans)
            else:
                log.warning("Datadog trace flush returned HTTP %d", status)
        except OSError:
            log.warning("error flushing traces to Datadog", exc_info=True)

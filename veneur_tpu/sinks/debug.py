"""Logging sinks (cf. /root/reference/sinks/debug/debug.go): print every
flushed metric / ingested span for debugging."""

from __future__ import annotations

import logging

from .base import MetricSink, SpanSink

log = logging.getLogger("veneur.sinks.debug")


class DebugMetricSink(MetricSink):
    @property
    def name(self) -> str:
        return "debug"

    def flush(self, metrics) -> None:
        for m in metrics:
            log.info("Flushed metric name=%r time=%d value=%f tags=%r type=%s",
                     m.name, m.timestamp, m.value, m.tags, m.type.value)

    def flush_other_samples(self, samples) -> None:
        for s in samples:
            log.info("Flushed sample %r", s)


class DebugSpanSink(SpanSink):
    @property
    def name(self) -> str:
        return "debug"

    def ingest(self, span) -> None:
        log.info("Ingested span %r", span)

    def flush(self) -> None:
        pass

"""Sink/plugin construction from config.

Mirrors the sink-construction section of ``NewFromConfig``
(``/root/reference/server.go:350-519``): each backend comes up iff its
config keys are set — SignalFx (server.go:350-390), Datadog metric +
span sinks (:392-419), LightStep (:421-437), Falconer (:439-449), Kafka
(:451-472), debug sinks under ``debug_flushed_metrics`` /
``debug_ingested_spans``, and the S3/localfile plugins (:477-519).
"""

from __future__ import annotations

import logging
from typing import List, Tuple

from veneur_tpu.config import Config, parse_duration
from veneur_tpu.plugins import Plugin
from veneur_tpu.plugins.localfile import LocalFilePlugin
from veneur_tpu.plugins.s3 import S3Plugin
from veneur_tpu.sinks.base import MetricSink, SpanSink
from veneur_tpu.sinks.datadog import DatadogMetricSink, DatadogSpanSink
from veneur_tpu.sinks.debug import DebugMetricSink, DebugSpanSink
from veneur_tpu.sinks.falconer import new_falconer_span_sink
from veneur_tpu.sinks.kafka import (KafkaMetricSink, KafkaSpanSink,
                                    ProducerConfig)
from veneur_tpu.sinks.lightstep import LightStepSpanSink
from veneur_tpu.sinks.signalfx import SignalFxClient, SignalFxSink

log = logging.getLogger("veneur.sinks.factory")


def span_sinks_configured(config: Config) -> bool:
    """Would create_sinks build any span sinks for this config? Used by
    the SIGHUP reload path, which cannot hot-swap span sinks (they are
    embedded in running span-worker lanes) and must not construct
    throwaway producers just to find out."""
    return bool(
        config.datadog_trace_api_address
        or config.lightstep_collector_host
        or config.falconer_address
        or (config.kafka_broker and config.kafka_span_topic)
        or config.debug_ingested_spans)


def create_sinks(config: Config) -> Tuple[List[MetricSink], List[SpanSink],
                                          List[Plugin]]:
    from veneur_tpu.resilience import (CircuitBreaker, RetryPolicy,
                                       faults_from_config)

    metric_sinks: List[MetricSink] = []
    span_sinks: List[SpanSink] = []
    plugins: List[Plugin] = []
    interval = parse_duration(config.interval)
    # shared egress resilience (docs/resilience.md): one retry policy
    # from the config knobs, one breaker per sink destination, and the
    # fault injector when a soak run configures one
    retry_policy = RetryPolicy.from_config(config)
    fault_injector = faults_from_config(config)

    def destination_breaker(name: str) -> CircuitBreaker:
        return CircuitBreaker(
            failure_threshold=config.breaker_failure_threshold or 5,
            reset_timeout=getattr(config, "breaker_reset_timeout_seconds",
                                  30.0),
            name=name)

    if config.signalfx_api_key and config.signalfx_endpoint_base:
        per_tag = {}
        for entry in config.signalfx_per_tag_api_keys:
            # list of {name:, api_key:} maps (config.go signalfx keys)
            per_tag[entry.get("name", "")] = SignalFxClient(
                config.signalfx_endpoint_base, entry.get("api_key", ""))
        # config tags become common dimensions (server.go:356's TagsAsMap)
        common_dims = dict(t.partition(":")[::2] for t in config.tags)
        metric_sinks.append(SignalFxSink(
            hostname_tag=config.signalfx_hostname_tag or "host",
            hostname=config.hostname,
            common_dimensions=common_dims,
            client=SignalFxClient(config.signalfx_endpoint_base,
                                  config.signalfx_api_key),
            vary_by=config.signalfx_vary_key_by,
            per_tag_clients=per_tag,
            excluded_tags=config.tags_exclude,
            retry_policy=retry_policy,
            breaker=destination_breaker(config.signalfx_endpoint_base),
            fault_injector=fault_injector))

    if config.datadog_api_key and config.datadog_api_hostname:
        metric_sinks.append(DatadogMetricSink(
            interval=interval,
            flush_max_per_body=config.datadog_flush_max_per_body,
            hostname=config.hostname, tags=config.tags,
            dd_hostname=config.datadog_api_hostname,
            api_key=config.datadog_api_key,
            retry_policy=retry_policy,
            breaker=destination_breaker(config.datadog_api_hostname),
            fault_injector=fault_injector,
            requeue_max_bytes=config.sink_requeue_max_bytes))
    if config.datadog_trace_api_address:
        span_sinks.append(DatadogSpanSink(
            trace_address=config.datadog_trace_api_address,
            buffer_size=config.datadog_span_buffer_size,
            retry_policy=retry_policy))

    if config.lightstep_collector_host:
        span_sinks.append(LightStepSpanSink(
            collector=config.lightstep_collector_host,
            reconnect_period=parse_duration(config.lightstep_reconnect_period)
            if config.lightstep_reconnect_period else 0.0,
            maximum_spans=config.lightstep_maximum_spans or 1024,
            num_clients=config.lightstep_num_clients,
            access_token=config.lightstep_access_token,
            retry_policy=retry_policy))

    if config.falconer_address:
        span_sinks.append(new_falconer_span_sink(config.falconer_address))

    if config.kafka_broker:
        if config.kafka_metric_topic:
            metric_sinks.append(KafkaMetricSink(
                brokers=config.kafka_broker,
                metric_topic=config.kafka_metric_topic,
                check_topic=config.kafka_check_topic,
                event_topic=config.kafka_event_topic,
                config=ProducerConfig(
                    ack_requirement=config.kafka_metric_require_acks or "all",
                    partitioner=config.kafka_partitioner or "hash",
                    retries=config.kafka_retry_max,
                    buffer_bytes=config.kafka_metric_buffer_bytes,
                    buffer_messages=config.kafka_metric_buffer_messages,
                    buffer_frequency=parse_duration(
                        config.kafka_metric_buffer_frequency)
                    if config.kafka_metric_buffer_frequency else 0.0),
                retry_policy=retry_policy))
        if config.kafka_span_topic:
            span_sinks.append(KafkaSpanSink(
                brokers=config.kafka_broker,
                topic=config.kafka_span_topic,
                serialization_format=(
                    config.kafka_span_serialization_format or "protobuf"),
                sample_tag=config.kafka_span_sample_tag,
                sample_rate_percentage=(
                    config.kafka_span_sample_rate_percent or 100),
                config=ProducerConfig(
                    ack_requirement=config.kafka_span_require_acks or "all",
                    partitioner=config.kafka_partitioner or "hash",
                    retries=config.kafka_retry_max,
                    buffer_bytes=config.kafka_span_buffer_bytes,
                    buffer_messages=config.kafka_span_buffer_mesages,
                    buffer_frequency=parse_duration(
                        config.kafka_span_buffer_frequency)
                    if config.kafka_span_buffer_frequency else 0.0)))

    if config.debug_flushed_metrics:
        metric_sinks.append(DebugMetricSink())
    if config.debug_ingested_spans:
        span_sinks.append(DebugSpanSink())

    if config.aws_s3_bucket:
        svc = None
        try:
            import boto3  # optional, not bundled
            svc = boto3.client("s3", region_name=config.aws_region or None)
        except ImportError:
            log.warning("aws_s3_bucket configured but boto3 is unavailable; "
                        "S3 plugin will error on flush until a client is "
                        "injected")
        plugins.append(S3Plugin(hostname=config.hostname,
                                bucket=config.aws_s3_bucket,
                                interval=int(interval), svc=svc))

    if config.flush_file:
        plugins.append(LocalFilePlugin(file_path=config.flush_file,
                                       hostname=config.hostname,
                                       interval=int(interval)))

    return metric_sinks, span_sinks, plugins

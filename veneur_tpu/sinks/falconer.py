"""Falconer span sink: a named wrapper over the generic gRPC span sink
(``/root/reference/sinks/falconer/falconer.go:11-17``)."""

from __future__ import annotations

from veneur_tpu.sinks.grpsink import GRPCSpanSink


def new_falconer_span_sink(target: str, timeout: float = 10.0) -> GRPCSpanSink:
    return GRPCSpanSink(target, name="falconer", timeout=timeout)

"""Generic gRPC span sink: stream each span via ``SpanSink.SendSpan``.

Behavioral port of ``/root/reference/sinks/grpsink/grpsink.go``: each
ingested span is validated and sent as one unary RPC
(``/grpsink.SpanSink/SendSpan``, grpc_sink.proto:8-10); errors increment
the drop counter and are logged once per connection-state transition to
avoid log spew under duress (grpsink.go:98-137); ``flush`` reports the
sent/dropped totals since the last flush (grpsink.go:139-160).

Also provides ``SpanSinkServer``, the in-process receiving end the
reference builds for its tests (grpsink_test.go) — and the Falconer
service this sink fronts in production.
"""

from __future__ import annotations

import logging
import threading
from concurrent import futures
from typing import Callable, List, Optional

import grpc

from veneur_tpu.protocol import wire
from veneur_tpu.protocol.gen.grpsink import grpc_sink_pb2
from veneur_tpu.protocol.gen.ssf import sample_pb2
from veneur_tpu.sinks.base import SpanSink

log = logging.getLogger("veneur.sinks.grpc")

_METHOD = "/grpsink.SpanSink/SendSpan"


class GRPCSpanSink(SpanSink):
    """Streams spans to a remote gRPC SpanSink service
    (grpsink.go:30-160)."""

    def __init__(self, target: str, name: str = "grpc",
                 timeout: float = 10.0):
        self.target = target
        self._name = name
        self.timeout = timeout
        self._channel = grpc.insecure_channel(target)
        self._send = self._channel.unary_unary(
            _METHOD,
            request_serializer=sample_pb2.SSFSpan.SerializeToString,
            response_deserializer=grpc_sink_pb2.Empty.FromString,
        )
        self._lock = threading.Lock()
        self.sent_count = 0
        self.drop_count = 0
        # log one error per connection-state transition (grpsink.go:115-127)
        self._logged_since_transition = False
        self._channel.subscribe(self._on_state_change)

    @property
    def name(self) -> str:
        return self._name

    def _on_state_change(self, connectivity) -> None:
        with self._lock:
            self._logged_since_transition = False

    def ingest(self, span) -> None:
        if not wire.valid_trace(span):
            raise ValueError("invalid span for gRPC sink")
        try:
            self._send(span, timeout=self.timeout)
            with self._lock:
                self.sent_count += 1
        except grpc.RpcError as e:
            # count the drop but don't propagate: re-raising would make the
            # span worker log a traceback per span — the log spew under
            # duress grpsink.go:115-127 exists to avoid
            with self._lock:
                self.drop_count += 1
                should_log = not self._logged_since_transition
                self._logged_since_transition = True
            if should_log:
                log.error("Error sending span to gRPC sink target %s "
                          "(name=%s): %s", self.target, self._name, e)

    def flush(self) -> None:
        """Report + reset sent/dropped totals (grpsink.go:139-160)."""
        with self._lock:
            sent, dropped = self.sent_count, self.drop_count
            self.sent_count = 0
            self.drop_count = 0
        if sent or dropped:
            log.info("gRPC span sink %s: %d sent, %d dropped since last "
                     "flush", self._name, sent, dropped)

    def close(self) -> None:
        self._channel.close()


class SpanSinkServer:
    """In-process gRPC SpanSink service — the receiving end
    (grpsink_test.go's MockSpanSinkServer; production: Falconer)."""

    def __init__(self, handler: Optional[Callable] = None, workers: int = 4):
        self.spans: List = []
        self._handler = handler
        self._lock = threading.Lock()
        self._grpc = grpc.server(
            futures.ThreadPoolExecutor(max_workers=workers))
        h = grpc.method_handlers_generic_handler(
            "grpsink.SpanSink",
            {"SendSpan": grpc.unary_unary_rpc_method_handler(
                self._send_span,
                request_deserializer=sample_pb2.SSFSpan.FromString,
                response_serializer=grpc_sink_pb2.Empty.SerializeToString)})
        self._grpc.add_generic_rpc_handlers((h,))
        self.port: Optional[int] = None

    def _send_span(self, span, context):
        if self._handler is not None:
            self._handler(span)
        else:
            with self._lock:
                self.spans.append(span)
        return grpc_sink_pb2.Empty()

    def start(self, addr: str = "[::]:0") -> int:
        self.port = self._grpc.add_insecure_port(addr)
        if self.port == 0:
            raise RuntimeError(f"could not bind span sink server to {addr}")
        self._grpc.start()
        return self.port

    def stop(self, grace: float = 1.0):
        self._grpc.stop(grace).wait(timeout=grace + 1.0)

"""Kafka sinks: JSON InterMetrics per message + JSON/protobuf span stream.

Behavioral port of ``/root/reference/sinks/kafka/kafka.go``:

- ``KafkaMetricSink.flush`` emits one JSON-serialized InterMetric per
  producer message on ``metric_topic`` (kafka.go:189-221).
- ``KafkaSpanSink.ingest`` serializes each span as JSON or protobuf onto
  ``span_topic`` (kafka.go:352-386), after crc32-based sampling: hash
  the trace id (or the configured ``sample_tag``'s value, dropping
  untagged spans) and reject hashes above the threshold derived from
  ``sample_rate_percentage`` (kafka.go:306-349).
- Producer tuning (ack requirement all/none/local, hash/random
  partitioner, retries, buffer bytes/messages/frequency;
  kafka.go:109-152) is carried on ``ProducerConfig`` for the real
  client.

The producer itself is injectable — the reference's tests swap in a
sarama mock (kafka_test.go); here any object with
``produce(topic, value)`` works. The default producer prefers the
optional ``kafka`` client package and falls back to the bundled
stdlib wire-protocol producer (``sinks/kafka_wire.py``) when it is
absent, so the sink works out of the box.
"""

from __future__ import annotations

import json
import logging
import zlib
from dataclasses import dataclass
from typing import List, Optional, Protocol

from veneur_tpu.samplers.intermetric import InterMetric
from veneur_tpu.sinks.base import MetricSink, SpanSink

log = logging.getLogger("veneur.sinks.kafka")

MAX_UINT32 = 0xFFFFFFFF


class Producer(Protocol):
    def produce(self, topic: str, value: bytes) -> None: ...

    def close(self) -> None: ...


@dataclass
class ProducerConfig:
    """Producer tuning, mirroring newProducerConfig (kafka.go:109-152)."""

    ack_requirement: str = "all"  # all | none | local
    partitioner: str = "hash"     # hash | random
    retries: int = 0
    buffer_bytes: int = 0
    buffer_messages: int = 0
    buffer_frequency: float = 0.0  # seconds

    def normalized_acks(self) -> str:
        if self.ack_requirement not in ("all", "none", "local"):
            log.warning("Unknown ack requirement %r, defaulting to all",
                        self.ack_requirement)
            return "all"
        return self.ack_requirement


def new_producer(brokers: str, config: ProducerConfig) -> Producer:
    """Build a real Kafka producer (kafka.go:155-172): the optional
    ``kafka`` client package when installed, else the bundled stdlib
    wire-protocol producer (sinks/kafka_wire.py)."""
    broker_list = [b for b in brokers.split(",") if b]
    if not broker_list:
        raise ValueError("No brokers in broker list")
    try:
        from kafka import KafkaProducer  # optional, not bundled
    except ImportError:
        from veneur_tpu.sinks.kafka_wire import WireProducer

        if config.buffer_bytes or config.buffer_messages or \
                config.buffer_frequency:
            log.warning("the bundled wire producer sends synchronously; "
                        "buffer_bytes/buffer_messages/buffer_frequency "
                        "are ignored (install the kafka package for "
                        "batched sends)")
        acks = {"all": -1, "none": 0, "local": 1}[config.normalized_acks()]
        # default the port like the kafka client does
        normalized = ",".join(b if ":" in b else f"{b}:9092"
                              for b in broker_list)
        return WireProducer(
            normalized, acks=acks, retry_max=config.retries,
            partitioner=config.partitioner or "hash")
    acks = {"all": "all", "none": 0, "local": 1}[config.normalized_acks()]
    kwargs = dict(
        bootstrap_servers=broker_list, acks=acks,
        retries=config.retries,
        batch_size=config.buffer_bytes or 16384,
        linger_ms=int(config.buffer_frequency * 1000))
    if config.partitioner == "random":
        import random

        def _random_partitioner(key, all_parts, available):
            return random.choice(available or all_parts)

        kwargs["partitioner"] = _random_partitioner
    if config.buffer_messages:
        # kafka-python batches by bytes/linger only (kafka.go:137-139's
        # Flush.Messages has no equivalent knob)
        log.warning("buffer_messages=%d is not supported by the kafka "
                    "client; batching is governed by buffer_bytes and "
                    "buffer_frequency", config.buffer_messages)
    kp = KafkaProducer(**kwargs)

    class _KP:
        def produce(self, topic: str, value: bytes) -> None:
            kp.send(topic, value)

        def close(self) -> None:
            kp.close()

    return _KP()


def _sample_threshold(sample_rate_percentage: float) -> int:
    """sampleRatePercentage → crc32 admission threshold
    (kafka.go:259-269)."""
    pct = min(max(sample_rate_percentage, 0.0), 100.0)
    return int(MAX_UINT32 * (pct / 100.0))


def _hash_key(value: str) -> int:
    """crc32 of the tag value (kafka.go:333-341 — the 64-byte scratch
    there is sliced back to the original length, so it is a plain
    ChecksumIEEE of the value bytes)."""
    return zlib.crc32(value.encode("utf-8"))


class KafkaMetricSink(MetricSink):
    """One JSON InterMetric per message (kafka.go:60-221).

    Deliberately NOT columnar (the one egress path that keeps per-row
    flush): the wire contract is one Kafka message per metric, so each
    metric pays a produce round anyway — the reference has the same
    shape (one sarama message each) and the per-message produce, not
    JSON serialization, bounds this sink at cardinality. High-cardinality
    egress belongs to the columnar Datadog/SignalFx/TSV paths."""

    def __init__(self, brokers: str, metric_topic: str,
                 check_topic: str = "", event_topic: str = "",
                 config: Optional[ProducerConfig] = None,
                 producer: Optional[Producer] = None,
                 retry_policy=None):
        from veneur_tpu.resilience import RetryPolicy

        if not metric_topic:
            raise ValueError("Cannot start Kafka metric sink with no topic")
        self.brokers = brokers
        self.metric_topic = metric_topic
        self.check_topic = check_topic
        self.event_topic = event_topic
        self.config = config or ProducerConfig()
        self.producer = producer
        # kafka_retry_max rides ProducerConfig.retries (kafka.go:131)
        # and sets the attempt budget; the backoff SHAPE comes from the
        # shared config knobs (retry_base_interval) when the factory
        # passes them
        shape = retry_policy or RetryPolicy(base_interval=0.05,
                                            max_interval=1.0)
        self.retry_policy = RetryPolicy(
            max_attempts=self.config.retries + 1,
            base_interval=shape.base_interval,
            max_interval=shape.max_interval)
        self.metrics_flushed = 0
        self.flush_errors = 0
        self.retries = 0

    @property
    def name(self) -> str:
        return "kafka"

    def start(self, trace_client=None) -> None:
        if self.producer is None:
            self.producer = new_producer(self.brokers, self.config)

    def _count_retry(self, retry_index, exc, pause) -> None:
        self.retries += 1

    def flush(self, metrics: List[InterMetric]) -> None:
        from veneur_tpu.resilience import call_with_retry

        if not metrics or self.producer is None:
            return
        # kafka_retry_max is honored HERE for every producer flavor —
        # the optional kafka client and the bundled wire producer apply
        # it to their own broker round-trips, but an injected producer
        # (tests, custom transports) previously made it a dead knob
        policy = self.retry_policy
        for m in metrics:
            if not m.is_acceptable_to(self.name):
                continue
            body = json.dumps({
                "name": m.name, "timestamp": m.timestamp, "value": m.value,
                "tags": m.tags, "type": m.type.value, "message": m.message,
                "hostname": m.hostname,
            }).encode("utf-8")
            try:
                # producer flavors raise different exception types
                # (socket errors, client library errors); all retryable
                call_with_retry(
                    lambda body=body: self.producer.produce(
                        self.metric_topic, body),
                    policy, deadline=self.flush_deadline,
                    retryable=(Exception,), on_retry=self._count_retry)
            except Exception:
                # one undeliverable metric must not drop the rest of
                # the batch
                self.flush_errors += 1
                log.warning("kafka produce to %s failed after %d "
                            "attempt(s)", self.metric_topic,
                            policy.max_attempts, exc_info=True)
                continue
            self.metrics_flushed += 1


class KafkaSpanSink(SpanSink):
    """Sampled JSON/protobuf span stream (kafka.go:230-396)."""

    def __init__(self, brokers: str, topic: str,
                 serialization_format: str = "protobuf",
                 sample_tag: str = "",
                 sample_rate_percentage: float = 100.0,
                 config: Optional[ProducerConfig] = None,
                 producer: Optional[Producer] = None):
        if not topic:
            raise ValueError("Cannot start Kafka span sink with no topic")
        serializer = serialization_format
        if serializer not in ("json", "protobuf"):
            log.warning("Unknown serialization format %r, defaulting to "
                        "protobuf", serializer)
            serializer = "protobuf"
        self.brokers = brokers
        self.topic = topic
        self.serializer = serializer
        self.sample_tag = sample_tag
        self.sample_threshold = _sample_threshold(sample_rate_percentage)
        self.config = config or ProducerConfig()
        self.producer = producer
        self.spans_flushed = 0
        self.spans_dropped = 0

    @property
    def name(self) -> str:
        return "kafka"

    def start(self, trace_client=None) -> None:
        if self.producer is None:
            self.producer = new_producer(self.brokers, self.config)

    def _should_sample(self, span) -> bool:
        if not self.sample_tag and self.sample_threshold >= MAX_UINT32:
            return True
        if not self.sample_tag:
            value = str(span.trace_id)
        else:
            value = span.tags.get(self.sample_tag)
            if value is None:
                # untagged spans drop regardless of rate (kafka.go:320-327)
                return False
        return _hash_key(value) <= self.sample_threshold

    def ingest(self, span) -> None:
        if self.producer is None:
            return
        if not self._should_sample(span):
            self.spans_dropped += 1
            return
        if self.serializer == "json":
            body = json.dumps({
                "version": span.version, "trace_id": span.trace_id,
                "id": span.id, "parent_id": span.parent_id,
                "start_timestamp": span.start_timestamp,
                "end_timestamp": span.end_timestamp,
                "error": span.error, "service": span.service,
                "tags": dict(span.tags), "indicator": span.indicator,
                "name": span.name,
            }).encode("utf-8")
        else:
            body = span.SerializeToString()
        self.producer.produce(self.topic, body)
        self.spans_flushed += 1

    def flush(self) -> None:
        """Spans ship asynchronously at ingest (kafka.go:388-396)."""

"""A dependency-free Kafka producer speaking the v0 wire protocol.

The reference bundles the sarama client (``sinks/kafka/kafka.go:155-172``
builds an AsyncProducer); this image bundles no Kafka client at all, so
the default producer is built on stdlib sockets:

- Metadata v0 (api_key 3) on first use per topic, for the partition
  count and per-partition leader address,
- Produce v0 (api_key 0) with CRC-framed message sets, honoring the
  ProducerConfig ack level (none/local/all), retry budget, and
  hash/random partitioner,
- one connection per broker, lazily (re)connected with the retry loop.

Only the surface veneur's Kafka sink needs is implemented — this is a
producer, not a client library. Wire layout follows the public Kafka
protocol specification (v0 APIs are stable and accepted by every broker
since 0.8, and by compatible implementations).
"""

from __future__ import annotations

import logging
import random
import socket
import struct
import threading
import zlib
from typing import Dict, List, Optional, Tuple

log = logging.getLogger("veneur.kafka.wire")

_API_PRODUCE = 0
_API_METADATA = 3


def _str(s: Optional[str]) -> bytes:
    if s is None:
        return struct.pack(">h", -1)
    b = s.encode("utf-8")
    return struct.pack(">h", len(b)) + b


def _bytes(b: Optional[bytes]) -> bytes:
    if b is None:
        return struct.pack(">i", -1)
    return struct.pack(">i", len(b)) + b


class _NoLeader(RuntimeError):
    """A keyed message's partition currently has no leader (election in
    flight) — retryable after a metadata refresh, without tearing down
    connections to healthy brokers."""


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def take(self, n: int) -> bytes:
        out = self.data[self.pos:self.pos + n]
        self.pos += n
        return out

    def i16(self) -> int:
        return struct.unpack(">h", self.take(2))[0]

    def i32(self) -> int:
        return struct.unpack(">i", self.take(4))[0]

    def i64(self) -> int:
        return struct.unpack(">q", self.take(8))[0]

    def string(self) -> str:
        n = self.i16()
        return "" if n < 0 else self.take(n).decode("utf-8", "replace")


def _message_set(value: bytes) -> bytes:
    """One v0 message: CRC over magic..value (offset 0, no key)."""
    body = struct.pack(">bb", 0, 0) + _bytes(None) + _bytes(value)
    msg = struct.pack(">I", zlib.crc32(body) & 0xFFFFFFFF) + body
    return struct.pack(">q", 0) + struct.pack(">i", len(msg)) + msg


class WireProducer:
    """produce(topic, value) over raw sockets; thread-safe."""

    def __init__(self, brokers: str, acks: int = 1, timeout_ms: int = 10000,
                 retry_max: int = 3, partitioner: str = "hash",
                 client_id: str = "veneur-tpu"):
        self.bootstrap: List[Tuple[str, int]] = []
        for b in brokers.split(","):
            host, sep, port = b.strip().rpartition(":")
            if sep and port.isdigit():
                self.bootstrap.append((host or "127.0.0.1", int(port)))
            else:
                # bare hostname (or trailing colon): default port 9092,
                # like the kafka clients do
                bare = host if sep else b.strip()
                self.bootstrap.append((bare or "127.0.0.1", 9092))
        self.acks = acks
        self.timeout_ms = timeout_ms
        self.retry_max = max(0, retry_max)
        self.partitioner = partitioner
        self.client_id = client_id
        self._lock = threading.Lock()
        self._correlation = 0
        self._conns: Dict[Tuple[str, int], socket.socket] = {}
        # topic -> (partition -> broker addr)
        self._leaders: Dict[str, Dict[int, Tuple[str, int]]] = {}
        # topic -> total partition count (incl. leaderless; hash modulus)
        self._npartitions: Dict[str, int] = {}
        self._rr = 0
        self.errors = 0

    # -- wire plumbing -----------------------------------------------------

    def _conn(self, addr: Tuple[str, int]) -> socket.socket:
        sock = self._conns.get(addr)
        if sock is not None:
            return sock
        sock = socket.create_connection(addr, timeout=self.timeout_ms / 1e3)
        self._conns[addr] = sock
        return sock

    def _drop(self, addr: Tuple[str, int]):
        sock = self._conns.pop(addr, None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def _roundtrip(self, addr: Tuple[str, int], api_key: int,
                   body: bytes, want_reply: bool) -> Optional[_Reader]:
        self._correlation += 1
        header = (struct.pack(">hhi", api_key, 0, self._correlation)
                  + _str(self.client_id))
        payload = header + body
        sock = self._conn(addr)
        sock.sendall(struct.pack(">i", len(payload)) + payload)
        if not want_reply:
            return None
        raw = b""
        while len(raw) < 4:
            chunk = sock.recv(4 - len(raw))
            if not chunk:
                raise ConnectionError("broker closed connection")
            raw += chunk
        (size,) = struct.unpack(">i", raw)
        data = b""
        while len(data) < size:
            chunk = sock.recv(size - len(data))
            if not chunk:
                raise ConnectionError("broker closed mid-response")
            data += chunk
        r = _Reader(data)
        r.i32()  # correlation id
        return r

    # -- metadata ----------------------------------------------------------

    def _refresh_metadata(self, topic: str):
        body = struct.pack(">i", 1) + _str(topic)
        last_err: Optional[Exception] = None
        for addr in self.bootstrap:
            try:
                r = self._roundtrip(addr, _API_METADATA, body, True)
            except OSError as e:
                last_err = e
                self._drop(addr)
                continue
            brokers: Dict[int, Tuple[str, int]] = {}
            for _ in range(r.i32()):
                node = r.i32()
                host = r.string()
                port = r.i32()
                brokers[node] = (host, port)
            leaders: Dict[int, Tuple[str, int]] = {}
            total = 0
            for _ in range(r.i32()):
                r.i16()  # topic error code
                r.string()  # topic name
                for _ in range(r.i32()):
                    r.i16()  # partition error code
                    pid = r.i32()
                    leader = r.i32()
                    for _ in range(r.i32()):
                        r.i32()  # replicas
                    for _ in range(r.i32()):
                        r.i32()  # isr
                    total += 1  # leaderless partitions still count for
                    # the hash modulus (sarama mods by the topic's full
                    # partition count, not the currently-leadered subset)
                    if leader in brokers:
                        leaders[pid] = brokers[leader]
            if leaders:
                self._leaders[topic] = leaders
                self._npartitions[topic] = total
                return
            last_err = RuntimeError(f"no leaders for topic {topic!r}")
        raise last_err or RuntimeError("no bootstrap broker reachable")

    def _pick(self, topic: str, key: Optional[str]) -> Tuple[int,
                                                             Tuple[str, int]]:
        parts = self._leaders[topic]
        if key is not None and self.partitioner == "hash":
            # sarama's HashPartitioner, bit-for-bit: FNV-1a 32, the hash
            # reinterpreted as int32 with a negative result negated —
            # which collapses to abs(int32(h)) — taken modulo the
            # topic's TOTAL partition count (leaderless partitions
            # included) — co-partitioning with Go producers/consumers
            # depends on both details. (Python's builtin hash() is
            # salted per process and would scatter one key across
            # partitions between restarts.)
            h = 2166136261
            for byte in key.encode("utf-8"):
                h = ((h ^ byte) * 16777619) & 0xFFFFFFFF
            if h >= 1 << 31:
                h -= 1 << 32  # int32 reinterpretation
            pid = abs(h) % self._npartitions[topic]
            if pid not in parts:
                # the key's partition is mid-election: fail this attempt
                # rather than silently re-route the key (produce() will
                # re-learn metadata and retry, keeping its connections)
                raise _NoLeader(
                    f"partition {pid} of {topic!r} has no leader")
        elif self.partitioner == "random" or self.partitioner == "hash":
            # nil-key messages under sarama's HashPartitioner dispatch
            # via the random partitioner (sarama partitioner.go), so a
            # hash-partitioned producer with no key lands here too
            pids = sorted(parts)
            pid = pids[random.randrange(len(pids))]
        else:
            pids = sorted(parts)
            self._rr += 1
            pid = pids[self._rr % len(pids)]
        return pid, parts[pid]

    # -- produce -----------------------------------------------------------

    def produce(self, topic: str, value: bytes,
                key: Optional[str] = None) -> None:
        # one socket, one in-flight produce: the lock IS the wire
        # serializer. Only the kafka sink's flush thread contends, and
        # the egress deadline bounds the hold
        with self._lock:  # lint: ok(lock-across-blocking) the lock IS the wire serializer (one socket, one in-flight produce); only the flush thread contends and the egress deadline bounds the hold
            err: Optional[Exception] = None
            for attempt in range(self.retry_max + 1):
                try:
                    if topic not in self._leaders:
                        self._refresh_metadata(topic)
                    pid, addr = self._pick(topic, key)
                    mset = _message_set(value)
                    body = (struct.pack(">hi", self.acks, self.timeout_ms)
                            + struct.pack(">i", 1) + _str(topic)
                            + struct.pack(">i", 1)
                            + struct.pack(">i", pid)
                            + struct.pack(">i", len(mset)) + mset)
                    r = self._roundtrip(addr, _API_PRODUCE, body,
                                        want_reply=self.acks != 0)
                    if r is not None:
                        r.i32()  # topic count (1)
                        r.string()
                        r.i32()  # partition count (1)
                        r.i32()  # partition id
                        code = r.i16()
                        r.i64()  # offset
                        if code != 0:
                            raise RuntimeError(
                                f"produce failed with error code {code}")
                    return
                except _NoLeader as e:
                    # expected during elections: re-learn metadata for
                    # this topic only; healthy-broker connections and
                    # other topics' leaders are untouched (no churn
                    # storm while the cluster is already degraded)
                    err = e
                    self._leaders.pop(topic, None)
                except Exception as e:
                    err = e
                    # leadership may have moved; reconnect + re-learn
                    self._leaders.pop(topic, None)
                    for a in list(self._conns):
                        self._drop(a)
            self.errors += 1
            raise err  # type: ignore[misc]

    def close(self) -> None:
        with self._lock:
            for a in list(self._conns):
                self._drop(a)

"""LightStep span sink: a tracer pool round-robined by trace id.

Behavioral port of ``/root/reference/sinks/lightstep/lightstep.go``:
``num_clients`` tracer clients are created against the collector URL
(http scheme ⇒ plaintext, default port 8080; lightstep.go:41-110) and
each span is routed to ``tracers[trace_id % len(tracers)]``
(lightstep.go:146-148), translated to an OpenTracing-style span — parent
id clamped to 0, ``error-code`` / ``indicator`` / component tags, error
flag — and finished with the SSF end timestamp (lightstep.go:124-175).
``flush`` reports and resets the per-service counts (lightstep.go:203+).

Transport: when an access token is configured the default tracer is
``HTTPReportingTracer`` — a bundled background reporter that POSTs
buffered span batches as JSON to ``{collector}/api/v2/reports`` with the
``Lightstep-Access-Token`` header, linear-backoff on failure, bounded
buffer with oldest-first drop (the role the vendored client's reporting
loop plays; the proprietary thrift/protobuf encoding is replaced by
JSON, which LightStep's collectors also accept on this endpoint).
A custom ``tracer_factory`` returning objects with ``report(span_dict)``
(and optionally ``close()``) can still be injected.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Dict, List, Optional
from urllib.parse import urlparse

from veneur_tpu.forward.http_forward import post_helper
from veneur_tpu.protocol import wire
from veneur_tpu.resilience import RetryPolicy
from veneur_tpu.sinks.base import SpanSink

log = logging.getLogger("veneur.sinks.lightstep")

LIGHTSTEP_DEFAULT_PORT = 8080
LIGHTSTEP_DEFAULT_INTERVAL = 300.0  # 5 minutes (lightstep.go:29)
INDICATOR_SPAN_TAG_NAME = "indicator"
RESOURCE_KEY = "resource"
REPORT_PATH = "/api/v2/reports"


class BufferingTracer:
    """Default tracer: buffers up to ``max_spans`` converted spans for an
    external shipper (the role the LightStep client's in-memory span
    buffer plays, lightstep.go:96-101)."""

    def __init__(self, max_spans: int = 1024):
        self.max_spans = max_spans
        self.spans: List[dict] = []
        self._lock = threading.Lock()
        self.dropped = 0

    def report(self, span: dict) -> None:
        with self._lock:
            if len(self.spans) >= self.max_spans:
                self.dropped += 1
                self.spans.pop(0)
            self.spans.append(span)

    def drain(self) -> List[dict]:
        with self._lock:
            out, self.spans = self.spans, []
            return out

    def close(self) -> None:
        pass


class HTTPReportingTracer(BufferingTracer):
    """Bundled reporting transport: the BufferingTracer's bounded buffer
    plus a daemon thread that drains it every ``report_interval``
    seconds (or when ``max_batch`` spans accumulate) and POSTs one JSON
    report to the collector via the shared ``post_helper``.

    Failure semantics mirror the reference's client behavior: the batch
    in flight is dropped on a failed POST (spans are telemetry, not
    durable data), the buffer keeps absorbing new spans with
    oldest-first drop, and retry waits back off exponentially with full
    jitter (the shared ``resilience.RetryPolicy`` shape, floored at one
    report interval) — the batch-full wake is ignored while failing, so
    an outage under load cannot turn into a tight connect loop
    (cf. trace/backend.go:135-180).
    """

    def __init__(self, host: str, port: int, plaintext: bool,
                 access_token: str, max_spans: int = 1024,
                 report_interval: float = 1.0, max_batch: int = 512,
                 reconnect_period: float = 0.0,
                 retry_policy: Optional[RetryPolicy] = None,
                 **_unused):
        super().__init__(max_spans=max_spans)
        scheme = "http" if plaintext else "https"
        self.url = f"{scheme}://{host}:{port}{REPORT_PATH}"
        self.access_token = access_token
        self.max_batch = max_batch
        self.report_interval = report_interval
        # backoff shape only (the reporter loop never gives up; the
        # buffer's oldest-first drop is the budget): base doubles from
        # one report interval, capped at 32 intervals
        self.retry_policy = retry_policy or RetryPolicy(
            max_attempts=1, base_interval=report_interval,
            max_interval=report_interval * 32)
        self.reported = 0
        self.retries = 0
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._failures = 0
        self._thread = threading.Thread(target=self._run,
                                        name="lightstep-reporter",
                                        daemon=True)
        self._thread.start()

    def report(self, span: dict) -> None:
        super().report(span)
        with self._lock:
            full = len(self.spans) >= self.max_batch
        if full:
            self._wake.set()

    def _post(self, batch: List[dict]) -> bool:
        try:
            status = post_helper(
                self.url, {"access_token": self.access_token,
                           "spans": batch},
                compress=False,
                headers={"Lightstep-Access-Token": self.access_token})
            if 200 <= status < 300:
                return True
            log.warning("lightstep report to %s got HTTP %d", self.url,
                        status)
        except Exception as e:
            # any transport/protocol error (URLError, OSError, bad
            # status line, ...) must never kill the reporter thread
            log.warning("lightstep report to %s failed: %s", self.url, e)
        return False

    def _run(self) -> None:
        while not self._stop.is_set():
            if self._failures:
                # honor the backoff even if report() keeps setting the
                # batch-full wake during an outage; exponential full
                # jitter, floored at one report interval so a run of
                # small jitter draws cannot tighten into a connect loop
                pause = max(self.report_interval,
                            self.retry_policy.backoff(self._failures - 1))
                self.retries += 1
                self._stop.wait(pause)
                self._wake.clear()
            else:
                self._wake.wait(timeout=self.report_interval)
                self._wake.clear()
            batch = self.drain()
            if not batch:
                continue
            if self._post(batch):
                with self._lock:
                    self.reported += len(batch)
                self._failures = 0
            else:
                # drop the failed batch; back off the next attempt
                with self._lock:
                    self.dropped += len(batch)
                self._failures += 1

    def close(self) -> None:
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=5)
        batch = self.drain()
        if batch:
            self._post(batch)


class LightStepSpanSink(SpanSink):
    """Round-robin tracer-pool span sink (lightstep.go:30-210)."""

    def __init__(self, collector: str, reconnect_period: float = 0.0,
                 maximum_spans: int = 1024, num_clients: int = 1,
                 access_token: str = "",
                 tracer_factory: Optional[Callable[..., object]] = None,
                 retry_policy: Optional[RetryPolicy] = None):
        host = urlparse(collector if "//" in collector
                        else "//" + collector)
        try:
            self.port = host.port or LIGHTSTEP_DEFAULT_PORT
        except ValueError:
            log.warning("Error parsing LightStep port, using default %d",
                        LIGHTSTEP_DEFAULT_PORT)
            self.port = LIGHTSTEP_DEFAULT_PORT
        self.host = host.hostname or "localhost"
        self.plaintext = host.scheme == "http"
        self.access_token = access_token
        if reconnect_period and tracer_factory is None:
            # not silently dead (the repo's config policy): the bundled
            # transports open a fresh connection per report, so the
            # vendored client's periodic-reconnect knob has no effect.
            # Logged once per sink, whatever the client count/transport.
            log.info("lightstep_reconnect_period has no effect on the "
                     "bundled transports (they reconnect per report)")
        self.reconnect_period = reconnect_period or LIGHTSTEP_DEFAULT_INTERVAL
        n = num_clients if num_clients > 0 else 1  # lightstep.go:77-81
        if tracer_factory is not None:
            factory = tracer_factory
        elif access_token:
            # a configured token means "actually ship": use the bundled
            # HTTP reporting transport
            factory = HTTPReportingTracer
        else:
            factory = lambda **kw: BufferingTracer(max_spans=maximum_spans)
        tracer_kwargs = dict(host=self.host, port=self.port,
                             plaintext=self.plaintext,
                             access_token=access_token,
                             max_spans=maximum_spans,
                             reconnect_period=self.reconnect_period)
        if retry_policy is not None:
            # the config-driven backoff shape reaches the reporter;
            # omitted (None) keeps the kwarg out so custom injected
            # factories need not accept it
            tracer_kwargs["retry_policy"] = retry_policy
        self.tracers = [factory(**tracer_kwargs) for _ in range(n)]
        self._lock = threading.Lock()
        self._service_count: Dict[str, int] = {}

    @property
    def name(self) -> str:
        return "lightstep"

    def ingest(self, span) -> None:
        if not wire.valid_trace(span):
            raise ValueError("invalid span for lightstep sink")
        if not self.tracers:
            raise RuntimeError("No lightstep tracer clients initialized")
        parent_id = max(span.parent_id, 0)
        error_code = 1 if span.error else 0
        tags = dict(span.tags)
        tags[RESOURCE_KEY] = tags.get(RESOURCE_KEY, "")
        tags["component"] = span.service
        tags[INDICATOR_SPAN_TAG_NAME] = str(span.indicator).lower()
        tags["type"] = "http"
        tags["error-code"] = error_code
        if error_code:
            tags["error"] = True  # OT-standard error flag
        tracer = self.tracers[span.trace_id % len(self.tracers)]
        tracer.report({
            "operation_name": span.name,
            "trace_id": span.trace_id,
            "span_id": span.id,
            "parent_span_id": parent_id,
            "start_timestamp": span.start_timestamp,
            "end_timestamp": span.end_timestamp,
            "tags": tags,
        })
        service = span.service or "unknown"
        with self._lock:
            self._service_count[service] = (
                self._service_count.get(service, 0) + 1)

    def flush(self) -> None:
        """Report + reset per-service counts (lightstep.go:203+)."""
        with self._lock:
            counts, self._service_count = self._service_count, {}
        for service, count in counts.items():
            log.info("lightstep sink: %d spans flushed for service %s",
                     count, service)

    def close(self) -> None:
        for t in self.tracers:
            close = getattr(t, "close", None)
            if close:
                close()

"""SignalFx sink: dimension-based datapoints with per-tag API-key fanout.

Behavioral port of ``/root/reference/sinks/signalfx/signalfx.go``:

- InterMetrics become SignalFx datapoints — gauges stay gauges, counters
  stay counters, status checks are emitted as gauges
  (signalfx.go:195-210); every tag becomes a dimension, the hostname is a
  dimension too since SFx has no first-class host field
  (signalfx.go:169-184), common dimensions are merged and excluded tags
  dropped (signalfx.go:185-192, SetExcludedTags :255).
- ``vary_key_by``: when set, the value of that tag selects a per-key
  client (its own API token); unmatched values use the default client
  (signalfx.go:135-143, :31-66). Each client's batch is submitted in
  parallel.
- DogStatsD events (``flush_other_samples``) are sent as SFx events to
  ``/v2/event`` (signalfx.go:227-253, reportEvent :272+).

The HTTP client is injectable for tests (the reference's tests swap the
``DPClient``; signalfx_test.go).
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional, Sequence

from veneur_tpu.forward.http_forward import post_helper
from veneur_tpu.protocol import constants as dogstatsd
from veneur_tpu.resilience import RetryPolicy, post_with_retry
from veneur_tpu.samplers.intermetric import InterMetric, MetricType
from veneur_tpu.sinks.base import MetricSink

log = logging.getLogger("veneur.sinks.signalfx")

EVENT_CATEGORY_USER_DEFINED = "USER_DEFINED"


class SignalFxClient:
    """One SignalFx ingest endpoint + token (signalfx.go:97-106).

    ``submit(datapoints)`` posts ``{"gauge": [...], "counter": [...]}`` to
    ``/v2/datapoint``; ``submit_event(event)`` posts to ``/v2/event``.
    """

    def __init__(self, endpoint: str, api_key: str, timeout: float = 10.0):
        self.endpoint = endpoint.rstrip("/")
        self.api_key = api_key
        self.timeout = timeout

    def _post(self, path: str, payload) -> int:
        return post_helper(self.endpoint + path, payload,
                           timeout=self.timeout, compress=False,
                           headers={"X-Sf-Token": self.api_key})

    def submit(self, datapoints: List[dict]) -> int:
        # non-destructive (no dp.pop): the retry loop may call submit
        # again with the same datapoint list
        body: Dict[str, List[dict]] = {}
        for dp in datapoints:
            body.setdefault(dp.get("_sfx_type", "gauge"), []).append(
                {k: v for k, v in dp.items() if k != "_sfx_type"})
        return self._post("/v2/datapoint", body)

    def submit_raw(self, body: bytes) -> int:
        """POST an already-serialized /v2/datapoint body (the native
        columnar serializer's output)."""
        return post_helper(self.endpoint + "/v2/datapoint", None,
                           timeout=self.timeout, compress=False,
                           headers={"X-Sf-Token": self.api_key},
                           raw_body=body)

    def submit_event(self, event: dict) -> int:
        return self._post("/v2/event", [event])


class SignalFxSink(MetricSink):
    """Dimension-based metric sink with vary-by-tag client fanout
    (signalfx.go:79-225)."""

    def __init__(self, hostname_tag: str, hostname: str,
                 common_dimensions: Optional[Dict[str, str]] = None,
                 client: Optional[SignalFxClient] = None,
                 vary_by: str = "",
                 per_tag_clients: Optional[Dict[str, SignalFxClient]] = None,
                 excluded_tags: Optional[Sequence[str]] = None,
                 retry_policy: Optional[RetryPolicy] = None,
                 breaker=None, fault_injector=None):
        self.hostname_tag = hostname_tag
        self.hostname = hostname
        self.common_dimensions = dict(common_dimensions or {})
        self.default_client = client
        self.vary_by = vary_by
        self.clients_by_tag_value = dict(per_tag_clients or {})
        self.excluded_tags = set(excluded_tags or ())
        # resilience: every submit (datapoints, raw bodies, events)
        # retries transport errors and 5xx with backoff clamped to the
        # flush deadline; one breaker covers the ingest endpoint
        self.retry_policy = retry_policy or RetryPolicy()
        self.breaker = breaker
        self._faults = fault_injector
        self._retry_lock = threading.Lock()
        self.retries = 0
        self.flush_errors = 0
        self.metrics_flushed = 0
        self.metrics_skipped = 0
        self.events_reported = 0

    @property
    def name(self) -> str:
        return "signalfx"

    def set_excluded_tags(self, excludes: Sequence[str]) -> None:
        """SetExcludedTags (signalfx.go:255-262)."""
        self.excluded_tags = set(excludes)

    def _count_retry(self, retry_index, exc, pause) -> None:
        with self._retry_lock:
            self.retries += 1

    def _count_error(self) -> None:
        with self._retry_lock:
            self.flush_errors += 1

    def _resilient_submit(self, call) -> int:
        """Run a submit closure under the shared retry loop and the
        ingest-endpoint breaker; an open breaker raises OSError so call
        sites log it through their existing error path."""
        from veneur_tpu.resilience import is_transient_status

        if self.breaker is not None and not self.breaker.allow():
            raise OSError("signalfx circuit breaker open")
        wrapped = (self._faults.wrap_post(call, "sink.signalfx")
                   if self._faults is not None else call)
        try:
            status = post_with_retry(wrapped, self.retry_policy,
                                     deadline=self.flush_deadline,
                                     on_retry=self._count_retry)
        except OSError:
            if self.breaker is not None:
                self.breaker.record_failure()
            raise
        if self.breaker is not None:
            if is_transient_status(status):
                self.breaker.record_failure()
            else:
                self.breaker.record_success()
        return status

    def _client(self, key: str) -> SignalFxClient:
        return self.clients_by_tag_value.get(key, self.default_client)

    def _dimensions(self, metric: InterMetric):
        dims = {self.hostname_tag: metric.hostname or self.hostname}
        for tag in metric.tags:
            k, sep, v = tag.partition(":")
            dims[k] = v if sep else ""
        dims.update(self.common_dimensions)
        metric_key = dims.get(self.vary_by, "") if self.vary_by else ""
        for k in self.excluded_tags:
            dims.pop(k, None)
        dims.pop("veneursinkonly", None)
        return dims, metric_key

    def flush_columnar(self, batch) -> None:
        """Columnar flush: serialize emission blocks to /v2/datapoint
        bodies in C++ (the vectorized twin of flush + _dimensions).
        The vary-by client fanout partitions rows by a tag VALUE, which
        the columnar serializer does not model — that configuration
        takes the per-row path on the materialized metrics."""
        from veneur_tpu.native import egress

        if self.vary_by or self.default_client is None:
            self.flush(batch.to_intermetrics())
            return
        import json as _json

        excluded = set(self.excluded_tags)
        common = {k: v for k, v in self.common_dimensions.items()
                  if k not in excluded}
        common_json = ",".join(
            f"{_json.dumps(k)}:{_json.dumps(v)}"
            for k, v in common.items()).encode("utf-8")
        submissions = []  # (body, points) — one body per block today
        for blk in batch.blocks:
            bodies = egress.sfx_datapoint_bodies(
                blk.names, blk.tags, blk.suffixes, blk.rows,
                blk.suffix_idx, blk.values, blk.type_codes,
                timestamp_ms=batch.timestamp * 1000,
                hostname_tag=(self.hostname_tag
                              if self.hostname_tag not in excluded
                              else ""),
                hostname=self.hostname,
                common_dims_json=common_json,
                common_keys=[k.encode() for k in common],
                excluded_keys=[k.encode() for k in excluded])
            for body in bodies:
                submissions.append(body)
            # count before submitting, exactly like the legacy flush()
            # (it appends to points_by_key and counts regardless of the
            # POST outcome; failures are logged, not un-counted)
            self.metrics_flushed += len(blk)

        def submit_one(body: bytes) -> None:
            try:
                status = self._resilient_submit(
                    lambda: self.default_client.submit_raw(body))
                if status >= 300:
                    log.warning("signalfx datapoint submit returned "
                                "HTTP %d", status)
                    self._count_error()
            except OSError:
                log.warning("could not submit to signalfx", exc_info=True)
                self._count_error()

        threads = []
        for body in submissions:
            t = threading.Thread(target=submit_one, args=(body,),
                                 daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join()
        if batch.extras:
            self.flush(batch.extras)

    def flush(self, metrics: List[InterMetric]) -> None:
        points_by_key: Dict[str, List[dict]] = {"": []}
        for m in metrics:
            if not m.is_acceptable_to(self.name):
                self.metrics_skipped += 1
                continue
            dims, metric_key = self._dimensions(m)
            if m.type == MetricType.COUNTER:
                point = {"_sfx_type": "counter", "metric": m.name,
                         "dimensions": dims, "value": int(m.value),
                         "timestamp": m.timestamp * 1000}
            else:
                # gauges and status checks both flush as gauges
                # (signalfx.go:195-207)
                point = {"_sfx_type": "gauge", "metric": m.name,
                         "dimensions": dims, "value": m.value,
                         "timestamp": m.timestamp * 1000}
            points_by_key.setdefault(metric_key, []).append(point)
            self.metrics_flushed += 1
        if self.default_client is None:
            return
        # one parallel submission per client (signalfx.go:44-66)
        threads = []
        for key, points in points_by_key.items():
            if not points:
                continue
            t = threading.Thread(target=self._submit_one,
                                 args=(self._client(key), points),
                                 daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join()

    def _submit_one(self, client: SignalFxClient, points: List[dict]) -> None:
        try:
            status = self._resilient_submit(lambda: client.submit(points))
            if status >= 300:
                log.warning("signalfx datapoint submit returned HTTP %d "
                            "(%d points dropped)", status, len(points))
                self._count_error()
        except OSError:
            log.warning("could not submit to signalfx", exc_info=True)
            self._count_error()

    def flush_other_samples(self, samples) -> None:
        """Events only; other sample kinds are ignored
        (signalfx.go:227-253)."""
        if self.default_client is None:
            return
        for sample in samples:
            if dogstatsd.EVENT_IDENTIFIER_KEY not in sample.tags:
                continue
            dims = dict(sample.tags)
            del dims[dogstatsd.EVENT_IDENTIFIER_KEY]
            for magic in (dogstatsd.EVENT_AGGREGATION_KEY_TAG,
                          dogstatsd.EVENT_ALERT_TYPE_TAG,
                          dogstatsd.EVENT_PRIORITY_TAG,
                          dogstatsd.EVENT_SOURCE_TYPE_TAG):
                dims.pop(magic, None)
            if dogstatsd.EVENT_HOSTNAME_TAG in dims:
                dims[self.hostname_tag] = dims.pop(
                    dogstatsd.EVENT_HOSTNAME_TAG)
            else:
                dims[self.hostname_tag] = self.hostname
            dims.update(self.common_dimensions)
            for k in self.excluded_tags:
                dims.pop(k, None)
            event = {
                "eventType": sample.name,
                "category": EVENT_CATEGORY_USER_DEFINED,
                "dimensions": dims,
                "properties": {"description": sample.message},
                "timestamp": sample.timestamp * 1000,
            }
            try:
                status = self._resilient_submit(
                    lambda: self.default_client.submit_event(event))
                if status >= 300:
                    log.warning("signalfx event submit returned HTTP %d",
                                status)
                    self._count_error()
                else:
                    self.events_reported += 1
            except OSError:
                log.warning("could not submit event to signalfx",
                            exc_info=True)
                self._count_error()

"""Metric-extraction span sink: how SSF samples reach the aggregation core.

Behavioral port of ``/root/reference/sinks/ssfmetrics/metrics.go:63-141``:
a span sink on the *main path* (server.go:282-290) that unpacks each span's
embedded SSFSamples into UDPMetrics, derives an indicator-span duration
timer when configured, and feeds everything into the metric store.
"""

from __future__ import annotations

import logging
from typing import Callable

from veneur_tpu.samplers import parser as p
from .base import SpanSink

log = logging.getLogger("veneur.sinks.ssfmetrics")


class MetricExtractionSink(SpanSink):
    """process_metric: callable accepting a UDPMetric (the store's ingest)."""

    def __init__(self, process_metric: Callable[[p.UDPMetric], None],
                 indicator_span_timer_name: str = ""):
        self._process = process_metric
        self._timer_name = indicator_span_timer_name

    @property
    def name(self) -> str:
        return "metric_extraction"

    def ingest(self, span) -> None:
        if getattr(span, "metrics_extracted", False):
            # the native SSF lane already converted the embedded
            # samples (and any indicator timer) into parsed records on
            # the C++ reader threads (server._native_ssf_pump)
            return
        metrics, invalid = p.convert_metrics(span)
        if invalid:
            log.error("parse errors on %d metrics", len(invalid))
        if span.indicator and self._timer_name:
            try:
                metrics.extend(
                    p.convert_indicator_metrics(span, self._timer_name))
            except p.ParseError as e:
                log.error("couldn't extract indicator metrics: %s", e)
        for m in metrics:
            self._process(m)

    def flush(self) -> None:
        pass

"""Production soak plane: deterministic multi-process chaos soak with
steady-state invariant gates (``docs/resilience.md`` "Soak & chaos").

One seed fully determines the chaos schedule
(:class:`~veneur_tpu.soak.scenario.SoakScenario`); the orchestrator
drives a real fleet (local → proxy → global) through it while the
:class:`~veneur_tpu.soak.monitor.SteadyStateMonitor` samples every
interval, and :mod:`veneur_tpu.soak.gates` machine-checks the
invariants at the end — exact conservation across kills, bounded RSS
slope, zero compile drift, timeline coverage, e2e freshness, full
recovery, bounded requeue memory."""

from veneur_tpu.soak.gates import (GateResult, SoakGateError, SoakLedger,
                                   enforce, gate_vector, run_gates)
from veneur_tpu.soak.monitor import IntervalSample, SteadyStateMonitor
from veneur_tpu.soak.orchestrator import (ChaosPost, FleetSpec,
                                          InProcessFleet, ProcessFleet,
                                          SoakReport, run_soak)
from veneur_tpu.soak.scenario import (KIND_KILL_FOREVER,
                                      KIND_KILL_RESTART, FaultWindow,
                                      GateThresholds, SoakScenario)

__all__ = [
    "ChaosPost", "FaultWindow", "FleetSpec", "GateResult",
    "GateThresholds", "InProcessFleet", "IntervalSample",
    "KIND_KILL_FOREVER", "KIND_KILL_RESTART", "ProcessFleet",
    "SoakGateError", "SoakLedger", "SoakReport", "SoakScenario",
    "SteadyStateMonitor", "enforce", "gate_vector", "run_gates",
    "run_soak",
]

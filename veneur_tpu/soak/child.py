"""One soak fleet role as a real OS process.

``python -m veneur_tpu.soak.child <role> <spec.json>`` boots the role
(local | proxy | global | standby) from the shared
:class:`~veneur_tpu.soak.orchestrator.FleetSpec`, prints one READY
JSON line on stdout, then serves the driver's line protocol: one
command per stdin line, exactly one JSON ack per command on stdout
(logs go to stderr so they can never corrupt the protocol). The driver
SIGKILLs this process for a scheduled kill — there is no crash
command; ``quit`` is the graceful path used at run end.

Commands: ``flush`` (driven interval; global acks its emitted ledger
value and steady-state sample), ``ckpt`` (checkpoint commit, retried
through injected ENOSPC), ``processed`` / ``imported`` (settle
reads), ``mode <m>`` (sink outage mode, global only), ``counters``
(monotone generation counters, read before a kill), ``hastatus``
(the StandbyManager snapshot — lease/replication state, global and
standby roles), ``ring`` (the proxy's live destination list, read by
the driver's re-route wait), ``quit``."""

from __future__ import annotations

import json
import logging
import os
import sys


def _serve(role: str, spec_path: str) -> int:
    logging.basicConfig(stream=sys.stderr, level=logging.WARNING)
    # the soak fleet is a CPU-host plane; keep any accelerator out of
    # the children so restarts pay a bounded, compile-only warmup
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from veneur_tpu.soak.monitor import read_rss_kb
    from veneur_tpu.soak.orchestrator import (GLOBAL_PREFIX, LOCAL_PREFIX,
                                              ChaosPost, FleetSpec,
                                              build_global_server,
                                              build_local_server,
                                              build_proxy,
                                              checkpoint_with_retry,
                                              drain_channel,
                                              global_counters,
                                              global_sample_fields,
                                              local_counters)

    with open(spec_path) as f:
        spec = FleetSpec.from_json(json.load(f))

    def ack(obj: dict) -> None:
        sys.stdout.write(json.dumps(obj) + "\n")
        sys.stdout.flush()

    server = sink = dd = proxy = None
    chaos = ChaosPost()
    offered = [0]
    if role == "local":
        server, sink = build_local_server(spec)
    elif role == "global":
        server, sink, dd, offered = build_global_server(spec, chaos)
    elif role == "standby":
        server, sink, dd, offered = build_global_server(
            spec, chaos, role="standby")
    elif role == "proxy":
        proxy = build_proxy(spec)
    else:
        ack({"ready": False, "error": f"unknown role {role!r}"})
        return 2
    ack({"ready": True, "role": role, "pid": os.getpid()})

    for line in sys.stdin:
        cmd = line.strip()
        if not cmd:
            continue
        try:
            if cmd == "quit":
                ack({"ok": True})
                break
            elif cmd == "flush" and server is not None:
                server.flush()
                if role in ("global", "standby"):
                    emitted = drain_channel(sink, GLOBAL_PREFIX)
                    sample = global_sample_fields(server, dd)
                    sample["rss_kb"] = read_rss_kb()
                    sample["degradations"] = list(sample["degradations"])
                    ack({"ok": True, "emitted": emitted, "sample": sample})
                else:
                    ack({"ok": True,
                         "emitted": drain_channel(sink, LOCAL_PREFIX)})
            elif cmd == "ckpt" and server is not None:
                attempts = checkpoint_with_retry(server)
                ack({"ok": True, "attempts": attempts})
            elif cmd == "processed" and server is not None:
                ack({"v": server.store.processed})
            elif cmd == "imported" and server is not None:
                ack({"v": server.store.imported})
            elif cmd.startswith("mode ") and role in ("global", "standby"):
                chaos.mode = cmd.split(None, 1)[1]
                ack({"ok": True, "mode": chaos.mode})
            elif cmd == "counters":
                if role in ("global", "standby"):
                    ack({"counters": global_counters(server, dd, offered)})
                elif role == "local":
                    ack({"counters": local_counters(server)})
                else:
                    ack({"counters": {}})
            elif cmd == "hastatus":
                sby = getattr(server, "standby_manager", None)
                ack({"ha": sby.snapshot() if sby is not None else {}})
            elif cmd == "ring" and proxy is not None:
                ack({"members": list(proxy.ring.members())})
            else:
                ack({"ok": False, "error": f"bad command {cmd!r}"})
        except Exception as e:  # the ack keeps the protocol in sync
            logging.getLogger("veneur.soak.child").exception(
                "command %r failed", cmd)
            ack({"ok": False, "error": f"{type(e).__name__}: {e}"})
    try:
        if server is not None:
            server.shutdown()
        if proxy is not None:
            proxy.shutdown()
    except Exception:
        pass
    return 0


def main(argv) -> int:
    if len(argv) != 3:
        print("usage: python -m veneur_tpu.soak.child "
              "<local|proxy|global|standby> <spec.json>", file=sys.stderr)
        return 2
    return _serve(argv[1], argv[2])


if __name__ == "__main__":
    sys.exit(main(sys.argv))

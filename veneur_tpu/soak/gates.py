"""The soak gate library: machine-checked steady-state invariants.

Every gate is a pure function of the run's :class:`SoakLedger` (exact
end-to-end counts, folded across process kills) and the
:class:`~veneur_tpu.soak.monitor.SteadyStateMonitor` samples. A
violated gate names itself, its measured value, its threshold, AND the
scenario's reproduction call — a failed soak is a seed, not a shrug
(``docs/resilience.md`` "Soak & chaos" gate table):

==================  ====================================================
gate                invariant
==================  ====================================================
conservation_global sent global-only counter value == value emitted by
                    the global's accounting sink + shed + quarantined
                    + accounted_lost (exact, across every kill/restart
                    via checkpoint epochs; ``accounted_lost`` is only
                    ever non-zero in a kill_forever scenario — the
                    active's un-flushed tail, measured at the kill)
conservation_local  same for local-only counters at the local instance
dd_rows_conserved   every Datadog emission row is acked, parked
                    (pending), dropped counted, or crash-lost counted —
                    folded across sink generations
rss_slope           post-warmup RSS slope ≤ threshold %/100 intervals
compile_drift       zero jit-compile growth per process generation
                    across the post-chaos steady state
coverage            median timeline coverage_ratio ≥ threshold
e2e_age_p99         p99 of veneur.fleet.e2e_age_ns ≤ threshold
recovery            final samples: overload level 0, breaker closed,
                    requeue drained, nothing pending, no degradations
requeue_bounded     max parked sink bytes ≤ the configured budget
device_buffers_bounded settled ``jax.live_arrays()`` growth in the
                    driver process ≤ the configured byte bound (the
                    runtime twin of the donation-safety lint pass;
                    vacuously green when the driver owns no device
                    arrays)
takeover            kill_forever only: the standby promoted, held the
                    lease within ``takeover_detect_max_s`` of the
                    active's SIGKILL, and the accounted loss is
                    bounded by the un-replicated tail (≤ 1 flush
                    interval's sent value)
==================  ====================================================
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List

from veneur_tpu.soak.monitor import SteadyStateMonitor
from veneur_tpu.soak.scenario import KIND_KILL_FOREVER, SoakScenario


@dataclass
class SoakLedger:
    """Exact end-to-end counts, folded across restarts. The driver
    accumulates the monotone per-generation counters (sink rows,
    shed/quarantine tallies, checkpoint/spool errors) into this ledger
    at every kill and once at the end, so a counter reset by a process
    death can never hide loss."""

    sent_global: int = 0       # counter VALUE sent tagged veneurglobalonly
    emitted_global: int = 0    # counter VALUE the global's channel sink saw
    sent_local: int = 0        # counter VALUE sent local-only
    emitted_local: int = 0     # counter VALUE the local's channel sink saw
    shed: int = 0              # overload sheds, folded across generations
    quarantined: int = 0       # quarantine ledger, folded
    dd_offered: int = 0        # rows offered to the Datadog chunk path
    dd_acked: int = 0          # rows 2xx-acked, folded
    dd_dropped: int = 0        # rows dropped counted (budget eviction)
    dd_crash_lost: int = 0     # rows parked at a kill — died with the sink
    dd_pending: int = 0        # rows still parked at the end
    ckpt_write_errors: int = 0  # injected/real ENOSPC commits survived
    spool_errors: int = 0       # handoff spool writes the disk refused
    ckpt_retries: int = 0       # kill-time checkpoint attempts past one
    restarts: Dict[str, int] = field(default_factory=dict)
    # kill_forever (HA takeover) accounting — all stay zero/-1 in a
    # kill_restart run. accounted_lost is the active's un-flushed tail
    # at the SIGKILL, measured exactly from the settled ledger;
    # takeover_loss_bound is the ≤-1-interval bound it must respect.
    accounted_lost: int = 0
    takeover_loss_bound: int = 0
    promotions: int = 0              # standby promotions observed
    takeover_detect_s: float = -1.0  # SIGKILL → standby holds the lease
    takeover_first_flush_s: float = -1.0  # SIGKILL → first good flush
    # driver-process BufferCensus fold (lint/buffer_census.py): max
    # settled jax.live_arrays() growth over the baseline, and the
    # census's own verdict/detail (suspect programs on a violation)
    device_buffer_growth_bytes: int = 0
    buffer_census_ok: bool = True
    buffer_census_detail: str = ""

    def restart_total(self) -> int:
        return sum(self.restarts.values())


@dataclass
class GateResult:
    name: str
    ok: bool
    value: object
    threshold: object
    detail: str = ""


class SoakGateError(AssertionError):
    """A steady-state gate failed. The message names every violated
    gate and the scenario's exact reproduction call."""


def run_gates(scenario: SoakScenario, monitor: SteadyStateMonitor,
              ledger: SoakLedger) -> List[GateResult]:
    thr = scenario.thresholds
    out: List[GateResult] = []

    # accounted_lost folds EXPLICITLY: a kill_forever run loses the
    # active's un-flushed tail by design, and conservation stays exact
    # only because that loss is measured and named, never shrugged
    want = (ledger.emitted_global + ledger.shed + ledger.quarantined
            + ledger.accounted_lost)
    out.append(GateResult(
        "conservation_global", ledger.sent_global == want,
        ledger.sent_global, want,
        f"sent={ledger.sent_global} emitted={ledger.emitted_global} "
        f"shed={ledger.shed} quarantined={ledger.quarantined} "
        f"accounted_lost={ledger.accounted_lost} "
        f"restarts={ledger.restart_total()}"))

    out.append(GateResult(
        "conservation_local", ledger.sent_local == ledger.emitted_local,
        ledger.sent_local, ledger.emitted_local,
        f"sent={ledger.sent_local} emitted={ledger.emitted_local}"))

    dd_accounted = (ledger.dd_acked + ledger.dd_pending
                    + ledger.dd_dropped + ledger.dd_crash_lost)
    out.append(GateResult(
        "dd_rows_conserved", ledger.dd_offered == dd_accounted,
        ledger.dd_offered, dd_accounted,
        f"offered={ledger.dd_offered} acked={ledger.dd_acked} "
        f"pending={ledger.dd_pending} dropped={ledger.dd_dropped} "
        f"crash_lost={ledger.dd_crash_lost}"))

    slope = monitor.rss_slope_pct_per_100()
    out.append(GateResult(
        "rss_slope", slope <= thr.rss_slope_pct_per_100,
        round(slope, 4), thr.rss_slope_pct_per_100,
        f"{len(monitor.post_warmup())} post-warmup samples"))

    # the zero bound reads the post-chaos steady state: kills and sink
    # windows first-exercise novel kernel shapes (a re-merged forward
    # part, a restarted generation's import path) and those one-off
    # compiles are legitimate; per-interval recompilation would keep
    # growing the counter into the steady tail and still fail here
    chaos_end = max(
        [at + 1 for at, _role in scenario.kills]
        + [w.end for w in scenario.sink_windows] + [0])
    drift = monitor.compile_drift(after_idx=chaos_end)
    out.append(GateResult(
        "compile_drift", drift <= thr.max_compile_drift,
        drift, thr.max_compile_drift,
        f"jit compiles past each generation's first steady-state "
        f"sample (idx >= {chaos_end})"))

    cov = monitor.coverage_median()
    out.append(GateResult(
        "coverage", cov is not None and cov >= thr.coverage_min,
        cov, thr.coverage_min, "median post-warmup coverage_ratio"))

    p99 = monitor.e2e_age_p99_s()
    out.append(GateResult(
        "e2e_age_p99", p99 is not None and p99 <= thr.e2e_age_p99_max_s,
        None if p99 is None else round(p99, 3), thr.e2e_age_p99_max_s,
        "p99 ingest→emission freshness, seconds"))

    tail = monitor.tail(thr.recovery_intervals)
    bad = [f"i{s.idx}:" + ",".join(
        (["overload"] if s.overload_level else [])
        + (["breaker"] if s.breaker_gauge else [])
        + (["requeue"] if s.requeue_bytes or s.rows_pending else [])
        + ([f"degraded({';'.join(s.degradations)})"]
           if s.degradations else []))
        for s in tail
        if (s.overload_level or s.breaker_gauge or s.requeue_bytes
            or s.rows_pending or s.degradations)]
    out.append(GateResult(
        "recovery", len(tail) >= min(thr.recovery_intervals,
                                     len(monitor.samples)) and not bad,
        "; ".join(bad) or "recovered", "clean final "
        f"{thr.recovery_intervals} intervals",
        "overload/breaker/requeue/degradation state in the tail"))

    mx = monitor.max_requeue_bytes()
    out.append(GateResult(
        "requeue_bounded", mx <= thr.requeue_max_bytes,
        mx, thr.requeue_max_bytes, "max parked sink bytes ever sampled"))

    out.append(GateResult(
        "device_buffers_bounded",
        (ledger.buffer_census_ok
         and ledger.device_buffer_growth_bytes
         <= thr.device_buffer_growth_max_bytes),
        ledger.device_buffer_growth_bytes,
        thr.device_buffer_growth_max_bytes,
        ledger.buffer_census_detail
        or "settled jax.live_arrays() growth in the driver process "
           "(vacuously green when the driver owns no device arrays)"))

    if scenario.kind == KIND_KILL_FOREVER:
        promoted = ledger.promotions >= 1
        detected = (0.0 <= ledger.takeover_detect_s
                    <= thr.takeover_detect_max_s)
        bounded = ledger.accounted_lost <= ledger.takeover_loss_bound
        out.append(GateResult(
            "takeover", promoted and detected and bounded,
            {"detect_s": round(ledger.takeover_detect_s, 3),
             "first_flush_s": round(ledger.takeover_first_flush_s, 3),
             "accounted_lost": ledger.accounted_lost,
             "promotions": ledger.promotions},
            {"detect_max_s": thr.takeover_detect_max_s,
             "loss_bound": ledger.takeover_loss_bound},
            "standby promoted, lease held within the detect bound, "
            "loss ≤ the un-replicated tail (1 flush interval)"))
    return out


def gate_vector(results: List[GateResult]) -> dict:
    """The machine-checked gate vector (lands in BENCH_rNN.json)."""
    return {
        "all_ok": all(r.ok for r in results),
        "gates": {r.name: {"ok": r.ok, "value": r.value,
                           "threshold": r.threshold, "detail": r.detail}
                  for r in results}}


def enforce(results: List[GateResult], scenario: SoakScenario) -> None:
    """Raise :class:`SoakGateError` naming every violated gate and the
    scenario seed; silent on a clean vector."""
    bad = [r for r in results if not r.ok]
    if not bad:
        return
    lines = [f"  gate '{r.name}' violated: value={r.value!r} "
             f"threshold={r.threshold!r} ({r.detail})" for r in bad]
    raise SoakGateError(
        "soak steady-state gates failed:\n" + "\n".join(lines)
        + f"\nreproduce with {scenario.repro()}")

"""Steady-state sampling for soak runs.

One :class:`IntervalSample` is captured per driven flush interval —
always on the GLOBAL role, after its flush — and the
:class:`SteadyStateMonitor` turns the series into the derived
statistics the gate library checks: the post-warmup RSS slope
(least-squares, as a percentage of the mean per 100 intervals),
per-process-generation compile-counter drift, the end-to-end freshness
p99, and the coverage/recovery views. RSS is the CURRENT resident set
from ``/proc/self/statm`` (``ru_maxrss`` is a high-water mark and can
never show a slope)."""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import List, Optional, Tuple

_PAGE_KB = os.sysconf("SC_PAGE_SIZE") // 1024 if hasattr(os, "sysconf") \
    else 4


def read_rss_kb(pid: int = 0) -> int:
    """Current resident set in KiB for ``pid`` (0 = this process).
    Returns 0 where /proc is unavailable — the RSS gate then reports
    an unmeasurable slope of 0.0 rather than crashing the soak."""
    path = f"/proc/{pid}/statm" if pid else "/proc/self/statm"
    try:
        with open(path) as f:
            return int(f.read().split()[1]) * _PAGE_KB
    except (OSError, IndexError, ValueError):
        return 0


@dataclass
class IntervalSample:
    """One interval's steady-state reading of the global role."""

    idx: int
    generation: int           # restarts of the sampled process so far
    rss_kb: int = 0
    compiles: int = 0
    coverage_ratio: Optional[float] = None
    e2e_age_ns: Optional[int] = None
    overload_level: int = 0
    breaker_gauge: float = 0.0
    requeue_bytes: int = 0
    rows_pending: int = 0
    ckpt_write_errors: int = 0
    spool_errors: int = 0
    degradations: Tuple[str, ...] = ()


class SteadyStateMonitor:
    """Accumulates interval samples and derives the gate statistics."""

    def __init__(self, warmup_intervals: int = 2):
        self.warmup = max(0, warmup_intervals)
        self.samples: List[IntervalSample] = []

    def add(self, sample: IntervalSample) -> None:
        self.samples.append(sample)

    def post_warmup(self) -> List[IntervalSample]:
        return self.samples[self.warmup:]

    # -- derived statistics ------------------------------------------------

    def rss_slope_pct_per_100(self) -> float:
        """Least-squares RSS slope over the post-warmup samples,
        normalized to percent-of-mean per 100 intervals (the
        acceptance bound is ≤ 1%/100)."""
        pts = [(float(s.idx), float(s.rss_kb))
               for s in self.post_warmup() if s.rss_kb > 0]
        if len(pts) < 2:
            return 0.0
        n = len(pts)
        mx = sum(x for x, _ in pts) / n
        my = sum(y for _, y in pts) / n
        denom = sum((x - mx) ** 2 for x, _ in pts)
        if denom <= 0 or my <= 0:
            return 0.0
        slope = sum((x - mx) * (y - my) for x, y in pts) / denom
        return slope * 100.0 / my * 100.0

    def compile_drift(self, after_idx: int = 0) -> int:
        """Total growth of the jit compile counter past each process
        generation's first sample at or after ``after_idx`` (the
        generation's own warmup: a restarted process legitimately
        recompiles once, and chaos can first-exercise a novel kernel
        shape late — e.g. a re-merged forward part after a proxy
        kill). Any residual growth is per-interval recompilation — the
        drift the gate pins to zero; the gate passes the end of the
        scenario's chaos span as ``after_idx`` so the zero bound reads
        the steady state, where sustained recompilation still shows."""
        drift = 0
        by_gen = {}
        for s in self.post_warmup():
            if s.idx < after_idx:
                continue
            by_gen.setdefault(s.generation, []).append(s.compiles)
        for counts in by_gen.values():
            if len(counts) >= 2:
                drift += max(0, counts[-1] - counts[0])
        return drift

    def coverage_median(self) -> Optional[float]:
        vals = sorted(s.coverage_ratio for s in self.post_warmup()
                      if s.coverage_ratio is not None)
        if not vals:
            return None
        return vals[len(vals) // 2]

    def e2e_age_p99_s(self) -> Optional[float]:
        vals = sorted(s.e2e_age_ns for s in self.post_warmup()
                      if s.e2e_age_ns is not None)
        if not vals:
            return None
        return vals[min(len(vals) - 1, int(0.99 * (len(vals) - 1)))] / 1e9

    def max_requeue_bytes(self) -> int:
        return max((s.requeue_bytes for s in self.samples), default=0)

    def tail(self, n: int) -> List[IntervalSample]:
        return self.samples[-n:] if n > 0 else []
